"""Cumulant / central-moment collision operators.

TPU-native re-design of the reference's symbolic cumulant machinery
(reference src/lib/cumulant.R + the generated collision in
src/d3q27_cumulant/Dynamics.c.Rt:1-408 and src/d2q9_cumulant/Dynamics.c):
instead of emitting thousands of closed-form C expressions at build time,
we exploit the tensor-product structure of the {-1,0,1}^d velocity set:

1. populations reshape to a (3,)*d tensor (one axis per lattice direction);
2. raw moments ``m_pqr = sum c^p c^q c^r f`` are three tiny matrix
   contractions (einsum with the 3x3 Vandermonde of (-1,0,1));
3. central moments follow by per-axis binomial shifts with the local u;
4. collision relaxes the second-order central moments (trace with
   ``omega_bulk``, deviatoric+off-diagonal with ``omega``) and rebuilds ALL
   higher central moments from the relaxed covariance via Isserlis' theorem
   — i.e. the post-collision distribution is the correlated Gaussian whose
   cumulants above second order vanish.  This is exactly the cumulant LBM
   with all higher-order relaxation rates = 1 (the parameter-free choice the
   reference defaults to);
5. inverse shifts + inverse Vandermonde give back populations.

Everything is elementwise + 3-wide contractions: ideal for the VPU, with no
per-node branches and no code generation.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

# velocity per tensor index: index 0,1,2 -> c = -1,0,+1
C = np.array([-1.0, 0.0, 1.0])
# Vandermonde T[p, i] = C[i]**p  (p = moment order 0,1,2)
T = np.stack([C ** 0, C ** 1, C ** 2])
T_INV = np.linalg.inv(T)


def velocity_set(ndim: int) -> np.ndarray:
    """Tensor-product velocity set in the reshape order of this module:
    index (i, j[, k]) -> velocity (C[i], C[j][, C[k]]), x-axis first."""
    if ndim == 2:
        return np.array([(int(cx), int(cy))
                         for cx in C for cy in C], dtype=np.int32)
    return np.array([(int(cx), int(cy), int(cz))
                     for cx in C for cy in C for cz in C], dtype=np.int32)


def _contract_axis(F: jnp.ndarray, mat: np.ndarray, axis: int) -> jnp.ndarray:
    """out[..., p, ...] = sum_i mat[p, i] * F[..., i, ...] along ``axis``.

    Unrolled over the static 3x3 matrix (entries are 0/±1/±0.5) instead of
    an einsum: the same scale-and-add chain XLA would emit, but expressed
    in primitives (static slice, mul, add, stack) that Mosaic also accepts,
    so :func:`collide_d3q27` can run unchanged inside a Pallas kernel."""
    parts = [jax.lax.index_in_dim(F, i, axis, keepdims=False)
             for i in range(3)]
    outs = []
    for p in range(3):
        acc = None
        for i in range(3):
            c = float(mat[p, i])
            if c == 0.0:
                continue
            t = parts[i] if c == 1.0 else \
                (-parts[i] if c == -1.0 else c * parts[i])
            acc = t if acc is None else acc + t
        outs.append(acc if acc is not None else jnp.zeros_like(parts[0]))
    return jnp.stack(outs, axis=axis)


def _raw_moments(F: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """m[p,q(,r)] = sum_ijk C_i^p C_j^q C_k^r F[i,j,k]."""
    for ax in range(ndim):
        F = _contract_axis(F, T, ax)
    return F


def _from_raw_moments(m: jnp.ndarray, ndim: int) -> jnp.ndarray:
    for ax in range(ndim):
        m = _contract_axis(m, T_INV, ax)
    return m


def _centralize(m: jnp.ndarray, u, axis: int) -> jnp.ndarray:
    """Shift raw->central moments along one tensor axis:
    k_0 = m_0; k_1 = m_1 - u m_0; k_2 = m_2 - 2u m_1 + u^2 m_0."""
    m0, m1, m2 = (jax.lax.index_in_dim(m, p, axis, keepdims=False)
                  for p in range(3))
    k0 = m0
    k1 = m1 - u * m0
    k2 = m2 - 2.0 * u * m1 + u * u * m0
    return jnp.stack([k0, k1, k2], axis=axis)


def _decentralize(k: jnp.ndarray, u, axis: int) -> jnp.ndarray:
    """Inverse shift: m_0 = k_0; m_1 = k_1 + u k_0;
    m_2 = k_2 + 2u k_1 + u^2 k_0."""
    k0, k1, k2 = (jax.lax.index_in_dim(k, p, axis, keepdims=False)
                  for p in range(3))
    m0 = k0
    m1 = k1 + u * k0
    m2 = k2 + 2.0 * u * k1 + u * u * k0
    return jnp.stack([m0, m1, m2], axis=axis)


def _moment_tensor(entries: dict, like: jnp.ndarray, ndim: int) -> jnp.ndarray:
    """Assemble a (3,)*ndim moment tensor from sparse {index: plane}
    entries (missing indices are zero planes) via nested stacks — the
    Mosaic-safe equivalent of zeros().at[idx].set(...)."""
    z = jnp.zeros_like(like)
    if ndim == 2:
        return jnp.stack(
            [jnp.stack([entries.get((p, q), z) for q in range(3)])
             for p in range(3)])
    return jnp.stack(
        [jnp.stack(
            [jnp.stack([entries.get((p, q, r), z) for r in range(3)])
             for q in range(3)])
         for p in range(3)])


def _low_moments_d3(F: jnp.ndarray):
    """rho, the first moments and the six second-order raw moments —
    the ONLY forward moments the cumulant collision consumes (all higher
    post-collision moments are rebuilt from the relaxed covariance).
    Computing just these (10 of 27 outputs, with the stage-2/3
    contractions restricted to total order <= 2) drops ~a third of the
    forward-transform work vs the full tensor transform; exact algebra.

    Returns (rho, (ux_num, uy_num, uz_num), dict of m_pqr) with the
    first-moment NUMERATORS (caller divides by rho once)."""
    # contract x (T rows: [1,1,1], [-1,0,1], [1,0,1])
    x0, x1, x2 = F[0], F[1], F[2]
    s0 = x0 + x1 + x2
    s1 = x2 - x0
    s2 = x2 + x0
    out = {}
    # contract y then z for each needed (p, q, r), order <= 2
    for p, sx in ((0, s0), (1, s1), (2, s2)):
        y0, y1, y2 = sx[0], sx[1], sx[2]
        t0 = y0 + y1 + y2
        t1 = y2 - y0
        t2 = y2 + y0
        for q, sy in ((0, t0), (1, t1), (2, t2)):
            if p + q > 2:
                continue
            z0, z1, z2 = sy[0], sy[1], sy[2]
            out[(p, q, 0)] = z0 + z1 + z2
            if p + q <= 1:
                out[(p, q, 1)] = z2 - z0
            if p + q == 0:
                out[(p, q, 2)] = z2 + z0
    rho = out[(0, 0, 0)]
    return rho, (out[(1, 0, 0)], out[(0, 1, 0)], out[(0, 0, 1)]), out


def collide_d3q27(F: jnp.ndarray, omega, omega_bulk=1.0,
                  force=(0.0, 0.0, 0.0), correlated: bool = True,
                  galilean=None):
    """Cumulant (``correlated=True``) or cascaded central-moment
    (``correlated=False``, the factorized-equilibrium d3q27 MRT) collision.

    ``F`` is the (3, 3, 3, *shape) population tensor (axes x, y, z; index
    order of :func:`velocity_set`).  ``force`` is an acceleration applied as
    a velocity shift in the back-transform (exact-difference forcing, like
    the reference's velocity-shift forcing in d2q9/d3q27 kernels).

    ``galilean`` (0..1) applies Geier's Galilean-invariance correction to
    the diagonal second-order relaxation: velocity-gradient estimates from
    the diagonal cumulants, ``dxu = -omega/2 (2c200 - c020 - c002)
    - omega_b/2 (c200 + c020 + c002 - 1)`` etc., enter the deviatoric/trace
    combinations as ``-3(1 - omega/2)(ux^2 dxu - uy^2 dyv)`` corrections
    (reference src/d3q27_cumulant/Dynamics.c.Rt:299-319, the
    ``GalileanCorrection`` setting that round-1 declared but never read).
    Returns (F', rho, (ux, uy, uz))."""
    rho, (jx, jy, jz), m = _low_moments_d3(F)
    inv = 1.0 / rho
    ux = jx * inv
    uy = jy * inv
    uz = jz * inv

    # second-order central moments (== second-order cumulants) via the
    # exact shift identities mu_ab = m_ab - rho u_a u_b (the first
    # central moments vanish) — no full-tensor centralization needed
    kxx = m[(2, 0, 0)] - jx * ux
    kyy = m[(0, 2, 0)] - jy * uy
    kzz = m[(0, 0, 2)] - jz * uz
    kxy = m[(1, 1, 0)] - jx * uy
    kxz = m[(1, 0, 1)] - jx * uz
    kyz = m[(0, 1, 1)] - jy * uz

    # relax: trace with omega_bulk toward rho (cs2 = 1/3 per axis),
    # deviatoric + off-diagonal with omega (reference cumulant relaxation,
    # src/d3q27_cumulant/Dynamics.c.Rt); expressed through the reference's
    # a/b/cc combinations so the Galilean correction drops in verbatim
    cxx, cyy, czz = kxx * inv, kyy * inv, kzz * inv
    a_c = (1.0 - omega) * (cxx - cyy)
    b_c = (1.0 - omega) * (cxx - czz)
    cc_c = omega_bulk + (1.0 - omega_bulk) * (cxx + cyy + czz)
    if galilean is not None:
        # velocity-gradient estimates + correction terms
        # (reference Dynamics.c.Rt:299-319); u includes the half force
        uxh = ux + 0.5 * force[0]
        uyh = uy + 0.5 * force[1]
        uzh = uz + 0.5 * force[2]
        dxu = -0.5 * omega * (2.0 * cxx - cyy - czz) \
            - 0.5 * omega_bulk * (cxx + cyy + czz - 1.0)
        dyv = dxu + 1.5 * omega * (cxx - cyy)
        dzw = dxu + 1.5 * omega * (cxx - czz)
        gc1 = 3.0 * (1.0 - 0.5 * omega) * (uxh * uxh * dxu
                                           - uyh * uyh * dyv)
        gc2 = 3.0 * (1.0 - 0.5 * omega) * (uxh * uxh * dxu
                                           - uzh * uzh * dzw)
        gc3 = 3.0 * (1.0 - 0.5 * omega_bulk) * (uxh * uxh * dxu
                                                + uyh * uyh * dyv
                                                + uzh * uzh * dzw)
        a_c = a_c - gc1 * galilean
        b_c = b_c - gc2 * galilean
        cc_c = cc_c - gc3 * galilean
    kxx_p = rho * (a_c + b_c + cc_c) / 3.0
    kyy_p = rho * (cc_c - 2.0 * a_c + b_c) / 3.0
    kzz_p = rho * (cc_c - 2.0 * b_c + a_c) / 3.0
    one_m = 1.0 - omega
    kxy_p, kxz_p, kyz_p = one_m * kxy, one_m * kxz, one_m * kyz

    z = jnp.zeros_like(rho)
    if not correlated:
        # cascaded/factorized equilibrium: higher moments from the
        # UNcorrelated Gaussian (diag cs2) — classic central-moment MRT
        g220 = kxx_p * kyy_p * inv
        g202 = kxx_p * kzz_p * inv
        g022 = kyy_p * kzz_p * inv
        g211 = z
        g121 = z
        g112 = z
        g222 = kxx_p * kyy_p * kzz_p * inv * inv
    else:
        # Isserlis closure on the full covariance: all cumulants of order
        # >= 3 vanish — the cumulant collision proper
        g220 = (kxx_p * kyy_p + 2.0 * kxy_p * kxy_p) * inv
        g202 = (kxx_p * kzz_p + 2.0 * kxz_p * kxz_p) * inv
        g022 = (kyy_p * kzz_p + 2.0 * kyz_p * kyz_p) * inv
        g211 = (kxx_p * kyz_p + 2.0 * kxy_p * kxz_p) * inv
        g121 = (kyy_p * kxz_p + 2.0 * kxy_p * kyz_p) * inv
        g112 = (kzz_p * kxy_p + 2.0 * kxz_p * kyz_p) * inv
        g222 = (kxx_p * kyy_p * kzz_p
                + 2.0 * (kxx_p * kyz_p * kyz_p
                         + kyy_p * kxz_p * kxz_p
                         + kzz_p * kxy_p * kxy_p)
                + 8.0 * kxy_p * kxz_p * kyz_p) * inv * inv

    ux2 = ux + force[0]
    uy2 = uy + force[1]
    uz2 = uz + force[2]
    # first (x-axis) decentralize pass evaluated SPARSELY on the 14
    # nonzero post-collision central moments (zero-mean Gaussian: any
    # odd axis power vanishes): m0 = k0; m1 = k1 + u k0;
    # m2 = k2 + 2u k1 + u^2 k0 with the known-zero k's dropped — the
    # dense pass spends ~4x the multiply-adds shifting zero planes
    u, uu = ux2, ux2 * ux2
    mx = {
        (0, 0, 0): rho, (1, 0, 0): u * rho,
        (2, 0, 0): kxx_p + uu * rho,
        (1, 1, 0): kxy_p, (2, 1, 0): 2.0 * u * kxy_p,
        (1, 0, 1): kxz_p, (2, 0, 1): 2.0 * u * kxz_p,
        (0, 1, 1): kyz_p, (1, 1, 1): u * kyz_p,
        (2, 1, 1): g211 + uu * kyz_p,
        (0, 2, 0): kyy_p, (1, 2, 0): u * kyy_p,
        (2, 2, 0): g220 + uu * kyy_p,
        (0, 0, 2): kzz_p, (1, 0, 2): u * kzz_p,
        (2, 0, 2): g202 + uu * kzz_p,
        (1, 2, 1): g121, (2, 2, 1): 2.0 * u * g121,
        (1, 1, 2): g112, (2, 1, 2): 2.0 * u * g112,
        (0, 2, 2): g022, (1, 2, 2): u * g022,
        (2, 2, 2): g222 + uu * g022,
    }
    mp = _moment_tensor(mx, rho, 3)
    mp = _decentralize(mp, uy2, 1)
    mp = _decentralize(mp, uz2, 2)
    return _from_raw_moments(mp, 3), rho, (ux, uy, uz)


def collide_d2q9(F: jnp.ndarray, omega, omega_bulk=1.0,
                 force=(0.0, 0.0), correlated: bool = True):
    """2D analogue (reference d2q9_cumulant, src/d2q9_cumulant/Dynamics.c):
    ``F`` is (3, 3, *shape) with axes (x, y).  Returns (F', rho, (ux, uy))."""
    m = _raw_moments(F, 2)
    rho = m[0, 0]
    inv = 1.0 / rho
    ux = m[1, 0] * inv
    uy = m[0, 1] * inv

    k = _centralize(m, ux, 0)
    k = _centralize(k, uy, 1)

    kxx, kyy, kxy = k[2, 0], k[0, 2], k[1, 1]
    tr = kxx + kyy
    tr_p = tr + omega_bulk * (2.0 * rho / 3.0 - tr)
    d = (1.0 - omega) * (kxx - kyy) / 2.0
    kxx_p = tr_p / 2.0 + d
    kyy_p = tr_p / 2.0 - d
    kxy_p = (1.0 - omega) * kxy

    if correlated:
        g22 = (kxx_p * kyy_p + 2.0 * kxy_p * kxy_p) * inv
    else:
        g22 = kxx_p * kyy_p * inv

    kp = _moment_tensor({
        (0, 0): rho, (2, 0): kxx_p, (0, 2): kyy_p,
        (1, 1): kxy_p, (2, 2): g22,
    }, rho, 2)

    mp = _decentralize(kp, ux + force[0], 0)
    mp = _decentralize(mp, uy + force[1], 1)
    return _from_raw_moments(mp, 2), rho, (ux, uy)
