"""Pallas fused collide-stream kernel for the 3D d3q27 model family
(d3q27_BGK, d3q27_BGK_galcor, d3q27_cumulant).

The 3D counterpart of ops/pallas_d2q9.py — the TPU equivalent of the
reference's tuned CUDA hot loop (reference
src/LatticeContainer.inc.cpp.Rt:247-266 ``RunKernel``, the d3q27 cumulant
kernel src/d3q27_cumulant/Dynamics.c.Rt): one kernel per z-slab band does
pull-streaming, boundary handling and collision in a single pass, reading
each density once from HBM and writing it once.

Design (TPU-first):

* the lattice (nz, ny, nx) is tiled into **z-slab bands** of ``BZ`` slabs;
  each grid step DMAs its band plus one wrapped halo slab above and below
  into VMEM.  The (ny, nx) plane is the natural (sublane, lane) tile and
  stays whole — the baseline-scale 3D cases (e.g. the reference's
  256x48x48 forced channel, example/3d_channel_test_periodic_force_driven
  .xml) fit whole planes comfortably;
* pull-streaming is slab-select in z (the halo slabs make ``z ± 1``
  local), a static 1-row roll in y (sublane shift) and a lane-roll in x;
* the boundary dispatch reuses ``family.boundary_cases`` — the IDENTICAL
  closure the XLA path applies — masked over an int32 flag block, and the
  collision reuses ``ops.cumulant.collide_d3q27`` / the BGK equilibrium
  verbatim (those modules are written in Mosaic-safe primitives);
* scalar Settings ride in SMEM; zonal Velocity/Density (+Turbulence) are
  pre-gathered into per-node planes outside the kernel;
* like the d2q9 kernel this is the "NoGlobals" specialization
  (src/cuda.cu.Rt Globals-mode template): ``state.globals_`` is zeroed.
  The cumulant model's running averages (avgP/avgU) ARE accumulated, and
  SynthT coupling planes pass through untouched.

``present`` (an iterable of node-type names) restricts which boundary
cases are materialized: every case is full-plane compute-then-select, so
skipping absent types is pure win; parity holds whenever the caller passes
(a superset of) the types actually painted — :func:`present_types`
computes that set from the host flag field.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tclb_tpu.core import shift as ddf
from tclb_tpu.core.lattice import LatticeState, SimParams
from tclb_tpu.core.registry import Model
from tclb_tpu.models import family
from tclb_tpu.ops import cumulant, fusion, lbm
from tclb_tpu.ops.pallas_generic import _CompilerParams

_SUPPORTED = ("d3q27_BGK", "d3q27_BGK_galcor", "d3q27_cumulant",
              "d3q19", "d3q19_les")
# storage dtypes this family can keep in HBM.  Compute is ALWAYS f32:
# fields are cast up right after the VMEM read and cast back down on the
# output write, so bf16 halves HBM bytes per node without touching the
# collision arithmetic (the precision-ladder contract; bf16 runs are
# validated by the error-vs-f32 harness in tclb_tpu/precision.py, not
# by bit-parity).  The marker is also what analysis/precision.py keys
# its unsafe-accumulation scan on.
STORAGE_DTYPES = (jnp.float32, jnp.bfloat16)
_COMPUTE_DTYPE = jnp.float32
_VMEM_BUDGET = 15 * 1024 * 1024
# the fused (K>=2) kernel budgets against a raised Mosaic ceiling: its
# scratch is deliberately larger (K halo slabs per side, 2 slots) and the
# widest fused window's collision intermediates (~_TEMP_PLANES stacked
# q-plane tensors) must coexist with it
_FUSED_BUDGET = 80 * 1024 * 1024
_FUSED_VMEM_LIMIT = 100 * 1024 * 1024
_TEMP_PLANES = 6

E = cumulant.velocity_set(3)
W = lbm.weights(E)
OPP = lbm.opposite(E)

E19 = lbm.d3q19_velocities()
W19 = lbm.weights(E19)
OPP19 = lbm.opposite(E19)
M19 = lbm.gram_schmidt_basis(E19)


def _q_of(model: Model) -> int:
    return 19 if model.name.startswith("d3q19") else 27


_RING = 4   # ring capacity: slab j lives in slot j % 4 for its 3-step life


def _ring_ok(model: Model, nz: int, ny: int, nx: int,
             itemsize: int = 4) -> bool:
    """Whether the rolling-window (neighbor-slab reuse) kernel applies:
    one z-slab per grid step, ring of 4 resident slabs, each slab DMA'd
    from HBM ONCE per lattice step (vs (bz+2)/bz read amplification of
    the block kernel — the round-3 d3q27 number was exactly 3x-read
    bound).  Needs nz % 4 == 0 so the three live slabs always occupy
    distinct ring slots (consecutive slab indices are distinct mod 4,
    including across the periodic wrap)."""
    ns = model.n_storage
    q = _q_of(model)
    naux = ns - q
    per = ny * nx * itemsize
    need = (_RING * q + 2 * naux + 2 * ns + 2 * 4) * per
    return nz % _RING == 0 and nz >= 2 * _RING and need <= _VMEM_BUDGET


def _slab_depth(model: Model, nz: int, ny: int, nx: int,
                itemsize: int = 4) -> Optional[int]:
    """Largest band depth BZ dividing nz whose working set fits VMEM:
    scratch (ns, BZ+2) slabs + output block + flag/zonal blocks + the
    collision's live intermediates (~6 stacked q-plane tensors)."""
    ns = model.n_storage
    q = _q_of(model)
    naux = ns - q
    per = ny * nx * itemsize
    best = None
    for bz in range(1, nz + 1):
        if nz % bz:
            continue
        # 2-slot f scratch (halo'd) + 2-slot aux scratch + pipelined
        # out/flags/zonal blocks; collision intermediates live in what
        # remains of the ~16 MB VMEM (Mosaic errors loudly if they don't)
        need = (2 * q * (bz + 2) + 2 * naux * bz + 2 * ns * bz
                + 2 * 4 * bz) * per
        if need > _VMEM_BUDGET:
            break
        best = bz
    return best


def _n_zonal(model: Model) -> int:
    return 3 if model.name == "d3q27_cumulant" else 2


def _fused_fits(model: Model, nz: int, ny: int, nx: int,
                bz: int, K: int, itemsize: int = 4) -> bool:
    """VMEM predicate for the fused kernel at (bz, K): 2-slot halo'd
    f+aux buffers + 2-slot flag buffers + pipelined out blocks + the
    widest fused window's collision intermediates.  The DMA scratch
    scales with the storage itemsize; the collision temporaries are
    always compute-dtype (f32) planes."""
    ns = model.n_storage
    q = _q_of(model)
    per = ny * nx
    H = bz + 2 * K
    scratch = (2 * ns * H + 2 * ns * bz) * per * itemsize
    flagbuf = 2 * H * per * 4   # int32 flag buffer, itemsize-invariant
    temp = _TEMP_PLANES * q * (bz + 2 * (K - 1)) * per * 4
    return scratch + flagbuf + temp <= _FUSED_BUDGET


def _fused_cost(model: Model, bz: int, K: int) -> float:
    """Modeled HBM planes per lattice step of the fused kernel: the
    f+aux stack and the flag plane are read with K halo slabs per side,
    the ns output planes written halo-free, all amortized over K steps."""
    ns = model.n_storage
    return ((ns + 1) * (bz + 2 * K) + ns * bz) / (K * bz)


def _base_cost(model: Model, nz: int, ny: int, nx: int,
               itemsize: int = 4) -> float:
    """Best single-step engine's HBM planes per step (the bar a fused
    config must beat): the ring kernel reads each plane once; the block
    kernel pays (bz+2)/bz read amplification on the f planes."""
    ns = model.n_storage
    q = _q_of(model)
    zn = _n_zonal(model)
    if _ring_ok(model, nz, ny, nx, itemsize):
        return 2.0 * ns + 1 + zn
    bz = _slab_depth(model, nz, ny, nx, itemsize)
    if bz is None:
        return float("inf")
    return (q * (bz + 2) + (ns - q) * bz + (1 + zn) * bz + ns * bz) / bz


def fused_cfg(model: Model, shape, itemsize: int = 4) -> Optional[tuple]:
    """Production fused-kernel config ``(bz, K)`` for this shape, or
    None when single-step is the better (or only feasible) plan.
    Shared with analysis/resources.py so the static VMEM check audits
    exactly what the engine will build."""
    cfg, _ = fused_cfg_explain(model, shape, itemsize)
    return cfg


def fused_cfg_explain(model: Model, shape, itemsize: int = 4
                      ) -> tuple[Optional[tuple], Optional[str]]:
    """Planner verdict WITH its reason: ``((bz, K), None)`` when a fused
    config wins, else ``(None, reason)`` naming the failing predicate
    term — either no (bz, K) fits ``_FUSED_BUDGET`` (VMEM) or the best
    feasible fused traffic does not beat the single-step engine (cost).
    The Lattice dispatch forwards the reason as a ``fused_rejected``
    telemetry event so a silent single-step demotion (the PR-5 bench's
    untagged d3q27 engine) can never recur unnoticed."""
    if model.name not in _SUPPORTED or len(shape) != 3:
        return None, "unsupported: model/shape outside the tuned 3D family"
    nz, ny, nx = (int(s) for s in shape)
    base = _base_cost(model, nz, ny, nx, itemsize)
    cfg = fusion.choose_fuse_slab(
        nz,
        lambda bz, K: _fused_fits(model, nz, ny, nx, bz, K, itemsize),
        lambda bz, K: _fused_cost(model, bz, K),
        base)
    if cfg is not None:
        return cfg, None
    # no K >= 2 selected: re-walk the search recording WHY
    feasible = []
    for K in range(2, fusion.FUSE_MAX + 1):
        if nz < 2 * K:
            break
        bzs = [bz for bz in range(1, nz + 1) if nz % bz == 0
               and _fused_fits(model, nz, ny, nx, bz, K, itemsize)]
        if bzs:
            feasible.append((max(bzs), K))
    if not feasible:
        return None, (
            f"vmem: no (bz, K) fits _FUSED_BUDGET="
            f"{_FUSED_BUDGET // (1024 * 1024)}MB at shape "
            f"{(nz, ny, nx)} (scratch + {_TEMP_PLANES} temp planes/q)")
    bz_b, K_b = min(feasible,
                    key=lambda c: _fused_cost(model, c[0], c[1]))
    return None, (
        f"cost: best fused (bz={bz_b}, K={K_b}) models "
        f"{_fused_cost(model, bz_b, K_b):.2f} planes/step >= "
        f"single-step {base:.2f}")


def choose_fuse(model: Model, shape, itemsize: int = 4) -> int:
    """Fusion depth K the engine will run at (1 = single-step)."""
    cfg = fused_cfg(model, shape, itemsize)
    return cfg[1] if cfg else 1


def supports(model: Model, shape, dtype, ext_halo: bool = False) -> bool:
    """Whether the fused 3D kernel can run this configuration.

    ``ext_halo=True`` asks about the sharded building block, which only
    has the block kernel — ring-only shapes (whose block working set
    exceeds VMEM) must answer False there so parallel/halo.py falls back
    cleanly instead of building a kernel Mosaic will reject."""
    if model.name not in _SUPPORTED:
        return False
    if len(shape) != 3 or jnp.dtype(dtype) not in (
            jnp.dtype(d) for d in STORAGE_DTYPES):
        return False
    if ext_halo and jnp.dtype(dtype) != jnp.dtype(jnp.float32):
        return False   # the sharded composition is f32-only (bit-parity)
    itemsize = jnp.dtype(dtype).itemsize
    nz, ny, nx = (int(s) for s in shape)
    if jax.default_backend() == "tpu" and (nx % 128 or ny % 8):
        return False  # (ny, nx) is the (sublane, lane) tile
    if _slab_depth(model, nz, ny, nx, itemsize) is not None:
        return True
    return (not ext_halo) and _ring_ok(model, nz, ny, nx, itemsize)


present_types = lbm.present_types   # shared helper (re-exported)


def make_pallas_iterate(model: Model, shape, dtype=jnp.float32,
                        interpret: Optional[bool] = None,
                        present: Optional[Iterable[str]] = None,
                        ext_halo: bool = False,
                        fuse: Optional[int] = None,
                        fuse_bz: Optional[int] = None,
                        shift: Optional[np.ndarray] = None):
    """Build ``iterate(state, params, niter) -> state`` running the fused
    3D Pallas kernel.  Caller must check :func:`supports` first.

    ``fuse=K`` runs K lattice steps per HBM round trip (temporal fusion:
    K wrapped halo slabs per side, valid interior shrinking one slab per
    step — the progressive-extension scheme the 2D band engines use);
    ``fuse=None`` picks (bz, K) from the VMEM budget via the shared
    planner (:func:`fused_cfg`), ``fuse=1`` forces the single-step
    block/ring kernels.  ``fuse_bz`` overrides the fused band depth
    (tests use it to exercise nz % (bz*K) != 0 layouts).

    ``ext_halo=True`` builds the sharded building block: ``shape`` is one
    device's z-block, the input stack carries ONE exchanged halo slab at
    each end ((ns, nz+2, ny, nx)) and the kernel reads those instead of
    wrapping; returns ``(call, bz)`` for parallel/halo.py to compose with
    ``ppermute``."""
    if not supports(model, shape, dtype):
        raise ValueError(f"pallas path unsupported for {model.name} {shape}")
    # storage dtype (what HBM holds) vs compute dtype (what the collision
    # arithmetic runs in).  At f32 storage the casts below are traced
    # no-ops, so the bit-parity contract with the XLA path is untouched;
    # at bf16 every field value is widened right after the VMEM read and
    # narrowed on the output write (accumulate-in-f32 — the
    # precision.unsafe_accum contract)
    cdtype = _COMPUTE_DTYPE
    itemsize = jnp.dtype(dtype).itemsize
    nz, ny, nx = (int(s) for s in shape)
    bz = _slab_depth(model, nz, ny, nx, itemsize) or 1
    if ext_halo:
        fuse = 1
    if fuse is None:
        cfg = fused_cfg(model, shape, itemsize)
    else:
        cfg = None
        if fuse >= 2:
            bzf = fuse_bz
            if bzf is None:
                bzf = max(b for b in range(1, nz + 1) if nz % b == 0
                          and (b == 1
                               or _fused_fits(model, nz, ny, nx, b, fuse,
                                              itemsize)))
            if nz % bzf:
                raise ValueError(f"fused band depth {bzf} must divide {nz}")
            cfg = (bzf, fuse)
    K = cfg[1] if cfg else 1
    bzK = cfg[0] if cfg else bz
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    is_cumulant = model.name == "d3q27_cumulant"
    galcor = model.name.endswith("galcor")
    q = _q_of(model)
    is_les = model.name == "d3q19_les"
    E_, W_, OPP_ = (E19, W19, OPP19) if q == 19 else (E, W, OPP)

    ns = model.n_storage
    f_idx = list(model.groups["f"])
    assert f_idx == list(range(q)), "kernel assumes f planes lead the stack"
    # per-plane DDF shift at the DMA seams: the f group widens/narrows
    # by its lattice weight, aux planes (SynthT/avg) by nothing — with
    # shift=None every helper call is a pure astype (raw contract)
    _shifts = ([None] * ns if shift is None
               else [float(w) or None for w in shift])
    si = model.setting_index
    sidx = model.storage_index
    nt = {n: (int(t.mask), int(t.value)) for n, t in model.node_types.items()}
    coll_mask = int(model.group_masks["COLLISION"])
    present = set(nt) if present is None else set(present)

    zonal_names = ["Velocity", "Density"] + \
        (["Turbulence"] if is_cumulant else [])
    if is_cumulant:
        synth_idx = [sidx[n] for n in ("SynthTX", "SynthTY", "SynthTZ")]
        avgp_idx = sidx["avgP"]
        avgu_idx = [sidx[n] for n in ("avgUX", "avgUY", "avgUZ")]
        aux_idx = synth_idx + [avgp_idx] + avgu_idx
    else:
        aux_idx = []
    # aux planes are DMA'd in storage order and read back by position:
    # the kernel's scra indexing assumes aux_idx IS ascending q..ns-1,
    # not merely covering it (a model registering avg/SynthT densities in
    # a different order would silently read wrong planes)
    assert f_idx + aux_idx == list(range(ns))
    zshift = model.zone_shift
    zone_max = model.zone_max
    zonal_si = [si[n] for n in zonal_names]

    def _is(flags, name):
        mask, val = nt[name]
        return (flags & jnp.int32(mask)) == jnp.int32(val)

    def _step(f, flags, zonal, synth, sett):
        """Boundaries + collision on one band — op-for-op the model's
        ``run`` (models/d3q27_bgk.py, models/d3q27_cumulant.py), minus
        globals."""
        vel, den = zonal[0], zonal[1]
        extra = None
        if is_cumulant:
            turb = zonal[2]
            turb_u = vel + turb * synth[0]
            extra = {"WVelocityTurbulent": lambda f: lbm.nebb_boundary(
                E, W, OPP, f, 0, +1, "velocity", turb_u,
                vt={1: turb * synth[1], 2: turb * synth[2]})}
        cases = family.boundary_cases(model, E_, W_, OPP_, vel, den, extra)
        f = family.dispatch_boundary_cases(
            cases, f, lambda n: _is(flags, n), present)

        coll = (flags & jnp.int32(coll_mask)) != jnp.int32(0)
        if is_cumulant:
            om = jnp.where(
                _is(flags, "Buffer"),
                1.0 / (3.0 * sett[si["nubuffer"]] + 0.5),
                sett[si["omega"]]).astype(f.dtype)
            force = tuple(sett[si[f"Force{a}"]] + sett[si[f"Gravitation{a}"]]
                          for a in "XYZ")
            F = f.reshape((3, 3, 3) + f.shape[1:])
            Fp, rho, (ux, uy, uz) = cumulant.collide_d3q27(
                F, om, sett[si["omega_bulk"]], force=force, correlated=True,
                galilean=sett[si["GalileanCorrection"]])
            f = jnp.where(coll[None], Fp.reshape(f.shape), f)
            return f, ((rho - 1.0) / 3.0, (ux, uy, uz))
        if q == 19:
            # rho/u spelled exactly as models/d3q19.py computes them
            # (jnp.sum reduce + edot) so the kernel is bit-identical to
            # the XLA path, not merely allclose.  The barriers pin the
            # collision's input (the boundary select chain) and output
            # (before the coll select): fused, either select alters the
            # FMA contraction of the relaxation arithmetic, which in the
            # XLA path lowers contraction-free — same 1-ULP class as the
            # streaming-roll barrier above
            f = jax.lax.optimization_barrier(f)
            rho = jnp.sum(f, axis=0)
            u = tuple(lbm.edot(E19[:, a], f) / rho for a in range(3))
            feq = lbm.equilibrium(E19, W19, rho, u)
            g = tuple(sett[si[f"Gravitation{a}"]] for a in "XYZ")
            u2 = tuple(u[a] + g[a] for a in range(3))
            feq2 = lbm.equilibrium(E19, W19, rho, u2)
            if is_les:
                # BGK + Smagorinsky (models/d3q19_les.py), shared
                # Mosaic-safe unrolled |Pi| helper
                om_eff = lbm.smagorinsky_omega_unrolled(
                    E19, f, feq, rho, sett[si["omega"]], sett[si["Smag"]])
                fc = jnp.stack([f[k] + om_eff * (feq[k] - f[k])
                                + (feq2[k] - feq[k]) for k in range(19)])
            else:
                # MRT (models/d3q19.py): the shared two-rate
                # stress-projection relaxation — only 6 rank-one
                # projections instead of the 15-row transform pair
                fneq = [f[k] - feq[k] for k in range(19)]
                relax = lbm.two_rate_relax(
                    M19, 4, 10, fneq,
                    1.0 - sett[si["omega"]], 1.0 - sett[si["S_high"]])
                fc = jnp.stack([relax[k] + feq2[k] for k in range(19)])
            fc = jax.lax.optimization_barrier(fc)
            return jnp.where(coll[None], fc, f), None
        from tclb_tpu.models.d3q27_bgk import _equilibrium
        rho = jnp.sum(f, axis=0)
        u = tuple(lbm.edot(E[:, a], f) / rho for a in range(3))
        om = sett[si["omega"]]
        feq = _equilibrium(rho, u, galcor)
        fc = f + om * (feq - f)
        g = tuple(sett[si[f"Gravitation{a}"]] for a in "XYZ")
        u2 = tuple(u[a] + g[a] for a in range(3))
        fc = fc + (_equilibrium(rho, u2, galcor) - feq)
        return jnp.where(coll[None], fc, f), None

    naux = len(aux_idx)
    ring_mode = (not ext_halo) and _ring_ok(model, nz, ny, nx, itemsize)

    def kernel_ring(sett, f_hbm, flags_ref, zonal_ref, out_ref, ring, scra,
                    sems, sems_a):
        """Rolling-window kernel: one z-slab per grid step, 4-slot ring of
        resident slabs (slab j lives in slot j % 4 for its 3-step life:
        prefetched at step j-2, read as z+1 / z / z-1 at steps j-1, j,
        j+1).  Each slab is DMA'd from HBM ONCE per lattice step — the
        neighbor-slab reuse that removes the block kernel's (bz+2)/bz
        read amplification (round-3 VERDICT Weak #2: the d3q27 cumulant
        was exactly 3x-read bound at bz=1).  The periodic wrap re-fetches
        slab 0 at step nz-2 (slot nz % 4 == 0 — hence the nz % 4 == 0
        eligibility), so no stale slot is ever read."""
        i = pl.program_id(0)
        n = pl.num_programs(0)   # == nz
        R = jnp.int32(_RING)

        def slab_dma(j, slot):
            return pltpu.make_async_copy(
                f_hbm.at[pl.ds(0, q), pl.ds(j, 1)],
                ring.at[slot], sems.at[slot])

        def aux_dma(j, slot):
            return pltpu.make_async_copy(
                f_hbm.at[pl.ds(q, naux), pl.ds(j, 1)],
                scra.at[slot], sems_a.at[slot])

        zm = jax.lax.rem(i - 1 + jnp.int32(n), jnp.int32(n))
        zp = jax.lax.rem(i + 1, jnp.int32(n))
        slot_m = jax.lax.rem(zm, R)
        slot_0 = jax.lax.rem(i, R)
        slot_p = jax.lax.rem(zp, R)

        @pl.when(i == 0)
        def _():
            # initial fill: the first step's three slabs
            slab_dma(zm, slot_m).start()
            slab_dma(jnp.int32(0), jnp.int32(0)).start()
            if naux:
                aux_dma(jnp.int32(0), jnp.int32(0)).start()

        @pl.when(i + 1 < n)
        def _():
            # prefetch slab i+2 for step i+1's z+1 read (slot (i+2)%4 is
            # free: its previous occupant, slab i-2, was last read at
            # step i-1; the wrap re-fetch of slab 0 lands in slot 0 at
            # step nz-2, after slot 0's occupant was last read)
            nxt_slab = jax.lax.rem(i + 2, jnp.int32(n))
            slab_dma(nxt_slab, jax.lax.rem(nxt_slab, R)).start()
            if naux:
                aux_dma(zp, jax.lax.rem(zp, jnp.int32(2))).start()

        @pl.when(i == 0)
        def _():
            # slab 1 (step 0's z+1) — the prefetch chain starts at slab 2
            slab_dma(jnp.int32(1), jnp.int32(1)).start()

        # waits: first use of each slab decrements its slot's semaphore
        @pl.when(i == 0)
        def _():
            slab_dma(zm, slot_m).wait()
            slab_dma(jnp.int32(0), jnp.int32(0)).wait()
            if naux:
                aux_dma(jnp.int32(0), jnp.int32(0)).wait()
        slab_dma(zp, slot_p).wait()
        aslot = jax.lax.rem(i, jnp.int32(2))
        if naux:
            @pl.when(i > 0)
            def _():
                aux_dma(i, aslot).wait()

        pulled = []
        for k in range(q):
            dx, dy, dz = int(E_[k, 0]), int(E_[k, 1]), int(E_[k, 2])
            slot = slot_m if dz == 1 else (slot_p if dz == -1 else slot_0)
            sl = ring[slot, k]          # (1, ny, nx)
            if dy:
                sl = jnp.roll(sl, dy, axis=1)
            if dx:
                sl = pltpu.roll(sl, dx % nx, axis=2)
            pulled.append(sl)
        # the barrier pins the streamed values before collision: without
        # it the compiler fuses the rolls into the collide arithmetic,
        # changing FMA contraction and breaking bit-parity with the XLA
        # path (where streaming materializes before the collide fusion).
        # the widen seam restores bf16 storage to the f32 compute dtype
        # (+ the per-plane DDF shift under the shifted representation —
        # scalar immediates, a Pallas kernel cannot capture an array
        # constant; no-op at f32/raw storage, so the parity contract is
        # untouched)
        f = jax.lax.optimization_barrier(
            jnp.stack([ddf.widen_plane(p, cdtype, _shifts[k])
                       for k, p in enumerate(pulled)]))
        flags = flags_ref[:]
        zonal = zonal_ref[:]
        synth = [ddf.widen_plane(scra[aslot, aux_idx.index(j)], cdtype,
                                 _shifts[j])
                 for j in synth_idx] if is_cumulant else None
        fnew, extras = _step(f, flags, zonal, synth, sett)
        for k in range(q):
            out_ref[k] = ddf.narrow_plane(fnew[k], dtype, _shifts[k])
        if is_cumulant:
            for j in synth_idx:
                out_ref[j] = scra[aslot, aux_idx.index(j)]
            p_inc, (ux, uy, uz) = extras
            out_ref[avgp_idx] = ddf.narrow_plane(
                ddf.widen_plane(scra[aslot, aux_idx.index(avgp_idx)],
                                cdtype, _shifts[avgp_idx])
                + p_inc, dtype, _shifts[avgp_idx])
            for j, u in zip(avgu_idx, (ux, uy, uz)):
                out_ref[j] = ddf.narrow_plane(
                    ddf.widen_plane(scra[aslot, aux_idx.index(j)], cdtype,
                                    _shifts[j])
                    + u, dtype, _shifts[j])

    def kernel(sett, f_hbm, flags_ref, zonal_ref, out_ref, scrf, scra, sems):
        # 2-slot double buffering: band i+1's DMAs are issued before band
        # i's compute, overlapping HBM fetch with VPU work across grid
        # steps (the reference gets the same overlap from its border/
        # interior kernel split + async memcpy streams,
        # src/Lattice.cu.Rt:424-456).  f planes get z±1 halo slabs; aux
        # planes (SynthT/avg) are local-only and skip the halo.
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def band_dmas(slot, band):
            base = band * jnp.int32(bz)
            if ext_halo:
                # input slabs are [halo(1) | local nz | halo(1)]: the band
                # lives at base+1, halos at base and base+1+bz — no wrap,
                # the exchanged slabs ARE the neighbors
                mid1 = base + jnp.int32(1)
                zm = base
                zp = base + jnp.int32(1 + bz)
            else:
                mid1 = base
                zm = jax.lax.rem(base - jnp.int32(1) + jnp.int32(nz),
                                 jnp.int32(nz))
                zp = jax.lax.rem(base + jnp.int32(bz), jnp.int32(nz))
            copies = [
                pltpu.make_async_copy(f_hbm.at[pl.ds(0, q), pl.ds(mid1, bz)],
                                      scrf.at[slot, :, pl.ds(1, bz)],
                                      sems.at[slot, 0]),
                pltpu.make_async_copy(f_hbm.at[pl.ds(0, q), pl.ds(zm, 1)],
                                      scrf.at[slot, :, pl.ds(0, 1)],
                                      sems.at[slot, 1]),
                pltpu.make_async_copy(f_hbm.at[pl.ds(0, q), pl.ds(zp, 1)],
                                      scrf.at[slot, :, pl.ds(bz + 1, 1)],
                                      sems.at[slot, 2]),
            ]
            if naux:
                copies.append(pltpu.make_async_copy(
                    f_hbm.at[pl.ds(q, naux), pl.ds(mid1, bz)],
                    scra.at[slot], sems.at[slot, 3]))
            return copies

        slot = jax.lax.rem(i, jnp.int32(2))
        nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

        @pl.when(i == 0)
        def _():
            for c in band_dmas(jnp.int32(0), i):
                c.start()

        @pl.when(i + 1 < n)
        def _():
            for c in band_dmas(nxt, i + jnp.int32(1)):
                c.start()

        for c in band_dmas(slot, i):
            c.wait()

        # pull-streaming: f_k(z,y,x) <- f_k(z-dz, y-dy, x-dx); halo slabs
        # cover z +- 1, a static sublane roll covers y, a lane-roll x
        # (matches core.lattice.pull_stream's periodic jnp.roll semantics)
        pulled = []
        for k in range(q):
            dx, dy, dz = int(E_[k, 0]), int(E_[k, 1]), int(E_[k, 2])
            sl = scrf[slot, k, 1 - dz:1 - dz + bz]
            if dy:
                sl = jnp.roll(sl, dy, axis=1)
            if dx:
                sl = pltpu.roll(sl, dx % nx, axis=2)
            pulled.append(sl)
        # the barrier pins the streamed values before collision: without
        # it the compiler fuses the rolls into the collide arithmetic,
        # changing FMA contraction and breaking bit-parity with the XLA
        # path (where streaming materializes before the collide fusion);
        # the widen seam restores bf16 storage to the f32 compute dtype
        # (+ the per-plane DDF shift under the shifted representation)
        f = jax.lax.optimization_barrier(
            jnp.stack([ddf.widen_plane(p, cdtype, _shifts[k])
                       for k, p in enumerate(pulled)]))
        flags = flags_ref[:]
        zonal = zonal_ref[:]
        synth = [ddf.widen_plane(scra[slot, aux_idx.index(j)], cdtype,
                                 _shifts[j])
                 for j in synth_idx] if is_cumulant else None
        fnew, extras = _step(f, flags, zonal, synth, sett)
        for k in range(q):
            out_ref[k] = ddf.narrow_plane(fnew[k], dtype, _shifts[k])
        if is_cumulant:
            # SynthT passthrough; running averages accumulate per step
            # (reference average=T densities + Lattice::resetAverage)
            for j in synth_idx:
                out_ref[j] = scra[slot, aux_idx.index(j)]
            p_inc, (ux, uy, uz) = extras
            out_ref[avgp_idx] = ddf.narrow_plane(
                ddf.widen_plane(scra[slot, aux_idx.index(avgp_idx)],
                                cdtype, _shifts[avgp_idx])
                + p_inc, dtype, _shifts[avgp_idx])
            for j, u in zip(avgu_idx, (ux, uy, uz)):
                out_ref[j] = ddf.narrow_plane(
                    ddf.widen_plane(scra[slot, aux_idx.index(j)], cdtype,
                                    _shifts[j])
                    + u, dtype, _shifts[j])

    if ring_mode:
        call = pl.pallas_call(
            kernel_ring,
            grid=(nz,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((1, ny, nx), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((len(zonal_names), 1, ny, nx),
                             lambda i: (0, i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((ns, 1, ny, nx), lambda i: (0, i, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((ns, nz, ny, nx), dtype),
            scratch_shapes=[
                pltpu.VMEM((_RING, q, 1, ny, nx), dtype),
                pltpu.VMEM((2, max(naux, 1), 1, ny, nx), dtype),
                pltpu.SemaphoreType.DMA((_RING,)),
                pltpu.SemaphoreType.DMA((2,)),
            ],
            interpret=interpret,
        )
    else:
        call = pl.pallas_call(
            kernel,
            grid=(nz // bz,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec((bz, ny, nx), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((len(zonal_names), bz, ny, nx),
                             lambda i: (0, i, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((ns, bz, ny, nx), lambda i: (0, i, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((ns, nz, ny, nx), dtype),
            scratch_shapes=[
                pltpu.VMEM((2, q, bz + 2, ny, nx), dtype),
                pltpu.VMEM((2, max(naux, 1), bz, ny, nx), dtype),
                pltpu.SemaphoreType.DMA((2, 4)),
            ],
            interpret=interpret,
        )

    if ext_halo:
        # zonal_names rides along so callers stack the zonal planes in
        # exactly the order this kernel's zonal_ref expects
        return call, bz, zonal_names

    H = bzK + 2 * K   # fused buffer depth: band + K wrapped halo slabs/side

    def kernel_fused(sett, ztab, f_hbm, flags_hbm, out_ref, scrf, scrg,
                     sems):
        """Multi-step fused band kernel: K lattice steps per HBM round
        trip.  The DMA'd buffer carries K wrapped halo slabs per side
        (f + aux stack AND flags — boundary dispatch in the halo region
        needs true node types so the recomputed halo sites agree with
        their home band's values); step j (0-based) computes buffer rows
        [j+1, H-(j+1)) from rows [j, H-j) of the step-(j-1) state, so
        after K steps rows [K, K+bz) hold the valid K-step-advanced
        band.  Zonal settings never ride the DMA: they are a pure
        function of the flag zone bits and the SMEM zone table, so they
        are reconstructed in-kernel (fusion.zone_plane) — the same aux
        diet the generic engine runs.  The 2-slot double-buffered band
        pipeline is kept: band i+1's (wider) blocks prefetch under band
        i's K-step compute."""
        i = pl.program_id(0)
        n = pl.num_programs(0)

        def band_dmas(slot, band):
            base = band * jnp.int32(bzK)
            copies = [
                pltpu.make_async_copy(
                    f_hbm.at[:, pl.ds(base, bzK)],
                    scrf.at[slot, :, pl.ds(K, bzK)], sems.at[slot, 0]),
                pltpu.make_async_copy(
                    flags_hbm.at[pl.ds(base, bzK)],
                    scrg.at[slot, pl.ds(K, bzK)], sems.at[slot, 1]),
            ]
            # halo slabs copied one at a time with individual wrapped
            # indices (a block copy would straddle the periodic seam)
            for h in range(1, K + 1):
                zm = jax.lax.rem(base - jnp.int32(h) + jnp.int32(nz),
                                 jnp.int32(nz))
                zp = jax.lax.rem(base + jnp.int32(bzK - 1 + h),
                                 jnp.int32(nz))
                s = 2 + 4 * (h - 1)
                copies += [
                    pltpu.make_async_copy(
                        f_hbm.at[:, pl.ds(zm, 1)],
                        scrf.at[slot, :, pl.ds(K - h, 1)],
                        sems.at[slot, s]),
                    pltpu.make_async_copy(
                        f_hbm.at[:, pl.ds(zp, 1)],
                        scrf.at[slot, :, pl.ds(K + bzK - 1 + h, 1)],
                        sems.at[slot, s + 1]),
                    pltpu.make_async_copy(
                        flags_hbm.at[pl.ds(zm, 1)],
                        scrg.at[slot, pl.ds(K - h, 1)],
                        sems.at[slot, s + 2]),
                    pltpu.make_async_copy(
                        flags_hbm.at[pl.ds(zp, 1)],
                        scrg.at[slot, pl.ds(K + bzK - 1 + h, 1)],
                        sems.at[slot, s + 3]),
                ]
            return copies

        slot = jax.lax.rem(i, jnp.int32(2))
        nxt = jax.lax.rem(i + jnp.int32(1), jnp.int32(2))

        @pl.when(i == 0)
        def _():
            for c in band_dmas(jnp.int32(0), i):
                c.start()

        @pl.when(i + 1 < n)
        def _():
            for c in band_dmas(nxt, i + jnp.int32(1)):
                c.start()

        for c in band_dmas(slot, i):
            c.wait()

        flagbuf = scrg[slot]
        zones = flagbuf >> zshift
        zonalbuf = [fusion.zone_plane(ztab, c, zone_max, zones)
                    for c in range(len(zonal_names))]
        synthbuf = [ddf.widen_plane(scrf[slot, j], cdtype, _shifts[j])
                    for j in synth_idx] if is_cumulant else None
        if is_cumulant:
            # widen ONCE, accumulate all K steps in f32, narrow on the
            # output write (the precision.unsafe_accum contract)
            acc_p = ddf.widen_plane(scrf[slot, avgp_idx, K:K + bzK],
                                    cdtype, _shifts[avgp_idx])
            acc_u = [ddf.widen_plane(scrf[slot, j, K:K + bzK], cdtype,
                                     _shifts[j])
                     for j in avgu_idx]

        # rows [0, H); widened to the compute dtype for the step chain
        # (the DDF shift restores once here and removes once at the
        # final narrow: all K in-between steps run on raw f in f32)
        cur = [ddf.widen_plane(scrf[slot, k], cdtype, _shifts[k])
               for k in range(q)]
        for j in range(K):
            lo = j + 1                       # output window in buffer rows
            n_j = bzK + 2 * (K - 1 - j)
            pulled = []
            for k in range(q):
                dx, dy, dz = int(E_[k, 0]), int(E_[k, 1]), int(E_[k, 2])
                a = lo - dz - j              # cur[k] covers rows [j, H-j)
                sl = cur[k][a:a + n_j]
                if dy:
                    sl = jnp.roll(sl, dy, axis=1)
                if dx:
                    sl = pltpu.roll(sl, dx % nx, axis=2)
                pulled.append(sl)
            # barrier before collision, same reason as the single-step
            # kernels: keep the rolls out of the collide fusion so every
            # fused step's arithmetic is bit-identical to an XLA step
            f = jax.lax.optimization_barrier(jnp.stack(pulled))
            flags = flagbuf[lo:lo + n_j]
            zonal = [zb[lo:lo + n_j] for zb in zonalbuf]
            synth = [sb[lo:lo + n_j] for sb in synthbuf] \
                if is_cumulant else None
            fnew, extras = _step(f, flags, zonal, synth, sett)
            cur = [fnew[k] for k in range(q)]   # now rows [lo, lo + n_j)
            if is_cumulant:
                # running averages accumulate on the central band only,
                # in the same left-fold order as K single XLA steps
                c0 = K - lo
                p_inc, us = extras
                acc_p = acc_p + p_inc[c0:c0 + bzK]
                acc_u = [au + u[c0:c0 + bzK] for au, u in zip(acc_u, us)]

        for k in range(q):
            out_ref[k] = ddf.narrow_plane(cur[k], dtype, _shifts[k])
        if is_cumulant:
            for j in synth_idx:
                out_ref[j] = scrf[slot, j, K:K + bzK]
            out_ref[avgp_idx] = ddf.narrow_plane(acc_p, dtype,
                                                 _shifts[avgp_idx])
            for j, au in zip(avgu_idx, acc_u):
                out_ref[j] = ddf.narrow_plane(au, dtype, _shifts[j])

    if K >= 2:
        call_f = pl.pallas_call(
            kernel_fused,
            grid=(nz // bzK,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((ns, bzK, ny, nx), lambda i: (0, i, 0, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((ns, nz, ny, nx), dtype),
            scratch_shapes=[
                pltpu.VMEM((2, ns, H, ny, nx), dtype),
                pltpu.VMEM((2, H, ny, nx), jnp.int32),
                pltpu.SemaphoreType.DMA((2, 2 + 4 * K)),
            ],
            interpret=interpret,
            compiler_params=_CompilerParams(
                vmem_limit_bytes=_FUSED_VMEM_LIMIT),
        )

    @partial(jax.jit, static_argnames=("niter",), donate_argnums=0)
    def _iterate_jit(state: LatticeState, params: SimParams,
                     niter: int) -> LatticeState:
        flags_i32 = state.flags.astype(jnp.int32)
        zones = flags_i32 >> zshift
        # zonal planes, settings and the SMEM zone table ride in the
        # COMPUTE dtype: only the field stack pays the storage narrowing
        zonal = jnp.stack([params.zone_table[j].astype(cdtype)[zones]
                           for j in zonal_si])
        sett = params.settings.astype(cdtype)
        fields = state.fields.astype(dtype)

        if K >= 2:
            ztab = jnp.concatenate(
                [params.zone_table[j].astype(cdtype) for j in zonal_si])

            def body_f(fields, _):
                return call_f(sett, ztab, fields, flags_i32), None

            fields, _ = jax.lax.scan(body_f, fields, None,
                                     length=niter // K)

        def body(fields, _):
            return call(sett, fields, flags_i32, zonal), None

        rem = niter % K if K >= 2 else niter
        fields, _ = jax.lax.scan(body, fields, None, length=rem)
        return LatticeState(
            fields=fields,
            flags=state.flags,
            globals_=jnp.zeros_like(state.globals_),
            iteration=state.iteration + niter,
        )

    def iterate(state: LatticeState, params: SimParams, niter: int
                ) -> LatticeState:
        if params.time_series is not None:
            raise ValueError(
                "pallas iterate does not support Control time series; "
                "use the XLA path for time-dependent zonal settings")
        return _iterate_jit(state, params, niter)

    return iterate
