"""Shared temporal-fusion planner for the band/slab Pallas engines.

Temporal fusion runs ``K`` lattice steps per HBM round trip: the DMA'd
band carries ``K * reach`` halo rows/slabs per side and each fused step
shrinks the valid interior by one reach (the progressive-extension
scheme ops/pallas_generic.py introduced in 2D).  Amortized traffic per
step drops from ``reads + writes`` to roughly
``(reads * (b + 2*K*reach) / b + writes) / K`` planes, which is why the
fused 2D engines sit at ~0.9x roofline while unfused band kernels are
read-amplification bound.

This module holds the *planning* logic — picking the fusion depth ``K``
(and slab depth ``bz`` in 3D) from the VMEM budget and the traffic
model — so the 2D band engine, the 3D generic slab engine and the tuned
d3q slab engine all make the same decision the same way.  It also holds
the in-kernel zonal-plane reconstruction used by the lean aux flavors
(flags are DMA'd; zonal settings are a pure function of the zone bits
and the SMEM zone table, so shipping them as planes is wasted HBM
traffic).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Tuple

import jax.numpy as jnp

FUSE_MAX = 8   # halo growth is priced by the planners; beyond 8 the
#                amortized read term (b + 2*K*reach)/b stops improving
#                faster than the halo cost grows for every model we ship


def choose_fuse_band(reach_of: Callable[[int], int], halo: int,
                     fmax: int = FUSE_MAX) -> int:
    """Largest fuse depth whose fused-plan reach fits a fixed band halo.

    ``reach_of(f)`` returns the total stencil reach of the f-step fused
    action plan (monotone in ``f``); ``halo`` is the rows the band
    kernel DMAs per side.  Used by the 2D band engines, where the halo
    is a fixed 8-row (sublane-aligned) block.
    """
    best = 1
    for f in range(2, fmax + 1):
        try:
            r = reach_of(f)
        except Exception:
            break
        if r > halo:
            break
        best = f
    return best


def choose_fuse_slab(nz: int, fits: Callable[[int, int], bool],
                     cost: Callable[[int, int], float],
                     base_cost: float, reach: int = 1,
                     fmax: int = FUSE_MAX) -> Optional[Tuple[int, int]]:
    """Pick ``(bz, K)`` minimizing amortized HBM traffic for a fused
    z-slab kernel, or None when no ``K >= 2`` config is feasible and
    cheaper than the best single-step engine.

    ``fits(bz, K)`` is the VMEM-budget predicate (monotone in ``bz``);
    ``cost(bz, K)`` the modeled planes-per-step traffic; ``base_cost``
    the best available K=1 engine's traffic — a fused config must beat
    it to be worth the wider halo.  For each K the largest feasible
    band depth dividing ``nz`` is used (traffic is decreasing in bz).
    """
    best, best_c = None, base_cost
    for K in range(2, fmax + 1):
        if nz < 2 * K * max(reach, 1):
            break
        bz_best = None
        for bz in range(1, nz + 1):
            if nz % bz:
                continue
            if not fits(bz, K):
                break
            bz_best = bz
        if bz_best is None:
            continue
        c = cost(bz_best, K)
        if c < best_c:
            best, best_c = (bz_best, K), c
    return best


ADJ_HALO_MAX = 8   # max halo slabs per side the fused 3D backward DMAs:
#                    the adjoint band needs 2*reach(K) slabs per side
#                    (cotangent cone + recompute cone), and past 8 the
#                    one-slab-at-a-time modular halo copies cost more
#                    HBM round trips than the fused chunk saves


def adjoint_slab_plan(nz: int, n_storage: int, plane_bytes: int,
                      reach_of: Callable[[int], int], k_max: int,
                      n_aux: int = 1,
                      budget: Optional[int] = None,
                      halo_max: int = ADJ_HALO_MAX
                      ) -> Optional[Tuple[int, int]]:
    """Pick ``(K, bz)`` for the fused 3D BACKWARD slab kernel, or None.

    The backward band holds THREE double-buffered stacks (chunk-input
    primal, output-cotangent, flags/aux) at height ``bz + 4*reach(K)``
    — 2R halo slabs per side, twice the forward's R, because the
    in-band VJP both recomputes the forward cone AND widens it again
    transposing it (the adjoint-band rule analysis/footprint.py pins).
    ``K`` is restricted to divisors of ``k_max`` so the caller's chunk
    loop (``niter % k == 0`` from the engine picker) stays exact, and
    to ``2*reach(K) <= halo_max`` / ``nz >= 2*reach(K)`` so the modular
    halo DMAs index true slabs.  Among feasible configs the amortized
    planes-per-step traffic decides; ties go to the deeper chunk.
    """
    if budget is None:
        budget = 24 * 1024 * 1024
    best, best_c = None, None
    for k in range(1, max(1, k_max) + 1):
        if k_max % k:
            continue
        try:
            r = max(int(reach_of(k)), 1)
        except Exception:
            break
        if 2 * r > halo_max or nz < 2 * r:
            continue
        per_slab = (2 * n_storage + n_aux) * plane_bytes
        bz_best = None
        for bz in range(1, nz + 1):
            if nz % bz:
                continue
            if 2 * (bz + 4 * r) * per_slab > budget:
                break
            bz_best = bz
        if bz_best is None:
            continue
        c = ((2 * n_storage + n_aux) * (bz_best + 4 * r)
             + n_storage * bz_best) / float(k * bz_best)
        if best_c is None or c < best_c - 1e-9:
            best, best_c = (k, bz_best), c
    return best


ENSEMBLE_BATCH_MAX = 256   # scheduling sanity cap, not a memory bound


def ensemble_batch_cap(n_storage: int, shape: Tuple[int, ...],
                       itemsize: int,
                       budget_bytes: Optional[int] = None,
                       bmax: int = ENSEMBLE_BATCH_MAX) -> int:
    """Largest ensemble batch whose working set fits the serving budget.

    The same shape of reasoning as the slab engines' VMEM predicates
    (pallas_d3q ``_fused_fits``), applied at the device-memory level the
    batched XLA engine lives at: per case the scan keeps the stacked
    fields twice (carry in + carry out — donation collapses the steady
    state to ~2x) plus one streamed temporary, and flags ride along.

    ``budget_bytes`` defaults to ``TCLB_SERVE_BUDGET_MB`` (MB) or 2 GiB —
    deliberately a fraction of any real device so a full sweep never
    OOMs the executor that also holds the compiled-executable cache.
    Always returns at least 1 (a single case must run regardless; if even
    that thrashes, the budget was a lie the allocator will report).
    """
    if budget_bytes is None:
        import os
        mb = os.environ.get("TCLB_SERVE_BUDGET_MB")
        budget_bytes = (int(mb) * 1024 * 1024 if mb
                        else 2 * 1024 * 1024 * 1024)
    nodes = 1
    for s in shape:
        nodes *= int(s)
    per_case = nodes * (3 * n_storage * itemsize + 2)
    return max(1, min(int(bmax), budget_bytes // max(per_case, 1)))


def snapshot_mem_slots(n_storage: int, shape: Tuple[int, ...],
                       itemsize: int,
                       budget_bytes: Optional[int] = None) -> int:
    """How many adjoint checkpoints (full field stacks) fit the HOST
    snapshot budget — the memory tier of the revolve two-tier store
    (adjoint/revolve.py); snapshots past this count spill to disk.

    ``budget_bytes`` defaults to ``TCLB_ADJOINT_BUDGET_MB`` (MB) or
    4 GiB of host RAM: snapshots are host-side numpy (the forward sweep
    parks them off-device precisely so device memory stays O(one chunk's
    remat tree)), so the budget is a host-RAM predicate, not an HBM one.
    Always at least 1 — revolve degenerates to the quadratic
    single-snapshot sweep rather than refusing to run.
    """
    if budget_bytes is None:
        import os
        mb = os.environ.get("TCLB_ADJOINT_BUDGET_MB")
        budget_bytes = (int(mb) * 1024 * 1024 if mb
                        else 4 * 1024 * 1024 * 1024)
    nodes = 1
    for s in shape:
        nodes *= int(s)
    per_snap = max(1, nodes * n_storage * itemsize)
    return max(1, int(budget_bytes) // per_snap)


def zone_plane(ztab, col: int, zone_max: int, zones,
               zones_present: Optional[Iterable[int]] = None):
    """Reconstruct one zonal-setting plane inside a kernel.

    ``ztab`` is the flattened SMEM zone table (row ``col`` holds that
    setting's per-zone values, ``ztab[col * zone_max + z]``); ``zones``
    the flag-derived zone ids (``flags >> zone_shift``, always in
    ``[0, zone_max)`` by bit width).  A where-chain over the present
    zones reproduces the host-side ``zone_table[si][zones]`` gather
    bit-exactly; ``zones_present=None`` means all zones (exact parity
    with no host knowledge).
    """
    zs = list(zones_present) if zones_present is not None \
        else list(range(zone_max))
    v0 = ztab[col * zone_max + zs[0]]
    plane = jnp.zeros(zones.shape, v0.dtype) + v0
    for z in zs[1:]:
        plane = jnp.where(zones == jnp.int32(z),
                          ztab[col * zone_max + z], plane)
    return plane
