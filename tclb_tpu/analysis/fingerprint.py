"""Structural model fingerprints for eligibility caches.

:func:`structural_fingerprint` hashes everything the kernel engines
specialize on (``Model.structural_key``) — the cache key the round-5
advisor asked for: ``id(model)`` keys alias recycled addresses (a rebuilt
model can inherit a stale verdict from a dead object at the same address)
and miss structurally identical rebuilds (every rebuild re-probes).

This module deliberately imports nothing from ``tclb_tpu.ops`` so the
kernel modules can import it without a cycle.
"""

from __future__ import annotations

from tclb_tpu.core.registry import Model


def structural_key(model: Model) -> tuple:
    return model.structural_key()


def structural_fingerprint(model: Model) -> str:
    """Short hex digest stable across processes and model rebuilds."""
    return model.fingerprint
