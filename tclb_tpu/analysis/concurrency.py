"""Whole-program lock-discipline analysis of the serving planes.

PRs 10-13 made the solver a multi-threaded serving product: gateway
handler threads, the scheduler worker, per-lane stager/exec threads,
pool supervisors, drain hooks and the SIGTERM handler all share state
behind explicit ``threading`` locks.  Because every thread entry point
and every lock is visible in the AST, a RacerX-style lock-discipline
pass (Engler & Ashcraft, SOSP 2003) is tractable — this module is that
pass, and the CI gate runs it on every commit:

* ``concurrency.unguarded_shared_state`` — an instance attribute of a
  thread-spawning class is reachable from two thread entry points and
  written outside any lock.  Intentional lock-free patterns (the
  telemetry single-boolean gate) carry a per-site waiver.
* ``concurrency.lock_order_cycle`` — the cross-module lock-order graph
  (lock B acquired while A is held, including through resolved calls)
  has a cycle: two threads taking the edges in opposite order deadlock.
* ``concurrency.blocking_under_lock`` — sleep / fsync / ``device_put``
  / pipe IPC / subprocess-wait / thread-join executed while a lock is
  held: every other thread contending on that lock inherits the stall.
* ``concurrency.signal_unsafe`` — a signal handler (or drain hook,
  which runs on the signal-handling main thread) acquires a
  non-reentrant lock or performs IO within two calls of the handler: if
  the interrupted main thread holds that lock, the process self-
  deadlocks mid-shutdown.

**Waiver syntax** (all four checks): a comment on the flagged line (or
the line directly above) of the form::

    # concurrency-ok[TAG]: justification

with TAG one of ``unguarded``, ``lock-order``, ``blocking``, ``signal``
(comma-separate to waive several checks at one site).  A waiver without
a justification does not count.

Scope and soundness: the pass resolves ``self.method()`` calls, module
functions, imported ``module.fn`` references, and attributes/locals
whose class is statically known (``x = ClassName(...)`` or an annotated
``__init__`` parameter).  Dynamic dispatch (callbacks, subscriber
fan-outs) is out of scope; mutation through container methods
(``list.append``) is not treated as a write.  The runtime half —
:mod:`tclb_tpu.telemetry.locks` under ``TCLB_LOCK_DEBUG=1`` — covers
what the static pass cannot see.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Optional

from tclb_tpu.analysis.findings import Finding
from tclb_tpu.analysis.hygiene import (_REPO_ROOT, _module_name, _py_files,
                                       _resolve_from)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: package subtrees (plus single files) the serving-plane analysis walks
_DEFAULT_DIRS = ("serve", "gateway", "telemetry", "checkpoint", "cluster")
_DEFAULT_FILES = ("faults.py",)

_WAIVER_RE = re.compile(
    r"#\s*concurrency-ok\[([a-z, -]+)\]\s*:\s*(\S.*)")

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}
_MAKE_CTORS = {"make_lock": "lock", "make_rlock": "rlock"}

#: http.server request-handler entry points (each runs on its own
#: ThreadingHTTPServer thread)
_HTTP_HANDLERS = ("do_GET", "do_POST", "do_PUT", "do_DELETE", "do_PATCH")


def _default_paths() -> list:
    out = []
    for d in _DEFAULT_DIRS:
        p = os.path.join(_PKG_ROOT, d)
        if os.path.isdir(p):
            out += _py_files(p)
    for f in _DEFAULT_FILES:
        p = os.path.join(_PKG_ROOT, f)
        if os.path.isfile(p):
            out.append(p)
    return sorted(out)


def _short(mod: str) -> str:
    return mod[len("tclb_tpu."):] if mod.startswith("tclb_tpu.") else mod


def _rel(path: str) -> str:
    ap = os.path.abspath(path)
    if ap.startswith(_REPO_ROOT + os.sep):
        return os.path.relpath(ap, _REPO_ROOT)
    return os.path.basename(ap)


# --------------------------------------------------------------------------- #
# model
# --------------------------------------------------------------------------- #


class _Fn:
    """Everything the checks need about one function/method body."""

    __slots__ = ("module", "qualname", "cls", "path", "lineno",
                 "acquires", "edges", "blocking", "calls",
                 "writes", "reads", "self_calls")

    def __init__(self, module, qualname, cls, path, lineno):
        self.module = module
        self.qualname = qualname
        self.cls = cls                  # enclosing class name or None
        self.path = path
        self.lineno = lineno
        self.acquires = []              # (lock_id, lineno)
        self.edges = []                 # (held_id, lock_id, lineno)
        self.blocking = []              # (desc, lineno, tuple(held))
        self.calls = []                 # ((module, qualname), lineno, held)
        self.writes = []                # (attr, lineno, tuple(held))
        self.reads = []                 # (attr, lineno)
        self.self_calls = set()         # method names called on self


class _Module:
    __slots__ = ("name", "short", "path", "tree", "lines", "waivers",
                 "imports", "mod_locks", "classes", "functions",
                 "var_types")

    def __init__(self, name, path, tree, lines):
        self.name = name
        self.short = _short(name)
        self.path = path
        self.tree = tree
        self.lines = lines
        self.waivers = _collect_waivers(lines)
        self.imports = {}               # alias -> "module" or "module:attr"
        self.mod_locks = {}             # name -> kind
        self.classes = {}               # ClassName -> _Class
        self.functions = {}             # qualname -> ast node
        self.var_types = {}             # module-level var -> (mod, Class)


class _Class:
    __slots__ = ("name", "locks", "aliases", "attr_types", "methods",
                 "spawns_threads", "thread_targets")

    def __init__(self, name):
        self.name = name
        self.locks = {}                 # attr -> kind
        self.aliases = {}               # attr -> attr (Condition -> its lock)
        self.attr_types = {}            # attr -> (module, ClassName)
        self.methods = {}               # qualname suffix -> ast node
        self.spawns_threads = False
        self.thread_targets = set()     # method names run on spawned threads


class _Program:
    __slots__ = ("modules", "functions", "findings", "thread_entries",
                 "signal_entries", "lock_kinds")

    def __init__(self):
        self.modules = {}               # module name -> _Module
        self.functions = {}             # (module, qualname) -> _Fn
        self.findings = []              # parse errors
        self.thread_entries = set()     # (module, qualname)
        self.signal_entries = set()     # (module, qualname)
        self.lock_kinds = {}            # lock_id -> "lock"|"rlock"|"condition"


def _collect_waivers(lines) -> dict:
    out = {}
    for i, line in enumerate(lines, 1):
        m = _WAIVER_RE.search(line)
        if m:
            tags = {t.strip() for t in m.group(1).split(",") if t.strip()}
            out[i] = tags
    return out


def _waived(mod: _Module, lineno: int, tag: str) -> bool:
    """A waiver applies to its own line (trailing comment) or anywhere
    in the contiguous comment block directly above the site — so the
    justification may take several lines."""
    if tag in mod.waivers.get(lineno, ()):
        return True
    i = lineno - 1
    while i >= 1 and i <= len(mod.lines):
        line = mod.lines[i - 1].strip()
        if not line.startswith("#"):
            break
        if tag in mod.waivers.get(i, ()):
            return True
        i -= 1
    return False


def _call_root(func) -> Optional[str]:
    """Terminal attribute/name of a call's func expression."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_lock_ctor(mod: _Module, call: ast.Call) -> Optional[str]:
    """The lock kind a constructor call produces, or None."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id == "threading" and f.attr in _LOCK_CTORS:
            return _LOCK_CTORS[f.attr]
        if f.value.id == "locks" and f.attr in _MAKE_CTORS:
            return _MAKE_CTORS[f.attr]
    if isinstance(f, ast.Name):
        tgt = mod.imports.get(f.id, "")
        if tgt.endswith(":" + f.id) or tgt == "":
            if f.id in _LOCK_CTORS and "threading:" in tgt + ":":
                pass
        if f.id in _LOCK_CTORS and \
                mod.imports.get(f.id, "").split(":")[-1] == f.id:
            return _LOCK_CTORS[f.id]
        if f.id in _MAKE_CTORS and \
                mod.imports.get(f.id, "").split(":")[-1] == f.id:
            return _MAKE_CTORS[f.id]
    return None


def _cond_lock_arg(call: ast.Call) -> Optional[ast.expr]:
    """The lock argument of a ``Condition(lock)`` constructor call."""
    root = _call_root(call.func)
    if root == "Condition" and call.args:
        return call.args[0]
    return None


# --------------------------------------------------------------------------- #
# pass 1: structure
# --------------------------------------------------------------------------- #


def _load(paths) -> _Program:
    prog = _Program()
    for path in paths:
        try:
            with open(path) as fh:
                src = fh.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError) as e:
            prog.findings.append(Finding(
                "concurrency.unparseable", "error", "",
                f"cannot parse {path}: {e}", _rel(path)))
            continue
        name = _module_name(path, _PKG_ROOT)
        mod = _Module(name, path, tree, src.splitlines())
        prog.modules[name] = mod
        _scan_structure(mod)
    _resolve_entries(prog)
    return prog


def _scan_structure(mod: _Module) -> None:
    for node in mod.tree.body:
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.imports[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = _resolve_from(node.module, node.level, mod.name)
            for a in node.names:
                mod.imports[a.asname or a.name] = f"{base}:{a.name}"
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call):
            tgt = node.targets[0].id
            kind = _is_lock_ctor(mod, node.value)
            if kind:
                mod.mod_locks[tgt] = kind
            else:
                cls = _class_of_call(mod, node.value)
                if cls:
                    mod.var_types[tgt] = cls
        elif isinstance(node, ast.ClassDef):
            _scan_class(mod, node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _collect_functions(mod, node, prefix="")


def _collect_functions(mod: _Module, node, prefix: str) -> None:
    qual = prefix + node.name
    mod.functions[qual] = node
    for child in ast.walk(node):
        if child is not node and \
                isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and child.name not in mod.functions:
            mod.functions[qual + "." + child.name] = child


def _scan_class(mod: _Module, node: ast.ClassDef) -> None:
    cls = _Class(node.name)
    mod.classes[node.name] = cls
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        qual = f"{node.name}.{item.name}"
        cls.methods[item.name] = item
        mod.functions[qual] = item
        for child in ast.walk(item):
            if child is not item and \
                    isinstance(child,
                               (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.functions[qual + "." + child.name] = child
        ann = {a.arg: a.annotation for a in item.args.args
               if a.annotation is not None}
        for stmt in ast.walk(item):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                tgt = stmt.targets[0]
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    _note_self_assign(mod, cls, tgt.attr, stmt.value, ann)
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Attribute) \
                    and isinstance(stmt.target.value, ast.Name) \
                    and stmt.target.value.id == "self":
                ty = _class_of_annotation(mod, stmt.annotation)
                if ty:
                    cls.attr_types[stmt.target.attr] = ty


def _note_self_assign(mod, cls, attr, value, ann) -> None:
    if isinstance(value, ast.Call):
        kind = _is_lock_ctor(mod, value)
        if kind:
            cls.locks[attr] = kind
            arg = _cond_lock_arg(value)
            if arg is not None and isinstance(arg, ast.Attribute) \
                    and isinstance(arg.value, ast.Name) \
                    and arg.value.id == "self":
                cls.aliases[attr] = arg.attr
            return
        ty = _class_of_call(mod, value)
        if ty:
            cls.attr_types[attr] = ty
            return
    if isinstance(value, ast.Name) and value.id in ann:
        ty = _class_of_annotation(mod, ann[value.id])
        if ty:
            cls.attr_types[attr] = ty


def _class_of_call(mod: _Module, call: ast.Call):
    """(module, ClassName) when the call constructs a known class."""
    f = call.func
    name = None
    if isinstance(f, ast.Name):
        name = f.id
    elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        base = mod.imports.get(f.value.id)
        if base and ":" not in base:
            return (base, f.attr) if f.attr[:1].isupper() else None
        name = None
    if name is None:
        return None
    if name in mod.classes:
        return (mod.name, name)
    tgt = mod.imports.get(name)
    if tgt and ":" in tgt:
        m2, attr = tgt.split(":", 1)
        if attr[:1].isupper():
            return (m2, attr)
    return None


def _class_of_annotation(mod: _Module, ann):
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.split("[")[0].strip().strip("'\"")
    elif isinstance(ann, ast.Name):
        name = ann.id
    elif isinstance(ann, ast.Attribute):
        name = ann.attr
    else:
        return None
    if name in mod.classes:
        return (mod.name, name)
    tgt = mod.imports.get(name)
    if tgt and ":" in tgt:
        m2, attr = tgt.split(":", 1)
        return (m2, attr)
    return None


def _resolve_entries(prog: _Program) -> None:
    """Find thread targets, HTTP handler methods, signal handlers and
    drain hooks across every loaded module."""
    for mod in prog.modules.values():
        for cname, cls in mod.classes.items():
            for mname in cls.methods:
                if mname in _HTTP_HANDLERS:
                    prog.thread_entries.add((mod.name, f"{cname}.{mname}"))
        for qual, fn in list(mod.functions.items()):
            encl_cls = qual.split(".")[0] if qual.split(".")[0] \
                in mod.classes else None
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                root = _call_root(node.func)
                if root == "Thread":
                    tgt = next((kw.value for kw in node.keywords
                                if kw.arg == "target"), None)
                    _mark_entry(prog, mod, encl_cls, qual, tgt,
                                prog.thread_entries)
                elif root == "signal" and isinstance(node.func,
                                                     ast.Attribute) \
                        and len(node.args) == 2:
                    _mark_entry(prog, mod, encl_cls, qual, node.args[1],
                                prog.signal_entries)
                elif root == "register_drain_hook" and len(node.args) == 2:
                    _mark_entry(prog, mod, encl_cls, qual, node.args[1],
                                prog.signal_entries)
        for cname, cls in mod.classes.items():
            for (m2, q2) in prog.thread_entries:
                if m2 == mod.name and q2.startswith(cname + "."):
                    cls.spawns_threads = True
                    cls.thread_targets.add(q2.split(".", 1)[1])


def _mark_entry(prog, mod, encl_cls, encl_qual, expr, into: set) -> None:
    if expr is None:
        return
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self" \
            and encl_cls is not None:
        if expr.attr in mod.classes[encl_cls].methods:
            into.add((mod.name, f"{encl_cls}.{expr.attr}"))
        return
    if isinstance(expr, ast.Name):
        # a module function, or a function nested in the enclosing one
        nested = f"{encl_qual}.{expr.id}"
        if nested in mod.functions:
            into.add((mod.name, nested))
        elif expr.id in mod.functions:
            into.add((mod.name, expr.id))


# --------------------------------------------------------------------------- #
# pass 2: per-function walk (held-lock tracking)
# --------------------------------------------------------------------------- #

_BLOCKING_WRITE_BASES = ("journal", "sink", "stdin", "stdout", "file", "fh")


class _Walker:
    """Statement-ordered walk of one function body, tracking the stack
    of held locks (``with`` scoping exact; bare ``acquire``/``release``
    approximated in source order)."""

    def __init__(self, prog: _Program, mod: _Module, fn: _Fn,
                 node, cls: Optional[_Class]):
        self.prog = prog
        self.mod = mod
        self.fn = fn
        self.cls = cls
        self.node = node
        self.var_types = dict(mod.var_types)
        args = node.args
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                ty = _class_of_annotation(mod, a.annotation)
                if ty:
                    self.var_types[a.arg] = ty

    def run(self) -> None:
        self._stmts(self.node.body, [])

    # -- lock expression resolution ----------------------------------------- #

    def _lock_of(self, expr) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls is not None:
            attr = self.cls.aliases.get(expr.attr, expr.attr)
            if attr in self.cls.locks:
                return f"{self.mod.short}.{self.cls.name}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self.mod.mod_locks:
            return f"{self.mod.short}.{expr.id}"
        return None

    def _lock_kind(self, lock_id: str) -> str:
        return self.prog.lock_kinds.get(lock_id, "lock")

    def _register_kind(self, expr, lock_id: str) -> None:
        if lock_id in self.prog.lock_kinds:
            return
        kind = None
        if isinstance(expr, ast.Attribute) and self.cls is not None:
            attr = self.cls.aliases.get(expr.attr, expr.attr)
            kind = self.cls.locks.get(attr)
        elif isinstance(expr, ast.Name):
            kind = self.mod.mod_locks.get(expr.id)
        self.prog.lock_kinds[lock_id] = kind or "lock"

    # -- call resolution ----------------------------------------------------- #

    def _callee_of(self, call: ast.Call):
        f = call.func
        if isinstance(f, ast.Name):
            name = f.id
            nested = f"{self.fn.qualname}.{name}"
            if nested in self.mod.functions:
                return (self.mod.name, nested)
            if name in self.mod.functions:
                return (self.mod.name, name)
            tgt = self.mod.imports.get(name)
            if tgt and ":" in tgt:
                m2, attr = tgt.split(":", 1)
                if attr[:1].isupper():
                    return (m2, f"{attr}.__init__")
                return (m2, attr)
            return None
        if not isinstance(f, ast.Attribute):
            return None
        base = f.value
        if isinstance(base, ast.Name):
            if base.id == "self" and self.cls is not None:
                if f.attr in self.cls.methods:
                    return (self.mod.name, f"{self.cls.name}.{f.attr}")
                ty = self.cls.attr_types.get(f.attr)
                return None if ty is None else ty
            ty = self.var_types.get(base.id)
            if ty is not None:
                return (ty[0], f"{ty[1]}.{f.attr}")
            tgt = self.mod.imports.get(base.id)
            if tgt and ":" not in tgt:
                return (tgt, f.attr)
            if tgt and ":" in tgt:
                m2, attr = tgt.split(":", 1)
                if attr[:1].isupper():
                    return (m2, f"{attr}.{f.attr}")
            return None
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and self.cls is not None:
            ty = self.cls.attr_types.get(base.attr)
            if ty is not None:
                return (ty[0], f"{ty[1]}.{f.attr}")
        return None

    # -- blocking matcher ---------------------------------------------------- #

    def _blocking_desc(self, call: ast.Call, held) -> Optional[str]:
        f = call.func
        root = _call_root(f)
        if root is None:
            return None
        base_name = None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                base_name = f.value.id
            elif isinstance(f.value, ast.Attribute):
                base_name = f.value.attr
            elif isinstance(f.value, ast.Constant):
                return None               # "sep".join(...) and friends
        if root == "sleep" and (base_name == "time" or (
                base_name is None and
                self.mod.imports.get("sleep", "").endswith(":sleep"))):
            return "time.sleep"
        if root == "fsync":
            return "fsync"
        if root == "atomic_write_bytes":
            return "atomic_write_bytes (fsync + rename)"
        if root == "device_put":
            return "jax.device_put"
        if root == "select" and base_name == "select":
            return "select.select"
        if root in ("read_frame", "write_frame"):
            return f"pipe IPC ({root})"
        if root in ("recv", "communicate"):
            return f"IPC .{root}()"
        if root == "Popen":
            return "subprocess.Popen"
        if root == "wait" and isinstance(f, ast.Attribute):
            lock = self._lock_of(f.value)
            if lock is not None and lock in held:
                return None               # Condition.wait releases it
            return f"blocking .wait() on {base_name or 'object'}"
        if root == "join" and isinstance(f, ast.Attribute) \
                and base_name not in (None, "os", "path"):
            return f"thread join on {base_name}"
        if root == "write" and isinstance(f, ast.Attribute) \
                and base_name is not None and any(
                    b in base_name.lower() for b in _BLOCKING_WRITE_BASES):
            return f"file/pipe write on {base_name}"
        return None

    # -- the walk ------------------------------------------------------------ #

    def _stmts(self, body, held) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt, held) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                        # nested defs walk separately
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                self._exprs(item.context_expr, held)
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self._register_kind(item.context_expr, lock)
                    self._acquire(lock, item.context_expr.lineno, held)
                    held.append(lock)
                    pushed += 1
            self._stmts(stmt.body, held)
            for _ in range(pushed):
                held.pop()
            return
        if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor, ast.While)):
            for expr in ast.iter_child_nodes(stmt):
                if isinstance(expr, ast.expr):
                    self._exprs(expr, held)
            branch = list(held)
            self._stmts(stmt.body, branch)
            branch = list(held)
            self._stmts(stmt.orelse, branch)
            return
        if isinstance(stmt, ast.Try):
            branch = list(held)
            self._stmts(stmt.body, branch)
            for h in stmt.handlers:
                branch = list(held)
                self._stmts(h.body, branch)
            branch = list(held)
            self._stmts(stmt.orelse, branch)
            self._stmts(stmt.finalbody, held)
            return
        # expression statements: acquire()/release() bookkeeping plus
        # the generic expression scan
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            root = _call_root(call.func)
            if root in ("acquire", "release") and \
                    isinstance(call.func, ast.Attribute):
                lock = self._lock_of(call.func.value)
                if lock is not None:
                    self._register_kind(call.func.value, lock)
                    if root == "acquire":
                        self._acquire(lock, call.lineno, held)
                        held.append(lock)
                    elif lock in held:
                        held.remove(lock)
                    return
        for expr in ast.iter_child_nodes(stmt):
            if isinstance(expr, ast.expr):
                self._exprs(expr, held)
        # simple local type inference: x = ClassName(...)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and isinstance(stmt.value, ast.Call):
            ty = _class_of_call(self.mod, stmt.value)
            if ty:
                self.var_types[stmt.targets[0].id] = ty
        self._attr_accesses(stmt, held)

    def _acquire(self, lock, lineno, held) -> None:
        self.fn.acquires.append((lock, lineno))
        for h in held:
            if h != lock:
                self.fn.edges.append((h, lock, lineno))

    def _exprs(self, expr, held) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            root = _call_root(node.func)
            if root == "Thread":
                continue                  # construction, not a call
            if root == "acquire" and isinstance(node.func, ast.Attribute):
                lock = self._lock_of(node.func.value)
                if lock is not None:
                    # non-statement acquire (e.g. `if l.acquire(False):`)
                    self._register_kind(node.func.value, lock)
                    self.fn.acquires.append((lock, node.lineno))
                    continue
            desc = self._blocking_desc(node, held)
            if desc is not None and held:
                self.fn.blocking.append((desc, node.lineno, tuple(held)))
            callee = self._callee_of(node)
            if callee is not None:
                self.fn.calls.append((callee, node.lineno, tuple(held)))
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self":
                    self.fn.self_calls.add(node.func.attr)

    def _attr_accesses(self, stmt, held) -> None:
        """self-attribute reads/writes for the shared-state map."""
        if self.cls is None:
            return

        def is_self_attr(node):
            return (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self")

        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for tgt in targets:
            if is_self_attr(tgt):
                self.fn.writes.append((tgt.attr, tgt.lineno, tuple(held)))
            elif isinstance(tgt, ast.Subscript) and is_self_attr(tgt.value):
                # self.d[k] = v mutates the container self.d points at
                self.fn.writes.append((tgt.value.attr, tgt.lineno,
                                       tuple(held)))
            elif isinstance(tgt, ast.Tuple):
                for el in tgt.elts:
                    if is_self_attr(el):
                        self.fn.writes.append((el.attr, el.lineno,
                                               tuple(held)))
        for node in ast.walk(stmt):
            if is_self_attr(node) and isinstance(node.ctx, ast.Load):
                self.fn.reads.append((node.attr, node.lineno))


# --------------------------------------------------------------------------- #
# analysis driver
# --------------------------------------------------------------------------- #

_analysis_cache: dict = {}


def _analyze(paths=None) -> _Program:
    paths = list(paths) if paths is not None else _default_paths()
    key = tuple((p, _stat_sig(p)) for p in paths)
    cached = _analysis_cache.get(key)
    if cached is not None:
        return cached
    prog = _load(paths)
    for mod in prog.modules.values():
        for qual, node in mod.functions.items():
            head = qual.split(".")[0]
            cls = mod.classes.get(head)
            fn = _Fn(mod.name, qual, cls.name if cls else None,
                     mod.path, node.lineno)
            prog.functions[(mod.name, qual)] = fn
            _Walker(prog, mod, fn, node, cls).run()
    _analysis_cache.clear()               # keep at most one program
    _analysis_cache[key] = prog
    return prog


def _stat_sig(path: str):
    try:
        st = os.stat(path)
        return (st.st_mtime_ns, st.st_size)
    except OSError:
        return None


def _may_acquire(prog: _Program) -> dict:
    """Fixpoint: every lock a function may acquire, transitively through
    resolved calls."""
    out = {k: {l for l, _ in fn.acquires}
           for k, fn in prog.functions.items()}
    changed = True
    while changed:
        changed = False
        for k, fn in prog.functions.items():
            acc = out[k]
            before = len(acc)
            for callee, _lineno, _held in fn.calls:
                acc |= out.get(callee, set())
            if len(acc) != before:
                changed = True
    return out


# --------------------------------------------------------------------------- #
# check 1: unguarded shared state
# --------------------------------------------------------------------------- #


def scan_unguarded_shared_state(paths=None) -> list:
    """Instance attributes of thread-spawning classes written outside
    any lock while reachable from two or more thread entry points."""
    prog = _analyze(paths)
    findings = list(prog.findings)
    for mod in prog.modules.values():
        for cname, cls in mod.classes.items():
            if not cls.spawns_threads and not any(
                    m in _HTTP_HANDLERS for m in cls.methods):
                continue
            entries = _class_entries(prog, mod, cls)
            if len(entries) < 2:
                continue
            # attr -> set of entries touching it; writes outside locks
            touched: dict = {}
            bad_writes: dict = {}
            for entry, methods in entries.items():
                for mname in methods:
                    fn = prog.functions.get((mod.name, f"{cname}.{mname}"))
                    if fn is None:
                        continue
                    for attr, lineno, held in fn.writes:
                        if mname == "__init__":
                            continue
                        touched.setdefault(attr, set()).add(entry)
                        if not held:
                            bad_writes.setdefault(attr, []).append(
                                (lineno, entry))
                    for attr, _lineno in fn.reads:
                        if mname != "__init__":
                            touched.setdefault(attr, set()).add(entry)
            for attr in sorted(bad_writes):
                if attr in cls.locks or len(touched.get(attr, ())) < 2:
                    continue
                for lineno, entry in sorted(bad_writes[attr]):
                    if _waived(mod, lineno, "unguarded"):
                        continue
                    rel = _rel(mod.path)
                    findings.append(Finding(
                        "concurrency.unguarded_shared_state", "error", "",
                        f"{rel}:{lineno} writes {cname}.{attr} outside "
                        f"any lock, but the attribute is reached from "
                        f"{len(touched[attr])} thread entry points "
                        f"({', '.join(sorted(touched[attr]))}); guard it "
                        "or waive with  # concurrency-ok[unguarded]: why",
                        f"{rel}:{lineno}",
                        details={"class": f"{mod.short}.{cname}",
                                 "attr": attr,
                                 "entries": sorted(touched[attr])}))
    return findings


def _class_entries(prog: _Program, mod: _Module, cls: _Class) -> dict:
    """entry label -> set of method names running under that entry.
    Thread targets (and HTTP do_* handlers) each form one entry; every
    externally-callable method forms the shared "api" entry (handler or
    caller threads).  Reachability closes over ``self.x()`` calls."""

    def closure(seed) -> set:
        seen = set(seed)
        frontier = list(seed)
        while frontier:
            m = frontier.pop()
            fn = prog.functions.get((mod.name, f"{cls.name}.{m}"))
            if fn is None:
                continue
            for callee in fn.self_calls:
                if callee in cls.methods and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    targets = set(cls.thread_targets) | {
        m for m in cls.methods if m in _HTTP_HANDLERS}
    entries = {}
    for t in sorted(targets):
        entries[f"thread:{t}"] = closure({t})
    api_seed = {m for m in cls.methods
                if m not in targets and not m.startswith("_")}
    api_seed |= {m for m in ("__enter__", "__exit__") if m in cls.methods}
    if api_seed:
        entries["api"] = closure(api_seed) - targets
    return entries


# --------------------------------------------------------------------------- #
# check 2: lock-order cycles
# --------------------------------------------------------------------------- #


def scan_lock_order_cycles(paths=None) -> list:
    """Cycles in the cross-module lock-order graph (lock B taken while
    A held, directly or through statically-resolved calls)."""
    prog = _analyze(paths)
    findings = list(prog.findings)
    may = _may_acquire(prog)
    edges: dict = {}                      # (a, b) -> witness "file:line"
    for (mname, _qual), fn in sorted(prog.functions.items()):
        mod = prog.modules[mname]
        for a, b, lineno in fn.edges:
            if not _waived(mod, lineno, "lock-order"):
                edges.setdefault((a, b), f"{_rel(mod.path)}:{lineno}")
        for callee, lineno, held in fn.calls:
            if not held or _waived(mod, lineno, "lock-order"):
                continue
            for b in may.get(callee, ()):
                for a in held:
                    if a != b:
                        edges.setdefault(
                            (a, b),
                            f"{_rel(mod.path)}:{lineno} (via "
                            f"{_short(callee[0])}.{callee[1]})")
    graph: dict = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    for cycle in _find_cycles(graph):
        witness = []
        for i, a in enumerate(cycle):
            b = cycle[(i + 1) % len(cycle)]
            witness.append(f"{a} -> {b} at {edges[(a, b)]}")
        findings.append(Finding(
            "concurrency.lock_order_cycle", "error", "",
            "lock-order cycle: " + "  |  ".join(witness) +
            " — two threads taking these edges in opposite order "
            "deadlock; impose one global order (or waive an edge with "
            "# concurrency-ok[lock-order]: why)",
            cycle[0], details={"cycle": list(cycle)}))
    return findings


def _find_cycles(graph: dict) -> list:
    """Minimal cycle witnesses: one per strongly-connected component
    with more than one node (plus self-loops)."""
    index: dict = {}
    low: dict = {}
    on_stack: set = set()
    stack: list = []
    sccs: list = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    nodes = set(graph) | {b for bs in graph.values() for b in bs}
    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    cycles = []
    for comp in sccs:
        if len(comp) > 1:
            cycles.append(_order_cycle(comp, graph))
        elif comp[0] in graph.get(comp[0], ()):
            cycles.append((comp[0],))
    return cycles


def _order_cycle(comp, graph) -> tuple:
    """Walk one actual cycle through the SCC for a readable witness."""
    comp_set = set(comp)
    start = min(comp)
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = min((w for w in graph.get(node, ()) if w in comp_set),
                  default=None)
        if nxt is None or nxt == start:
            return tuple(path)
        if nxt in seen:
            return tuple(path[path.index(nxt):])
        path.append(nxt)
        seen.add(nxt)
        node = nxt


def lock_order_graph(paths=None) -> dict:
    """The static lock-order graph ``{a: {b, ...}}`` (for validation
    against :func:`tclb_tpu.telemetry.locks.order_graph`)."""
    prog = _analyze(paths)
    may = _may_acquire(prog)
    graph: dict = {}
    for (_mname, _qual), fn in prog.functions.items():
        for a, b, _lineno in fn.edges:
            graph.setdefault(a, set()).add(b)
        for callee, _lineno, held in fn.calls:
            for b in may.get(callee, ()):
                for a in held:
                    if a != b:
                        graph.setdefault(a, set()).add(b)
    return graph


# --------------------------------------------------------------------------- #
# check 3: blocking work under a lock
# --------------------------------------------------------------------------- #


def scan_blocking_under_lock(paths=None) -> list:
    """sleep / fsync / device_put / pipe IPC / subprocess-wait / thread
    join executed while a lock is held (same-function analysis)."""
    prog = _analyze(paths)
    findings = list(prog.findings)
    for (mname, _qual), fn in sorted(prog.functions.items()):
        mod = prog.modules[mname]
        for desc, lineno, held in fn.blocking:
            if _waived(mod, lineno, "blocking"):
                continue
            rel = _rel(mod.path)
            findings.append(Finding(
                "concurrency.blocking_under_lock", "error", "",
                f"{rel}:{lineno} performs {desc} while holding "
                f"{', '.join(held)} — every thread contending on that "
                "lock inherits the stall; move the blocking work "
                "outside the critical section (or waive with "
                "# concurrency-ok[blocking]: why)",
                f"{rel}:{lineno}",
                details={"blocking": desc, "held": list(held)}))
    return findings


# --------------------------------------------------------------------------- #
# check 4: signal-unsafe handler paths
# --------------------------------------------------------------------------- #

_SIGNAL_DEPTH = 2


def scan_signal_unsafe(paths=None) -> list:
    """Non-reentrant lock acquisition or blocking IO within
    ``_SIGNAL_DEPTH`` calls of a signal handler or drain hook.  The
    handler runs on the main thread between bytecodes: if the
    interrupted code holds the same non-reentrant lock, the process
    self-deadlocks.  Reentrant (RLock) acquisition is allowed."""
    prog = _analyze(paths)
    findings = list(prog.findings)
    seen_sites = set()
    frontier = [(entry, 0) for entry in sorted(prog.signal_entries)]
    visited = set()
    while frontier:
        (key, depth) = frontier.pop()
        if key in visited:
            continue
        visited.add(key)
        fn = prog.functions.get(key)
        if fn is None:
            continue
        mod = prog.modules[key[0]]
        rel = _rel(mod.path)
        for lock, lineno in fn.acquires:
            kind = prog.lock_kinds.get(lock, "lock")
            if kind in ("rlock", "condition"):
                continue
            site = (rel, lineno, lock)
            if site in seen_sites or _waived(mod, lineno, "signal"):
                continue
            seen_sites.add(site)
            findings.append(Finding(
                "concurrency.signal_unsafe", "error", "",
                f"{rel}:{lineno} acquires non-reentrant lock {lock} on "
                f"a signal-handler path (via {fn.qualname}); if the "
                "interrupted main thread holds it, the process "
                "self-deadlocks — use an RLock (or waive with "
                "# concurrency-ok[signal]: why)",
                f"{rel}:{lineno}",
                details={"lock": lock, "via": fn.qualname}))
        for desc, lineno, _held in fn.blocking:
            site = (rel, lineno, desc)
            if site in seen_sites or _waived(mod, lineno, "signal"):
                continue
            seen_sites.add(site)
            findings.append(Finding(
                "concurrency.signal_unsafe", "error", "",
                f"{rel}:{lineno} performs {desc} on a signal-handler "
                f"path (via {fn.qualname}) — IO in a handler context "
                "can wedge the dying process (or waive with "
                "# concurrency-ok[signal]: why)",
                f"{rel}:{lineno}",
                details={"blocking": desc, "via": fn.qualname}))
        for node in ast.walk(prog.modules[key[0]].functions.get(
                key[1], ast.Pass())):
            if isinstance(node, ast.Call):
                root = _call_root(node.func)
                if root == "open" and isinstance(node.func, ast.Name):
                    lineno = node.lineno
                    site = (rel, lineno, "open")
                    if site in seen_sites or _waived(mod, lineno, "signal"):
                        continue
                    seen_sites.add(site)
                    findings.append(Finding(
                        "concurrency.signal_unsafe", "error", "",
                        f"{rel}:{lineno} opens a file on a "
                        f"signal-handler path (via {fn.qualname}) — "
                        "IO in a handler context can wedge the dying "
                        "process (or waive with "
                        "# concurrency-ok[signal]: why)",
                        f"{rel}:{lineno}",
                        details={"blocking": "open", "via": fn.qualname}))
        if depth < _SIGNAL_DEPTH:
            for callee, _lineno, _held in fn.calls:
                frontier.append((callee, depth + 1))
    return findings


# --------------------------------------------------------------------------- #
# aggregate
# --------------------------------------------------------------------------- #


def check_concurrency(paths=None) -> list:
    """All four concurrency checks (what ``check_repo`` chains)."""
    out = scan_unguarded_shared_state(paths)
    # parse failures are reported once by the first scan; the other
    # scans re-report them, so dedupe on (check, where, message)
    seen = {(f.check, f.where, f.message) for f in out}
    for scan in (scan_lock_order_cycles, scan_blocking_under_lock,
                 scan_signal_unsafe):
        for f in scan(paths):
            key = (f.check, f.where, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
    return out
