"""Finding: one static-analysis diagnostic, severity-ranked.

The reference catches model-definition errors at codegen time (its R
templates refuse to emit a kernel for a malformed velocity set or stencil);
this port has no codegen, so the analyzer reports the same classes of
defect as data instead.  Severities:

* ``error``   — the model (or the repo) is broken: wrong physics or a
  kernel that would silently read garbage.  The engine dispatch refuses
  Pallas kernels for models with kernel-safety errors, and the CLI exits
  nonzero.
* ``warning`` — a capability limit with a correct fallback (e.g. a stencil
  too deep for the band kernels: the XLA path still runs it) or a hygiene
  smell worth tracking.
* ``info``    — advisory facts (resource estimates, skipped checks).
"""

from __future__ import annotations

from dataclasses import dataclass, field

SEVERITIES = ("error", "warning", "info")
_RANK = {s: i for i, s in enumerate(SEVERITIES)}


@dataclass(frozen=True)
class Finding:
    """One diagnostic: ``check`` is the dotted check id (e.g.
    ``footprint.undeclared_read``), ``model`` the registered model name
    (or ``""`` for repo-level findings), ``where`` an optional
    file/stage/plane locator, ``details`` structured data for tooling."""

    check: str
    severity: str
    model: str
    message: str
    where: str = ""
    details: dict = field(default_factory=dict, compare=False)

    def __post_init__(self):
        if self.severity not in _RANK:
            raise ValueError(f"unknown severity {self.severity!r}")

    @property
    def rank(self) -> int:
        return _RANK[self.severity]

    def to_dict(self) -> dict:
        # "code" duplicates "check": the stable, documented finding code
        # external tooling keys on (grep-able in the check catalog)
        return {"check": self.check, "code": self.check,
                "severity": self.severity,
                "model": self.model, "message": self.message,
                "where": self.where, "details": self.details}


def sort_findings(findings: list) -> list:
    """Most severe first, then by check id and locator (stable output for
    goldens and diffs)."""
    return sorted(findings, key=lambda f: (f.rank, f.check, f.model,
                                           f.where, f.message))


def worst_severity(findings: list) -> str | None:
    if not findings:
        return None
    return min(findings, key=lambda f: f.rank).severity
