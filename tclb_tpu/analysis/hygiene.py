"""Registry/repo hygiene: dead engine entry points, ``id()``-keyed
caches, unbound stages, and model test/golden inventory.

The round-5 advisor found two instances of the same disease — an engine
entry point (``pallas_generic.supports_resident``/``make_resident_iterate``)
that no dispatch arm ever calls, and an eligibility cache keyed on
``id(model)`` (stale verdicts on recycled addresses, useless re-probes on
rebuilt models).  Both are statically detectable, so this module detects
them for good:

* **dead entry points** — every public ``make_*``/``supports*`` function
  in ``tclb_tpu/ops`` must be reachable: referenced from another module
  (qualified ``module.fn`` or ``from module import fn``) or from a LIVE
  function in its own module.  The liveness fixpoint matters: a dead
  builder calling its own dead eligibility check must not keep either
  alive.
* **id()-keyed caches** — any ``id(...)`` call in package source is
  flagged (the package has no legitimate use; dict keys were the only
  historical one).
"""

from __future__ import annotations

import ast
import os

from tclb_tpu.analysis.findings import Finding
from tclb_tpu.core.registry import Model

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)


def _py_files(root: str) -> list:
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        out += [os.path.join(dirpath, f) for f in filenames
                if f.endswith(".py")]
    return sorted(out)


def _default_sources() -> list:
    srcs = _py_files(_PKG_ROOT)
    tests = os.path.join(_REPO_ROOT, "tests")
    if os.path.isdir(tests):
        srcs += _py_files(tests)
    for extra in ("bench.py",):
        p = os.path.join(_REPO_ROOT, extra)
        if os.path.isfile(p):
            srcs.append(p)
    return srcs


def _module_name(path: str, root: str) -> str:
    ap = os.path.abspath(path)
    base = os.path.dirname(os.path.abspath(root))
    if not ap.startswith(base + os.sep):
        # out-of-tree sources (the detector's own tests scan tmp dirs):
        # name relative to the grandparent, so ``<tmp>/ops/eng.py``
        # becomes ``ops.eng`` — matching how its scanned users import it
        base = os.path.dirname(os.path.dirname(ap))
    rel = os.path.relpath(ap, base)
    mod = rel[:-3].replace(os.sep, ".")
    return mod[:-len(".__init__")] if mod.endswith(".__init__") else mod


def _resolve_from(module, level: int, here: str) -> str:
    """Resolve a (possibly relative) ``from ... import`` module path."""
    if level == 0:
        return module or ""
    parts = here.split(".")[:-level]
    return ".".join(parts + ([module] if module else []))


def scan_id_keyed_caches(paths=None) -> list:
    """Flag every call of the builtin ``id`` in the given sources."""
    findings = []
    for path in (paths if paths is not None
                 else _py_files(_PKG_ROOT)):
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except SyntaxError as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id == "id":
                rel = os.path.relpath(path, _REPO_ROOT)
                findings.append(Finding(
                    "hygiene.id_keyed_cache", "error", "",
                    f"{rel}:{node.lineno} uses id(...) — object-identity "
                    "keys alias recycled addresses and miss structurally "
                    "identical rebuilds; key on Model.fingerprint "
                    "instead", f"{rel}:{node.lineno}"))
    return findings


def _file_refs(tree, modname: str):
    """(qualified_refs, own_module_uses) for one parsed file.

    ``qualified_refs``: set of (module, attr) — ``mod.fn`` attribute
    accesses through import aliases plus direct ``from mod import fn``.
    ``own_module_uses``: {name: set of enclosing top-level function names
    (or "" for module level)} for bare Name loads."""
    aliases: dict = {}
    refs: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = _resolve_from(node.module, node.level, modname)
            for a in node.names:
                refs.add((base, a.name))
                aliases[a.asname or a.name] = (base + "." + a.name
                                               if base else a.name)
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in aliases:
            refs.add((aliases[node.value.id], node.attr))

    own: dict = {}

    def collect_names(node, scope: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = scope if scope else child.name
                for dec in child.decorator_list:
                    for n in ast.walk(dec):
                        if isinstance(n, ast.Name):
                            own.setdefault(n.id, set()).add(scope)
                collect_names(child, inner)
            elif isinstance(child, ast.Name) \
                    and isinstance(child.ctx, ast.Load):
                own.setdefault(child.id, set()).add(scope)
                collect_names(child, scope)
            else:
                if isinstance(child, ast.Name):
                    own.setdefault(child.id, set()).add(scope)
                collect_names(child, scope)
    collect_names(tree, "")
    return refs, own


_HORIZON_CALLS = {"scan", "nested_checkpoint_scan", "make_objective_run",
                  "fori_loop", "while_loop"}
_REVERSE_CALLS = {"grad", "value_and_grad", "vjp"}
_POLICY_NAMES = {"levels", "segment", "segments", "revolve_schedule",
                 "schedule", "checkpoint", "remat", "snapshots"}


def _call_name(call: ast.Call):
    fn = call.func
    return (fn.attr if isinstance(fn, ast.Attribute)
            else fn.id if isinstance(fn, ast.Name) else None)


def _horizon_inside(fnode, defs, _seen=None) -> bool:
    """True if ``fnode`` (a def or lambda) contains a horizon loop,
    following calls to sibling nested defs (one level of resolution is
    enough for the closure-factory idiom used throughout adjoint/)."""
    if _seen is None:
        _seen = set()
    if fnode in _seen:
        return False
    _seen.add(fnode)
    for sub in ast.walk(fnode):
        if isinstance(sub, ast.Call):
            name = _call_name(sub)
            if name in _HORIZON_CALLS:
                return True
            if name in defs and _horizon_inside(defs[name], defs, _seen):
                return True
    return False


def scan_unbounded_adjoint(paths=None) -> list:
    """Flag reverse-mode entry points in ``adjoint/`` that differentiate
    a full-horizon loop with NO checkpoint policy in scope.

    A function that takes ``jax.grad``/``value_and_grad``/``vjp`` of a
    program containing a horizon loop (``lax.scan``/``fori_loop``/
    ``make_objective_run``/...) stores O(T) residuals — at production
    horizons that is an OOM wired into the API, invisible until someone
    raises ``niter``.  Every such entry must show its policy in the same
    function: a ``levels`` remat depth (nested checkpoint scan), a
    ``segment``/spill tier, ``jax.checkpoint``/``remat``, or a revolve
    ``schedule``/``snapshots`` budget.

    A horizon loop that merely COEXISTS with a reverse call is fine —
    the fixed-point adjoint iterates a Neumann series around the VJP of
    one step without ever differentiating through the loop.  The loop
    must sit inside the function handed to the reverse-mode call (the
    differentiated region) to count."""
    if paths is None:
        paths = _py_files(os.path.join(_PKG_ROOT, "adjoint"))
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except SyntaxError as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            has_horizon = has_policy = False
            diffs_horizon = False
            defs = {d.name: d for d in ast.walk(node)
                    if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and d is not node}
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    name = _call_name(sub)
                    if name in _HORIZON_CALLS:
                        has_horizon = True
                    if name in ("checkpoint", "remat"):
                        has_policy = True
                    for kw in sub.keywords:
                        if kw.arg in _POLICY_NAMES:
                            has_policy = True
                if isinstance(sub, ast.Name) and sub.id in _POLICY_NAMES:
                    has_policy = True
                if isinstance(sub, ast.arg) and sub.arg in _POLICY_NAMES:
                    has_policy = True
            for sub in ast.walk(node):
                if not (isinstance(sub, ast.Call)
                        and _call_name(sub) in _REVERSE_CALLS
                        and sub.args):
                    continue
                target = sub.args[0]
                if isinstance(target, ast.Lambda):
                    diffs_horizon |= _horizon_inside(target, defs)
                elif isinstance(target, ast.Name) and target.id in defs:
                    diffs_horizon |= _horizon_inside(defs[target.id], defs)
                elif isinstance(target, (ast.Name, ast.Attribute, ast.Call)):
                    # unresolvable callable (imported fn, partial, method):
                    # stay conservative — any loop in scope counts
                    diffs_horizon |= has_horizon
                # tuple/constant first arg: that is a returned vjp function
                # being APPLIED to a cotangent, not a differentiation
            if diffs_horizon and not has_policy:
                rel = os.path.relpath(path, _REPO_ROOT)
                findings.append(Finding(
                    "hygiene.unbounded_adjoint", "error", "",
                    f"{rel}:{node.lineno} `{node.name}` differentiates "
                    "a full-horizon loop with no checkpoint policy "
                    "(no levels=/segment=/snapshots= budget, no "
                    "jax.checkpoint/remat, no revolve schedule) — "
                    "reverse-mode residuals grow O(T) and OOM at "
                    "production horizons", f"{rel}:{node.lineno}"))
    return findings


def scan_dead_entry_points(engine_dir=None, sources=None) -> list:
    """Unreachable engine entry points: public ``make_*``/``supports*``
    functions in ``tclb_tpu/ops`` no live code refers to."""
    engine_dir = engine_dir or os.path.join(_PKG_ROOT, "ops")
    sources = sources if sources is not None else _default_sources()

    entry: dict = {}          # (module, fn) -> lineno
    own_uses: dict = {}       # module -> {name: {enclosing fn or ""}}
    all_refs: set = set()     # qualified (module, fn) refs, everywhere
    parsed: dict = {}
    for path in sorted(set(_py_files(engine_dir)) | set(sources)):
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except SyntaxError:
            continue
        modname = _module_name(path, _PKG_ROOT)
        parsed[modname] = path
        refs, own = _file_refs(tree, modname)
        all_refs |= refs
        if os.path.dirname(os.path.abspath(path)) \
                == os.path.abspath(engine_dir):
            own_uses[modname] = own
            for node in ast.iter_child_nodes(tree):
                if isinstance(node, ast.FunctionDef) \
                        and not node.name.startswith("_") \
                        and (node.name.startswith("make_")
                             or node.name.startswith("supports")):
                    entry[(modname, node.name)] = node.lineno

    # liveness fixpoint: externally referenced entry points are live;
    # an own-module use keeps a function live only if it comes from
    # module level or from a function that is not itself a dead entry
    # point.
    dead = {k for k in entry if k not in all_refs}
    changed = True
    while changed:
        changed = False
        for mod, fn in list(dead):
            users = own_uses.get(mod, {}).get(fn, set())
            live_users = {u for u in users
                          if u == "" or (mod, u) not in dead}
            if live_users:
                dead.discard((mod, fn))
                changed = True

    findings = []
    for mod, fn in sorted(dead):
        rel = os.path.relpath(parsed[mod], _REPO_ROOT)
        findings.append(Finding(
            "hygiene.dead_entry_point", "error", "",
            f"{mod}.{fn} ({rel}:{entry[(mod, fn)]}) is an engine entry "
            "point nothing dispatches to — wire it into the Lattice/"
            "adjoint selection or delete it",
            f"{rel}:{entry[(mod, fn)]}"))
    return findings


def _calls_named(node, name: str) -> bool:
    """True if any call under ``node`` targets ``name`` — bare
    (``engine_selected(...)``) or qualified (``telemetry.engine_selected``)."""
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if isinstance(f, ast.Name) and f.id == name:
            return True
        if isinstance(f, ast.Attribute) and f.attr == name:
            return True
    return False


def _assigns_fast_name(node) -> bool:
    """True if any statement under ``node`` assigns ``self._fast_name``."""
    for n in ast.walk(node):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "_fast_name" \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    return True
    return False


def scan_dispatch_telemetry(lattice_path=None) -> list:
    """Engine dispatch must be observable: ``_fast_path`` emits
    ``engine_selected`` and every except handler that reassigns
    ``self._fast_name`` (i.e. demotes the engine) emits
    ``engine_fallback``.  Without these, a production trace cannot say
    which engine ran — the exact blind spot that made the BENCH_r05
    heat_adj regression untriageable."""
    path = lattice_path or os.path.join(_PKG_ROOT, "core", "lattice.py")
    rel = os.path.relpath(path, _REPO_ROOT)
    try:
        with open(path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError) as e:
        return [Finding("hygiene.unparseable", "error", "",
                        f"cannot parse {path}: {e}", path)]

    findings = []
    fast_path = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_fast_path":
            fast_path = node
            break
    if fast_path is None:
        findings.append(Finding(
            "hygiene.untraced_dispatch", "error", "",
            f"{rel} has no _fast_path — the dispatch tracing contract "
            "expects one", rel))
    elif not _calls_named(fast_path, "engine_selected"):
        findings.append(Finding(
            "hygiene.untraced_dispatch", "error", "",
            f"{rel}:{fast_path.lineno} _fast_path never emits "
            "engine_selected — traces cannot attribute iterate spans to "
            "an engine", f"{rel}:{fast_path.lineno}"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if _assigns_fast_name(node) \
                and not _calls_named(node, "engine_fallback"):
            findings.append(Finding(
                "hygiene.untraced_dispatch", "error", "",
                f"{rel}:{node.lineno} except handler demotes "
                "self._fast_name without emitting engine_fallback — "
                "silent engine downgrades are invisible in traces",
                f"{rel}:{node.lineno}"))
    return findings


def _public_self_attr_writes(fn_node) -> list:
    """``(attr, lineno)`` for every public ``self.<attr>`` the function
    assigns — plain/augmented assignment targets and subscript stores
    (``self.old[name] = ...``)."""
    out = []
    for n in ast.walk(fn_node):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                if isinstance(t, ast.Subscript):
                    t = t.value
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self" \
                        and not t.attr.startswith("_"):
                    out.append((t.attr, n.lineno))
    return out


def scan_unrestorable_handlers(paths=None) -> list:
    """Checkpoint completeness: a Handler subclass whose ``do_it`` mutates
    public ``self`` attributes carries run-state that a full-run
    checkpoint must capture — it must implement ``restorable_state`` in
    its own body (or explicitly opt out with ``checkpoint_exempt =
    True``), otherwise a kill-resume silently resets that state and the
    resumed run diverges from the uninterrupted one."""
    if paths is None:
        paths = _py_files(os.path.join(_PKG_ROOT, "control"))
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        rel = os.path.relpath(path, _REPO_ROOT)

        classes = {n.name: n for n in ast.walk(tree)
                   if isinstance(n, ast.ClassDef)}

        def is_handler(cls, seen=None) -> bool:
            seen = seen or set()
            if cls.name in seen:
                return False
            seen.add(cls.name)
            for b in cls.bases:
                name = b.id if isinstance(b, ast.Name) else \
                    (b.attr if isinstance(b, ast.Attribute) else None)
                if name == "Handler":
                    return True
                if name in classes and is_handler(classes[name], seen):
                    return True
            return False

        for cls in classes.values():
            if cls.name == "Handler" or not is_handler(cls):
                continue
            body_fns = {n.name for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
            exempt = any(
                isinstance(n, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "checkpoint_exempt"
                        for t in n.targets)
                and isinstance(n.value, ast.Constant) and n.value.value
                for n in cls.body)
            if "restorable_state" in body_fns or exempt:
                continue
            do_it = next((n for n in cls.body
                          if isinstance(n, ast.FunctionDef)
                          and n.name == "do_it"), None)
            if do_it is None:
                continue
            writes = _public_self_attr_writes(do_it)
            if writes:
                attrs = sorted({a for a, _ln in writes})
                findings.append(Finding(
                    "hygiene.unrestorable_handler", "error", "",
                    f"{rel}:{cls.lineno} {cls.name}.do_it mutates "
                    f"self.{', self.'.join(attrs)} but the class neither "
                    "implements restorable_state() nor sets "
                    "checkpoint_exempt = True — this state is lost on "
                    "checkpoint resume", f"{rel}:{cls.lineno}"))
    return findings


_CTX_TAINT_ATTRS = ("setting", "setting_dt")
_HOST_CASTS = ("float", "int", "bool")


def _is_ctx_setting_call(node) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _CTX_TAINT_ATTRS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "ctx")


def _assigned_names(target) -> list:
    """Names an assignment target binds.  A subscript store taints only
    the container (``out[i] = tainted`` taints ``out``, never the index
    ``i`` — an index is read, not bound)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        return [x for e in target.elts for x in _assigned_names(e)]
    if isinstance(target, (ast.Subscript, ast.Starred)):
        return _assigned_names(target.value)
    return []


def scan_ensemble_unsafe(paths=None) -> list:
    """Python-level branching/host-casting on per-case setting values in
    model stage code.

    Under the batched ensemble engine every case carries its *own*
    ``SimParams``, so a setting is a traced per-case value — a
    ``float(...)``/``int(...)``/``bool(...)`` cast, an ``.item()`` pull
    or an ``if``-test on anything derived from ``ctx.setting``/
    ``ctx.setting_dt`` freezes one case's value into the compiled
    program (or fails outright under vmap) and silently breaks the
    bit-parity contract for every other case in the batch.  Casts of
    genuine host constants (``float(E[i, 0])`` on a numpy stencil
    table) are fine and not flagged: taint starts at the ctx setting
    accessors and propagates only through assigned names."""
    if paths is None:
        paths = _py_files(os.path.join(_PKG_ROOT, "models"))
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        rel = os.path.relpath(path, _REPO_ROOT)
        ctx_fns = [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and n.args.args and n.args.args[0].arg == "ctx"]
        seen: set = set()
        for fn in ctx_fns:
            # assignment events in source order.  Taint is replayed as a
            # forward flow: a plain Name assignment from a clean RHS
            # CLEARS the name (models reuse short names like ``c`` for
            # both stencil constants and setting-derived arrays), a
            # subscript store only ever adds taint to the container, and
            # an augmented assignment keeps the old value's taint.
            events: list = []
            for n in ast.walk(fn):
                if not isinstance(n, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)):
                    continue
                if n.value is None:
                    continue
                targets = (n.targets if isinstance(n, ast.Assign)
                           else [n.target])
                updates = []
                for t in targets:
                    strong = isinstance(n, (ast.Assign, ast.AnnAssign)) \
                        and isinstance(t, (ast.Name, ast.Tuple, ast.List))
                    for name in _assigned_names(t):
                        updates.append((name, strong))
                if updates:
                    events.append((n.lineno, updates, n.value))
            events.sort(key=lambda e: e[0])

            def expr_tainted(e, tset) -> bool:
                for n in ast.walk(e):
                    if _is_ctx_setting_call(n):
                        return True
                    if isinstance(n, ast.Name) \
                            and isinstance(n.ctx, ast.Load) \
                            and n.id in tset:
                        return True
                return False

            def taint_at(lineno: int) -> set:
                tset: set = set()
                for ln, updates, rhs in events:
                    if ln >= lineno:
                        break
                    hot = expr_tainted(rhs, tset)
                    for name, strong in updates:
                        if hot:
                            tset.add(name)
                        elif strong:
                            tset.discard(name)
                return tset

            def flag(lineno: int, what: str) -> None:
                key = (rel, lineno, what)
                if key in seen:
                    return
                seen.add(key)
                findings.append(Finding(
                    "hygiene.ensemble_unsafe", "error", "",
                    f"{rel}:{lineno} {fn.name}: {what} on a "
                    "ctx.setting-derived value — per-case settings are "
                    "traced under the batched ensemble engine; this "
                    "freezes one case's value into the compiled step "
                    "(keep the computation in jax ops instead)",
                    f"{rel}:{lineno}"))

            def is_none_test(e) -> bool:
                # ``x is None`` / ``x is not None`` are host-structural
                # dispatch, not branching on the setting's value
                return isinstance(e, ast.Compare) \
                    and all(isinstance(op, (ast.Is, ast.IsNot))
                            for op in e.ops)

            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    f = n.func
                    if isinstance(f, ast.Name) and f.id in _HOST_CASTS \
                            and n.args \
                            and expr_tainted(n.args[0], taint_at(n.lineno)):
                        flag(n.lineno, f"host cast {f.id}(...)")
                    elif isinstance(f, ast.Attribute) and f.attr == "item" \
                            and not n.args \
                            and expr_tainted(f.value, taint_at(n.lineno)):
                        flag(n.lineno, ".item() pull")
                elif isinstance(n, (ast.If, ast.While)) \
                        and not is_none_test(n.test) \
                        and expr_tainted(n.test, taint_at(n.lineno)):
                    flag(n.lineno,
                         f"python {type(n).__name__.lower()}-branch")
                elif isinstance(n, ast.IfExp) \
                        and not is_none_test(n.test) \
                        and expr_tainted(n.test, taint_at(n.lineno)):
                    flag(n.lineno, "python conditional expression")
    return findings


def scan_unpinned_device_put(paths=None) -> list:
    """Device-placement hygiene for the serving fleet: every
    ``device_put`` in ``tclb_tpu/serve`` must name an explicit target —
    a second positional argument or a ``device=``/``sharding=`` keyword.

    A bare ``jax.device_put(x)`` commits to ``jax.devices()[0]``, which
    on a fleet lane silently funnels every lane's staging traffic onto
    device 0 — the exact cross-lane contention the dispatcher exists to
    avoid, and invisible in tests that run on one device."""
    if paths is None:
        paths = _py_files(os.path.join(_PKG_ROOT, "serve"))
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        rel = os.path.relpath(path, _REPO_ROOT)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.id if isinstance(f, ast.Name) else \
                (f.attr if isinstance(f, ast.Attribute) else None)
            if name != "device_put":
                continue
            pinned = len(node.args) >= 2 or any(
                kw.arg in ("device", "sharding") for kw in node.keywords)
            if not pinned:
                findings.append(Finding(
                    "hygiene.unpinned_device_put", "error", "",
                    f"{rel}:{node.lineno} device_put without an explicit "
                    "device/sharding — in serve/ this commits to "
                    "jax.devices()[0] and funnels every fleet lane's "
                    "staging onto device 0; pass the lane's device "
                    "(or a NamedSharding) explicitly",
                    f"{rel}:{node.lineno}"))
    return findings


_MONITOR_BANNED_ROOTS = ("jax", "jaxlib")
_MONITOR_BANNED_CALLS = ("device_put", "block_until_ready", "device_get")
_MONITOR_BANNED_NAMES = ("Lattice",)


def scan_device_work_in_monitor(paths=None) -> list:
    """The HTTP monitor handler thread must never touch device state: a
    scrape that calls into jax (or walks a Lattice) can deadlock against
    the solve loop's dispatch or, worse, enqueue host-to-device work from
    an arbitrary thread mid-iterate.  The contract is structural —
    ``telemetry/http.py`` reads registry/status snapshots only — so this
    check enforces it by AST: no jax/jaxlib import, no
    ``device_put``/``block_until_ready``/``device_get`` call, and no
    ``Lattice`` reference anywhere in the monitor module."""
    if paths is None:
        paths = [os.path.join(_PKG_ROOT, "telemetry", "http.py")]
    return _scan_device_free_module(
        paths, "hygiene.device_work_in_monitor",
        "the monitor handler thread must only read registry/status "
        "snapshots, never touch jax or device state (scrapes racing the "
        "solve loop can deadlock dispatch); move the work behind a "
        "status provider registered from the owning thread")


def scan_device_work_in_gateway(paths=None) -> list:
    """Same contract, serving front door: the gateway's HTTP handler
    module (``gateway/http.py``) must never import jax or reference a
    Lattice — handler threads validate, write store records, and wait on
    plain events only.  Device work belongs to the
    :class:`GatewayService` worker threads, so a slow or hostile client
    can never fence, allocate on, or deadlock a device."""
    if paths is None:
        paths = [os.path.join(_PKG_ROOT, "gateway", "http.py")]
    return _scan_device_free_module(
        paths, "hygiene.device_work_in_gateway",
        "the gateway handler thread must only validate, enqueue job "
        "records and snapshot plain-python state, never touch jax or "
        "device state (a slow client would be holding a device "
        "hostage); move the work onto the GatewayService worker side")


def _scan_device_free_module(paths, check_name: str, contract: str) -> list:
    """Shared AST enforcement for modules whose threads must stay off
    the device: no jax/jaxlib import, no ``device_put``/
    ``block_until_ready``/``device_get`` call, no ``Lattice``
    reference."""
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        rel = os.path.relpath(path, _REPO_ROOT)

        def flag(lineno: int, what: str) -> None:
            findings.append(Finding(
                check_name, "error", "",
                f"{rel}:{lineno} {what} — {contract}",
                f"{rel}:{lineno}"))

        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    root = a.name.split(".")[0]
                    if root in _MONITOR_BANNED_ROOTS:
                        flag(node.lineno, f"imports {a.name}")
            elif isinstance(node, ast.ImportFrom):
                root = (node.module or "").split(".")[0]
                if root in _MONITOR_BANNED_ROOTS:
                    flag(node.lineno, f"imports from {node.module}")
                for a in node.names:
                    if a.name in _MONITOR_BANNED_CALLS \
                            or a.name in _MONITOR_BANNED_NAMES:
                        flag(node.lineno, f"imports {a.name}")
            elif isinstance(node, ast.Call):
                f = node.func
                name = f.id if isinstance(f, ast.Name) else \
                    (f.attr if isinstance(f, ast.Attribute) else None)
                if name in _MONITOR_BANNED_CALLS:
                    flag(node.lineno, f"calls {name}(...)")
            elif isinstance(node, ast.Name) \
                    and node.id in _MONITOR_BANNED_NAMES:
                flag(node.lineno, f"references {node.id}")
            elif isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in _MONITOR_BANNED_ROOTS:
                flag(node.lineno,
                     f"uses {node.value.id}.{node.attr}")
    return findings


def scan_unpoliced_retry(paths=None) -> list:
    """Retry discipline for the serving stack: a retry loop in
    ``tclb_tpu/serve`` or ``tclb_tpu/gateway`` — a ``for``/``while``
    that catches exceptions and sleeps a *fixed* amount before going
    around again — must run through :class:`serve.retry.RetryPolicy`.

    Hand-rolled fixed-delay retries are exactly what chaos testing
    punishes: no exponential backoff, no jitter (retry stampedes), and
    no deadline awareness, so a retry ladder can outlive the caller's
    submitted ``timeout_s``.  The structural signature is a loop whose
    body contains an ``except`` handler AND a constant-argument
    ``sleep(...)``, inside a function that never references
    ``RetryPolicy``/``retry_policy``."""
    if paths is None:
        paths = (_py_files(os.path.join(_PKG_ROOT, "serve"))
                 + _py_files(os.path.join(_PKG_ROOT, "gateway"))
                 + _py_files(os.path.join(_PKG_ROOT, "cluster")))
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        rel = os.path.relpath(path, _REPO_ROOT)
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            policed = False
            for n in ast.walk(fn):
                if (isinstance(n, ast.Name) and n.id == "RetryPolicy") \
                        or (isinstance(n, (ast.Attribute, ast.keyword))
                            and (getattr(n, "attr", None) == "retry_policy"
                                 or getattr(n, "arg", None)
                                 == "retry_policy")):
                    policed = True
                    break
            if policed:
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                has_except = any(isinstance(n, ast.ExceptHandler)
                                 for n in ast.walk(loop))
                sleep_line = None
                for n in ast.walk(loop):
                    if isinstance(n, ast.Call):
                        f = n.func
                        name = f.id if isinstance(f, ast.Name) else \
                            (f.attr if isinstance(f, ast.Attribute)
                             else None)
                        if name == "sleep" and n.args \
                                and isinstance(n.args[0], ast.Constant):
                            sleep_line = n.lineno
                            break
                if has_except and sleep_line is not None:
                    findings.append(Finding(
                        "hygiene.unpoliced_retry", "error", "",
                        f"{rel}:{sleep_line} {fn.name}: retry loop with a "
                        "fixed sleep bypasses RetryPolicy — hand-rolled "
                        "backoff has no jitter and no deadline awareness, "
                        "so retries can stampede and outlive the caller's "
                        "timeout_s; compute delays with "
                        "serve.retry.RetryPolicy.next_delay",
                        f"{rel}:{sleep_line}"))
                    break  # one finding per function is enough signal
    return findings


#: subprocess-spawning calls the serving stack may only make inside the
#: supervised pool (attribute name -> how we describe it)
_SPAWN_CALLS = frozenset({"Popen", "run", "call", "check_call",
                          "check_output", "fork", "forkpty", "spawnv",
                          "spawnve", "posix_spawn"})


def scan_unsupervised_subprocess(paths=None) -> list:
    """Process-spawning discipline for the serving stack: the ONLY
    module in ``tclb_tpu/serve`` or ``tclb_tpu/gateway`` allowed to
    start a child process is ``serve/pool.py`` — the supervisor that
    owns heartbeat watchdogs, SIGTERM→SIGKILL escalation, crash-loop
    backoff, and job requeue.

    A ``subprocess.Popen``/``os.fork`` anywhere else is an orphan
    factory: nobody watches its heartbeat, nobody reaps it on hang, and
    a crash loses whatever job it carried.  The structural signature is
    any call to a spawning API (``subprocess.Popen/run/call/check_*``,
    ``os.fork``/``forkpty``/``posix_spawn``) or a ``from subprocess
    import Popen``-style alias, outside the pool module.  The cluster
    plane (``tclb_tpu/cluster``) is held to the same rule: the
    host-agent supervises its local lanes *through* ``WorkerPool``
    rather than spawning children of its own."""
    if paths is None:
        paths = (_py_files(os.path.join(_PKG_ROOT, "serve"))
                 + _py_files(os.path.join(_PKG_ROOT, "gateway"))
                 + _py_files(os.path.join(_PKG_ROOT, "cluster")))
    findings = []
    for path in paths:
        if os.path.basename(path) == "pool.py" \
                and os.path.basename(os.path.dirname(path)) == "serve":
            continue  # the one sanctioned spawner
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "hygiene.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        rel = os.path.relpath(path, _REPO_ROOT)

        def flag(lineno: int, what: str) -> None:
            findings.append(Finding(
                "hygiene.unsupervised_subprocess", "error", "",
                f"{rel}:{lineno} {what} outside serve/pool.py — an "
                "unsupervised child has no heartbeat watchdog, no "
                "kill escalation, and no crash-loop backoff, and a "
                "crash silently loses its job; route process spawning "
                "through serve.pool.WorkerPool",
                f"{rel}:{lineno}"))

        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                if (node.module or "").split(".")[0] == "subprocess":
                    for a in node.names:
                        if a.name in _SPAWN_CALLS:
                            flag(node.lineno,
                                 f"imports subprocess.{a.name}")
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in ("subprocess", "os") \
                        and f.attr in _SPAWN_CALLS:
                    flag(node.lineno,
                         f"calls {f.value.id}.{f.attr}(...)")
                elif isinstance(f, ast.Name) and f.id == "Popen":
                    flag(node.lineno, "calls Popen(...)")
    return findings


def check_repo(engine_dir=None, sources=None) -> list:
    from tclb_tpu.analysis.concurrency import check_concurrency
    from tclb_tpu.analysis.precision import (scan_unsafe_accum,
                                             scan_unshifted_cast)
    return (scan_dead_entry_points(engine_dir, sources)
            + scan_id_keyed_caches()
            + scan_unbounded_adjoint()
            + scan_dispatch_telemetry()
            + scan_unrestorable_handlers()
            + scan_ensemble_unsafe()
            + scan_unpinned_device_put()
            + scan_device_work_in_monitor()
            + scan_device_work_in_gateway()
            + scan_unpoliced_retry()
            + scan_unsupervised_subprocess()
            + scan_unsafe_accum()
            + scan_unshifted_cast()
            + check_concurrency())


def check_model_hygiene(model: Model, shape=None) -> list:
    """Per-model hygiene: unbound stages behind registered actions, and
    the test/golden inventory (informational — the generic parametrized
    sweeps cover models no test names explicitly)."""
    findings: list = []
    for action, stages in sorted(model.actions.items()):
        for sname in stages:
            st = model.stages.get(sname)
            if st is None:
                findings.append(Finding(
                    "hygiene.missing_stage", "error", model.name,
                    f"action {action!r} references unregistered stage "
                    f"{sname!r}", f"action:{action}"))
            elif model.stage_fns.get(st.main) is None:
                findings.append(Finding(
                    "hygiene.unbound_stage", "error", model.name,
                    f"action {action!r} stage {sname!r} has no bound "
                    f"function {st.main!r}", f"action:{action}"))

    tests_dir = os.path.join(_REPO_ROOT, "tests")
    named = False
    if os.path.isdir(tests_dir):
        needle_a, needle_b = f'"{model.name}"', f"'{model.name}'"
        for p in _py_files(tests_dir):
            with open(p) as fh:
                src = fh.read()
            if needle_a in src or needle_b in src:
                named = True
                break
    if not named:
        findings.append(Finding(
            "hygiene.no_named_test", "info", model.name,
            "no test references this model by name (the parametrized "
            "all-models sweeps still cover it)"))
    goldens_dir = os.path.join(tests_dir, "goldens")
    has_golden = False
    if os.path.isdir(goldens_dir):
        for f in os.listdir(goldens_dir):
            path = os.path.join(goldens_dir, f)
            if f.endswith(".json") and os.path.isfile(path):
                with open(path) as fh:
                    if model.name in fh.read():
                        has_golden = True
                        break
    if not has_golden:
        findings.append(Finding(
            "hygiene.no_golden", "info", model.name,
            "no golden regression file references this model"))
    return findings
