"""Velocity-set / moment invariants.

The reference's codegen reads each model's velocity set and weights from
its R registration and any malformed set dies at template-expansion time;
here the registry only stores the streaming vectors, so this check
re-derives the lattice weights (ops/lbm's shell tables — the same tables
the physics callables use) and verifies the discrete moment conditions
every LBM velocity set must satisfy:

* weights positive and summing to 1;
* first moments vanish: ``sum_i w_i e_i = 0`` (and ``sum_i e_i = 0``);
* second-moment isotropy: ``sum_i w_i e_ia e_ib = cs^2 delta_ab`` with a
  single sound speed across axes;
* opposite-direction pairing: every ``e_i`` has ``-e_i`` in the same
  group (bounce-back reflects per pair — an unpaired vector makes every
  Wall node silently lose mass).

A model may carry ``declared_weights`` (mapping group name -> weight
array, in storage order) — e.g. a test fixture or a model with
non-standard weights; those are checked instead of the shell table.
"""

from __future__ import annotations

import numpy as np

from tclb_tpu.analysis.findings import Finding
from tclb_tpu.core.registry import Model

_TOL = 1e-12


def _velocity_groups(model: Model):
    """Groups that look like streamed velocity sets: >= 2 members, all
    densities, at least one nonzero streaming vector."""
    n_dens = len(model.densities)
    out = {}
    for g, idx in model.groups.items():
        if len(idx) < 2 or any(i >= n_dens for i in idx):
            continue
        E = model.ei[list(idx), :model.ndim]
        if not np.any(E):
            continue
        out[g] = E
    return out


def check_invariants(model: Model, shape=None) -> list:
    findings: list = []
    vgroups = _velocity_groups(model)
    if not vgroups:
        findings.append(Finding(
            "invariants.no_velocity_set", "info", model.name,
            "no streamed velocity-set group to check"))
        return findings

    declared = getattr(model, "declared_weights", None) or {}

    for g, E in vgroups.items():
        q, d = E.shape
        where = f"group:{g}"

        # -- set symmetry (weights not needed) -------------------------- #
        net = E.sum(axis=0)
        if np.any(net != 0):
            findings.append(Finding(
                "invariants.net_velocity", "error", model.name,
                f"velocity set {g!r} does not sum to zero: "
                f"sum(e) = {net.tolist()}", where,
                {"sum_e": net.tolist()}))
        vset = {tuple(int(v) for v in e) for e in E}
        unpaired = sorted(e for e in vset
                          if tuple(-v for v in e) not in vset)
        if unpaired:
            findings.append(Finding(
                "invariants.opposite_pairing", "error", model.name,
                f"velocity set {g!r} has vectors without an opposite "
                f"(bounce-back would lose mass): {unpaired}", where,
                {"unpaired": [list(e) for e in unpaired]}))
        if len(vset) != q:
            findings.append(Finding(
                "invariants.duplicate_vector", "error", model.name,
                f"velocity set {g!r} has duplicate streaming vectors",
                where))

        # -- weights ---------------------------------------------------- #
        if g in declared:
            w = np.asarray(declared[g], dtype=np.float64)
            src = "declared"
        else:
            try:
                from tclb_tpu.ops import lbm
                w = np.asarray(lbm.weights(E), dtype=np.float64)
                src = "shell-table"
            except Exception:
                findings.append(Finding(
                    "invariants.no_weight_table", "info", model.name,
                    f"velocity set {g!r} (q={q}, d={d}) has no standard "
                    "weight table; weight-moment checks skipped", where))
                continue
        if w.shape != (q,):
            findings.append(Finding(
                "invariants.weight_shape", "error", model.name,
                f"{src} weights for {g!r} have shape {w.shape}, "
                f"expected ({q},)", where))
            continue
        if np.any(w <= 0):
            findings.append(Finding(
                "invariants.weight_sign", "error", model.name,
                f"{src} weights for {g!r} are not all positive", where,
                {"weights": w.tolist()}))
        wsum = float(w.sum())
        if abs(wsum - 1.0) > 1e-9:
            findings.append(Finding(
                "invariants.weight_sum", "error", model.name,
                f"{src} weights for {g!r} sum to {wsum!r}, expected 1",
                where, {"sum": wsum}))
        m1 = w @ E
        if np.max(np.abs(m1)) > 1e-9:
            findings.append(Finding(
                "invariants.first_moment", "error", model.name,
                f"first moment of {g!r} does not vanish: "
                f"sum(w e) = {m1.tolist()}", where,
                {"first_moment": m1.tolist()}))
        # second moment: T_ab = sum_i w_i e_ia e_ib = cs^2 delta_ab
        T = np.einsum("i,ia,ib->ab", w, E, E)
        off = T - np.diag(np.diag(T))
        diag = np.diag(T)
        if np.max(np.abs(off)) > 1e-9:
            findings.append(Finding(
                "invariants.second_moment_cross", "error", model.name,
                f"second moment of {g!r} has nonzero cross terms", where,
                {"T": T.tolist()}))
        if np.max(np.abs(diag - diag[0])) > 1e-9:
            findings.append(Finding(
                "invariants.second_moment_anisotropy", "error", model.name,
                f"second moment of {g!r} is anisotropic: "
                f"diag = {diag.tolist()}", where, {"T": T.tolist()}))
        else:
            findings.append(Finding(
                "invariants.sound_speed", "info", model.name,
                f"velocity set {g!r}: q={q} d={d} cs^2={diag[0]:.6g} "
                f"({src} weights)", where,
                {"cs2": float(diag[0]), "q": q, "d": d}))
    return findings
