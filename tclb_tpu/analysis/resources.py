"""Kernel-resource estimation: per-engine VMEM/tile budgets, statically.

Mirrors the sizing arithmetic each engine applies at build time
(``_band_rows``/``_pad_rows``/``_slab_depth_gen``/the backward kernel's
``by_bwd`` heuristic) and evaluates it at PRODUCTION shapes — including
the ``k = max_chunk`` fused-chain widths that ``supports_diff``'s cheap
k=1 probe historically never exercised.  That turns "auto fell back
because the first TPU compile died" into a finding the analyzer (and the
eligibility caches) can report before anything compiles.

``adjoint_static_ok`` is the verdict ``supports_diff`` consults: the
backward band kernel's three double-buffered scratch stacks at the
minimum band height, against its VMEM ceiling.
"""

from __future__ import annotations

from tclb_tpu.analysis.findings import Finding
from tclb_tpu.core.registry import Model

# the backward band kernel raises the compiler's VMEM ceiling to 100 MB
# (ops/pallas_adjoint); its scratch must leave room for the VJP chain's
# live temporaries, so the static gate draws the line well below that.
_ADJ_SCRATCH_LIMIT = 64 * 1024 * 1024


def default_shape(model: Model) -> tuple:
    """Representative production shape (the bench cases' scale)."""
    return (512, 1024) if model.ndim == 2 else (48, 48, 256)


def _adjoint_scratch_bytes(model: Model, nx: int, by: int,
                           series: bool) -> int:
    """Bytes of the backward kernel's double-buffered primal + lambda +
    aux band stacks at band height ``by`` (mirrors make_diff_step)."""
    halo = 8
    n_aux = 1 + (2 if series else 1) * len(model.zonal_settings)
    per_row = (2 * model.n_storage + n_aux) * nx * 4
    return 2 * (by + 2 * halo) * per_row


def adjoint_static_ok(model: Model, nx: int, series: bool = False) -> bool:
    """Whether the backward band kernel can possibly fit VMEM at this
    width: even the minimum 8-row band must stay under the scratch
    limit.  Consulted by ``supports_diff`` so ineligibility is decided
    statically instead of by a compile failure."""
    return _adjoint_scratch_bytes(model, nx, 8, series) \
        <= _ADJ_SCRATCH_LIMIT


def check_resources(model: Model, shape=None) -> list:
    findings: list = []
    from tclb_tpu.ops import pallas_generic

    shape = tuple(int(s) for s in (shape or default_shape(model)))
    if len(shape) != model.ndim:
        findings.append(Finding(
            "resources.bad_shape", "warning", model.name,
            f"shape {shape} does not match model ndim={model.ndim}; "
            "resource checks skipped"))
        return findings
    try:
        _, reach = pallas_generic.action_plan(model, "Iteration", fuse=1)
    except Exception:  # noqa: BLE001 — no Iteration action / broken plan
        return findings
    where = f"shape:{'x'.join(str(s) for s in shape)}"

    if model.ndim == 2:
        ny, nx = shape
        # -- forward band engine ---------------------------------------- #
        pad = pallas_generic._pad_rows(model, ny, nx, max(reach, 1))
        if pad is None:
            findings.append(Finding(
                "resources.band_vmem", "warning", model.name,
                f"no band height fits the "
                f"{pallas_generic._VMEM_SCRATCH_BUDGET >> 20} MB scratch "
                f"budget at {ny}x{nx} ({model.n_storage} storage planes): "
                "generic band engine ineligible, XLA fallback", where,
                {"n_storage": model.n_storage, "shape": list(shape)}))
        else:
            by = pallas_generic._band_rows(model, ny + pad, nx)
            n_aux = 1 + 2 * len(model.zonal_settings)
            est = 2 * (by + 16) * (model.n_storage + n_aux) * nx * 4
            findings.append(Finding(
                "resources.band_layout", "info", model.name,
                f"band engine: by={by} pad={pad} scratch~{est >> 10} KiB",
                where, {"by": by, "pad": pad, "scratch_bytes": est}))
        # -- resident engine -------------------------------------------- #
        n_aux_r = 1 + len(model.zonal_settings)
        res_bytes = (2 * model.n_storage + n_aux_r) * ny * nx * 4
        res_ok = (ny % 8 == 0 and nx % 128 == 0
                  and res_bytes <= pallas_generic._RESIDENT_BUDGET
                  and reach <= pallas_generic.HALO)
        findings.append(Finding(
            "resources.resident", "info", model.name,
            f"VMEM-resident engine {'eligible' if res_ok else 'ineligible'}"
            f" at {ny}x{nx} (state+aux {res_bytes >> 20} MiB / "
            f"{pallas_generic._RESIDENT_BUDGET >> 20} MiB budget)", where,
            {"eligible": res_ok, "resident_bytes": res_bytes}))
        # -- adjoint backward kernel at the production chunk ------------ #
        from tclb_tpu.ops import pallas_adjoint
        k = pallas_adjoint.max_chunk(model)
        if k >= 1:
            for series in (False, True):
                if series and not model.zonal_settings:
                    continue
                if not adjoint_static_ok(model, nx, series):
                    findings.append(Finding(
                        "resources.adjoint_vmem", "warning", model.name,
                        f"backward band kernel cannot fit VMEM at width "
                        f"nx={nx}"
                        + (" (series flavor)" if series else "")
                        + f": minimum-band scratch "
                        f"{_adjoint_scratch_bytes(model, nx, 8, series) >> 20}"
                        f" MiB > {_ADJ_SCRATCH_LIMIT >> 20} MiB — "
                        "engine='auto' adjoint falls back to XLA "
                        "statically", where,
                        {"series": series, "nx": nx,
                         "scratch_bytes":
                             _adjoint_scratch_bytes(model, nx, 8, series)}))
                else:
                    # the default by_bwd the builder would pick at k
                    n_aux = 1 + (2 if series else 1) \
                        * len(model.zonal_settings)
                    per_row = (2 * model.n_storage + n_aux) * nx * 4
                    by = 64
                    while by > 8 and 2 * (by + 16) * per_row \
                            > 24 * 1024 * 1024:
                        by -= 8
                    findings.append(Finding(
                        "resources.adjoint_layout", "info", model.name,
                        f"adjoint kernel at production chunk k={k}"
                        + (" (series: k=1)" if series else "")
                        + f": by_bwd={by} scratch~"
                        f"{2 * (by + 16) * per_row >> 20} MiB", where,
                        {"k": 1 if series else k, "by_bwd": by,
                         "series": series}))
    else:
        nz, ny, nx = shape
        bz = pallas_generic._slab_depth_gen(model, nz, ny, nx,
                                            max(reach, 1))
        if bz is None:
            findings.append(Finding(
                "resources.slab_vmem", "warning", model.name,
                f"no z-slab depth fits the 12 MB scratch budget at "
                f"{nz}x{ny}x{nx} ({model.n_storage} storage planes): "
                "generic 3D engine ineligible, XLA fallback", where,
                {"n_storage": model.n_storage, "shape": list(shape)}))
        else:
            n_aux = 1 + 2 * len(model.zonal_settings)
            est = 2 * (bz + 2 * max(reach, 1)) \
                * (model.n_storage + n_aux) * ny * nx * 4
            findings.append(Finding(
                "resources.slab_layout", "info", model.name,
                f"3D slab engine: bz={bz} scratch~{est >> 20} MiB",
                where, {"bz": bz, "scratch_bytes": est}))
        # -- fused (K>=2) working sets at the PRODUCTION fusion depth -- #
        # the planners only propose configs their own fits() predicate
        # accepts, so a config exceeding its engine's budget here means
        # planner and builder have drifted apart — an error, because the
        # first TPU compile would die where the probe ladder can't see it
        K3 = pallas_generic.choose_fuse_3d(model, shape)
        if K3 >= 2:
            _, rK = pallas_generic.action_plan(model, "Iteration",
                                               fuse=K3)
            RK = max(rK, 1)
            bzK = pallas_generic._slab_depth_gen(
                model, nz, ny, nx, RK, n_aux=1,
                budget=pallas_generic._FUSED3D_BUDGET)
            estK = None if bzK is None else \
                2 * (bzK + 2 * RK) * (model.n_storage + 1) * ny * nx * 4
            if bzK is None or estK > pallas_generic._FUSED3D_BUDGET:
                findings.append(Finding(
                    "resources.fused_vmem", "error", model.name,
                    f"generic 3D planner picked fuse={K3} but no slab "
                    f"depth fits the "
                    f"{pallas_generic._FUSED3D_BUDGET >> 20} MB fused "
                    f"scratch budget at {nz}x{ny}x{nx}: planner/builder "
                    "drift, first TPU compile will fail", where,
                    {"fuse": K3, "reach": RK}))
            else:
                findings.append(Finding(
                    "resources.fused_slab", "info", model.name,
                    f"generic 3D fused engine: fuse={K3} bz={bzK} "
                    f"reach={RK} scratch~{estK >> 20} MiB", where,
                    {"fuse": K3, "bz": bzK, "reach": RK,
                     "scratch_bytes": estK}))
        from tclb_tpu.ops import pallas_d3q
        cfg = pallas_d3q.fused_cfg(model, shape)
        if cfg is not None:
            bzD, KD = cfg
            if not pallas_d3q._fused_fits(model, nz, ny, nx, bzD, KD):
                findings.append(Finding(
                    "resources.fused_vmem", "error", model.name,
                    f"tuned d3q planner picked (bz={bzD}, K={KD}) but "
                    f"its working set exceeds the "
                    f"{pallas_d3q._FUSED_BUDGET >> 20} MB fused budget "
                    f"at {nz}x{ny}x{nx}: planner/builder drift", where,
                    {"fuse": KD, "bz": bzD}))
            else:
                H = bzD + 2 * KD
                per = ny * nx * 4
                estD = (2 * (model.n_storage + 1) * H
                        + 2 * model.n_storage * bzD) * per
                findings.append(Finding(
                    "resources.fused_slab", "info", model.name,
                    f"tuned d3q fused engine: fuse={KD} bz={bzD} "
                    f"scratch~{estD >> 20} MiB (+ collision "
                    "temporaries)", where,
                    {"fuse": KD, "bz": bzD, "scratch_bytes": estD}))
        # -- fused 3D backward kernel at the production chunk ----------- #
        # mirror the 2D adjoint_layout finding: evaluate the Run_b slab
        # planner at the shape production actually runs, so an infeasible
        # plan surfaces as a finding instead of a silent XLA-chain sweep
        from tclb_tpu.ops import pallas_adjoint
        if model.name.endswith("_adj") \
                and pallas_adjoint.max_chunk(model) >= 1:
            k3 = pallas_adjoint.max_chunk(model)
            plan3 = pallas_adjoint.adjoint_slab_plan(model, shape, k=k3)
            if plan3 is None:
                findings.append(Finding(
                    "resources.adjoint_vmem", "warning", model.name,
                    f"fused 3D backward: no (k, bz) fits the slab "
                    f"scratch budget at {nz}x{ny}x{nx} "
                    f"({model.n_storage} storage planes) — reverse "
                    "sweeps degrade to the XLA chain", where,
                    {"k_max": k3, "shape": list(shape)}))
            else:
                kb, bzb = plan3
                _, rb = pallas_generic.action_plan(model, "Iteration",
                                                   fuse=kb)
                Rb = max(rb, 1)
                estB = 2 * (bzb + 4 * Rb) \
                    * (2 * model.n_storage + 1) * ny * nx * 4
                findings.append(Finding(
                    "resources.adjoint_slab", "info", model.name,
                    f"fused 3D backward kernel: k={kb} bz={bzb} "
                    f"reach={Rb} scratch~{estB >> 20} MiB", where,
                    {"k": kb, "bz": bzb, "reach": Rb,
                     "scratch_bytes": estB}))
    return findings
