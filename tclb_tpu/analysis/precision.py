"""Mixed-precision safety: kernels must accumulate in f32, never in the
storage dtype.

The precision ladder (``Lattice(storage_dtype=...)``) stores
distribution fields narrow (bf16) but contracts that every kernel
widens planes to the compute dtype at the read and narrows only on the
output write — bf16's 8-bit mantissa makes direct accumulation
(moment sums, in-kernel Globals reductions) lose mass at ~1e-2
relative error per few hundred steps, which is exactly the silent
wrong-physics failure the error harness (``tclb_tpu.precision``) exists
to bound.

This check makes the contract static: in every engine module that
*declares* narrowed-storage support (a module-level ``STORAGE_DTYPES``
tuple containing ``bfloat16``), kernel functions may not feed a raw
(un-``astype``-ed) read of a field buffer into a reduction or an
additive accumulation.  Aux/flag buffers are exempt — they are
allocated in the compute dtype regardless of the storage knob.
"""

from __future__ import annotations

import ast
import os

from tclb_tpu.analysis.findings import Finding

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

# buffer/ref names that carry STORAGE-dtype field planes inside the
# narrowed-capable kernels (aux stacks — bufa/scra/aux_ref — are
# compute-dtype by construction and deliberately absent)
_FIELD_REFS = frozenset({
    "buff", "buf", "ring", "scrf", "f_ref", "f_hbm", "src", "out_ref",
})

_REDUCTIONS = frozenset({
    "sum", "mean", "prod", "cumsum", "dot", "matmul", "tensordot",
})

#: the sanctioned widen/narrow seams (core/shift.py helpers): routing a
#: storage-dtype read through one of these yields a compute-dtype value
#: (clean for the taint pass) AND applies the representation's DDF
#: shift; a bare ``.astype`` also widens the dtype but silently drops
#: the shift — which is what ``precision.unshifted_cast`` flags
_SHIFT_HELPERS = frozenset({
    "widen_plane", "narrow_plane", "widen_group",
    "widen_stack", "narrow_stack",
})

_CLEANERS = frozenset({"astype"}) | _SHIFT_HELPERS


def _declares_narrow_storage(tree) -> bool:
    """Module-level ``STORAGE_DTYPES = (..., jnp.bfloat16, ...)``."""
    for node in ast.iter_child_nodes(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "STORAGE_DTYPES"
                   for t in node.targets):
            continue
        for n in ast.walk(node.value):
            if isinstance(n, ast.Attribute) and n.attr == "bfloat16":
                return True
            if isinstance(n, ast.Constant) and n.value == "bfloat16":
                return True
    return False


def _base_name(expr):
    """The root ``Name`` under a chain of subscripts; ``None`` through
    attribute access (``buff.at[...]`` is a DMA ref handle, not a value
    read)."""
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _expr_tainted(expr, tainted: set) -> bool:
    """Whether evaluating ``expr`` reads a storage-dtype value: a raw
    subscript of a field ref, or a name taint already flowed into.
    ``.astype(...)`` and the shared shift helpers widen — their whole
    subtree is clean."""
    if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute) \
            and expr.func.attr in _CLEANERS:
        return False
    if isinstance(expr, ast.Subscript) \
            and isinstance(expr.ctx, ast.Load) \
            and _base_name(expr) in _FIELD_REFS:
        return True
    if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load) \
            and expr.id in tainted:
        return True
    for child in ast.iter_child_nodes(expr):
        if _expr_tainted(child, tainted):
            return True
    return False


def _target_names(target) -> list:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        return [x for e in target.elts for x in _target_names(e)]
    if isinstance(target, (ast.Subscript, ast.Starred)):
        return _target_names(target.value)
    return []


def scan_unsafe_accum(paths=None) -> list:
    """Storage-dtype accumulation in narrowed-capable kernel code.

    For each ``kernel*`` function in a ``STORAGE_DTYPES``-declaring ops
    module, a forward taint pass marks names bound from raw field-buffer
    reads (no ``.astype``); any reduction call (``jnp.sum``, ``.sum()``,
    dot products) or additive accumulation (``x += tainted``,
    ``x = x + tainted``) over tainted values is an error finding."""
    if paths is None:
        paths = sorted(
            os.path.join(_PKG_ROOT, "ops", f)
            for f in os.listdir(os.path.join(_PKG_ROOT, "ops"))
            if f.endswith(".py"))
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError) as e:
            findings.append(Finding(
                "precision.unparseable", "error", "",
                f"cannot parse {path}: {e}", path))
            continue
        if not _declares_narrow_storage(tree):
            continue
        rel = os.path.relpath(path, _REPO_ROOT)
        kernels = [n for n in ast.walk(tree)
                   if isinstance(n, ast.FunctionDef) and "kernel" in n.name]
        seen: set = set()   # one finding per source line, even when a
        #                     kernel nests inside a kernel-named factory
        for fn in kernels:
            findings += _scan_kernel(fn, rel, seen)
    return findings


def _scan_kernel(fn, rel: str, seen: set) -> list:
    findings = []
    tainted: set = set()

    def flag(lineno: int, what: str) -> None:
        key = (rel, lineno)
        if key in seen:
            return
        seen.add(key)
        findings.append(Finding(
            "precision.unsafe_accum", "error", "",
            f"{rel}:{lineno} {fn.name}: {what} over a storage-dtype "
            "field read — widen with .astype(<compute dtype>) at the "
            "read so narrowed (bf16) storage never accumulates in "
            "8 mantissa bits", f"{rel}:{lineno}"))

    def check_expr(expr) -> None:
        """Reductions anywhere inside ``expr``."""
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            f = n.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name not in _REDUCTIONS:
                continue
            operands = list(n.args)
            # method form (``x.sum()``): the receiver is the operand
            if isinstance(f, ast.Attribute):
                operands.append(f.value)
            if any(_expr_tainted(a, tainted) for a in operands):
                flag(n.lineno, f"reduction {name}(...)")

    def ordered_stmts(node):
        """Statements in source order, recursing into nested bodies
        (taint must flow forward; ``ast.walk`` is breadth-first)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                yield child
            yield from ordered_stmts(child)

    for stmt in ordered_stmts(fn):
        if isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.op, (ast.Add, ast.Sub)) \
                    and _expr_tainted(stmt.value, tainted):
                flag(stmt.lineno, "additive accumulation (augmented)")
            check_expr(stmt.value)
            if _expr_tainted(stmt.value, tainted):
                tainted.update(_target_names(stmt.target))
        elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            if stmt.value is None:
                continue
            check_expr(stmt.value)
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            names = [x for t in targets for x in _target_names(t)]
            # self-accumulation: x = x + <tainted>
            if isinstance(stmt.value, ast.BinOp) \
                    and isinstance(stmt.value.op, (ast.Add, ast.Sub)) \
                    and _expr_tainted(stmt.value, tainted) \
                    and any(isinstance(n, ast.Name) and n.id in names
                            for n in ast.walk(stmt.value)):
                flag(stmt.lineno, "additive accumulation")
            hot = _expr_tainted(stmt.value, tainted)
            for t in targets:
                strong = isinstance(t, (ast.Name, ast.Tuple, ast.List))
                for name in _target_names(t):
                    if hot:
                        tainted.add(name)
                    elif strong:
                        tainted.discard(name)
        elif isinstance(stmt, (ast.Expr, ast.Return)):
            if stmt.value is not None:
                check_expr(stmt.value)
    return findings


# --------------------------------------------------------------------------- #
# unshifted_cast: every narrow/widen cast of distribution fields must go
# through the shared shift helpers (core/shift.py)
# --------------------------------------------------------------------------- #

#: write targets that hold STORAGE-dtype field planes (the narrow seam);
#: superset of :data:`_FIELD_REFS` — the resident engine's ping-pong
#: passes its output stack as a ``dst`` parameter
_SEAM_REFS = _FIELD_REFS | frozenset({"dst"})


def scan_unshifted_cast(paths=None) -> list:
    """Field-plane casts bypassing the shared DDF-shift helpers.

    The shifted storage representation (``storage_repr="shifted"``,
    ``core/shift.py``) lives entirely in the widen/narrow seams: a
    kernel that casts a distribution plane with a bare ``.astype``
    instead of ``widen_plane``/``narrow_plane`` (or the stack variants)
    silently reads the *deviation* ``f_i - w_i`` as if it were ``f_i``
    — wrong physics with no crash.  In every ``STORAGE_DTYPES``-
    declaring ops module, each ``kernel*`` function is checked for:

    * a ``.astype(...)`` whose receiver derives from a raw field-buffer
      read (widen seam bypass), and
    * a ``.astype(...)`` anywhere in a value stored into a field-buffer
      subscript (narrow seam bypass — the cast target is the storage
      stack even when the value itself is a clean compute-dtype name).
    """
    if paths is None:
        paths = sorted(
            os.path.join(_PKG_ROOT, "ops", f)
            for f in os.listdir(os.path.join(_PKG_ROOT, "ops"))
            if f.endswith(".py"))
    findings = []
    for path in paths:
        try:
            with open(path) as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError):
            continue   # unsafe_accum already reports unparseable files
        if not _declares_narrow_storage(tree):
            continue
        rel = os.path.relpath(path, _REPO_ROOT)
        seen: set = set()
        for fn in ast.walk(tree):
            if isinstance(fn, ast.FunctionDef) and "kernel" in fn.name:
                findings += _scan_casts(fn, rel, seen)
    return findings


def _scan_casts(fn, rel: str, seen: set) -> list:
    findings = []
    tainted: set = set()

    def flag(lineno: int, what: str) -> None:
        key = (rel, lineno)
        if key in seen:
            return
        seen.add(key)
        findings.append(Finding(
            "precision.unshifted_cast", "error", "",
            f"{rel}:{lineno} {fn.name}: {what} bypasses the shared "
            "shift helpers — route field-plane casts through "
            "core.shift.widen_plane/narrow_plane (or the stack "
            "variants) so the shifted storage representation is "
            "restored/removed at every seam", f"{rel}:{lineno}"))

    def has_astype(expr) -> bool:
        return any(isinstance(n, ast.Call)
                   and isinstance(n.func, ast.Attribute)
                   and n.func.attr == "astype"
                   for n in ast.walk(expr))

    def check_widen(expr) -> None:
        """``<field-derived>.astype(...)`` anywhere inside ``expr``."""
        for n in ast.walk(expr):
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "astype" \
                    and _expr_tainted(n.func.value, tainted):
                flag(n.lineno, "a bare .astype over a field-buffer read")

    def ordered_stmts(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                yield child
            yield from ordered_stmts(child)

    for stmt in ordered_stmts(fn):
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        value = getattr(stmt, "value", None)
        if value is None or not isinstance(
                stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                       ast.Expr, ast.Return)):
            continue
        check_widen(value)
        for t in targets:
            if isinstance(t, ast.Subscript) \
                    and _base_name(t) in _SEAM_REFS \
                    and has_astype(value):
                flag(stmt.lineno,
                     "a bare .astype in a field-buffer store")
        # the same forward taint flow as the accumulation scan, so a
        # name bound from a raw field read stays flagged downstream
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            hot = _expr_tainted(value, tainted)
            for t in targets:
                strong = isinstance(t, (ast.Name, ast.Tuple, ast.List))
                for name in _target_names(t):
                    if hot:
                        tainted.add(name)
                    elif strong:
                        tainted.discard(name)
    return findings
