import sys

from tclb_tpu.analysis.cli import main

sys.exit(main())
