"""Static analysis of registered models and kernel engines.

The reference's R-template codegen is also its validator: a malformed
velocity set, a stencil wider than the generated margins, or an
impossible kernel configuration dies at template-expansion time.  This
port traces instead of generating, so those defects used to surface as
cryptic Pallas lowering errors (or silent wrong physics) deep inside
``engine='auto'``.  This package is the replacement gate:

* :func:`analyze_model` — run all checks on one model, returning
  severity-ranked :class:`Finding`s (invariants, stencil footprint,
  kernel resources, hygiene);
* :func:`analyze_repo` — repo-level checks (dead engine entry points,
  ``id()``-keyed caches);
* :func:`kernel_safety_ok` — the verdict the engine dispatch consults:
  no error-severity footprint findings (an undeclared banded-axis read
  would make the band kernels silently compute wrong physics);
* CLI: ``python -m tclb_tpu.analysis [--all | MODEL ...]
  [--format text|json]`` — exits nonzero on any error finding.

Check modules import the kernel engines lazily, so ``tclb_tpu.ops``
modules can import :mod:`tclb_tpu.analysis.fingerprint` (and
``analysis.resources`` inside functions) without a cycle.
"""

from __future__ import annotations

from tclb_tpu.analysis.findings import (Finding, SEVERITIES,  # noqa: F401
                                        sort_findings, worst_severity)
from tclb_tpu.analysis.fingerprint import (  # noqa: F401
    structural_fingerprint)

_safety_cache: dict = {}


def _as_model(model_or_name):
    if isinstance(model_or_name, str):
        from tclb_tpu.models import get_model
        return get_model(model_or_name)
    return model_or_name


def analyze_model(model_or_name, shape=None) -> list:
    """All per-model checks; returns findings sorted most-severe first."""
    from tclb_tpu.analysis import footprint, hygiene, invariants, resources
    model = _as_model(model_or_name)
    findings = []
    for check in (invariants.check_invariants, footprint.check_footprint,
                  resources.check_resources, hygiene.check_model_hygiene):
        try:
            findings += check(model, shape)
        except Exception as e:  # noqa: BLE001 — a crashed check is a finding
            findings.append(Finding(
                "analysis.check_crashed", "error", model.name,
                f"{check.__module__.rsplit('.', 1)[-1]} crashed: "
                f"{type(e).__name__}: {str(e)[:200]}"))
    return sort_findings(findings)


def analyze_repo() -> list:
    """Repo-level checks (model-independent)."""
    from tclb_tpu.analysis import hygiene
    return sort_findings(hygiene.check_repo())


def analyze_all(shape=None) -> dict:
    """``{model_name: findings}`` over every registered model, plus
    repo-level findings under the empty key."""
    from tclb_tpu.models import list_models
    out = {"": analyze_repo()}
    for name in list_models():
        out[name] = analyze_model(name, shape)
    return out


def kernel_safety_ok(model) -> bool:
    """Whether the Pallas engines may run this model: no error-severity
    stencil-footprint findings.  Cached on the structural fingerprint —
    the dispatch consults this on every engine build."""
    key = model.fingerprint
    if key not in _safety_cache:
        from tclb_tpu.analysis.footprint import kernel_safety_errors
        try:
            errors = kernel_safety_errors(model)
        except Exception:  # noqa: BLE001 — analyzer failure must not
            errors = []    # take the engines down; probes still gate
        if errors:
            from tclb_tpu.utils import log
            for f in errors:
                log.warning(f"analysis: {model.name}: {f.message}")
        _safety_cache[key] = not errors
    return _safety_cache[key]
