"""``python -m tclb_tpu.analysis``: the static gate as a command.

Exit status: 0 = no error-severity findings, 1 = errors found,
2 = usage error.  ``--format json`` emits one machine-readable document
(schema: ``{"models": {name: [finding...]}, "repo": [finding...],
"summary": {...}}``) — what CI gates on.
"""

from __future__ import annotations

import argparse
import json
import sys


def _parse_shape(text):
    try:
        shape = tuple(int(v) for v in text.replace("x", ",").split(","))
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bad shape {text!r}: expected NY,NX or NZ,NY,NX")
    if len(shape) not in (2, 3):
        raise argparse.ArgumentTypeError(
            f"bad shape {text!r}: expected 2 or 3 dims")
    return shape


def main(argv=None) -> int:
    from tclb_tpu import analysis
    from tclb_tpu.models import list_models

    p = argparse.ArgumentParser(
        prog="python -m tclb_tpu.analysis",
        description="Static analyzer: velocity-set invariants, stencil "
                    "footprints vs halo, kernel VMEM budgets, registry "
                    "hygiene.")
    p.add_argument("models", nargs="*", metavar="MODEL",
                   help="model names to analyze (see --all)")
    p.add_argument("--all", action="store_true",
                   help="analyze every registered model + repo checks")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--shape", type=_parse_shape, default=None,
                   metavar="NY,NX",
                   help="lattice shape for the resource checks "
                        "(default: a production-scale shape per ndim)")
    p.add_argument("--min-severity", choices=("error", "warning", "info"),
                   default="info",
                   help="hide findings below this severity in the output "
                        "(the exit code always reflects errors)")
    args = p.parse_args(argv)

    if not args.all and not args.models:
        p.print_usage(sys.stderr)
        print("error: give model names or --all", file=sys.stderr)
        return 2
    known = set(list_models())
    unknown = [m for m in args.models if m not in known]
    if unknown:
        print(f"error: unknown models {unknown}; known: "
              f"{sorted(known)}", file=sys.stderr)
        return 2

    names = sorted(known) if args.all else args.models
    per_model = {n: analysis.analyze_model(n, args.shape) for n in names}
    repo = analysis.analyze_repo() if args.all else []

    everything = repo + [f for fs in per_model.values() for f in fs]
    n_err = sum(f.severity == "error" for f in everything)
    n_warn = sum(f.severity == "warning" for f in everything)
    n_info = sum(f.severity == "info" for f in everything)

    max_rank = {"error": 0, "warning": 1, "info": 2}[args.min_severity]

    if args.format == "json":
        doc = {
            "models": {n: [f.to_dict() for f in fs if f.rank <= max_rank]
                       for n, fs in per_model.items()},
            "repo": [f.to_dict() for f in repo if f.rank <= max_rank],
            "summary": {"models": len(names), "errors": n_err,
                        "warnings": n_warn, "info": n_info},
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        def show(fs, head):
            fs = [f for f in fs if f.rank <= max_rank]
            if not fs:
                return
            print(head)
            for f in fs:
                loc = f" [{f.where}]" if f.where else ""
                print(f"  {f.severity.upper():7s} {f.check}{loc}: "
                      f"{f.message}")
        show(repo, "repo:")
        for n in names:
            show(per_model[n], f"{n}:")
        print(f"{len(names)} models: {n_err} errors, {n_warn} warnings, "
              f"{n_info} info")
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
