"""``python -m tclb_tpu.analysis``: the static gate as a command.

Exit status: 0 = no error-severity findings, 1 = errors found,
2 = usage error.  ``--format json`` emits one machine-readable document
(schema: ``{"models": {name: [finding...]}, "repo": [finding...],
"summary": {...}}``) — what CI gates on.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys


def _changed_files() -> set:
    """Repo-relative paths changed vs HEAD (staged + unstaged), for
    ``--changed`` pre-commit filtering.  Empty set when git is absent
    or this is not a work tree (the filter then hides everything, which
    a pre-commit hook on a pristine tree should)."""
    from tclb_tpu.analysis.hygiene import _REPO_ROOT
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=_REPO_ROOT, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return set()
    if out.returncode != 0:
        return set()
    return {line.strip() for line in out.stdout.splitlines()
            if line.strip()}


def _check_selected(check: str, wanted) -> bool:
    """True when ``check`` matches a ``--check`` entry — exact id, or a
    family prefix (``concurrency`` selects every ``concurrency.*``)."""
    for w in wanted:
        if check == w or check.startswith(w + "."):
            return True
    return False


def _parse_shape(text):
    try:
        shape = tuple(int(v) for v in text.replace("x", ",").split(","))
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"bad shape {text!r}: expected NY,NX or NZ,NY,NX")
    if len(shape) not in (2, 3):
        raise argparse.ArgumentTypeError(
            f"bad shape {text!r}: expected 2 or 3 dims")
    return shape


def main(argv=None) -> int:
    from tclb_tpu import analysis
    from tclb_tpu.models import list_models

    p = argparse.ArgumentParser(
        prog="python -m tclb_tpu.analysis",
        description="Static analyzer: velocity-set invariants, stencil "
                    "footprints vs halo, kernel VMEM budgets, registry "
                    "hygiene.")
    p.add_argument("models", nargs="*", metavar="MODEL",
                   help="model names to analyze (see --all)")
    p.add_argument("--all", action="store_true",
                   help="analyze every registered model + repo checks")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--shape", type=_parse_shape, default=None,
                   metavar="NY,NX",
                   help="lattice shape for the resource checks "
                        "(default: a production-scale shape per ndim)")
    p.add_argument("--min-severity", choices=("error", "warning", "info"),
                   default="info",
                   help="hide findings below this severity in the output "
                        "(the exit code always reflects errors)")
    p.add_argument("--check", default=None, metavar="NAME[,NAME...]",
                   help="only run/report these checks — exact ids "
                        "(concurrency.lock_order_cycle) or families "
                        "(concurrency); exit code reflects the filtered "
                        "set")
    p.add_argument("--changed", action="store_true",
                   help="only report findings located in files changed "
                        "vs HEAD (fast pre-commit mode; model-level "
                        "findings without a file locator are hidden)")
    args = p.parse_args(argv)

    wanted = None
    if args.check:
        wanted = [w.strip() for w in args.check.split(",") if w.strip()]
        if not wanted:
            print("error: --check needs at least one name",
                  file=sys.stderr)
            return 2

    # --check/--changed alone mean "run the repo gate" (pre-commit use)
    if not args.all and not args.models and not (wanted or args.changed):
        p.print_usage(sys.stderr)
        print("error: give model names or --all", file=sys.stderr)
        return 2
    known = set(list_models())
    unknown = [m for m in args.models if m not in known]
    if unknown:
        print(f"error: unknown models {unknown}; known: "
              f"{sorted(known)}", file=sys.stderr)
        return 2

    names = sorted(known) if args.all else args.models
    # a pure-concurrency --check never produces model findings: skip the
    # per-model analysis entirely (this is the fast pre-commit path)
    if wanted is not None and all(
            w == "concurrency" or w.startswith("concurrency.")
            for w in wanted):
        names = []
    per_model = {n: analysis.analyze_model(n, args.shape) for n in names}
    run_repo = args.all or (not args.models and (wanted is not None
                                                 or args.changed))
    repo = analysis.analyze_repo() if run_repo else []

    if wanted is not None:
        per_model = {n: [f for f in fs if _check_selected(f.check, wanted)]
                     for n, fs in per_model.items()}
        repo = [f for f in repo if _check_selected(f.check, wanted)]
    if args.changed:
        changed = _changed_files()

        def in_changed(f):
            path = f.where.split(":")[0].replace(os.sep, "/")
            return path in changed

        per_model = {n: [f for f in fs if in_changed(f)]
                     for n, fs in per_model.items()}
        repo = [f for f in repo if in_changed(f)]

    everything = repo + [f for fs in per_model.values() for f in fs]
    n_err = sum(f.severity == "error" for f in everything)
    n_warn = sum(f.severity == "warning" for f in everything)
    n_info = sum(f.severity == "info" for f in everything)

    max_rank = {"error": 0, "warning": 1, "info": 2}[args.min_severity]

    if args.format == "json":
        doc = {
            "models": {n: [f.to_dict() for f in fs if f.rank <= max_rank]
                       for n, fs in per_model.items()},
            "repo": [f.to_dict() for f in repo if f.rank <= max_rank],
            "summary": {"models": len(names), "errors": n_err,
                        "warnings": n_warn, "info": n_info},
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        def show(fs, head):
            fs = [f for f in fs if f.rank <= max_rank]
            if not fs:
                return
            print(head)
            for f in fs:
                loc = f" [{f.where}]" if f.where else ""
                print(f"  {f.severity.upper():7s} {f.check}{loc}: "
                      f"{f.message}")
        show(repo, "repo:")
        for n in names:
            show(per_model[n], f"{n}:")
        print(f"{len(names)} models: {n_err} errors, {n_warn} warnings, "
              f"{n_info} info")
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
