"""Stencil footprint vs halo: abstract trace of every stage's reads.

The band kernels (ops/pallas_generic) size their windows from DECLARED
metadata only — streaming vectors and ``Field.d*_range`` — via
``_stage_reach``/``action_plan``.  A stage that ``ctx.load``s beyond its
declaration therefore reads rows outside the valid band window: the
slice stays in-bounds of the buffer, so nothing crashes — the kernel
silently computes on stale halo rows (exactly the class of bug the
reference's generated margins make impossible).  This check traces each
stage function abstractly (``jax.eval_shape`` against a recording
:class:`~tclb_tpu.ops.pallas_generic.KernelCtx`) and compares every
recorded ``(dx, dy, dz)`` against the declaration.

On top of the per-stage trace it verifies the plan-level budgets:

* forward band engine: total fuse=1 reach within the 8-row DMA halo;
* adjoint band kernel: the R-extended backward window needs
  ``2*R <= halo`` — beyond that, the cotangent cone of one band reaches
  rows another band also seeds, and the masked-window arithmetic that
  prevents cross-band double-counting of cotangents no longer holds.
"""

from __future__ import annotations

from tclb_tpu.analysis.findings import Finding
from tclb_tpu.core.registry import Model

# severity of an undeclared read depends on the axis: the banded axis
# (y in 2D, z in 3D) is windowed — reads beyond the declaration hit
# stale rows; the other axes wrap whole rows/planes exactly, so an
# undeclared offset there is only a metadata smell.
_BANDED_AXIS = {2: "dy", 3: "dz"}


def trace_stage_reads(model: Model, action: str) -> dict:
    """``{stage_name: set[(storage_index, dx, dy, dz)]}`` of every
    ``ctx.load`` each stage performs, recorded during an abstract trace
    (no FLOPs run).  Raises on untraceable stages — callers wrap."""
    import jax
    import jax.numpy as jnp

    from tclb_tpu.ops.pallas_generic import KernelCtx

    pshape = (8, 16) if model.ndim == 2 else (4, 8, 16)
    dtype = jnp.float32
    zonal = list(model.zonal_settings)
    out: dict = {}
    for sname in model.actions[action]:
        stage = model.stages[sname]
        fn = model.stage_fns.get(stage.main)
        if fn is None:
            raise ValueError(f"stage {sname!r}: no bound function "
                             f"{stage.main!r}")
        recs: set = set()

        def run(stack, flags, sett, zstack, _fn=fn, _recs=recs):
            planes = [stack[i] for i in range(model.n_storage)]

            def loader(index, dx=0, dy=0, dz=0):
                _recs.add((int(index), int(dx), int(dy), int(dz)))
                return stack[index]

            ctx = KernelCtx(
                model, planes, loader, flags,
                {nm: zstack[j] for j, nm in enumerate(zonal)},
                sett, dtype, 0, None, compute_globals=True)
            return _fn(ctx)

        jax.eval_shape(
            run,
            jax.ShapeDtypeStruct((model.n_storage,) + pshape, dtype),
            jax.ShapeDtypeStruct(pshape, jnp.int32),
            jax.ShapeDtypeStruct((len(model.settings),), dtype),
            jax.ShapeDtypeStruct((max(len(zonal), 1),) + pshape, dtype))
        out[sname] = recs
    return out


def _declared_ranges(model: Model, index: int):
    """Declared per-axis (lo, hi) load ranges of a storage plane:
    a Field's registered stencil; densities have no declared ``load``
    stencil (streaming is separate and always declared)."""
    n_dens = len(model.densities)
    if index >= n_dens:
        f = model.fields[index - n_dens]
        return {"dx": f.dx_range, "dy": f.dy_range, "dz": f.dz_range}
    return {"dx": (0, 0), "dy": (0, 0), "dz": (0, 0)}


def check_footprint(model: Model, shape=None) -> list:
    findings: list = []
    from tclb_tpu.ops import pallas_generic

    for action in sorted(model.actions):
        try:
            reads = trace_stage_reads(model, action)
        except Exception as e:  # noqa: BLE001 — untraceable stage
            findings.append(Finding(
                "footprint.trace_failed", "info", model.name,
                f"action {action!r} not traceable in a kernel context "
                f"({type(e).__name__}: {str(e)[:120]}) — the band-engine "
                "capability probe rejects it for the same reason",
                f"action:{action}"))
            continue
        banded = _BANDED_AXIS[model.ndim]
        for sname, recs in reads.items():
            for index, dx, dy, dz in sorted(recs):
                decl = _declared_ranges(model, index)
                offs = {"dx": dx, "dy": dy, "dz": dz}
                plane = model.storage_names[index]
                for axis, off in offs.items():
                    lo, hi = decl[axis]
                    if lo <= off <= hi:
                        continue
                    if axis == banded:
                        findings.append(Finding(
                            "footprint.undeclared_read", "error",
                            model.name,
                            f"stage {sname!r} loads {plane!r} at "
                            f"{axis}={off}, outside its declared range "
                            f"[{lo}, {hi}]: the band kernels size their "
                            f"{banded} windows from the declaration and "
                            "would silently read stale halo rows",
                            f"action:{action}/stage:{sname}/"
                            f"plane:{plane}",
                            {"axis": axis, "offset": off,
                             "declared": [lo, hi]}))
                    else:
                        findings.append(Finding(
                            "footprint.undeclared_read_wrapped", "warning",
                            model.name,
                            f"stage {sname!r} loads {plane!r} at "
                            f"{axis}={off}, outside its declared range "
                            f"[{lo}, {hi}] (axis wraps exactly in-kernel, "
                            "but the declaration understates the stencil)",
                            f"action:{action}/stage:{sname}/"
                            f"plane:{plane}",
                            {"axis": axis, "offset": off,
                             "declared": [lo, hi]}))

    # -- plan-level halo budgets (the Iteration action is what the band
    #    engines fuse) ---------------------------------------------------- #
    if "Iteration" in model.actions:
        try:
            _, reach = pallas_generic.action_plan(model, "Iteration",
                                                  fuse=1)
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                "footprint.plan_failed", "warning", model.name,
                f"action_plan failed: {type(e).__name__}: "
                f"{str(e)[:120]}"))
            return findings
        halo = pallas_generic.HALO
        if reach > halo:
            findings.append(Finding(
                "footprint.halo", "warning", model.name,
                f"Iteration stencil reach {reach} exceeds the {halo}-row "
                "DMA halo: band engines ineligible (XLA path still "
                "correct)", "action:Iteration",
                {"reach": reach, "halo": halo}))
        if model.ndim == 2:
            R = max(reach, 1)
            if 2 * R > halo:
                findings.append(Finding(
                    "footprint.adjoint_band", "warning", model.name,
                    f"adjoint R-extended band needs 2*R = {2 * R} halo "
                    f"rows (> {halo}): one band's cotangent cone would "
                    "alias rows a neighboring band also seeds, "
                    "double-counting cotangents — fused Pallas adjoint "
                    "ineligible", "action:Iteration",
                    {"R": R, "halo": halo}))
            else:
                from tclb_tpu.ops import pallas_adjoint
                k = pallas_adjoint.max_chunk(model)
                findings.append(Finding(
                    "footprint.adjoint_chunk", "info", model.name,
                    f"adjoint chunk budget: max_chunk={k} "
                    f"(fuse-1 reach {reach})", "action:Iteration",
                    {"max_chunk": k, "reach": reach}))
        # -- K-step fused halos ------------------------------------------ #
        # The fused engines DMA K reach-slabs of halo per side and let
        # each of the K steps consume one: a stencil wider than one
        # reach-unit per step outgrows the halo silently (the slices
        # stay in-bounds — the kernel just computes on stale rows).
        if model.ndim == 2:
            fz = pallas_generic.choose_fuse(model)
            if fz >= 2:
                try:
                    _, rf = pallas_generic.action_plan(
                        model, "Iteration", fuse=fz)
                except Exception:  # noqa: BLE001
                    rf = None
                if rf is not None and rf > halo:
                    findings.append(Finding(
                        "footprint.fusion_halo", "error", model.name,
                        f"planner picked fuse={fz} but the fused plan's "
                        f"reach {rf} exceeds the {halo}-row DMA halo: "
                        "the band kernel would compute on stale halo "
                        "rows", "action:Iteration",
                        {"fuse": fz, "reach": rf, "halo": halo}))
        else:
            from tclb_tpu.ops import pallas_d3q
            if model.name in pallas_d3q._SUPPORTED:
                # the tuned z-slab kernel widens its halo by exactly ONE
                # slab per fused step: structural eligibility (the name
                # allowlist) must imply per-step z-reach <= 1 from the
                # declarations (streaming vectors + field dz stencils)
                zr = max((abs(int(e[2])) for e in model.ei), default=0)
                for f in model.fields:
                    lo, hi = f.dz_range
                    zr = max(zr, abs(int(lo)), abs(int(hi)))
                if zr > 1:
                    findings.append(Finding(
                        "footprint.fusion_halo", "error", model.name,
                        f"model is name-eligible for the tuned d3q "
                        f"kernel but declares per-step z-reach {zr} > 1:"
                        " the fused kernel's K-slab halo covers exactly "
                        "one reach-slab per fused step — wider stencils "
                        "read stale halo slabs", "action:Iteration",
                        {"z_reach": zr}))
            # -- 3D adjoint band (the fused Run_b slab kernel) ----------- #
            # The backward band DMAs 2*R halo slabs per side — the
            # adjoint-band rule extended to z-slabs: the in-band chain
            # recomputes the forward cone AND transposes it, each
            # costing one reach.  The modular halo DMA chain caps at
            # fusion.ADJ_HALO_MAX slabs per side; a chain reach beyond
            # it means the Run_b slab halo is NARROWER than the adjoint
            # reach — one band's cotangent cone would alias slabs a
            # neighbor band also seeds, double-counting cotangents.
            from tclb_tpu.ops import fusion, pallas_adjoint
            R1 = max(reach, 1)
            is_adj = model.name.endswith("_adj")
            if 2 * R1 > fusion.ADJ_HALO_MAX:
                findings.append(Finding(
                    "footprint.adjoint_band",
                    "error" if is_adj else "warning", model.name,
                    f"3D adjoint band needs 2*R = {2 * R1} halo slabs "
                    f"per side but the Run_b slab kernel DMAs at most "
                    f"{fusion.ADJ_HALO_MAX}: the slab halo is narrower "
                    "than the adjoint reach"
                    + (" — fused 3D backward ineligible (an _adj model "
                       "silently degrades to the XLA reverse chain)"
                       if is_adj else ""),
                    "action:Iteration",
                    {"R": R1, "halo": fusion.ADJ_HALO_MAX}))
            else:
                k = pallas_adjoint.max_chunk(model)
                data = {"max_chunk": k, "reach": reach}
                if shape is not None and len(shape) == 3:
                    plan3 = pallas_adjoint.adjoint_slab_plan(model, shape)
                    if plan3 is None:
                        findings.append(Finding(
                            "footprint.adjoint_band", "warning",
                            model.name,
                            f"no (k, bz) fits the fused 3D backward's "
                            f"VMEM budget at shape {tuple(shape)} — "
                            "reverse sweeps degrade to the XLA chain",
                            "action:Iteration", {"shape": list(shape)}))
                    else:
                        data.update({"k": plan3[0], "bz": plan3[1]})
                findings.append(Finding(
                    "footprint.adjoint_chunk", "info", model.name,
                    f"3D adjoint chunk budget: max_chunk={k} "
                    f"(fuse-1 reach {reach})", "action:Iteration", data))
    return findings


def kernel_safety_errors(model: Model) -> list:
    """Error-severity footprint findings only — what the engine dispatch
    consults before handing a model to the band kernels (an undeclared
    banded-axis read means the kernel computes wrong physics without
    failing)."""
    return [f for f in check_footprint(model) if f.severity == "error"]
