"""Optimization / adjoint XML handlers.

Parity targets (reference src/Handlers.cpp.Rt): ``<Adjoint>`` (acUSAdjoint
:1614 / acSAdjoint :1664), ``<Optimize>`` (acOptimize :1815), ``<FDTest>``
(acFDTest :1944), ``<Threshold>``/``<ThresholdNow>`` (:2100/:2149),
``<OptSolve>`` (acOptSolve :1571), and the design-parameter family
``<InternalTopology>`` (:166), ``<OptimalControl>`` (:201), ``<Fourier>``
(:431), ``<BSpline>`` (:575), ``<RepeatControl>`` (:727).

The reference's imperative structure (NLopt calls back into the handler
tree, workers follow rank 0 via MPI broadcast) becomes declarative: design
handlers register :class:`~tclb_tpu.adjoint.design.Design` objects on the
solver; <Adjoint>/<Optimize> build a differentiable objective over a fixed
horizon and call the adjoint machinery.  There is no worker loop — the mesh
parallelism lives inside the jitted objective itself.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tclb_tpu.adjoint import (BSpline, CompositeDesign, ControlSecond, Fourier,
                              InternalTopology, OptimalControl,
                              RepeatControl, fd_test, make_objective_run,
                              make_steady_gradient, make_unsteady_gradient,
                              optimize, threshold_topology)
from tclb_tpu.control.handlers import Handler, GenericAction, register_handler
from tclb_tpu.utils import log
from tclb_tpu.control.solver import Solver


def _active_design(solver: Solver):
    """All registered designs (or the model's parameter fields if none was
    declared — the reference errors instead; defaulting is kinder)."""
    if solver.designs:
        if len(solver.designs) == 1:
            return solver.designs[0]
        return CompositeDesign(solver.designs)
    return InternalTopology(solver.model)


def _design_bounds(design):
    b = design.bounds()
    if isinstance(b, tuple) and len(b) == 2 and not isinstance(b[0], tuple):
        return b
    # composite: use the tightest common box (scipy path needs one box)
    los = [x[0] for x in b if x[0] is not None]
    his = [x[1] for x in b if x[1] is not None]
    return (max(los) if los else None, min(his) if his else None)


class dInternalTopology(Handler):
    """<InternalTopology/>: expose parameter=True fields as design variables
    (reference InternalTopology, src/Handlers.cpp.Rt:166-200)."""

    kind = "design"

    def init(self) -> int:
        super().init()
        self.solver.designs.append(InternalTopology(self.solver.model))
        return 0


def _series_for(solver: Solver, what: str) -> tuple[str, int]:
    par, zone = what, 0
    if "-" in what:
        par, zname = what.split("-", 1)
        zone = solver.geometry.setting_zones[zname]
    if par not in solver.model.setting_index:
        raise ValueError(f"unknown setting {par!r} in design handler")
    return par, zone


class dOptimalControl(Handler):
    """<OptimalControl what="Velocity-inlet" lower="..." upper="...">
    (reference OptimalControl, src/Handlers.cpp.Rt:201-303).  If no series
    exists yet, a constant series over Length= iterations is created."""

    kind = "design"

    def init(self) -> int:
        super().init()
        s = self.solver
        par, zone = _series_for(s, self.node.get("what", ""))
        sidx = s.model.setting_index[par]
        have = any(si == sidx and z == zone
                   for si, z, _ in s.lattice.params.series_map)
        if not have:
            T = int(round(s.units.alt(self.node.get("Length", "0"))))
            if T <= 0:
                raise ValueError("OptimalControl on a setting without a "
                                 "<Control> series needs Length=")
            cur = float(np.asarray(s.lattice.params.zone_table)[sidx, zone])
            s.lattice.set_setting_series(par, np.full(T, cur), zone=zone)
        lo = self.node.get("lower")
        hi = self.node.get("upper")
        self._register(OptimalControl(
            s.model, par, zone,
            lower=s.units.alt(lo) if lo else None,
            upper=s.units.alt(hi) if hi else None))
        return 0

    def _register(self, inner) -> None:
        self.solver.designs.append(inner)


class dFourier(dOptimalControl):
    """<Fourier what=... Modes="K">: truncated-Fourier reparameterization
    (reference Fourier, src/Handlers.cpp.Rt:431-574)."""

    def _register(self, inner) -> None:
        T = self.solver.lattice.params.time_series.shape[1]
        modes = int(self.node.get("Modes", "3"))
        self.solver.designs.append(Fourier(inner, T, modes))


class dBSpline(dOptimalControl):
    """<BSpline what=... Points="P" periodic="true|false">
    (reference BSpline, src/Handlers.cpp.Rt:575-726)."""

    def _register(self, inner) -> None:
        T = self.solver.lattice.params.time_series.shape[1]
        pts = int(self.node.get("Points", "6"))
        periodic = self.node.get("periodic", "false").lower() in ("1", "true")
        self.solver.designs.append(BSpline(inner, T, pts, periodic=periodic))


class dRepeatControl(dOptimalControl):
    """<RepeatControl what=... Period="P"> (reference RepeatControl,
    src/Handlers.cpp.Rt:727-846)."""

    def _register(self, inner) -> None:
        T = self.solver.lattice.params.time_series.shape[1]
        period = int(round(self.solver.units.alt(
            self.node.get("Period", "1"))))
        self.solver.designs.append(RepeatControl(inner, T, period))


class dOptimalControlSecond(dOptimalControl):
    """<OptimalControlSecond what=...>: optimal control at half temporal
    resolution with linear interpolation between the optimized samples
    (reference OptimalControlSecond, src/Handlers.cpp.Rt:304-430)."""

    def _register(self, inner) -> None:
        T = self.solver.lattice.params.time_series.shape[1]
        self.solver.designs.append(ControlSecond(inner, T))


class acAdjoint(GenericAction):
    """<Adjoint type="unsteady|steady" Iterations="N">: children first
    (reference runs the recorded primal there), then gradient of the
    InObj-weighted objective wrt the active design; result stored as
    ``solver.objective``/``solver.gradient`` and the primal state advances
    (reference acUSAdjoint/acSAdjoint, src/Handlers.cpp.Rt:1614-1707)."""

    def init(self) -> int:
        Handler.init(self)
        ret = self.execute_internal()
        if ret not in (0, None):
            return ret
        s = self.solver
        design = _active_design(s)
        kind = self.node.get("type", "unsteady")
        theta = design.get(s.lattice.state, s.lattice.params)
        if kind == "steady":
            n_adj = int(round(s.units.alt(self.node.get("NAdjoint", "100"))))
            grad_fn = make_steady_gradient(s.model, design, n_adjoint=n_adj,
                                           shape=s.lattice.shape,
                                           dtype=s.lattice.dtype)
            obj, g = grad_fn(theta, s.lattice.state, s.lattice.params)
        else:
            niter = int(round(s.units.alt(self.node.get("Iterations", "0"))))
            if niter <= 0:
                raise ValueError("unsteady <Adjoint> needs Iterations=")
            grad_fn = make_unsteady_gradient(s.model, design, niter,
                                             shape=s.lattice.shape,
                                             dtype=s.lattice.dtype,
                                             has_series=s.lattice.params
                                             .time_series is not None)
            s.adjoint_engine = grad_fn.engine_name
            obj, g, final = grad_fn(theta, s.lattice.state, s.lattice.params)
            s.lattice.state = final
            s.iter += niter
        s.objective = float(obj)
        s.gradient = g
        s.design = design
        self.unstack()
        return 0


class acFDTest(GenericAction):
    """<FDTest Iterations="N" Checks="K" Epsilon="eps">: compare the adjoint
    gradient with central differences and store/print the verdict
    (reference acFDTest, src/Handlers.cpp.Rt:1944-2099)."""

    def init(self) -> int:
        Handler.init(self)
        s = self.solver
        design = _active_design(s)
        niter = int(round(s.units.alt(self.node.get("Iterations", "4"))))
        checks = int(self.node.get("Checks", "5"))
        eps = float(self.node.get("Epsilon", "1e-6"))
        theta = design.get(s.lattice.state, s.lattice.params)
        grad_fn = make_unsteady_gradient(s.model, design, niter,
                                         shape=s.lattice.shape,
                                         dtype=s.lattice.dtype,
                                         has_series=s.lattice.params
                                         .time_series is not None)
        s.adjoint_engine = grad_fn.engine_name
        obj, g, _ = grad_fn(theta, s.lattice.state, s.lattice.params)
        run = make_objective_run(s.model, niter)

        def loss(th):
            st, pa = design.put(th, s.lattice.state, s.lattice.params)
            return run(st, pa)[0]

        records = fd_test(loss, g, theta, n_checks=checks, eps=eps)
        s.fd_records = records
        worst = max((r["rel_err"] for r in records
                     if not (r["adjoint"] == 0 and abs(r["fd"]) < 1e-12)),
                    default=0.0)
        log.info(f"FDTest: objective={float(obj):.6g} worst rel err={worst:.3e}")
        for r in records:
            log.info(f"  component {r['index']}: adjoint={r['adjoint']:.8g} "
                  f"fd={r['fd']:.8g} rel_err={r['rel_err']:.3e}")
        return 0


class acThresholdNow(Handler):
    """<ThresholdNow Level="0.5"/>: binarize topology immediately
    (reference acThresholdNow, src/Handlers.cpp.Rt:2149)."""

    def init(self) -> int:
        super().init()
        self.do_threshold()
        return 0

    def do_threshold(self) -> None:
        s = self.solver
        level = float(self.node.get("Level", "0.5"))
        s.lattice.state = threshold_topology(s.model, s.lattice.state, level)


class acThreshold(acThresholdNow):
    """<Threshold Iterations="N">: periodic binarization callback
    (reference acThreshold, src/Handlers.cpp.Rt:2100)."""

    kind = "callback"

    def init(self) -> int:
        Handler.init(self)
        if not self.every_iter:
            self.do_threshold()
        return 0

    def do_it(self) -> int:
        self.do_threshold()
        return 0


class acOptimize(GenericAction):
    """<Optimize Method="MMA" MaxEvaluations="20" Iterations="N" Step="1">
    — outer optimization loop over the registered designs (reference
    acOptimize + GenericOptimizer::Execute, src/Handlers.cpp.Rt:1708-1943).
    Children register designs / configure; the objective is the
    InObj-weighted globals integrated over ``Iterations`` steps from the
    current state."""

    def init(self) -> int:
        Handler.init(self)
        ret = self.execute_internal()
        if ret not in (0, None):
            return ret
        s = self.solver
        design = _active_design(s)
        niter = int(round(s.units.alt(self.node.get("Iterations", "0"))))
        if niter <= 0:
            raise ValueError("<Optimize> needs Iterations= (objective "
                             "horizon per evaluation)")
        method = self.node.get("Method", "MMA")
        max_eval = int(self.node.get("MaxEvaluations", "20"))
        step = float(self.node.get("Step", "1.0"))
        grad_full = make_unsteady_gradient(s.model, design, niter,
                                           shape=s.lattice.shape,
                                           dtype=s.lattice.dtype,
                                           has_series=s.lattice.params
                                           .time_series is not None)
        s.adjoint_engine = grad_full.engine_name

        def grad_fn(theta):
            obj, g, _ = grad_full(theta, s.lattice.state, s.lattice.params)
            return obj, g

        def cb(k, obj, theta):
            s.opt_iter = k
            log.info(f"Optimize[{method}] eval {k}: objective={obj:.8g}")

        theta0 = design.get(s.lattice.state, s.lattice.params)
        # Material="more|less": keep total design material above/below its
        # starting value (reference nlopt_add_inequality_constraint with
        # FMaterialMore/FMaterialLess, src/Handlers.cpp.Rt:1870-1886)
        material = None
        mat = self.node.get("Material")
        if mat is not None:
            if mat not in ("more", "less"):
                raise ValueError('Material attribute in Optimize should '
                                 'be "more" or "less"')
            from jax.flatten_util import ravel_pytree

            def _child_mask(d, th):
                """Per-design material mask: the reference's parameter
                vector holds ONLY design nodes; an InternalTopology theta
                is the full plane, so the constraint counts (and the
                projection moves) design nodes only — other designs'
                entries are all real parameters."""
                a = np.asarray(th)
                if hasattr(d, "_mask"):
                    mm = np.asarray(d._mask(s.lattice.state))
                    return np.broadcast_to(mm[None], a.shape).astype(
                        np.float64).ravel()
                return np.ones(a.size)

            children = design.designs if hasattr(design, "designs") \
                else (design,)
            thetas = theta0 if isinstance(theta0, tuple) else (theta0,)
            mask = np.concatenate([_child_mask(d, th)
                                   for d, th in zip(children, thetas)])
            flat0 = np.asarray(ravel_pytree(theta0)[0], dtype=np.float64)
            assert flat0.size == mask.size
            m0 = float(flat0 @ mask)
            material = (mat, m0, mask)
            log.info(f"Optimize material constraint: {mat} than {m0:.6g}")
        theta, obj = optimize(grad_fn, theta0, method=method,
                              max_eval=max_eval, step=step,
                              bounds=_design_bounds(design), callback=cb,
                              material=material)
        s.lattice.state, s.lattice.params = design.put(
            theta, s.lattice.state, s.lattice.params)
        s.objective = obj
        self.unstack()
        return 0


class acOptSolve(GenericAction):
    """<OptSolve Iterations="N" Chunk="C" Step="a">: simultaneous
    primal+adjoint+descent (reference acOptSolve + ITER_OPT / Iteration_Opt,
    src/Handlers.cpp.Rt:1571-1613, src/cuda.cu.Rt:224-234): every chunk of C
    iterations, take one clamped descent step on the design using the
    gradient over that chunk."""

    def init(self) -> int:
        Handler.init(self)
        ret = self.execute_internal()
        if ret not in (0, None):
            return ret
        s = self.solver
        design = _active_design(s)
        niter = int(round(s.units.alt(self.node.get("Iterations", "0"))))
        chunk = int(round(s.units.alt(self.node.get("Chunk", "1"))))
        step = float(self.node.get("Step", "1.0"))
        if niter <= 0:
            raise ValueError("<OptSolve> needs Iterations=")
        grad_fn = make_unsteady_gradient(s.model, design, chunk,
                                         shape=s.lattice.shape,
                                         dtype=s.lattice.dtype,
                                         has_series=s.lattice.params
                                         .time_series is not None)
        s.adjoint_engine = grad_fn.engine_name
        lo, hi = _design_bounds(design)
        done = 0
        while done < niter:
            theta = design.get(s.lattice.state, s.lattice.params)
            obj, g, final = grad_fn(theta, s.lattice.state, s.lattice.params)
            theta = jnp.clip(
                theta - step * g,
                lo if lo is not None else -np.inf,
                hi if hi is not None else np.inf)
            s.lattice.state, s.lattice.params = design.put(
                theta, final, s.lattice.params)
            done += chunk
            s.iter += chunk
            s.objective = float(obj)
            for h in s.hands:
                if h.now(s.iter):
                    h.do_it()
        self.unstack()
        return 0


register_handler("Adjoint", acAdjoint)
register_handler("FDTest", acFDTest)
register_handler("Threshold", acThreshold)
register_handler("ThresholdNow", acThresholdNow)
register_handler("Optimize", acOptimize)
register_handler("OptSolve", acOptSolve)
register_handler("InternalTopology", dInternalTopology)
register_handler("OptimalControl", dOptimalControl)
register_handler("OptimalControlSecond", dOptimalControlSecond)
register_handler("Fourier", dFourier)
register_handler("BSpline", dBSpline)
register_handler("RepeatControl", dRepeatControl)
