"""Control layer: the XML-driven run orchestration (reference Handlers,
src/Handlers.{h,cpp.Rt}; Solver, src/Solver.{h,cpp}.Rt; main,
src/main.cpp.Rt).  The config file *is* the program."""

from tclb_tpu.control.solver import Solver, run_config, run_config_string

__all__ = ["Solver", "run_config", "run_config_string"]
