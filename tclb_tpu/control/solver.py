"""Solver: process-level orchestration + config entry points.

Parity target: reference ``Solver`` (src/Solver.h.Rt:57-171,
src/Solver.cpp.Rt) and ``main()`` (src/main.cpp.Rt:172-346): read units and
gauge them, size the lattice from the <Geometry> element, run the handler
tree, fan out VTK/TXT/BIN/Log output, keep the iteration counter and the
stacked periodic callbacks.

The reference's per-rank MPI bookkeeping (MPIDivision, node tables) has no
equivalent here: device parallelism is a ``jax.sharding.Mesh`` handed to the
Lattice, and every host-side array is the *global* lattice (JAX global-view
arrays), so output and geometry code is rank-free by construction.
"""

from __future__ import annotations

import os
import time
import xml.etree.ElementTree as ET
from typing import Any, Optional

import numpy as np

from tclb_tpu import telemetry
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.core.registry import Model
from tclb_tpu.utils.geometry import Geometry
from tclb_tpu.utils.units import UnitEnv
from tclb_tpu.utils.vtk import CSVLog

ITERATION_STOP = 1


class Solver:
    """Host orchestration state shared by all handlers."""

    def __init__(self, model: Model, output: str = "output/",
                 mesh: Any = None, dtype: Any = None):
        self.model = model
        self.units = UnitEnv()
        self.output_prefix = output
        self.mesh = mesh
        self.dtype = dtype
        self.lattice: Optional[Lattice] = None
        self.geometry: Optional[Geometry] = None
        self.shape: tuple[int, ...] = ()
        self.iter = 0
        self.iter_type = 0
        self.opt_iter = 0
        self.hands: list = []        # stacked periodic callbacks
        self.designs: list = []      # registered design parameterizations
        self.objective: Optional[float] = None
        self.gradient = None
        self.fd_records: Optional[list] = None
        self.log: Optional[CSVLog] = None
        self.start_walltime = time.time()
        self.conf_name = "run"
        self.stop_flag = False
        self.synthetic_turbulence = None   # set by <SyntheticTurbulence>
        # checkpoint/restart plumbing (tclb_tpu.checkpoint)
        self.resume_from: Optional[str] = None  # --resume target, consumed
        self.solve_stack: list = []    # acSolve handlers currently running
        self._pending_restore: dict = {}   # ck_key -> restored handler state
        self._ck_counts: dict = {}     # class name -> instances seen so far

    def next_ck_key(self, cls_name: str) -> str:
        """Deterministic per-handler checkpoint key: Nth instance of a
        handler class in config order gets ``"<Class>#<N>"``.  Stable
        across runs of the same config, which is what lets a checkpoint's
        per-handler state find its owner on resume."""
        n = self._ck_counts.get(cls_name, 0)
        self._ck_counts[cls_name] = n + 1
        return f"{cls_name}#{n}"

    # -- naming (reference Solver::outIterFile/outGlobalFile) --------------- #

    def out_path(self, name: str, ext: str, with_iter: bool = True) -> str:
        base = self.output_prefix
        if base.endswith("/"):
            os.makedirs(base, exist_ok=True)
            base = os.path.join(base, self.conf_name)
        tag = f"_{name}_{self.iter:08d}" if with_iter else f"_{name}"
        return f"{base}{tag}.{ext}"

    @property
    def is_main(self) -> bool:
        """Rank-0 duty filter for file output under --distributed (the
        reference's InitPrint root filter, src/main.cpp.Rt:186): every
        host runs the identical handler tree, only one writes files."""
        import jax
        return jax.process_index() == 0

    # -- setup --------------------------------------------------------------- #

    def set_size(self, shape: tuple[int, ...]) -> None:
        """Allocate lattice + geometry painter (reference Solver::setSize +
        InitAll, src/Solver.cpp.Rt:265-395)."""
        self.shape = tuple(int(s) for s in shape)
        import jax.numpy as jnp
        self.lattice = Lattice(self.model, self.shape,
                               dtype=self.dtype or jnp.float32,
                               mesh=self.mesh)
        self.geometry = Geometry(self.model, self.shape, self.units)

    def set_unit(self, name: str, value: str, gauge: str = "1") -> None:
        self.units.set_unit(name, self.units.read_text(value),
                            float(self.units.si(gauge)))

    def gauge(self) -> None:
        self.units.make_gauge()

    # -- progress/throughput (reference MainCallback live MLBUps/GB/s,
    #    src/main.cpp.Rt:67-156: reports auto-tuned to ~1/s) -------------- #

    def progress(self, steps: int) -> None:
        """Called by <Solve> after each iterate chunk: prints a live
        MLUPS + effective-GB/s line, throttled to ~1 report/s (the
        reference's desired_fps mechanism)."""
        import jax

        from tclb_tpu.utils import log
        now = time.time()
        if not hasattr(self, "_prog_t0"):
            self._prog_t0, self._prog_iters = now, 0
            return
        self._prog_iters += steps
        dt = now - self._prog_t0
        if dt < 1.0:
            return
        # force execution so the rate is real (jit dispatch is async);
        # only the elapsed chunk is billed
        jax.block_until_ready(self.lattice.state.fields)
        dt = time.time() - self._prog_t0
        nodes = float(np.prod(self.shape))
        mlups = nodes * self._prog_iters / dt / 1e6
        bytes_per = (2 * self.model.n_storage
                     * np.dtype(self.lattice.state.fields.dtype).itemsize
                     + 2)
        log.info(f"iter {self.iter}: {mlups:8.1f} MLUPS "
                 f"({mlups * bytes_per / 1e3:6.1f} GB/s eff) "
                 f"[{self._prog_iters} it in {dt:.2f} s]")
        telemetry.event("progress", iteration=self.iter,
                        mlups=round(mlups, 1),
                        gbps=round(mlups * bytes_per / 1e3, 1))
        self._prog_t0, self._prog_iters = time.time(), 0

    # -- config provenance (reference MainContainer dump with version/
    #    precision/backend, src/Handlers.cpp.Rt:1504-1522) ---------------- #

    def dump_config(self, root) -> None:
        import copy as _copy

        import jax
        import jax.numpy as jnp

        from tclb_tpu import __version__
        annotated = _copy.deepcopy(root)
        annotated.set("solver_version", __version__)
        annotated.set("model_name", self.model.name)
        annotated.set("precision",
                      "double" if (self.dtype or jnp.float32) == jnp.float64
                      else "single")
        annotated.set("backend", jax.default_backend())
        path = self.out_path("config", "xml", with_iter=False)
        ET.ElementTree(annotated).write(path)

    # -- synthetic turbulence (reference ST.Generate per iteration,
    #    src/Lattice.cu.Rt:391-397; segment-wise here — utils/turbulence) -- #

    def update_synthetic_turbulence(self, steps: int) -> None:
        """Advance the SynthT* coupling planes by one handler segment of
        ``steps`` iterations with the variance-exact AR(1) update."""
        st = self.synthetic_turbulence
        m = self.model
        if st is None or st.nmodes == 0 or "SynthT" not in m.groups:
            return
        fluct = st.evaluate(self.shape)
        k_aa = st.ar1_factor(steps)
        k_bb = float(np.sqrt(max(0.0, 1.0 - k_aa * k_aa)))
        lat = self.lattice
        idx = list(m.groups["SynthT"])
        # slice on device first: only the SynthT planes cross to the host
        import jax.numpy as jnp
        old = np.asarray(lat.state.fields[jnp.asarray(idx)])
        lat.set_density_planes(
            {m.storage_names[i]: k_aa * old[c] + k_bb * fluct[c]
             for c, i in enumerate(idx)})

    def log_row(self) -> dict[str, float]:
        m = self.model
        lat = self.lattice
        row: dict[str, float] = {
            "Iteration": float(self.iter),
            # 1 s == units.scale[1] lattice iterations (UnitEnv gauge),
            # so SI time of iteration n is n / scale[1]
            "Time_si": float(self.iter) / float(self.units.scale[1]),
            "Walltime": time.time() - self.start_walltime,
            "OptIteration": float(self.opt_iter),
        }
        svec = np.asarray(lat.params.settings)
        for s in m.settings:
            row[f"{s.name}"] = float(svec[m.setting_index[s.name]])
        if self.geometry:
            table = np.asarray(lat.params.zone_table)
            for s in m.zonal_settings:
                for zname, zid in self.geometry.setting_zones.items():
                    row[f"{s}-{zname}"] = float(table[m.setting_index[s], zid])
        for name, val in lat.get_globals().items():
            row[name] = val
        return row

    def write_log(self) -> None:
        if not self.is_main:
            return
        with telemetry.span("output.log", iteration=self.iter):
            if self.log is None:
                self.log = CSVLog(self.out_path("Log", "csv",
                                                with_iter=False))
            self.log.write(self.log_row())

    # -- output fan-out ------------------------------------------------------ #

    def quantity_arrays(self, what: Optional[set[str]] = None
                        ) -> dict[str, np.ndarray]:
        """Evaluate selected quantities -> host arrays (reference
        vtkWriteLattice quantity loop, src/vtkLattice.cpp.Rt:47-66)."""
        out = {}
        for q in self.model.quantities:
            if q.adjoint:
                continue
            if what and q.name not in what and "all" not in what:
                continue
            out[q.name] = np.asarray(self.lattice.get_quantity(q.name))
        return out

    def write_geometry_vti(self) -> str:
        """Write the painted geometry as VTI: raw flags, one 0/1 layer per
        node-type GROUP, and the settings-zone ids (the reference writes
        the geometry's node-type layers through vtkWriteLattice,
        src/vtkLattice.cpp.Rt:33-46)."""
        from tclb_tpu.utils.vtk import write_vti
        m = self.model
        flags = np.asarray(self.lattice.state.flags)
        arrays = {"Flag": flags}
        for group, mask in m.group_masks.items():
            if group in ("ALL", "SETTINGZONE") or mask == 0:
                continue
            arrays[group] = ((flags & mask) != 0).astype(np.uint8)
        arrays["Zone"] = (flags >> m.zone_shift).astype(np.uint16)
        path = self.out_path("geometry", "vti", with_iter=False)
        write_vti(path, arrays)
        return path

    def write_vtk(self, what: Optional[set[str]] = None,
                  compress: bool = False) -> Optional[str]:
        if not self.is_main:
            return None
        from tclb_tpu.utils.vtk import write_pvti, write_vti
        with telemetry.span("output.vtk", iteration=self.iter):
            arrays = self.quantity_arrays(what)
            flags = np.asarray(self.lattice.state.flags)
            # node-type group layers (reference writes one flag layer per
            # selected group, src/vtkLattice.cpp.Rt:33-46)
            if what is None or "flag" in (what or set()) or not what:
                arrays["Flag"] = flags
            piece = write_vti(self.out_path("VTK", "vti"), arrays,
                              compress=compress)
            write_pvti(self.out_path("VTK", "pvti"), piece, arrays)
        return piece

    def write_txt(self, what: Optional[set[str]] = None,
                  gzip_out: bool = True) -> list[str]:
        """Per-quantity text dumps (reference cbTXT/writeTXT gzip path,
        src/Solver.cpp.Rt:228-260)."""
        import gzip

        from tclb_tpu.checkpoint.writer import atomic_path
        if not self.is_main:
            return []
        paths = []
        with telemetry.span("output.txt", iteration=self.iter):
            for name, arr in self.quantity_arrays(what).items():
                p = self.out_path(f"TXT_{name}",
                                  "txt.gz" if gzip_out else "txt")
                a2 = arr.reshape(-1, arr.shape[-1])
                with atomic_path(p) as tmp:
                    if gzip_out:
                        with gzip.open(tmp, "wt") as f:
                            np.savetxt(f, a2)
                    else:
                        np.savetxt(tmp, a2)
                paths.append(p)
        return paths

    def write_bin(self) -> Optional[str]:
        """Raw binary dump of all storage planes (reference cbBIN,
        src/Handlers.cpp.Rt:1011-1027)."""
        if not self.is_main:
            return None
        p = self.out_path("BIN", "npz")
        with telemetry.span("output.bin", iteration=self.iter):
            self.lattice.save(p)
        return p


# --------------------------------------------------------------------------- #
# Config entry points (reference main(), src/main.cpp.Rt:172-346)
# --------------------------------------------------------------------------- #


def _read_units(root: ET.Element, solver: Solver) -> None:
    """<Units><Params Re="100" gauge="1"/>...</Units> (reference readUnits,
    src/main.cpp.Rt:35-62)."""
    units = root.find("Units")
    if units is None:
        return
    for p in units.findall("Params"):
        gauge = p.get("gauge", "1")
        rest = {k: v for k, v in p.attrib.items() if k != "gauge"}
        if len(rest) != 1:
            raise ValueError(
                f"exactly one variable per Units/Params, got {sorted(rest)}")
        (name, value), = rest.items()
        solver.set_unit(name, value, gauge)
    solver.gauge()


def run_config_string(xml_text: str, model: Model, mesh: Any = None,
                      dtype: Any = None, output: Optional[str] = None,
                      conf_name: str = "run",
                      resume: Optional[str] = None) -> Solver:
    root = ET.fromstring(xml_text)
    return _run_root(root, model, mesh, dtype, output, conf_name,
                     resume=resume)


def run_config(path: str, model: Model, mesh: Any = None,
               dtype: Any = None, output: Optional[str] = None,
               resume: Optional[str] = None) -> Solver:
    root = ET.parse(path).getroot()
    name = os.path.splitext(os.path.basename(path))[0]
    return _run_root(root, model, mesh, dtype, output, name, resume=resume)


def _run_root(root: ET.Element, model: Model, mesh, dtype,
              output: Optional[str], conf_name: str,
              resume: Optional[str] = None) -> Solver:
    from tclb_tpu.control.handlers import MainContainer
    if root.tag != "CLBConfig":
        raise ValueError(f"config root must be <CLBConfig>, got <{root.tag}>")
    solver = Solver(model,
                    output=output or root.get("output", "output/"),
                    mesh=mesh, dtype=dtype)
    solver.conf_name = conf_name
    solver.resume_from = resume
    _read_units(root, solver)
    geom = root.find("Geometry")
    if geom is None:
        raise ValueError("config must contain a <Geometry> element")
    if model.ndim == 2:
        shape = (int(round(solver.units.alt(geom.get("ny", "1")))),
                 int(round(solver.units.alt(geom.get("nx", "1")))))
    else:
        shape = (int(round(solver.units.alt(geom.get("nz", "1")))),
                 int(round(solver.units.alt(geom.get("ny", "1")))),
                 int(round(solver.units.alt(geom.get("nx", "1")))))
    solver.set_size(shape)
    MainContainer(root, solver).init()
    if solver.resume_from is not None:
        from tclb_tpu.utils import log
        log.warning("--resume was given but the config has no "
                    "<SaveCheckpoint> handler — nothing was restored")
    return solver
