"""Cartesian parameter-sweep expansion from an example config XML.

``python -m tclb_tpu sweep case.xml --param "nu=0.01:0.05:8"`` takes an
ordinary run config as the *base case* — its Units, Geometry painting
and <Model><Params> become the shared setup — and expands the --param
grids into ensemble cases for the serve subsystem.  Only the setup
subtree is executed; the action handlers (<Solve>, outputs,
checkpoints) are NOT run — <Solve Iterations> is read as the default
iteration count.

Param specs (values go through the units engine, like <Params>):

* ``nu=0.01:0.05:8``      — 8 values linspace'd over [0.01, 0.05]
* ``nu=0.01,0.02,0.05``   — an explicit list
* ``Velocity-zone=...``   — zonal: applies to the named settings-zone
"""

from __future__ import annotations

import itertools
import os
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from tclb_tpu.control.solver import Solver, _read_units
from tclb_tpu.core.registry import Model
from tclb_tpu.serve.ensemble import Case


@dataclass
class SweepSetup:
    """The shared base every ensemble member starts from."""

    solver: Solver
    model: Model
    shape: tuple[int, ...]
    flags: np.ndarray
    niter: int                      # <Solve Iterations> default
    conf_name: str = "sweep"
    zone_names: dict[str, int] = field(default_factory=dict)


def parse_param(spec: str) -> tuple[str, list[str]]:
    """``name=lo:hi:n`` or ``name=v1,v2,...`` -> (name, raw values).
    Values stay strings so the units engine can read them (``0.01:1m/s:4``
    is rejected — ranges must be plain numbers; lists may carry units)."""
    name, sep, rhs = spec.partition("=")
    name, rhs = name.strip(), rhs.strip()
    if not sep or not name or not rhs:
        raise ValueError(f"--param needs name=values, got {spec!r}")
    if ":" in rhs:
        parts = rhs.split(":")
        if len(parts) != 3:
            raise ValueError(f"range spec must be lo:hi:n, got {rhs!r}")
        lo, hi, n = float(parts[0]), float(parts[1]), int(parts[2])
        if n < 1:
            raise ValueError(f"range count must be >= 1, got {n}")
        return name, [repr(float(v)) for v in np.linspace(lo, hi, n)]
    return name, [v.strip() for v in rhs.split(",") if v.strip()]


def expand_grid(grid: dict) -> list[Case]:
    """Cartesian sweep expansion for plain (units-free) grids — the
    gateway's ``POST /v1/jobs`` ``sweep`` bodies.  Axis values are either
    a ``"lo:hi:n"`` range string (same grammar as :func:`parse_param`)
    or an explicit number list; values are already in lattice units (no
    XML, no units engine).  An empty grid is one unnamed case."""
    axes: list[tuple[str, list[float]]] = []
    for name, raw in grid.items():
        if isinstance(raw, str):
            _, vals = parse_param(f"{name}={raw}")
            axes.append((name, [float(v) for v in vals]))
        elif isinstance(raw, (list, tuple)):
            if not raw:
                raise ValueError(f"sweep axis {name!r} is empty")
            axes.append((name, [float(v) for v in raw]))
        else:
            raise ValueError(f"sweep axis {name!r} must be a 'lo:hi:n' "
                             f"string or a number list")
    if not axes:
        return [Case(name="case0")]
    cases = []
    for combo in itertools.product(*(vals for _, vals in axes)):
        settings = {name: v for (name, _), v in zip(axes, combo)}
        cases.append(Case(settings=settings,
                          name=",".join(f"{n}={v:g}"
                                        for n, v in settings.items())))
    return cases


def load_setup(path: str, model: Optional[Model] = None,
               dtype: Any = None) -> SweepSetup:
    """Execute just the setup subtree of a config: units, geometry
    painting, base <Model><Params>.  The returned lattice is painted but
    NOT initialized — the ensemble engine runs Init per case (init
    depends on the swept settings)."""
    from tclb_tpu.control.handlers import acGeometry, acParams
    root = ET.parse(path).getroot()
    if root.tag != "CLBConfig":
        raise ValueError(f"config root must be <CLBConfig>, got "
                         f"<{root.tag}>")
    if model is None:
        name = root.get("model")
        if not name:
            raise ValueError("config has no model= attribute; pass --model")
        from tclb_tpu.models import get_model
        model = get_model(name)
    solver = Solver(model, output=root.get("output", "output/"),
                    dtype=dtype)
    solver.conf_name = os.path.splitext(os.path.basename(path))[0]
    _read_units(root, solver)
    geom = root.find("Geometry")
    if geom is None:
        raise ValueError("config must contain a <Geometry> element")
    if model.ndim == 2:
        shape = (int(round(solver.units.alt(geom.get("ny", "1")))),
                 int(round(solver.units.alt(geom.get("nx", "1")))))
    else:
        shape = (int(round(solver.units.alt(geom.get("nz", "1")))),
                 int(round(solver.units.alt(geom.get("ny", "1")))),
                 int(round(solver.units.alt(geom.get("nx", "1")))))
    solver.set_size(shape)
    acGeometry(geom, solver).init()
    model_node = root.find("Model")
    if model_node is not None:
        for child in model_node:
            if child.tag == "Params":
                acParams(child, solver).init()
    solve = root.find("Solve")
    niter = (int(round(solver.units.alt(solve.get("Iterations", "0"))))
             if solve is not None else 0)
    return SweepSetup(solver=solver, model=model, shape=solver.shape,
                      flags=solver.lattice._flags_host(), niter=niter,
                      conf_name=solver.conf_name,
                      zone_names=dict(solver.geometry.setting_zones))


def expand_cases(setup: SweepSetup, param_specs: list[str]) -> list[Case]:
    """Cartesian product of the --param grids -> ensemble cases.

    Values go through the solver's units engine (the same ``alt`` path
    <Params> uses); ``name-zone`` specs resolve the zone against the
    geometry's settings-zones and land in the case's zonal table."""
    m = setup.model
    axes: list[tuple[str, Optional[int], list[float]]] = []
    for spec in param_specs:
        name, raws = parse_param(spec)
        zone: Optional[int] = None
        par = name
        if "-" in name:
            par, zname = name.split("-", 1)
            if zname not in setup.zone_names:
                raise ValueError(f"unknown settings-zone {zname!r} "
                                 f"(have {sorted(setup.zone_names)})")
            zone = setup.zone_names[zname]
        if par not in m.setting_index:
            raise ValueError(f"model {m.name} has no setting {par!r}")
        values = [float(setup.solver.units.alt(r)) for r in raws]
        axes.append((par, zone, values))
    if not axes:
        return [Case(name="case0")]
    cases = []
    for combo in itertools.product(*(vals for _, _, vals in axes)):
        settings: dict[str, float] = {}
        zonal: dict[tuple[str, int], float] = {}
        tags = []
        for (par, zone, _), v in zip(axes, combo):
            if zone is None:
                settings[par] = v
                tags.append(f"{par}={v:g}")
            else:
                zonal[(par, zone)] = v
                tags.append(f"{par}@{zone}={v:g}")
        cases.append(Case(settings=settings, zonal=zonal,
                          name=",".join(tags)))
    return cases
