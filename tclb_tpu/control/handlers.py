"""XML handler tree — element name -> behavior.

Parity target: the reference Handlers layer (src/Handlers.{h,cpp.Rt}):
``vHandler`` scheduling with fractional intervals (Now/Next,
src/Handlers.h:46-78), ``GenericAction`` recursive execution + callback
stacking (src/Handlers.cpp.Rt:1418-1454), ``getHandler`` dispatch
(:2989-3119), and the individual handler classes listed in SURVEY.md §2.2.

Handlers run host-side; everything device-bound goes through the Lattice.
"""

from __future__ import annotations

import math
import os
import re
import xml.etree.ElementTree as ET
from typing import Optional

import numpy as np

from tclb_tpu import telemetry
from tclb_tpu.control.solver import ITERATION_STOP, Solver
from tclb_tpu.utils import log


class Handler:
    """Base scheduling unit (reference vHandler, src/Handlers.h:24-78)."""

    kind = "action"   # action | callback | container | design
    # handlers with mutable numeric run-state must either implement
    # restorable_state()/restore_state() or set this marker (enforced by
    # the hygiene.unrestorable_handler static check)
    checkpoint_exempt = False

    def __init__(self, node: ET.Element, solver: Solver):
        self.node = node
        self.solver = solver
        self.start_iter = 0
        self.every_iter = 0.0
        self.ck_key: Optional[str] = None

    # -- schedule ----------------------------------------------------------- #

    def _parse_interval(self) -> None:
        # deterministic config-order key: the same document always yields
        # the same keys, so checkpointed handler state finds its handler
        # again on a resume replay
        self.ck_key = self.solver.next_ck_key(type(self).__name__)
        self.start_iter = self.solver.iter
        attr = self.node.get("Iterations")
        self.every_iter = self.solver.units.alt(attr) if attr else 0.0
        # a resume restores each recorded handler's schedule anchor before
        # its init body runs (init may immediately start a Solve loop)
        st = self.solver._pending_restore.get(self.ck_key)
        if st is not None and "__start_iter" in st:
            self.start_iter = int(st["__start_iter"])

    def now(self, it: int) -> bool:
        """True when ``it`` is a firing iteration (reference vHandler::Now:
        handles fractional intervals by floor-crossing)."""
        if not self.every_iter:
            return False
        it -= self.start_iter
        return math.floor(it / self.every_iter) > \
            math.floor((it - 1) / self.every_iter)

    def next_it(self, it: int) -> int:
        """Steps until the next firing (reference vHandler::Next)."""
        if not self.every_iter:
            return -1
        it -= self.start_iter
        k = math.floor(it / self.every_iter)
        return int(-math.floor(-(k + 1) * self.every_iter)) - it

    # -- lifecycle ---------------------------------------------------------- #

    def init(self) -> int:
        self._parse_interval()
        if self.node.get("output"):
            self.solver.output_prefix = self.node.get("output")
        return 0

    def do_it(self) -> int:
        return 0

    def finish(self) -> int:
        return 0

    # -- checkpoint protocol ------------------------------------------------- #

    def restorable_state(self) -> dict:
        """Mutable run-state a full-run checkpoint must capture (must be
        JSON-serializable).  The default is stateless; any handler whose
        ``do_it`` mutates numeric attributes overrides this (the
        ``hygiene.unrestorable_handler`` static check enforces it)."""
        return {}

    def restore_state(self, state: dict) -> None:
        """Re-apply a dict previously produced by ``restorable_state``."""


class GenericAction(Handler):
    """Container executing children immediately; periodic children stack
    into ``solver.hands`` until this action completes (reference
    GenericAction::ExecuteInternal/Unstack, src/Handlers.cpp.Rt:1418-1454)."""

    def init(self) -> int:
        super().init()
        return self.execute_internal()

    def execute_internal(self) -> int:
        self._stacked = 0
        for child in self.node:
            h = get_handler(child, self.solver)
            if h is None:
                continue
            ret = h.init()
            if ret not in (0, None):
                return ret
            # a pending resume state for this handler (parked by
            # apply_restored_solver_state) lands after init so the init
            # body can't clobber the restored values
            st = self.solver._pending_restore.pop(
                getattr(h, "ck_key", None) or "", None)
            if st is not None:
                h.restore_state({k: v for k, v in st.items()
                                 if not k.startswith("__")})
            if h.every_iter or h.kind == "design":
                self.solver.hands.append(h)
                self._stacked += 1
        return 0

    def unstack(self) -> None:
        for _ in range(getattr(self, "_stacked", 0)):
            h = self.solver.hands.pop()
            h.finish()


class MainContainer(GenericAction):
    """<CLBConfig> root (reference MainContainer,
    src/Handlers.cpp.Rt:1501-1529)."""

    kind = "container"

    def init(self) -> int:
        self.start_iter = self.solver.iter
        self.every_iter = 0.0
        if self.node.get("output"):
            self.solver.output_prefix = self.node.get("output")
        # annotated provenance copy of the config (reference MainContainer
        # dump with version/precision/backend, src/Handlers.cpp.Rt:1504-1522)
        self.solver.dump_config(self.node)
        ret = self.execute_internal()
        self.unstack()
        return ret


class acSolve(GenericAction):
    """<Solve Iterations="N">: the main loop — event-driven batching of
    lattice iterations between due callbacks (reference acSolve,
    src/Handlers.cpp.Rt:1531-1570)."""

    def init(self) -> int:
        Handler.init(self)
        if not self.every_iter:
            raise ValueError("<Solve> needs a positive Iterations attribute")
        ret = self.execute_internal()
        if ret not in (0, None):
            return ret
        s = self.solver
        stop = False
        # visible to checkpoint collection: the running Solve's schedule
        # anchor must be saved so a resume replay completes to the same
        # absolute iteration instead of restarting its count
        s.solve_stack.append(self)
        try:
            while True:
                next_it = self.next_it(s.iter)
                for h in s.hands:
                    it = h.next_it(s.iter)
                    if 0 < it < next_it:
                        next_it = it
                steps = next_it
                s.iter += steps
                s.update_synthetic_turbulence(steps)
                s.lattice.iterate(steps)
                s.progress(steps)
                for h in s.hands:
                    if h.now(s.iter):
                        # each periodic callback runs under its own span, so
                        # a trace attributes Solve wall-time between lattice
                        # iteration and VTK/Log/Failcheck/... output work
                        with telemetry.span("handler",
                                            handler=type(h).__name__,
                                            iteration=s.iter):
                            r = h.do_it()
                        if r == ITERATION_STOP:
                            stop = True
                        elif r not in (0, None):
                            return r
                if stop or self.now(s.iter):
                    break
        finally:
            s.solve_stack.pop()
        self.unstack()
        return 0


class acRepeat(GenericAction):
    """<Repeat Times="N">: run children N times (reference acRepeat,
    src/Handlers.cpp.Rt:2191-2212)."""

    def init(self) -> int:
        Handler.init(self)
        times = int(self.node.get("Times", "1"))
        for _ in range(times):
            ret = self.execute_internal()
            if ret not in (0, None):
                return ret
            self.unstack()
        return 0


class acGeometry(Handler):
    """<Geometry>: run the painter and push flags (reference acGeometry,
    src/Handlers.cpp.Rt:2975-2988)."""

    def init(self) -> int:
        super().init()
        s = self.solver
        s.geometry.load(self.node)
        s.lattice.set_flags(s.geometry.result())
        if self.node.get("export") == "vti":
            s.write_geometry_vti()
        return 0


class acModel(GenericAction):
    """<Model>: children (Params) then lattice Init (reference acModel,
    src/Handlers.cpp.Rt:2643-2652)."""

    def init(self) -> int:
        Handler.init(self)
        ret = self.execute_internal()
        if ret not in (0, None):
            return ret
        self.solver.lattice.init()
        self.unstack()
        return 0


class acInit(Handler):
    """<Init/>: re-run the Init action (reference acInit,
    src/Handlers.cpp.Rt:2653-2662)."""

    def init(self) -> int:
        super().init()
        self.solver.lattice.init()
        return 0


class acParams(Handler):
    """<Params name="value" name-zone="value">: set (zonal) settings through
    the units engine; unknown names are ignored with a warning (reference
    acParams, src/Handlers.cpp.Rt:2487-2530)."""

    def init(self) -> int:
        super().init()
        s = self.solver
        m = s.model
        for name, raw in self.node.attrib.items():
            if name in ("Iterations", "output"):
                continue
            zone: Optional[int] = None
            par = name
            if "-" in name:
                par, zname = name.split("-", 1)
                if zname in s.geometry.setting_zones:
                    zone = s.geometry.setting_zones[zname]
                else:
                    log.warning(f"unknown zone {zname!r} "
                          f"(setting {par})")
                    continue
            if par in m.setting_index:
                val = s.units.alt(raw)
                s.lattice.set_setting(par, val, zone=zone)
            else:
                # the reference silently skips unknown names
                # (src/Handlers.cpp.Rt:2512-2525 has no else branch) —
                # a warning is kinder: a typo'd Params otherwise runs a
                # silently different case
                log.warning(f"Params: model {m.name} has no setting "
                            f"{par!r} — ignored")
        return 0


class conControl(Handler):
    """<Control Iterations="N"><CSV file="..." Time="col*1s"/>
    <Params name-zone="col*1m/s+0.5"/></Control>

    Time-dependent zonal settings (reference conControl,
    src/Handlers.cpp.Rt:2213-2452): CSV columns are read through the units
    engine into a context, linearly interpolated onto the iteration grid
    [0, N), and <Params> attribute values are expressions
    ``term + term + ...`` with each term ``variable*scale`` (variable from
    the context) or a units-bearing constant.  The resulting per-iteration
    series land in the lattice's zonal time tables."""

    def init(self) -> int:
        super().init()
        s = self.solver
        horizon = int(round(s.units.alt(self.node.get("Iterations", "0"))))
        if horizon <= 0:
            raise ValueError("<Control> needs a positive Iterations horizon")
        self.horizon = horizon
        context: dict[str, np.ndarray] = {}
        for child in self.node:
            if child.tag == "CSV":
                self._load_csv(child, context)
            elif child.tag == "Params":
                self._params(child, context)
            else:
                raise ValueError(f"unknown element <{child.tag}> in Control")
        return 0

    def _eval(self, context: dict[str, np.ndarray], expr: str) -> np.ndarray:
        """``var*scale+var2*scale2+const`` -> per-iteration array
        (reference conControl::get, src/Handlers.cpp.Rt:2253-2310).

        Terms are split on top-level ``+``/``-``; a sign directly after
        ``e``/``E`` is a numeric exponent (``1e+5``), not a term boundary,
        and a leading sign negates the first term."""
        s = self.solver
        out = np.zeros(self.horizon)
        # a +/- is an exponent sign only in digit-e contexts ("1e+5", "2.E-3");
        # after an identifier ending in e/E ("rate+flow") it still splits.
        # A sign directly after '*' is a negative factor ("flow*-2"), not a
        # term boundary (tighten spaces around '*' first so "flow * -2"
        # parses the same way).
        expr = re.sub(r"\s*\*\s*", "*", expr)
        parts = re.split(r"(?<![\d.][eE])(?<!\*)([+-])", expr)
        sign = 1.0
        for part in parts:
            part = part.strip()
            if part == "+":
                continue
            if part == "-":
                sign = -sign
                continue
            if not part:
                continue
            factors = part.split("*")
            if factors[0].strip() in context:
                val = context[factors[0].strip()].copy()
                for f in factors[1:]:
                    val = val * s.units.alt(f)
            else:
                v = 1.0
                for f in factors:
                    v *= s.units.alt(f)
                val = v
            out = out + sign * val
            sign = 1.0
        return out

    def _load_csv(self, node: ET.Element, context: dict) -> None:
        """reference conControl::Internal (src/Handlers.cpp.Rt:2311-2452):
        parse, convert through units, interpolate onto the iteration grid."""
        s = self.solver
        fn = node.get("file")
        if not fn:
            raise ValueError("<CSV> in Control needs file=")
        with open(fn) as f:
            header = [h.strip().strip('"') for h in
                      f.readline().strip().split(",")]
            rows = [[s.units.alt(tok) for tok in line.strip().split(",")]
                    for line in f if line.strip()]
        data = {name: np.array([r[i] for r in rows])
                for i, name in enumerate(header)}
        n = len(rows)
        data["_index"] = np.arange(n, dtype=np.float64)
        tattr = node.get("Time")
        if tattr:
            # time expression in iteration units (units.alt maps s -> iters);
            # evaluate over the CSV rows, not the iteration grid
            saved, self.horizon = self.horizon, n
            t = self._eval(data, tattr)
            self.horizon = saved
        else:
            t = data["_index"] * (self.horizon / n)
        # np.interp silently misbehaves on a non-increasing sample grid —
        # sort rows by time and reject duplicates instead
        order = np.argsort(t, kind="stable")
        t = np.asarray(t, dtype=np.float64)[order]
        if (np.diff(t) <= 0).any():
            raise ValueError(f"<CSV {fn}>: Time column has duplicate or "
                             "non-increasing entries after sorting")
        grid = np.arange(self.horizon, dtype=np.float64)
        for name, col in data.items():
            context[name] = np.interp(grid, t, np.asarray(col)[order])
        # the reference also accepts <Params> nested inside <CSV>
        # (conControl::Internal tail, src/Handlers.cpp.Rt:2430-2450)
        for child in node:
            if child.tag == "Params":
                self._params(child, context)

    def _params(self, node: ET.Element, context: dict) -> None:
        s = self.solver
        for name, raw in node.attrib.items():
            par, zones = name, None
            if "-" in name:
                par, zname = name.split("-", 1)
                if zname in s.geometry.setting_zones:
                    zones = [s.geometry.setting_zones[zname]]
                else:
                    log.warning(f"unknown zone {zname!r} (Control "
                          f"setting {par})")
                    continue
            if par not in s.model.setting_index:
                continue
            if zones is None:
                # zone-less: apply to every allocated zone (reference
                # zSet.set with zone -1, src/ZoneSettings.h)
                zones = sorted({0} | set(s.geometry.setting_zones.values()))
            series = self._eval(context, raw)
            for z in zones:
                s.lattice.set_setting_series(par, series, zone=z)


class cbVTK(Handler):
    kind = "callback"

    def _what(self) -> Optional[set]:
        w = self.node.get("what")
        return set(w.split(",")) if w else None

    def do_it(self) -> int:
        compress = (self.node.get("compress", "") or "").lower() \
            in ("1", "true", "yes")
        self.solver.write_vtk(self._what(), compress=compress)
        return 0

    def init(self) -> int:
        super().init()
        if not self.every_iter:
            return self.do_it()
        return 0


class cbTXT(cbVTK):
    def do_it(self) -> int:
        self.solver.write_txt(self._what())
        return 0


class cbBIN(cbVTK):
    def do_it(self) -> int:
        self.solver.write_bin()
        return 0


class cbLog(Handler):
    kind = "callback"

    def do_it(self) -> int:
        self.solver.write_log()
        return 0

    def init(self) -> int:
        super().init()
        if not self.every_iter:
            return self.do_it()
        return 0


class cbDumpSettings(Handler):
    kind = "callback"

    def do_it(self) -> int:
        s = self.solver
        path = s.out_path("Settings", "txt")
        svec = np.asarray(s.lattice.params.settings)
        with open(path, "w") as f:
            for spec in s.model.settings:
                f.write(f"{spec.name} = "
                        f"{svec[s.model.setting_index[spec.name]]!r}\n")
        return 0

    def init(self) -> int:
        super().init()
        if not self.every_iter:
            return self.do_it()
        return 0


class cbStop(Handler):
    """<Stop GlobalChange="eps" Times="k">: stop when every watched Global
    changed less than eps for k consecutive checks (reference cbStop,
    src/Handlers.cpp.Rt:1079-1157)."""

    kind = "callback"

    def init(self) -> int:
        super().init()
        m = self.solver.model
        self.watch: list[tuple[str, float]] = []
        for g in m.globals_:
            a = self.node.get(g.name + "Change")
            if a is not None:
                self.watch.append((g.name, float(a)))
        if not self.watch:
            raise ValueError("No *Change attribute in <Stop>")
        self.times = int(self.node.get("Times", "1"))
        self.old = {n: -12341234.0 for n, _ in self.watch}
        self.score = 0
        return 0

    def do_it(self) -> int:
        g = self.solver.lattice.get_globals()
        any_change = 0
        for name, eps in self.watch:
            if abs(self.old[name] - g[name]) > eps:
                any_change += 1
            self.old[name] = g[name]
        self.score = 0 if any_change else self.score + 1
        if self.score >= self.times:
            self.score = 0
            for name, _ in self.watch:
                self.old[name] = -12341234.0
            return ITERATION_STOP
        return 0

    def restorable_state(self) -> dict:
        return {"old": {k: float(v) for k, v in self.old.items()},
                "score": int(self.score)}

    def restore_state(self, state: dict) -> None:
        for k, v in state.get("old", {}).items():
            if k in self.old:
                self.old[k] = float(v)
        self.score = int(state.get("score", 0))


class cbFailcheck(Handler):
    """<Failcheck Iterations="N">: NaN scan of quantities; on failure run
    child elements (rescue dump) then stop (reference cbFailcheck,
    src/Handlers.cpp.Rt:1175-1277)."""

    kind = "callback"

    def do_it(self) -> int:
        s = self.solver
        what = self.node.get("what")
        names = set(what.split(",")) if what else {"all"}
        bad = False
        for q in s.model.quantities:
            if q.adjoint:
                continue
            if "all" not in names and q.name not in names:
                continue
            arr = np.asarray(s.lattice.get_quantity(q.name))
            finite = np.isfinite(arr)
            if not finite.all():
                n_bad = int(arr.size - finite.sum())
                log.warning(f"Failcheck: {q.name} has {n_bad} non-finite "
                            f"values at iteration {s.iter}")
                telemetry.failcheck(
                    iteration=s.iter, quantity=q.name, n_bad=n_bad,
                    engine=getattr(s.lattice, "_fast_name", None) or "xla")
                bad = True
                break
        if bad:
            for child in self.node:
                h = get_handler(child, self.solver)
                if h is not None:
                    h.init()
                    h.do_it()
            return ITERATION_STOP
        return 0


class cbSample(Handler):
    """<Sample what="U,Rho" Iterations="N"><Point dx=... dy=.../></Sample>
    (reference cbSample, src/Handlers.cpp.Rt:1278-1337): per-iteration point
    probes flushed on the callback."""

    kind = "callback"

    def init(self) -> int:
        super().init()
        if not self.every_iter:
            raise ValueError("Sampler needs a nonzero Iterations attribute")
        s = self.solver
        what = self.node.get("what")
        quants = ([q.name for q in s.model.quantities if not q.adjoint]
                  if not what or what == "all" else what.split(","))
        pts = []
        for p in self.node:
            if p.tag != "Point":
                raise ValueError(f"unknown element <{p.tag}> in Sampler")
            x = int(round(s.units.alt(p.get("dx", "0"))))
            y = int(round(s.units.alt(p.get("dy", "0"))))
            z = int(round(s.units.alt(p.get("dz", "0"))))
            pts.append((z, y, x)[-s.model.ndim:])
        from tclb_tpu.utils.sampler import Sampler
        self.sampler = Sampler(s.model, quants, np.asarray(pts),
                               s.out_path("Sample", "csv", with_iter=False),
                               s.units)
        s.lattice.attach_sampler(self.sampler)
        return 0

    def do_it(self) -> int:
        self.sampler.flush()
        return 0

    def finish(self) -> int:
        self.sampler.flush()
        self.solver.lattice.sampler = None
        return 0

    def restorable_state(self) -> dict:
        # flush so no buffered probe rows die with the process; the header
        # flag makes a resumed run append to the CSV instead of rewriting
        self.sampler.flush()
        return {"wrote_header": bool(self.sampler._wrote_header)}

    def restore_state(self, state: dict) -> None:
        if state.get("wrote_header"):
            self.sampler._wrote_header = True


class cbKeep(Handler):
    """<Keep What="..." Above=|Below=|Equal=...>: feedback controller pinning
    a Global by adjusting its InObj weight (reference cbKeep,
    src/Handlers.cpp.Rt:1339-1417)."""

    kind = "callback"

    def init(self) -> int:
        super().init()
        self.gname = self.node.get("What")
        if self.gname not in self.solver.model.global_index:
            raise ValueError(f"Keep: unknown global {self.gname!r}")
        for mode in ("Above", "Below", "Equal"):
            if self.node.get(mode) is not None:
                self.mode = mode
                self.target = self.solver.units.alt(self.node.get(mode))
                break
        else:
            raise ValueError("Keep needs Above=, Below= or Equal=")
        self.rate = float(self.node.get("Rate", "1.0"))
        return 0

    def do_it(self) -> int:
        s = self.solver
        val = s.lattice.get_globals()[self.gname]
        wname = self.gname + "InObj"
        cur = float(np.asarray(s.lattice.params.settings)[
            s.model.setting_index[wname]])
        err = val - self.target
        if (self.mode == "Above" and err < 0) or \
           (self.mode == "Below" and err > 0) or self.mode == "Equal":
            cur -= self.rate * err
            s.lattice.set_setting(wname, cur)
        return 0


class cbSaveBinary(Handler):
    """<SaveBinary [comp=f[i]] [filename=...]>, re-backed onto the
    checkpoint subsystem: path suffixes go through its centralized
    normalization (an exact-extension rule — stems containing dots no
    longer confuse the old ``fn[:-4]`` juggling), every write is atomic,
    and a filename *without* the legacy ``.npz`` suffix saves the new
    manifest-verified checkpoint directory format."""

    kind = "callback"

    def do_it(self) -> int:
        from tclb_tpu import checkpoint as ckpt
        s = self.solver
        comp = self.node.get("comp")
        if comp:
            # per-component dump (reference saveComp,
            # src/Solver.cpp.Rt:480-510: one density -> one .comp file)
            fn = ckpt.with_suffix(self.node.get("filename")
                                  or s.out_path(f"Save_{comp}", "npy"),
                                  ".npy")
            with ckpt.atomic_path(fn) as tmp:
                with open(tmp, "wb") as f:
                    np.save(f, np.asarray(s.lattice.get_density(comp)))
            return 0
        fn = self.node.get("filename") or s.out_path("Save", "npz")
        if fn.endswith(".npz"):
            s.lattice.save(fn)      # legacy single-file format (atomic)
        else:
            ckpt.save_checkpoint(fn, s.lattice,
                                 extra=ckpt.collect_solver_state(s))
        return 0

    def init(self) -> int:
        super().init()
        if not self.every_iter:
            return self.do_it()
        return 0


class acLoadBinary(Handler):
    """<LoadBinary filename=... [comp=f[i]]>: restore a SaveBinary dump —
    either the manifest-verified checkpoint directory format or a legacy
    ``.npz`` — and reconcile the Solver clock with the restored lattice
    iteration so ``every=``-based handlers keep firing on schedule after
    a restart (previously the solver stayed at its old count while the
    lattice jumped, and Control series/Log output went misaligned)."""

    def init(self) -> int:
        super().init()
        fn = self.node.get("filename")
        if not fn:
            raise ValueError("LoadBinary needs filename=")
        from tclb_tpu import checkpoint as ckpt
        comp = self.node.get("comp")
        if comp:
            # per-component restore (reference loadComp,
            # src/Solver.cpp.Rt:512-545); mirror SaveBinary's suffixing
            self.solver.lattice.set_density(
                comp, np.load(ckpt.with_suffix(fn, ".npy")))
            return 0
        man = ckpt.load_any(self.solver.lattice, fn)
        ckpt.apply_restored_solver_state(self.solver, man)
        return 0


class cbSaveCheckpoint(Handler):
    """<SaveCheckpoint Iterations="N" [dir=...] [keep="3"] [mode="async"]
    [compress="zstd"]>: periodic full-run checkpoints through
    :class:`tclb_tpu.checkpoint.CheckpointManager` — atomic, CRC-verified,
    keep-last-N, serialized off-thread (``mode="sync"`` forces blocking
    saves).  ``compress`` codecs the shard files ("zlib"/"zstd"; a zstd
    request without the zstandard package degrades to uncompressed with
    a warning).  Captures lattice state *plus* solver/handler run-state
    (averaging origin, optimizer iteration, every stacked handler's
    ``restorable_state``).

    This handler is also the resume point: when the solver carries a
    ``--resume`` request, its init restores from the requested checkpoint
    (default: the manager's newest *valid* one — corrupted checkpoints
    are skipped) before any <Solve> runs."""

    kind = "callback"

    def init(self) -> int:
        super().init()
        s = self.solver
        from tclb_tpu.checkpoint import CheckpointManager
        root = self.node.get("dir")
        if not root:
            base = s.output_prefix
            if base.endswith("/"):
                os.makedirs(base, exist_ok=True)
                base = os.path.join(base, s.conf_name)
            root = base + "_checkpoint"
        mode = (self.node.get("mode", "async") or "async").lower()
        self.manager = CheckpointManager(
            root, keep_last=int(self.node.get("keep", "3")),
            async_saves=mode != "sync",
            compress=self.node.get("compress"))
        if s.resume_from is not None:
            self._resume()
        return 0

    def _resume(self) -> None:
        s = self.solver
        from tclb_tpu import checkpoint as ckpt
        target, s.resume_from = s.resume_from, None
        if isinstance(target, str) and target not in ("", "latest", "auto"):
            path = target
            if not ckpt.is_checkpoint_dir(path):
                raise ValueError(
                    f"--resume: {path} is not a checkpoint directory")
        else:
            path = self.manager.latest()
        if path is None:
            log.notice("resume requested but no valid checkpoint under "
                       f"{self.manager.root} — starting cold")
            return
        man = ckpt.restore_lattice(s.lattice, path)
        ckpt.apply_restored_solver_state(s, man)
        log.notice(f"resumed from {path} at iteration {s.iter}")

    def do_it(self) -> int:
        s = self.solver
        from tclb_tpu.checkpoint import collect_solver_state
        self.manager.save(s.lattice, step=s.iter,
                          extra=collect_solver_state(s))
        return 0

    def finish(self) -> int:
        self.manager.wait()
        return 0


class acCallPython(Handler):
    """<CallPython module="m" function="f">: call a user function with the
    solver — the reference builds numpy views over staged component buffers
    (cbPythonCall, src/Handlers.cpp.Rt:2774-2970); here the framework *is*
    Python, so the user function receives the live Solver and mutates
    densities via get/set_density."""

    kind = "callback"

    def init(self) -> int:
        super().init()
        import importlib
        mod = self.node.get("module")
        fn = self.node.get("function", "run")
        self._fn = getattr(importlib.import_module(mod), fn)
        if not self.every_iter:
            return self.do_it()
        return 0

    def do_it(self) -> int:
        ret = self._fn(self.solver)
        return int(ret) if ret else 0


class GenericContainer(GenericAction):
    kind = "container"

    def init(self) -> int:
        Handler.init(self)
        ret = self.execute_internal()
        self.unstack()
        return ret


class acNop(Handler):
    """Elements handled elsewhere (Units is read before the tree runs)."""

    def init(self) -> int:
        return 0


class acSyntheticTurbulence(Handler):
    """<SyntheticTurbulence>: configure the synthetic-inflow turbulence
    generator (reference acSyntheticTurbulence,
    src/Handlers.cpp.Rt:2532-2642).  Wave parameters accept
    <name>WaveLength (inverted), <name>WaveNumber, or <name>WaveFrequency
    (x 2 pi), all unit-converted."""

    def _wave_number(self, name: str):
        u = self.solver.units
        val = None
        a = self.node.get(name + "WaveLength")
        if a is not None:
            val = 1.0 / u.alt(a)
        a = self.node.get(name + "WaveNumber")
        if a is not None:
            val = u.alt(a)
        a = self.node.get(name + "WaveFrequency")
        if a is not None:
            val = u.alt(a) * 2.0 * math.pi
        return val

    def init(self) -> int:
        super().init()
        from tclb_tpu.utils.turbulence import SyntheticTurbulence
        st = SyntheticTurbulence()
        nmodes = int(self.node.get("Modes", 100))
        spec = self.node.get("Spectrum", "Von Karman")
        if spec == "Von Karman":
            main_wn = self._wave_number("Main")
            diff_wn = self._wave_number("Diffusion")
            if main_wn is None or diff_wn is None:
                raise ValueError(
                    "Von Karman spectrum needs MainWaveNumber and "
                    "DiffusionWaveNumber (or WaveLength/Frequency forms)")
            max_wn = self._wave_number("Shortest")
            if max_wn is None:
                max_wn = 2.0 * math.pi / 4.0   # 2 pi over 4 elements
            min_wn = self._wave_number("Longest")
            if min_wn is None:
                min_wn = main_wn / 2.0
            frac = st.set_von_karman(main_wn, diff_wn, min_wn, max_wn,
                                     nmodes)
            if frac < 0.7:
                log.notice(f"synthetic turbulence resolves only "
                           f"{frac:.0%} of the spectrum")
        elif spec == "One Wave":
            wn = self._wave_number("")
            if wn is None:
                raise ValueError("One Wave spectrum needs a WaveNumber")
            st.set_one_wave(wn)
        else:
            raise ValueError(f"unknown spectrum {spec!r}")
        t_wn = self._wave_number("Time")
        if t_wn is None:
            raise ValueError("synthetic turbulence needs TimeWaveNumber "
                             "(iteration correlation scale)")
        st.set_time_scale(t_wn)
        self.solver.synthetic_turbulence = st
        return 0


class cbCatalyst(Handler):
    """<Catalyst what="U,Rho" [slice_axis= slice_index=] [vmin= vmax=]>:
    in-situ frame rendering — the TPU-native equivalent of both the
    ParaView Catalyst co-processor (reference cbCatalyst,
    src/Handlers.cpp.Rt:898-1006) and the GLUT GUI's live Color() view
    (src/gpu_anim.h; see utils/render.py for the redesign rationale).
    Vector quantities render their magnitude; 3D lattices render the
    middle slice of ``slice_axis`` (default z) unless slice_index= is
    given."""

    kind = "callback"

    def do_it(self) -> int:
        from tclb_tpu.utils.render import render_frame
        s = self.solver
        what = (self.node.get("what") or "U").split(",")
        axis = int(self.node.get("slice_axis", "0"))
        vmin = self.node.get("vmin")
        vmax = self.node.get("vmax")
        for q in what:
            q = q.strip()
            a = np.asarray(s.lattice.get_quantity(q))
            if a.ndim == len(s.shape) + 1:      # vector -> magnitude
                a = np.sqrt((a ** 2).sum(axis=0))
            if a.ndim == 3:
                idx = int(self.node.get("slice_index",
                                        str(a.shape[axis] // 2)))
                a = np.take(a, idx, axis=axis)
            render_frame(s.out_path(f"frame_{q}", "png"), a,
                         vmin=s.units.alt(vmin) if vmin else None,
                         vmax=s.units.alt(vmax) if vmax else None)
        return 0

    def init(self) -> int:
        super().init()
        if not self.every_iter:
            return self.do_it()
        return 0


class cbAveraging(Handler):
    """<Average>: reset the running averages (average=True densities) and
    restart the sample counter (reference cbAveraging,
    src/Handlers.cpp.Rt:1158-1174 + Lattice::resetAverage,
    src/Lattice.cu.Rt:1193-1201)."""

    kind = "callback"

    def init(self) -> int:
        super().init()
        self.solver.lattice.reset_average()
        return 0

    def do_it(self) -> int:
        self.solver.lattice.reset_average()
        return 0


_HANDLERS = {
    "CLBConfig": MainContainer,
    "SyntheticTurbulence": acSyntheticTurbulence,
    "Average": cbAveraging,
    "Catalyst": cbCatalyst,
    "Solve": acSolve,
    "Repeat": acRepeat,
    "Geometry": acGeometry,
    "Model": acModel,
    "Init": acInit,
    "Params": acParams,
    "Control": conControl,
    "VTK": cbVTK,
    "TXT": cbTXT,
    "BIN": cbBIN,
    "Log": cbLog,
    "Stop": cbStop,
    "Failcheck": cbFailcheck,
    "Sample": cbSample,
    "Keep": cbKeep,
    "SaveBinary": cbSaveBinary,
    "SaveMemoryDump": cbSaveBinary,
    "SaveCheckpoint": cbSaveCheckpoint,
    "LoadBinary": acLoadBinary,
    "LoadMemoryDump": acLoadBinary,
    "DumpSettings": cbDumpSettings,
    "CallPython": acCallPython,
    "Units": acNop,
    "Container": GenericContainer,
    # the reference declares these two with empty Init bodies
    # (src/Handlers.cpp.Rt:2454/2470) — same here: accepted, no-op
    "FieldParameter": acNop,
    "ControlParameter": acNop,
}


def register_handler(name: str, cls) -> None:
    _HANDLERS[name] = cls


def get_handler(node: ET.Element, solver: Solver) -> Optional[Handler]:
    """Element name -> handler instance (reference getHandler,
    src/Handlers.cpp.Rt:2989-3119)."""
    cls = _HANDLERS.get(node.tag)
    if cls is None:
        raise ValueError(f"unknown config element <{node.tag}>")
    return cls(node, solver)


# optimization/adjoint handlers register themselves on import
from tclb_tpu.control import opt_handlers  # noqa: E402,F401  (registration)
