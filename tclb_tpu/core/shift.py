"""DDF shifting: the storage representation of the precision ladder.

Raw distributions carry an O(1) rest-equilibrium background (the lattice
weights ``w_i``), so narrowing storage to bf16 spends the 8-bit mantissa
on a constant and leaves ~``2**-8 * w_i`` of quantization noise per
round trip — at low Mach that noise rivals the velocity signal itself.
DDF shifting (Lehmann et al. 2022, "Accuracy and performance of the LBM
with 64-bit, 32-bit, and customized 16-bit number formats") stores the
*deviation* ``f_i - w_i`` instead: the mantissa goes to the signal and
the low-Mach velocity error drops by roughly the background/signal
ratio.  The shift is a per-plane compile-time constant, so it commutes
with pull streaming (a per-plane roll) and costs one add per
widen/narrow seam — seams that already exist for the cast.

This module is the single source of truth for that representation:

* :data:`STORAGE_REPRS` — the representation vocabulary (``"raw"``
  stores ``f_i``; ``"shifted"`` stores ``f_i - w_i``), stamped into
  checkpoint manifests, serve/cache keys and telemetry spans;
* :func:`storage_shift` — the per-plane shift vector, derived from the
  model's velocity sets (standard D2Q9/D3Q19/D3Q27 weight recognition;
  unrecognized groups and non-streamed planes shift by 0);
* the **shared seam helpers** (:func:`widen_plane`/:func:`narrow_plane`
  for Pallas kernels, :func:`widen_stack`/:func:`narrow_stack` for the
  XLA cast wrappers, :func:`widen_group` for stacked kernel planes) —
  every narrow/widen cast of distribution fields MUST go through these
  (the static ``precision.unshifted_cast`` check enforces it).  With
  ``shift=None`` (the raw representation) every helper reduces to a
  pure ``astype``: no ``+ 0.0`` is ever traced, so the default f32
  path stays BIT-identical (``-0.0 + 0.0 == +0.0`` would break it).

Host-side representation conversion (checkpoint restore across
representations) runs in float64 (:func:`convert_fields_host`), so a
shifted-bf16 -> raw-f32 -> shifted-bf16 round trip is bit-faithful.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

#: at-rest layouts of the distribution-field stack: ``raw`` stores
#: ``f_i``, ``shifted`` stores ``f_i - w_i`` (w_i = lattice weights)
STORAGE_REPRS = ("raw", "shifted")

# |e|^2 -> weight for the standard velocity sets, with the member count
# per ring that identifies the set (recognition must be exact — a group
# that merely has 9 members is NOT a D2Q9 set)
_WEIGHT_TABLES = {
    9: ({0: 4.0 / 9.0, 1: 1.0 / 9.0, 2: 1.0 / 36.0},
        {0: 1, 1: 4, 2: 4}),
    19: ({0: 1.0 / 3.0, 1: 1.0 / 18.0, 2: 1.0 / 36.0},
         {0: 1, 1: 6, 2: 12}),
    27: ({0: 8.0 / 27.0, 1: 2.0 / 27.0, 2: 1.0 / 54.0, 3: 1.0 / 216.0},
         {0: 1, 1: 6, 2: 12, 3: 8}),
}

_shift_cache: dict[str, np.ndarray] = {}


def group_weights(ei: np.ndarray) -> Optional[np.ndarray]:
    """Lattice weights for one density group's velocity vectors, or
    ``None`` when the group is not a standard D2Q9/D3Q19/D3Q27 set.

    ``ei`` is the (q, 3) integer offset block of the group's members
    (fields are zero-padded in ``Model.ei``, so a field group can never
    masquerade as a velocity set — all-zero rows fail the ring count).
    """
    ei = np.asarray(ei)
    q = len(ei)
    table = _WEIGHT_TABLES.get(q)
    if table is None or np.any(np.abs(ei) > 1):
        return None
    weights, counts = table
    e2 = (ei * ei).sum(axis=1)
    have = {int(v): int(n) for v, n in
            zip(*np.unique(e2, return_counts=True))}
    if have != counts:
        return None
    return np.array([weights[int(v)] for v in e2], dtype=np.float64)


def storage_shift(model) -> np.ndarray:
    """Per-plane shift vector ``(n_storage,)`` in float64: the lattice
    weight for every plane of a recognized velocity-set group, 0 for
    everything else (fields, averaged planes, unrecognized groups).
    Cached on ``Model.fingerprint`` (never ``id()``)."""
    key = model.fingerprint
    out = _shift_cache.get(key)
    if out is None:
        out = np.zeros((model.n_storage,), dtype=np.float64)
        n_dens = len(model.densities)
        for _name, idx in model.groups.items():
            idx = [i for i in idx if i < n_dens]   # streamed planes only
            if not idx:
                continue
            w = group_weights(model.ei[idx])
            if w is not None:
                out[idx] = w
        _shift_cache[key] = out
    return out


def has_shift(model) -> bool:
    """Whether the model has any recognized velocity set to shift."""
    return bool(np.any(storage_shift(model)))


def default_repr(model, narrowed: bool) -> str:
    """The representation a :class:`Lattice` picks when none is asked
    for: ``shifted`` on a narrowed rung with a recognized velocity set
    (the Mach-independent default), ``raw`` otherwise (including every
    full-width lattice — the f32 path never changes representation)."""
    return "shifted" if (narrowed and has_shift(model)) else "raw"


def resolve_repr(model, narrowed: bool, storage_repr: Optional[str]) -> str:
    """Validate/resolve a requested representation for one lattice."""
    if storage_repr is None:
        return default_repr(model, narrowed)
    if storage_repr not in STORAGE_REPRS:
        raise ValueError(f"storage_repr {storage_repr!r} must be one of "
                         f"{STORAGE_REPRS}")
    if storage_repr == "shifted":
        if not narrowed:
            raise ValueError(
                "storage_repr='shifted' requires a narrowed storage_dtype "
                "(the full-width path keeps the raw representation so it "
                "stays bit-identical)")
        if not has_shift(model):
            raise ValueError(
                f"model {model.name} has no recognized standard velocity "
                "set to derive DDF shifts from; use storage_repr='raw'")
    return storage_repr


def shift_of(model, storage_repr: str) -> Optional[np.ndarray]:
    """The shift vector the seam helpers take: the per-plane weights for
    ``"shifted"``, ``None`` for ``"raw"`` (pure-``astype`` seams)."""
    return storage_shift(model) if storage_repr == "shifted" else None


def plane_shifts(model, storage_repr: str) -> list:
    """Per-plane helper arguments for kernel factories: python floats
    (0.0 entries become ``None`` so the helper stays a pure cast)."""
    vec = shift_of(model, storage_repr)
    if vec is None:
        return [None] * model.n_storage
    return [float(w) if w else None for w in vec]


# --------------------------------------------------------------------------- #
# Seam helpers.  These are the ONLY sanctioned narrow/widen casts of
# distribution fields (analysis/precision.py's unshifted_cast check
# flags any bypass); with a falsy shift they are pure astype, so the
# raw/f32 contract is untouched.
# --------------------------------------------------------------------------- #


def widen_plane(x, cdtype, w: Optional[float] = None):
    """Storage plane -> compute dtype (+ per-plane shift restore)."""
    y = x.astype(cdtype)
    return y + y.dtype.type(w) if w else y


def narrow_plane(x, sdtype, w: Optional[float] = None):
    """Compute plane -> storage dtype (shift removed before the cast,
    in the compute dtype, so the narrow rounds the deviation)."""
    return (x - x.dtype.type(w)).astype(sdtype) if w else x.astype(sdtype)


def _bshape(shift: np.ndarray, ndim: int) -> np.ndarray:
    return np.asarray(shift).reshape((len(shift),) + (1,) * (ndim - 1))


def widen_group(stack, cdtype, shift: Optional[np.ndarray] = None):
    """Stacked kernel planes (leading plane axis) -> compute dtype."""
    y = stack.astype(cdtype)
    if shift is None or not np.any(shift):
        return y
    return y + _bshape(shift, stack.ndim).astype(np.dtype(cdtype))


def widen_stack(fields, cdtype, shift_b: Optional[np.ndarray] = None):
    """Whole field stack -> compute dtype.  ``shift_b`` is the
    pre-broadcast shift block from :func:`stack_shift` (``None`` = raw:
    pure astype)."""
    y = fields.astype(cdtype)
    return y if shift_b is None else y + shift_b.astype(np.dtype(cdtype))


def narrow_stack(fields, sdtype, shift_b: Optional[np.ndarray] = None):
    """Whole compute-dtype field stack -> storage dtype."""
    if shift_b is None:
        return fields.astype(sdtype)
    return (fields - shift_b.astype(np.dtype(fields.dtype))).astype(sdtype)


def stack_shift(model, storage_repr: str) -> Optional[np.ndarray]:
    """The broadcastable ``(n_storage, 1[, 1[, 1]])`` float32 shift
    block for :func:`widen_stack`/:func:`narrow_stack` — shaped by the
    model's space rank so it broadcasts under a leading batch axis too
    (ensemble carries).  ``None`` for the raw representation."""
    vec = shift_of(model, storage_repr)
    if vec is None:
        return None
    return _bshape(vec, model.ndim + 1).astype(np.float32)


def convert_fields_host(arr: np.ndarray, from_repr: str, to_repr: str,
                        shift: np.ndarray, dtype: Any) -> np.ndarray:
    """Host-side representation conversion for checkpoint restore /
    legacy loads: ``arr`` (at-rest, any storage dtype/repr) -> the
    target ``(dtype, to_repr)`` at-rest layout.  The arithmetic runs in
    float64 so a shifted-bf16 -> raw-f32 -> shifted-bf16 round trip is
    bit-faithful (f64 holds the sum ``f_dev + w`` exactly for every
    representable deviation)."""
    for r in (from_repr, to_repr):
        if r not in STORAGE_REPRS:
            raise ValueError(f"unknown storage_repr {r!r}; "
                             f"known: {STORAGE_REPRS}")
    wide = np.asarray(arr).astype(np.float64)
    sb = _bshape(np.asarray(shift, dtype=np.float64), wide.ndim)
    if from_repr == "shifted":
        wide = wide + sb
    if to_repr == "shifted":
        wide = wide - sb
    return wide.astype(np.dtype(dtype))
