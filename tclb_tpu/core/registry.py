"""Model registry DSL — the TPU-native equivalent of the reference R DSL.

The reference describes every physical model with R calls (``AddDensity``,
``AddSetting``, ``AddGlobal``, ``AddQuantity``, ``AddNodeType``, ``AddStage``,
``AddAction`` — reference src/conf.R:104-339) and derives from them the
node-type bit packing (src/conf.R:391-447), the settings table and the kernel
dispatch table.  Here the same vocabulary is a set of Python dataclasses
collected by :class:`ModelDef` and frozen into a :class:`Model`, which the
lattice engine (core/lattice.py) consumes.  There is no code generation step:
models are ordinary traced JAX functions, specialized by ``jax.jit``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import numpy as np

# --------------------------------------------------------------------------- #
# Specs
# --------------------------------------------------------------------------- #


@dataclass(frozen=True)
class Density:
    """A per-node stored & streamed variable (reference AddDensity, conf.R:104).

    ``dx,dy,dz`` is the streaming vector: during the streaming step the value
    at node ``x`` is pulled from ``x - (dx,dy,dz)`` (pull scheme, reference
    src/LatticeAccess.inc.cpp.Rt).  A density with a zero vector is stored but
    not moved (the reference uses those for coupling buffers, e.g. d2q9's
    ``BC[0]``, src/d2q9/Dynamics.R:18-20).
    """

    name: str
    dx: int = 0
    dy: int = 0
    dz: int = 0
    group: str = ""
    comment: str = ""
    average: bool = False       # participates in running averages (<Average>)
    parameter: bool = False     # is a design variable (adjoint optimization)


@dataclass(frozen=True)
class Field:
    """A stored, non-streamed array with a declared access stencil
    (reference AddField, conf.R:134).  Models read neighbors of a Field with
    ``ctx.load(name, dx, dy, dz)``; the declared ranges bound the halo width.
    """

    name: str
    dx_range: tuple[int, int] = (0, 0)
    dy_range: tuple[int, int] = (0, 0)
    dz_range: tuple[int, int] = (0, 0)
    group: str = ""
    comment: str = ""
    average: bool = False
    parameter: bool = False


@dataclass(frozen=True)
class Setting:
    """A scalar (or zonal) runtime parameter (reference AddSetting, conf.R:167).

    ``derived`` maps *other* setting names to functions of this setting's
    value: assigning this setting also assigns those (the reference expresses
    this as e.g. ``AddSetting(name="nu", omega='1.0/(3*nu+0.5)')``,
    src/d2q9/Dynamics.R:38).
    """

    name: str
    default: float = 0.0
    unit: str = "1"
    zonal: bool = False
    comment: str = ""
    derived: tuple[tuple[str, Callable[[float], float]], ...] = ()


@dataclass(frozen=True)
class GlobalSpec:
    """A monitored/optimized global integral (reference AddGlobal, conf.R:203).

    ``op`` is the reduction: "SUM" or "MAX".  Each global also implies an
    ``<name>InObj`` setting — its weight in the scalar objective (reference
    Lattice.cu.Rt:1113-1129)."""

    name: str
    op: str = "SUM"
    unit: str = "1"
    comment: str = ""


@dataclass(frozen=True)
class Quantity:
    """An exportable derived field (reference AddQuantity, conf.R:222)."""

    name: str
    unit: str = "1"
    vector: bool = False
    adjoint: bool = False
    comment: str = ""


@dataclass(frozen=True)
class NodeTypeSpec:
    name: str
    group: str


@dataclass(frozen=True)
class NodeType:
    """A packed node-type constant: ``(flags & mask) == value`` tests membership
    (reference packing algorithm at src/conf.R:391-447)."""

    name: str
    group: str
    value: int
    mask: int
    shift: int
    index: int


@dataclass(frozen=True)
class Stage:
    """One kernel pass (reference AddStage, conf.R:290).  ``main`` is the name
    of the model function run by the pass; ``load_densities`` controls whether
    streamed reads happen (Init stages don't stream)."""

    name: str
    main: str
    load_densities: bool = True
    save_fields: bool = True
    fixed_point: bool = False


# Default node types every model gets (reference src/conf.R:263-286).
_DEFAULT_NODE_TYPES: tuple[tuple[str, str], ...] = (
    ("BGK", "COLLISION"),
    ("MRT", "COLLISION"),
    ("Wall", "BOUNDARY"),
    ("Solid", "BOUNDARY"),
    ("WVelocity", "BOUNDARY"),
    ("WPressure", "BOUNDARY"),
    ("WPressureL", "BOUNDARY"),
    ("EPressure", "BOUNDARY"),
    ("EVelocity", "BOUNDARY"),
    ("Inlet", "OBJECTIVE"),
    ("Outlet", "OBJECTIVE"),
    ("DesignSpace", "DESIGNSPACE"),
)

FLAG_BITS = 16  # the reference's flag_t is a 16-bit bitfield (src/types.h:14)


class ModelDef:
    """Mutable builder mirroring the reference DSL registration phase."""

    def __init__(self, name: str, ndim: int = 2, description: str = ""):
        self.name = name
        self.ndim = ndim
        self.description = description or name
        self.densities: list[Density] = []
        self.fields: list[Field] = []
        self.settings: list[Setting] = []
        self.globals_: list[GlobalSpec] = []
        self.quantities: list[Quantity] = []
        self._node_type_specs: list[NodeTypeSpec] = [
            NodeTypeSpec(n, g) for n, g in _DEFAULT_NODE_TYPES
        ]
        self.stages: list[Stage] = []
        self.actions: dict[str, tuple[str, ...]] = {}

    # -- registration API (names mirror the reference DSL) ----------------- #

    def add_density(self, name: str, dx: int = 0, dy: int = 0, dz: int = 0,
                    group: str = "", comment: str = "", average: bool = False,
                    parameter: bool = False) -> None:
        if not group:
            group = name.split("[")[0]
        self.densities.append(
            Density(name, dx, dy, dz, group, comment, average, parameter))

    def add_densities(self, base: str, e: Sequence[Sequence[int]],
                      group: str = "", **kw: Any) -> None:
        """Register a family ``base[i]`` with streaming vectors ``e[i]``."""
        for i, v in enumerate(e):
            v = tuple(v) + (0,) * (3 - len(v))
            self.add_density(f"{base}[{i}]", *v, group=group or base, **kw)

    def add_field(self, name: str, dx: Any = 0, dy: Any = 0, dz: Any = 0,
                  group: str = "", comment: str = "", average: bool = False,
                  parameter: bool = False) -> None:
        def _rng(r: Any) -> tuple[int, int]:
            if isinstance(r, (tuple, list)):
                return (int(min(r)), int(max(r)))
            return (min(0, int(r)), max(0, int(r)))
        if not group:
            group = name.split("[")[0]
        self.fields.append(Field(name, _rng(dx), _rng(dy), _rng(dz), group,
                                 comment, average, parameter))

    def add_setting(self, name: str, default: float = 0.0, unit: str = "1",
                    zonal: bool = False, comment: str = "",
                    derived: Optional[dict[str, Callable[[float], float]]] = None
                    ) -> None:
        d = tuple(sorted((derived or {}).items()))
        self.settings.append(Setting(name, float(default), unit, zonal, comment, d))

    def add_global(self, name: str, op: str = "SUM", unit: str = "1",
                   comment: str = "") -> None:
        assert op in ("SUM", "MAX"), op
        self.globals_.append(GlobalSpec(name, op, unit, comment))

    def add_quantity(self, name: str, unit: str = "1", vector: bool = False,
                     adjoint: bool = False, comment: str = "") -> None:
        self.quantities.append(Quantity(name, unit, vector, adjoint, comment))

    def add_node_type(self, name: str, group: str) -> None:
        self._node_type_specs.append(NodeTypeSpec(name, group))

    def add_stage(self, name: str, main: str = "", load_densities: bool = True,
                  save_fields: bool = True, fixed_point: bool = False) -> None:
        self.stages.append(
            Stage(name, main or name, load_densities, save_fields, fixed_point))

    def add_action(self, name: str, stages: Sequence[str]) -> None:
        self.actions[name] = tuple(stages)

    # -- finalize ----------------------------------------------------------- #

    def finalize(self) -> "Model":
        # Default stages/actions (reference src/conf.R:350-363): every model
        # has an Iteration action running the "Run" stage and an Init action.
        stages = list(self.stages)
        actions = dict(self.actions)
        if "Iteration" not in actions:
            actions["Iteration"] = ("BaseIteration",)
        if "Init" not in actions:
            actions["Init"] = ("BaseInit",)
        names = {s.name for s in stages}
        if "BaseIteration" in {st for a in actions.values() for st in a} \
                and "BaseIteration" not in names:
            stages.append(Stage("BaseIteration", "Run", True, True))
        if "BaseInit" in {st for a in actions.values() for st in a} \
                and "BaseInit" not in names:
            stages.append(Stage("BaseInit", "Init", False, True))
        return Model(self, stages, actions)


def _pack_node_types(specs: Sequence[NodeTypeSpec]) -> tuple[dict, dict, int, int]:
    """Pack node-type groups into a 16-bit flag.

    Same algorithm as the reference (src/conf.R:391-447): groups are laid out
    in alphabetical order; a group with n members occupies ceil(log2(n+1))
    bits holding values 1..n; remaining high bits are the settings-zone index.
    Returns (types, group_masks, zone_shift, zone_bits).
    """
    seen: dict[str, list[str]] = {}
    for s in specs:
        seen.setdefault(s.group, [])
        if s.name not in seen[s.group]:
            seen[s.group].append(s.name)
    types: dict[str, NodeType] = {}
    group_masks: dict[str, int] = {}
    shift = 0
    for group in sorted(seen):
        members = seen[group]
        bits = math.ceil(math.log2(len(members) + 1))
        mask = ((1 << bits) - 1) << shift
        group_masks[group] = mask
        for i, name in enumerate(members, start=1):
            types[name] = NodeType(name, group, i << shift, mask, shift, i)
        shift += bits
    if shift > FLAG_BITS:
        raise ValueError(
            f"node types need {shift} bits; flag is {FLAG_BITS}-bit")
    zone_shift = shift
    zone_bits = FLAG_BITS - shift
    group_masks["SETTINGZONE"] = ((1 << zone_bits) - 1) << zone_shift
    types["DefaultZone"] = NodeType("DefaultZone", "SETTINGZONE", 0,
                                    group_masks["SETTINGZONE"], zone_shift, 1)
    types["None"] = NodeType("None", "NONE", 0, 0, 0, 1)
    group_masks["ALL"] = (1 << FLAG_BITS) - 1
    return types, group_masks, zone_shift, zone_bits


class Model:
    """Frozen model metadata consumed by the lattice engine.

    Physics callables are attached by the model module via
    :meth:`bind` — ``run``/``init`` operate on a :class:`~tclb_tpu.core.lattice.NodeCtx`.
    """

    def __init__(self, d: ModelDef, stages: list[Stage],
                 actions: dict[str, tuple[str, ...]]):
        self.name = d.name
        self.ndim = d.ndim
        self.description = d.description
        self.densities = tuple(d.densities)
        self.fields = tuple(d.fields)
        self.settings = tuple(d.settings)
        self.globals_ = tuple(d.globals_)
        self.quantities = tuple(d.quantities)
        self.stages = {s.name: s for s in stages}
        self.actions = dict(actions)

        # storage layout: densities first, then fields, one plane each
        self.storage_names = tuple([x.name for x in self.densities]
                                   + [x.name for x in self.fields])
        self.storage_index = {n: i for i, n in enumerate(self.storage_names)}
        self.n_storage = len(self.storage_names)
        # streaming vectors, zero-padded for fields
        ei = [(x.dx, x.dy, x.dz) for x in self.densities] \
            + [(0, 0, 0) for _ in self.fields]
        self.ei = np.array(ei, dtype=np.int32)

        # group -> ordered storage indices (densities and fields share groups)
        groups: dict[str, list[int]] = {}
        for i, x in enumerate(list(self.densities) + list(self.fields)):
            groups.setdefault(x.group, []).append(i)
        self.groups = {g: tuple(ix) for g, ix in groups.items()}

        # settings layout; every Global implies an "<name>InObj" weight setting
        # (reference src/conf.R:212-216)
        settings = list(self.settings)
        have = {s.name for s in settings}
        for g in self.globals_:
            if g.name + "InObj" not in have:
                settings.append(Setting(g.name + "InObj", 0.0, "1", False,
                                        f"weight of {g.name} in objective"))
        self.settings = tuple(settings)
        self.setting_index = {s.name: i for i, s in enumerate(self.settings)}
        self.setting_defaults = np.array([s.default for s in self.settings],
                                         dtype=np.float64)
        self.zonal_settings = tuple(s.name for s in self.settings if s.zonal)

        self.global_index = {g.name: i for i, g in enumerate(self.globals_)}
        self.n_globals = len(self.globals_)

        (self.node_types, self.group_masks,
         self.zone_shift, self.zone_bits) = _pack_node_types(d._node_type_specs)
        self.zone_max = 1 << self.zone_bits

        # physics callables, bound by the model module
        self.run: Optional[Callable] = None
        self.init: Optional[Callable] = None
        self.quantity_fns: dict[str, Callable] = {}
        self.stage_fns: dict[str, Callable] = {}
        self.max_stencil = int(np.max(np.abs(self.ei))) if len(ei) else 1
        for f in self.fields:
            for lo, hi in (f.dx_range, f.dy_range, f.dz_range):
                self.max_stencil = max(self.max_stencil, abs(lo), abs(hi))

    # -- structural identity ------------------------------------------------ #

    def structural_key(self) -> tuple:
        """A hashable tuple of everything the kernel engines specialize on:
        storage layout, streaming vectors, declared stencils, settings
        (zonal-ness + derived targets), globals, node-type packing and the
        stage/action plan.  Two independently built instances of the same
        model compare equal, so caches keyed on this survive model rebuilds
        — unlike ``id(model)`` keys, which both alias recycled addresses
        and miss rebuilt-but-identical models."""
        return (
            self.name, self.ndim,
            tuple((x.name, x.dx, x.dy, x.dz, x.group, x.average,
                   x.parameter) for x in self.densities),
            tuple((x.name, x.dx_range, x.dy_range, x.dz_range, x.group,
                   x.average, x.parameter) for x in self.fields),
            tuple((s.name, s.default, s.zonal,
                   tuple(t for t, _ in s.derived)) for s in self.settings),
            tuple((g.name, g.op) for g in self.globals_),
            tuple((t.name, t.group, t.value, t.mask, t.shift)
                  for t in self.node_types.values()),
            tuple((s.name, s.main, s.load_densities, s.save_fields,
                   s.fixed_point) for s in self.stages.values()),
            tuple((a, tuple(st)) for a, st in sorted(self.actions.items())),
        )

    @property
    def fingerprint(self) -> str:
        """Short stable hex digest of :meth:`structural_key`."""
        if getattr(self, "_fingerprint", None) is None:
            import hashlib
            raw = repr(self.structural_key()).encode()
            self._fingerprint = hashlib.sha1(raw).hexdigest()[:16]
        return self._fingerprint

    # -- binding physics ---------------------------------------------------- #

    def bind(self, run: Callable = None, init: Callable = None,
             quantities: Optional[dict[str, Callable]] = None,
             stages: Optional[dict[str, Callable]] = None) -> "Model":
        self.run = run
        self.init = init
        if quantities:
            self.quantity_fns.update(quantities)
        self.stage_fns = {"Run": run, "Init": init}
        if stages:
            self.stage_fns.update(stages)
        return self

    # -- node-type helpers -------------------------------------------------- #

    def nt_value(self, name: str) -> int:
        return self.node_types[name].value

    def group_mask(self, group: str) -> int:
        return self.group_masks[group]

    def flag_for(self, *names: str, zone: int = 0) -> int:
        """Compose a flag value from node-type names + a settings-zone index
        (what the geometry painter writes into the flag field)."""
        v = 0
        for n in names:
            v |= self.node_types[n].value
        return v | (zone << self.zone_shift)

    def settings_vector(self, values: Optional[dict[str, float]] = None
                        ) -> np.ndarray:
        """Defaults + user values, with derived-setting propagation
        (reference src/Lattice.cu.Rt:1164-1191)."""
        vec = self.setting_defaults.copy()
        # propagate defaults through derived chains recursively, in
        # declaration order, so later defaults (e.g. nu) re-derive earlier
        # targets (omega, then S78) consistently
        for s in self.settings:
            self._set_with_derived(vec, s.name, vec[self.setting_index[s.name]])
        for k, v in (values or {}).items():
            self._set_with_derived(vec, k, float(v))
        return vec

    def _set_with_derived(self, vec: np.ndarray, name: str, value: float) -> None:
        if name not in self.setting_index:
            raise KeyError(f"model {self.name} has no setting {name!r}; "
                           f"has: {sorted(self.setting_index)}")
        vec[self.setting_index[name]] = value
        for s in self.settings:
            if s.name == name:
                for target, fn in s.derived:
                    self._set_with_derived(vec, target, fn(value))
