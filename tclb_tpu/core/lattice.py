"""Lattice engine: state, streaming, per-stage step, iteration.

TPU-native re-design of the reference lattice engine (reference
src/Lattice.cu.Rt, src/LatticeContainer.inc.cpp.Rt, src/cuda.cu.Rt):

* the reference's double-buffered ``FTabs`` snapshots + 27 margin blocks
  become a single dense ``(n_storage, *shape)`` array per state; streaming is
  a functional pull (``jnp.roll`` — periodic like the reference's wrapped
  margins), so double buffering is XLA's problem (donated buffers), not ours;
* the reference's per-(operation x globals x stage) generated kernel zoo
  (src/cuda.cu.Rt:81-283) becomes ONE traced step function per stage,
  specialized by ``jax.jit``;
* per-node ``switch (NodeType & NODE_BOUNDARY)`` dispatch
  (src/d2q9/Dynamics.c.Rt:121-150) becomes mask/select algebra on the flag
  field — branchless, which is exactly what the VPU wants;
* globals accumulated with shared-memory trees + atomics
  (src/cuda.cu.Rt:176-202) become masked ``jnp.sum``/``max`` reductions
  (deterministic, unlike the reference's atomic order).

The engine is pure-functional: ``step(state, params) -> state`` is jittable,
differentiable (the adjoint path — reference Tapenade machinery, tools/makeAD)
and shardable (parallel/halo.py wraps it in ``shard_map``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from tclb_tpu.core import shift as ddf
from tclb_tpu.core.registry import Model
from tclb_tpu import telemetry

FLAG_DTYPE = jnp.uint16


@struct.dataclass
class SimParams:
    """Runtime parameters: the reference's GPU-const-memory settings
    (src/LatticeContainer.inc.cpp.Rt:32-55) + zonal setting tables (C7,
    src/ZoneSettings.h).  ``zone_table[s, z]`` is the value of setting ``s``
    in settings-zone ``z``; non-zonal settings read ``settings[s]``.

    Time-dependent zonal settings (the reference's per-(setting, zone)
    time tables, src/ZoneSettings.h:9-120) live in ``time_series``: row
    ``r`` of the ``(n_series, T)`` array is the per-iteration value of the
    (setting, zone) pair recorded in the static ``series_map`` as
    ``(setting_index, zone, r)``.  At iteration ``t`` the effective value is
    ``time_series[r, t % T]``, overriding ``zone_table``.  Gradients with
    respect to ``time_series`` are the reference's GRAD planes (control
    gradients) — free here because the whole step is differentiable."""

    settings: jnp.ndarray        # (n_settings,) real
    zone_table: jnp.ndarray      # (n_settings, zone_max) real
    time_series: Optional[jnp.ndarray] = None   # (n_series, T) real
    series_map: tuple = struct.field(pytree_node=False, default=())


@struct.dataclass
class LatticeState:
    """The complete per-step lattice state (a pytree — one pytree per
    reference ``FTabs`` snapshot)."""

    fields: jnp.ndarray          # (n_storage, *shape) real
    flags: jnp.ndarray           # (*shape) uint16 node-type bitfield
    globals_: jnp.ndarray        # (n_globals,) per-iteration integrals
    iteration: jnp.ndarray       # () int32


# --------------------------------------------------------------------------- #
# Streaming
# --------------------------------------------------------------------------- #


def pull_stream(model: Model, fields: jnp.ndarray) -> jnp.ndarray:
    """Pull-scheme streaming: plane ``i`` at node ``x`` receives the value
    stored at ``x - e_i`` (reference pull streaming,
    src/LatticeAccess.inc.cpp.Rt:182-263).  Periodic wrap — the reference's
    global domain is periodic through its margin wiring; walls are painted.

    ``jnp.roll(a, s)[x] == a[x - s]``, so rolling plane ``i`` by ``e_i``
    is exactly the pull.  Zero-vector planes are left untouched.
    """
    ndim = model.ndim
    out = []
    for i in range(model.n_storage):
        dx, dy, dz = (int(v) for v in model.ei[i])
        plane = fields[i]
        shifts, axes = [], []
        # axis layout: (..., z, y, x) — x is last (TPU lane dimension)
        for shift, axis in ((dz, -3), (dy, -2), (dx, -1)):
            if shift and (ndim >= -axis):
                shifts.append(shift)
                axes.append(axis)
        if shifts:
            plane = jnp.roll(plane, shifts, axes)
        out.append(plane)
    return jnp.stack(out)


class Streaming:
    """Streaming strategy: how pulled densities and neighbor Field loads are
    realized.  This default implements the single-device / global-array case
    (periodic roll).  The sharded engine substitutes
    :class:`tclb_tpu.parallel.halo.HaloStreaming`, which fetches halos over
    the mesh — injecting the strategy here keeps model code identical in both
    worlds (the reference achieves the same with its margin-block pointer
    rewiring, src/Lattice.cu.Rt:399-410)."""

    def __init__(self, model: Model):
        self.model = model

    def pull(self, fields: jnp.ndarray) -> jnp.ndarray:
        return pull_stream(self.model, fields)

    def make_loader(self, raw: jnp.ndarray) -> Callable:
        """Return ``load(index, dx, dy, dz)`` giving the ``x + d`` neighbor
        of storage plane ``index``."""
        ndim = self.model.ndim

        def load(index: int, dx: int, dy: int, dz: int) -> jnp.ndarray:
            plane = raw[index]
            shifts, axes = [], []
            for shift, axis in ((dz, -3), (dy, -2), (dx, -1)):
                if shift and (ndim >= -axis):
                    shifts.append(-shift)
                    axes.append(axis)
            return jnp.roll(plane, shifts, axes) if shifts else plane

        return load


# --------------------------------------------------------------------------- #
# Node context — what a model's Run()/Init() sees
# --------------------------------------------------------------------------- #


def series_overrides(params: SimParams, i: int, iteration) -> list:
    """``[(zone, value)]`` scalar overrides of setting ``i`` from its
    registered <Control> time series at ``iteration`` (mod-T wrap);
    empty without a series.  Shared by NodeCtx.setting and the fast
    engines' per-step aux planes — one implementation, no drift.

    Returned as per-zone SCALARS to be applied with
    ``jnp.where(zones == z, value, plane)`` against a loop-invariant
    base plane: modifying the zone TABLE and re-gathering per step keeps
    a (zone_max,)->(ny,nx) gather inside the iteration scan, which XLA
    cannot hoist and lowers catastrophically (~25 ms/step at 1024^2 on
    v5e); masked selects against the hoisted base plane are free."""
    rows = [(z, r) for (si, z, r) in params.series_map if si == i]
    if not rows or params.time_series is None:
        return []
    T = params.time_series.shape[1]
    t = jnp.mod(jnp.asarray(iteration, jnp.int32), T)
    return [(z, params.time_series[r, t]) for z, r in rows]


def series_dt_overrides(params: SimParams, i: int, iteration) -> list:
    """``[(zone, d/dt value)]`` for setting ``i``'s series: one-sided
    central differences clamped at the horizon endpoints (the finite
    control horizon is not periodic — a wrapped difference would mix the
    two ends into a spurious spike); empty without a series."""
    rows = [(z, r) for (si, z, r) in params.series_map if si == i]
    if not rows or params.time_series is None:
        return []
    ts = params.time_series
    T = ts.shape[1]
    t = jnp.mod(jnp.asarray(iteration, jnp.int32), T)
    lo = jnp.maximum(t - 1, 0)
    hi = jnp.minimum(t + 1, T - 1)
    span = jnp.maximum(hi - lo, 1).astype(ts.dtype)
    return [(z, (ts[r, hi] - ts[r, lo]) / span) for z, r in rows]


class NodeCtx:
    """The model-facing view of one lattice-wide kernel invocation.

    Plays the role of the reference's generated node object (``Node_Run`` with
    its pop'ed density locals, settings in const memory and NodeType register,
    src/cuda.cu.Rt:236-274) — but vectorized over the whole (local) lattice:
    every accessor returns full planes, and "per-node dispatch" is mask
    algebra via :meth:`nt_is` / :meth:`boundary_case`.
    """

    def __init__(self, model: Model, fields: jnp.ndarray, raw: jnp.ndarray,
                 flags: jnp.ndarray, params: SimParams,
                 loader: Optional[Callable] = None,
                 iteration: Any = 0, avg_start: Any = 0,
                 present: Optional[set] = None,
                 compute_globals: bool = True):
        self.model = model
        self._fields = fields      # pulled (streamed) storage
        self._raw = raw            # un-streamed storage (for Field loads)
        self._loader = loader or Streaming(model).make_loader(raw)
        self.flags = flags
        self.params = params
        self.iteration = iteration
        self.avg_start = avg_start
        self._globals: dict[str, jnp.ndarray] = {}
        self._zone_ids = None
        # static specialization knobs (the reference compiles its kernels
        # per model boundary set and per Globals mode, src/cuda.cu.Rt:81):
        # `present` skips boundary cases whose node types are not painted;
        # `compute_globals=False` is the NoGlobals kernel flavor
        self.present = present
        self.compute_globals = compute_globals

    def avg_samples(self) -> jnp.ndarray:
        """Iterations accumulated into the running averages since the last
        <Average> reset (reference ``iter - reset_iter``); at least 1."""
        n = jnp.asarray(self.iteration) - jnp.asarray(self.avg_start)
        return jnp.maximum(n.astype(self._fields.dtype), 1.0)

    # -- field access ------------------------------------------------------- #

    def group(self, name: str) -> jnp.ndarray:
        """Streamed stack of all densities in a group: shape (n, *shape)."""
        idx = self.model.groups[name]
        return self._fields[jnp.array(idx)] if len(idx) > 1 \
            else self._fields[idx[0]][None]

    def density(self, name: str) -> jnp.ndarray:
        return self._fields[self.model.storage_index[name]]

    def load(self, name: str, dx: int = 0, dy: int = 0, dz: int = 0
             ) -> jnp.ndarray:
        """Neighbor access to a stored Field: value at ``x + (dx,dy,dz)``
        (reference ``load_<field><DX,DY,DZ>``,
        src/LatticeAccess.inc.cpp.Rt:266-292).  Goes through the injected
        streaming strategy so sharded runs fetch across shard boundaries."""
        return self._loader(self.model.storage_index[name], dx, dy, dz)

    def store(self, groups: dict[str, jnp.ndarray]) -> dict:
        """Declare the stage's write set: group/plane name -> new stack
        (the reference's push_<Stage> writes,
        src/LatticeAccess.inc.cpp.Rt:216-225, restricted to the stage's
        ``save`` set, AddStage in src/conf.R:290).  The engine writes ONLY
        these planes back into storage; unmentioned planes keep their
        previous (un-streamed) value — which equals the streamed value for
        every zero-velocity plane, and saves the HBM write for
        never-changing planes (BC buffers, coupling fields, cut
        distances)."""
        return groups

    # -- settings ----------------------------------------------------------- #

    def setting(self, name: str) -> jnp.ndarray:
        """Scalar for plain settings; per-node plane for zonal settings
        (gathered through the flag's zone bits — reference ``ZoneSetting()``
        device accessor, src/LatticeContainer.h.Rt:89-108).  Zones with a
        registered time series (``<Control>``) read the current iteration's
        entry instead of the constant table."""
        m = self.model
        i = m.setting_index[name]
        spec = m.settings[i]
        if not spec.zonal:
            return self.params.settings[i]
        plane = self.params.zone_table[i][self._zones()]
        for z, v in series_overrides(self.params, i, self.iteration):
            plane = jnp.where(self._zones() == z,
                              v.astype(plane.dtype), plane)
        return plane

    def setting_dt(self, name: str) -> jnp.ndarray:
        """Time derivative of a zonal setting: central difference over its
        time series (reference ``<setting>_DT`` planes, the ``set_internal``
        derivative at src/ZoneSettings.h:102-119); zero where no series.
        One-sided differences at the series endpoints — the series is a
        finite control horizon, not periodic, so a wrapped central
        difference would mix the two ends into a spurious spike."""
        m = self.model
        i = m.setting_index[name]
        plane = jnp.zeros(self.flags.shape, dtype=self._fields.dtype)
        for z, v in series_dt_overrides(self.params, i, self.iteration):
            plane = jnp.where(self._zones() == z,
                              v.astype(plane.dtype), plane)
        return plane

    def _zones(self) -> jnp.ndarray:
        if self._zone_ids is None:
            self._zone_ids = (self.flags.astype(jnp.int32)
                              >> self.model.zone_shift)
        return self._zone_ids

    # -- node types --------------------------------------------------------- #

    def nt_is(self, name: str) -> jnp.ndarray:
        """Bool plane: node's group-field equals this node type."""
        t = self.model.node_types[name]
        return (self.flags & FLAG_DTYPE(t.mask)) == FLAG_DTYPE(t.value)

    def nt_in_group(self, group: str) -> jnp.ndarray:
        m = self.model.group_masks[group]
        return (self.flags & FLAG_DTYPE(m)) != FLAG_DTYPE(0)

    def boundary_case(self, f: jnp.ndarray,
                      cases: dict[str, Callable[[jnp.ndarray], jnp.ndarray]]
                      ) -> jnp.ndarray:
        """Vectorized ``switch (NodeType & NODE_<group>)``: each case function
        maps the full stack to a modified stack; nodes whose group-field
        equals the named type select that case's result, others keep ``f``
        (each node type carries its own group mask).  Multiple names may
        share a function by passing a tuple key."""
        out = f
        for names, fn in cases.items():
            if isinstance(names, str):
                names = (names,)
            if self.present is not None:
                names = tuple(n for n in names if n in self.present)
                if not names:
                    continue   # type not painted: skip the whole case
            mask = self.nt_is(names[0])
            for n in names[1:]:
                mask = mask | self.nt_is(n)
            out = jnp.where(mask[None], fn(f), out)
        return out

    # -- globals ------------------------------------------------------------ #

    def add_global(self, name: str, plane: jnp.ndarray,
                   where: Optional[jnp.ndarray] = None) -> None:
        """Accumulate a per-node contribution to a Global (reference
        ``AddTo<Global>`` + atomic reduction, src/cuda.cu.Rt:130-202).
        ``where`` masks contributing nodes (e.g. objective node types)."""
        if not self.compute_globals:
            return
        if where is not None:
            plane = jnp.where(where, plane, jnp.zeros_like(plane))
        if name in self._globals:
            self._globals[name] = self._globals[name] + plane
        else:
            self._globals[name] = plane

    def reduce_globals(self) -> jnp.ndarray:
        m = self.model
        out = jnp.zeros((m.n_globals,),
                        dtype=self._fields.dtype)
        for name, plane in self._globals.items():
            g = m.globals_[m.global_index[name]]
            red = jnp.max(plane) if g.op == "MAX" else jnp.sum(plane)
            out = out.at[m.global_index[name]].set(red)
        return out


# --------------------------------------------------------------------------- #
# Step / iterate
# --------------------------------------------------------------------------- #


def make_stage_step(model: Model, stage_name: str,
                    streaming: Optional[Streaming] = None,
                    present: Optional[set] = None,
                    compute_globals: bool = True) -> Callable:
    """Build the pure step function for one stage (the reference compiles a
    ``Node_Run`` kernel per stage, src/cuda.cu.Rt:209-283; we trace one).

    ``streaming`` injects the streaming strategy (pull + neighbor loads):
    default is the global periodic roll; the sharded engine
    (parallel/halo.py) injects a halo-exchange strategy instead.

    ``present``/``compute_globals`` specialize the trace the way the
    reference specializes its kernel zoo (per boundary set and per
    Globals mode): absent node types skip their full-lattice boundary
    case, and the NoGlobals flavor skips every reduction."""
    stage = model.stages[stage_name]
    fn = model.stage_fns[stage.main]
    if fn is None:
        raise ValueError(f"model {model.name}: stage {stage_name} has no "
                         f"bound function {stage.main!r}")
    if streaming is None:
        streaming = Streaming(model)

    def step(state: LatticeState, params: SimParams) -> LatticeState:
        # full-f32 matmuls: on TPU, einsum/tensordot otherwise default to
        # bf16 MXU passes, and bf16's 8 mantissa bits destroy the moment
        # transforms (the d2q9 Karman case visibly diverges by iteration
        # ~100).  LBM is bandwidth-bound — exact matmuls cost nothing
        # measurable.  Scoped here, not via global config, so importing the
        # framework never changes precision for unrelated user code.
        with jax.default_matmul_precision("highest"):
            return _step_inner(state, params)

    def _step_inner(state: LatticeState, params: SimParams) -> LatticeState:
        raw = state.fields
        pulled = streaming.pull(raw) if stage.load_densities else raw
        ctx = NodeCtx(model, pulled, raw, state.flags, params,
                      loader=streaming.make_loader(raw),
                      iteration=state.iteration,
                      present=present, compute_globals=compute_globals)
        new_fields = fn(ctx)
        # A stage returns its write set as a dict (group or plane name ->
        # stack/plane): only the named planes are saved, everything else
        # keeps its UN-streamed storage — the reference's per-stage save
        # set (AddStage save=..., src/conf.R:290; e.g. d2q9_kuper's
        # CalcPhi saves only phi while reading streamed f).  This is the
        # cheap half of the 1R+1W traffic story: never-changing planes
        # (BC buffers, SynthT, cut distances) are not rewritten per step.
        # A full-array return still means "replace the whole stack".
        if isinstance(new_fields, dict):
            buf = raw
            for name, stack in new_fields.items():
                if name in model.groups:
                    idx = model.groups[name]
                    if len(idx) == 1:
                        plane = stack[0] if stack.ndim > buf.ndim - 1 \
                            else stack
                        buf = buf.at[idx[0]].set(plane)
                    else:
                        buf = buf.at[jnp.array(idx)].set(stack)
                else:
                    buf = buf.at[model.storage_index[name]].set(stack)
            new_fields = buf
        # Solid/Wall nodes keep the engine's semantics from the model's Run();
        # nothing special here — BCs are the model's job via ctx.boundary_case.
        # Globals accumulate across the stages of one action (the reference
        # clears the GPU globals buffer at iteration start and every stage's
        # kernels atomically add into it, src/Lattice.cu.Rt:383-461);
        # make_action_step zeroes the buffer before its first stage, so a
        # trailing non-global stage (e.g. kuper's CalcPhi) no longer wipes
        # the objectives the Run stage just computed.  SUM globals add;
        # MAX globals combine with max (the reference's atomicMax path,
        # src/cross.h:104-132) — adding per-stage maxima would double-count.
        if not compute_globals:
            return LatticeState(
                fields=new_fields, flags=state.flags,
                globals_=state.globals_, iteration=state.iteration)
        stage_globals = ctx.reduce_globals()
        max_rows = [i for i, g in enumerate(model.globals_) if g.op == "MAX"]
        if max_rows:
            is_max = jnp.zeros((model.n_globals,), dtype=bool
                               ).at[jnp.array(max_rows)].set(True)
            combined = jnp.where(is_max,
                                 jnp.maximum(state.globals_, stage_globals),
                                 state.globals_ + stage_globals)
        else:
            combined = state.globals_ + stage_globals
        return LatticeState(
            fields=new_fields,
            flags=state.flags,
            globals_=combined,
            iteration=state.iteration,
        )

    return step


def make_action_step(model: Model, action: str = "Iteration",
                     streaming: Optional[Streaming] = None,
                     present: Optional[set] = None,
                     compute_globals: bool = True) -> Callable:
    """Compose an action's stages into one step (reference Actions,
    src/conf.R:339 + the per-stage loop in Lattice::Iteration,
    src/Lattice.cu.Rt:414-457)."""
    steps = [make_stage_step(model, s, streaming, present=present,
                             compute_globals=compute_globals)
             for s in model.actions[action]]
    # one action == one lattice iteration (when it streams at all):
    # the counter advances once per action, not per stage
    advances = any(model.stages[s].load_densities
                   for s in model.actions[action])

    def step(state: LatticeState, params: SimParams) -> LatticeState:
        if compute_globals:
            state = state.replace(globals_=jnp.zeros_like(state.globals_))
        for s in steps:
            state = s(state, params)
        if advances:
            state = state.replace(iteration=state.iteration + 1)
        return state

    return step


def make_iterate(model: Model, action: str = "Iteration",
                 unroll: int = 1,
                 streaming: Optional[Streaming] = None,
                 present: Optional[set] = None,
                 storage_dtype: Any = None,
                 storage_shift: Optional[np.ndarray] = None) -> Callable:
    """niter-step loop as a ``lax.scan`` (reference Lattice::Iterate,
    src/Lattice.cu.Rt:780-869).  Differentiable; wrap with ``jax.checkpoint``
    policies for long-horizon adjoints (reference SnapLevel tape,
    src/Lattice.cu.Rt:34-49).

    ``iterate``'s contract is "globals_ = the LAST step's integrals"
    (each action step zeroes them), so the first niter-1 steps run the
    NoGlobals specialization — the reductions are pure waste there (the
    reference's Globals-mode template parameter, src/cuda.cu.Rt:81) —
    and only the final step reduces.

    ``storage_dtype`` (precision ladder) narrows the scan CARRY to that
    dtype: each step widens the fields to the compute dtype (taken from
    ``params.settings.dtype``), runs the action, and narrows the result
    back, so the HBM-resident state between steps is genuinely
    ``storage_dtype`` — the same round-trip truncation the Pallas
    engines apply per DMA, which is what the error-vs-f32 harness
    (tclb_tpu/precision.py) must measure.  ``None`` keeps today's exact
    path (the casts never enter the trace).

    ``storage_shift`` (DDF shifting, ``storage_repr="shifted"``) is the
    broadcastable per-plane weight block from
    :func:`tclb_tpu.core.shift.stack_shift`: the narrow carry then
    stores ``f_i - w_i`` and every widen seam restores the shift before
    the physics (f32 accumulation unchanged).  ``None`` = raw
    representation (the seam helpers reduce to pure ``astype``)."""
    step_ng = make_action_step(model, action, streaming, present=present,
                               compute_globals=False)
    step_full = make_action_step(model, action, streaming, present=present,
                                 compute_globals=True)
    sdt = None if storage_dtype is None else jnp.dtype(storage_dtype)
    sb = storage_shift if sdt is not None else None

    def iterate(state: LatticeState, params: SimParams, niter: int
                ) -> LatticeState:
        if niter <= 0:
            return state
        if sdt is None:
            def body(s, _):
                return step_ng(s, params), None
            state, _ = jax.lax.scan(body, state, None, length=niter - 1,
                                    unroll=unroll)
            return step_full(state, params)

        cdt = params.settings.dtype

        def body(s, _):
            out = step_ng(
                s.replace(fields=ddf.widen_stack(s.fields, cdt, sb)),
                params)
            return out.replace(
                fields=ddf.narrow_stack(out.fields, sdt, sb)), None
        state, _ = jax.lax.scan(
            body, state.replace(fields=state.fields.astype(sdt)),
            None, length=niter - 1, unroll=unroll)
        out = step_full(
            state.replace(fields=ddf.widen_stack(state.fields, cdt, sb)),
            params)
        return out.replace(fields=ddf.narrow_stack(out.fields, sdt, sb))

    return iterate


def make_ensemble_step(model: Model, action: str = "Init",
                       present: Optional[set] = None) -> Callable:
    """Batched single-action step for an ensemble of independent cases:
    ``step(states, params) -> states`` over a leading case axis.

    Runs the cases through ``lax.map`` (a scan over the batch), NOT
    ``vmap``: a scan body is compiled as its own isolated computation, so
    the per-case arithmetic clusters exactly like the sequential
    ``jit(step)`` program and the result is bit-identical to running the
    cases one by one — the ensemble contract (serve/ensemble.py).  One
    action per run (Init, a globals-reducing final step) is cheap; the
    niter-step bulk goes through :func:`make_ensemble_iterate` instead."""
    step = make_action_step(model, action, present=present)

    def batched(states: LatticeState, params: SimParams) -> LatticeState:
        return jax.lax.map(lambda sp: step(sp[0], sp[1]), (states, params))

    return batched


def make_ensemble_iterate(model: Model, action: str = "Iteration",
                          unroll: int = 1,
                          present: Optional[set] = None,
                          mode: str = "map",
                          storage_dtype: Any = None,
                          storage_shift: Optional[np.ndarray] = None
                          ) -> Callable:
    """Batched counterpart of :func:`make_iterate`: advance N independent
    cases (stacked ``LatticeState``s + per-case ``SimParams``) in ONE
    device dispatch.

    ``mode="map"`` (default) runs each case's whole niter-step loop as a
    ``lax.map`` body: a map body is compiled as its own isolated
    computation, so the per-case arithmetic clusters exactly like the
    sequential ``jit(make_iterate(...))`` program and the output is
    **bit-identical** to N sequential runs — the ensemble contract
    (serve/ensemble.py).  The throughput win is dispatch/compile
    amortization and cross-case pipelining, not SIMD over the batch.

    ``mode="vmap"`` vmaps the NoGlobals bulk over the case axis inside
    the time scan (XLA vectorizes the whole batch per step) and runs the
    final full-globals step through ``lax.map``.  Faster where the
    per-case work underfills the vector units, but NOT parity-safe in
    general: under a batch dimension XLA:CPU re-clusters some models'
    multiply-add chains (the same re-association ``lbm.pin`` fences
    elsewhere) and drifts fields by 1 ulp — e.g. d2q9_kuper's forcing
    stage on a painted cavity.  Opt in only where throughput beats
    bit-reproducibility.

    ``storage_dtype`` narrows each case's carry between steps exactly
    like :func:`make_iterate`'s precision ladder — the serving tier's
    doubled batch caps come from genuinely bf16-resident ensemble
    state, so the per-step round trip must match the single-case
    engines' truncation.  ``storage_shift`` selects the shifted (DDF)
    representation for that carry, exactly as in :func:`make_iterate`
    (the shift block broadcasts under the leading case axis)."""
    if mode not in ("map", "vmap"):
        raise ValueError(f"ensemble mode must be 'map' or 'vmap', "
                         f"got {mode!r}")
    step_ng = make_action_step(model, action, present=present,
                               compute_globals=False)
    step_full = make_action_step(model, action, present=present,
                                 compute_globals=True)
    sdt = None if storage_dtype is None else jnp.dtype(storage_dtype)
    sb = storage_shift if sdt is not None else None

    def _wrap(step, params):
        if sdt is None:
            return step

        def stepped(st, p=params):
            cdt = p.settings.dtype
            out = step(
                st.replace(fields=ddf.widen_stack(st.fields, cdt, sb)), p)
            return out.replace(fields=ddf.narrow_stack(out.fields, sdt, sb))
        return stepped

    def iterate_map(states: LatticeState, params: SimParams, niter: int
                    ) -> LatticeState:
        if niter <= 0:
            return states

        def one(sp):
            s, p = sp
            ng, fl = _wrap(step_ng, p), _wrap(step_full, p)

            def body(st, _):
                return ng(st, p) if sdt is None else ng(st), None
            s, _ = jax.lax.scan(body, s, None, length=niter - 1,
                                unroll=unroll)
            return fl(s, p) if sdt is None else fl(s)

        return jax.lax.map(one, (states, params))

    def iterate_vmap(states: LatticeState, params: SimParams, niter: int
                     ) -> LatticeState:
        if niter <= 0:
            return states

        if sdt is None:
            def body(s, _):
                return jax.vmap(step_ng)(s, params), None
        else:
            def narrow_step(st, p):
                out = step_ng(
                    st.replace(fields=ddf.widen_stack(
                        st.fields, p.settings.dtype, sb)), p)
                return out.replace(
                    fields=ddf.narrow_stack(out.fields, sdt, sb))

            def body(s, _):
                return jax.vmap(narrow_step)(s, params), None
        states, _ = jax.lax.scan(body, states, None, length=niter - 1,
                                 unroll=unroll)

        def final(sp):
            s, p = sp
            if sdt is None:
                return step_full(s, p)
            out = step_full(
                s.replace(fields=ddf.widen_stack(
                    s.fields, p.settings.dtype, sb)), p)
            return out.replace(fields=ddf.narrow_stack(out.fields, sdt, sb))
        return jax.lax.map(final, (states, params))

    return iterate_map if mode == "map" else iterate_vmap


def make_sampled_iterate(model: Model, points: np.ndarray,
                         quantities: Sequence[str],
                         action: str = "Iteration",
                         streaming: Optional[Streaming] = None) -> Callable:
    """Like :func:`make_iterate` but also gathers the listed quantities at
    fixed lattice points after every step, returned as the scan ys —
    the functional equivalent of the reference Sampler's per-iteration GPU
    ring buffer (reference updateAllSamples, src/Lattice.cu.Rt:1212-1225).

    ``points`` is (npoints, ndim) in array index order (z, y, x / y, x).
    Returns ``iterate(state, params, niter) -> (state, samples)`` with
    samples shaped (niter, npoints, ncols); vector quantities contribute
    their components as consecutive columns.
    """
    step = make_action_step(model, action, streaming)
    idx = tuple(jnp.asarray(points[:, k].astype(np.int32))
                for k in range(points.shape[1]))
    qfns = [(q, model.quantity_fns[q]) for q in quantities]

    def sample(state: LatticeState, params: SimParams,
               avg_start: Any = 0) -> jnp.ndarray:
        ctx = NodeCtx(model, state.fields, state.fields, state.flags, params,
                      iteration=state.iteration, avg_start=avg_start)
        cols = []
        for _, fn in qfns:
            with jax.default_matmul_precision("highest"):
                plane = fn(ctx)
            if plane.ndim == len(state.flags.shape):
                cols.append(plane[idx][:, None])
            else:  # vector: (ncomp, *shape) -> (npoints, ncomp)
                cols.append(plane[(slice(None),) + idx].T)
        return jnp.concatenate(cols, axis=-1)

    def iterate(state: LatticeState, params: SimParams, niter: int,
                avg_start=0):
        def body(s, _):
            s2 = step(s, params)
            return s2, sample(s2, params, avg_start)
        return jax.lax.scan(body, state, None, length=niter)

    return iterate


# --------------------------------------------------------------------------- #
# Host-side Lattice wrapper
# --------------------------------------------------------------------------- #


class Lattice:
    """Host-side convenience wrapper, mirroring the reference ``Lattice``
    class surface (src/Lattice.h.Rt:36-168): allocate, Init, Iterate,
    Get/Set densities, GetQuantity, settings, save/load."""

    def __init__(self, model: Model, shape: Sequence[int],
                 dtype: Any = jnp.float32,
                 settings: Optional[dict[str, float]] = None,
                 mesh: Any = None,
                 storage_dtype: Any = None,
                 storage_repr: Optional[str] = None,
                 device: Any = None):
        if len(shape) != model.ndim:
            raise ValueError(f"model {model.name} is {model.ndim}D; "
                             f"got shape {shape}")
        self.model = model
        self.shape = tuple(int(s) for s in shape)
        self.dtype = dtype
        # precision ladder: ``storage_dtype`` narrows the HBM-resident
        # distribution fields only — every kernel still accumulates in
        # the compute dtype (``dtype``), settings/zone tables/globals
        # stay wide, and flags are untouched.  Strictly OPT-IN: the
        # default is the compute dtype and nothing ever narrows
        # silently.  Validated by the error-vs-reference harness
        # (tclb_tpu/precision.py), not by bit-parity.
        sdt = jnp.dtype(dtype) if storage_dtype is None \
            else jnp.dtype(storage_dtype)
        if sdt != jnp.dtype(dtype):
            if not jnp.issubdtype(sdt, jnp.floating) \
                    or sdt.itemsize > jnp.dtype(dtype).itemsize:
                raise ValueError(
                    f"storage_dtype {sdt} must be a float dtype no wider "
                    f"than the compute dtype {jnp.dtype(dtype)}")
            if mesh is not None:
                raise ValueError("narrowed storage_dtype is not supported "
                                 "on sharded (mesh) lattices: the halo "
                                 "building block is f32-only")
        self.storage_dtype = sdt
        # at-rest representation (DDF shifting): narrowed lattices with
        # a recognized velocity set default to "shifted" (store
        # f_i - w_i, Mach-independent bf16 accuracy); full-width storage
        # is always "raw" so the f32 path stays bit-identical.  The
        # repr is stamped into checkpoint manifests, serve/cache keys
        # and telemetry spans — raw and shifted layouts never mix
        # silently (core/shift.py).
        narrowed = sdt != jnp.dtype(dtype)
        self.storage_repr = ddf.resolve_repr(model, narrowed, storage_repr)
        self._shift_vec = ddf.shift_of(model, self.storage_repr)
        self._shift_block = ddf.stack_shift(model, self.storage_repr)
        self.mesh = mesh
        vec = model.settings_vector(settings)
        self._series: dict[tuple[int, int], np.ndarray] = {}
        self.params = SimParams(
            settings=jnp.asarray(vec, dtype=dtype),
            zone_table=jnp.asarray(
                np.broadcast_to(vec[:, None], (len(vec), model.zone_max)),
                dtype=dtype),
        )
        self.state = LatticeState(
            fields=jnp.zeros((model.n_storage,) + self.shape, dtype=sdt),
            flags=jnp.zeros(self.shape, dtype=FLAG_DTYPE),
            globals_=jnp.zeros((model.n_globals,), dtype=dtype),
            iteration=jnp.zeros((), dtype=jnp.int32),
        )
        if mesh is not None and device is not None:
            raise ValueError("pass either mesh= (sharded) or device= "
                             "(single-device pin), not both")
        self.device = device
        if mesh is not None:
            from tclb_tpu.parallel.mesh import shard_state
            self._place = lambda: shard_state(self.state, self.params, mesh)
            self.state, self.params = self._place()
        elif device is not None:
            # single-device pin (the fleet dispatcher's lane seam): commit
            # state+params to the named device so every downstream dispatch
            # runs there instead of on JAX's default device
            self._place = lambda: (jax.device_put(self.state, device),
                                   jax.device_put(self.params, device))
            self.state, self.params = self._place()
        else:
            self._place = None
        # the XLA engine is built lazily so its trace can specialize on
        # the PAINTED node types (the reference compiles per boundary
        # set); set_flags invalidates it.  _host_flags keeps the host-side
        # copy present_types needs — under multi-host the sharded device
        # flags span non-addressable devices and cannot be fetched back
        self._iterate_cached = None
        self._host_flags: Optional[np.ndarray] = None
        step_init = make_action_step(model, "Init")
        if narrowed:
            def _init_narrow(state, params, _step=step_init,
                             _cdt=jnp.dtype(dtype), _sdt=sdt,
                             _sb=self._shift_block):
                out = _step(state.replace(
                    fields=ddf.widen_stack(state.fields, _cdt, _sb)),
                    params)
                return out.replace(
                    fields=ddf.narrow_stack(out.fields, _sdt, _sb))
            step_init = _init_narrow
        self._init = jax.jit(step_init, donate_argnums=0)
        self.sampler = None
        self._iterate_sampled = None
        self.avg_start = 0    # iteration of the last <Average> reset
        # fused Pallas fast path: built lazily at the first iterate() so the
        # painted flags are known (the 3D kernel specializes on present node
        # types); see _fast_path()
        self._fast = None
        self._fast_name = None
        self._fast_tried = False
        self._fast_probing = False
        self._fast_cfg = (1, None)

    # -- setup -------------------------------------------------------------- #

    def set_flags(self, flags: np.ndarray) -> None:
        """Overwrite the node-type field (reference Lattice::FlagOverwrite,
        src/Lattice.cu.Rt:892-905)."""
        assert flags.shape == self.shape
        self._host_flags = np.asarray(flags, dtype=np.uint16).copy()
        self.state = dataclasses.replace(
            self.state, flags=jnp.asarray(flags, dtype=FLAG_DTYPE))
        if self._place is not None:
            self.state, self.params = self._place()
        self._fast_tried = False   # present node types may have changed
        self._iterate_cached = None

    def set_setting(self, name: str, value: float, zone: Optional[int] = None
                    ) -> None:
        """reference Lattice::setSetting + zonal variant
        (src/Lattice.cu.Rt:1135-1191)."""
        m = self.model
        vec = np.array(self.params.settings, dtype=np.float64)
        table = np.array(self.params.zone_table, dtype=np.float64)
        if zone is None:
            m._set_with_derived(vec, name, float(value))
            # keep un-touched zones following the scalar value
            table[m.setting_index[name], :] = vec[m.setting_index[name]]
        else:
            table[m.setting_index[name], zone] = float(value)
        self.params = self.params.replace(
            settings=jnp.asarray(vec, dtype=self.dtype),
            zone_table=jnp.asarray(table, dtype=self.dtype))
        if self._place is not None:
            self.state, self.params = self._place()

    def set_setting_series(self, name: str, values: np.ndarray, zone: int = 0
                           ) -> None:
        """Attach a per-iteration time series to a zonal setting (reference
        ``zSet.set(setting, zone, vector)`` filled by <Control>,
        src/Handlers.cpp.Rt:2213-2452).  All series share one horizon length
        (the reference's ``zSet.len``); iteration wraps modulo that length."""
        m = self.model
        i = m.setting_index[name]
        if not m.settings[i].zonal:
            raise ValueError(f"setting {name!r} is not zonal; Control time "
                             "series apply to zonal settings")
        values = np.asarray(values, dtype=np.float64).ravel()
        for old in self._series.values():
            if len(old) != len(values):
                raise ValueError(
                    f"all Control series must share one horizon: got "
                    f"{len(values)}, existing {len(old)}")
        self._series[(i, int(zone))] = values
        self._fast_tried = False   # the engine re-selects series-aware
        keys = sorted(self._series)
        series_map = tuple((si, z, r) for r, (si, z) in enumerate(keys))
        ts = np.stack([self._series[k] for k in keys])
        self.params = self.params.replace(
            time_series=jnp.asarray(ts, dtype=self.dtype),
            series_map=series_map)
        if self._place is not None:
            self.state, self.params = self._place()

    def init(self) -> None:
        """Run the model's Init action (reference Lattice::Init)."""
        self.state = self._init(self.state, self.params)

    # -- running ------------------------------------------------------------ #

    def _flags_host(self) -> np.ndarray:
        """Host-side flag field for static specialization (multi-host
        safe: sharded device flags may span non-addressable devices)."""
        if self._host_flags is not None:
            return self._host_flags
        return np.asarray(self.state.flags)

    @property
    def _iterate(self):
        """The XLA engine, built on demand and specialized on the painted
        node types (absent boundary cases are skipped; globals reduce on
        the final step only — iterate()'s contract)."""
        if self._iterate_cached is None:
            from tclb_tpu.ops.lbm import present_types
            present = present_types(self.model, self._flags_host())
            if self.mesh is not None:
                from tclb_tpu.parallel.halo import make_sharded_iterate
                self._iterate_cached = make_sharded_iterate(
                    self.model, self.mesh, present=present)
            else:
                narrowed = self.storage_dtype != jnp.dtype(self.dtype)
                self._iterate_cached = jax.jit(
                    make_iterate(self.model, present=present,
                                 storage_dtype=(self.storage_dtype
                                                if narrowed else None),
                                 storage_shift=self._shift_block),
                    static_argnames=("niter",), donate_argnums=0)
        return self._iterate_cached

    def _build_fast(self):
        """Try to build the fused Pallas fast path for this configuration
        (the reference's tuned kernel IS its engine — Lattice::Iteration
        launches it every step, src/Lattice.cu.Rt:414-457; this makes the
        Pallas kernel play the same role).  Auto-selected on TPU only: in
        interpret mode (CPU) the kernels are an emulation, far slower than
        XLA.  ``TCLB_FASTPATH=0`` disables; ``TCLB_FASTPATH=force`` enables
        off-TPU (tests use this to exercise the dispatch in interpret
        mode)."""
        import os
        mode = os.environ.get("TCLB_FASTPATH", "auto")
        if mode == "0":
            return None, None
        if jax.default_backend() != "tpu" and mode != "force":
            return None, None
        from tclb_tpu.ops import pallas_d2q9, pallas_d3q
        # a Control time series needs per-iteration zonal planes, which
        # only the generic engine implements — skip the tuned kernels
        # (set_setting_series invalidates the engine so this re-runs)
        has_series = self.params.time_series is not None
        # engines receive the STORAGE dtype: their HBM stacks and DMA
        # scratch narrow with it while their compute stays f32 (each
        # kernel family widens on read / narrows on write); f32-only
        # families (pallas_d2q9, sharded) reject it in supports() and
        # dispatch falls through to the d3q/generic families
        sdt = self.storage_dtype
        s_itemsize = jnp.dtype(sdt).itemsize
        if self.mesh is not None:
            from tclb_tpu.ops.lbm import present_types
            from tclb_tpu.parallel.halo import make_sharded_pallas_iterate
            it = make_sharded_pallas_iterate(
                self.model, self.mesh, self.shape, self.dtype,
                present=present_types(self.model, self._flags_host()))
            if it is not None:
                if getattr(it, "uses_generic", False):
                    self._fast_probing = True
                return it, f"pallas_sharded[{dict(self.mesh.shape)}]"
            return None, None
        if (not has_series
                and pallas_d2q9.supports_resident(self.model, self.shape,
                                                  sdt)):
            # small domains: whole lattice VMEM-resident, 8 steps per
            # kernel call — (1R+1W)/8 HBM traffic per step.  First call
            # is probed (the budget cannot see Mosaic's temporaries);
            # on failure the probe falls back — for the resident engine
            # the ladder is empty, so straight to the band/XLA path
            present = pallas_d2q9.present_types(
                self.model, self._flags_host())
            self._fast_probing = True
            return (pallas_d2q9.make_resident_iterate(
                self.model, self.shape, sdt, present=present),
                f"pallas_resident[{self.model.name},fuse=8]")
        if (not has_series
                and pallas_d2q9.supports(self.model, self.shape, sdt)):
            present = pallas_d2q9.present_types(
                self.model, self._flags_host())
            return (pallas_d2q9.make_pallas_iterate(
                self.model, self.shape, sdt, fuse=2,
                present=present),
                f"pallas_2d[{self.model.name},fuse=2]")
        if not has_series and pallas_d3q.supports(
                self.model, self.shape, sdt):
            present = pallas_d3q.present_types(
                self.model, self._flags_host())
            # K>=2 multi-step fusion (one HBM round trip per K steps)
            # compiles against the raised scoped-vmem ceiling: first TPU
            # compile may still hit Mosaic temporaries the planner can't
            # see, so the fused build is probed (fallback: fuse=1)
            k3 = pallas_d3q.choose_fuse(self.model, self.shape,
                                        itemsize=s_itemsize)
            if k3 >= 2:
                self._fast_probing = True
            else:
                # single-step demotion must never be silent: record WHY
                # the fused planner rejected every (bz, K) so a floor
                # regression can be triaged from telemetry alone
                _, why = pallas_d3q.fused_cfg_explain(
                    self.model, self.shape, itemsize=s_itemsize)
                telemetry.event(
                    "fused_rejected", engine="pallas_d3q",
                    model=self.model.name, shape=list(self.shape),
                    reason=why or "unknown")
            return (pallas_d3q.make_pallas_iterate(
                self.model, self.shape, sdt, present=present,
                shift=self._shift_vec),
                f"pallas_d3q[{self.model.name},fuse={k3}]")
        from tclb_tpu.ops import pallas_generic
        # the static analyzer's kernel-safety verdict gates EVERY
        # registry-driven kernel: a stage reading beyond its declared
        # stencil would make the band windows silently wrong (the XLA
        # path wraps exactly, so it stays the safe fallback)
        from tclb_tpu import analysis
        if not analysis.kernel_safety_ok(self.model):
            return None, None
        if (not has_series
                and pallas_generic.supports_resident(self.model, self.shape,
                                                     sdt)
                and pallas_generic.mosaic_ok(self.model, self.shape)):
            # generic counterpart of the tuned d2q9 resident engine
            # (checked above): whole lattice VMEM-resident, 8 steps per
            # kernel call, for ANY registry model that fits the budget.
            # First call is probed; on failure the generic BAND engine
            # is the fallback (see iterate()'s was_resident branch)
            from tclb_tpu.ops.lbm import present_types
            present = present_types(self.model, self._flags_host())
            self._fast_probing = True
            return (pallas_generic.make_resident_iterate(
                self.model, self.shape, sdt, present=present,
                shift=self._shift_vec),
                f"pallas_resident_generic[{self.model.name},fuse=8]")
        if (pallas_generic.supports(self.model, self.shape, sdt)
                and pallas_generic.mosaic_ok(self.model, self.shape)):
            from tclb_tpu.ops.lbm import present_types
            present = present_types(self.model, self._flags_host())
            cfg = pallas_generic.get_build_cfg(self.model, self.shape)
            if cfg is not None:
                # this model/shape already proved it compiles: skip the
                # first-call probe (and its full-state copy)
                fz, cap = cfg
            else:
                self._fast_probing = True   # first call may hit a Mosaic
                # temporal fusion amortizes one HBM round trip over K
                # steps; the shared planner caps K by the stencil reach
                # fitting the halo (2D: fixed 8-row block; deep-stencil
                # models like lee at reach 6/step stay fuse=1) or by the
                # traffic model vs the K=1 engine (3D: slab halos grow
                # with K, so the win must be priced)
                fz = (pallas_generic.choose_fuse_3d(self.model,
                                                    self.shape,
                                                    itemsize=s_itemsize)
                      if self.model.ndim == 3
                      else pallas_generic.choose_fuse(self.model))
                cap = None
            self._fast_cfg = (fz, cap)
            return (pallas_generic.make_pallas_iterate(  # lowering gap
                self.model, self.shape, sdt, fuse=fz,
                present=present, by_cap=cap,
                shift=self._shift_vec),
                f"pallas_generic[{self.model.name},fuse={fz}]")
        return None, None

    def _fast_path(self):
        if not self._fast_tried:
            self._fast_tried = True
            self._fast, self._fast_name = self._build_fast()
            from tclb_tpu.utils import log
            if self._fast is not None:
                suffix = "(in-kernel globals)" if getattr(
                    self._fast, "full_globals", False) \
                    else "(+1 XLA step per call for globals)"
                log.info(f"engine: {self._fast_name} fused fast path "
                         f"{suffix}")
            else:
                log.debug(f"engine: XLA path ({self.model.name} "
                          f"{self.shape})")
            telemetry.engine_selected(
                self._fast_name or "xla", model=self.model.name,
                shape=list(self.shape), backend=jax.default_backend(),
                probed=self._fast_probing)
        return self._fast

    def iterate(self, niter: int) -> None:
        """Advance ``niter`` steps on the auto-selected engine.  With
        telemetry enabled the chunk runs under an ``iterate`` span
        (block_until_ready-fenced wall time, MLUPS + vs-roofline derived
        metrics); disabled, the span machinery is a single boolean check."""
        if not telemetry.enabled():
            self._iterate_impl(niter)
            return
        # int(iteration) forces a device sync BEFORE the span opens, so
        # the measured wall time never bills a previous chunk's async tail
        with telemetry.span(
                "iterate", iters=int(niter),
                nodes=float(np.prod(self.shape)),
                bytes_per_node=(2 * self.model.n_storage
                                * np.dtype(self.state.fields.dtype).itemsize
                                + 2),
                storage_dtype=np.dtype(self.state.fields.dtype).name,
                storage_repr=self.storage_repr,
                model=self.model.name,
                iteration=int(self.state.iteration)) as sp:
            self._iterate_impl(niter)
            engine = ("sampled_xla" if self.sampler is not None
                      else (self._fast_name or "xla"))
            sp.add(engine=engine, fuse=telemetry.fuse_of(engine))
            sp.sync(self.state.fields)

    def _iterate_impl(self, niter: int) -> None:
        if self.sampler is not None:
            it0 = int(self.state.iteration)
            self.state, samples = self._iterate_sampled(
                self.state, self.params, niter,
                jnp.asarray(self.avg_start, jnp.int32))
            self.sampler.append(it0, np.asarray(samples))
            return
        fast = self._fast_path()
        # an engine advertising full_globals returns the LAST step's
        # Globals itself (in-kernel accumulation, ≡ the reference's
        # src/cuda.cu.Rt:176-202) — no trailing XLA step; the hybrid
        # engines run niter-1 fused steps + one XLA step instead.
        # Engines advertising supports_series gather Control time series
        # per iteration themselves; others fall back to XLA for those.
        full = bool(getattr(fast, "full_globals", False))
        ok_series = (self.params.time_series is None
                     or getattr(fast, "supports_series", False))
        nfast = niter if full else niter - 1
        if fast is not None and ok_series and nfast >= 1:
            if self._fast_probing:
                # the generic engine's trace probe cannot see Mosaic
                # lowering gaps (e.g. a model using arccos) or
                # scoped-VMEM overflows — those only surface at first
                # TPU compile.  Probe on a COPY of the state (the
                # engines donate their input; a failure that happens at
                # execution rather than compile would otherwise leave
                # the real state's buffers deleted), retry down a
                # smaller-band/no-fusion ladder, remember the verdict
                # process-wide, and fall back to XLA if nothing fits.
                from tclb_tpu.ops import pallas_generic
                from tclb_tpu.utils import log

                def attempt(it_fn):
                    probe = jax.tree.map(jnp.copy, self.state)
                    return it_fn(probe, self.params, nfast)

                was_resident = (self._fast_name or "").startswith(
                    "pallas_resident")
                was_generic_res = (self._fast_name or "").startswith(
                    "pallas_resident_generic")
                was_d3q = (self._fast_name or "").startswith(
                    "pallas_d3q[")
                try:
                    self.state = attempt(fast)
                except Exception as e:  # noqa: BLE001
                    if was_d3q:
                        # fused (K>=2) tuned-3D probe failed — its
                        # raised-ceiling scratch budget cannot see
                        # Mosaic's compute temporaries.  The K=1 block
                        # kernel is the proven engine for these models:
                        # swap it in and continue this very call.
                        failed = self._fast_name
                        log.info(f"engine: {self._fast_name} failed to "
                                 f"compile ({e!r}); fuse=1 "
                                 "d3q fallback")
                        from tclb_tpu.ops import pallas_d3q
                        present = pallas_d3q.present_types(
                            self.model, self._flags_host())
                        self._fast = fast = \
                            pallas_d3q.make_pallas_iterate(
                                self.model, self.shape, self.storage_dtype,
                                present=present, fuse=1,
                                shift=self._shift_vec)
                        self._fast_name = (
                            f"pallas_d3q[{self.model.name},fuse=1]")
                        telemetry.engine_fallback(
                            failed, self._fast_name, repr(e),
                            model=self.model.name)
                        self._fast_probing = False
                        self.state = fast(self.state, self.params, nfast)
                        if not full:
                            self.state = self._iterate(
                                self.state, self.params, 1)
                        return
                    if was_resident:
                        # resident probe failed (its budget can't see
                        # Mosaic temporaries): the band engine is the
                        # proven fallback for these models — swap it in
                        # and continue this very call.  Each resident
                        # flavor falls back to ITS band family: the
                        # tuned d2q9 resident to the tuned d2q9 band,
                        # the generic resident to the generic band.
                        failed = self._fast_name
                        log.info(f"engine: {self._fast_name} failed to "
                                 f"compile ({e!r}); band "
                                 "engine fallback")
                        if was_generic_res:
                            from tclb_tpu.ops.lbm import present_types
                            present = present_types(self.model,
                                                    self._flags_host())
                            fz = (pallas_generic.choose_fuse_3d(
                                self.model, self.shape,
                                itemsize=jnp.dtype(
                                    self.storage_dtype).itemsize)
                                if self.model.ndim == 3
                                else pallas_generic.choose_fuse(
                                    self.model))
                            self._fast = fast = \
                                pallas_generic.make_pallas_iterate(
                                    self.model, self.shape,
                                    self.storage_dtype,
                                    fuse=fz, present=present,
                                    shift=self._shift_vec)
                            self._fast_cfg = (fz, None)
                            self._fast_name = (
                                f"pallas_generic"
                                f"[{self.model.name},fuse={fz}]")
                        else:
                            from tclb_tpu.ops import pallas_d2q9
                            present = pallas_d2q9.present_types(
                                self.model, self._flags_host())
                            self._fast = fast = \
                                pallas_d2q9.make_pallas_iterate(
                                    self.model, self.shape, self.dtype,
                                    fuse=2, present=present)
                            self._fast_name = (f"pallas_2d"
                                               f"[{self.model.name},"
                                               f"fuse=2]")
                        telemetry.engine_fallback(
                            failed, self._fast_name, repr(e),
                            model=self.model.name)
                        self._fast_probing = False
                        self.state = fast(self.state, self.params, nfast)
                        if not full:
                            self.state = self._iterate(
                                self.state, self.params, 1)
                        return
                    failed = self._fast_name
                    if self.mesh is not None:
                        ladder = []   # sharded engine: no cap ladder
                    else:
                        log.debug(f"engine: {self._fast_name} first "
                                  f"compile failed ({e!r}); "
                                  "trying smaller bands")
                        from tclb_tpu.ops.lbm import present_types
                        present = present_types(self.model,
                                                self._flags_host())
                        fz0, _ = self._fast_cfg
                        ladder = [(fz0, 16), (fz0, 8)]
                        if fz0 >= 2:
                            ladder += [(1, 16), (1, 8)]
                        if self.model.ndim == 3:
                            # last resort: raised scoped-vmem ceiling
                            # (negative cap encodes it; ~2x slower
                            # codegen, still ~3x the XLA path)
                            ladder += [(fz0, -16), (fz0, -8)]
                        ladder = [c for c in ladder
                                  if c != self._fast_cfg]
                    for fz, cap in ladder:
                        try:
                            it2 = pallas_generic.make_pallas_iterate(
                                self.model, self.shape, self.storage_dtype,
                                fuse=fz, present=present, by_cap=cap,
                                shift=self._shift_vec)
                            self.state = attempt(it2)
                        except Exception:  # noqa: BLE001
                            continue
                        self._fast = fast = it2
                        self._fast_cfg = (fz, cap)
                        self._fast_name = (f"pallas_generic"
                                           f"[{self.model.name},fuse={fz},"
                                           f"by<={cap}]")
                        telemetry.engine_fallback(
                            failed, self._fast_name, repr(e),
                            model=self.model.name)
                        break
                    else:
                        log.info(f"engine: {self._fast_name} failed to "
                                 f"compile ({e!r}); XLA "
                                 "fallback")
                        telemetry.engine_fallback(
                            failed, "xla", repr(e),
                            model=self.model.name)
                        if self.mesh is None:
                            # the sharded probe exercised a DIFFERENT
                            # kernel (local shard shape) — never poison
                            # the single-device caches from it
                            pallas_generic.set_mosaic_ok(self.model,
                                                         self.shape,
                                                         False)
                        self._fast = fast = None
                        self._fast_name = None
                        self._fast_probing = False
                        self.state = self._iterate(self.state, self.params,
                                                   niter)
                        return
                if self.mesh is None and not was_resident \
                        and not was_d3q:
                    # verdict caches belong to the generic engine only
                    pallas_generic.set_mosaic_ok(self.model, self.shape,
                                                 True)
                    pallas_generic.set_build_cfg(self.model, self.shape,
                                                 *self._fast_cfg)
                self._fast_probing = False
            else:
                self.state = fast(self.state, self.params, nfast)
            if not full:
                self.state = self._iterate(self.state, self.params, 1)
        else:
            self.state = self._iterate(self.state, self.params, niter)

    def attach_sampler(self, sampler) -> None:
        """Register a point sampler: every subsequent step also gathers its
        quantities at the sample points (reference Sampler, C16).  Sampled
        iteration runs the global-view step (XLA partitions it over the mesh
        automatically when state is sharded)."""
        self.sampler = sampler
        f = make_sampled_iterate(self.model, sampler.points,
                                 sampler.quantities)
        self._iterate_sampled = jax.jit(f, static_argnames=("niter",))

    # -- inspection --------------------------------------------------------- #

    def get_quantity(self, name: str) -> jnp.ndarray:
        """Evaluate a registered Quantity over the lattice (reference
        Lattice::GetQuantity, src/Lattice.cu.Rt:1012-1036)."""
        fn = self.model.quantity_fns[name]
        # quantities evaluate in the compute dtype over RAW distributions
        # (no-op cast at f32; the shifted rung restores f_i = dev + w_i
        # at this widen seam, so extraction never sees the deviation)
        fields = ddf.widen_stack(self.state.fields, self.dtype,
                                 self._shift_block)
        ctx = NodeCtx(self.model, fields, fields,
                      self.state.flags, self.params,
                      iteration=self.state.iteration,
                      avg_start=self.avg_start)
        with jax.default_matmul_precision("highest"):
            return fn(ctx)

    def reset_average(self) -> None:
        """Zero the ``average=True`` storage planes and restart the sample
        counter (reference Lattice::resetAverage,
        src/Lattice.cu.Rt:1193-1201: CudaMemset of each averaged plane +
        ``reset_iter = iter``)."""
        m = self.model
        idx = [i for i, d in enumerate(m.densities) if d.average]
        if idx:
            fields = self.state.fields
            for i in idx:
                fields = fields.at[i].set(0.0)
            self.state = dataclasses.replace(self.state, fields=fields)
            if self._place is not None:
                self.state, self.params = self._place()
        self.avg_start = int(self.state.iteration)

    def _plane_w(self, idx: int):
        """Per-plane shift for the density accessors: the lattice weight
        under the shifted representation, falsy (``None``) otherwise."""
        if self._shift_vec is None:
            return None
        w = float(self._shift_vec[idx])
        return w or None

    def get_density(self, name: str) -> jnp.ndarray:
        """One storage plane in RAW distribution values (the shifted
        rung widens + restores ``w_i``; raw storage returns the plane
        untouched, exactly the pre-shift behavior)."""
        idx = self.model.storage_index[name]
        w = self._plane_w(idx)
        if w is None:
            return self.state.fields[idx]
        return ddf.widen_plane(self.state.fields[idx], self.dtype, w)

    def set_density_planes(self, values: dict) -> None:
        """Write several storage planes with ONE device placement (a
        per-plane set_density would re-shard the whole state each time).
        Values are RAW distributions; the shifted rung removes ``w_i``
        in the compute dtype before narrowing."""
        fields = self.state.fields
        for name, value in values.items():
            idx = self.model.storage_index[name]
            w = self._plane_w(idx)
            if w is None:
                plane = jnp.asarray(value, dtype=self.storage_dtype)
            else:
                plane = ddf.narrow_plane(
                    jnp.asarray(value, dtype=self.dtype),
                    self.storage_dtype, w)
            fields = fields.at[idx].set(plane)
        self.state = dataclasses.replace(self.state, fields=fields)
        if self._place is not None:
            self.state, self.params = self._place()

    def set_density(self, name: str, value: np.ndarray) -> None:
        self.set_density_planes({name: value})

    def fields_raw(self) -> np.ndarray:
        """At-rest field stack as a host float64 array in the RAW
        representation — the representation-independent view the
        precision harness and state digests compare against (the
        arithmetic runs in f64, so it is exact for either storage
        layout)."""
        return ddf.convert_fields_host(
            np.asarray(self.state.fields), self.storage_repr, "raw",
            ddf.storage_shift(self.model), np.float64)

    def get_globals(self) -> dict[str, float]:
        """reference Lattice::getGlobals (src/Lattice.cu.Rt:1093-1106)."""
        vals = np.asarray(self.state.globals_)
        return {g.name: float(vals[i]) for i, g in enumerate(self.model.globals_)}

    def get_objective(self) -> float:
        """Weighted objective from <Global>InObj settings (reference
        Lattice::calcGlobals, src/Lattice.cu.Rt:1113-1129)."""
        m = self.model
        obj = 0.0
        vals = np.asarray(self.state.globals_)
        svec = np.asarray(self.params.settings)
        for i, g in enumerate(m.globals_):
            obj += float(svec[m.setting_index[g.name + "InObj"]]) * float(vals[i])
        return obj

    # -- checkpoint --------------------------------------------------------- #

    def save(self, path: str) -> None:
        """Full-state dump (reference Lattice::save, src/Lattice.cu.Rt:592-626),
        including any Control time series.  Legacy ``.npz`` format, written
        atomically (temp + fsync + rename) through the checkpoint
        subsystem's writer so a kill mid-save never corrupts an existing
        copy; the manifest-verified directory format lives in
        :mod:`tclb_tpu.checkpoint`."""
        from tclb_tpu.checkpoint.restore import npy_safe
        from tclb_tpu.checkpoint.writer import atomic_path, with_suffix
        extra = {}
        if self.params.time_series is not None:
            extra["time_series"] = np.asarray(self.params.time_series)
            extra["series_map"] = np.asarray(self.params.series_map,
                                             dtype=np.int64)
        target = with_suffix(path, ".npz")
        with telemetry.span("checkpoint.save", mode="legacy_npz",
                            path=target) as sp:
            sp.sync(self.state.fields)
            with atomic_path(target) as tmp:
                with open(tmp, "wb") as f:
                    np.savez(f,
                             fields=npy_safe(np.asarray(self.state.fields)),
                             flags=np.asarray(self.state.flags),
                             iteration=int(self.state.iteration),
                             settings=np.asarray(self.params.settings),
                             zone_table=np.asarray(self.params.zone_table),
                             storage_dtype=str(
                                 np.dtype(self.storage_dtype)),
                             storage_repr=self.storage_repr,
                             **extra)

    def load(self, path: str) -> None:
        from tclb_tpu.checkpoint.restore import npy_restore
        from tclb_tpu.checkpoint.writer import resolve_npz
        d = np.load(resolve_npz(path))
        self._fast_tried = False   # restored flags may paint new types
        self._iterate_cached = None
        self._host_flags = np.asarray(d["flags"], dtype=np.uint16)
        # files older than the storage_repr stamp are raw by definition;
        # a cross-representation load converts on the host in f64 (an
        # unknown stamp raises rather than loading garbage)
        src_repr = (str(d["storage_repr"]) if "storage_repr" in d
                    else "raw")
        src_sdt = (str(d["storage_dtype"]) if "storage_dtype" in d
                   else str(np.dtype(self.dtype)))
        raw_fields = npy_restore(d["fields"], src_sdt)
        if src_repr == self.storage_repr:
            fields = jnp.asarray(raw_fields, dtype=self.storage_dtype)
        else:
            fields = jnp.asarray(ddf.convert_fields_host(
                raw_fields, src_repr, self.storage_repr,
                ddf.storage_shift(self.model), self.storage_dtype))
        self.state = LatticeState(
            fields=fields,
            flags=jnp.asarray(d["flags"], dtype=FLAG_DTYPE),
            globals_=self.state.globals_,
            iteration=jnp.asarray(d["iteration"], dtype=jnp.int32),
        )
        self._series = {}
        ts, smap = None, ()
        if "time_series" in d:
            ts = jnp.asarray(d["time_series"], dtype=self.dtype)
            smap = tuple(tuple(int(v) for v in row) for row in d["series_map"])
            for si, z, r in smap:
                self._series[(si, z)] = np.asarray(d["time_series"][r])
        self.params = SimParams(
            settings=jnp.asarray(d["settings"], dtype=self.dtype),
            zone_table=jnp.asarray(d["zone_table"], dtype=self.dtype),
            time_series=ts, series_map=smap)
        if self._place is not None:
            self.state, self.params = self._place()
