"""Gateway CLI: ``python -m tclb_tpu gateway --port 8080 --store /var/jobs``.

Stands up the full serving front door — persistent job store, admission
control, scheduler (or, with ``--workers N``, a process-isolated
:class:`~tclb_tpu.serve.pool.WorkerPool`), HTTP listener — and blocks
until interrupted.  On restart with the same ``--store``, every
non-terminal job is recovered: queued jobs re-run, resumable jobs
continue from their newest checkpoint.

SIGTERM drains instead of dying: admission stops (503 + Retry-After,
``/healthz/ready`` goes 503), in-flight resumable jobs park at their
next checkpointed segment boundary, the store snapshot flushes, and the
process exits 0 — the zero-downtime half of a rolling restart.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time


def add_gateway_arguments(p: argparse.ArgumentParser) -> None:
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default 127.0.0.1)")
    p.add_argument("--port", type=int, default=8080,
                   help="listen port (0 picks a free one)")
    p.add_argument("--store", default="gateway-store",
                   help="job store directory (journal + snapshots + "
                   "per-job checkpoints); reuse it across restarts to "
                   "recover jobs")
    p.add_argument("--max-batch", type=int, default=None,
                   help="cap cases per batched dispatch (default: "
                   "memory-predicated)")
    p.add_argument("--queue-limit", type=int, default=1024,
                   help="global admission cap on queued cases")
    p.add_argument("--quota-default", default=None, metavar="QUEUED[:WORK]",
                   help="default per-tenant quota: max queued/running "
                   "jobs, optionally :max inflight work "
                   "(cells x niter x cases); '-' = unlimited")
    p.add_argument("--quota", action="append", default=[],
                   metavar="TENANT=QUEUED[:WORK]",
                   help="per-tenant quota override (repeatable)")
    p.add_argument("--token", action="append", default=[],
                   metavar="TENANT=SECRET",
                   help="per-tenant bearer token (repeatable); with any "
                   "configured, every /v1/jobs route needs "
                   "Authorization: Bearer (reads scoped to the token's "
                   "tenant)")
    p.add_argument("--rate-default", default=None, metavar="RPS[:BURST]",
                   help="default per-tenant submission rate limit "
                   "(token bucket; 429 + Retry-After on excess)")
    p.add_argument("--rate", action="append", default=[],
                   metavar="TENANT=RPS[:BURST]",
                   help="per-tenant rate override (repeatable)")
    p.add_argument("--retain-secs", type=float, default=None,
                   help="TTL for terminal job records; expired ones are "
                   "garbage-collected at snapshot compaction "
                   "(default: keep forever)")
    p.add_argument("--monitor", default=None, metavar="[HOST]:PORT",
                   help="also serve live /metrics + /status (the "
                   "gateway registers its own status provider there)")
    p.add_argument("--workers", type=int, default=0,
                   help="run solves in N supervised worker subprocesses "
                   "(process isolation: a hung or crashed solve kills "
                   "one worker, never the gateway; 0 = in-process "
                   "scheduler)")
    p.add_argument("--cluster", default=None, metavar="[HOST]:PORT",
                   help="serve through a pod of host-agents instead of "
                   "local lanes: listen for `python -m "
                   "tclb_tpu.cluster.agent` enrollments on this "
                   "address (port 0 picks a free one; the resolved "
                   "address is printed as `cluster: HOST:PORT`)")
    p.add_argument("--cluster-heartbeat-timeout", type=float,
                   default=15.0, metavar="SECONDS",
                   help="seconds without an agent heartbeat before the "
                   "gateway declares the host lost and requeues its "
                   "in-flight jobs (with --cluster)")
    p.add_argument("--heartbeat-timeout", type=float, default=60.0,
                   help="seconds without a worker heartbeat before the "
                   "supervisor declares it hung and restarts it "
                   "(with --workers)")
    p.add_argument("--no-relay", action="store_true",
                   help="disable the cross-process telemetry relay "
                   "(workers stop forwarding iterate spans/events to "
                   "the gateway's /metrics and trace; with --workers)")
    p.add_argument("--drain-grace", type=float, default=30.0,
                   help="seconds SIGTERM drain waits for in-flight jobs "
                   "to finish or park at a checkpoint before killing "
                   "workers")


def run_gateway(args) -> int:
    from tclb_tpu.gateway.http import GatewayServer
    from tclb_tpu.gateway.service import GatewayService
    from tclb_tpu.gateway.tenancy import (RateLimiter, TenancyConfig,
                                          TokenAuth)
    from tclb_tpu.telemetry import live as tlive

    tenancy = TenancyConfig.parse(args.quota_default, args.quota)
    auth = TokenAuth.parse(args.token)
    rate = RateLimiter.parse(args.rate_default, args.rate)
    monitor = None
    if args.monitor:
        from tclb_tpu.telemetry.http import MonitorServer
        monitor = MonitorServer.from_spec(args.monitor).start()
        print(f"monitor: {monitor.url}/status")
    pool = None
    workers = int(getattr(args, "workers", 0) or 0)
    cluster_spec = getattr(args, "cluster", None)
    if cluster_spec:
        # pod mode: the "pool" is the cluster control plane; host-agents
        # bring the actual worker lanes when they enroll
        from tclb_tpu.cluster.server import ClusterServer
        from tclb_tpu.telemetry.live import parse_monitor_spec
        chost, cport = parse_monitor_spec(cluster_spec)
        pool = ClusterServer(
            chost, cport,
            heartbeat_timeout_s=args.cluster_heartbeat_timeout)
        print(f"cluster: {pool.address}", flush=True)
    elif workers > 0:
        from tclb_tpu.serve.pool import WorkerPool
        pool = WorkerPool(workers=workers,
                          heartbeat_timeout_s=args.heartbeat_timeout,
                          autostart=False,
                          relay=not getattr(args, "no_relay", False))
    svc = GatewayService(args.store, tenancy=tenancy,
                         queue_limit=args.queue_limit,
                         max_batch=args.max_batch,
                         auth=auth, rate=rate,
                         retain_secs=args.retain_secs,
                         pool=pool)
    # attach on the MAIN thread before serving: this is what installs
    # the SIGTERM handler that runs the drain hook below
    tlive.flight_recorder().attach()
    srv = GatewayServer(svc, host=args.host, port=args.port).start()
    stop = threading.Event()

    def _drain(reason: str) -> bool:
        print(f"gateway: draining ({reason})", flush=True)
        svc.drain(grace_s=args.drain_grace)
        stop.set()
        return True  # claim the shutdown: exit 0, not SIGTERM death

    tlive.register_drain_hook("gateway", _drain)
    print(f"gateway: {srv.url}/v1/jobs  (store: {svc.store.root}"
          + (f", cluster: {pool.address}" if cluster_spec
             else (f", workers: {workers}" if pool is not None else ""))
          + ")", flush=True)
    try:
        while not stop.is_set():
            # wait() (not a bare sleep) so the drain hook's stop.set()
            # turns the loop promptly once the signal handler returns
            stop.wait(timeout=3600)
    except KeyboardInterrupt:
        print("gateway: shutting down")
    finally:
        tlive.unregister_drain_hook("gateway", _drain)
        srv.stop()
        if monitor is not None:
            monitor.stop()
        tlive.flight_recorder().detach()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tclb-gateway",
        description="multi-tenant HTTP serving gateway")
    add_gateway_arguments(p)
    return run_gateway(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
