"""Gateway CLI: ``python -m tclb_tpu gateway --port 8080 --store /var/jobs``.

Stands up the full serving front door — persistent job store, admission
control, scheduler, HTTP listener — and blocks until interrupted.  On
restart with the same ``--store``, every non-terminal job is recovered:
queued jobs re-run, resumable jobs continue from their newest
checkpoint.
"""

from __future__ import annotations

import argparse
import sys
import time


def add_gateway_arguments(p: argparse.ArgumentParser) -> None:
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default 127.0.0.1)")
    p.add_argument("--port", type=int, default=8080,
                   help="listen port (0 picks a free one)")
    p.add_argument("--store", default="gateway-store",
                   help="job store directory (journal + snapshots + "
                   "per-job checkpoints); reuse it across restarts to "
                   "recover jobs")
    p.add_argument("--max-batch", type=int, default=None,
                   help="cap cases per batched dispatch (default: "
                   "memory-predicated)")
    p.add_argument("--queue-limit", type=int, default=1024,
                   help="global admission cap on queued cases")
    p.add_argument("--quota-default", default=None, metavar="QUEUED[:WORK]",
                   help="default per-tenant quota: max queued/running "
                   "jobs, optionally :max inflight work "
                   "(cells x niter x cases); '-' = unlimited")
    p.add_argument("--quota", action="append", default=[],
                   metavar="TENANT=QUEUED[:WORK]",
                   help="per-tenant quota override (repeatable)")
    p.add_argument("--token", action="append", default=[],
                   metavar="TENANT=SECRET",
                   help="per-tenant bearer token (repeatable); with any "
                   "configured, every /v1/jobs route needs "
                   "Authorization: Bearer (reads scoped to the token's "
                   "tenant)")
    p.add_argument("--rate-default", default=None, metavar="RPS[:BURST]",
                   help="default per-tenant submission rate limit "
                   "(token bucket; 429 + Retry-After on excess)")
    p.add_argument("--rate", action="append", default=[],
                   metavar="TENANT=RPS[:BURST]",
                   help="per-tenant rate override (repeatable)")
    p.add_argument("--retain-secs", type=float, default=None,
                   help="TTL for terminal job records; expired ones are "
                   "garbage-collected at snapshot compaction "
                   "(default: keep forever)")
    p.add_argument("--monitor", default=None, metavar="[HOST]:PORT",
                   help="also serve live /metrics + /status (the "
                   "gateway registers its own status provider there)")


def run_gateway(args) -> int:
    from tclb_tpu.gateway.http import GatewayServer
    from tclb_tpu.gateway.service import GatewayService
    from tclb_tpu.gateway.tenancy import (RateLimiter, TenancyConfig,
                                          TokenAuth)

    tenancy = TenancyConfig.parse(args.quota_default, args.quota)
    auth = TokenAuth.parse(args.token)
    rate = RateLimiter.parse(args.rate_default, args.rate)
    monitor = None
    if args.monitor:
        from tclb_tpu.telemetry.http import MonitorServer
        monitor = MonitorServer.from_spec(args.monitor).start()
        print(f"monitor: {monitor.url}/status")
    svc = GatewayService(args.store, tenancy=tenancy,
                         queue_limit=args.queue_limit,
                         max_batch=args.max_batch,
                         auth=auth, rate=rate,
                         retain_secs=args.retain_secs)
    srv = GatewayServer(svc, host=args.host, port=args.port).start()
    print(f"gateway: {srv.url}/v1/jobs  (store: {svc.store.root})")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        print("gateway: shutting down")
    finally:
        srv.stop()
        if monitor is not None:
            monitor.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tclb-gateway",
        description="multi-tenant HTTP serving gateway")
    add_gateway_arguments(p)
    return run_gateway(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
