"""Per-tenant quotas and admission control — plain python only.

Quotas bound two things per tenant: how many jobs may sit queued or
running at once (``max_queued``), and how much *work* those jobs may
represent (``max_inflight_work`` = sum of cells x niter x cases — the
same working-set arithmetic the batch cap uses, so a tenant cannot park
one enormous job inside a small job count).  On top of the per-tenant
limits, a global ``queue_limit`` backpressures everyone using the
scheduler's queue-depth signal.

Rejections are structured (HTTP 429 with ``reason``/``limit``/
``current``) so clients can distinguish "you are over quota" from "the
pod is saturated" and back off accordingly.
"""

from __future__ import annotations

import dataclasses
import hmac
import time
from typing import Callable, Optional, Sequence

from tclb_tpu.gateway.jobs import TERMINAL, JobRecord
from tclb_tpu.telemetry import locks

#: rejection reasons (stable API + metrics label values)
REASON_MAX_QUEUED = "tenant_max_queued"
REASON_MAX_WORK = "tenant_max_inflight_work"
REASON_SATURATED = "queue_saturated"
REASON_RATE = "rate_limited"


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Limits for one tenant; ``None`` disables a limit."""

    max_queued: Optional[int] = 64
    max_inflight_work: Optional[int] = None

    @classmethod
    def parse(cls, spec: str) -> "TenantQuota":
        """``QUEUED[:WORK]`` with ``-`` for unlimited, e.g. ``8:1e9``."""
        parts = str(spec).split(":")
        if len(parts) not in (1, 2):
            raise ValueError(f"quota must be QUEUED[:WORK], got {spec!r}")

        def num(s: str) -> Optional[int]:
            s = s.strip()
            if s in ("", "-"):
                return None
            return int(float(s))
        work = num(parts[1]) if len(parts) == 2 else None
        return cls(max_queued=num(parts[0]), max_inflight_work=work)


@dataclasses.dataclass
class TenancyConfig:
    """The quota table: per-tenant overrides over a default."""

    default: TenantQuota = dataclasses.field(default_factory=TenantQuota)
    tenants: dict[str, TenantQuota] = dataclasses.field(
        default_factory=dict)

    def quota(self, tenant: str) -> TenantQuota:
        return self.tenants.get(tenant, self.default)

    @classmethod
    def parse(cls, default_spec: Optional[str] = None,
              tenant_specs: Sequence[str] = ()) -> "TenancyConfig":
        """CLI surface: ``--quota-default 8:1e9`` and repeatable
        ``--quota tenant=QUEUED[:WORK]``."""
        default = (TenantQuota.parse(default_spec)
                   if default_spec else TenantQuota())
        tenants = {}
        for spec in tenant_specs:
            name, sep, rhs = str(spec).partition("=")
            if not sep or not name.strip():
                raise ValueError(
                    f"--quota needs tenant=QUEUED[:WORK], got {spec!r}")
            tenants[name.strip()] = TenantQuota.parse(rhs)
        return cls(default=default, tenants=tenants)


class AdmissionController:
    """Stateless admission decisions over the store + scheduler signals.

    ``admit`` returns ``None`` to accept, or a structured rejection dict
    (the 429 body) naming the reason, the limit hit, and the current
    level — computed from the tenant's non-terminal records plus the
    global queue depth the scheduler's status provider reports."""

    def __init__(self, config: Optional[TenancyConfig] = None,
                 queue_limit: Optional[int] = 1024) -> None:
        self.config = config or TenancyConfig()
        self.queue_limit = queue_limit

    def admit(self, tenant: str, n_cases: int, work: int,
              active: Sequence[JobRecord],
              queue_depth: int = 0) -> Optional[dict]:
        active = [r for r in active if r.status not in TERMINAL]
        if self.queue_limit is not None \
                and queue_depth + n_cases > self.queue_limit:
            return _reject(REASON_SATURATED, tenant,
                           limit=self.queue_limit,
                           current=queue_depth,
                           detail="scheduler queue is saturated; "
                                  "retry with backoff")
        q = self.config.quota(tenant)
        mine = [r for r in active if r.tenant == tenant]
        if q.max_queued is not None and len(mine) + 1 > q.max_queued:
            return _reject(REASON_MAX_QUEUED, tenant,
                           limit=q.max_queued, current=len(mine),
                           detail="tenant has too many queued/running "
                                  "jobs; wait for completions")
        if q.max_inflight_work is not None:
            inflight = sum(r.work() for r in mine)
            if inflight + work > q.max_inflight_work:
                return _reject(REASON_MAX_WORK, tenant,
                               limit=q.max_inflight_work,
                               current=inflight,
                               detail="tenant inflight work "
                                      "(cells x niter x cases) over "
                                      "quota")
        return None


def _reject(reason: str, tenant: str, limit, current, detail: str) -> dict:
    return {"error": "quota exceeded", "reason": reason, "tenant": tenant,
            "limit": limit, "current": current, "detail": detail,
            "retry_after_s": 1.0}


class TokenAuth:
    """Per-tenant bearer tokens, checked at the door (before admission).

    An empty token table means the gateway is open (the default, and
    what every pre-auth deployment gets).  With tokens configured, a
    submission must carry ``Authorization: Bearer <secret>`` matching
    the token of the tenant it claims — compared constant-time so the
    check leaks nothing about prefix matches."""

    def __init__(self, tokens: Optional[dict[str, str]] = None) -> None:
        self.tokens = dict(tokens or {})

    @classmethod
    def parse(cls, specs: Sequence[str] = ()) -> "TokenAuth":
        """CLI surface: repeatable ``--token TENANT=SECRET``."""
        tokens = {}
        for spec in specs:
            name, sep, secret = str(spec).partition("=")
            if not sep or not name.strip() or not secret:
                raise ValueError(
                    f"--token needs TENANT=SECRET, got {spec!r}")
            tokens[name.strip()] = secret
        return cls(tokens)

    @property
    def enabled(self) -> bool:
        return bool(self.tokens)

    def check(self, tenant: str, presented: Optional[str]) -> bool:
        """True when ``presented`` is the tenant's secret (or auth is
        off).  Unknown tenants are compared against a dummy so timing
        does not reveal which tenant names exist."""
        if not self.tokens:
            return True
        if not presented:
            return False
        expected = self.tokens.get(tenant)
        if expected is None:
            hmac.compare_digest(presented, "invalid-tenant-placeholder")
            return False
        return hmac.compare_digest(presented, expected)

    def tenant_for(self, presented: Optional[str]) -> Optional[str]:
        """The tenant whose secret is ``presented`` (None when nothing
        matches or auth is off).  Compares against *every* configured
        secret — no early exit — so timing does not reveal which entry
        matched."""
        if not self.tokens or not presented:
            return None
        match = None
        for tenant, secret in self.tokens.items():
            if hmac.compare_digest(presented, secret) and match is None:
                match = tenant
        return match


@dataclasses.dataclass(frozen=True)
class RateSpec:
    """Token-bucket parameters: sustained ``rps`` with ``burst`` room."""

    rps: float
    burst: float

    @classmethod
    def parse(cls, spec: str) -> "RateSpec":
        """``RPS[:BURST]``, e.g. ``5`` or ``5:20`` (burst defaults to
        max(1, rps))."""
        parts = str(spec).split(":")
        if len(parts) not in (1, 2):
            raise ValueError(f"rate must be RPS[:BURST], got {spec!r}")
        rps = float(parts[0])
        if rps <= 0:
            raise ValueError(f"rate rps must be > 0, got {spec!r}")
        burst = float(parts[1]) if len(parts) == 2 else max(1.0, rps)
        if burst < 1:
            raise ValueError(f"rate burst must be >= 1, got {spec!r}")
        return cls(rps=rps, burst=burst)


class RateLimiter:
    """Per-tenant token buckets below the auth check, above admission.

    Distinct failure domain from quotas: a 429 with
    ``reason="rate_limited"`` means "slow down your request *rate*",
    while the quota reasons mean "you hold too much *inflight work*".
    ``clock`` is injectable for deterministic tests."""

    def __init__(self, default: Optional[RateSpec] = None,
                 tenants: Optional[dict[str, RateSpec]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.default = default
        self.tenants = dict(tenants or {})
        self._clock = clock
        self._lock = locks.make_lock("gateway.tenancy.RateLimiter._lock")
        # tenant -> [tokens, last_refill_ts]
        self._buckets: dict[str, list[float]] = {}

    @classmethod
    def parse(cls, default_spec: Optional[str] = None,
              tenant_specs: Sequence[str] = ()) -> "RateLimiter":
        """CLI surface: ``--rate-default RPS[:BURST]`` and repeatable
        ``--rate TENANT=RPS[:BURST]``."""
        default = RateSpec.parse(default_spec) if default_spec else None
        tenants = {}
        for spec in tenant_specs:
            name, sep, rhs = str(spec).partition("=")
            if not sep or not name.strip():
                raise ValueError(
                    f"--rate needs TENANT=RPS[:BURST], got {spec!r}")
            tenants[name.strip()] = RateSpec.parse(rhs)
        return cls(default=default, tenants=tenants)

    @property
    def enabled(self) -> bool:
        return self.default is not None or bool(self.tenants)

    def allow(self, tenant: str) -> Optional[dict]:
        """``None`` to accept; a structured 429 body (with
        ``retry_after_s`` = time until one token refills) to reject."""
        spec = self.tenants.get(tenant, self.default)
        if spec is None:
            return None
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = [spec.burst, now]
                self._buckets[tenant] = bucket
            tokens, last = bucket
            tokens = min(spec.burst, tokens + (now - last) * spec.rps)
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                bucket[1] = now
                return None
            bucket[0] = tokens
            bucket[1] = now
            retry_after = (1.0 - tokens) / spec.rps
        return {"error": "rate limited", "reason": REASON_RATE,
                "tenant": tenant, "limit": spec.rps,
                "current": round(tokens, 4),
                "detail": "tenant request rate over limit; slow down",
                "retry_after_s": round(retry_after, 4)}
