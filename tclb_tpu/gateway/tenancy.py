"""Per-tenant quotas and admission control — plain python only.

Quotas bound two things per tenant: how many jobs may sit queued or
running at once (``max_queued``), and how much *work* those jobs may
represent (``max_inflight_work`` = sum of cells x niter x cases — the
same working-set arithmetic the batch cap uses, so a tenant cannot park
one enormous job inside a small job count).  On top of the per-tenant
limits, a global ``queue_limit`` backpressures everyone using the
scheduler's queue-depth signal.

Rejections are structured (HTTP 429 with ``reason``/``limit``/
``current``) so clients can distinguish "you are over quota" from "the
pod is saturated" and back off accordingly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from tclb_tpu.gateway.jobs import TERMINAL, JobRecord

#: rejection reasons (stable API + metrics label values)
REASON_MAX_QUEUED = "tenant_max_queued"
REASON_MAX_WORK = "tenant_max_inflight_work"
REASON_SATURATED = "queue_saturated"


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Limits for one tenant; ``None`` disables a limit."""

    max_queued: Optional[int] = 64
    max_inflight_work: Optional[int] = None

    @classmethod
    def parse(cls, spec: str) -> "TenantQuota":
        """``QUEUED[:WORK]`` with ``-`` for unlimited, e.g. ``8:1e9``."""
        parts = str(spec).split(":")
        if len(parts) not in (1, 2):
            raise ValueError(f"quota must be QUEUED[:WORK], got {spec!r}")

        def num(s: str) -> Optional[int]:
            s = s.strip()
            if s in ("", "-"):
                return None
            return int(float(s))
        work = num(parts[1]) if len(parts) == 2 else None
        return cls(max_queued=num(parts[0]), max_inflight_work=work)


@dataclasses.dataclass
class TenancyConfig:
    """The quota table: per-tenant overrides over a default."""

    default: TenantQuota = dataclasses.field(default_factory=TenantQuota)
    tenants: dict[str, TenantQuota] = dataclasses.field(
        default_factory=dict)

    def quota(self, tenant: str) -> TenantQuota:
        return self.tenants.get(tenant, self.default)

    @classmethod
    def parse(cls, default_spec: Optional[str] = None,
              tenant_specs: Sequence[str] = ()) -> "TenancyConfig":
        """CLI surface: ``--quota-default 8:1e9`` and repeatable
        ``--quota tenant=QUEUED[:WORK]``."""
        default = (TenantQuota.parse(default_spec)
                   if default_spec else TenantQuota())
        tenants = {}
        for spec in tenant_specs:
            name, sep, rhs = str(spec).partition("=")
            if not sep or not name.strip():
                raise ValueError(
                    f"--quota needs tenant=QUEUED[:WORK], got {spec!r}")
            tenants[name.strip()] = TenantQuota.parse(rhs)
        return cls(default=default, tenants=tenants)


class AdmissionController:
    """Stateless admission decisions over the store + scheduler signals.

    ``admit`` returns ``None`` to accept, or a structured rejection dict
    (the 429 body) naming the reason, the limit hit, and the current
    level — computed from the tenant's non-terminal records plus the
    global queue depth the scheduler's status provider reports."""

    def __init__(self, config: Optional[TenancyConfig] = None,
                 queue_limit: Optional[int] = 1024) -> None:
        self.config = config or TenancyConfig()
        self.queue_limit = queue_limit

    def admit(self, tenant: str, n_cases: int, work: int,
              active: Sequence[JobRecord],
              queue_depth: int = 0) -> Optional[dict]:
        active = [r for r in active if r.status not in TERMINAL]
        if self.queue_limit is not None \
                and queue_depth + n_cases > self.queue_limit:
            return _reject(REASON_SATURATED, tenant,
                           limit=self.queue_limit,
                           current=queue_depth,
                           detail="scheduler queue is saturated; "
                                  "retry with backoff")
        q = self.config.quota(tenant)
        mine = [r for r in active if r.tenant == tenant]
        if q.max_queued is not None and len(mine) + 1 > q.max_queued:
            return _reject(REASON_MAX_QUEUED, tenant,
                           limit=q.max_queued, current=len(mine),
                           detail="tenant has too many queued/running "
                                  "jobs; wait for completions")
        if q.max_inflight_work is not None:
            inflight = sum(r.work() for r in mine)
            if inflight + work > q.max_inflight_work:
                return _reject(REASON_MAX_WORK, tenant,
                               limit=q.max_inflight_work,
                               current=inflight,
                               detail="tenant inflight work "
                                      "(cells x niter x cases) over "
                                      "quota")
        return None


def _reject(reason: str, tenant: str, limit, current, detail: str) -> dict:
    return {"error": "quota exceeded", "reason": reason, "tenant": tenant,
            "limit": limit, "current": current, "detail": detail,
            "retry_after_s": 1.0}
