"""Persistent job store: append-only JSONL journal + atomic snapshots.

Durability model (the checkpoint subsystem's idioms, applied to job
metadata):

* every record mutation appends one ``{"op": "put", "record": ...}``
  line to ``journal.jsonl`` (line-buffered — a SIGKILL loses at most the
  line being written, never corrupts earlier ones);
* every ``snapshot_every`` puts the whole store is compacted into
  ``store.json`` via :func:`tclb_tpu.checkpoint.writer.atomic_write_bytes`
  (temp + fsync + rename — readers never see a torn snapshot) and the
  journal is truncated;
* ``load()`` replays snapshot-then-journal, so a restarted gateway
  recovers every queued/running/done record (:mod:`service` then
  re-enqueues the non-terminal ones).

The store root also anchors per-job checkpoint trees
(:meth:`JobStore.ckpt_root` — ``<root>/ckpt/<job_id>``).  In pod mode
this directory is the cross-host resume contract: every host-agent must
see the same filesystem at the same path (NFS or equivalent), because a
resumable job requeued off a dead host re-enters from
``CheckpointManager.latest()`` under this root on whichever surviving
host picks it up.

Thread-safe; jax-free (HTTP handler threads write records directly).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from tclb_tpu import faults, telemetry
from tclb_tpu.checkpoint import writer
from tclb_tpu.gateway.jobs import TERMINAL, JobRecord
from tclb_tpu.telemetry import locks

SNAPSHOT_EVERY = 256


class JobStore:
    """Durable ``job_id -> JobRecord`` map with idempotency-key lookup.

    ``retain_secs`` (None = keep forever) is the result-retention TTL:
    terminal records whose ``finished_ts`` is older than the TTL are
    garbage-collected during snapshot compaction (and their idempotency
    keys released)."""

    def __init__(self, root: str,
                 snapshot_every: int = SNAPSHOT_EVERY,
                 retain_secs: Optional[float] = None) -> None:
        self.root = os.path.abspath(root)
        self.snapshot_every = max(1, int(snapshot_every))
        self.retain_secs = None if retain_secs is None else float(retain_secs)
        self.degraded = False
        self._snap_path = os.path.join(self.root, "store.json")
        self._journal_path = os.path.join(self.root, "journal.jsonl")
        # two-lock split: ``_lock`` guards the in-memory index (the
        # request path: get/put-index/records) and is never held across
        # IO; ``_io_lock`` serializes durable writes (journal appends,
        # snapshot compaction, handle swaps).  Only ``_io_lock -> _lock``
        # nesting is permitted, so the order graph stays acyclic.
        self._lock = locks.make_lock("gateway.store.JobStore._lock")
        self._io_lock = locks.make_lock("gateway.store.JobStore._io_lock")
        self._records: dict[str, JobRecord] = {}
        # (tenant, idempotency_key) -> job id; a client retry after a
        # dropped connection maps to the existing record, never a dupe
        self._idem: dict[tuple[str, str], str] = {}
        self._seq = 0
        self._puts_since_snapshot = 0
        # True when the last append may have ended mid-line (IO error
        # or injected torn write): the next append leads with "\n" so
        # the torn fragment stays its own unparseable line instead of
        # swallowing the following record
        self._tail_torn = False
        self._gc_horizon = 0.0
        self._last_gc_check = 0.0
        self._journal = None
        os.makedirs(self.root, exist_ok=True)
        self._load()
        self._open_journal()

    # -- recovery ----------------------------------------------------------- #

    def _load(self) -> None:
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path) as fh:
                    doc = json.load(fh)
                self._seq = int(doc.get("seq", 0))
                self._gc_horizon = float(doc.get("gc_horizon") or 0.0)
                for rd in doc.get("records", []):
                    self._index(JobRecord.from_dict(rd))
            except (OSError, ValueError, TypeError, KeyError):
                # a torn snapshot cannot happen (atomic rename), but a
                # hand-edited one can; fall back to the journal alone
                self._records.clear()
                self._idem.clear()
                self._gc_horizon = 0.0
        if os.path.exists(self._journal_path):
            with open(self._journal_path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        doc = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn final line from a kill mid-write
                    if doc.get("op") == "put":
                        try:
                            rec = JobRecord.from_dict(doc["record"])
                        except (TypeError, KeyError):
                            continue
                        cur = self._records.get(rec.id)
                        if cur is not None and \
                                (cur.updated_ts or 0.0) > \
                                (rec.updated_ts or 0.0):
                            # a crash between the snapshot rename and the
                            # journal truncate leaves a pre-compaction
                            # tail: never regress a newer snapshot image
                            continue
                        if cur is None and \
                                (rec.updated_ts or 0.0) < self._gc_horizon:
                            # absent from the snapshot yet older than its
                            # compaction horizon: a TTL-GC'd record in the
                            # pre-truncate tail — do not resurrect it (or
                            # its idempotency key)
                            continue
                        self._index(rec)
                        self._seq = max(self._seq, _seq_of(rec.id))

    def _index(self, rec: JobRecord) -> None:
        self._records[rec.id] = rec
        if rec.idempotency_key:
            self._idem[(rec.tenant, rec.idempotency_key)] = rec.id

    def _open_journal(self) -> None:
        self._journal = open(self._journal_path, "a", buffering=1)

    # -- mutation ----------------------------------------------------------- #

    def new_id(self) -> str:
        with self._lock:
            self._seq += 1
            return "j-%06d" % self._seq

    def put(self, rec: JobRecord) -> None:
        """Journal one record state (insert or overwrite), compacting
        into an atomic snapshot every ``snapshot_every`` puts.

        Journal and snapshot IO failures (disk full, torn write, a
        handle a failed compaction left closed) *degrade* the store —
        the in-memory index stays authoritative and serving continues —
        they never propagate into the request path.  Failed puts still
        count toward the snapshot trigger, so a degraded store keeps
        re-attempting compaction (which restores durability and clears
        the flag) instead of staying memory-only until ``close()``.

        The in-memory index is updated under ``_lock`` *before* the
        journal append takes ``_io_lock``, so readers (and the HTTP
        status path) never wait behind disk IO; concurrent puts of
        different jobs may journal out of index order, which replay
        already tolerates via the ``updated_ts`` regression guard."""
        line = json.dumps({"op": "put", "record": rec.to_dict()}) + "\n"
        with self._lock:
            self._index(rec)
            self._puts_since_snapshot += 1
            want_snapshot = self._puts_since_snapshot >= self.snapshot_every
        with self._io_lock:
            if self._journal is None:
                # a late daemon thread finishing after close(): the
                # final snapshot already captured everything durable
                return
            try:
                mode = faults.fire("store.journal", job=rec.id)
                if self._tail_torn:
                    # the previous append may have ended mid-line: lead
                    # with a newline so replay drops one unparseable
                    # fragment, not this record concatenated onto it
                    # concurrency-ok[blocking]: _io_lock IS the durable-
                    # write mutex; the request path holds only _lock
                    self._journal.write("\n")
                    self._tail_torn = False
                if mode == "torn":
                    # concurrency-ok[blocking]: _io_lock serializes IO
                    self._journal.write(line[:max(1, len(line) // 2)])
                    self._tail_torn = True
                else:
                    # concurrency-ok[blocking]: _io_lock serializes IO
                    self._journal.write(line)
            except (OSError, ValueError, faults.InjectedFault) as e:
                self._tail_torn = True
                self._degrade(e, job=rec.id)
        if want_snapshot:
            self._try_snapshot(job=rec.id)

    def _degrade(self, exc: BaseException, job: str = "-") -> None:
        if not self.degraded:
            self.degraded = True
            telemetry.event("gateway.store_degraded",
                            error=repr(exc), job=job)
            telemetry.counter("gateway.store_degraded")

    def _try_snapshot(self, job: str = "-") -> bool:
        """``snapshot()`` under the same degrade-never-raise contract
        as the journal append: a failed compaction (ENOSPC on the
        atomic write, journal reopen failure) marks the store degraded
        and resets the put counter, so the next ``snapshot_every`` puts
        trigger a retry rather than hammering every request.  Always
        called with *no* store lock held (it takes both internally)."""
        try:
            self.snapshot()
            return True
        except ValueError as e:
            if str(e) == "store is closed":
                return False  # lost a benign race with close(); not a fault
            self._reset_put_counter()
            self._degrade(e, job=job)
            return False
        except OSError as e:
            self._reset_put_counter()
            self._degrade(e, job=job)
            return False

    def _reset_put_counter(self) -> None:
        with self._lock:
            self._puts_since_snapshot = 0

    def _expired(self, now: float) -> list[JobRecord]:
        if self.retain_secs is None:
            return []
        cutoff = now - self.retain_secs
        return [r for r in self._records.values()
                if r.status in TERMINAL
                and r.finished_ts is not None and r.finished_ts < cutoff]

    def snapshot(self) -> str:
        """Compact the whole store into ``store.json`` (fsync + rename)
        and truncate the journal.  Retention GC happens here: terminal
        records past the TTL are dropped from the compacted image, and
        the snapshot carries the GC horizon so a pre-truncate journal
        tail can never resurrect them on replay.

        Holds ``_io_lock`` for the whole compaction (serializing against
        journal appends) but ``_lock`` only for the in-memory GC and the
        image capture — readers are never blocked behind the fsync."""
        with self._io_lock:
            if self._journal is None:
                raise ValueError("store is closed")
            now = time.time()
            with self._lock:
                expired = self._expired(now)
                for rec in expired:
                    self._records.pop(rec.id, None)
                    if rec.idempotency_key:
                        self._idem.pop((rec.tenant,
                                        rec.idempotency_key), None)
                doc = {"seq": self._seq,
                       "records": [r.to_dict()
                                   for r in self._records.values()]}
                if self.retain_secs is not None:
                    doc["gc_horizon"] = now
                    self._gc_horizon = now
            if expired:
                telemetry.event("gateway.store_gc", removed=len(expired),
                                retain_secs=self.retain_secs)
                telemetry.counter("gateway.store_gc", len(expired))
            # concurrency-ok[blocking]: the fsync+rename is the point of
            # _io_lock; only journal appends contend, never readers
            writer.atomic_write_bytes(
                self._snap_path,
                json.dumps(doc, indent=1).encode())
            # the snapshot is durable; only now truncate the journal.
            # If the reopen fails the old handle keeps appending — the
            # stale tail is dropped on replay by the ts/horizon guards.
            new_journal = open(self._journal_path, "w", buffering=1)
            old, self._journal = self._journal, new_journal
            try:
                old.close()
            except OSError:
                pass
            self._tail_torn = False
            with self._lock:
                self._puts_since_snapshot = 0
            self.degraded = False
            return self._snap_path

    def maybe_gc(self, now: Optional[float] = None) -> bool:
        """Opportunistic TTL compaction for an *idle* store.  Put-driven
        snapshots never fire without traffic, so the service worker
        ticks this from its idle loop; it is a no-op without a TTL,
        when nothing has expired, or within the rate-limit interval."""
        if self.retain_secs is None:
            return False
        now = time.time() if now is None else now
        with self._lock:
            interval = max(1.0, min(self.retain_secs, 60.0))
            if now - self._last_gc_check < interval:
                return False
            self._last_gc_check = now
            if not self._expired(now):
                return False
        # compaction runs lock-free (snapshot takes what it needs); a
        # close() racing in is caught by _try_snapshot's closed check
        return self._try_snapshot()

    def close(self) -> None:
        # degrade-safe: if the final compaction fails (disk still full)
        # the journal keeps whatever it has — a restart replays it
        # instead of losing the shutdown
        self._try_snapshot()
        with self._io_lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    # -- queries ------------------------------------------------------------ #

    def ckpt_root(self, job_id: str) -> str:
        """Canonical per-job checkpoint directory under the store root.
        One definition on purpose: the serving path saves here and the
        pod's cross-host resume contract (module doc) restores from
        here — they must never drift apart."""
        return os.path.join(self.root, "ckpt", job_id)

    def get(self, job_id: str) -> Optional[JobRecord]:
        with self._lock:
            return self._records.get(job_id)

    def find_idempotent(self, tenant: str,
                        key: Optional[str]) -> Optional[JobRecord]:
        if not key:
            return None
        with self._lock:
            jid = self._idem.get((tenant, key))
            return self._records.get(jid) if jid else None

    def records(self, tenant: Optional[str] = None,
                status: Optional[str] = None) -> list[JobRecord]:
        with self._lock:
            out = list(self._records.values())
        if tenant is not None:
            out = [r for r in out if r.tenant == tenant]
        if status is not None:
            out = [r for r in out if r.status == status]
        return sorted(out, key=lambda r: r.id)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def _seq_of(job_id: str) -> int:
    try:
        return int(job_id.rsplit("-", 1)[-1])
    except ValueError:
        return 0
