"""Gateway service: admitted job records -> Scheduler submissions.

The split that keeps the HTTP layer device-clean:

* **handler-thread side** (``submit``/``job``/``jobs``/``result``/
  ``cancel``/``_status``) — validation, admission control, store writes,
  long-poll waits on plain ``threading.Event``s.  Zero jax.
* **worker side** — a dispatcher thread turns queued records into
  :class:`~tclb_tpu.serve.scheduler.JobSpec` bursts on the shared
  :class:`Scheduler` (same-class cases of *different tenants* still bin
  into one batched dispatch), and per-job threads drive **resumable**
  jobs: the solve runs as checkpoint-sized segments through the same
  scheduler rails, saving through :class:`CheckpointManager` after each
  segment, so a SIGKILLed worker restarts from ``latest()`` instead of
  iteration 0.  Every segment reuses one AOT-compiled executable (the
  cache never keys on base state).

Restart recovery: ``start()`` replays the job store and re-enqueues
every non-terminal record — queued jobs run from scratch, resumable ones
from their newest valid checkpoint (``gateway.resumed`` event).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Optional

from tclb_tpu import faults, telemetry
from tclb_tpu.checkpoint.manager import CheckpointSaveError
from tclb_tpu.gateway import jobs as J
from tclb_tpu.gateway.jobs import JobRecord, ValidationError
from tclb_tpu.gateway.store import JobStore
from tclb_tpu.gateway.tenancy import (AdmissionController, RateLimiter,
                                      TenancyConfig, TokenAuth)
from tclb_tpu.telemetry import live as tlive
from tclb_tpu.telemetry import locks
from tclb_tpu.utils import log


def _now() -> float:
    return round(time.time(), 6)


def _state_digest(state) -> str:
    """Content hash of a case's final fields — the bit-parity handle a
    client can compare across serving paths (opt-in via ``digest``)."""
    import hashlib

    import numpy as np
    arr = np.ascontiguousarray(np.asarray(state.fields))
    return hashlib.sha256(arr.tobytes()).hexdigest()


class GatewayService:
    """The gateway's engine room: store + admission + scheduler glue."""

    def __init__(self, store_root: str,
                 tenancy: Optional[TenancyConfig] = None,
                 queue_limit: Optional[int] = 1024,
                 scheduler: Optional[Any] = None,
                 max_batch: Optional[int] = None,
                 cache: Optional[Any] = None,
                 checkpoint_keep: int = 2,
                 max_resumable: int = 4,
                 auth: Optional[TokenAuth] = None,
                 rate: Optional[RateLimiter] = None,
                 retain_secs: Optional[float] = None,
                 pool: Optional[Any] = None) -> None:
        self.store = JobStore(store_root, retain_secs=retain_secs)
        self.admission = AdmissionController(tenancy,
                                             queue_limit=queue_limit)
        self.auth = auth or TokenAuth()
        self.rate = rate or RateLimiter()
        self._cache = cache
        self._sched = scheduler
        self._owns_sched = scheduler is None
        self._max_batch = max_batch
        self.checkpoint_keep = max(1, int(checkpoint_keep))
        self._work: queue.Queue[str] = queue.Queue()
        self._done_events: dict[str, threading.Event] = {}
        # latest in-situ progress sample per job (seq-numbered); the
        # /stream long-poll waits on the condition for a fresher one
        self._progress: dict[str, dict] = {}
        self._progress_cond = threading.Condition(
            locks.make_lock("gateway.service.GatewayService._progress_cond"))
        self._cancel: set[str] = set()
        # scheduler job id -> (record id, case index) for async fan-in
        self._pending_cases: dict[int, tuple[str, int]] = {}
        self._case_slots: dict[str, list] = {}
        self._lock = locks.make_rlock("gateway.service.GatewayService._lock")
        self._closing = False
        self._draining = False
        # process isolation: with a WorkerPool attached, solve jobs run
        # in supervised worker SUBPROCESSES (serve/pool.py) and this
        # process never touches jax — a wedged device or native crash
        # kills one worker, never the front door
        self._pool = pool
        self._pool_threads = 0
        self._worker: Optional[threading.Thread] = None
        self._status_fn = None  # the exact callable given to register_status
        self._resume_sem = threading.Semaphore(max(1, int(max_resumable)))
        # plain-python tallies for /status (metrics live in the registry)
        self._admitted = 0
        self._rejected: dict[str, int] = {}
        self._resumed = 0

    # -- lifecycle ---------------------------------------------------------- #

    @property
    def cache(self):
        """The scheduler's compiled-executable cache (built on start)."""
        return self._cache

    def start(self) -> "GatewayService":
        if self._worker is not None:
            return self
        tlive.enable_live()  # gateway events -> /metrics registry
        # pin ONE bound method: unregister_status only evicts the exact
        # object it was given (attribute access rebinds each time)
        self._status_fn = self._status
        tlive.register_status("gateway", self._status_fn)
        if self._pool is not None:
            self._pool.start()
        elif self._sched is None:
            from tclb_tpu.serve.cache import CompiledCache
            from tclb_tpu.serve.scheduler import Scheduler
            if self._cache is None:
                self._cache = CompiledCache()
            # concurrency-ok[unguarded]: written before the worker
            # thread exists; Thread.start() publishes it (happens-before)
            self._sched = Scheduler(max_batch=self._max_batch,
                                    cache=self._cache,
                                    on_result=self._on_sched_result,
                                    autostart=True)
        elif self._cache is None:
            self._cache = getattr(self._sched, "cache", None)
        self._recover()
        self._worker = threading.Thread(target=self._loop,
                                        name="tclb-gateway-worker",
                                        daemon=True)
        self._worker.start()
        return self

    def _recover(self) -> None:
        """Re-enqueue every non-terminal record from the journal — a
        restarted gateway picks its queue back up; resumable jobs will
        restore from their newest checkpoint when they run."""
        for rec in self.store.records():
            if rec.status in J.TERMINAL:
                continue
            if rec.status == J.RUNNING:
                rec.status = J.QUEUED
                rec.touch()
                self.store.put(rec)
            telemetry.event("gateway.recovered", job_id=rec.id,
                            tenant=rec.tenant, resumable=rec.resumable)
            with self._lock:
                self._done_events.setdefault(rec.id, threading.Event())
            self._work.put(rec.id)

    def close(self, wait: bool = True) -> None:
        self._closing = True
        started = self._worker is not None
        if wait and started:
            self._worker.join(timeout=30)
        if self._pool is not None:
            self._pool.close(wait=wait)
        if self._owns_sched and self._sched is not None:
            self._sched.close(wait=wait)
        if self._status_fn is not None:
            tlive.unregister_status("gateway", self._status_fn)
            self._status_fn = None
        if started:  # balance start()'s enable_live refcount
            tlive.disable_live()
        self.store.close()

    def __enter__(self) -> "GatewayService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- handler-thread API (zero device work) ------------------------------ #

    def submit(self, body: Any, tenant: Optional[str] = None,
               idempotency_key: Optional[str] = None,
               auth_token: Optional[str] = None) -> tuple[int, dict]:
        """Validate + admit + persist + enqueue one submission; returns
        ``(http_status, response_doc)``.  Safe on HTTP handler threads:
        no jax, no device work — the worker thread does the heavy part.

        Door order: auth (401) -> rate limit (429, ``rate_limited``) ->
        validation (400) -> admission control (429, quota reasons)."""
        if self._closing or self._draining:
            return 503, {"error": "gateway is draining"
                                  if self._draining and not self._closing
                                  else "gateway is shutting down",
                         "retry_after_s": 5}
        try:
            faults.fire("gateway.request", op="submit")
        except (OSError, faults.InjectedFault) as e:
            # the request fails, the gateway does not
            return 500, {"error": "internal error", "detail": repr(e)}
        if not isinstance(body, dict):
            return 400, {"error": "invalid job",
                         "detail": "body must be a JSON object"}
        tenant = (tenant or body.get("tenant") or "default").strip()
        idem = idempotency_key or body.get("idempotency_key")
        if not self.auth.check(tenant, auth_token):
            telemetry.event("gateway.unauthorized", tenant=tenant)
            telemetry.counter("gateway.unauthorized")
            return 401, {"error": "unauthorized", "tenant": tenant,
                         "detail": "missing or wrong bearer token for "
                                   "this tenant"}
        limited = self.rate.allow(tenant)
        if limited is not None:
            with self._lock:
                self._rejected[limited["reason"]] = \
                    self._rejected.get(limited["reason"], 0) + 1
            telemetry.event("gateway.rejected", tenant=tenant,
                            reason=limited["reason"],
                            model=body.get("model"))
            telemetry.counter("gateway.jobs.rejected")
            return 429, limited
        try:
            derived = J.validate_body(body,
                                      known_models=self._model_names())
        except ValidationError as e:
            return 400, {"error": "invalid job", "detail": str(e)}
        work = (derived["cells"] * derived["niter"]
                * derived["n_cases"])
        with self._lock:
            existing = self.store.find_idempotent(tenant, idem)
            if existing is not None:
                return 200, {"job": existing.public(),
                             "deduplicated": True}
            rejection = self.admission.admit(
                tenant, derived["n_cases"], work,
                self.store.records(), queue_depth=self._queue_depth())
            if rejection is not None:
                self._rejected[rejection["reason"]] = \
                    self._rejected.get(rejection["reason"], 0) + 1
                telemetry.event("gateway.rejected", tenant=tenant,
                                reason=rejection["reason"],
                                model=body.get("model"))
                telemetry.counter("gateway.jobs.rejected")
                return 429, rejection
            now = _now()
            rec = JobRecord(id=self.store.new_id(), tenant=tenant,
                            body=dict(body), idempotency_key=idem,
                            created_ts=now, updated_ts=now, **derived)
            self.store.put(rec)
            self._done_events[rec.id] = threading.Event()
            self._admitted += 1
        telemetry.event("gateway.admitted", job_id=rec.id, tenant=tenant,
                        model=body.get("model"), n_cases=rec.n_cases,
                        niter=rec.niter, resumable=rec.resumable)
        telemetry.counter("gateway.jobs.admitted")
        self._work.put(rec.id)
        return 202, {"job": rec.public()}

    def _deny(self, rec: Optional[JobRecord], job_id: str,
              auth_token: Optional[str]) -> Optional[tuple[int, dict]]:
        """Auth gate for per-record reads/cancel: ``None`` when the
        caller may see the record, else the ``(code, doc)`` refusal.
        With tokens configured, no token at all is 401; a token for a
        *different* tenant gets the same 404 a nonexistent id does, so
        record existence stays tenant-scoped."""
        if self.auth.enabled and auth_token is None:
            telemetry.event("gateway.unauthorized", op="read")
            telemetry.counter("gateway.unauthorized")
            return 401, {"error": "unauthorized",
                         "detail": "missing bearer token"}
        if rec is None or not self.auth.check(rec.tenant, auth_token):
            return 404, {"error": f"no such job {job_id!r}"}
        return None

    def job(self, job_id: str,
            auth_token: Optional[str] = None) -> tuple[int, dict]:
        rec = self.store.get(job_id)
        denied = self._deny(rec, job_id, auth_token)
        if denied is not None:
            return denied
        return 200, {"job": rec.public()}

    def jobs(self, tenant: Optional[str] = None,
             status: Optional[str] = None,
             auth_token: Optional[str] = None) -> tuple[int, dict]:
        """List job records.  With tokens configured the listing is
        scoped to the token's tenant (401 without a valid token, 403
        when an explicit ``tenant`` filter names somebody else)."""
        if self.auth.enabled:
            authed = self.auth.tenant_for(auth_token)
            if authed is None:
                telemetry.event("gateway.unauthorized", op="list")
                telemetry.counter("gateway.unauthorized")
                return 401, {"error": "unauthorized",
                             "detail": "missing or wrong bearer token"}
            if tenant is not None and tenant != authed:
                return 403, {"error": "forbidden", "tenant": tenant,
                             "detail": "tenant filter does not match "
                                       "the presented token"}
            tenant = authed
        recs = self.store.records(tenant=tenant, status=status)
        return 200, {"jobs": [r.public() for r in recs],
                     "count": len(recs)}

    def result(self, job_id: str, wait: Optional[float] = None,
               auth_token: Optional[str] = None) -> tuple[int, dict]:
        """The job's outcome; ``wait`` long-polls (bounded) on a plain
        event until the job is terminal.  202 while still in flight."""
        rec = self.store.get(job_id)
        denied = self._deny(rec, job_id, auth_token)
        if denied is not None:
            return denied
        if wait and rec.status not in J.TERMINAL:
            with self._lock:
                ev = self._done_events.setdefault(job_id,
                                                  threading.Event())
            ev.wait(timeout=min(float(wait), 300.0))
            rec = self.store.get(job_id) or rec
        if rec.status not in J.TERMINAL:
            return 202, {"job": rec.public()}
        return 200, {"job": rec.public(), "results": rec.results}

    def stream(self, job_id: str, wait: Optional[float] = None,
               since: Optional[int] = None,
               auth_token: Optional[str] = None) -> tuple[int, dict]:
        """The latest in-situ progress sample for a running job
        (iteration / MLUPS / wall / opt-in downsampled reductions).
        ``wait`` long-polls (bounded) until a sample with ``seq`` >
        ``since`` arrives or the job goes terminal — a dashboard polls
        this for kilobytes instead of field dumps.  Handler-thread safe:
        plain dict reads under a condition, zero device work."""
        rec = self.store.get(job_id)
        denied = self._deny(rec, job_id, auth_token)
        if denied is not None:
            return denied
        floor = int(since) if since is not None else 0
        deadline = (time.monotonic() + min(float(wait), 300.0)
                    if wait else None)
        with self._progress_cond:
            while True:
                entry = self._progress.get(job_id)
                if entry is not None and entry["seq"] > floor:
                    break
                if rec.status in J.TERMINAL or deadline is None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._progress_cond.wait(timeout=min(remaining, 1.0))
                rec = self.store.get(job_id) or rec
            entry = self._progress.get(job_id)
        return 200, {"job_id": job_id, "status": rec.status,
                     "seq": 0 if entry is None else entry["seq"],
                     "progress": (None if entry is None
                                  else entry["sample"])}

    def _on_pool_progress(self, pj) -> None:
        """Pool ``on_progress`` fan-in: stash the worker's latest sample
        under the gateway record id and wake /stream long-polls."""
        rec_id = pj.doc.get("job_id")
        if rec_id is None or pj.progress is None:
            return
        sample = dict(pj.progress)
        with self._progress_cond:
            prev = self._progress.get(rec_id)
            self._progress[rec_id] = {
                "seq": (1 if prev is None else prev["seq"] + 1),
                "sample": sample}
            self._progress_cond.notify_all()

    def cancel(self, job_id: str,
               auth_token: Optional[str] = None) -> tuple[int, dict]:
        """Cancel a job.  Queued jobs cancel immediately; a running
        resumable job stops at its next segment boundary; a running
        non-resumable job is already inside a device dispatch and cannot
        be aborted (409).  Same token gate as the reads: with auth on,
        only the record's tenant can cancel it."""
        with self._lock:
            rec = self.store.get(job_id)
            denied = self._deny(rec, job_id, auth_token)
            if denied is not None:
                return denied
            if rec.status in J.TERMINAL:
                return 200, {"job": rec.public()}
            self._cancel.add(job_id)
            if rec.status == J.QUEUED:
                self._finish_locked(rec, J.CANCELLED)
                return 200, {"job": rec.public()}
        if rec.resumable:
            return 202, {"job": rec.public(),
                         "detail": "cancelling at the next segment "
                                   "boundary"}
        return 409, {"job": rec.public(),
                     "error": "job is inside a device dispatch; "
                              "non-resumable jobs cannot be aborted "
                              "mid-flight"}

    def health(self) -> dict:
        """Liveness/readiness fragment for ``/healthz`` (handler-thread
        safe, zero device work).  Liveness is unconditional: a process
        that answers is live.  Readiness goes false while draining /
        closing, or when a worker pool is attached and zero workers are
        live."""
        workers = (None if self._pool is None
                   else self._pool.live_workers())
        ready = not (self._closing or self._draining) \
            and (workers is None or workers > 0)
        doc: dict[str, Any] = {"live": True, "ready": ready,
                               "draining": self._draining,
                               "closing": self._closing}
        if workers is not None:
            doc["workers_live"] = workers
        live_hosts = getattr(self._pool, "live_hosts", None)
        if live_hosts is not None:
            doc["hosts_live"] = live_hosts()
        return doc

    def hosts(self) -> tuple[int, dict]:
        """Pod membership view for ``GET /v1/hosts`` (cluster mode
        only): enrollment state, lanes, heartbeat ages, and dead-host
        dumps straight from the :class:`HostRegistry`."""
        registry = getattr(self._pool, "registry", None)
        if registry is None:
            return 404, {"error": "no cluster control plane attached "
                                  "(start the gateway with --cluster)"}
        return 200, registry.snapshot()

    def drain(self, grace_s: float = 30.0) -> None:
        """Graceful shutdown, phase one: stop admission (submits answer
        503 + Retry-After, readiness goes false), let in-flight
        resumable jobs reach a segment boundary — each boundary is
        already checkpointed, so their records park back to QUEUED and
        the next incarnation resumes from ``latest()`` — then flush a
        store snapshot.  The caller (SIGTERM drain hook, ``close``)
        decides when the process actually exits."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        telemetry.event("gateway.draining", store=self.store.root)
        telemetry.counter("gateway.drained")
        # phase two: give in-flight work a bounded chance to finish (or,
        # for resumable jobs, to park at an already-checkpointed segment
        # boundary) ...
        deadline = time.monotonic() + max(0.0, float(grace_s))
        while time.monotonic() < deadline:
            if not any(r.status == J.RUNNING
                       for r in self.store.records()):
                break
            time.sleep(0.05)
        # ... then kill what's left: pool workers die, their PoolJob
        # handles fail, and _run_pooled parks those records back to
        # QUEUED (anything that slips through is flipped RUNNING->QUEUED
        # by _recover on the next start — no job lost either way)
        if self._pool is not None:
            self._pool.close(wait=False)
            park_by = time.monotonic() + 5.0
            while time.monotonic() < park_by:
                with self._lock:
                    if self._pool_threads == 0:
                        break
                time.sleep(0.05)
        try:
            self.store.snapshot()
        except Exception as e:  # noqa: BLE001 — drain must not crash;
            log.warning(f"gateway: drain snapshot failed: {e!r}")
            # the journal already holds every record

    def _status(self) -> dict:
        """Plain-python /status provider fragment."""
        by_status: dict[str, int] = {}
        for rec in self.store.records():
            by_status[rec.status] = by_status.get(rec.status, 0) + 1
        with self._lock:
            rejected = dict(self._rejected)
            admitted = self._admitted
            resumed = self._resumed
        cache = self._cache
        return {
            "store": self.store.root,
            "jobs": by_status,
            "backlog": self._work.qsize(),
            "admitted": admitted,
            "rejected": rejected,
            "resumed": resumed,
            "cache": cache.stats() if cache is not None else None,
            "draining": self._draining,
            "closing": self._closing,
        }

    # -- handler-safe helpers ----------------------------------------------- #

    _models_cache: Optional[list] = None

    def _model_names(self) -> list:
        if GatewayService._models_cache is None:
            from tclb_tpu.models import list_models
            GatewayService._models_cache = list(list_models())
        return GatewayService._models_cache

    def _queue_depth(self) -> int:
        depth = self._work.qsize()
        sched = self._sched
        if sched is not None:
            try:
                depth += int(sched._status().get("queue_depth", 0))
            except Exception:  # noqa: BLE001 — a signal, not a contract
                pass
        return depth

    # -- worker side (jax-touching) ----------------------------------------- #

    def _loop(self) -> None:
        while not self._closing:
            try:
                jid = self._work.get(timeout=0.2)
            except queue.Empty:
                # put-driven snapshots never fire without traffic; let
                # an idle gateway still expire TTL'd results (jax-free)
                self.store.maybe_gc()
                continue
            rec = self.store.get(jid)
            if rec is None or rec.status != J.QUEUED:
                continue
            try:
                if self._pool is not None:
                    with self._lock:
                        self._pool_threads += 1
                    threading.Thread(target=self._run_pooled,
                                     args=(rec,), daemon=True,
                                     name=f"tclb-gateway-{rec.id}"
                                     ).start()
                elif rec.resumable:
                    threading.Thread(target=self._run_resumable,
                                     args=(rec,), daemon=True,
                                     name=f"tclb-gateway-{rec.id}"
                                     ).start()
                else:
                    self._dispatch(rec)
            except BaseException as e:  # noqa: BLE001 — per-job verdict
                log.warning(f"gateway: job {rec.id} failed to "
                            f"dispatch: {e!r}")
                rec.error = repr(e)
                with self._lock:
                    self._finish_locked(rec, J.FAILED)

    def _job_pieces(self, rec: JobRecord):
        """Model / dtypes / cases for one record (worker thread only)."""
        import jax
        import jax.numpy as jnp

        from tclb_tpu.control.sweep import expand_grid
        from tclb_tpu.models import get_model
        model = get_model(rec.body["model"])
        precision = rec.body.get("precision", "f32")
        if precision == "f64":
            jax.config.update("jax_enable_x64", True)
        dtype = jnp.float64 if precision == "f64" else jnp.float32
        sdt = {"bf16": jnp.bfloat16, "f32": jnp.float32,
               "f64": jnp.float64}.get(rec.body.get("storage_dtype"))
        srepr = rec.body.get("storage_repr")
        cases = expand_grid(rec.body.get("sweep") or {})
        return model, dtype, sdt, srepr, cases

    def _dispatch(self, rec: JobRecord) -> None:
        """Submit one record's cases as an atomic burst — same-class
        cases (across records AND tenants) bin into batched dispatches
        on the shared scheduler."""
        from tclb_tpu.serve.scheduler import JobSpec
        model, dtype, sdt, srepr, cases = self._job_pieces(rec)
        shape = tuple(int(s) for s in rec.body["shape"])
        params = dict(rec.body.get("params") or {})
        specs = [JobSpec(model=model, shape=shape, case=c,
                         niter=rec.niter, dtype=dtype, storage_dtype=sdt,
                         storage_repr=srepr,
                         base_settings=params or None,
                         timeout_s=rec.body.get("timeout_s"),
                         tenant=rec.tenant,
                         name=f"{rec.id}/{c.name or i}")
                 for i, c in enumerate(cases)]
        rec.status = J.RUNNING
        rec.started_ts = _now()
        rec.touch()
        self.store.put(rec)
        with self._lock:
            self._case_slots[rec.id] = [None] * len(specs)
        jobs = self._sched.submit_many(specs)
        with self._lock:
            for i, j in enumerate(jobs):
                self._pending_cases[j.id] = (rec.id, i)

    def _on_sched_result(self, job) -> None:
        """Scheduler ``on_result`` fan-in: collect per-case outcomes and
        finish the record once every case is terminal."""
        with self._lock:
            ref = self._pending_cases.pop(job.id, None)
            if ref is None:
                return  # a resumable segment (driven synchronously)
            rec_id, idx = ref
            slots = self._case_slots.get(rec_id)
            if slots is None:
                return
            slots[idx] = job
            if any(s is None for s in slots):
                return
            del self._case_slots[rec_id]
            rec = self.store.get(rec_id)
        if rec is None:
            return
        results, errors = [], []
        digest = bool(rec.body.get("digest"))
        for s in slots:
            if s.status == "done":
                r = s._result
                row = {"name": r.case.name,
                       "settings": dict(r.case.settings),
                       "globals": r.globals}
                if digest:
                    row["state_sha256"] = _state_digest(r.state)
                results.append(row)
            else:
                results.append({"name": s.spec.name,
                                "error": repr(s.error)})
                errors.append(repr(s.error))
        rec.results = results
        if errors:
            rec.error = "; ".join(errors[:4])
        rec.progress_iter = rec.niter if not errors else rec.progress_iter
        with self._lock:
            self._finish_locked(rec, J.FAILED if errors else J.DONE)

    def _ckpt_root(self, job_id: str) -> str:
        # the store owns the layout (and the pod's shared-filesystem
        # resume contract documented there)
        return self.store.ckpt_root(job_id)

    def _run_pooled(self, rec: JobRecord) -> None:
        """Drive one record through the process-isolated worker pool.
        This thread never touches jax: it builds plain-JSON pool docs,
        waits on :class:`~tclb_tpu.serve.pool.PoolJob` handles, and
        collects plain-python results — the solve lives in supervised
        worker subprocesses.  A failure while draining/closing parks the
        record back to QUEUED (resumable jobs re-enter from their newest
        checkpoint, non-resumable ones rerun from scratch) instead of
        failing it — the no-lost-jobs half of graceful drain."""
        try:
            self._run_pooled_inner(rec)
        except BaseException as e:  # noqa: BLE001 — per-job verdict
            if self._draining or self._closing:
                rec.status = J.QUEUED
                rec.touch()
                self.store.put(rec)
                telemetry.event("gateway.parked", job_id=rec.id,
                                tenant=rec.tenant, reason=repr(e))
            else:
                log.warning(f"gateway: pooled job {rec.id} "
                            f"failed: {e!r}")
                rec.error = repr(e)
                with self._lock:
                    self._finish_locked(rec, J.FAILED)
        finally:
            with self._lock:
                self._pool_threads -= 1

    def _run_pooled_inner(self, rec: JobRecord) -> None:
        from tclb_tpu.control.sweep import expand_grid
        body = rec.body
        params = dict(body.get("params") or {})
        base = {"model": body["model"],
                "shape": [int(s) for s in body["shape"]],
                "niter": rec.niter,
                "dtype": ("f64" if body.get("precision") == "f64"
                          else "f32"),
                "storage_dtype": body.get("storage_dtype"),
                "storage_repr": body.get("storage_repr"),
                "params": params,
                "timeout_s": body.get("timeout_s"),
                "digest": bool(body.get("digest")),
                # cross-process trace context: the worker stamps relayed
                # events with this record id + parent span, so one
                # `telemetry report --job` timeline spans both processes
                "job_id": rec.id,
                "parent_span": f"gw-{rec.id}",
                # progress frames are on by default for gateway jobs
                # (cheap: a small JSON frame per solve chunk)
                "progress": True}
        if body.get("stream"):
            base["stream"] = body["stream"]
        if rec.resumable:
            # validate_body guarantees resumable => exactly one case
            docs = [dict(base,
                         case={"name": rec.id, "settings": {}},
                         ckpt_root=self._ckpt_root(rec.id),
                         checkpoint_every=(rec.checkpoint_every
                                           or max(1, rec.niter // 10)),
                         checkpoint_keep=self.checkpoint_keep)]
            cases = [None]
        else:
            cases = expand_grid(body.get("sweep") or {})
            docs = [dict(base,
                         case={"name": c.name or str(i),
                               "settings": dict(c.settings)})
                    for i, c in enumerate(cases)]
        rec.status = J.RUNNING
        rec.started_ts = _now()
        rec.touch()
        self.store.put(rec)
        handles = [self._pool.submit(d, on_progress=self._on_pool_progress)
                   for d in docs]
        results, errors = [], []
        phases: dict[str, float] = {}
        for i, (pj, doc) in enumerate(zip(handles, docs)):
            name = doc["case"]["name"]
            try:
                res = pj.result()
            except BaseException as e:  # noqa: BLE001 — per-case verdict
                if self._draining or self._closing:
                    raise  # park the whole record for the next run
                results.append({"name": name, "error": repr(e)})
                errors.append(repr(e))
                continue
            for k, v in (res.get("phases") or {}).items():
                phases[k] = round(phases.get(k, 0.0) + float(v), 6)
            row = {"name": name,
                   "settings": doc["case"]["settings"],
                   "globals": res.get("globals") or {}}
            if res.get("state_sha256"):
                row["state_sha256"] = res["state_sha256"]
            if res.get("host") is not None:
                # pod mode: record which host served each case, so a
                # sweep's spread across the pod is auditable from the
                # job record alone
                row["host"] = res["host"]
            results.append(row)
            resumed = res.get("resumed_from")
            if rec.resumable:
                rec.progress_iter = int(res.get("iteration")
                                        or rec.niter)
                if resumed is not None:
                    rec.resumed_from = int(resumed)
                    with self._lock:
                        self._resumed += 1
                    telemetry.event("gateway.resumed", job_id=rec.id,
                                    tenant=rec.tenant, step=resumed,
                                    lane=res.get("lane"),
                                    host=res.get("host"))
                    telemetry.counter("gateway.jobs.resumed")
        rec.results = results
        rec.phases = phases or None
        if errors:
            rec.error = "; ".join(errors[:4])
        else:
            rec.progress_iter = rec.niter
        if rec.id in self._cancel:
            # the work already ran to completion in a worker; honor the
            # intent on the record without discarding the results
            with self._lock:
                self._finish_locked(rec, J.CANCELLED)
            return
        with self._lock:
            self._finish_locked(rec, J.FAILED if errors else J.DONE)

    def _run_resumable(self, rec: JobRecord) -> None:
        with self._resume_sem:
            try:
                self._run_resumable_inner(rec)
            except CheckpointSaveError as e:
                # survivable save failure (e.g. disk full after the
                # emergency prune): this job fails *resumable* — its
                # newest committed checkpoint is intact, so a re-submit
                # (or restart) picks up from there.  The process lives.
                log.warning(f"gateway: resumable job {rec.id} failed on "
                            f"checkpoint save: {e}")
                rec.error = str(e)
                rec.error_kind = f"checkpoint_{e.kind}"
                with self._lock:
                    self._finish_locked(rec, J.FAILED)
            except BaseException as e:  # noqa: BLE001 — per-job verdict
                log.warning(f"gateway: resumable job {rec.id} "
                            f"failed: {e!r}")
                rec.error = repr(e)
                with self._lock:
                    self._finish_locked(rec, J.FAILED)

    def _run_resumable_inner(self, rec: JobRecord) -> None:
        """Drive one long job as checkpoint-sized segments through the
        scheduler.  Each segment is a ``JobSpec`` whose plan continues
        from the previous segment's final state (``init_on_run=False``
        + ``rebase``); after each segment the lattice is saved through
        :class:`CheckpointManager`.  On entry, a newest valid checkpoint
        (from a previous incarnation of this process) short-circuits the
        already-done prefix — the kill-resume contract, through the
        serving path.  Segment boundaries are deterministic, so the
        resumed trajectory is bit-identical to an uninterrupted one."""
        import numpy as np

        from tclb_tpu.checkpoint.manager import CheckpointManager
        from tclb_tpu.core.lattice import Lattice
        from tclb_tpu.serve.ensemble import Case, EnsemblePlan
        from tclb_tpu.serve.scheduler import JobSpec
        model, dtype, sdt, srepr, _ = self._job_pieces(rec)
        shape = tuple(int(s) for s in rec.body["shape"])
        params = dict(rec.body.get("params") or {})
        niter = rec.niter
        lat = Lattice(model, shape, dtype=dtype, storage_dtype=sdt,
                      storage_repr=srepr, settings=params or None)
        mgr = CheckpointManager(self._ckpt_root(rec.id),
                                keep_last=self.checkpoint_keep)
        newest = mgr.latest()
        if newest is not None:
            mgr.restore(lat, newest)
            start = int(np.asarray(lat.state.iteration))
            rec.resumed_from = start
            with self._lock:
                self._resumed += 1
            telemetry.event("gateway.resumed", job_id=rec.id,
                            tenant=rec.tenant, step=start, path=newest)
            telemetry.counter("gateway.jobs.resumed")
        else:
            lat.init()
            start = 0
        rec.status = J.RUNNING
        rec.started_ts = _now()
        rec.progress_iter = start
        rec.touch()
        self.store.put(rec)
        every = rec.checkpoint_every or max(1, niter // 10)
        plan = EnsemblePlan(model, shape, dtype=dtype, storage_dtype=sdt,
                            storage_repr=srepr, base=lat,
                            init_on_run=False)
        done = start
        while done < niter:
            if self._draining and done > start:
                # graceful drain: the segment just finished is already
                # checkpointed — park the record so the next incarnation
                # resumes from latest() bit-identically
                rec.status = J.QUEUED
                rec.touch()
                self.store.put(rec)
                telemetry.event("gateway.parked", job_id=rec.id,
                                tenant=rec.tenant, step=done)
                return
            if rec.id in self._cancel or self._closing:
                with self._lock:
                    self._finish_locked(rec, J.CANCELLED)
                return
            seg = min(every, niter - done)
            spec = JobSpec(model=model, shape=shape,
                           case=Case(name=rec.id), niter=seg,
                           dtype=dtype, storage_dtype=sdt,
                           storage_repr=srepr, plan=plan,
                           tenant=rec.tenant, bin_tag=f"gw-{rec.id}",
                           timeout_s=rec.body.get("timeout_s"),
                           name=f"{rec.id}@{done}")
            r = self._sched.submit(spec).result()
            plan.rebase(r.state)
            lat.state = r.state
            done += seg
            mgr.save(lat, step=done)
            rec.progress_iter = done
            rec.touch()
            self.store.put(rec)
        mgr.wait()
        row = {"name": rec.id, "settings": params,
               "globals": lat.get_globals()}
        if rec.body.get("digest"):
            row["state_sha256"] = _state_digest(lat.state)
        rec.results = [row]
        with self._lock:
            self._finish_locked(rec, J.DONE)

    # -- completion --------------------------------------------------------- #

    def _finish_locked(self, rec: JobRecord, status: str) -> None:
        """Terminal transition + durable write + wakeups.  Caller holds
        ``_lock`` (or is single-threaded on this record)."""
        rec.status = status
        rec.finished_ts = _now()
        rec.touch()
        self.store.put(rec)
        self._cancel.discard(rec.id)
        ev = self._done_events.setdefault(rec.id, threading.Event())
        ev.set()
        # wake /stream long-polls so a terminal job answers immediately
        with self._progress_cond:
            self._progress_cond.notify_all()
        wait_s = (None if rec.started_ts is None
                  else round(rec.started_ts - rec.created_ts, 6))
        ph = rec.phases or {}
        telemetry.event("gateway.job_done", job_id=rec.id,
                        tenant=rec.tenant, status=status,
                        queue_wait_s=wait_s,
                        stage_s=ph.get("stage_s"),
                        solve_s=ph.get("solve_s"),
                        d2h_s=ph.get("d2h_s"),
                        wall_s=round(rec.finished_ts - rec.created_ts, 6),
                        resumed=rec.resumed_from is not None)
        telemetry.counter("gateway.jobs.done" if status == J.DONE
                          else "gateway.jobs.failed")
