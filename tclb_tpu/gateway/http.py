"""Gateway HTTP front door: job submission and retrieval over the wire.

A stdlib-threaded (``http.server.ThreadingHTTPServer``) API surface over
:class:`~tclb_tpu.gateway.service.GatewayService`:

* ``POST /v1/jobs``                — submit one job (202), idempotent
  retries via ``X-Idempotency-Key`` (200 + ``deduplicated``), quota
  rejections as structured 429, validation problems as 400;
* ``GET /v1/jobs[?tenant=&status=]`` — list job records;
* ``GET /v1/jobs/<id>``            — one record;
* ``GET /v1/jobs/<id>/result?wait=N`` — outcome; ``wait`` long-polls on
  a plain event until the job is terminal (202 while in flight);
* ``GET /v1/jobs/<id>/stream?wait=N&since=K`` — latest in-situ progress
  sample (iteration / MLUPS / wall / opt-in downsampled reductions);
  ``wait`` long-polls until a sample newer than ``since`` arrives or the
  job goes terminal — a dashboard costs kilobytes, not field dumps;
* ``DELETE /v1/jobs/<id>`` (or ``POST /v1/jobs/<id>/cancel``) — cancel;
* ``GET /v1/hosts``                — pod membership (cluster mode):
  enrollment state, lanes, heartbeat ages, dead-host dumps; 404 when
  the gateway serves through local lanes instead of a pod.  Read-only
  operational telemetry, unauthenticated like ``/healthz``;
* ``GET /healthz``                 — liveness (200 while the process
  answers at all);
* ``GET /healthz/ready`` (alias ``/readyz``) — readiness: 503 +
  ``Retry-After`` while the gateway is draining/closing or a worker
  pool has zero live workers — the signal a load balancer uses to stop
  routing before a rolling restart.

The tenant comes from the ``X-Tclb-Tenant`` header (or the body's
``tenant`` key).  With ``--token TENANT=SECRET`` configured, *every*
``/v1/jobs`` route requires ``Authorization: Bearer <secret>``: a
submission must carry the token of the tenant it claims (401 at the
door, before admission control), listings are scoped to the
authenticated tenant, and per-job reads/cancels of another tenant's
record answer the same 404 a nonexistent id gets.  Without tokens,
multi-tenancy is a scoping mechanism, not a security boundary.

Hygiene contract (enforced by ``analysis.hygiene.device_work_in_gateway``):
nothing in this module may touch jax, ``device_put``, or ``Lattice``
state — handler threads only validate, write store records, and wait on
events; the service's worker threads do every device-touching step.  A
slow or hostile client can therefore never fence, allocate on, or
deadlock a device.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

_INDEX = (b"tclb_tpu gateway\n"
          b"  POST   /v1/jobs                   submit a job\n"
          b"  GET    /v1/jobs                   list jobs\n"
          b"  GET    /v1/jobs/<id>              job record\n"
          b"  GET    /v1/jobs/<id>/result?wait=N  outcome (long-poll)\n"
          b"  GET    /v1/jobs/<id>/stream?wait=N  latest progress sample "
          b"(long-poll)\n"
          b"  DELETE /v1/jobs/<id>              cancel\n"
          b"  GET    /v1/hosts                  pod membership (cluster)\n"
          b"  GET    /healthz                   liveness\n"
          b"  GET    /healthz/ready             readiness (503 draining)\n")

_MAX_BODY = 4 * 1024 * 1024  # a submission body is metadata, not data


class _Handler(BaseHTTPRequestHandler):
    server_version = "tclb-gateway"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr
        pass

    @property
    def service(self):
        return self.server.service  # attached by GatewayServer.start

    # -- plumbing ----------------------------------------------------------- #

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc: dict) -> None:
        body = json.dumps(doc, indent=2, default=str).encode()
        if code in (429, 503) and "retry_after_s" in doc:
            # surfaced as a real header too, for naive clients
            self.send_response(code)
            self.send_header("Retry-After",
                             str(int(float(doc["retry_after_s"]) + 0.5)
                                 or 1))
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body) + 1))
            self.end_headers()
            self.wfile.write(body + b"\n")
            return
        self._send(code, body + b"\n", "application/json")

    def _bearer(self) -> Optional[str]:
        """The ``Authorization: Bearer <secret>`` token, if presented."""
        auth = self.headers.get("Authorization") or ""
        scheme, _, token = auth.partition(" ")
        if scheme.lower() == "bearer" and token.strip():
            return token.strip()
        return None

    def _read_body(self) -> Optional[dict]:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0 or n > _MAX_BODY:
            return None
        try:
            return json.loads(self.rfile.read(n).decode())
        except (ValueError, UnicodeDecodeError):
            return None

    # -- routes ------------------------------------------------------------- #

    def do_POST(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if parts[:2] == ["v1", "jobs"] and len(parts) == 2:
                body = self._read_body()
                if body is None:
                    self._send_json(400, {"error": "body must be a JSON "
                                                   "object"})
                    return
                code, doc = self.service.submit(
                    body,
                    tenant=self.headers.get("X-Tclb-Tenant"),
                    idempotency_key=self.headers.get("X-Idempotency-Key"),
                    auth_token=self._bearer())
                self._send_json(code, doc)
            elif parts[:2] == ["v1", "jobs"] and len(parts) == 4 \
                    and parts[3] == "cancel":
                code, doc = self.service.cancel(
                    parts[2], auth_token=self._bearer())
                self._send_json(code, doc)
            else:
                self._send_json(404, {"error": "no such route"})
        except BrokenPipeError:  # pragma: no cover — client went away
            pass
        except Exception as e:  # noqa: BLE001 — a request must never
            try:                # kill the gateway
                self._send_json(500, {"error": repr(e)})
            except Exception:  # noqa: BLE001
                pass

    def do_DELETE(self):  # noqa: N802 — http.server API
        parts = [p for p in urlparse(self.path).path.split("/") if p]
        try:
            if parts[:2] == ["v1", "jobs"] and len(parts) == 3:
                code, doc = self.service.cancel(
                    parts[2], auth_token=self._bearer())
                self._send_json(code, doc)
            else:
                self._send_json(404, {"error": "no such route"})
        except BrokenPipeError:  # pragma: no cover
            pass
        except Exception as e:  # noqa: BLE001
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:  # noqa: BLE001
                pass

    def do_GET(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        qs = parse_qs(url.query)
        try:
            if parts == ["healthz"]:
                # liveness: a process that answers is live, full stop —
                # a draining gateway must keep serving reads/results
                h = self.service.health()
                self._send_json(200, {"ok": True, **h})
            elif parts in (["healthz", "ready"], ["readyz"]):
                h = self.service.health()
                if h.get("ready"):
                    self._send_json(200, {"ok": True, **h})
                else:
                    self._send_json(503, {"ok": False,
                                          "retry_after_s": 5, **h})
            elif parts == ["v1", "hosts"]:
                code, doc = self.service.hosts()
                self._send_json(code, doc)
            elif parts[:2] == ["v1", "jobs"] and len(parts) == 2:
                code, doc = self.service.jobs(
                    tenant=(qs.get("tenant") or [None])[0],
                    status=(qs.get("status") or [None])[0],
                    auth_token=self._bearer())
                self._send_json(code, doc)
            elif parts[:2] == ["v1", "jobs"] and len(parts) == 3:
                code, doc = self.service.job(parts[2],
                                             auth_token=self._bearer())
                self._send_json(code, doc)
            elif parts[:2] == ["v1", "jobs"] and len(parts) == 4 \
                    and parts[3] == "result":
                wait = float((qs.get("wait") or ["0"])[0])
                code, doc = self.service.result(parts[2], wait=wait,
                                                auth_token=self._bearer())
                self._send_json(code, doc)
            elif parts[:2] == ["v1", "jobs"] and len(parts) == 4 \
                    and parts[3] == "stream":
                wait = float((qs.get("wait") or ["0"])[0])
                since = (qs.get("since") or [None])[0]
                code, doc = self.service.stream(
                    parts[2], wait=wait,
                    since=None if since is None else int(since),
                    auth_token=self._bearer())
                self._send_json(code, doc)
            elif not parts:
                self._send(200, _INDEX, "text/plain; charset=utf-8")
            else:
                self._send_json(404, {"error": "no such route"})
        except BrokenPipeError:  # pragma: no cover
            pass
        except Exception as e:  # noqa: BLE001
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:  # noqa: BLE001
                pass


class GatewayServer:
    """The network front door: a daemon-threaded HTTP server bound to a
    :class:`GatewayService`.  ``start()`` starts the service (recovery +
    worker) then the listener; ``stop()`` tears both down."""

    def __init__(self, service, host: str = "127.0.0.1",
                 port: int = 0) -> None:
        self.service = service
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return "http://%s:%d" % (self.host, self.port)

    def start(self) -> "GatewayServer":
        if self._server is not None:
            return self
        self.service.start()
        try:
            srv = ThreadingHTTPServer((self.host, self.port), _Handler)
        except Exception:
            self.service.close()
            raise
        srv.daemon_threads = True
        srv.service = self.service
        self._server = srv
        self.port = srv.server_address[1]
        self._thread = threading.Thread(target=srv.serve_forever,
                                        name="tclb-gateway-http",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        srv, self._server = self._server, None
        if srv is None:
            return
        try:
            srv.shutdown()
            srv.server_close()
        finally:
            self.service.close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "GatewayServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
