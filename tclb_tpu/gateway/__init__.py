"""Network front door for the serving stack: HTTP job submission over
the existing :class:`~tclb_tpu.serve.scheduler.Scheduler` rails.

The gateway is the multi-tenant pod service the ROADMAP's "network
serving plane" direction names: everything below the socket already
exists (batched ensembles, the compiled-executable cache, fleet lanes,
the monitor plane) — this package adds the socket:

* :mod:`~tclb_tpu.gateway.http` — the stdlib-threaded HTTP API.  The
  handler module is jax-free by static contract
  (``hygiene.device_work_in_gateway``): handler threads only validate,
  enqueue and snapshot plain-python state.
* :mod:`~tclb_tpu.gateway.store` — the persistent job store: an
  append-only JSONL journal compacted into atomic snapshots with the
  checkpoint subsystem's fsync+rename helpers, so a gateway restart
  recovers every queued/running/done job record.
* :mod:`~tclb_tpu.gateway.tenancy` — per-tenant quotas and admission
  control (structured 429s) over queue-depth signals.
* :mod:`~tclb_tpu.gateway.service` — the jax-touching side: worker
  threads that turn admitted records into ``JobSpec`` submissions, and
  checkpoint-backed resumability for long jobs (periodic
  ``CheckpointManager`` saves; a killed worker restarts from
  ``latest()`` instead of iteration 0).
"""

from tclb_tpu.gateway.jobs import (CANCELLED, DONE, FAILED, QUEUED,  # noqa: F401
                                   RUNNING, TERMINAL, JobRecord,
                                   ValidationError, validate_body)
from tclb_tpu.gateway.service import GatewayService  # noqa: F401
from tclb_tpu.gateway.store import JobStore  # noqa: F401
from tclb_tpu.gateway.tenancy import (AdmissionController,  # noqa: F401
                                      RateLimiter, RateSpec,
                                      TenancyConfig, TenantQuota,
                                      TokenAuth)

__all__ = [
    "JobRecord", "JobStore", "GatewayService", "AdmissionController",
    "RateLimiter", "RateSpec", "TenancyConfig", "TenantQuota", "TokenAuth",
    "ValidationError", "validate_body",
    "QUEUED", "RUNNING", "DONE", "FAILED", "CANCELLED", "TERMINAL",
]
