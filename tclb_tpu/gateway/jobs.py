"""Gateway job records and submission validation — plain python only.

A :class:`JobRecord` is the durable unit the store journals and the HTTP
API serves back: the validated submission body plus lifecycle state.
Everything in this module is JSON-round-trippable and jax-free — records
are built and mutated on HTTP handler threads, which must never touch
device state (the ``service`` worker threads do the jax work).
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Optional

QUEUED, RUNNING = "queued", "running"
DONE, FAILED, CANCELLED = "done", "failed", "cancelled"
TERMINAL = frozenset({DONE, FAILED, CANCELLED})

#: submission knobs the validator understands; anything else is a 400
#: (catching typos like "iterations" for "niter" at the door)
_KNOWN_KEYS = frozenset({
    "model", "shape", "niter", "params", "sweep", "precision",
    "storage_dtype", "storage_repr", "resumable", "checkpoint_every",
    "timeout_s", "tenant", "idempotency_key", "name", "digest",
    "stream",
})

_PRECISIONS = ("f32", "f64")
_STORAGE_DTYPES = ("f32", "f64", "bf16")
_STORAGE_REPRS = ("raw", "shifted")


class ValidationError(ValueError):
    """A malformed submission body (HTTP 400)."""


@dataclasses.dataclass
class JobRecord:
    """One durable gateway job: the validated body + lifecycle state."""

    id: str
    tenant: str = "default"
    body: dict = dataclasses.field(default_factory=dict)
    status: str = QUEUED
    idempotency_key: Optional[str] = None
    created_ts: float = 0.0
    updated_ts: float = 0.0
    started_ts: Optional[float] = None
    finished_ts: Optional[float] = None
    # derived sizing, used by admission control (cells x niter x cases)
    n_cases: int = 1
    cells: int = 0
    niter: int = 0
    resumable: bool = False
    checkpoint_every: int = 0
    progress_iter: int = 0
    resumed_from: Optional[int] = None
    error: Optional[str] = None
    # failure class for structured errors ("checkpoint_enospc", ...);
    # lets clients distinguish failed-resumable jobs from hard failures
    error_kind: Optional[str] = None
    # per-case outcome dicts ({name, settings, globals}) once done
    results: Optional[list] = None
    # summed per-phase wall times from the workers (stage_s / solve_s /
    # d2h_s) — the SLO breakdown stamped onto gateway.job_done
    phases: Optional[dict] = None

    def work(self) -> int:
        """The admission-control cost of this job: cells x niter x cases."""
        return int(self.cells) * int(self.niter) * int(self.n_cases)

    def hosts(self) -> list:
        """Distinct pod hosts that served this job's cases, in first-use
        order (from the result rows' ``host`` stamps; empty when the job
        ran on local lanes rather than through a cluster)."""
        seen: list = []
        for row in self.results or ():
            h = row.get("host") if isinstance(row, dict) else None
            if h is not None and h not in seen:
                seen.append(h)
        return seen

    def touch(self) -> None:
        self.updated_ts = round(time.time(), 6)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, doc: dict) -> "JobRecord":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})

    def public(self) -> dict:
        """The API view: the record without the raw body's bulk."""
        doc = self.to_dict()
        doc["work"] = self.work()
        hosts = self.hosts()
        if hosts:
            doc["hosts"] = hosts
        return doc


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValidationError(msg)


def validate_body(body: Any, known_models: Optional[list] = None) -> dict:
    """Check a ``POST /v1/jobs`` body and derive the record sizing.

    Pure syntactic validation — no model objects are built and no jax is
    touched (this runs on the HTTP handler thread).  Returns a dict of
    :class:`JobRecord` field overrides (``n_cases``/``cells``/``niter``/
    ``resumable``/``checkpoint_every``).  Raises
    :class:`ValidationError` on any problem."""
    _require(isinstance(body, dict), "body must be a JSON object")
    unknown = sorted(set(body) - _KNOWN_KEYS)
    _require(not unknown, f"unknown keys: {unknown} "
             f"(accepted: {sorted(_KNOWN_KEYS)})")

    model = body.get("model")
    _require(isinstance(model, str) and model,
             "'model' must be a non-empty string")
    if known_models is not None:
        _require(model in known_models,
                 f"unknown model {model!r} (have {sorted(known_models)})")

    shape = body.get("shape")
    _require(isinstance(shape, (list, tuple)) and len(shape) in (2, 3),
             "'shape' must be a list of 2 or 3 ints")
    for s in shape:
        _require(isinstance(s, int) and not isinstance(s, bool) and s > 0,
                 f"'shape' entries must be positive ints, got {s!r}")
    cells = math.prod(int(s) for s in shape)

    niter = body.get("niter")
    _require(isinstance(niter, int) and not isinstance(niter, bool)
             and niter > 0, "'niter' must be a positive int")

    params = body.get("params", {})
    _require(isinstance(params, dict), "'params' must be an object")
    for k, v in params.items():
        _require(isinstance(k, str) and isinstance(v, (int, float))
                 and not isinstance(v, bool),
                 f"'params' entries must be name -> number, got "
                 f"{k!r}: {v!r}")

    sweep = body.get("sweep", {})
    _require(isinstance(sweep, dict), "'sweep' must be an object")
    n_cases = 1
    for k, v in sweep.items():
        _require(isinstance(k, str), "'sweep' keys must be setting names")
        n = _sweep_axis_len(k, v)
        n_cases *= n
    _require(n_cases >= 1, "'sweep' expands to zero cases")

    precision = body.get("precision", "f32")
    _require(precision in _PRECISIONS,
             f"'precision' must be one of {_PRECISIONS}")
    sdt = body.get("storage_dtype")
    _require(sdt is None or sdt in _STORAGE_DTYPES,
             f"'storage_dtype' must be one of {_STORAGE_DTYPES}")
    srepr = body.get("storage_repr")
    _require(srepr is None or srepr in _STORAGE_REPRS,
             f"'storage_repr' must be one of {_STORAGE_REPRS}")
    if srepr == "shifted":
        # shifted is an encoding of *narrowed* storage; on a full-width
        # lattice it would change the f32 bit-exact contract
        _require(sdt is not None and sdt != precision,
                 "'storage_repr': 'shifted' requires a narrowed "
                 "'storage_dtype' (e.g. 'bf16')")

    resumable = bool(body.get("resumable", False))
    every = body.get("checkpoint_every", 0)
    _require(isinstance(every, int) and not isinstance(every, bool)
             and every >= 0, "'checkpoint_every' must be an int >= 0")
    if resumable:
        _require(n_cases == 1,
                 "resumable jobs take a single case (no 'sweep'); "
                 "submit one job per point instead")
    _require(isinstance(body.get("digest", False), bool),
             "'digest' must be a bool")
    stream = body.get("stream", False)
    _require(isinstance(stream, (bool, dict)),
             "'stream' must be a bool or an object")
    if isinstance(stream, dict):
        bad = sorted(set(stream) - {"quantity", "max_dim"})
        _require(not bad, f"'stream' unknown keys: {bad} "
                 f"(accepted: ['max_dim', 'quantity'])")
        qty = stream.get("quantity")
        _require(qty is None or (isinstance(qty, str) and qty),
                 "'stream.quantity' must be a non-empty string")
        md = stream.get("max_dim")
        _require(md is None or (isinstance(md, int)
                                and not isinstance(md, bool) and md > 0),
                 "'stream.max_dim' must be a positive int")
    timeout_s = body.get("timeout_s")
    _require(timeout_s is None
             or (isinstance(timeout_s, (int, float))
                 and not isinstance(timeout_s, bool) and timeout_s > 0),
             "'timeout_s' must be a positive number")

    return {"n_cases": int(n_cases), "cells": int(cells),
            "niter": int(niter), "resumable": resumable,
            "checkpoint_every": int(every)}


def _sweep_axis_len(name: str, spec: Any) -> int:
    """Length of one sweep axis without materializing values (values
    come later, on the worker, through control.sweep.expand_grid)."""
    if isinstance(spec, (list, tuple)):
        _require(len(spec) > 0, f"sweep axis {name!r} is an empty list")
        for v in spec:
            _require(isinstance(v, (int, float))
                     and not isinstance(v, bool),
                     f"sweep axis {name!r} entries must be numbers")
        return len(spec)
    if isinstance(spec, str):
        parts = spec.split(":")
        _require(len(parts) == 3,
                 f"sweep axis {name!r} must be 'lo:hi:n' or a list")
        try:
            float(parts[0]), float(parts[1])
            n = int(parts[2])
        except ValueError:
            raise ValidationError(
                f"sweep axis {name!r}: bad range spec {spec!r}")
        _require(n >= 1, f"sweep axis {name!r}: count must be >= 1")
        return n
    raise ValidationError(
        f"sweep axis {name!r} must be a 'lo:hi:n' string or a number "
        f"list, got {type(spec).__name__}")
