"""Timing spans with honest walls and model-derived roofline metrics.

A span measures host wall-time around a region.  JAX dispatch is
asynchronous, so a naive ``perf_counter`` pair times the *enqueue*, not
the work — callers fence with :meth:`Span.sync` (``jax.block_until_ready``
on the region's output) before the span closes, the same discipline
bench.py's ``timed`` enforces with its in-region checksum.

When the region carries enough context (``nodes``/``iters`` fields), the
span exit stamps derived metrics the way the reference prints its own
MLUPS line (reference src/main.cpp.Rt:100-126):

* ``mlups``      — ``nodes * iters / dt / 1e6``;
* ``vs_roofline`` — achieved fraction of this chip's HBM streaming
  roofline under the classical LBM traffic model (``bytes_per_node`` =
  2 x n_storage x sizeof(real) + flag read per node update) — the same
  math bench.py gates its credibility asserts on (it imports
  :data:`HBM_GBS` from here so the two can never drift).

Spans also wrap ``jax.profiler.TraceAnnotation`` when available, so a
concurrent ``jax.profiler`` capture shows the same region names.
"""

from __future__ import annotations

import re
import time
from typing import Any, Optional

from tclb_tpu.telemetry import events

# known per-chip HBM bandwidths (GB/s); unknown kinds fall back to an
# ESTIMATE flagged by roofline_known=False (bench.py additionally skips
# its credibility asserts for unknown chips)
HBM_GBS = {"TPU v5 lite": 819.0, "TPU v5e": 819.0,
           "TPU v5p": 2765.0, "TPU v4": 1228.0,
           "TPU v6 lite": 1640.0, "TPU v6e": 1640.0}
HBM_GBS_FALLBACK = 819.0

_device_kind_cache: Optional[tuple] = None


def device_kind() -> str:
    """The first device's kind (cached; '' if jax has no devices)."""
    global _device_kind_cache
    if _device_kind_cache is None:
        try:
            import jax
            _device_kind_cache = (jax.devices()[0].device_kind,)
        except Exception:  # noqa: BLE001
            _device_kind_cache = ("",)
    return _device_kind_cache[0]


def roofline_mlups(bytes_per_node: float,
                   kind: Optional[str] = None) -> tuple[float, bool]:
    """``(MLUPS ceiling, bandwidth_known)`` for the 1R+1W streaming
    traffic model on ``kind`` (default: this process's first device)."""
    if kind is None:
        kind = device_kind()
    hbm = HBM_GBS.get(kind)
    known = hbm is not None
    if hbm is None:
        hbm = HBM_GBS_FALLBACK
    return hbm * 1e9 / float(bytes_per_node) / 1e6, known


def fuse_of(engine: Optional[str]) -> int:
    """Temporal-fusion depth encoded in an engine name (the
    ``,fuse=K`` tag every fused engine carries, e.g.
    ``pallas_d3q[d3q19,fuse=3]``); 1 when absent (XLA, unfused
    engines).  bench.py and the report CLI key their per-engine
    credibility caps off this, so the tag format lives next to the
    roofline table it feeds."""
    if not engine:
        return 1
    m = re.search(r"[\[,]fuse=(-?\d+)", engine)
    return int(m.group(1)) if m else 1


class Span:
    """Context manager timing one region; emits a ``span`` event on exit.

    Only constructed when telemetry is enabled (use :func:`span`, which
    returns the shared no-op otherwise), so it may import jax freely."""

    __slots__ = ("name", "fields", "_t0", "_annotation")

    def __init__(self, name: str, fields: dict):
        self.name = name
        self.fields = fields
        self._t0 = 0.0
        self._annotation = None

    def add(self, **fields: Any) -> None:
        """Attach/overwrite fields on the pending span event."""
        self.fields.update(fields)

    def sync(self, x: Any) -> Any:
        """Fence: block until ``x`` (any pytree of jax arrays) is computed
        so the span's wall-time covers the work, not the enqueue."""
        import jax
        return jax.block_until_ready(x)

    def __enter__(self) -> "Span":
        try:
            from jax.profiler import TraceAnnotation
            self._annotation = TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:  # noqa: BLE001 — profiler is optional garnish
            self._annotation = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dt = time.perf_counter() - self._t0
        if self._annotation is not None:
            try:
                self._annotation.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001
                pass
        fields = self.fields
        if exc is not None:
            fields["ok"] = False
            fields["error"] = repr(exc)
        nodes, iters = fields.get("nodes"), fields.get("iters")
        if nodes and iters and dt > 0:
            # 6 significant digits, not 6 decimals: tiny test domains sit
            # far below 1 MLUPS and must not round to zero
            mlups = float(nodes) * float(iters) / dt / 1e6
            fields["mlups"] = float(f"{mlups:.6g}")
            bpn = fields.get("bytes_per_node")
            if bpn:
                ceiling, known = roofline_mlups(bpn)
                fields["vs_roofline"] = round(fields["mlups"] / ceiling, 4)
                fields["roofline_known"] = known
                fields["device_kind"] = device_kind()
        events.event("span", name=self.name, dur_s=round(dt, 6), **fields)
        return False


class _NoopSpan:
    """The disabled-mode span: never touches jax, files, or the clock."""

    __slots__ = ()

    def add(self, **fields: Any) -> None:
        pass

    def sync(self, x: Any) -> Any:
        return x

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NOOP_SPAN = _NoopSpan()


def span(name: str, **fields: Any):
    """A timing span over a region: ``with span("iterate", niter=n) as sp``.
    Returns the shared no-op (no timing, no sync, no emission) when
    telemetry is disabled."""
    if not events.enabled():
        return NOOP_SPAN
    return Span(name, fields)
