import sys

from tclb_tpu.telemetry.report import main

sys.exit(main())
