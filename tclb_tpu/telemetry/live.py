"""Live observability plane: in-process metrics registry + flight recorder.

Post-hoc JSONL traces (:mod:`tclb_tpu.telemetry.events`) answer "what did
this run do"; this module answers "what is this process doing *right now*"
and "what was it doing when it died" — the live counterpart of the
reference's in-situ Catalyst monitoring:

* :class:`MetricsRegistry` — gauges, monotonic counters, and fixed-bucket
  histograms derived from the already-instrumented event/span seams
  (iterate wall, MLUPS, queue wait, stage/stall, compile time).  It is a
  fan-out subscriber on :mod:`events`; the HTTP monitor
  (:mod:`tclb_tpu.telemetry.http`) serves its snapshots — the handler
  thread never touches jax or device state.
* :class:`FlightRecorder` — a bounded in-memory ring of the last ~4k
  events (deque append, no I/O), on by default inside ``serve/``, dumped
  to ``flight-<pid>.jsonl`` on failcheck, device eviction, unhandled
  dispatcher/scheduler exceptions, and SIGTERM, so a crashed serving
  process yields a post-mortem even when ``TCLB_TELEMETRY`` was never
  set.
* **status providers** — components (FleetDispatcher, Scheduler) publish
  plain-python callables that report queue depth / lane occupancy /
  inflight ages from their own thread-safe state; :func:`status_snapshot`
  assembles the ``/status`` document from those plus the registry.

Nothing here imports jax at module scope; the on-demand profiler capture
(:func:`capture_profile`) imports ``jax.profiler`` lazily on a background
thread — never on the monitor handler thread.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from tclb_tpu.telemetry import events
from tclb_tpu.telemetry import locks

_T0 = time.time()

# -- metric metadata ---------------------------------------------------------- #

#: fixed log-ish buckets for wall-time histograms (seconds)
SECONDS_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_META = {
    "tclb_iterate_seconds": ("histogram",
                             "Wall time of iterate spans (fenced)"),
    "tclb_mlups": ("gauge",
                   "MLUPS of the last iterate span, by engine/model"),
    "tclb_vs_roofline": ("gauge",
                         "Fraction of the HBM roofline achieved by the "
                         "last iterate span"),
    "tclb_iterations_total": ("counter", "Lattice iterations completed"),
    "tclb_node_updates_total": ("counter", "Lattice node updates completed"),
    "tclb_batch_seconds": ("histogram",
                           "Wall time of serve batches (scheduler and "
                           "fleet lanes)"),
    "tclb_stage_seconds": ("histogram",
                           "Host-to-device staging time per lane batch"),
    "tclb_stall_seconds": ("histogram",
                           "Staging stall exposed on the lane critical "
                           "path"),
    "tclb_queue_wait_seconds": ("histogram",
                                "Job queue wait before dispatch"),
    "tclb_compile_seconds": ("histogram",
                             "Compile (cache-miss) time of serve "
                             "executables"),
    "tclb_lane_batches_total": ("counter", "Batches served, by lane"),
    "tclb_lane_jobs_total": ("counter", "Jobs served, by lane"),
    "tclb_jobs_total": ("counter", "Serve jobs by terminal status"),
    "tclb_failchecks_total": ("counter", "NaN/Inf failcheck events"),
    "tclb_engine_fallbacks_total": ("counter", "Engine dispatch fallbacks"),
    "tclb_devices_evicted_total": ("counter",
                                   "Devices evicted from the fleet"),
    "tclb_devices_reinstated_total": ("counter",
                                      "Evicted devices probed healthy and "
                                      "returned to the fleet"),
    "tclb_faults_injected_total": ("counter",
                                   "Chaos faults injected, by point/mode"),
    "tclb_checkpoint_last_unix_ts": ("gauge",
                                     "Unix time of the last checkpoint "
                                     "save"),
    "tclb_counter_total": ("counter",
                           "Process counters from telemetry.counter(), "
                           "by name"),
    "tclb_events_total": ("counter", "Telemetry events observed, by kind"),
    "tclb_gateway_admissions_total": ("counter",
                                      "Gateway jobs admitted, by tenant"),
    "tclb_gateway_rejections_total": ("counter",
                                      "Gateway submissions rejected, by "
                                      "reason/tenant"),
    "tclb_gateway_resumed_total": ("counter",
                                   "Gateway jobs resumed from a "
                                   "checkpoint instead of iteration 0"),
    "tclb_gateway_jobs_total": ("counter",
                                "Gateway jobs finished, by terminal "
                                "status"),
    "tclb_gateway_queue_wait_seconds": ("histogram",
                                        "Gateway job wait from admission "
                                        "to first dispatch"),
    "tclb_gateway_unauthorized_total": ("counter",
                                        "Gateway submissions refused for a "
                                        "missing/wrong bearer token, by "
                                        "tenant"),
    "tclb_pool_workers_spawned_total": ("counter",
                                        "Pool worker subprocesses spawned, "
                                        "by lane"),
    "tclb_pool_workers_hung_total": ("counter",
                                     "Pool workers declared hung (missed "
                                     "heartbeat), by lane"),
    "tclb_pool_workers_killed_total": ("counter",
                                       "Pool workers killed by the "
                                       "supervisor (SIGTERM/SIGKILL "
                                       "escalation), by lane"),
    "tclb_pool_workers_restarted_total": ("counter",
                                          "Pool workers respawned after a "
                                          "crash or hang, by lane"),
    "tclb_gateway_phase_seconds": ("histogram",
                                   "Gateway job phase latency (queue_wait/"
                                   "stage/solve/d2h/e2e), by phase"),
    "tclb_cluster_hosts_enrolled_total": ("counter",
                                          "Pod host-agents enrolled, by "
                                          "host"),
    "tclb_cluster_hosts_lost_total": ("counter",
                                      "Pod host-agents lost (channel "
                                      "death or heartbeat timeout), by "
                                      "host"),
    "tclb_cluster_hosts_rejoined_total": ("counter",
                                          "Pod host-agents re-enrolled "
                                          "after a loss, by host"),
    "tclb_cluster_jobs_requeued_total": ("counter",
                                         "Cluster jobs requeued after a "
                                         "host death, by host"),
}

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Hist:
    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1 for +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        self.sum += v
        self.count += 1
        for i, le in enumerate(self.buckets):
            if v <= le:
                self.counts[i] += 1
                return
        self.counts[-1] += 1


class MetricsRegistry:
    """Thread-safe store of gauges / counters / fixed-bucket histograms.

    Series are keyed by ``(name, sorted(labels))``.  All values are plain
    python floats — reading a snapshot never touches jax, devices, or
    files, so the HTTP monitor thread can scrape mid-solve.
    """

    def __init__(self) -> None:
        self._lock = locks.make_lock("telemetry.live.MetricsRegistry._lock")
        self._gauges: dict[tuple, float] = {}
        self._counters: dict[tuple, float] = {}
        self._hists: dict[tuple, _Hist] = {}
        self._info: dict[str, Any] = {}

    @staticmethod
    def _key(name: str, labels: dict) -> tuple:
        return (name, tuple(sorted(labels.items())))

    def gauge(self, name: str, value: float, **labels: Any) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = float(value)

    def count(self, name: str, inc: float = 1.0, **labels: Any) -> None:
        with self._lock:
            k = self._key(name, labels)
            self._counters[k] = self._counters.get(k, 0.0) + float(inc)

    def observe(self, name: str, value: float,
                buckets=SECONDS_BUCKETS, **labels: Any) -> None:
        with self._lock:
            k = self._key(name, labels)
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = _Hist(buckets)
            h.observe(value)

    def set_info(self, key: str, value: Any) -> None:
        """Stash a plain-python status fragment (e.g. last-iterate doc)."""
        with self._lock:
            self._info[key] = value

    def info(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._info.get(key, default)

    def snapshot(self) -> dict:
        """Plain-dict copy of every series (for /status and tests)."""
        def label_str(lbl):
            return ",".join("%s=%s" % (k, v) for k, v in lbl)
        with self._lock:
            return {
                "gauges": {"%s{%s}" % (n, label_str(l)) if l else n: v
                           for (n, l), v in self._gauges.items()},
                "counters": {"%s{%s}" % (n, label_str(l)) if l else n: v
                             for (n, l), v in self._counters.items()},
                "histograms": {
                    "%s{%s}" % (n, label_str(l)) if l else n:
                        {"count": h.count, "sum": h.sum}
                    for (n, l), h in self._hists.items()},
                "info": dict(self._info),
            }

    def reset(self) -> None:
        with self._lock:
            self._gauges.clear()
            self._counters.clear()
            self._hists.clear()
            self._info.clear()

    # -- Prometheus text exposition ------------------------------------------ #

    def to_prometheus(self,
                      extra_counters: Optional[dict] = None) -> str:
        """Render the registry (plus ``events.counter`` totals, mapped to
        ``tclb_counter_total{name=...}``) as Prometheus text exposition
        format 0.0.4."""
        with self._lock:
            gauges = dict(self._gauges)
            counters = dict(self._counters)
            hists = {k: (h.buckets, list(h.counts), h.sum, h.count)
                     for k, h in self._hists.items()}
        if extra_counters:
            for cname, v in sorted(extra_counters.items()):
                counters[("tclb_counter_total",
                          (("name", cname),))] = float(v)

        out: list[str] = []
        seen_help: set[str] = set()

        def header(name: str, mtype: str) -> None:
            if name in seen_help:
                return
            seen_help.add(name)
            meta = _META.get(name)
            if meta:
                out.append("# HELP %s %s" % (name, meta[1]))
            out.append("# TYPE %s %s" % (name, meta[0] if meta else mtype))

        def series(name: str, labels: tuple, value: float,
                   extra_label: Optional[tuple] = None) -> None:
            lbl = list(labels)
            if extra_label:
                lbl.append(extra_label)
            if lbl:
                body = ",".join('%s="%s"' % (k, _escape_label(v))
                                for k, v in lbl)
                out.append("%s{%s} %s" % (name, body, _fmt(value)))
            else:
                out.append("%s %s" % (name, _fmt(value)))

        for (name, labels), v in sorted(gauges.items()):
            header(name, "gauge")
            series(name, labels, v)
        for (name, labels), v in sorted(counters.items()):
            header(name, "counter")
            series(name, labels, v)
        for (name, labels), (buckets, counts, hsum, hcount) in \
                sorted(hists.items()):
            header(name, "histogram")
            cum = 0
            for le, c in zip(buckets, counts):
                cum += c
                series(name + "_bucket", labels, cum, ("le", _fmt(le)))
            series(name + "_bucket", labels, hcount, ("le", "+Inf"))
            series(name + "_sum", labels, hsum)
            series(name + "_count", labels, hcount)
        return "\n".join(out) + "\n"


_registry = MetricsRegistry()
_live_refs = 0
_live_lock = locks.make_lock("telemetry.live._live_lock")


def registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _registry


def _observe(doc: dict) -> None:
    """events subscriber: derive registry metrics from one event doc.
    Runs under the events lock — plain arithmetic only."""
    reg = _registry
    kind = doc.get("kind")
    reg.count("tclb_events_total", 1.0, kind=str(kind))
    if kind == "span":
        name = doc.get("name")
        dur = doc.get("dur_s")
        if name == "iterate":
            # relayed worker spans carry a worker_pid stamp — and,
            # through an enrolled host-agent, a host stamp; both become
            # labels so per-process series survive worker restarts and
            # two hosts reusing a pid stay distinct series
            wp = doc.get("worker_pid")
            wlbl: dict = {}
            if wp is not None:
                wlbl["worker_pid"] = str(wp)
                if doc.get("host") is not None:
                    wlbl["host"] = str(doc["host"])
            if dur is not None:
                reg.observe("tclb_iterate_seconds", dur, **wlbl)
            engine = str(doc.get("engine", "?"))
            model = str(doc.get("model", "?"))
            if doc.get("mlups") is not None:
                reg.gauge("tclb_mlups", doc["mlups"], engine=engine,
                          model=model, **wlbl)
            if doc.get("vs_roofline") is not None:
                reg.gauge("tclb_vs_roofline", doc["vs_roofline"],
                          engine=engine)
            iters = doc.get("iters")
            if iters:
                reg.count("tclb_iterations_total", iters)
                nodes = doc.get("nodes")
                if nodes:
                    reg.count("tclb_node_updates_total",
                              float(nodes) * float(iters))
            last = {
                "engine": engine, "model": model,
                "mlups": doc.get("mlups"),
                "vs_roofline": doc.get("vs_roofline"),
                "iteration": doc.get("iteration"),
                "dur_s": dur, "ts": doc.get("ts"),
            }
            if wp is not None:
                last["worker_pid"] = wp
                last["lane"] = doc.get("lane")
                if doc.get("host") is not None:
                    last["host"] = doc["host"]
            reg.set_info("last_iterate", last)
        elif name in ("serve.batch", "serve.lane_batch"):
            if dur is not None:
                reg.observe("tclb_batch_seconds", dur)
            lane = doc.get("lane")
            if lane is not None:
                reg.count("tclb_lane_batches_total", 1.0, lane=str(lane))
                if doc.get("batch"):
                    reg.count("tclb_lane_jobs_total", float(doc["batch"]),
                              lane=str(lane))
            if doc.get("stage_s") is not None:
                reg.observe("tclb_stage_seconds", doc["stage_s"])
            if doc.get("stall_s") is not None:
                reg.observe("tclb_stall_seconds", doc["stall_s"])
            for w in (doc.get("wait_s") or ()):
                reg.observe("tclb_queue_wait_seconds", w)
        elif name == "serve.compile":
            if dur is not None:
                reg.observe("tclb_compile_seconds", dur)
        elif name in ("checkpoint.save", "checkpoint.restore"):
            if name == "checkpoint.save" and doc.get("ts") is not None:
                reg.gauge("tclb_checkpoint_last_unix_ts", doc["ts"])
    elif kind == "failcheck":
        reg.count("tclb_failchecks_total", 1.0)
    elif kind == "engine_fallback":
        reg.count("tclb_engine_fallbacks_total", 1.0)
    elif kind == "serve.device_evicted":
        reg.count("tclb_devices_evicted_total", 1.0,
                  lane=str(doc.get("lane", "?")))
    elif kind == "serve.device_reinstated":
        reg.count("tclb_devices_reinstated_total", 1.0,
                  lane=str(doc.get("lane", "?")))
    elif kind == "fault.injected":
        reg.count("tclb_faults_injected_total", 1.0,
                  point=str(doc.get("point", "?")),
                  mode=str(doc.get("mode", "?")))
    elif kind == "serve.job_done":
        reg.count("tclb_jobs_total", 1.0,
                  status=str(doc.get("status", "?")))
    elif kind == "gateway.admitted":
        reg.count("tclb_gateway_admissions_total", 1.0,
                  tenant=str(doc.get("tenant", "?")))
    elif kind == "gateway.unauthorized":
        reg.count("tclb_gateway_unauthorized_total", 1.0,
                  tenant=doc.get("tenant", ""))
    elif kind == "gateway.rejected":
        reg.count("tclb_gateway_rejections_total", 1.0,
                  reason=str(doc.get("reason", "?")),
                  tenant=str(doc.get("tenant", "?")))
    elif kind == "gateway.resumed":
        reg.count("tclb_gateway_resumed_total", 1.0)
    elif kind == "serve.worker_spawned":
        reg.count("tclb_pool_workers_spawned_total", 1.0,
                  lane=str(doc.get("lane", "?")))
    elif kind == "serve.worker_hung":
        reg.count("tclb_pool_workers_hung_total", 1.0,
                  lane=str(doc.get("lane", "?")))
    elif kind == "serve.worker_killed":
        reg.count("tclb_pool_workers_killed_total", 1.0,
                  lane=str(doc.get("lane", "?")))
    elif kind == "serve.worker_restarted":
        reg.count("tclb_pool_workers_restarted_total", 1.0,
                  lane=str(doc.get("lane", "?")))
    elif kind == "gateway.host_enrolled":
        reg.count("tclb_cluster_hosts_enrolled_total", 1.0,
                  host=str(doc.get("host", "?")))
    elif kind == "gateway.host_lost":
        reg.count("tclb_cluster_hosts_lost_total", 1.0,
                  host=str(doc.get("host", "?")))
    elif kind == "gateway.host_rejoined":
        reg.count("tclb_cluster_hosts_rejoined_total", 1.0,
                  host=str(doc.get("host", "?")))
    elif kind == "cluster.job_requeued":
        reg.count("tclb_cluster_jobs_requeued_total", 1.0,
                  host=str(doc.get("host", "?")))
    elif kind == "gateway.job_done":
        reg.count("tclb_gateway_jobs_total", 1.0,
                  status=str(doc.get("status", "?")))
        if doc.get("queue_wait_s") is not None:
            reg.observe("tclb_gateway_queue_wait_seconds",
                        doc["queue_wait_s"])
        # per-phase SLO histograms: one series per phase of the job's
        # door-to-result path
        for phase, field in (("queue_wait", "queue_wait_s"),
                             ("stage", "stage_s"),
                             ("solve", "solve_s"),
                             ("d2h", "d2h_s"),
                             ("e2e", "wall_s")):
            v = doc.get(field)
            if v is not None:
                reg.observe("tclb_gateway_phase_seconds", float(v),
                            phase=phase)


def enable_live() -> MetricsRegistry:
    """Subscribe the default registry to the event fan-out (refcounted);
    returns the registry."""
    global _live_refs
    with _live_lock:
        _live_refs += 1
        if _live_refs == 1:
            events.subscribe(_observe)
    return _registry


def disable_live() -> None:
    """Drop one live reference; unsubscribes the registry at zero."""
    global _live_refs
    with _live_lock:
        if _live_refs > 0:
            _live_refs -= 1
            if _live_refs == 0:
                events.unsubscribe(_observe)


def prometheus_text() -> str:
    """The full /metrics payload: registry series + process counters."""
    return _registry.to_prometheus(extra_counters=events.counters())


# -- flight recorder ---------------------------------------------------------- #

#: event kinds that trigger an automatic ring dump
DUMP_KINDS = frozenset({"failcheck", "serve.device_evicted",
                        "gateway.host_lost"})

FLIGHT_CAPACITY = 4096


class FlightRecorder:
    """Bounded in-memory ring of the last events (deque append, no I/O),
    dumped to ``flight-<pid>.jsonl`` on failcheck / eviction / unhandled
    serve exceptions / SIGTERM.  Attach/detach are refcounted so nested
    Scheduler-inside-FleetDispatcher setups share one ring."""

    def __init__(self, capacity: int = FLIGHT_CAPACITY,
                 dump_dir: Optional[str] = None) -> None:
        self.capacity = capacity
        self._ring: deque = deque(maxlen=capacity)
        self._lock = locks.make_lock("telemetry.live.FlightRecorder._lock")
        self._refs = 0
        self._dumps: list[str] = []
        self._dump_dir = dump_dir

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def attached(self) -> bool:
        return self._refs > 0

    @property
    def dumps(self) -> list[str]:
        return list(self._dumps)

    def record(self, doc: dict) -> None:
        self._ring.append(doc)
        kind = doc.get("kind")
        if kind in DUMP_KINDS:
            self.dump(reason=str(kind))
        elif kind == "fault.injected":
            # crash-mode injections (error/enospc/torn) get a dump so
            # every injected failure leaves a forensic trail; `slow`
            # injections are latency, not crashes — no dump
            from tclb_tpu import faults
            if doc.get("mode") in faults.CRASH_MODES:
                self.dump(reason=f"fault.injected:{doc.get('point')}")

    def events(self) -> list[dict]:
        return list(self._ring)

    def attach(self) -> None:
        """Subscribe the ring to the event fan-out (refcounted).  Opt out
        process-wide with ``TCLB_FLIGHT=0``."""
        if os.environ.get("TCLB_FLIGHT", "1") == "0":
            return
        with self._lock:
            self._refs += 1
            if self._refs == 1:
                events.subscribe(self.record)
        _install_sigterm_handler()

    def detach(self) -> None:
        with self._lock:
            if self._refs > 0:
                self._refs -= 1
                if self._refs == 0:
                    events.unsubscribe(self.record)

    def dump(self, reason: str, **extra: Any) -> Optional[str]:
        """Write the ring (plus one trailing ``flight_dump`` marker) to
        ``flight-<pid>.jsonl`` under ``TCLB_FLIGHT_DIR`` (default: cwd).
        Returns the path, or None when the ring is empty."""
        ring = list(self._ring)
        if not ring:
            return None
        d = self._dump_dir or os.environ.get("TCLB_FLIGHT_DIR") or os.getcwd()
        path = os.path.join(d, "flight-%d.jsonl" % os.getpid())
        marker = {"kind": "flight_dump", "ts": round(time.time(), 6),
                  "reason": reason, "events": len(ring)}
        marker.update(extra)
        try:
            os.makedirs(d, exist_ok=True)
            # concurrency-ok[signal]: dumping on the dying path is the
            # flight recorder's purpose; failures are contained below
            with open(path, "w") as fh:
                for doc in ring:
                    fh.write(json.dumps(doc,
                                        default=events._json_default) + "\n")
                fh.write(json.dumps(marker,
                                    default=events._json_default) + "\n")
        except Exception:  # noqa: BLE001 — the crash path must not crash
            return None
        if path not in self._dumps:
            self._dumps.append(path)
        return path


_recorder = FlightRecorder()
_sigterm_installed = False
_prev_sigterm: Any = None


def flight_recorder() -> FlightRecorder:
    """The process-wide default flight recorder."""
    return _recorder


# -- drain hooks: shutdown work that must run before SIGTERM kills us -------- #

_drain_hooks: dict[str, Callable[[str], Any]] = {}
# reentrant: run_drain_hooks executes inside the SIGTERM handler on the
# main thread — if the signal interrupts register/unregister_drain_hook
# mid-critical-section, a plain Lock would self-deadlock the shutdown
_drain_lock = locks.make_rlock("telemetry.live._drain_lock")


def register_drain_hook(name: str, fn: Callable[[str], Any]) -> None:
    """Register shutdown work to run on SIGTERM *before* the process
    dies (stop admission, checkpoint in-flight jobs, snapshot the
    store).  ``fn(reason)`` runs on the signal-handling main thread; a
    truthy return claims the shutdown — the handler then returns instead
    of re-raising, letting the registrant drive a clean ``exit 0``.
    Last registration per name wins; hooks run in registration order."""
    with _drain_lock:
        _drain_hooks[name] = fn


def unregister_drain_hook(name: str,
                          fn: Optional[Callable] = None) -> None:
    """Remove a drain hook; with ``fn`` given, only if it is the current
    one (a closing component can't evict its replacement)."""
    with _drain_lock:
        cur = _drain_hooks.get(name)
        if cur is not None and (fn is None or cur is fn):
            del _drain_hooks[name]


def run_drain_hooks(reason: str) -> bool:
    """Run every registered drain hook (exceptions contained — the
    shutdown path must not crash); True when any hook claimed the
    shutdown."""
    with _drain_lock:
        hooks = list(_drain_hooks.items())
    claimed = False
    for name, fn in hooks:
        try:
            if fn(reason):
                claimed = True
        except Exception as e:  # noqa: BLE001 — dying cleanly beats
            try:                # dying loudly
                _recorder.dump(reason=f"drain_hook_error:{name}",
                               error=repr(e))
            except Exception:  # noqa: BLE001
                pass
    return claimed


def _on_sigterm(signum, frame):  # pragma: no cover — exercised in CI smoke
    # drain first (stop admission, checkpoint, snapshot) while the
    # process is still healthy, then dump the forensic ring; only
    # re-raise when no hook claimed the shutdown
    claimed = run_drain_hooks("sigterm")
    _recorder.dump(reason="sigterm")
    if claimed:
        return
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
    else:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)


def _install_sigterm_handler() -> None:
    global _sigterm_installed, _prev_sigterm
    if _sigterm_installed:
        return
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
        _sigterm_installed = True
    except (ValueError, OSError):  # pragma: no cover — exotic hosts
        pass


# -- status providers --------------------------------------------------------- #

_providers: dict[str, Callable[[], dict]] = {}
_providers_lock = locks.make_lock("telemetry.live._providers_lock")


def register_status(name: str, fn: Callable[[], dict]) -> None:
    """Publish a plain-python status callable under ``name`` (last one
    wins); it must read only thread-safe python state — never jax."""
    with _providers_lock:
        _providers[name] = fn


def unregister_status(name: str,
                      fn: Optional[Callable[[], dict]] = None) -> None:
    """Remove a provider; with ``fn`` given, only if it is the current
    one (so a closing component can't evict its replacement)."""
    with _providers_lock:
        cur = _providers.get(name)
        if cur is not None and (fn is None or cur is fn):
            del _providers[name]


def status_snapshot() -> dict:
    """Assemble the ``/status`` document from registry info, process
    counters, and registered providers.  Plain python only — safe to
    call from the monitor handler thread mid-solve."""
    now = time.time()
    doc: dict[str, Any] = {
        "pid": os.getpid(),
        "time": round(now, 3),
        "uptime_s": round(now - _T0, 3),
        "telemetry": {"enabled": events.enabled(),
                      "trace": events.path()},
        "counters": events.counters(),
        "last_iterate": _registry.info("last_iterate"),
        "flight_recorder": {"attached": _recorder.attached,
                            "events": len(_recorder),
                            "dumps": _recorder.dumps},
    }
    ckpt_ts = None
    snap = _registry.snapshot()
    g = snap["gauges"].get("tclb_checkpoint_last_unix_ts")
    if g is not None:
        ckpt_ts = g
    doc["checkpoint_age_s"] = (round(now - ckpt_ts, 3)
                               if ckpt_ts is not None else None)
    with _providers_lock:
        providers = dict(_providers)
    for name, fn in providers.items():
        try:
            doc[name] = fn()
        except Exception as e:  # noqa: BLE001 — a dying component must
            doc[name] = {"error": repr(e)}   # not take /status down
    return doc


# -- on-demand profiler capture ----------------------------------------------- #

# raw on purpose: acquired by the caller thread, released by the worker
# thread — per-thread sanitizer tracking cannot model cross-thread release
_profile_lock = threading.Lock()


def capture_profile(secs: float, outdir: Optional[str] = None) -> str:
    """Start an on-demand ``jax.profiler`` capture of ``secs`` seconds on
    a background thread; returns the artifact dir immediately.  Raises
    RuntimeError if a capture is already running.  This is the only
    jax-touching path in the live plane, and it never runs on the
    monitor handler thread."""
    if not _profile_lock.acquire(blocking=False):
        raise RuntimeError("a profiler capture is already running")
    secs = max(0.1, min(float(secs), 300.0))
    if outdir is None:
        outdir = os.path.join(
            os.environ.get("TCLB_TRACE_DIR") or os.getcwd(),
            "tclb-profile-%d-%d" % (os.getpid(), int(time.time())))

    def _run():  # pragma: no cover — needs a real profiler backend
        try:
            import jax
            jax.profiler.start_trace(outdir)
            time.sleep(secs)
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — capture failure is non-fatal
            pass
        finally:
            _profile_lock.release()

    threading.Thread(target=_run, name="tclb-profile-capture",
                     daemon=True).start()
    return outdir


def parse_monitor_spec(spec: str) -> tuple[str, int]:
    """Parse ``--monitor [host]:port`` (``8080``, ``:8080``,
    ``0.0.0.0:9100``) into ``(host, port)``; host defaults to
    127.0.0.1."""
    s = str(spec).strip()
    host, sep, port = s.rpartition(":")
    if not sep:
        host, port = "", s
    host = host or "127.0.0.1"
    try:
        p = int(port)
    except ValueError:
        raise ValueError("--monitor expects [host]:port, got %r" % spec)
    if not (0 <= p <= 65535):
        raise ValueError("--monitor port out of range: %r" % spec)
    return host, p
