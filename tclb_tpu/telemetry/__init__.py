"""Structured telemetry for engine-dispatch tracing and perf attribution.

Usage (a trace costs nothing unless asked for):

* ``TCLB_TELEMETRY=trace.jsonl python run.py`` — or
  ``telemetry.enable("trace.jsonl")`` — turns the process-wide JSONL
  sink on; everything below is a strict no-op otherwise;
* ``telemetry.event(kind, **fields)`` — one structured event line;
* ``with telemetry.span("iterate", nodes=n, iters=k) as sp: ...;
  sp.sync(out)`` — honest wall-time (``block_until_ready`` fencing),
  MLUPS / vs-roofline derived metrics, ``jax.profiler.TraceAnnotation``
  passthrough;
* ``telemetry.counter(name)`` — monotonic counters, flushed on close;
* ``python -m tclb_tpu.telemetry report trace.jsonl [--format text|json]
  [--compare other.jsonl]`` — per-engine/per-span aggregation and trace
  diffing (see telemetry/report.py).
"""

from tclb_tpu.telemetry.events import (  # noqa: F401
    counter, counters, disable, enable, enabled, engine_fallback,
    engine_selected, event, failcheck, path)
from tclb_tpu.telemetry.spans import (  # noqa: F401
    HBM_GBS, NOOP_SPAN, Span, device_kind, fuse_of, roofline_mlups,
    span)
