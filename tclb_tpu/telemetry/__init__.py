"""Structured telemetry for engine-dispatch tracing and perf attribution.

Usage (a trace costs nothing unless asked for):

* ``TCLB_TELEMETRY=trace.jsonl python run.py`` — or
  ``telemetry.enable("trace.jsonl")`` — turns the process-wide JSONL
  sink on; everything below is a strict no-op otherwise;
* ``telemetry.event(kind, **fields)`` — one structured event line;
* ``with telemetry.span("iterate", nodes=n, iters=k) as sp: ...;
  sp.sync(out)`` — honest wall-time (``block_until_ready`` fencing),
  MLUPS / vs-roofline derived metrics, ``jax.profiler.TraceAnnotation``
  passthrough;
* ``telemetry.counter(name)`` — monotonic counters, snapshotted
  periodically and flushed on close;
* ``telemetry.subscribe(fn)`` — fan the event stream out to extra sinks
  (the live metrics registry and the flight recorder in telemetry/live.py
  are subscribers; the monitor endpoint in telemetry/http.py serves
  their snapshots over ``/metrics`` + ``/status``);
* ``python -m tclb_tpu.telemetry report trace.jsonl [--format text|json]
  [--compare other.jsonl] [--job ID]`` — per-engine/per-span aggregation,
  trace diffing, and per-job timelines (see telemetry/report.py).
"""

from tclb_tpu.telemetry.events import (  # noqa: F401
    counter, counters, current_job, disable, enable, enabled,
    engine_fallback, engine_selected, event, failcheck, job_context,
    path, set_job, subscribe, unsubscribe)
from tclb_tpu.telemetry.spans import (  # noqa: F401
    HBM_GBS, NOOP_SPAN, Span, device_kind, fuse_of, roofline_mlups,
    span)
