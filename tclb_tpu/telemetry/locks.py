"""Opt-in runtime lock sanitizer: the dynamic half of the concurrency gate.

The static pass (:mod:`tclb_tpu.analysis.concurrency`) proves lock
discipline from the AST; this module validates the same discipline
against what the threads actually do.  With ``TCLB_LOCK_DEBUG=1`` every
lock built through :func:`make_lock` / :func:`make_rlock` is wrapped in
a :class:`DebugLock` that records, per thread, the order locks are
taken in and how long they are held:

* **order inversions** — thread X was ever seen taking ``a`` then ``b``;
  some thread now takes ``b`` then ``a``.  That pair is one scheduling
  accident away from a deadlock, even if this run got away with it.
  Emitted as a ``lock.inversion`` telemetry event (flight-recorder and
  trace visible) and kept in :func:`inversions` for assertions.
* **long holds** — a lock held longer than ``TCLB_LOCK_DEBUG_MS``
  (default 100 ms) indicates blocking work inside a critical section —
  the runtime shadow of ``concurrency.blocking_under_lock``.  Emitted
  as ``lock.long_hold``.

Design constraints:

* **strict no-op when off** — :func:`make_lock` returns a *raw*
  ``threading.Lock`` when the sanitizer is disabled; production runs pay
  literally nothing (no wrapper object, no extra attribute hop).
* **no emission under a lock** — findings are queued per-thread and
  flushed only once the thread has dropped its last instrumented lock,
  so the sanitizer can never deadlock against the telemetry fan-out it
  reports through.
* **Condition-compatible** — :class:`DebugLock` implements the private
  ``_release_save`` / ``_acquire_restore`` / ``_is_owned`` protocol, so
  ``threading.Condition(make_rlock(...))`` behaves exactly like a
  Condition on the raw primitive.

The observed order graph (:func:`order_graph`) uses the same
``module.Class.attr`` node names as the static analyzer's lock-order
graph, so CI can check the runtime edges against the proven ones.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

#: keep at most this many inversion / long-hold records for inspection
MAX_RECORDS = 256

_enabled = os.environ.get("TCLB_LOCK_DEBUG", "") == "1"
_long_hold_ms = float(os.environ.get("TCLB_LOCK_DEBUG_MS", "100"))

_graph_lock = threading.Lock()          # raw on purpose: the meta-lock
_order: dict[str, set[str]] = {}        # observed edges a -> b (a held
_edge_sites: dict[tuple, str] = {}      # first witness thread per edge
_inversions: list[dict] = []
_long_holds: list[dict] = []

_tls = threading.local()                # .held: [(name, t_acquire)],
                                        # .pending: [event docs]


def enabled() -> bool:
    """Whether new locks built via make_lock/make_rlock are instrumented."""
    return _enabled


def long_hold_ms() -> float:
    return _long_hold_ms


def enable(hold_ms: Optional[float] = None) -> None:
    """Turn the sanitizer on for locks constructed *after* this call
    (tests; production uses ``TCLB_LOCK_DEBUG=1`` at process start)."""
    global _enabled, _long_hold_ms
    _enabled = True
    if hold_ms is not None:
        _long_hold_ms = float(hold_ms)


def disable() -> None:
    """Stop instrumenting newly-constructed locks (existing DebugLocks
    keep working — they are still real locks)."""
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop all recorded edges / inversions / long holds (tests)."""
    with _graph_lock:
        _order.clear()
        _edge_sites.clear()
        del _inversions[:]
        del _long_holds[:]


def inversions() -> list[dict]:
    with _graph_lock:
        return list(_inversions)


def long_holds() -> list[dict]:
    with _graph_lock:
        return list(_long_holds)


def order_graph() -> dict[str, set[str]]:
    """Copy of the observed lock-order graph: ``{a: {b, ...}}`` means
    some thread acquired ``b`` while holding ``a``."""
    with _graph_lock:
        return {a: set(bs) for a, bs in _order.items()}


# -- per-thread bookkeeping ---------------------------------------------------- #


def _state():
    st = _tls
    if not hasattr(st, "held"):
        st.held = []
        st.pending = []
    return st


def _note_acquire(name: str) -> None:
    st = _state()
    t = time.monotonic()
    held_names = [n for n, _ in st.held]
    if held_names and name not in held_names:
        # record edges held -> name; an edge already known in the
        # opposite direction is an order inversion
        docs = []
        with _graph_lock:
            for h in dict.fromkeys(held_names):
                if name in _order and h in _order[name]:
                    doc = {"kind": "lock.inversion",
                           "first": name, "then": h,
                           "now_first": h, "now_then": name,
                           "held": list(dict.fromkeys(held_names)),
                           "thread": threading.current_thread().name,
                           "prior_thread": _edge_sites.get((name, h), "?")}
                    if len(_inversions) < MAX_RECORDS:
                        _inversions.append(doc)
                    docs.append(doc)
                edge = (h, name)
                if name not in _order.get(h, ()):
                    _order.setdefault(h, set()).add(name)
                    _edge_sites[edge] = threading.current_thread().name
        st.pending.extend(docs)
    st.held.append((name, t))


def _note_release(name: str, full: bool = False) -> int:
    """Pop the most recent hold of ``name`` (all of them with ``full``,
    for Condition.wait's total release); returns the number popped."""
    st = _state()
    popped = 0
    outermost_t = None
    for i in range(len(st.held) - 1, -1, -1):
        if st.held[i][0] == name:
            outermost_t = st.held[i][1]
            del st.held[i]
            popped += 1
            if not full:
                break
    if popped and outermost_t is not None:
        dur_ms = (time.monotonic() - outermost_t) * 1e3
        remaining = any(n == name for n, _ in st.held)
        if not remaining and dur_ms > _long_hold_ms:
            doc = {"kind": "lock.long_hold", "lock": name,
                   "held_ms": round(dur_ms, 3),
                   "limit_ms": _long_hold_ms,
                   "thread": threading.current_thread().name}
            with _graph_lock:
                if len(_long_holds) < MAX_RECORDS:
                    _long_holds.append(doc)
            st.pending.append(doc)
    if not st.held and st.pending:
        pending, st.pending = st.pending, []
        _emit(pending)
    return popped


def _emit(docs: list) -> None:
    # only called with an empty held-stack: emitting takes the events
    # lock, and a subscriber (live._observe) may take registry locks —
    # never do that while holding an instrumented lock
    from tclb_tpu.telemetry import events
    for doc in docs:
        fields = {k: v for k, v in doc.items() if k != "kind"}
        events.event(doc["kind"], **fields)
        events.counter(doc["kind"])


# -- the wrapper --------------------------------------------------------------- #


class DebugLock:
    """An instrumented stand-in for ``threading.Lock``/``RLock`` that
    records acquisition order and hold times.  Only constructed when the
    sanitizer is enabled; supports the Condition lock protocol."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str, inner: Any) -> None:
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self.name)
        return ok

    def release(self) -> None:
        self._inner.release()
        _note_release(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "DebugLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<DebugLock {self.name} wrapping {self._inner!r}>"

    # -- threading.Condition lock protocol ---------------------------------- #

    def _is_owned(self) -> bool:
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):       # plain-Lock fallback, as Condition's
            inner.release()
            return False
        return True

    def _release_save(self):
        popped = _note_release(self.name, full=True)
        if hasattr(self._inner, "_release_save"):
            return (self._inner._release_save(), popped)
        self._inner.release()
        return (None, popped)

    def _acquire_restore(self, saved) -> None:
        inner_saved, popped = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(inner_saved)
        else:
            self._inner.acquire()
        t = time.monotonic()
        st = _state()
        for _ in range(max(1, popped)):
            st.held.append((self.name, t))


def make_lock(name: str):
    """A ``threading.Lock`` — instrumented as a :class:`DebugLock` when
    ``TCLB_LOCK_DEBUG=1``, otherwise the raw primitive (strict no-op).
    ``name`` must match the static analyzer's node naming
    (``module.Class.attr``) so the two order graphs line up."""
    if _enabled:
        return DebugLock(name, threading.Lock())
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — instrumented when ``TCLB_LOCK_DEBUG=1``.
    Reentrant re-acquisition records no order edge."""
    if _enabled:
        return DebugLock(name, threading.RLock())
    return threading.RLock()
