"""HTTP monitor endpoint: ``/metrics``, ``/status``, ``/trace?secs=N``.

A stdlib-threaded (``http.server.ThreadingHTTPServer``) monitor attached
to a running solve or serving fleet via ``--monitor [host]:port``
(``python -m tclb_tpu run``) or ``FleetDispatcher(monitor=...)``:

* ``GET /metrics`` — Prometheus text exposition (format 0.0.4) of the
  in-process registry plus ``telemetry.counter`` totals;
* ``GET /status``  — JSON: per-lane occupancy, queue depth, inflight jobs
  with ages, last-iterate MLUPS/engine tag, checkpoint age, evicted
  devices, flight-recorder state;
* ``GET /trace?secs=N`` — kick an on-demand profiler capture to a named
  artifact dir (runs on a background thread, not the handler).

Hygiene contract (enforced by ``analysis.hygiene.device_work_in_monitor``):
nothing in this module may touch jax, ``device_put``, or ``Lattice``
state — the handler thread reads only plain-python registry snapshots,
so a scrape can never fence, allocate on, or deadlock a device.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from tclb_tpu.telemetry import live

_INDEX = (b"tclb_tpu monitor\n"
          b"  /metrics        Prometheus text exposition\n"
          b"  /status         JSON process status\n"
          b"  /trace?secs=N   on-demand profiler capture\n")


class _Handler(BaseHTTPRequestHandler):
    server_version = "tclb-monitor"

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc: dict) -> None:
        body = json.dumps(doc, indent=2, default=str).encode()
        self._send(code, body + b"\n", "application/json")

    def do_GET(self):  # noqa: N802 — http.server API
        url = urlparse(self.path)
        route = url.path.rstrip("/") or "/"
        try:
            if route == "/metrics":
                self._send(200, live.prometheus_text().encode(),
                           live.CONTENT_TYPE)
            elif route == "/status":
                self._send_json(200, live.status_snapshot())
            elif route == "/trace":
                qs = parse_qs(url.query)
                secs = float(qs.get("secs", ["3"])[0])
                try:
                    outdir = live.capture_profile(secs)
                except RuntimeError as e:
                    self._send_json(409, {"error": str(e)})
                    return
                self._send_json(200, {"artifact_dir": outdir,
                                      "secs": secs, "started": True})
            elif route == "/":
                self._send(200, _INDEX, "text/plain; charset=utf-8")
            else:
                self._send_json(404, {"error": "no such route",
                                      "routes": ["/metrics", "/status",
                                                 "/trace"]})
        except BrokenPipeError:  # pragma: no cover — client went away
            pass
        except Exception as e:  # noqa: BLE001 — a scrape must never kill
            try:                # the process it is observing
                self._send_json(500, {"error": repr(e)})
            except Exception:  # noqa: BLE001
                pass


class MonitorServer:
    """The live monitor: a daemon-threaded HTTP server over the metrics
    registry.  ``start()`` subscribes the registry to the event fan-out
    (refcounted); ``stop()`` releases it."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self.host = host
        self.port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def from_spec(cls, spec: str) -> "MonitorServer":
        """Build from a ``[host]:port`` string (see
        :func:`live.parse_monitor_spec`)."""
        host, port = live.parse_monitor_spec(spec)
        return cls(host=host, port=port)

    @property
    def url(self) -> str:
        return "http://%s:%d" % (self.host, self.port)

    def start(self) -> "MonitorServer":
        if self._server is not None:
            return self
        live.enable_live()
        try:
            srv = ThreadingHTTPServer((self.host, self.port), _Handler)
        except Exception:
            live.disable_live()
            raise
        srv.daemon_threads = True
        self._server = srv
        self.port = srv.server_address[1]
        self._thread = threading.Thread(target=srv.serve_forever,
                                        name="tclb-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        srv, self._server = self._server, None
        if srv is None:
            return
        try:
            srv.shutdown()
            srv.server_close()
        finally:
            live.disable_live()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "MonitorServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
