"""Trace aggregation and diffing: ``python -m tclb_tpu.telemetry report``.

Turns a JSONL trace (telemetry/events.py) into the attribution the
BENCH/ROADMAP triage loop needs:

* **per-engine iterate summary** — for every engine the dispatch ran
  (``iterate`` spans grouped by their ``engine`` field): chunks, total
  iterations, wall time, aggregate MLUPS (total node-updates / total
  time) and the traffic-model roofline fraction;
* **per-span table** — every span name with count/total/mean/max;
* **dispatch history** — ``engine_selected`` decisions and the
  ``engine_fallback`` chain with each fallback's exception cause (the
  information the old free-form log strings swallowed);
* **failchecks and counters**.

``--compare other.jsonl`` diffs two traces engine-by-engine and
span-by-span, flagging slowdowns beyond ``--threshold`` (default 5%) —
the intended first tool for localizing regressions like the tracked
BENCH_r05 ``heat_adj_vs_roofline`` 0.91 -> 0.79 drop.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional


def load(path: str) -> list[dict]:
    """Parse a JSONL trace, skipping malformed lines (a crashed run may
    truncate its last line mid-write)."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(doc, dict) and "kind" in doc:
                out.append(doc)
    return out


def _percentile(vals: list, q: float) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    i = (len(vals) - 1) * q
    lo = int(i)
    hi = min(lo + 1, len(vals) - 1)
    return vals[lo] + (vals[hi] - vals[lo]) * (i - lo)


def _serving_summary(evts: list[dict]) -> dict:
    """The serving health numbers (from ``serve.batch``/``serve.compile``
    spans): batch occupancy, queue wait percentiles, compile-cache hit
    rate.  Empty dict when the trace has no serving activity."""
    batches = [e for e in evts if e.get("kind") == "span"
               and e.get("name") == "serve.batch"]
    compiles = [e for e in evts if e.get("kind") == "span"
                and e.get("name") == "serve.compile"]
    if not batches and not compiles:
        return {}
    out: dict = {}
    if batches:
        jobs = sum(int(b.get("batch", 0)) for b in batches)
        cap = sum(int(b.get("capacity", 0)) for b in batches)
        waits = [float(w) for b in batches
                 for w in (b.get("wait_s") or [])]
        out["batches"] = len(batches)
        out["jobs"] = jobs
        out["occupancy_pct"] = (round(100.0 * jobs / cap, 2)
                                if cap else None)
        out["degraded_batches"] = sum(
            1 for b in batches if b.get("outcome") == "degraded")
        p50, p95 = _percentile(waits, 0.50), _percentile(waits, 0.95)
        out["queue_wait_p50_s"] = None if p50 is None else round(p50, 6)
        out["queue_wait_p95_s"] = None if p95 is None else round(p95, 6)
    if compiles:
        hits = sum(1 for c in compiles if c.get("cache") == "hit")
        out["compile_lookups"] = len(compiles)
        out["cache_hit_rate_pct"] = round(100.0 * hits / len(compiles), 2)
        out["compile_miss_s"] = round(sum(
            float(c.get("dur_s", 0.0)) for c in compiles
            if c.get("cache") == "miss"), 6)
    return out


def _adjoint_summary(evts: list[dict]) -> dict:
    """The gradient-engine health numbers (from ``adjoint.sweep``
    spans): per (model, mode) sweep counts, wall time, snapshots held,
    recompute factor and spilled bytes.  Empty dict when the trace has
    no adjoint activity."""
    sweeps = [e for e in evts if e.get("kind") == "span"
              and e.get("name") == "adjoint.sweep"]
    if not sweeps:
        return {}
    rows: dict[str, dict] = {}
    for s in sweeps:
        key = f"{s.get('model', '?')}/{s.get('mode', '?')}"
        row = rows.setdefault(key, {
            "sweeps": 0, "total_s": 0.0, "peak_snapshots": 0,
            "spill_bytes": 0, "spill_mem": 0, "spill_peer": 0,
            "spill_disk": 0, "recompute_factor": None,
            "engine": s.get("engine")})
        row["sweeps"] += 1
        row["total_s"] += float(s.get("dur_s", 0.0))
        row["peak_snapshots"] = max(row["peak_snapshots"],
                                    int(s.get("peak_snapshots", 0) or 0))
        row["spill_bytes"] += int(s.get("spill_bytes", 0) or 0)
        for tier in ("spill_mem", "spill_peer", "spill_disk"):
            row[tier] += int(s.get(tier, 0) or 0)
        if s.get("recompute_factor") is not None:
            row["recompute_factor"] = float(s["recompute_factor"])
        if s.get("engine") is not None:
            row["engine"] = s["engine"]
    for row in rows.values():
        row["total_s"] = round(row["total_s"], 6)
    return {"modes": dict(sorted(rows.items())),
            "sweeps": sum(r["sweeps"] for r in rows.values())}


def _fleet_summary(evts: list[dict]) -> dict:
    """The fleet dispatcher's health numbers: per-device occupancy (lane
    busy time over the ``serve.fleet`` lifetime span), queue waits, the
    staging-overlap fraction, and the routing/eviction event counts.

    Staging overlap is the fraction of host-staging time hidden under
    device execution, ``1 - sum(stall_s)/sum(stage_s)`` over
    ``serve.lane_batch`` spans — a lane's first fill has nothing to
    overlap with and is excluded (``first=True`` rows).  The bench gate
    wants >90% on the fleet workload."""
    lanes = [e for e in evts if e.get("kind") == "span"
             and e.get("name") == "serve.lane_batch"]
    fleet = [e for e in evts if e.get("kind") == "span"
             and e.get("name") == "serve.fleet"]
    routed = sum(1 for e in evts if e.get("kind") == "serve.route_sharded")
    evicted = sum(1 for e in evts
                  if e.get("kind") == "serve.device_evicted")
    if not lanes and not fleet and not routed:
        return {}
    wall = sum(float(f.get("dur_s", 0.0)) for f in fleet) or None
    per: dict[str, dict] = {}
    stage_tot = stall_tot = 0.0
    waits: list[float] = []
    for b in lanes:
        dev = str(b.get("device", "?"))
        row = per.setdefault(dev, {"batches": 0, "jobs": 0, "busy_s": 0.0})
        row["batches"] += 1
        row["jobs"] += int(b.get("batch", 0))
        row["busy_s"] += float(b.get("dur_s", 0.0))
        waits.extend(float(w) for w in (b.get("wait_s") or []))
        if not b.get("first"):
            stage_tot += float(b.get("stage_s", 0.0))
            stall_tot += float(b.get("stall_s", 0.0))
    for row in per.values():
        row["busy_s"] = round(row["busy_s"], 6)
        row["occupancy_pct"] = (round(100.0 * row["busy_s"] / wall, 2)
                                if wall else None)
    occ = [r["occupancy_pct"] for r in per.values()
           if r["occupancy_pct"] is not None]
    p50, p95 = _percentile(waits, 0.50), _percentile(waits, 0.95)
    return {
        "lanes": dict(sorted(per.items())),
        "lanes_active": sum(1 for r in per.values() if r["jobs"] > 0),
        "batches": len(lanes),
        "jobs": sum(r["jobs"] for r in per.values()),
        "wall_s": None if wall is None else round(wall, 6),
        "mean_occupancy_pct": (round(sum(occ) / len(occ), 2)
                               if occ else None),
        "staging_overlap_pct": (
            round(100.0 * (1.0 - stall_tot / stage_tot), 2)
            if stage_tot > 0 else None),
        "queue_wait_p50_s": None if p50 is None else round(p50, 6),
        "queue_wait_p95_s": None if p95 is None else round(p95, 6),
        "routed_sharded": routed,
        "devices_evicted": evicted,
    }


def _gateway_summary(evts: list[dict]) -> dict:
    """The serving front door's health numbers (from ``gateway.*``
    events): admissions, rejections by reason, per-tenant queue-wait
    percentiles and the resumed-job count.  Empty dict when the trace
    has no gateway activity."""
    admitted = [e for e in evts if e.get("kind") == "gateway.admitted"]
    rejected = [e for e in evts if e.get("kind") == "gateway.rejected"]
    resumed = [e for e in evts if e.get("kind") == "gateway.resumed"]
    done = [e for e in evts if e.get("kind") == "gateway.job_done"]
    recovered = sum(1 for e in evts
                    if e.get("kind") == "gateway.recovered")
    if not admitted and not rejected and not done:
        return {}
    by_reason: dict[str, int] = {}
    for e in rejected:
        r = str(e.get("reason", "?"))
        by_reason[r] = by_reason.get(r, 0) + 1
    by_status: dict[str, int] = {}
    waits: dict[str, list] = {}
    for e in done:
        by_status[str(e.get("status", "?"))] = \
            by_status.get(str(e.get("status", "?")), 0) + 1
        if e.get("queue_wait_s") is not None:
            waits.setdefault(str(e.get("tenant", "?")), []).append(
                float(e["queue_wait_s"]))
    tenants: dict[str, dict] = {}
    for t, vals in sorted(waits.items()):
        p50, p95 = _percentile(vals, 0.50), _percentile(vals, 0.95)
        tenants[t] = {
            "jobs": len(vals),
            "queue_wait_p50_s": None if p50 is None else round(p50, 6),
            "queue_wait_p95_s": None if p95 is None else round(p95, 6)}
    total = len(admitted) + len(rejected)
    return {
        "admitted": len(admitted),
        "rejected": len(rejected),
        "admission_rate_pct": (round(100.0 * len(admitted) / total, 2)
                               if total else None),
        "rejections_by_reason": dict(sorted(by_reason.items())),
        "jobs_by_status": dict(sorted(by_status.items())),
        "resumed": len(resumed),
        "recovered": recovered,
        "tenants": tenants,
    }


def _faults_summary(evts: list[dict]) -> dict:
    """Chaos-injection accounting (``fault.injected`` events) next to
    the recovery signals the faults should have triggered: retries,
    evictions/reinstatements, store degradations, checkpoint ENOSPC
    prunes.  Empty dict when the trace has no injected faults."""
    injected = [e for e in evts if e.get("kind") == "fault.injected"]
    if not injected:
        return {}
    by_point: dict[str, int] = {}
    for e in injected:
        key = f"{e.get('point', '?')}:{e.get('mode', '?')}"
        by_point[key] = by_point.get(key, 0) + 1
    def count(k: str) -> int:
        return sum(1 for e in evts if e.get("kind") == k)
    return {
        "injected": len(injected),
        "by_point_mode": dict(sorted(by_point.items())),
        "retries": count("serve.batch.retry"),
        "devices_evicted": count("serve.device_evicted"),
        "devices_reinstated": count("serve.device_reinstated"),
        "store_degraded": count("gateway.store_degraded"),
        "checkpoint_enospc": count("checkpoint.enospc"),
    }


_SLO_PHASES = (("queue_wait", "queue_wait_s"), ("stage", "stage_s"),
               ("solve", "solve_s"), ("d2h", "d2h_s"), ("e2e", "wall_s"))


def _slo_summary(evts: list[dict]) -> dict:
    """Per-phase latency distribution of finished gateway jobs (from
    ``gateway.job_done`` events: queue-wait, stage, solve, d2h, and
    door-to-result end-to-end).  Empty dict when the trace has no
    finished gateway jobs."""
    vals: dict[str, list] = {phase: [] for phase, _ in _SLO_PHASES}
    for e in evts:
        if e.get("kind") != "gateway.job_done":
            continue
        for phase, field in _SLO_PHASES:
            v = e.get(field)
            if v is not None:
                vals[phase].append(float(v))
    out: dict = {}
    for phase, _ in _SLO_PHASES:
        vs = vals[phase]
        if not vs:
            continue
        out[phase] = {
            "count": len(vs),
            "p50_s": round(_percentile(vs, 0.50), 6),
            "p95_s": round(_percentile(vs, 0.95), 6),
            "max_s": round(max(vs), 6)}
    return out


def summarize(evts: list[dict]) -> dict:
    """Aggregate one trace into the report structure (all plain dicts,
    JSON-serializable as-is)."""
    spans: dict[str, dict] = {}
    engines: dict[str, dict] = {}
    selected: list[dict] = []
    fallbacks: list[dict] = []
    failchecks: list[dict] = []
    cnt: dict[str, float] = {}
    sess_cnt: dict[str, float] = {}
    kinds: dict[str, int] = {}

    def _fold_session() -> None:
        # counters snapshots are cumulative within one enable()..disable()
        # session (periodic + final flush), so a session contributes its
        # max per key; sessions (delimited by trace_start) add up
        for k, v in sess_cnt.items():
            cnt[k] = cnt.get(k, 0) + v
        sess_cnt.clear()

    for e in evts:
        kind = e.get("kind", "")
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == "span":
            name = e.get("name", "?")
            dt = float(e.get("dur_s", 0.0))
            s = spans.setdefault(name, {"count": 0, "total_s": 0.0,
                                        "max_s": 0.0})
            s["count"] += 1
            s["total_s"] += dt
            s["max_s"] = max(s["max_s"], dt)
            if name == "iterate":
                eng = e.get("engine", "?")
                g = engines.setdefault(eng, {
                    "chunks": 0, "iters": 0, "node_updates": 0.0,
                    "total_s": 0.0, "vs_roofline": None,
                    "roofline_known": e.get("roofline_known"),
                    "storage_dtype": e.get("storage_dtype"),
                    "storage_repr": e.get("storage_repr")})
                if e.get("storage_dtype") is not None:
                    g["storage_dtype"] = e["storage_dtype"]
                if e.get("storage_repr") is not None:
                    g["storage_repr"] = e["storage_repr"]
                g["chunks"] += 1
                g["iters"] += int(e.get("iters", 0))
                g["node_updates"] += (float(e.get("nodes", 0.0))
                                      * float(e.get("iters", 0)))
                g["total_s"] += dt
        elif kind == "engine_selected":
            selected.append(e)
        elif kind == "engine_fallback":
            fallbacks.append(e)
        elif kind == "failcheck":
            failchecks.append(e)
        elif kind == "trace_start":
            _fold_session()
        elif kind == "counters":
            for k, v in (e.get("counters") or {}).items():
                sess_cnt[k] = max(sess_cnt.get(k, 0), v)
    _fold_session()
    for s in spans.values():
        s["total_s"] = round(s["total_s"], 6)
        s["mean_s"] = round(s["total_s"] / max(s["count"], 1), 6)
        s["max_s"] = round(s["max_s"], 6)
    for g in engines.values():
        if g["total_s"] > 0 and g["node_updates"] > 0:
            # significant digits, not decimals: tiny smoke domains sit
            # far below 1 MLUPS and must not collapse to 0
            g["mlups"] = float(f"{g['node_updates'] / g['total_s'] / 1e6:.6g}")
        else:
            g["mlups"] = None
        g["total_s"] = round(g["total_s"], 6)
        del g["node_updates"]
    # stamp each engine's roofline fraction from its own iterate spans
    # (weighted by node-updates so short chunks don't skew it)
    w: dict[str, list] = {}
    for e in evts:
        if e.get("kind") == "span" and e.get("name") == "iterate" \
                and e.get("vs_roofline") is not None:
            nu = float(e.get("nodes", 0.0)) * float(e.get("iters", 0))
            w.setdefault(e.get("engine", "?"), []).append(
                (nu, float(e["vs_roofline"])))
        if e.get("kind") == "span" and e.get("name") == "iterate" \
                and e.get("roofline_known") is not None:
            eng = e.get("engine", "?")
            if eng in engines:
                engines[eng]["roofline_known"] = e["roofline_known"]
    for eng, rows in w.items():
        tot = sum(nu for nu, _ in rows)
        if tot > 0 and eng in engines:
            engines[eng]["vs_roofline"] = round(
                sum(nu * r for nu, r in rows) / tot, 4)
    return {"engines": engines, "spans": spans,
            "serving": _serving_summary(evts),
            "adjoint": _adjoint_summary(evts),
            "fleet": _fleet_summary(evts),
            "gateway": _gateway_summary(evts),
            "slo": _slo_summary(evts),
            "faults": _faults_summary(evts),
            "engine_selected": [
                {k: v for k, v in e.items() if k not in ("kind",)}
                for e in selected],
            "fallbacks": [
                {k: v for k, v in e.items() if k not in ("kind",)}
                for e in fallbacks],
            "failchecks": failchecks,
            "counters": cnt,
            "event_counts": kinds}


def compare(base: dict, other: dict, threshold: float = 0.05) -> dict:
    """Diff two summaries (``base`` = reference, ``other`` = candidate).
    Positive deltas mean the candidate is faster/higher.  Entries whose
    MLUPS dropped (or span time grew) by more than ``threshold`` land in
    ``regressions``."""
    out: dict = {"engines": {}, "spans": {}, "regressions": [],
                 "threshold": threshold}
    for eng in sorted(set(base["engines"]) | set(other["engines"])):
        a = base["engines"].get(eng)
        b = other["engines"].get(eng)
        row: dict = {"base_mlups": a and a.get("mlups"),
                     "other_mlups": b and b.get("mlups"),
                     "base_vs_roofline": a and a.get("vs_roofline"),
                     "other_vs_roofline": b and b.get("vs_roofline")}
        if a and b and (a.get("storage_repr") or "raw") \
                != (b.get("storage_repr") or "raw"):
            # a storage-representation switch is a different compiled
            # program — like an engine change, it is a note, never a
            # throughput regression
            row["note"] = (
                f"storage repr changed "
                f"({a.get('storage_repr') or 'raw'} -> "
                f"{b.get('storage_repr') or 'raw'}) — not comparable")
        elif a and b and a.get("mlups") and b.get("mlups"):
            delta = (b["mlups"] - a["mlups"]) / a["mlups"]
            row["mlups_delta_pct"] = round(100 * delta, 2)
            if delta < -threshold:
                out["regressions"].append({
                    "what": "engine_mlups", "engine": eng,
                    "base": a["mlups"], "other": b["mlups"],
                    "delta_pct": row["mlups_delta_pct"]})
        elif a and not b:
            row["note"] = "engine absent in other trace"
        elif b and not a:
            row["note"] = "engine absent in base trace"
        out["engines"][eng] = row
    for name in sorted(set(base["spans"]) | set(other["spans"])):
        a = base["spans"].get(name)
        b = other["spans"].get(name)
        row = {"base_total_s": a and a["total_s"],
               "other_total_s": b and b["total_s"],
               "base_mean_s": a and a["mean_s"],
               "other_mean_s": b and b["mean_s"]}
        if a and b and a["mean_s"] > 0:
            delta = (b["mean_s"] - a["mean_s"]) / a["mean_s"]
            row["mean_delta_pct"] = round(100 * delta, 2)
            if delta > threshold:
                out["regressions"].append({
                    "what": "span_time", "span": name,
                    "base_mean_s": a["mean_s"], "other_mean_s": b["mean_s"],
                    "delta_pct": row["mean_delta_pct"]})
        out["spans"][name] = row
    # serving health: flag occupancy and cache-hit-rate drops (an
    # ensemble fleet quietly falling back to singleton batches is a
    # throughput regression timing alone may hide behind retries)
    sa = base.get("serving") or {}
    sb = other.get("serving") or {}
    if sa or sb:
        row = {"base_occupancy_pct": sa.get("occupancy_pct"),
               "other_occupancy_pct": sb.get("occupancy_pct"),
               "base_cache_hit_rate_pct": sa.get("cache_hit_rate_pct"),
               "other_cache_hit_rate_pct": sb.get("cache_hit_rate_pct")}
        for what, key in (("batch_occupancy", "occupancy_pct"),
                          ("compile_cache_hit_rate",
                           "cache_hit_rate_pct")):
            av, bv = sa.get(key), sb.get(key)
            if av and bv is not None:
                delta = (bv - av) / av
                row[f"{key}_delta_pct"] = round(100 * delta, 2)
                if delta < -threshold:
                    out["regressions"].append({
                        "what": what, "base": av, "other": bv,
                        "delta_pct": row[f"{key}_delta_pct"]})
        out["serving"] = row
    # fleet health: a shrinking per-device occupancy or a staging
    # overlap that stops hiding under execution is the multi-device
    # analogue of the batch-occupancy regression above
    fa = base.get("fleet") or {}
    fb = other.get("fleet") or {}
    if fa or fb:
        row = {"base_mean_occupancy_pct": fa.get("mean_occupancy_pct"),
               "other_mean_occupancy_pct": fb.get("mean_occupancy_pct"),
               "base_staging_overlap_pct": fa.get("staging_overlap_pct"),
               "other_staging_overlap_pct": fb.get("staging_overlap_pct"),
               "base_lanes_active": fa.get("lanes_active"),
               "other_lanes_active": fb.get("lanes_active")}
        for what, key in (("fleet_occupancy", "mean_occupancy_pct"),
                          ("fleet_staging_overlap",
                           "staging_overlap_pct")):
            av, bv = fa.get(key), fb.get(key)
            if av and bv is not None:
                delta = (bv - av) / av
                row[f"{key}_delta_pct"] = round(100 * delta, 2)
                if delta < -threshold:
                    out["regressions"].append({
                        "what": what, "base": av, "other": bv,
                        "delta_pct": row[f"{key}_delta_pct"]})
        la, lb = fa.get("lanes_active"), fb.get("lanes_active")
        if la and lb is not None and lb < la:
            out["regressions"].append({
                "what": "fleet_lanes_active", "base": la, "other": lb})
        out["fleet"] = row
    # gateway health: a falling admission rate means quota/saturation
    # rejections grew; a growing queue-wait p95 (worst tenant) means
    # jobs sit admitted-but-undispatched longer — both are front-door
    # regressions the span timings cannot see
    ga = base.get("gateway") or {}
    gb = other.get("gateway") or {}
    if ga or gb:
        def worst_p95(g: dict):
            vals = [t.get("queue_wait_p95_s")
                    for t in (g.get("tenants") or {}).values()
                    if t.get("queue_wait_p95_s") is not None]
            return max(vals) if vals else None
        row = {"base_admission_rate_pct": ga.get("admission_rate_pct"),
               "other_admission_rate_pct": gb.get("admission_rate_pct"),
               "base_queue_wait_p95_s": worst_p95(ga),
               "other_queue_wait_p95_s": worst_p95(gb)}
        av, bv = ga.get("admission_rate_pct"), gb.get("admission_rate_pct")
        if av and bv is not None:
            delta = (bv - av) / av
            row["admission_rate_delta_pct"] = round(100 * delta, 2)
            if delta < -threshold:
                out["regressions"].append({
                    "what": "gateway_admission_rate", "base": av,
                    "other": bv,
                    "delta_pct": row["admission_rate_delta_pct"]})
        wa, wb = worst_p95(ga), worst_p95(gb)
        if wa and wb is not None:
            delta = (wb - wa) / wa
            row["queue_wait_p95_delta_pct"] = round(100 * delta, 2)
            if delta > threshold:
                out["regressions"].append({
                    "what": "gateway_queue_wait_p95", "base": wa,
                    "other": wb,
                    "delta_pct": row["queue_wait_p95_delta_pct"]})
        out["gateway"] = row
    # per-phase SLO drift: a p95 that grew beyond the threshold names
    # WHICH phase of the door-to-result path regressed (queue vs stage
    # vs solve vs d2h) instead of just "jobs got slower"
    sa = base.get("slo") or {}
    sb = other.get("slo") or {}
    if sa or sb:
        rows: dict = {}
        for phase in (p for p, _ in _SLO_PHASES
                      if p in sa or p in sb):
            pa = (sa.get(phase) or {}).get("p95_s")
            pb = (sb.get(phase) or {}).get("p95_s")
            row = {"base_p95_s": pa, "other_p95_s": pb}
            if pa and pb is not None:
                delta = (pb - pa) / pa
                row["p95_delta_pct"] = round(100 * delta, 2)
                if delta > threshold:
                    out["regressions"].append({
                        "what": "slo_phase_p95", "phase": phase,
                        "base": pa, "other": pb,
                        "delta_pct": row["p95_delta_pct"]})
            rows[phase] = row
        out["slo"] = rows
    # adjoint tier split: parking snapshots on a peer device (or disk)
    # must stay cheap — a sweep whose mean wall time grew past the
    # threshold while the candidate's spill columns carry bytes
    # localizes the regression to a TIER, not just "gradients got
    # slower" (the CI spill-overhead gate keys on exactly this row)
    aa = (base.get("adjoint") or {}).get("modes") or {}
    ab = (other.get("adjoint") or {}).get("modes") or {}
    if aa or ab:
        def _tiers(r):
            return None if r is None else {
                "mem": int(r.get("spill_mem", 0) or 0),
                "peer": int(r.get("spill_peer", 0) or 0),
                "disk": int(r.get("spill_disk", 0) or 0)}

        def _mean(r):
            return None if not r or not r.get("sweeps") else \
                r["total_s"] / r["sweeps"]
        rows = {}
        for key in sorted(set(aa) | set(ab)):
            ra, rb = aa.get(key), ab.get(key)
            ma, mb = _mean(ra), _mean(rb)
            row = {"base_spill": _tiers(ra), "other_spill": _tiers(rb),
                   "base_mean_s": None if ma is None else round(ma, 6),
                   "other_mean_s": None if mb is None else round(mb, 6)}
            if ma and mb is not None:
                delta = (mb - ma) / ma
                row["mean_delta_pct"] = round(100 * delta, 2)
                if delta > threshold:
                    out["regressions"].append({
                        "what": "adjoint_sweep_time", "mode": key,
                        "base_mean_s": round(ma, 6),
                        "other_mean_s": round(mb, 6),
                        "delta_pct": row["mean_delta_pct"],
                        "other_spill": _tiers(rb)})
            rows[key] = row
        out["adjoint"] = rows
    # fallback-chain drift is a regression signal of its own (an engine
    # newly failing to compile shows up here before any timing does)
    fb_a = [(f.get("from"), f.get("to")) for f in base.get("fallbacks", [])]
    fb_b = [(f.get("from"), f.get("to")) for f in other.get("fallbacks", [])]
    if fb_a != fb_b:
        out["fallback_drift"] = {"base": fb_a, "other": fb_b}
        new = [f for f in fb_b if f not in fb_a]
        if new:
            out["regressions"].append({
                "what": "new_fallbacks", "fallbacks": new})
    return out


# -- per-job timeline --------------------------------------------------------- #


def job_events(evts: list[dict], job_id) -> list[dict]:
    """Every event attributed to ``job_id`` — via its own ``job_id`` /
    ``job`` field or membership in a batch's ``job_ids`` list."""
    jid = str(job_id)
    out = []
    for e in evts:
        ids = {str(e[k]) for k in ("job_id", "job") if e.get(k) is not None}
        ids.update(str(x) for x in (e.get("job_ids") or ()))
        if jid in ids:
            out.append(e)
    return out


_TIMELINE_VERBS = {
    "serve.job_queued": "queued",
    "serve.stage": "staged",
    "serve.batch": "dispatched",
    "serve.lane_batch": "dispatched",
    "serve.d2h": "d2h",
    "serve.sharded_job": "sharded",
    "serve.route_sharded": "routed",
    "serve.job_degraded": "degraded",
    "serve.job_done": "done",
    "failcheck": "failcheck",
    # gateway + pool verbs: with the cross-process relay, one --job
    # timeline runs gateway door -> worker kernel and back
    "gateway.admitted": "queued",
    "gateway.resumed": "resumed",
    "gateway.parked": "parked",
    "gateway.job_done": "done",
    "serve.pool_job_started": "worker-sent",
    "serve.pool_job_requeued": "requeued",
    "serve.pool_job_done": "pool-done",
    # cluster verbs: on a pod the same timeline crosses hosts —
    # admission -> host dispatch -> worker iterate spans -> done, each
    # relayed event carrying its `host` stamp
    "cluster.job_dispatched": "host-sent",
    "cluster.job_requeued": "requeued",
    "cluster.job_done": "host-done",
    "gateway.host_enrolled": "host-enroll",
    "gateway.host_lost": "host-lost",
    "gateway.host_rejoined": "host-rejoin",
}


def format_job_timeline(evts: list[dict], job_id) -> str:
    """One job's end-to-end timeline (queued -> staged -> dispatched ->
    d2h -> done, with retries/degrades/failchecks), offsets relative to
    its first event.  Span rows are placed at their *start* time
    (``ts - dur_s``; the trace stamps spans on exit)."""
    rows = job_events(evts, job_id)
    if not rows:
        return f"job {job_id}: no matching events in trace"

    def start_ts(e: dict) -> float:
        ts = float(e.get("ts", 0.0))
        if e.get("kind") == "span" and e.get("dur_s") is not None:
            return ts - float(e["dur_s"])
        return ts

    rows = sorted(rows, key=start_ts)
    t0 = start_ts(rows[0])
    lines = [f"job {job_id} timeline ({len(rows)} events)"]
    skip = {"kind", "ts", "name", "job_id", "job", "job_ids", "dur_s"}
    for e in rows:
        kind = e.get("kind")
        label = e.get("name") if kind == "span" else kind
        verb = _TIMELINE_VERBS.get(label, label)
        fields = " ".join(f"{k}={e[k]}" for k in e if k not in skip)
        if len(fields) > 120:
            fields = fields[:120] + "..."
        dur = (f"  ({float(e['dur_s']):.4f}s)"
               if e.get("dur_s") is not None else "")
        lines.append(f"  +{start_ts(e) - t0:8.4f}s  {verb:<11} "
                     f"{fields}{dur}")
    return "\n".join(lines)


# -- rendering --------------------------------------------------------------- #


def _fmt(v, nd=2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def format_text(summary: dict) -> str:
    lines = []
    if summary["engines"]:
        lines.append("per-engine iterate summary")
        lines.append(f"  {'engine':<44} {'storage':>17} {'chunks':>6} "
                     f"{'iters':>9} {'time_s':>10} {'MLUPS':>10} "
                     f"{'vs_roofline':>12}")
        for eng, g in sorted(summary["engines"].items()):
            star = "" if g.get("roofline_known", True) else "~"
            sdt = g.get("storage_dtype")
            # dtype/repr: the at-rest layout in one cell (repr only
            # matters on a narrowed rung, where it names the encoding)
            storage = "-" if sdt is None else (
                f"{sdt}/{g['storage_repr']}" if g.get("storage_repr")
                else str(sdt))
            lines.append(
                f"  {eng:<44} {storage:>17} "
                f"{g['chunks']:>6} {g['iters']:>9} "
                f"{_fmt(g['total_s'], 3):>10} {_fmt(g['mlups'], 1):>10} "
                f"{star + _fmt(g['vs_roofline'], 4):>12}")
        if any(not g.get("roofline_known", True)
               for g in summary["engines"].values()):
            lines.append("  (~ = roofline estimated: unknown device kind)")
        lines.append("")
    if summary["spans"]:
        lines.append("spans")
        lines.append(f"  {'name':<32} {'count':>6} {'total_s':>10} "
                     f"{'mean_s':>10} {'max_s':>10}")
        for name, s in sorted(summary["spans"].items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"  {name:<32} {s['count']:>6} "
                         f"{_fmt(s['total_s'], 4):>10} "
                         f"{_fmt(s['mean_s'], 4):>10} "
                         f"{_fmt(s['max_s'], 4):>10}")
        lines.append("")
    if summary.get("serving"):
        sv = summary["serving"]
        lines.append("serving")
        if "batches" in sv:
            lines.append(
                f"  batches {sv['batches']}  jobs {sv['jobs']}  "
                f"occupancy {_fmt(sv['occupancy_pct'], 1)}%  "
                f"degraded {sv['degraded_batches']}")
            lines.append(
                f"  queue wait p50 {_fmt(sv['queue_wait_p50_s'], 4)}s  "
                f"p95 {_fmt(sv['queue_wait_p95_s'], 4)}s")
        if "compile_lookups" in sv:
            lines.append(
                f"  compile cache: {sv['compile_lookups']} lookups, "
                f"hit rate {_fmt(sv['cache_hit_rate_pct'], 1)}%, "
                f"{_fmt(sv['compile_miss_s'], 3)}s compiling")
        lines.append("")
    if summary.get("adjoint"):
        ad = summary["adjoint"]
        lines.append("adjoint")
        lines.append(f"  {'model/mode':<28} {'sweeps':>6} {'time_s':>10} "
                     f"{'peak_snaps':>10} {'recompute':>10} "
                     f"{'mem_MB':>8} {'peer_MB':>8} {'disk_MB':>8}")
        for key, r in ad["modes"].items():
            lines.append(
                f"  {key:<28} {r['sweeps']:>6} "
                f"{_fmt(r['total_s'], 3):>10} "
                f"{r['peak_snapshots']:>10} "
                f"{_fmt(r['recompute_factor'], 3):>10} "
                f"{_fmt(r.get('spill_mem', 0) / 1e6, 2):>8} "
                f"{_fmt(r.get('spill_peer', 0) / 1e6, 2):>8} "
                f"{_fmt(r.get('spill_disk', 0) / 1e6, 2):>8}")
        lines.append("")
    if summary.get("fleet"):
        fl = summary["fleet"]
        lines.append("fleet")
        if fl.get("lanes"):
            lines.append(f"  {'device':<28} {'batches':>8} {'jobs':>6} "
                         f"{'busy_s':>10} {'occupancy':>10}")
            for dev, r in fl["lanes"].items():
                occ = (_fmt(r["occupancy_pct"], 1) + "%"
                       if r.get("occupancy_pct") is not None else "-")
                lines.append(f"  {dev:<28} {r['batches']:>8} "
                             f"{r['jobs']:>6} {_fmt(r['busy_s'], 4):>10} "
                             f"{occ:>10}")
        lines.append(
            f"  lanes active {fl['lanes_active']}  "
            f"staging overlap {_fmt(fl['staging_overlap_pct'], 1)}%  "
            f"routed sharded {fl['routed_sharded']}  "
            f"evicted {fl['devices_evicted']}")
        lines.append(
            f"  queue wait p50 {_fmt(fl['queue_wait_p50_s'], 4)}s  "
            f"p95 {_fmt(fl['queue_wait_p95_s'], 4)}s")
        lines.append("")
    if summary.get("gateway"):
        gw = summary["gateway"]
        lines.append("gateway")
        lines.append(
            f"  admitted {gw['admitted']}  rejected {gw['rejected']}  "
            f"admission rate {_fmt(gw['admission_rate_pct'], 1)}%  "
            f"resumed {gw['resumed']}  recovered {gw['recovered']}")
        if gw["rejections_by_reason"]:
            lines.append("  rejections: " + "  ".join(
                f"{r}={n}" for r, n in gw["rejections_by_reason"].items()))
        if gw["jobs_by_status"]:
            lines.append("  outcomes:   " + "  ".join(
                f"{s}={n}" for s, n in gw["jobs_by_status"].items()))
        if gw["tenants"]:
            lines.append(f"  {'tenant':<28} {'jobs':>6} {'wait_p50_s':>11} "
                         f"{'wait_p95_s':>11}")
            for t, r in gw["tenants"].items():
                lines.append(
                    f"  {t:<28} {r['jobs']:>6} "
                    f"{_fmt(r['queue_wait_p50_s'], 4):>11} "
                    f"{_fmt(r['queue_wait_p95_s'], 4):>11}")
        lines.append("")
    if summary.get("slo"):
        slo = summary["slo"]
        lines.append("gateway SLO (per-phase latency)")
        lines.append(f"  {'phase':<14} {'jobs':>6} {'p50_s':>10} "
                     f"{'p95_s':>10} {'max_s':>10}")
        for phase, _ in _SLO_PHASES:
            r = slo.get(phase)
            if r is None:
                continue
            lines.append(f"  {phase:<14} {r['count']:>6} "
                         f"{_fmt(r['p50_s'], 4):>10} "
                         f"{_fmt(r['p95_s'], 4):>10} "
                         f"{_fmt(r['max_s'], 4):>10}")
        lines.append("")
    if summary.get("faults"):
        fa = summary["faults"]
        lines.append("injected faults (chaos)")
        lines.append("  " + "  ".join(
            f"{k}={n}" for k, n in fa["by_point_mode"].items()))
        lines.append(
            f"  recovery: retries {fa['retries']}  "
            f"evicted {fa['devices_evicted']}  "
            f"reinstated {fa['devices_reinstated']}  "
            f"store degraded {fa['store_degraded']}  "
            f"ckpt enospc {fa['checkpoint_enospc']}")
        lines.append("")
    if summary["engine_selected"]:
        lines.append("engine selections")
        for e in summary["engine_selected"]:
            lines.append(f"  {e.get('engine')}  model={e.get('model')} "
                         f"shape={e.get('shape')} "
                         f"backend={e.get('backend')}")
        lines.append("")
    if summary["fallbacks"]:
        lines.append("fallback chain")
        for f in summary["fallbacks"]:
            lines.append(f"  {f.get('from')} -> {f.get('to')}: "
                         f"{f.get('cause')}")
        lines.append("")
    if summary["failchecks"]:
        lines.append("failchecks")
        for f in summary["failchecks"]:
            lines.append(f"  iteration {f.get('iteration')}: "
                         f"{f.get('quantity')} has {f.get('n_bad')} "
                         "non-finite values")
        lines.append("")
    if summary["counters"]:
        lines.append("counters")
        for k, v in sorted(summary["counters"].items()):
            lines.append(f"  {k:<40} {v}")
        lines.append("")
    lines.append("events: " + ", ".join(
        f"{k}={v}" for k, v in sorted(summary["event_counts"].items())))
    return "\n".join(lines)


def format_compare_text(diff: dict) -> str:
    lines = ["trace comparison (base -> other)"]
    if diff["engines"]:
        lines.append(f"  {'engine':<44} {'base MLUPS':>12} "
                     f"{'other MLUPS':>12} {'delta':>9}")
        for eng, row in sorted(diff["engines"].items()):
            d = row.get("mlups_delta_pct")
            lines.append(
                f"  {eng:<44} {_fmt(row['base_mlups'], 1):>12} "
                f"{_fmt(row['other_mlups'], 1):>12} "
                f"{(_fmt(d, 2) + '%') if d is not None else '-':>9}"
                + (f"  ({row['note']})" if "note" in row else ""))
    slow_spans = [(n, r) for n, r in sorted(diff["spans"].items())
                  if r.get("mean_delta_pct") is not None]
    if slow_spans:
        lines.append(f"  {'span':<44} {'base mean_s':>12} "
                     f"{'other mean_s':>12} {'delta':>9}")
        for name, row in slow_spans:
            lines.append(f"  {name:<44} {_fmt(row['base_mean_s'], 4):>12} "
                         f"{_fmt(row['other_mean_s'], 4):>12} "
                         f"{_fmt(row['mean_delta_pct'], 2):>8}%")
    if diff.get("serving"):
        sv = diff["serving"]
        lines.append(
            "  serving: occupancy "
            f"{_fmt(sv['base_occupancy_pct'], 1)}% -> "
            f"{_fmt(sv['other_occupancy_pct'], 1)}%, cache hit rate "
            f"{_fmt(sv['base_cache_hit_rate_pct'], 1)}% -> "
            f"{_fmt(sv['other_cache_hit_rate_pct'], 1)}%")
    if diff.get("fleet"):
        fl = diff["fleet"]
        lines.append(
            "  fleet: occupancy "
            f"{_fmt(fl['base_mean_occupancy_pct'], 1)}% -> "
            f"{_fmt(fl['other_mean_occupancy_pct'], 1)}%, "
            "staging overlap "
            f"{_fmt(fl['base_staging_overlap_pct'], 1)}% -> "
            f"{_fmt(fl['other_staging_overlap_pct'], 1)}%, lanes "
            f"{_fmt(fl['base_lanes_active'])} -> "
            f"{_fmt(fl['other_lanes_active'])}")
    if diff.get("gateway"):
        gw = diff["gateway"]
        lines.append(
            "  gateway: admission rate "
            f"{_fmt(gw['base_admission_rate_pct'], 1)}% -> "
            f"{_fmt(gw['other_admission_rate_pct'], 1)}%, "
            "queue wait p95 "
            f"{_fmt(gw['base_queue_wait_p95_s'], 4)}s -> "
            f"{_fmt(gw['other_queue_wait_p95_s'], 4)}s")
    if diff.get("slo"):
        for phase, row in diff["slo"].items():
            d = row.get("p95_delta_pct")
            lines.append(
                f"  slo {phase}: p95 {_fmt(row['base_p95_s'], 4)}s -> "
                f"{_fmt(row['other_p95_s'], 4)}s"
                + (f" ({_fmt(d, 2)}%)" if d is not None else ""))
    if diff.get("fallback_drift"):
        lines.append("  fallback drift: "
                     f"base={diff['fallback_drift']['base']} "
                     f"other={diff['fallback_drift']['other']}")
    if diff["regressions"]:
        lines.append(f"REGRESSIONS (>{100 * diff['threshold']:.0f}%):")
        for r in diff["regressions"]:
            lines.append("  " + json.dumps(r))
    else:
        lines.append("no regressions beyond threshold")
    return "\n".join(lines)


# -- CLI --------------------------------------------------------------------- #


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tclb_tpu.telemetry",
        description="Aggregate and diff tclb_tpu telemetry traces.")
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="summarize a JSONL trace")
    rp.add_argument("trace", help="trace file (JSONL)")
    rp.add_argument("--format", choices=("text", "json"), default="text")
    rp.add_argument("--compare", metavar="OTHER", default=None,
                    help="second trace to diff against (trace = base)")
    rp.add_argument("--threshold", type=float, default=0.05,
                    help="relative slowdown flagged as regression "
                         "(default 0.05)")
    rp.add_argument("--fail-on-regression", action="store_true",
                    help="exit 4 if the comparison finds regressions")
    rp.add_argument("--job", metavar="ID", default=None,
                    help="render one job's end-to-end timeline instead "
                         "of the aggregate report (exit 3 if the trace "
                         "has no events for that job)")
    args = p.parse_args(argv)

    try:
        evts = load(args.trace)
    except OSError as e:
        print(f"error: cannot read {args.trace}: {e}", file=sys.stderr)
        return 2
    if args.job is not None:
        txt = format_job_timeline(evts, args.job)
        print(txt)
        return 3 if not job_events(evts, args.job) else 0
    base = summarize(evts)
    if args.compare is None:
        if args.format == "json":
            print(json.dumps(base, indent=2, sort_keys=True))
        else:
            print(format_text(base))
        return 0
    try:
        other = summarize(load(args.compare))
    except OSError as e:
        print(f"error: cannot read {args.compare}: {e}", file=sys.stderr)
        return 2
    diff = compare(base, other, threshold=args.threshold)
    if args.format == "json":
        print(json.dumps({"base": base, "other": other, "compare": diff},
                         indent=2, sort_keys=True))
    else:
        print(format_compare_text(diff))
    if args.fail_on_regression and diff["regressions"]:
        return 4
    return 0
