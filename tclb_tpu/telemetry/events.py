"""Structured telemetry: a process-wide fan-out of typed events.

The reference ships real observability — the per-iteration globals CSV
(``cbLog``), NaN failchecks (``cbFailcheck``) and in-situ Catalyst
monitoring — but all of it is human-facing output.  This module is the
machine-facing counterpart: one stream of typed events
(``{"kind": ..., "ts": ...}`` per record) fanned out to pluggable sinks.
The original append-only JSONL file sink (``TCLB_TELEMETRY`` /
:func:`enable`) is one subscriber; the live metrics registry and the
flight recorder (:mod:`tclb_tpu.telemetry.live`) are others.

Design constraints:

* **no-op when disabled** — every entry point starts with an ``enabled()``
  check (a single boolean test); nothing is imported, opened, synced or
  allocated while no sink is subscribed, so instrumented hot seams cost
  nothing in production runs that don't ask for a trace or a monitor;
* **process-wide** — one fan-out shared by every Lattice/Solver in the
  process; the JSONL sink is selected via the ``TCLB_TELEMETRY``
  environment variable at import or :func:`enable` at runtime (the
  reference's equivalent switch is its compile-time logging level);
* **append-only JSONL** — one self-describing JSON object per line, so a
  crashed run still yields a readable (truncated) trace and two traces
  diff line-wise;
* **counters survive abnormal exits** — cumulative ``counters`` snapshots
  are emitted every ``COUNTER_SNAPSHOT_S`` seconds (piggybacked on event
  traffic), so a SIGKILLed run's trace still carries counter totals; the
  final flush on :func:`disable` remains authoritative.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Callable, Iterator, Optional, TextIO
from contextlib import contextmanager

SCHEMA_VERSION = 1

#: cadence of cumulative ``counters`` snapshots (seconds); snapshots ride
#: on event traffic, so an idle process emits none
COUNTER_SNAPSHOT_S = 5.0

#: arrays larger than this are summarized (shape/dtype) instead of being
#: serialized element-wise into the trace
MAX_INLINE_ELEMS = 64

_lock = threading.RLock()
_subscribers: list[Callable[[dict], None]] = []
_enabled = False                    # single-boolean gate: bool(_subscribers)
_sink: Optional[TextIO] = None      # the JSONL file sink (one subscriber)
_path: Optional[str] = None
_counters: dict[str, float] = {}
_counters_last_emit = 0.0           # monotonic ts of the last snapshot
_atexit_registered = False
_job_local = threading.local()      # per-thread active job id (correlation)


def enabled() -> bool:
    """Fast check instrumentation sites gate on (a plain boolean test)."""
    return _enabled


def path() -> Optional[str]:
    """The active JSONL trace path, or None when the file sink is off."""
    return _path


def _json_default(obj: Any):
    # numpy / jax scalars and arrays reach here from instrumentation
    # sites; keep the trace readable rather than crash the run — and
    # never serialize a whole lattice field into one trace line
    shape = getattr(obj, "shape", None)
    size = getattr(obj, "size", None)
    if shape is not None and isinstance(size, int) and size > MAX_INLINE_ELEMS:
        return ("<array shape=%s dtype=%s>"
                % (tuple(shape), getattr(obj, "dtype", "?")))
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:  # noqa: BLE001 — e.g. .item() on an array
                continue
    s = str(obj)
    if len(s) > 512:
        s = s[:512] + "...(+%d chars)" % (len(s) - 512)
    return s


# -- sink fan-out ------------------------------------------------------------- #


def subscribe(fn: Callable[[dict], None]) -> None:
    """Register ``fn(doc)`` to receive every event document.  Subscribers
    run under the module lock and must be fast and never call back into
    this module's emitters; exceptions are swallowed per-sink."""
    global _enabled
    with _lock:
        if fn not in _subscribers:
            _subscribers.append(fn)
        _enabled = True


def unsubscribe(fn: Callable[[dict], None]) -> None:
    """Remove a subscriber (idempotent); recomputes the enabled gate."""
    global _enabled
    with _lock:
        try:
            _subscribers.remove(fn)
        except ValueError:
            pass
        _enabled = bool(_subscribers)


def _fanout_locked(doc: dict) -> None:
    for fn in list(_subscribers):
        try:
            fn(doc)
        except Exception:  # noqa: BLE001 — one bad sink must not kill others
            pass


def _jsonl_write(doc: dict) -> None:
    if _sink is not None:
        _sink.write(json.dumps(doc, default=_json_default) + "\n")


def enable(trace_path: str) -> None:
    """Open (append) the JSONL sink at ``trace_path`` and start recording.
    Re-enabling with a different path closes the previous sink first."""
    global _sink, _path, _atexit_registered
    with _lock:
        if _sink is not None:
            if _path == trace_path:
                return
            _close_locked()
        d = os.path.dirname(os.path.abspath(trace_path))
        os.makedirs(d, exist_ok=True)
        _sink = open(trace_path, "a", buffering=1)  # line-buffered
        _path = trace_path
        # counters are session-scoped: a fresh JSONL session must not
        # inherit bumps recorded while only live sinks were attached
        _counters.clear()
        subscribe(_jsonl_write)
        if not _atexit_registered:
            atexit.register(disable)
            _atexit_registered = True
    from tclb_tpu import __version__
    event("trace_start", schema=SCHEMA_VERSION, version=__version__,
          pid=os.getpid())


def _close_locked() -> None:
    global _sink, _path
    if _sink is None:
        return
    if _counters:
        _fanout_locked({"kind": "counters", "ts": round(time.time(), 6),
                        "counters": dict(_counters), "final": True})
        _counters.clear()
    try:
        _sink.close()
    except Exception:  # noqa: BLE001
        pass
    _sink = None
    _path = None
    unsubscribe(_jsonl_write)


def disable() -> None:
    """Flush counters, close the JSONL sink, and stop file recording
    (idempotent).  Other subscribers (registry, flight recorder) stay,
    but the counter session ends here either way."""
    with _lock:
        _close_locked()
        _counters.clear()


def event(kind: str, **fields: Any) -> None:
    """Emit one structured event; silently a no-op when disabled."""
    if not _enabled:
        return
    doc = {"kind": kind, "ts": round(time.time(), 6)}
    doc.update(fields)
    with _lock:
        _maybe_snapshot_counters_locked()
        _fanout_locked(doc)


def counter(name: str, inc: float = 1) -> None:
    """Bump a monotonic process counter (snapshotted periodically and
    flushed as a final ``counters`` event when the JSONL sink closes);
    no-op when disabled."""
    global _counters_last_emit
    if not _enabled:
        return
    with _lock:
        if not _counters:
            _counters_last_emit = time.monotonic()
        _counters[name] = _counters.get(name, 0) + inc


def counters() -> dict[str, float]:
    """Snapshot of the live counters (empty when disabled)."""
    with _lock:
        return dict(_counters)


def _maybe_snapshot_counters_locked() -> None:
    # Counter loss on abnormal exit: the final flush in _close_locked
    # never happens on SIGKILL, so piggyback a cumulative snapshot on
    # event traffic every COUNTER_SNAPSHOT_S seconds.  Snapshots are
    # cumulative, so the report aggregates them with per-session max.
    global _counters_last_emit
    if not _counters:
        return
    now = time.monotonic()
    if now - _counters_last_emit < COUNTER_SNAPSHOT_S:
        return
    _counters_last_emit = now
    _fanout_locked({"kind": "counters", "ts": round(time.time(), 6),
                    "counters": dict(_counters)})


# -- job correlation ---------------------------------------------------------- #
# serve/ threads stamp the job id they are working for; emitters below
# (failcheck) pick it up so post-mortems localize without cross-referencing.


def set_job(job_id: Optional[Any]) -> None:
    """Set (or clear, with None) the active job id for this thread."""
    _job_local.job_id = job_id


def current_job() -> Optional[Any]:
    """The active job id for this thread, or None."""
    return getattr(_job_local, "job_id", None)


@contextmanager
def job_context(job_id: Any) -> Iterator[None]:
    """Scope the active job id for the calling thread."""
    prev = current_job()
    set_job(job_id)
    try:
        yield
    finally:
        set_job(prev)


# -- named emitters ---------------------------------------------------------- #
# The engine dispatch and failcheck sites call these by name so the static
# hygiene gate (analysis.hygiene.scan_dispatch_telemetry) can verify by AST
# that every dispatch decision and fallback is traced.


def engine_selected(engine: str, **fields: Any) -> None:
    """The dispatch chose an engine (``engine='xla'`` for the pure-XLA
    path).  Fields: model, shape, backend, ..."""
    event("engine_selected", engine=engine, **fields)


def engine_fallback(from_engine: str, to_engine: str, cause: str,
                    **fields: Any) -> None:
    """An engine failed its first compile/probe and the dispatch swapped
    in a fallback; ``cause`` is the ``repr`` of the triggering
    exception."""
    event("engine_fallback", **{"from": from_engine, "to": to_engine,
                                "cause": cause, **fields})


def failcheck(**fields: Any) -> None:
    """A NaN/Inf failcheck fired.  Fields: iteration, quantity, n_bad,
    engine.  The active job id (when a serve thread set one) is stamped
    automatically."""
    jid = current_job()
    if jid is not None and "job_id" not in fields:
        fields["job_id"] = jid
    event("failcheck", **fields)


# environment selection: TCLB_TELEMETRY=<path> turns the sink on for the
# whole process (CI sets this around the tier-1 trace smoke)
_env_path = os.environ.get("TCLB_TELEMETRY")
if _env_path:
    enable(_env_path)
del _env_path
