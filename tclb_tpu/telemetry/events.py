"""Structured telemetry: the process-wide JSONL event sink.

The reference ships real observability — the per-iteration globals CSV
(``cbLog``), NaN failchecks (``cbFailcheck``) and in-situ Catalyst
monitoring — but all of it is human-facing output.  This module is the
machine-facing counterpart: one append-only JSONL stream of typed events
(``{"kind": ..., "ts": ...}`` per line) that the report CLI
(``python -m tclb_tpu.telemetry report``) aggregates into per-engine /
per-span attributions.

Design constraints:

* **no-op when disabled** — every entry point starts with an ``enabled()``
  check (a single attribute test); nothing is imported, opened, synced or
  allocated on the disabled path, so instrumented hot seams cost nothing
  in production runs that don't ask for a trace;
* **process-wide** — one sink shared by every Lattice/Solver in the
  process, selected via the ``TCLB_TELEMETRY`` environment variable at
  import or :func:`enable` at runtime (the reference's equivalent switch
  is its compile-time logging level);
* **append-only JSONL** — one self-describing JSON object per line, so a
  crashed run still yields a readable (truncated) trace and two traces
  diff line-wise.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Optional, TextIO

SCHEMA_VERSION = 1

_lock = threading.Lock()
_sink: Optional[TextIO] = None
_path: Optional[str] = None
_counters: dict[str, float] = {}
_atexit_registered = False


def enabled() -> bool:
    """Fast check instrumentation sites gate on (a plain attribute test)."""
    return _sink is not None


def path() -> Optional[str]:
    """The active trace path, or None when disabled."""
    return _path


def _json_default(obj: Any):
    # numpy / jax scalars and arrays reach here from instrumentation
    # sites; keep the trace readable rather than crash the run
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if callable(fn):
            try:
                return fn()
            except Exception:  # noqa: BLE001 — e.g. .item() on an array
                continue
    return str(obj)


def enable(trace_path: str) -> None:
    """Open (append) the JSONL sink at ``trace_path`` and start recording.
    Re-enabling with a different path closes the previous sink first."""
    global _sink, _path, _atexit_registered
    with _lock:
        if _sink is not None:
            if _path == trace_path:
                return
            _close_locked()
        d = os.path.dirname(os.path.abspath(trace_path))
        os.makedirs(d, exist_ok=True)
        _sink = open(trace_path, "a", buffering=1)  # line-buffered
        _path = trace_path
        if not _atexit_registered:
            atexit.register(disable)
            _atexit_registered = True
    from tclb_tpu import __version__
    event("trace_start", schema=SCHEMA_VERSION, version=__version__,
          pid=os.getpid())


def _close_locked() -> None:
    global _sink, _path
    if _sink is None:
        return
    if _counters:
        _write_locked({"kind": "counters", "ts": round(time.time(), 6),
                       "counters": dict(_counters)})
        _counters.clear()
    try:
        _sink.close()
    except Exception:  # noqa: BLE001
        pass
    _sink = None
    _path = None


def disable() -> None:
    """Flush counters, close the sink, and stop recording (idempotent)."""
    with _lock:
        _close_locked()


def _write_locked(doc: dict) -> None:
    assert _sink is not None
    _sink.write(json.dumps(doc, default=_json_default) + "\n")


def event(kind: str, **fields: Any) -> None:
    """Emit one structured event; silently a no-op when disabled."""
    if _sink is None:
        return
    doc = {"kind": kind, "ts": round(time.time(), 6)}
    doc.update(fields)
    with _lock:
        if _sink is not None:
            _write_locked(doc)


def counter(name: str, inc: float = 1) -> None:
    """Bump a monotonic process counter (flushed as one ``counters``
    event when the sink closes); no-op when disabled."""
    if _sink is None:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + inc


def counters() -> dict[str, float]:
    """Snapshot of the live counters (empty when disabled)."""
    with _lock:
        return dict(_counters)


# -- named emitters ---------------------------------------------------------- #
# The engine dispatch and failcheck sites call these by name so the static
# hygiene gate (analysis.hygiene.scan_dispatch_telemetry) can verify by AST
# that every dispatch decision and fallback is traced.


def engine_selected(engine: str, **fields: Any) -> None:
    """The dispatch chose an engine (``engine='xla'`` for the pure-XLA
    path).  Fields: model, shape, backend, ..."""
    event("engine_selected", engine=engine, **fields)


def engine_fallback(from_engine: str, to_engine: str, cause: str,
                    **fields: Any) -> None:
    """An engine failed its first compile/probe and the dispatch swapped
    in a fallback; ``cause`` is the ``repr`` of the triggering
    exception."""
    event("engine_fallback", **{"from": from_engine, "to": to_engine,
                                "cause": cause, **fields})


def failcheck(**fields: Any) -> None:
    """A NaN/Inf failcheck fired.  Fields: iteration, quantity, n_bad."""
    event("failcheck", **fields)


# environment selection: TCLB_TELEMETRY=<path> turns the sink on for the
# whole process (CI sets this around the tier-1 trace smoke)
_env_path = os.environ.get("TCLB_TELEMETRY")
if _env_path:
    enable(_env_path)
del _env_path
