"""Cross-process telemetry relay + in-situ streaming tests.

Fast coverage of the observability pipe between worker subprocesses and
the supervisor: the bounded worker-side relay queue (`_TelemetryRelay`
backpressure, faulted-flush containment, torn-frame discipline), the
supervisor re-emit (worker identity stamping, preserved timestamps,
``pool.relay_dropped`` / ``pool.relay_events`` accounting), progress
frame routing onto :class:`PoolJob` handles, unknown-frame counting,
worker post-mortem harvesting into ``/status``, the relay-off strict
no-op, and the gateway's ``/v1/jobs/<id>/stream`` long-poll — all
against stub workers speaking the frame protocol (no solver imports),
so the whole suite runs in milliseconds.
"""

import io
import json
import os
import sys
import threading
import time
import urllib.request

import pytest

from tclb_tpu import faults, telemetry
from tclb_tpu.faults import FaultPlan
from tclb_tpu.serve.pool import PoolJobError, WorkerPool
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.serve.worker import _TelemetryRelay, read_frame
from tclb_tpu.telemetry import events, live


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    live.registry().reset()
    faults.uninstall()
    yield
    faults.uninstall()
    telemetry.disable()
    live.registry().reset()


# --------------------------------------------------------------------------- #
# Worker-side relay unit: bounded queue, contained faults, torn frames
# --------------------------------------------------------------------------- #


def test_relay_queue_cap_honored_and_drops_counted():
    relay = _TelemetryRelay(lane=0, cap=4)
    for i in range(7):
        relay.sink({"kind": "span", "name": "iterate", "i": i})
    assert len(relay) == 4                      # cap, not 7
    assert relay.dropped_total == 3
    buf = io.BytesIO()
    relay.flush(buf, "pj-1", "gw-job-1", "gw-span")
    buf.seek(0)
    doc, payload = read_frame(buf)
    assert doc["t"] == "telemetry" and doc["id"] == "pj-1"
    assert len(doc["events"]) == 4 and doc["dropped"] == 3
    assert payload == b""
    # every relayed doc carries the cross-process trace context
    for ev in doc["events"]:
        assert ev["job_id"] == "gw-job-1"
        assert ev["parent_span"] == "gw-span"
    # drained: an empty relay writes no frame at all
    buf2 = io.BytesIO()
    relay.flush(buf2, "pj-1", "gw-job-1")
    assert buf2.getvalue() == b""


def test_relay_skips_counters_snapshots():
    """Counter snapshots stay worker-local — the parent folds its own
    counter sessions, and relaying a child's cumulative snapshot would
    double-count in `telemetry report`."""
    relay = _TelemetryRelay(lane=0)
    relay.sink({"kind": "counters", "counters": {"x": 1}})
    relay.sink({"kind": "span", "name": "iterate"})
    assert len(relay) == 1


def test_relay_faulted_flush_drops_batch_never_raises():
    """The pool.telemetry_relay chaos point: an error-mode injection
    drops that flush's batch (counted), the relay keeps working, and
    the loss is re-reported on the next successful frame."""
    faults.install(FaultPlan.parse("seed=9;pool.telemetry_relay:error:n=1"))
    relay = _TelemetryRelay(lane=0)
    relay.sink({"kind": "span", "name": "iterate"})
    relay.sink({"kind": "failcheck"})
    buf = io.BytesIO()
    relay.flush(buf, "pj-1", "t-1")             # injected: must not raise
    assert buf.getvalue() == b""                # nothing written
    assert relay.dropped_total == 2
    relay.sink({"kind": "span", "name": "iterate"})
    buf2 = io.BytesIO()
    relay.flush(buf2, "pj-1", "t-1")            # budget spent: clean
    buf2.seek(0)
    doc, _ = read_frame(buf2)
    assert len(doc["events"]) == 1
    assert doc["dropped"] == 2                  # the loss is observable


def test_relay_torn_mode_writes_no_partial_frame():
    """Torn mode must write NOTHING: a half frame would desync the
    whole pipe, so the contained truncation drops the batch instead."""
    faults.install(FaultPlan.parse("seed=9;pool.telemetry_relay:torn:n=1"))
    relay = _TelemetryRelay(lane=0)
    relay.sink({"kind": "span", "name": "iterate"})
    buf = io.BytesIO()
    relay.flush(buf, "pj-1", "t-1")
    assert buf.getvalue() == b""
    assert relay.dropped_total == 1


def test_relay_write_failure_contained():
    class _Broken:
        def write(self, b):
            raise OSError("pipe gone")

        def flush(self):
            pass

    relay = _TelemetryRelay(lane=0)
    relay.sink({"kind": "span"})
    relay.flush(_Broken(), "pj-1", "t-1")       # must not raise
    assert relay.dropped_total == 1


def test_relay_off_is_strict_noop_in_worker_main():
    """Without TCLB_POOL_RELAY the worker builds no relay at all — no
    queue, no subscriber, no clock reads.  Asserted at the seam the
    worker main() gates on, plus: subscribing a relay sink is what flips
    the telemetry gate, so no-relay keeps events.enabled() False."""
    assert not events.enabled()
    relay = _TelemetryRelay(lane=0)
    events.subscribe(relay.sink)
    try:
        assert events.enabled()
    finally:
        events.unsubscribe(relay.sink)
    assert not events.enabled()


# --------------------------------------------------------------------------- #
# Supervisor side, against a stub worker speaking the frame protocol
# --------------------------------------------------------------------------- #

RELAY_STUB = """
import json, os, struct, sys, time
H = struct.Struct("!II")
out = os.fdopen(os.dup(1), "wb"); os.dup2(2, 1)
inp = os.fdopen(os.dup(0), "rb")
lane = int(sys.argv[sys.argv.index("--lane") + 1])
RELAY = os.environ.get("TCLB_POOL_RELAY") == "1"

def send(doc):
    body = json.dumps(doc).encode()
    out.write(H.pack(len(body), 0)); out.write(body); out.flush()

def recv():
    h = inp.read(H.size)
    if len(h) < H.size:
        raise EOFError
    bl, pl = H.unpack(h)
    doc = json.loads(inp.read(bl).decode())
    inp.read(pl)
    return doc

send({"t": "ready", "pid": os.getpid(), "lane": lane})
while True:
    try:
        doc = recv()
    except EOFError:
        sys.exit(0)
    if doc.get("t") == "shutdown":
        sys.exit(0)
    if doc.get("t") != "job":
        continue
    jid, spec = doc["id"], doc.get("spec") or {}
    send({"t": "hb", "id": jid})
    if RELAY and spec.get("events"):            # honest worker: relays
        send({"t": "telemetry", "id": jid,      # only when asked to
              "events": spec["events"],
              "dropped": spec.get("dropped", 0)})
    if spec.get("progress") or spec.get("stream"):
        niter = spec.get("niter", 2)
        for i in range(1, 3):
            fr = {"t": "progress", "id": jid, "iter": i, "niter": niter,
                  "wall_s": 0.01 * i, "mlups": 1.5 * i}
            if spec.get("stream"):
                fr["reductions"] = {"quantity": "rho", "mean": 1.0,
                                    "min": 0.9, "max": 1.1,
                                    "shape": [2, 2],
                                    "data": [[1.0, 1.0], [1.0, 1.0]]}
            send(fr)
            time.sleep(0.02)
    for fr in spec.get("frames") or []:
        fr = dict(fr); fr.setdefault("id", jid); send(fr)
    if spec.get("behave") == "crash":
        os._exit(3)
    gate = os.environ.get("STUB_GATE")
    while gate and not os.path.exists(gate):
        send({"t": "hb", "id": jid})            # stay live while held
        time.sleep(0.05)
    send({"t": "result", "id": jid, "ok": True, "lane": lane,
          "pid": os.getpid(), "relay_env": RELAY,
          "globals": {"x": 1.0}, "iteration": spec.get("niter", 0),
          "phases": {"stage_s": 0.01, "solve_s": 0.2, "d2h_s": 0.001}})
"""


@pytest.fixture()
def stub_cmd(tmp_path):
    script = tmp_path / "relay_stub.py"
    script.write_text(RELAY_STUB)
    return [sys.executable, str(script)]


def _fast_pool(stub_cmd, **kw):
    kw.setdefault("workers", 1)
    kw.setdefault("heartbeat_timeout_s", 3.0)
    kw.setdefault("spawn_timeout_s", 30.0)
    kw.setdefault("term_grace_s", 0.5)
    kw.setdefault("stable_after_s", 0.2)
    kw.setdefault("retry_policy",
                  RetryPolicy(max_attempts=4, base_delay_s=0.02,
                              max_delay_s=0.1))
    return WorkerPool(worker_cmd=stub_cmd, autostart=False, **kw)


def test_reemit_stamps_worker_identity_and_preserves_ts(stub_cmd):
    """Relayed events re-enter the parent fan-out stamped with the
    worker's pid / lane / incarnation, with the worker's original
    timestamps intact — the merged timeline keeps true ordering."""
    seen = []
    telemetry.subscribe(seen.append)
    try:
        worker_events = [
            {"kind": "span", "name": "iterate", "ts": 123.456,
             "dur_s": 0.5, "mlups": 2.0, "job_id": "gw-1",
             "parent_span": "gw-span-1"},
            {"kind": "engine_selected", "ts": 123.001, "engine": "xla"},
        ]
        with _fast_pool(stub_cmd) as pool:
            job = pool.submit({"events": worker_events, "dropped": 5})
            res = job.result(timeout=60)
        iterate = [e for e in seen if e.get("kind") == "span"
                   and e.get("name") == "iterate"]
        assert len(iterate) == 1
        ev = iterate[0]
        assert ev["worker_pid"] == res["pid"]
        assert ev["lane"] == 0 and ev["incarnation"] == 0
        assert ev["ts"] == 123.456              # original ts survives
        assert ev["job_id"] == "gw-1"
        assert ev["parent_span"] == "gw-span-1"
        sel = [e for e in seen if e.get("kind") == "engine_selected"]
        assert sel and sel[0]["ts"] == 123.001
        ctrs = events.counters()
        assert ctrs.get("pool.relay_events") == 2
        assert ctrs.get("pool.relay_dropped") == 5
    finally:
        telemetry.unsubscribe(seen.append)


def test_unknown_frame_kind_counted_and_warned_once(stub_cmd):
    """Protocol drift (a frame kind this supervisor doesn't know) is
    counted and warned once per kind — and never fails the job."""
    seen = []
    telemetry.subscribe(seen.append)
    try:
        with _fast_pool(stub_cmd) as pool:
            job = pool.submit({"frames": [{"t": "bogus", "x": 1},
                                          {"t": "bogus", "x": 2},
                                          {"t": "wat"}]})
            assert job.result(timeout=60)["globals"] == {"x": 1.0}
            assert pool._unknown_kinds == {"bogus", "wat"}
        assert events.counters().get("pool.unknown_frame") == 3
    finally:
        telemetry.unsubscribe(seen.append)


def test_progress_frames_land_on_job_and_callback(stub_cmd):
    samples = []
    with _fast_pool(stub_cmd) as pool:
        job = pool.submit({"progress": True, "niter": 2},
                          on_progress=lambda j: samples.append(
                              dict(j.progress)))
        job.result(timeout=60)
    assert len(samples) == 2
    assert [s["iter"] for s in samples] == [1, 2]
    assert all("t" not in s and "id" not in s for s in samples)
    assert job.progress["iter"] == 2 and job.progress["mlups"] == 3.0


def test_progress_callback_error_never_fails_job(stub_cmd):
    def bad(_):
        raise RuntimeError("dashboard died")

    with _fast_pool(stub_cmd) as pool:
        job = pool.submit({"progress": True}, on_progress=bad)
        assert job.result(timeout=60)["globals"] == {"x": 1.0}


def test_relay_env_set_by_default_and_cleared_on_opt_out(stub_cmd):
    """relay=True (the default) asks workers to relay via
    TCLB_POOL_RELAY=1; relay=False must clear it even if it leaked into
    the supervisor's own environment — the worker-side strict no-op."""
    with _fast_pool(stub_cmd) as pool:
        assert pool.submit({}).result(timeout=60)["relay_env"] is True
    seen = []
    telemetry.subscribe(seen.append)
    try:
        with _fast_pool(stub_cmd, relay=False,
                        env={"TCLB_POOL_RELAY": "1"}) as pool:
            job = pool.submit({"events": [{"kind": "span",
                                           "name": "iterate"}]})
            assert job.result(timeout=60)["relay_env"] is False
        # no telemetry frames -> nothing re-emitted, nothing counted
        assert not [e for e in seen if e.get("kind") == "span"]
        assert "pool.relay_events" not in events.counters()
    finally:
        telemetry.unsubscribe(seen.append)


def test_worker_crash_harvests_flight_dump(stub_cmd, tmp_path):
    """A dead worker's post-mortem is harvested: the exit event carries
    its flight-<pid>.jsonl path and the pool /status provider lists the
    recent dumps, so triage never hunts the flight dir by pid."""
    seen = []
    telemetry.subscribe(seen.append)
    try:
        with _fast_pool(stub_cmd, job_attempts=1,
                        env={"TCLB_FLIGHT_DIR": str(tmp_path)}) as pool:
            pool.start()
            deadline = time.monotonic() + 30
            while pool.live_workers() < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            pid = pool._workers[0].pid
            assert pid is not None
            # the stub attaches no recorder; fake the dump it would leave
            flight = tmp_path / f"flight-{pid}.jsonl"
            flight.write_text('{"kind": "flight_dump"}\n')
            job = pool.submit({"behave": "crash"})
            with pytest.raises(PoolJobError):
                job.result(timeout=60)
            deadline = time.monotonic() + 30
            while not pool._status()["worker_dumps"] \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            dumps = pool._status()["worker_dumps"]
            assert any(d["pid"] == pid and d["flight"] == str(flight)
                       for d in dumps)
        exits = [e for e in seen if e.get("kind") == "serve.worker_exit"
                 and e.get("pid") == pid]
        assert exits and exits[0]["flight"] == str(flight)
    finally:
        telemetry.unsubscribe(seen.append)


# --------------------------------------------------------------------------- #
# Phase metrics: worker_pid labels + the gateway phase histogram
# --------------------------------------------------------------------------- #


def test_registry_labels_worker_iterate_spans_and_phase_histogram():
    live.enable_live()
    try:
        telemetry.event("span", name="iterate", dur_s=0.25, iters=10,
                        mlups=3.5, engine="xla", model="d2q9",
                        iteration=10, worker_pid=4242, lane=1)
        telemetry.event("gateway.job_done", job_id="j1", status="done",
                        queue_wait_s=0.5, stage_s=0.1, solve_s=2.0,
                        d2h_s=0.01, wall_s=2.7)
        text = live.prometheus_text()
        assert 'tclb_iterate_seconds_count{worker_pid="4242"} 1' in text
        assert ('tclb_mlups{engine="xla",model="d2q9",'
                'worker_pid="4242"} 3.5') in text
        for phase in ("queue_wait", "stage", "solve", "d2h", "e2e"):
            assert ('tclb_gateway_phase_seconds_count{phase="%s"} 1'
                    % phase) in text
        snap = live.registry().snapshot()
        info = snap["info"]["last_iterate"]
        assert info["worker_pid"] == 4242 and info["lane"] == 1
    finally:
        live.disable_live()


def test_report_slo_table_and_compare_regression(tmp_path):
    from tclb_tpu.telemetry import report

    def _trace(path, solve_s):
        telemetry.enable(str(path))
        for i in range(4):
            telemetry.event("gateway.job_done", job_id=f"j{i}",
                            status="done", queue_wait_s=0.1,
                            stage_s=0.2, solve_s=solve_s,
                            d2h_s=0.01, wall_s=solve_s + 0.31)
        telemetry.disable()
        return report.summarize(report.load(str(path)))

    base = _trace(tmp_path / "base.jsonl", 1.0)
    slow = _trace(tmp_path / "slow.jsonl", 2.0)
    assert base["slo"]["solve"]["count"] == 4
    assert base["slo"]["solve"]["p95_s"] == pytest.approx(1.0)
    assert base["slo"]["e2e"]["p50_s"] == pytest.approx(1.31)
    cmp = report.compare(base, slow, threshold=0.2)
    slo_regs = [r for r in cmp["regressions"]
                if r["what"] == "slo_phase_p95"]
    assert {r["phase"] for r in slo_regs} >= {"solve", "e2e"}
    text = report.format_text(base)
    assert "gateway SLO" in text and "solve" in text
    ctext = report.format_compare_text(cmp)
    assert "slo solve" in ctext


# --------------------------------------------------------------------------- #
# Gateway /stream long-poll (stub-backed pool: no jax, no solver)
# --------------------------------------------------------------------------- #


def _stream_body():
    return {"model": "d2q9", "shape": [8, 16], "niter": 2,
            "stream": {"quantity": "rho", "max_dim": 4}}


def test_gateway_stream_long_poll_and_terminal_sample(stub_cmd, tmp_path):
    from tclb_tpu.gateway.http import GatewayServer
    from tclb_tpu.gateway.service import GatewayService

    pool = _fast_pool(stub_cmd)
    svc = GatewayService(str(tmp_path / "store"), pool=pool)
    with GatewayServer(svc, port=0) as srv:
        code, doc = svc.submit(_stream_body())
        assert code == 202, doc
        jid = doc["job"]["id"]
        code, doc = svc.stream(jid, wait=60)
        assert code == 200
        assert doc["seq"] >= 1 and doc["progress"]["iter"] >= 1
        assert doc["progress"]["reductions"]["quantity"] == "rho"
        first_seq = doc["seq"]
        code, res = svc.result(jid, wait=120)
        assert code == 200 and res["job"]["status"] == "done"
        # after terminal: the last sample is retained, seq monotonic
        code, doc = svc.stream(jid, since=0)
        assert code == 200 and doc["status"] == "done"
        assert doc["seq"] >= first_seq
        assert doc["progress"]["iter"] == 2
        # the HTTP route serves the same document
        with urllib.request.urlopen(
                srv.url + f"/v1/jobs/{jid}/stream?wait=5&since=0",
                timeout=30) as resp:
            assert resp.status == 200
            got = json.loads(resp.read())
        assert got["job_id"] == jid and got["progress"]["iter"] == 2
        # phases summed off the worker results land on the record
        assert res["job"]["phases"]["solve_s"] == pytest.approx(0.2)
        # unknown job: a clean 404, not a hang
        code, doc = svc.stream("nope", wait=0)
        assert code == 404


def test_gateway_stream_wakes_on_terminal_when_no_newer_sample(
        stub_cmd, tmp_path):
    """A long-poll waiting for a sample newer than the latest one is
    woken by job completion (instead of sleeping out its full wait
    budget): the stub holds its result frame behind a gate file while
    the poll is in flight."""
    from tclb_tpu.gateway.service import GatewayService

    gate = tmp_path / "gate"
    pool = _fast_pool(stub_cmd, env={"STUB_GATE": str(gate)})
    svc = GatewayService(str(tmp_path / "store"), pool=pool)
    svc.start()
    try:
        code, doc = svc.submit({"model": "d2q9", "shape": [8, 16],
                                "niter": 2})
        assert code == 202, doc
        jid = doc["job"]["id"]
        # both progress samples land before the stub blocks on the gate
        code, doc = svc.stream(jid, wait=60)
        assert code == 200 and doc["status"] == "running"
        deadline = time.monotonic() + 30
        while doc["progress"]["iter"] < 2 \
                and time.monotonic() < deadline:
            code, doc = svc.stream(jid, since=doc["seq"], wait=30)
        latest = doc["seq"]
        got = {}

        def poll():
            got["resp"] = svc.stream(jid, since=latest, wait=120)

        t = threading.Thread(target=poll)
        t.start()
        time.sleep(0.2)                         # poll is parked
        gate.write_text("go")                   # release the result
        code, _ = svc.result(jid, wait=120)
        assert code == 200
        t.join(timeout=30)
        assert not t.is_alive(), "/stream long-poll outlived the job"
        code, doc = got["resp"]
        assert code == 200 and doc["status"] == "done"
        assert doc["seq"] == latest             # no phantom sample
    finally:
        svc.close()


def test_stream_validation_rejects_bad_specs(tmp_path):
    from tclb_tpu.gateway.jobs import ValidationError, validate_body

    ok = {"model": "d2q9", "shape": [8, 16], "niter": 2}
    validate_body(dict(ok, stream=True))
    validate_body(dict(ok, stream={"quantity": "rho", "max_dim": 16}))
    for bad in ({"stream": "yes"}, {"stream": {"nope": 1}},
                {"stream": {"quantity": ""}},
                {"stream": {"max_dim": 0}},
                {"stream": {"max_dim": True}}):
        with pytest.raises(ValidationError):
            validate_body(dict(ok, **bad))


def test_downsample_strides_and_rejects_non_2d():
    import numpy as np

    from tclb_tpu.utils.render import downsample
    plane = np.arange(64 * 48, dtype=np.float64).reshape(64, 48)
    coarse = downsample(plane, max_dim=16)
    assert max(coarse.shape) <= 16
    assert coarse[0, 0] == plane[0, 0]          # stride sample, not blur
    with pytest.raises(ValueError):
        downsample(np.zeros(8), max_dim=4)
