"""Cross-host pod serving tests: the cluster control plane
(:class:`ClusterServer` + :class:`HostRegistry`) and the host-agent
data plane (:class:`ClusterAgent`), in-process over stub-worker pools.

Covered contracts:

* the shared ``!II`` wire: framed duplex :class:`Channel` roundtrips
  (with ``.npy`` payloads) and the ``tear()`` chaos helper producing a
  mid-frame :class:`IpcError` at the peer;
* routing: fair-share spread of a job burst across enrolled hosts,
  resumable ``ckpt_root`` affinity while the owner lives, affinity
  dissolution on host death, and exactly-once in-flight claiming by
  ``mark_lost`` no matter which thread notices a death first;
* enroll / serve / result plumbing end-to-end: results carry the
  serving ``host`` stamp, relayed telemetry is re-emitted gateway-side
  with a ``host`` stamp, and the ``hosts`` ``/status`` provider and
  ``GET /v1/hosts`` snapshot reflect enrollment state;
* requeue-on-host-death: an abruptly lost host's in-flight jobs finish
  on the surviving host (zero lost), the loss leaves a dead-host dump
  and a flight-recorder file, and a re-enrollment under the same host
  id bumps the incarnation (``gateway.host_rejoined``).

The agents here run in-process against stub worker pools (no solver
imports — milliseconds per job); the full multi-process pod smoke
(separate gateway + agent OS processes, SIGKILL mid-solve, digest
parity) runs in CI's ``pod`` job.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tclb_tpu import faults, telemetry
from tclb_tpu.cluster import wire
from tclb_tpu.cluster.agent import ClusterAgent
from tclb_tpu.cluster.registry import HostRegistry
from tclb_tpu.cluster.server import ClusterServer
from tclb_tpu.gateway.service import GatewayService
from tclb_tpu.serve.pool import WorkerPool
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.telemetry import live


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    # host-loss events trigger automatic flight dumps: keep them in tmp
    monkeypatch.setenv("TCLB_FLIGHT_DIR", str(tmp_path / "flight"))
    telemetry.disable()
    live.registry().reset()
    faults.uninstall()
    yield
    faults.uninstall()
    telemetry.disable()
    live.registry().reset()


# --------------------------------------------------------------------------- #
# Wire: framed channels over a socket
# --------------------------------------------------------------------------- #


def test_channel_roundtrip_and_tear():
    sa, sb = socket.socketpair()
    a, b = wire.Channel(sa, peer="a"), wire.Channel(sb, peer="b")
    try:
        arr = np.arange(6, dtype=np.float64).reshape(2, 3)
        a.send({"t": "result", "id": "cj-1", "ok": True},
               wire.npy_bytes(arr))
        doc, payload = b.recv()
        assert doc["t"] == "result" and doc["ok"] is True
        np.testing.assert_array_equal(wire.npy_load(payload), arr)
        # tear(): the peer sees a mid-frame IpcError, not a clean EOF
        a.tear()
        with pytest.raises(wire.IpcError):
            b.recv()
    finally:
        a.close()
        b.close()


def test_channel_close_gives_clean_eof():
    sa, sb = socket.socketpair()
    a, b = wire.Channel(sa, peer="a"), wire.Channel(sb, peer="b")
    a.send({"t": "hb"})
    assert b.recv()[0] == {"t": "hb"}
    a.close()
    with pytest.raises(EOFError):
        b.recv()
    b.close()


# --------------------------------------------------------------------------- #
# Registry: routing + death bookkeeping (no sockets)
# --------------------------------------------------------------------------- #


class _Job:
    def __init__(self, jid):
        self.id = jid


def test_registry_fair_share_spreads_burst():
    reg = HostRegistry()
    a, _, _ = reg.enroll("A", 1, lanes=1, channel=None)
    b, _, _ = reg.enroll("B", 2, lanes=1, channel=None)
    counts = {"A": 0, "B": 0}
    for i in range(8):
        rec = reg.pick({"job_id": f"j{i}"})
        assert reg.assign(rec, _Job(f"j{i}"))
        counts[rec.host] += 1
    # load-per-lane routing: an 8-job burst lands 4/4, not 8/0
    assert counts == {"A": 4, "B": 4}


def test_registry_resumable_affinity_until_owner_dies():
    reg = HostRegistry()
    reg.enroll("A", 1, lanes=1, channel=None)
    reg.enroll("B", 2, lanes=1, channel=None)
    doc = {"ckpt_root": "/store/ckpt/j-7"}
    owner = reg.pick(doc).host
    for _ in range(4):          # segments stick to the warm host
        assert reg.pick(doc).host == owner
    jobs = reg.mark_lost(reg.get(owner), "preempted")
    assert jobs == []
    other = reg.pick(doc)
    assert other is not None and other.host != owner
    snap = reg.snapshot()
    assert snap["dead_host_dumps"][-1]["host"] == owner


def test_registry_mark_lost_claims_inflight_exactly_once():
    reg = HostRegistry()
    rec, _, _ = reg.enroll("A", 1, lanes=2, channel=None)
    reg.assign(rec, _Job("j1"))
    reg.assign(rec, _Job("j2"))
    jobs = reg.mark_lost(rec, "channel closed")
    assert sorted(j.id for j in jobs) == ["j1", "j2"]
    # the racing watchdog/reader gets None and must not requeue again
    assert reg.mark_lost(rec, "heartbeat timeout") is None
    assert reg.live() == [] and reg.live_lanes() == 0


def test_registry_rejoin_bumps_incarnation():
    reg = HostRegistry()
    first, rejoined, stale = reg.enroll("A", 1, lanes=1, channel=None)
    assert first.incarnation == 0 and not rejoined and stale is None
    reg.mark_lost(first, "gone")
    second, rejoined, stale = reg.enroll("A", 9, lanes=2, channel=None)
    assert second.incarnation == 1 and rejoined and stale is None
    # a still-live duplicate is handed back for teardown
    third, rejoined, stale = reg.enroll("A", 10, lanes=2, channel=None)
    assert third.incarnation == 2 and rejoined and stale is second


# --------------------------------------------------------------------------- #
# Server + agents in-process over stub pools
# --------------------------------------------------------------------------- #

STUB_WORKER = """
import hashlib, json, os, struct, sys, time
H = struct.Struct("!II")
out = os.fdopen(os.dup(1), "wb")
os.dup2(2, 1)
inp = os.fdopen(os.dup(0), "rb")
lane = int(sys.argv[sys.argv.index("--lane") + 1])

def send(doc):
    body = json.dumps(doc).encode()
    out.write(H.pack(len(body), 0)); out.write(body); out.flush()

def recv():
    h = inp.read(H.size)
    if len(h) < H.size:
        raise EOFError
    bl, pl = H.unpack(h)
    doc = json.loads(inp.read(bl).decode())
    inp.read(pl)
    return doc

send({"t": "ready", "pid": os.getpid(), "lane": lane})
while True:
    try:
        doc = recv()
    except EOFError:
        sys.exit(0)
    if doc.get("t") == "shutdown":
        sys.exit(0)
    if doc.get("t") != "job":
        continue
    jid, spec = doc["id"], doc.get("spec") or {}
    send({"t": "hb", "id": jid})
    time.sleep(float(spec.get("sleep", 0)))
    work = {k: v for k, v in spec.items() if k != "sleep"}
    digest = hashlib.sha256(
        json.dumps(work, sort_keys=True).encode()).hexdigest()
    send({"t": "result", "id": jid, "ok": True, "lane": lane,
          "pid": os.getpid(), "globals": {"n": spec.get("n")},
          "state_sha256": digest, "iteration": spec.get("niter", 0)})
"""


@pytest.fixture()
def stub_cmd(tmp_path):
    script = tmp_path / "stub_worker.py"
    script.write_text(STUB_WORKER)
    return [sys.executable, str(script)]


def _stub_pool(stub_cmd, workers=1):
    return WorkerPool(worker_cmd=stub_cmd, workers=workers,
                      heartbeat_timeout_s=30.0, spawn_timeout_s=30.0,
                      term_grace_s=0.5,
                      retry_policy=RetryPolicy(max_attempts=4,
                                               base_delay_s=0.02,
                                               max_delay_s=0.1),
                      autostart=False)


def _agent(server, host_id, stub_cmd, workers=1, relay=False):
    return ClusterAgent(server.address, host_id=host_id,
                        hb_interval_s=0.2,
                        relay=relay,
                        pool=_stub_pool(stub_cmd, workers=workers))


def _wait(cond, timeout=60, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_agent_enrolls_and_serves_with_host_stamp(stub_cmd):
    evts = []
    telemetry.subscribe(evts.append)
    srv = ClusterServer(heartbeat_timeout_s=10.0)
    agent = None
    try:
        srv.start()
        agent = _agent(srv, "h1", stub_cmd).start()
        _wait(lambda: srv.live_hosts() == 1, what="enrollment")
        assert srv.live_workers() >= 1
        jobs = [srv.submit({"n": i, "niter": 3}) for i in range(3)]
        for i, j in enumerate(jobs):
            res = j.result(timeout=60)
            assert res["globals"] == {"n": i}
            assert res["host"] == "h1"          # the serving host stamp
            assert res["iteration"] == 3
        st = srv.stats()
        assert st["done"] == 3 and st["failed"] == 0
        assert st["hosts_live"] == 1
        # the hosts /status provider reflects the enrollment
        snap = live.status_snapshot()["hosts"]
        (h,) = snap["hosts"]
        assert h["host"] == "h1" and h["state"] == "live"
        assert h["incarnation"] == 0 and h["jobs_done"] == 3
        assert h["last_heartbeat_age_s"] < 10.0
        kinds = [e.get("kind") for e in evts]
        assert "gateway.host_enrolled" in kinds
        assert "cluster.job_dispatched" in kinds
        assert "cluster.job_done" in kinds
    finally:
        if agent is not None:
            agent.stop()
        srv.close(wait=False)
        telemetry.unsubscribe(evts.append)
    assert "hosts" not in live.status_snapshot()


def test_job_burst_fair_shares_across_two_hosts(stub_cmd):
    srv = ClusterServer()
    agents = []
    try:
        srv.start()
        agents = [_agent(srv, h, stub_cmd).start() for h in ("hA", "hB")]
        _wait(lambda: srv.live_hosts() == 2, what="two enrollments")
        jobs = [srv.submit({"n": i, "sleep": 0.3}) for i in range(6)]
        served = {j.result(timeout=120)["host"] for j in jobs}
        # the burst spread: neither host swallowed the whole sweep
        assert served == {"hA", "hB"}
        assert srv.stats()["done"] == 6
    finally:
        for a in agents:
            a.stop()
        srv.close(wait=False)


def test_host_death_requeues_inflight_and_rejoins(stub_cmd, tmp_path):
    """Kill one of two hosts mid-burst: every job still completes on
    the survivor (zero lost), the loss is recorded (event + dead-host
    dump + flight file), and a restarted agent under the same host id
    re-enrolls at the next incarnation."""
    evts = []
    telemetry.subscribe(evts.append)
    srv = ClusterServer(job_attempts=3, heartbeat_timeout_s=10.0)
    b = rejoin = None
    try:
        srv.start()
        a = _agent(srv, "hA", stub_cmd).start()
        b = _agent(srv, "hB", stub_cmd).start()
        _wait(lambda: srv.live_hosts() == 2, what="two enrollments")
        jobs = [srv.submit({"n": i, "sleep": 0.5}) for i in range(4)]
        _wait(lambda: len(srv.registry.get("hA").inflight) >= 1,
              what="a job in flight on hA")
        a.stop()                       # abrupt: no result for its jobs
        for i, j in enumerate(jobs):   # zero lost: all complete on hB
            res = j.result(timeout=120)
            assert res["globals"] == {"n": i}
        hosts = {j._result["host"] for j in jobs}
        assert "hB" in hosts
        st = srv.stats()
        assert st["done"] == 4 and st["failed"] == 0
        assert st["requeued"] >= 1
        lost = next(e for e in evts
                    if e.get("kind") == "gateway.host_lost")
        assert lost["host"] == "hA" and lost["jobs_requeued"] >= 1
        assert any(e.get("kind") == "cluster.job_requeued"
                   for e in evts)
        snap = srv.registry.snapshot()
        assert snap["dead_host_dumps"][-1]["host"] == "hA"
        # the loss dumped the flight recorder for the post-mortem
        flight = tmp_path / "flight"
        assert flight.exists() and any(
            n.startswith("flight-") for n in os.listdir(flight))
        # restart under the same id: rejoin at the next incarnation
        rejoin = _agent(srv, "hA", stub_cmd).start()
        _wait(lambda: (srv.registry.get("hA").state == "live"
                       and srv.registry.get("hA").incarnation == 1),
              what="hA rejoin")
        assert any(e.get("kind") == "gateway.host_rejoined"
                   and e.get("host") == "hA" for e in evts)
        res = srv.submit({"n": 99}).result(timeout=60)
        assert res["host"] in ("hA", "hB")
    finally:
        for ag in (b, rejoin):
            if ag is not None:
                ag.stop()
        srv.close(wait=False)
        telemetry.unsubscribe(evts.append)


def test_relayed_telemetry_reemitted_with_host_stamp(stub_cmd):
    """Agent-side pool events cross the control channel and re-emit in
    the gateway's fan-out stamped with the originating host, so one
    trace renders a cross-host timeline even when two hosts reuse a
    worker pid."""
    evts = []
    telemetry.subscribe(evts.append)
    srv = ClusterServer()
    agent = None
    try:
        srv.start()
        agent = _agent(srv, "h1", stub_cmd, relay=True).start()
        _wait(lambda: srv.live_hosts() == 1, what="enrollment")
        assert srv.submit({"n": 0}).result(timeout=60)["host"] == "h1"

        def relayed():
            return [e for e in evts
                    if e.get("kind") == "serve.pool_job_started"
                    and e.get("host") == "h1"]

        _wait(relayed, what="a host-stamped relayed pool event")
        # the direct (agent-local) emission has no host; the relayed
        # re-emission is the disambiguated cross-host copy
        assert any(e.get("kind") == "serve.pool_job_started"
                   and "host" not in e for e in evts)
    finally:
        if agent is not None:
            agent.stop()
        srv.close(wait=False)
        telemetry.unsubscribe(evts.append)


def test_empty_pod_holds_jobs_until_first_enrollment(stub_cmd):
    srv = ClusterServer()
    agent = None
    try:
        srv.start()
        job = srv.submit({"n": 1})     # no hosts yet: waits, no fail-fast
        time.sleep(0.3)
        assert not job.done
        agent = _agent(srv, "late", stub_cmd).start()
        assert job.result(timeout=60)["host"] == "late"
    finally:
        if agent is not None:
            agent.stop()
        srv.close(wait=False)


def test_close_fails_pending_jobs_fast(stub_cmd):
    srv = ClusterServer()
    srv.start()
    job = srv.submit({"n": 1})         # empty pod: would wait forever
    srv.close(wait=False)
    from tclb_tpu.serve.pool import PoolJobError
    with pytest.raises(PoolJobError, match="closed"):
        job.result(timeout=10)
    with pytest.raises(PoolJobError, match="closed"):
        srv.submit({"n": 2})


# --------------------------------------------------------------------------- #
# Gateway surface: /v1/hosts provider
# --------------------------------------------------------------------------- #


def test_gateway_hosts_endpoint_requires_cluster(tmp_path):
    svc = GatewayService(str(tmp_path / "store"))
    try:
        code, doc = svc.hosts()
        assert code == 404 and "--cluster" in doc["error"]
    finally:
        svc.store.close()


def test_gateway_hosts_endpoint_snapshots_registry(tmp_path, stub_cmd):
    srv = ClusterServer()
    svc = GatewayService(str(tmp_path / "store"), pool=srv)
    agent = None
    try:
        srv.start()
        agent = _agent(srv, "pod-0", stub_cmd).start()
        _wait(lambda: srv.live_hosts() == 1, what="enrollment")
        code, doc = svc.hosts()
        assert code == 200
        (h,) = doc["hosts"]
        assert h["host"] == "pod-0" and h["state"] == "live"
        assert h["lanes"] == 1
        assert svc.health()["hosts_live"] == 1
    finally:
        if agent is not None:
            agent.stop()
        srv.close(wait=False)
        svc.store.close()


# --------------------------------------------------------------------------- #
# Full pod smoke: real gateway + agent OS processes (CI `pod` job)
# --------------------------------------------------------------------------- #

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pod_env(tmp_path, tag, trace=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               TCLB_FLIGHT_DIR=str(tmp_path / f"flight-{tag}"))
    # the gateway's trace must not leak into agents (nor any ambient
    # fault schedule into either side)
    env.pop("TCLB_TELEMETRY", None)
    env.pop("TCLB_FAULTS", None)
    if trace is not None:
        env["TCLB_TELEMETRY"] = str(trace)
    return env


def _http(url, method="GET", body=None, timeout=300):
    req = urllib.request.Request(
        url, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _spawn_pod_gateway(tmp_path, store, tag):
    """Start ``python -m tclb_tpu gateway --cluster`` (pod mode: zero
    local lanes) and parse the three addresses it prints — HTTP front
    door, monitor, and the cluster control plane agents dial."""
    logf = open(tmp_path / f"gateway-{tag}.log", "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tclb_tpu", "gateway",
         "--port", "0", "--store", str(store), "--workers", "0",
         "--cluster", "127.0.0.1:0",
         "--cluster-heartbeat-timeout", "3",
         "--monitor", "127.0.0.1:0"],
        env=_pod_env(tmp_path, f"gw-{tag}",
                     trace=tmp_path / f"trace-{tag}.jsonl"),
        cwd=REPO, stdout=logf, stderr=subprocess.STDOUT, text=True)
    deadline = time.time() + 120
    urls = {}
    while time.time() < deadline:
        text = open(logf.name).read()
        for line in text.splitlines():
            if line.startswith("monitor: "):
                urls["monitor"] = line.split()[1].rsplit("/", 1)[0]
            elif line.startswith("cluster: "):
                urls["cluster"] = line.split()[1]
            elif line.startswith("gateway: http"):
                urls["gateway"] = line.split()[1].rsplit("/v1", 1)[0]
        if len(urls) == 3:
            return proc, urls
        if proc.poll() is not None:
            raise AssertionError(f"gateway CLI died:\n{text}")
        time.sleep(0.1)
    proc.kill()
    raise AssertionError(f"gateway CLI never printed its URLs: {urls}")


def _spawn_agent(tmp_path, cluster_addr, host_id, incarnation=0):
    """Start a host-agent OS process (own process group, so a SIGKILL
    takes its worker lanes with it — a whole-host death) and wait for
    its enrollment line at the expected incarnation."""
    logf = open(tmp_path / f"agent-{host_id}.log", "a+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tclb_tpu.cluster.agent",
         "--gateway", cluster_addr, "--host-id", host_id,
         "--workers", "1", "--hb-interval", "0.5"],
        env=_pod_env(tmp_path, host_id), cwd=REPO,
        stdout=logf, stderr=subprocess.STDOUT, text=True,
        start_new_session=True)
    needle = f"agent: enrolled host={host_id} incarnation={incarnation}"
    deadline = time.time() + 120
    while time.time() < deadline:
        if needle in open(logf.name).read():
            return proc
        if proc.poll() is not None:
            raise AssertionError(
                f"agent {host_id} died:\n{open(logf.name).read()}")
        time.sleep(0.1)
    proc.kill()
    raise AssertionError(f"agent {host_id} never enrolled")


#: resumable pod job: big enough that the first checkpoint lands with
#: most of the solve still ahead (a wide SIGKILL window), small enough
#: that the uninterrupted reference stays a few seconds on CPU
_POD_RESUMABLE = {"model": "d2q9", "shape": [64, 128], "niter": 6000,
                  "params": {"nu": 0.05}, "resumable": True,
                  "checkpoint_every": 200, "digest": True}


@pytest.mark.slow
def test_pod_cli_agents_spread_sigkill_resume_bit_identical(tmp_path):
    """The full pod smoke (CI ``pod`` job): a gateway CLI in pod mode
    (``--cluster``, zero local lanes) + two host-agent OS processes.  A
    16-job burst spreads over both hosts; SIGKILLing one agent's whole
    process group mid-resumable-solve never touches the gateway — the
    job requeues to the survivor, resumes from its newest checkpoint
    (``resumed_from > 0``) and lands bit-identical to the uninterrupted
    reference; the killed host re-enrolls at the next incarnation; the
    gateway trace and /metrics carry host-stamped worker telemetry."""
    store = tmp_path / "store"
    gw, urls = _spawn_pod_gateway(tmp_path, store, "pod")
    agents = {}
    try:
        for hid in ("hostA", "hostB"):
            agents[hid] = _spawn_agent(tmp_path, urls["cluster"], hid)
        code, doc = _http(urls["gateway"] + "/v1/hosts")
        assert code == 200
        assert {h["host"]: h["state"] for h in doc["hosts"]} == \
            {"hostA": "live", "hostB": "live"}

        # 16-job burst: fair share must give BOTH hosts work, and every
        # record + result row must say which host served it
        jids = []
        for i in range(16):
            code, doc = _http(urls["gateway"] + "/v1/jobs", "POST",
                              {"model": "d2q9", "shape": [16, 32],
                               "niter": 5, "params": {"nu": 0.05},
                               "digest": True, "name": f"sweep{i}"})
            assert code == 202, doc
            jids.append(doc["job"]["id"])
        served = {}
        for jid in jids:
            code, doc = _http(urls["gateway"]
                              + f"/v1/jobs/{jid}/result?wait=300")
            assert code == 200 and doc["job"]["status"] == "done", doc
            (host,) = doc["job"]["hosts"]
            served[host] = served.get(host, 0) + 1
            assert doc["results"][0]["host"] == host
        assert set(served) == {"hostA", "hostB"} and \
            min(served.values()) >= 1, served

        # uninterrupted reference for the resumable digest
        code, doc = _http(urls["gateway"] + "/v1/jobs", "POST",
                          dict(_POD_RESUMABLE, name="ref"))
        assert code == 202, doc
        code, doc = _http(
            urls["gateway"] + f"/v1/jobs/{doc['job']['id']}"
            + "/result?wait=300")
        assert code == 200 and doc["job"]["status"] == "done", doc
        assert doc["job"]["resumed_from"] is None
        ref = doc["results"][0]

        # chaos run: once a checkpoint has landed, SIGKILL the serving
        # host's whole process group (agent + its worker lanes)
        code, doc = _http(urls["gateway"] + "/v1/jobs", "POST",
                          dict(_POD_RESUMABLE, name="chaos"))
        assert code == 202, doc
        jid = doc["job"]["id"]
        ckroot = store / "ckpt" / jid
        victim = None
        deadline = time.time() + 240
        while time.time() < deadline:
            _, snap = _http(urls["gateway"] + "/v1/hosts")
            busy = [h for h in snap["hosts"]
                    if h["state"] == "live" and h["inflight"] >= 1]
            if busy and ckroot.exists() and os.listdir(ckroot):
                victim = busy[0]["host"]
                break
            assert gw.poll() is None
            time.sleep(0.05)
        assert victim, "no host went busy with a landed checkpoint"
        os.killpg(agents[victim].pid, signal.SIGKILL)

        code, doc = _http(urls["gateway"]
                          + f"/v1/jobs/{jid}/result?wait=300")
        assert code == 200, doc
        assert gw.poll() is None            # the gateway never died
        job = doc["job"]
        assert job["status"] == "done"
        assert job["resumed_from"] is not None and job["resumed_from"] > 0
        survivor = ({"hostA", "hostB"} - {victim}).pop()
        assert survivor in job["hosts"]
        got = doc["results"][0]
        assert got["state_sha256"] == ref["state_sha256"]
        assert got["globals"] == ref["globals"]

        # the killed host re-enrolls under the same id, next incarnation
        agents[victim].wait(timeout=30)
        agents[victim] = _spawn_agent(tmp_path, urls["cluster"], victim,
                                      incarnation=1)

        def _rejoined():
            _, snap = _http(urls["gateway"] + "/v1/hosts")
            rec = {h["host"]: h for h in snap["hosts"]}[victim]
            return rec["state"] == "live" and rec["incarnation"] == 1
        _wait(_rejoined, what="host rejoin at incarnation 1")

        # relayed telemetry: the agents' worker iterate spans reach the
        # GATEWAY's /metrics and JSONL trace with a host label, and the
        # membership churn left its flight-recorder events
        with urllib.request.urlopen(urls["monitor"] + "/metrics",
                                    timeout=30) as resp:
            metrics = resp.read().decode()
        assert 'host="host' in metrics, metrics[:400]
        assert "tclb_cluster_hosts_lost_total" in metrics
        trace = [json.loads(line)
                 for line in open(tmp_path / "trace-pod.jsonl")]
        kinds = {e.get("kind") for e in trace}
        assert {"gateway.host_enrolled", "gateway.host_lost",
                "gateway.host_rejoined"} <= kinds, sorted(
                    k for k in kinds if k)
        span_hosts = {e.get("host") for e in trace
                      if e.get("kind") == "span"
                      and e.get("name") == "iterate"}
        assert span_hosts & {"hostA", "hostB"}, sorted(
            h for h in span_hosts if h)
    finally:
        for p in agents.values():
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                p.kill()
            p.wait()
        gw.kill()
        gw.wait()
