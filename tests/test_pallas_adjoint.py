"""Differentiable Pallas fast path (ops/pallas_adjoint): the custom_vjp
step whose backward is itself a Pallas band kernel — the TPU analogue of
the reference's Tapenade-generated ``Run_b`` device kernel
(reference src/cuda.cu.Rt:240-256).  Pinned against the XLA adjoint (the
reference pins Tapenade against <FDTest>), plus an FD check."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.adjoint import (InternalTopology, fd_test,
                              make_unsteady_gradient)
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.ops import pallas_adjoint

pytestmark = pytest.mark.slow


def _setup(ny=16, nx=128):
    m = get_model("d2q9_adj")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                            "DragInObj": 1.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[4:12, 40:80] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    return m, lat


def test_supports_diff():
    m = get_model("d2q9_adj")
    assert pallas_adjoint.supports_diff(m, (16, 128), jnp.float32)
    assert not pallas_adjoint.supports_diff(m, (15, 128), jnp.float32)
    assert not pallas_adjoint.supports_diff(m, (16, 96), jnp.float32)
    # Field-stencil models are out of the pointwise-collide scope
    assert not pallas_adjoint.supports_diff(get_model("d2q9_kuper"),
                                            (16, 128), jnp.float32)
    # multi-lattice single-stage IS in scope
    assert pallas_adjoint.supports_diff(get_model("d2q9_heat"),
                                        (16, 128), jnp.float32)


def test_pallas_gradient_matches_xla():
    """The whole point: identical gradients from the Pallas primal+adjoint
    kernels and the XLA reverse-mode — same physics, two engines."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 6

    g_x = make_unsteady_gradient(m, design, niter, levels=1)
    obj_x, gx, fin_x = g_x(theta0, lat.state, lat.params)
    g_p = make_unsteady_gradient(m, design, niter, levels=1,
                                 engine="pallas", shape=lat.shape)
    obj_p, gp, fin_p = g_p(theta0, lat.state, lat.params)

    assert float(obj_x) == pytest.approx(float(obj_p), rel=1e-5)
    gx, gp = np.asarray(gx), np.asarray(gp)
    assert np.abs(gx).max() > 0.0, "vacuous: gradient must be nonzero"
    np.testing.assert_allclose(gp, gx, rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(np.asarray(fin_p.fields),
                               np.asarray(fin_x.fields),
                               rtol=1e-5, atol=1e-6)


def test_pallas_gradient_vs_fd():
    """FDTest on the Pallas engine (reference acFDTest,
    src/Handlers.cpp.Rt:1944): central differences at f32 tolerance."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 5
    grad_fn = make_unsteady_gradient(m, design, niter, levels=1,
                                     engine="pallas", shape=lat.shape)
    obj, g, _ = grad_fn(theta0, lat.state, lat.params)

    def loss(theta):
        o, _, _ = grad_fn(theta, lat.state, lat.params)
        return o

    # f32 primal: FD step and tolerance sized for single precision
    recs = fd_test(loss, g, theta0, n_checks=3, eps=3e-3)
    for r in recs:
        if abs(r["adjoint"]) < 1e-6 and abs(r["fd"]) < 1e-2:
            continue  # flat component: FD is pure f32 noise there
        assert r["rel_err"] < 5e-2, r


def test_pallas_gradient_with_checkpoint_levels():
    """The custom_vjp step composes with the nested remat scan (the
    SnapLevel analogue) — levels=1 and levels=2 agree."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    g1 = make_unsteady_gradient(m, design, 9, levels=1,
                                engine="pallas", shape=lat.shape)
    g2 = make_unsteady_gradient(m, design, 9, levels=2,
                                engine="pallas", shape=lat.shape)
    o1, gr1, _ = g1(theta0, lat.state, lat.params)
    o2, gr2, _ = g2(theta0, lat.state, lat.params)
    assert float(o1) == pytest.approx(float(o2), rel=1e-6)
    np.testing.assert_allclose(np.asarray(gr1), np.asarray(gr2),
                               rtol=1e-5, atol=1e-8)
