"""Differentiable Pallas fast path (ops/pallas_adjoint): the custom_vjp
chunk whose backward is the in-band VJP of the SAME traced action chain
the forward kernel runs — the TPU analogue of the reference's
Tapenade-generated ``Run_b`` device kernel (reference
src/cuda.cu.Rt:240-256) including its settings tape (``DynamicsS_b``,
tools/makeAD:24).  Pinned against the XLA adjoint (the reference pins
Tapenade against <FDTest>), plus an FD check."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.adjoint import (InternalTopology, OptimalControl, fd_test,
                              make_unsteady_gradient)
from tclb_tpu.adjoint.run import design_needs
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.ops import pallas_adjoint

pytestmark = pytest.mark.slow


def _setup(ny=16, nx=128, model="d2q9_adj"):
    m = get_model(model)
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                            "DragInObj": 1.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[4:12, 40:80] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    return m, lat


def test_supports_diff():
    m = get_model("d2q9_adj")
    assert pallas_adjoint.supports_diff(m, (16, 128), jnp.float32)
    assert not pallas_adjoint.supports_diff(m, (15, 128), jnp.float32)
    assert not pallas_adjoint.supports_diff(m, (16, 96), jnp.float32)
    # Field-stencil + multi-stage models ARE in scope now (the backward
    # kernel VJPs the full traced chain; round-4's pointwise-collide
    # restriction is gone) — kuper at reduced chunk k=2
    assert pallas_adjoint.supports_diff(get_model("d2q9_kuper_adj"),
                                        (16, 128), jnp.float32)
    assert pallas_adjoint.max_chunk(get_model("d2q9_kuper_adj")) == 2
    assert pallas_adjoint.max_chunk(m) == 4
    # multi-lattice single-stage
    assert pallas_adjoint.supports_diff(get_model("d2q9_heat"),
                                        (16, 128), jnp.float32)
    # the heat_adj BASELINE config runs the fused adjoint (round-4 gap)
    assert pallas_adjoint.supports_diff(get_model("d2q9_heat_adj"),
                                        (16, 128), jnp.float32)
    # series flavor (control gradients)
    assert pallas_adjoint.supports_diff(m, (16, 128), jnp.float32,
                                        series=True)
    # 3D is in scope: fused Pallas backward whenever a (k, bz) slab
    # config fits VMEM, XLA-chain backward otherwise
    m3 = get_model("d3q19_adj")
    assert pallas_adjoint.supports_diff(m3, (8, 16, 128), jnp.float32)
    assert pallas_adjoint.max_chunk(m3) == 4
    plan3 = pallas_adjoint.adjoint_slab_plan(m3, (8, 16, 128))
    assert plan3 is not None
    k3, bz3 = plan3
    assert k3 >= 1 and 8 % bz3 == 0


def test_design_needs_classifier():
    m = get_model("d2q9_adj")
    assert design_needs(InternalTopology(m)) == {"state"}
    assert design_needs(OptimalControl(m, "Velocity")) == {"series"}

    class Weird:
        pass

    assert design_needs(Weird()) is None


def test_pallas_gradient_matches_xla():
    """The whole point: identical gradients from the Pallas primal+adjoint
    kernels and the XLA reverse-mode — same physics, two engines."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 8   # divisible by the k=4 chunk

    g_x = make_unsteady_gradient(m, design, niter, levels=1, engine="xla")
    obj_x, gx, fin_x = g_x(theta0, lat.state, lat.params)
    g_p = make_unsteady_gradient(m, design, niter, levels=1,
                                 engine="pallas", shape=lat.shape)
    assert g_p.engine_name.startswith("pallas_adjoint")
    obj_p, gp, fin_p = g_p(theta0, lat.state, lat.params)

    assert float(obj_x) == pytest.approx(float(obj_p), rel=1e-5)
    gx, gp = np.asarray(gx), np.asarray(gp)
    assert np.abs(gx).max() > 0.0, "vacuous: gradient must be nonzero"
    np.testing.assert_allclose(gp, gx, rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(np.asarray(fin_p.fields),
                               np.asarray(fin_x.fields),
                               rtol=1e-5, atol=1e-6)


def test_pallas_series_gradient_matches_xla():
    """Control-series (settings-tape) cotangents: an OptimalControl design
    differentiates through params.time_series on the fused kernels —
    round 4 returned ZERO here by contract (the reference's control
    gradients always ran the tuned adjoint kernel via DynamicsS_b)."""
    m, lat = _setup()
    niter = 8
    lat.set_setting_series("Velocity",
                           0.05 + 0.01 * np.sin(np.arange(niter)), zone=0)
    design = OptimalControl(m, "Velocity", zone=0)
    theta0 = design.get(lat.state, lat.params)
    g_x = make_unsteady_gradient(m, design, niter, levels=1, engine="xla")
    obj_x, gx, _ = g_x(theta0, lat.state, lat.params)
    g_p = make_unsteady_gradient(m, design, niter, levels=1,
                                 engine="pallas", shape=lat.shape)
    assert "series" in g_p.engine_name
    obj_p, gp, _ = g_p(theta0, lat.state, lat.params)
    gx, gp = np.asarray(gx), np.asarray(gp)
    assert float(obj_x) == pytest.approx(float(obj_p), rel=1e-5)
    assert np.abs(gx).max() > 0.0
    np.testing.assert_allclose(gp, gx, rtol=2e-4, atol=1e-6)


def test_pallas_heat_adj_gradient():
    """The d2q9_heat_adj BASELINE gradient (heat_adj.xml's physics) runs
    the fused adjoint and matches XLA — round-4 Missing #1."""
    m = get_model("d2q9_heat_adj")
    ny, nx = 16, 128
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.05, "InletVelocity": 0.02,
                            "FluidAlfa": 0.05, "HeatFluxInObj": 1.0,
                            "DragInObj": 0.3})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[1:-1, -3] = m.flag_for("MRT", "Outlet")
    flags[4:12, 40:80] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = jnp.clip(design.get(lat.state, lat.params) * 0.7 + 0.1, 0, 1)
    g_x = make_unsteady_gradient(m, design, 8, levels=1, engine="xla")
    obj_x, gx, _ = g_x(theta0, lat.state, lat.params)
    g_p = make_unsteady_gradient(m, design, 8, levels=1,
                                 engine="pallas", shape=lat.shape)
    obj_p, gp, _ = g_p(theta0, lat.state, lat.params)
    gx, gp = np.asarray(gx), np.asarray(gp)
    assert float(obj_x) == pytest.approx(float(obj_p), rel=1e-5)
    assert np.abs(gx).max() > 0.0
    np.testing.assert_allclose(gp, gx, rtol=1e-4, atol=1e-7)


def test_pallas_kuper_gradient():
    """Multi-stage + Field-stencil chain (d2q9_kuper_adj: BaseIteration +
    CalcPhi, psi stencil): the generalized backward covers it at k=2."""
    m = get_model("d2q9_kuper_adj")
    ny, nx = 16, 128
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"omega": 1.0, "Temperature": 0.56, "FAcc": 1.0,
                            "Magic": 0.01, "MagicA": -0.152,
                            "MagicF": -2.0 / 3.0, "Density": 3.26,
                            "WallForceXInObj": 1.0})
    lat.set_setting("Density", 0.0145, zone=1)
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    yy, xx = np.mgrid[0:ny, 0:nx]
    flags[((yy - 8) ** 2 + (xx - 50) ** 2) < 36] = m.flag_for("MRT", zone=1)
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[4:12, 40:80] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    g_x = make_unsteady_gradient(m, design, 4, levels=1, engine="xla")
    obj_x, gx, _ = g_x(theta0, lat.state, lat.params)
    g_p = make_unsteady_gradient(m, design, 4, levels=1,
                                 engine="pallas", shape=lat.shape)
    obj_p, gp, _ = g_p(theta0, lat.state, lat.params)
    gx, gp = np.asarray(gx), np.asarray(gp)
    assert np.abs(gx).max() > 0.0
    np.testing.assert_allclose(gp, gx, rtol=1e-3, atol=2e-6)


def _setup_3d(shape=(4, 8, 128)):
    m = get_model("d3q19_adj")
    lat = Lattice(m, shape, dtype=jnp.float32,
                  settings={"nu": 0.1, "Velocity": 0.02, "Porocity": 0.5,
                            "DragInObj": 1.0})
    flags = np.full(shape, m.flag_for("MRT"), np.uint16)
    flags[:, 0, :] = flags[:, -1, :] = m.flag_for("Wall")
    flags[1:3, 2:6, 20:40] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    return m, lat


def test_pallas_3d_fused_gradient_matches_xla():
    """The 3D tentpole: the fused z-slab Pallas BACKWARD kernel (the 3D
    ``Run_b``) against the all-XLA adjoint — same traced action chain,
    so the gradients must agree at f32 tolerance.  (4, 8, 128) is the
    smallest k=2 slab config, kept small because CPU interpret-mode
    compiles dominate the wall clock."""
    m, lat = _setup_3d()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    g_x = make_unsteady_gradient(m, design, 4, levels=1, engine="xla")
    obj_x, gx, fin_x = g_x(theta0, lat.state, lat.params)
    g_p = make_unsteady_gradient(m, design, 4, levels=1,
                                 engine="pallas", shape=lat.shape,
                                 dtype=jnp.float32)
    # the fused backward, NOT the PR 9 hybrid: a silent degrade to the
    # XLA-chain backward would tag pallas_adjoint3d[...,bwd=xla]
    assert g_p.engine_name.startswith("pallas_adjoint[d3q19_adj")
    assert ",3d]" in g_p.engine_name and "k=2" in g_p.engine_name
    obj_p, gp, fin_p = g_p(theta0, lat.state, lat.params)
    gx, gp = np.asarray(gx), np.asarray(gp)
    assert float(obj_x) == pytest.approx(float(obj_p), rel=1e-5)
    assert np.abs(gx).max() > 0.0
    np.testing.assert_allclose(gp, gx, rtol=1e-4, atol=3e-7)
    np.testing.assert_allclose(np.asarray(fin_p.fields),
                               np.asarray(fin_x.fields),
                               rtol=1e-5, atol=1e-6)


def test_pallas_3d_hybrid_gradient_matches_xla(monkeypatch):
    """The PR 9 hybrid (Pallas forward / XLA-chain backward) stays
    available as the degrade target: with no feasible (k, bz) slab plan
    the auto path builds it, tags it honestly, and still matches the
    all-XLA adjoint."""
    monkeypatch.setattr(pallas_adjoint, "adjoint_slab_plan",
                        lambda *a, **k: None)
    m, lat = _setup_3d()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    g_x = make_unsteady_gradient(m, design, 4, levels=1, engine="xla")
    obj_x, gx, _ = g_x(theta0, lat.state, lat.params)
    g_p = make_unsteady_gradient(m, design, 4, levels=1,
                                 engine="pallas", shape=lat.shape,
                                 dtype=jnp.float32)
    assert g_p.engine_name.startswith("pallas_adjoint3d")
    assert "bwd=xla" in g_p.engine_name
    obj_p, gp, _ = g_p(theta0, lat.state, lat.params)
    gx, gp = np.asarray(gx), np.asarray(gp)
    assert float(obj_x) == pytest.approx(float(obj_p), rel=1e-5)
    assert np.abs(gx).max() > 0.0
    np.testing.assert_allclose(gp, gx, rtol=1e-4, atol=1e-6)


def test_pallas_3d_bwd_pallas_raises_when_infeasible(monkeypatch):
    """``bwd="pallas"`` is a hard request: when no slab config fits the
    VMEM budget it must raise, never silently hand back the hybrid."""
    monkeypatch.setattr(pallas_adjoint, "adjoint_slab_plan",
                        lambda *a, **k: None)
    m = get_model("d3q19_adj")
    with pytest.raises(ValueError, match="VMEM"):
        pallas_adjoint.make_diff_step(m, (4, 8, 128), jnp.float32,
                                      k=2, bwd="pallas")


def test_pallas_gradient_vs_fd():
    """FDTest on the Pallas engine (reference acFDTest,
    src/Handlers.cpp.Rt:1944): central differences at f32 tolerance."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 4
    grad_fn = make_unsteady_gradient(m, design, niter, levels=1,
                                     engine="pallas", shape=lat.shape)
    obj, g, _ = grad_fn(theta0, lat.state, lat.params)

    def loss(theta):
        o, _, _ = grad_fn(theta, lat.state, lat.params)
        return o

    # f32 primal: FD step and tolerance sized for single precision
    recs = fd_test(loss, g, theta0, n_checks=3, eps=3e-3)
    for r in recs:
        if abs(r["adjoint"]) < 1e-6 and abs(r["fd"]) < 1e-2:
            continue  # flat component: FD is pure f32 noise there
        assert r["rel_err"] < 5e-2, r


def test_pallas_gradient_with_checkpoint_levels():
    """The custom_vjp chunk composes with the nested remat scan (the
    SnapLevel analogue) — levels=1 and levels=2 agree."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    g1 = make_unsteady_gradient(m, design, 8, levels=1,
                                engine="pallas", shape=lat.shape)
    g2 = make_unsteady_gradient(m, design, 8, levels=2,
                                engine="pallas", shape=lat.shape)
    o1, gr1, _ = g1(theta0, lat.state, lat.params)
    o2, gr2, _ = g2(theta0, lat.state, lat.params)
    assert float(o1) == pytest.approx(float(o2), rel=1e-6)
    np.testing.assert_allclose(np.asarray(gr1), np.asarray(gr2),
                               rtol=1e-5, atol=1e-8)


def test_iteration_counter_threaded():
    """The in-kernel iteration counter follows state.iteration (advisor
    round-4 finding: it was hardwired to 0) — gradients from a shifted
    start match the XLA engine exactly."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    import dataclasses
    state7 = dataclasses.replace(lat.state,
                                 iteration=jnp.asarray(12, jnp.int32))
    g_x = make_unsteady_gradient(m, design, 4, levels=1, engine="xla")
    g_p = make_unsteady_gradient(m, design, 4, levels=1,
                                 engine="pallas", shape=lat.shape)
    obj_x, gx, fin_x = g_x(theta0, state7, lat.params)
    obj_p, gp, fin_p = g_p(theta0, state7, lat.params)
    assert int(fin_p.iteration) == int(fin_x.iteration) == 16
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gx),
                               rtol=1e-4, atol=1e-7)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="wall-clock assert needs real TPU kernels")
def test_pallas_adjoint_faster_than_xla():
    """Round-4 weak #8: the wall-clock regression guard.  The fused
    adjoint must beat the XLA adjoint by >= 2x on hardware — a silent
    fallback to the slow path fails here."""
    import time
    m, lat = _setup(ny=256, nx=512)
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 200

    def timed(engine):
        gf = make_unsteady_gradient(m, design, niter, levels=1,
                                    engine=engine, shape=lat.shape)
        obj, g, _ = gf(theta0, lat.state, lat.params)
        float(obj)
        t0 = time.perf_counter()
        obj, g, _ = gf(theta0, lat.state, lat.params)
        s = float(obj) + float(jnp.sum(g))
        assert np.isfinite(s)
        return time.perf_counter() - t0

    t_x = timed("xla")
    t_p = timed("pallas")
    assert t_p * 2.0 < t_x, (t_p, t_x)
