"""DDF-shifted storage representation (core/shift.py + its seams).

The shifted representation stores the deviation ``f_i - w_i`` at rest so
bf16's 8-bit mantissa goes to the signal instead of the O(1)
rest-equilibrium background.  These tests pin the contract edges:

* weight recognition derives the standard D2Q9/D3Q19/D3Q27 tables from
  ``Model.ei`` and refuses everything else (fields can never shift);
* representation resolution: shifted is the *default* narrow rung, the
  full-width f32 path stays raw (and bit-identical — the raw seams are
  pure ``astype``, no ``+ 0.0`` is ever traced);
* checkpoints stamp ``storage`` (dtype + repr) and restore *converts*
  across representations bit-faithfully rather than refusing, while an
  unknown repr stamp fails ``latest()``/restore with a structured error
  instead of silently falling back to a stale checkpoint;
* serving keys (ensemble ``engine_tag``, scheduler ``_bin_key``) split
  on the representation — a raw-bf16 and a shifted-bf16 plan compile
  different programs and must never share a cache entry or a batch.
"""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu import checkpoint as ckpt
from tclb_tpu.checkpoint import manifest as mf
from tclb_tpu.checkpoint import restore as rst
from tclb_tpu.checkpoint.manager import CheckpointManager
from tclb_tpu.core import shift as ddf
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model


def _cavity(model="d2q9", n=16, **kw):
    m = get_model(model)
    lat = Lattice(m, (n,) * m.ndim, dtype=jnp.float32,
                  settings={"nu": 0.05, "Velocity": 0.02}, **kw)
    flags = np.full((n,) * m.ndim, m.flag_for("MRT"), dtype=np.uint16)
    flags[0] = flags[-1] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    return lat


# --------------------------------------------------------------------------- #
# Weight recognition / shift derivation
# --------------------------------------------------------------------------- #


@pytest.mark.parametrize("name,q,w0", [
    ("d2q9", 9, 4.0 / 9.0),
    ("d3q19", 19, 1.0 / 3.0),
    ("d3q27", 27, 8.0 / 27.0),
])
def test_storage_shift_recognizes_standard_sets(name, q, w0):
    m = get_model(name)
    vec = ddf.storage_shift(m)
    assert vec.shape == (m.n_storage,)
    dens = vec[vec > 0]
    # every standard set: q weights summing to 1, rest plane = w0
    assert len(dens) % q == 0 and len(dens) >= q
    np.testing.assert_allclose(dens[:q].sum(), 1.0, rtol=1e-12)
    assert float(vec.max()) == pytest.approx(w0)
    # non-density planes (fields, averaged copies) never shift
    n_dens = len(m.densities)
    assert not np.any(vec[n_dens:])


def test_group_weights_rejects_nonstandard_groups():
    # all-zero offsets (how field groups appear in Model.ei): the ring
    # counts cannot match a velocity set
    assert ddf.group_weights(np.zeros((9, 3), dtype=np.int64)) is None
    # right member count, wrong rings
    assert ddf.group_weights(np.ones((9, 3), dtype=np.int64)) is None
    # non-unit offsets are never a standard set
    ei = np.zeros((9, 3), dtype=np.int64)
    ei[1, 0] = 2
    assert ddf.group_weights(ei) is None


def test_repr_resolution_defaults_and_refusals():
    m = get_model("d2q9")
    assert ddf.resolve_repr(m, False, None) == "raw"
    assert ddf.resolve_repr(m, True, None) == "shifted"
    assert ddf.resolve_repr(m, True, "raw") == "raw"
    with pytest.raises(ValueError, match="narrowed"):
        ddf.resolve_repr(m, False, "shifted")
    with pytest.raises(ValueError, match="must be one of"):
        ddf.resolve_repr(m, True, "hyperbolic")


def test_lattice_repr_resolution():
    assert _cavity().storage_repr == "raw"
    assert _cavity(storage_dtype=jnp.bfloat16).storage_repr == "shifted"
    assert _cavity(storage_dtype=jnp.bfloat16,
                   storage_repr="raw").storage_repr == "raw"
    with pytest.raises(ValueError, match="narrowed"):
        _cavity(storage_repr="shifted")


def test_raw_seams_are_pure_casts():
    """shift=None must never trace ``+ 0.0``: ``-0.0 + 0.0 == +0.0``
    would silently break the f32 path's bit-identity contract."""
    x = jnp.asarray([-0.0, 1.5], dtype=jnp.float32)
    y = ddf.widen_plane(x, jnp.float32, None)
    np.testing.assert_array_equal(
        np.asarray(y).view(np.uint32), np.asarray(x).view(np.uint32))
    z = ddf.narrow_plane(x, jnp.float32, None)
    np.testing.assert_array_equal(
        np.asarray(z).view(np.uint32), np.asarray(x).view(np.uint32))


def test_shifted_at_rest_layout_and_physics():
    """At rest the shifted lattice stores deviations (small numbers);
    both representations describe the same physics through the raw
    accessors."""
    raw = _cavity(storage_dtype=jnp.bfloat16, storage_repr="raw")
    sh = _cavity(storage_dtype=jnp.bfloat16, storage_repr="shifted")
    vec = ddf.storage_shift(raw.model)
    dens = vec > 0
    # raw at-rest planes carry the O(1) background, shifted ones don't
    raw_f = np.asarray(raw.state.fields, dtype=np.float64)
    sh_f = np.asarray(sh.state.fields, dtype=np.float64)
    assert np.max(np.abs(raw_f[dens])) > 0.1
    assert np.max(np.abs(sh_f[dens])) < 0.1
    # same physics once un-shifted
    np.testing.assert_allclose(raw.fields_raw(), sh.fields_raw(),
                               atol=1e-2)
    # quantities come out in raw physics units on both representations
    np.testing.assert_allclose(np.asarray(sh.get_quantity("Rho")),
                               np.asarray(raw.get_quantity("Rho")),
                               atol=2e-2)
    np.testing.assert_allclose(np.asarray(sh.get_quantity("Rho")),
                               1.0, atol=5e-2)


def test_shifted_iteration_tracks_raw_reference():
    """A short shifted-bf16 run stays close to the f32 reference — and
    much closer than raw-bf16 on the velocity field (the ladder's
    reason to flip the default)."""
    ref = _cavity(n=32)
    raw = _cavity(n=32, storage_dtype=jnp.bfloat16, storage_repr="raw")
    sh = _cavity(n=32, storage_dtype=jnp.bfloat16,
                 storage_repr="shifted")
    for lat in (ref, raw, sh):
        lat.iterate(40)
    u = np.asarray(ref.get_quantity("U"), dtype=np.float64)
    du_raw = np.max(np.abs(
        np.asarray(raw.get_quantity("U"), dtype=np.float64) - u))
    du_sh = np.max(np.abs(
        np.asarray(sh.get_quantity("U"), dtype=np.float64) - u))
    assert du_sh <= du_raw / 10


# --------------------------------------------------------------------------- #
# Checkpoint stamping + cross-representation restore
# --------------------------------------------------------------------------- #


def test_npy_safe_roundtrips_bfloat16():
    import ml_dtypes
    a = np.arange(-8, 8, dtype=np.float32).astype(ml_dtypes.bfloat16)
    packed = rst.npy_safe(a)
    assert packed.dtype == np.uint16
    back = rst.npy_restore(packed, "bfloat16")
    np.testing.assert_array_equal(back.view(np.uint16),
                                  a.view(np.uint16))
    # f32 arrays pass through untouched
    b = np.ones(3, dtype=np.float32)
    assert rst.npy_safe(b) is b
    assert rst.npy_restore(b, "float32") is b


def test_checkpoint_stamps_storage_and_restores_across_reprs(tmp_path):
    sh = _cavity(storage_dtype=jnp.bfloat16)
    sh.iterate(12)
    d1 = str(tmp_path / "shifted")
    ckpt.save_checkpoint(d1, sh)
    man = mf.read_manifest(d1)
    assert man["storage"] == {"dtype": "bfloat16", "repr": "shifted"}
    assert rst.storage_layout(man) == ("bfloat16", "shifted")

    # shifted-bf16 -> raw-f32 lattice: restore CONVERTS, not refuses
    wide = _cavity()
    ckpt.restore_lattice(wide, d1)
    assert int(np.asarray(wide.state.iteration)) == 12
    np.testing.assert_allclose(wide.fields_raw(), sh.fields_raw(),
                               atol=1e-6)

    # ... and back onto a shifted-bf16 lattice bit-faithfully: f64
    # conversion arithmetic preserves every representable deviation
    d2 = str(tmp_path / "wide")
    ckpt.save_checkpoint(d2, wide)
    assert mf.read_manifest(d2)["storage"] == {"dtype": "float32",
                                               "repr": "raw"}
    sh2 = _cavity(storage_dtype=jnp.bfloat16)
    ckpt.restore_lattice(sh2, d2)
    np.testing.assert_array_equal(
        np.asarray(sh2.state.fields).view(np.uint16),
        np.asarray(sh.state.fields).view(np.uint16))


def test_same_repr_restore_is_bit_exact_at_rest(tmp_path):
    sh = _cavity(storage_dtype=jnp.bfloat16)
    sh.iterate(8)
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, sh)
    sh2 = _cavity(storage_dtype=jnp.bfloat16)
    ckpt.restore_lattice(sh2, d)
    np.testing.assert_array_equal(
        np.asarray(sh2.state.fields).view(np.uint16),
        np.asarray(sh.state.fields).view(np.uint16))
    # restored lattices keep computing identically
    sh.iterate(8)
    sh2.iterate(8)
    np.testing.assert_array_equal(
        np.asarray(sh2.state.fields).view(np.uint16),
        np.asarray(sh.state.fields).view(np.uint16))


def test_legacy_npz_roundtrip_across_reprs(tmp_path):
    sh = _cavity(storage_dtype=jnp.bfloat16)
    sh.iterate(6)
    p = str(tmp_path / "state.npz")
    sh.save(p)
    same = _cavity(storage_dtype=jnp.bfloat16)
    same.load(p)
    np.testing.assert_array_equal(
        np.asarray(same.state.fields).view(np.uint16),
        np.asarray(sh.state.fields).view(np.uint16))
    wide = _cavity()
    wide.load(p)
    np.testing.assert_allclose(wide.fields_raw(), sh.fields_raw(),
                               atol=1e-6)


def test_unknown_repr_is_a_structured_error(tmp_path):
    lat = _cavity(storage_dtype=jnp.bfloat16)
    lat.iterate(4)
    mgr = CheckpointManager(str(tmp_path / "root"), keep_last=3,
                            async_saves=False)
    path = mgr.save(lat, step=4)
    man = mf.read_manifest(path)
    man["storage"]["repr"] = "hyperbolic"
    with open(os.path.join(path, "manifest.json"), "w") as fh:
        json.dump(man, fh)

    # the checkpoint is intact — latest() must NOT fall back past it
    with pytest.raises(mf.CheckpointError) as ei:
        mgr.latest()
    assert ei.value.kind == "storage_repr"
    with pytest.raises(mf.CheckpointError) as ei:
        ckpt.restore_lattice(_cavity(storage_dtype=jnp.bfloat16),
                             str(path))
    assert ei.value.kind == "storage_repr"


def test_pre_stamp_manifest_reads_as_raw():
    man = {"dtype": "float32"}
    assert rst.storage_layout(man) == ("float32", "raw")


# --------------------------------------------------------------------------- #
# Serving keys split on representation
# --------------------------------------------------------------------------- #


def test_engine_tag_and_bin_key_split_on_repr():
    from tclb_tpu.serve.ensemble import Case, EnsemblePlan
    from tclb_tpu.serve.scheduler import JobSpec, _bin_key
    m = get_model("d2q9")
    flags = np.full((16, 16), m.flag_for("MRT"), dtype=np.uint16)
    base = dict(flags=flags, base_settings={"nu": 0.05})
    f32 = EnsemblePlan(m, (16, 16), **base)
    raw = EnsemblePlan(m, (16, 16), storage_dtype=jnp.bfloat16,
                       storage_repr="raw", **base)
    sh = EnsemblePlan(m, (16, 16), storage_dtype=jnp.bfloat16, **base)
    assert sh.storage_repr == "shifted"
    tags = {p.engine_tag(4) for p in (f32, raw, sh)}
    assert len(tags) == 3
    assert "bfloat16/shifted" in sh.engine_tag(4)
    assert "/" not in f32.engine_tag(4).split("[")[1]

    def spec(**kw):
        return JobSpec(model=m, shape=(16, 16), case=Case(name="c"),
                       niter=5, **kw)
    k_f32 = _bin_key(spec())
    k_raw = _bin_key(spec(storage_dtype=jnp.bfloat16,
                          storage_repr="raw"))
    k_def = _bin_key(spec(storage_dtype=jnp.bfloat16))
    k_sh = _bin_key(spec(storage_dtype=jnp.bfloat16,
                         storage_repr="shifted"))
    assert k_def == k_sh            # None resolves to the default
    assert len({k_f32, k_raw, k_sh}) == 3


def test_gateway_validates_storage_repr():
    from tclb_tpu.gateway import jobs as gj
    body = {"model": "d2q9", "shape": [16, 16], "niter": 5}
    gj.validate_body(dict(body, storage_dtype="bf16",
                          storage_repr="shifted"))
    gj.validate_body(dict(body, storage_dtype="bf16",
                          storage_repr="raw"))
    with pytest.raises(gj.ValidationError, match="must be one of"):
        gj.validate_body(dict(body, storage_dtype="bf16",
                              storage_repr="hyperbolic"))
    with pytest.raises(gj.ValidationError, match="narrowed"):
        gj.validate_body(dict(body, storage_repr="shifted"))
    with pytest.raises(gj.ValidationError, match="narrowed"):
        gj.validate_body(dict(body, storage_dtype="f32",
                              storage_repr="shifted"))
