"""Telemetry contract tests: strict no-op when disabled, JSONL schema
when enabled, engine dispatch events on the real Lattice (including the
forced-fallback path), failcheck events, report aggregation, and the
--compare regression detector on synthetic traces.
"""

import json
import os
import subprocess
import sys
import xml.etree.ElementTree as ET

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu import telemetry
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.ops import pallas_d2q9
from tclb_tpu.telemetry import report
from tclb_tpu.telemetry.spans import NOOP_SPAN
from tclb_tpu.utils import log


@pytest.fixture(autouse=True)
def _sink_off():
    """Telemetry is process-global: every test starts and ends disabled."""
    telemetry.disable()
    yield
    telemetry.disable()


def _mrt_lattice(ny=8, nx=16):
    m = get_model("d2q9")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.05})
    lat.set_flags(np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16))
    lat.init()
    return m, lat


def _karman_lattice(ny=64, nx=128):
    m = get_model("d2q9")
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings={"nu": 0.05, "Velocity": 0.03})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    return m, lat


# --------------------------------------------------------------------------- #
# Disabled mode: strict no-op
# --------------------------------------------------------------------------- #


def test_disabled_is_strict_noop(monkeypatch):
    assert not telemetry.enabled()
    assert telemetry.path() is None
    telemetry.event("anything", x=1)          # must not raise or write
    telemetry.counter("c", 5)
    assert telemetry.counters() == {}

    # the disabled span is the shared no-op singleton: no clock, no jax
    sp = telemetry.span("iterate", iters=10)
    assert sp is NOOP_SPAN
    sentinel = object()

    def boom(_):
        raise AssertionError("disabled span must never touch jax")

    monkeypatch.setattr(jax, "block_until_ready", boom)
    with sp:
        sp.add(engine="xla")
        assert sp.sync(sentinel) is sentinel


def test_disabled_lattice_iterate_never_syncs(monkeypatch):
    _, lat = _mrt_lattice()

    real = jax.block_until_ready

    def boom(_):
        raise AssertionError("disabled iterate must not fence")

    monkeypatch.setattr(jax, "block_until_ready", boom)
    lat.iterate(2)                             # telemetry disabled
    monkeypatch.setattr(jax, "block_until_ready", real)
    assert int(lat.state.iteration) == 2


# --------------------------------------------------------------------------- #
# Enabled mode: JSONL schema
# --------------------------------------------------------------------------- #


def test_enabled_schema_golden(tmp_path):
    trace = tmp_path / "t.jsonl"
    telemetry.enable(str(trace))
    assert telemetry.enabled() and telemetry.path() == str(trace)
    telemetry.event("custom", n=np.int64(3), arr=np.arange(2))
    telemetry.counter("halo.exchanges", 4)
    telemetry.counter("halo.exchanges", 2)
    with telemetry.span("work", nodes=1000.0, iters=100) as sp:
        sp.add(engine="xla")
    telemetry.disable()
    assert not telemetry.enabled()

    lines = [json.loads(x) for x in trace.read_text().splitlines()]
    kinds = [e["kind"] for e in lines]
    assert kinds == ["trace_start", "custom", "span", "counters"]
    head = lines[0]
    assert head["schema"] == 1
    assert head["pid"] == os.getpid()
    assert isinstance(head["version"], str)
    assert all(isinstance(e["ts"], float) for e in lines)
    assert lines[1]["n"] == 3 and lines[1]["arr"] == [0, 1]  # numpy coerced
    span_evt = lines[2]
    assert span_evt["name"] == "work" and span_evt["engine"] == "xla"
    assert span_evt["dur_s"] >= 0 and "mlups" in span_evt
    assert lines[3]["counters"] == {"halo.exchanges": 6}


def test_load_skips_truncated_lines(tmp_path):
    trace = tmp_path / "t.jsonl"
    trace.write_text('{"kind": "a", "ts": 1.0}\n'
                     '{"kind": "b", "ts": 2.0'        # crash mid-write
                     '\n\n{"kind": "c", "ts": 3.0}\n')
    assert [e["kind"] for e in report.load(str(trace))] == ["a", "c"]


# --------------------------------------------------------------------------- #
# Lattice dispatch events
# --------------------------------------------------------------------------- #


def test_lattice_iterate_emits_engine_and_span(tmp_path, monkeypatch):
    monkeypatch.delenv("TCLB_FASTPATH", raising=False)
    trace = tmp_path / "t.jsonl"
    telemetry.enable(str(trace))
    m, lat = _mrt_lattice()
    lat.iterate(3)
    lat.iterate(2)
    telemetry.disable()

    evts = report.load(str(trace))
    sel = [e for e in evts if e["kind"] == "engine_selected"]
    assert len(sel) == 1                      # once per built engine
    assert sel[0]["engine"] == "xla"          # CPU + auto => XLA path
    assert sel[0]["model"] == "d2q9" and sel[0]["shape"] == [8, 16]

    it = [e for e in evts if e["kind"] == "span" and e["name"] == "iterate"]
    assert [e["iters"] for e in it] == [3, 2]
    assert [e["iteration"] for e in it] == [0, 3]
    for e in it:
        assert e["engine"] == "xla"
        assert e["nodes"] == 8 * 16
        assert e["mlups"] > 0
        # classical traffic model: 1R+1W of every storage field + flag
        assert e["bytes_per_node"] == 2 * m.n_storage * 4 + 2
        # CPU device kind is not in the HBM table: estimated roofline
        assert e["roofline_known"] is False
        assert e["vs_roofline"] >= 0


def test_forced_fallback_emits_events(tmp_path, monkeypatch):
    """Break the resident engine's probe: the dispatch must land on the
    band engine AND leave an engine_fallback breadcrumb with the cause."""
    monkeypatch.setenv("TCLB_FASTPATH", "force")

    def bad_resident(model, shape, dtype, present=None):
        def it(state, params, niter):
            raise RuntimeError("synthetic mosaic failure")
        return it

    monkeypatch.setattr(pallas_d2q9, "make_resident_iterate", bad_resident)

    trace = tmp_path / "t.jsonl"
    telemetry.enable(str(trace))
    _, lat = _karman_lattice()
    niter = 5
    lat.iterate(niter)
    telemetry.disable()

    assert lat._fast_name == "pallas_2d[d2q9,fuse=2]"
    assert int(lat.state.iteration) == niter

    evts = report.load(str(trace))
    sel = [e for e in evts if e["kind"] == "engine_selected"]
    assert sel and sel[0]["engine"] == "pallas_resident[d2q9,fuse=8]"
    assert sel[0]["probed"] is True
    fb = [e for e in evts if e["kind"] == "engine_fallback"]
    assert len(fb) == 1
    assert fb[0]["from"] == "pallas_resident[d2q9,fuse=8]"
    assert fb[0]["to"] == "pallas_2d[d2q9,fuse=2]"
    assert "synthetic mosaic failure" in fb[0]["cause"]
    # the iterate span records the engine that actually finished the chunk
    it = [e for e in evts if e["kind"] == "span" and e["name"] == "iterate"]
    assert it and it[-1]["engine"] == "pallas_2d[d2q9,fuse=2]"


# --------------------------------------------------------------------------- #
# Failcheck events
# --------------------------------------------------------------------------- #


def test_failcheck_event(tmp_path):
    from tclb_tpu.control.handlers import cbFailcheck
    from tclb_tpu.control.solver import ITERATION_STOP, Solver

    trace = tmp_path / "t.jsonl"
    telemetry.enable(str(trace))
    m = get_model("d2q9")
    s = Solver(m, output=str(tmp_path / "out") + "/")
    s.set_size((8, 16))
    s.lattice.set_flags(
        np.full((8, 16), m.flag_for("MRT"), dtype=np.uint16))
    s.lattice.init()
    f = np.asarray(s.lattice.state.fields).copy()
    f[0, 2, 3] = np.nan
    s.lattice.state = s.lattice.state.replace(fields=jnp.asarray(f))

    h = cbFailcheck(ET.Element("Failcheck"), s)
    h.init()
    assert h.do_it() == ITERATION_STOP
    telemetry.disable()

    fc = [e for e in report.load(str(trace)) if e["kind"] == "failcheck"]
    assert len(fc) == 1
    assert fc[0]["iteration"] == 0
    assert fc[0]["n_bad"] >= 1
    assert isinstance(fc[0]["quantity"], str) and fc[0]["quantity"]


# --------------------------------------------------------------------------- #
# Report aggregation + compare
# --------------------------------------------------------------------------- #

_ENG = "pallas_2d[d2q9,fuse=2]"


def _iterate_span(dur_s, nodes=8192.0, iters=100, engine=_ENG):
    return {"kind": "span", "ts": 1.0, "name": "iterate", "dur_s": dur_s,
            "iters": iters, "nodes": nodes, "engine": engine,
            "mlups": round(nodes * iters / dur_s / 1e6, 3),
            "vs_roofline": 0.5, "roofline_known": True}


def _write_trace(path, events):
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")
    return str(path)


def test_summarize_engine_table(tmp_path):
    evts = [{"kind": "trace_start", "ts": 0.0, "schema": 1},
            _iterate_span(0.01), _iterate_span(0.01),
            {"kind": "span", "ts": 1.0, "name": "output.vtk",
             "dur_s": 0.25},
            {"kind": "engine_selected", "ts": 0.5, "engine": _ENG,
             "model": "d2q9"},
            {"kind": "counters", "ts": 2.0,
             "counters": {"halo.exchanges": 12}}]
    s = report.summarize(report.load(_write_trace(tmp_path / "a.jsonl",
                                                  evts)))
    g = s["engines"][_ENG]
    assert g["chunks"] == 2 and g["iters"] == 200
    assert g["mlups"] == pytest.approx(8192 * 200 / 0.02 / 1e6, rel=1e-3)
    assert g["vs_roofline"] == pytest.approx(0.5)
    assert s["spans"]["output.vtk"]["count"] == 1
    assert s["counters"] == {"halo.exchanges": 12}
    txt = report.format_text(s)
    assert "per-engine iterate summary" in txt and _ENG in txt


def test_compare_detects_injected_slowdown(tmp_path):
    base = _write_trace(tmp_path / "base.jsonl",
                        [_iterate_span(0.010) for _ in range(3)])
    # candidate runs the same work 40% slower — far beyond the 5% gate
    other = _write_trace(tmp_path / "other.jsonl",
                         [_iterate_span(0.014) for _ in range(3)])
    diff = report.compare(report.summarize(report.load(base)),
                          report.summarize(report.load(other)))
    regs = [r for r in diff["regressions"] if r["what"] == "engine_mlups"]
    assert len(regs) == 1 and regs[0]["engine"] == _ENG
    assert regs[0]["delta_pct"] < -25

    # identical traces: clean bill
    diff2 = report.compare(report.summarize(report.load(base)),
                           report.summarize(report.load(base)))
    assert diff2["regressions"] == []


def test_compare_flags_new_fallbacks(tmp_path):
    base = _write_trace(tmp_path / "base.jsonl", [_iterate_span(0.01)])
    other = _write_trace(
        tmp_path / "other.jsonl",
        [{"kind": "engine_fallback", "ts": 0.1, "from": _ENG, "to": "xla",
          "cause": "RuntimeError('mosaic')"},
         _iterate_span(0.01, engine="xla")])
    diff = report.compare(report.summarize(report.load(base)),
                          report.summarize(report.load(other)))
    assert diff["fallback_drift"]["other"] == [[_ENG, "xla"]] \
        or diff["fallback_drift"]["other"] == [(_ENG, "xla")]
    assert any(r["what"] == "new_fallbacks" for r in diff["regressions"])


def test_report_cli(tmp_path, capsys):
    base = _write_trace(tmp_path / "base.jsonl",
                        [_iterate_span(0.010) for _ in range(3)])
    other = _write_trace(tmp_path / "other.jsonl",
                         [_iterate_span(0.020) for _ in range(3)])

    assert report.main(["report", base]) == 0
    assert _ENG in capsys.readouterr().out

    assert report.main(["report", base, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["engines"][_ENG]["chunks"] == 3

    assert report.main(["report", base, "--compare", other,
                        "--fail-on-regression"]) == 4
    out = capsys.readouterr().out
    assert "REGRESSIONS" in out

    assert report.main(["report", base, "--compare", other,
                        "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["compare"]["regressions"]

    assert report.main(["report", str(tmp_path / "missing.jsonl")]) == 2
    capsys.readouterr()


# --------------------------------------------------------------------------- #
# Env activation + log-level validation (satellite)
# --------------------------------------------------------------------------- #


def test_env_activation_and_bad_log_level(tmp_path):
    """TCLB_TELEMETRY turns the sink on at import; a bogus TCLB_LOG warns
    once (naming the value and the accepted levels) and falls back."""
    trace = tmp_path / "env.jsonl"
    env = dict(os.environ, TCLB_TELEMETRY=str(trace), TCLB_LOG="bogus",
               JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-c",
         "from tclb_tpu.utils import log\n"
         "from tclb_tpu import telemetry\n"
         "assert telemetry.enabled()\n"
         "telemetry.event('ping', x=1)\n"
         "telemetry.disable()\n"],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "TCLB_LOG" in r.stderr and "'bogus'" in r.stderr
    assert "debug" in r.stderr and "error" in r.stderr   # accepted levels
    kinds = [e["kind"] for e in report.load(str(trace))]
    assert kinds[0] == "trace_start" and "ping" in kinds


def test_set_level_rejects_unknown():
    old = log._threshold
    try:
        with pytest.raises(ValueError, match="bogus"):
            log.set_level("bogus")
        assert log._threshold == old          # unchanged on error
        log.set_level("warning")
        assert log._threshold == log.LEVELS["warning"]
    finally:
        log._threshold = old
