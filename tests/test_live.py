"""Live observability plane tests: metrics-registry semantics, the
Prometheus text exposition, scrape-during-solve safety, flight-recorder
ring bounds + auto-dump triggers, job-correlated timelines, and the
strict no-op contract when no sink is attached.

The conftest forces 8 host devices, so the dispatcher tests here run
against a real multi-lane fleet (with injected runners where the test
needs failure, mirroring tests/test_fleet.py).
"""

import json
import os
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from tclb_tpu import telemetry
from tclb_tpu.models import get_model
from tclb_tpu.serve import Case, EnsemblePlan, FleetDispatcher, JobSpec
from tclb_tpu.serve.scheduler import DONE, Scheduler
from tclb_tpu.telemetry import events, live, report
from tclb_tpu.telemetry.http import MonitorServer
from tclb_tpu.telemetry.live import FlightRecorder, MetricsRegistry


@pytest.fixture(autouse=True)
def _sink_off():
    telemetry.disable()
    live.registry().reset()
    yield
    telemetry.disable()
    live.registry().reset()


def _channel_flags(m, ny, nx):
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    return flags


def _d2q9_plan(ny=12, nx=24, **kw):
    m = get_model("d2q9")
    return EnsemblePlan(m, (ny, nx), flags=_channel_flags(m, ny, nx),
                        base_settings={"nu": 0.05, "Velocity": 0.02}, **kw)


def _specs(plan, nus, niter=6, **kw):
    return [JobSpec(model=plan.model, shape=plan.shape,
                    case=Case(settings={"nu": v}, name=f"nu={v}"),
                    niter=niter, flags=plan.flags,
                    base_settings={"nu": 0.05, "Velocity": 0.02},
                    name=f"nu={v}", **kw) for v in nus]


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), \
            r.read().decode("utf-8")


# --------------------------------------------------------------------------- #
# MetricsRegistry semantics
# --------------------------------------------------------------------------- #


def test_registry_gauge_counter_histogram():
    reg = MetricsRegistry()
    reg.gauge("g", 1.5, engine="xla")
    reg.gauge("g", 2.5, engine="xla")          # gauges overwrite
    reg.count("c", 1.0, lane="0")
    reg.count("c", 2.0, lane="0")              # counters accumulate
    reg.count("c", 5.0, lane="1")              # per-label series
    reg.observe("h", 0.003)
    reg.observe("h", 0.02)
    reg.observe("h", 999.0)                    # lands in +Inf
    snap = reg.snapshot()
    assert snap["gauges"]["g{engine=xla}"] == 2.5
    assert snap["counters"]["c{lane=0}"] == 3.0
    assert snap["counters"]["c{lane=1}"] == 5.0
    h = snap["histograms"]["h"]
    assert h["count"] == 3 and h["sum"] == pytest.approx(999.023)
    reg.set_info("last", {"engine": "xla"})
    assert reg.info("last") == {"engine": "xla"}
    assert reg.info("missing", 42) == 42
    reg.reset()
    empty = reg.snapshot()
    assert empty["gauges"] == {} and empty["counters"] == {} \
        and empty["histograms"] == {} and empty["info"] == {}


def test_registry_label_order_is_canonical():
    reg = MetricsRegistry()
    reg.count("c", 1.0, a="1", b="2")
    reg.count("c", 1.0, b="2", a="1")          # same series, any kw order
    assert reg.snapshot()["counters"]["c{a=1,b=2}"] == 2.0


def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    reg.gauge("tclb_mlups", 123.0, engine="xla", model="d2q9")
    reg.count("tclb_lane_batches_total", 4, lane="0")
    reg.observe("tclb_iterate_seconds", 0.003)
    reg.observe("tclb_iterate_seconds", 0.02)
    txt = reg.to_prometheus(extra_counters={"serve.jobs.submitted": 7})
    lines = txt.splitlines()
    assert "# HELP tclb_mlups MLUPS of the last iterate span, " \
        "by engine/model" in lines
    assert "# TYPE tclb_mlups gauge" in lines
    assert 'tclb_mlups{engine="xla",model="d2q9"} 123' in lines
    assert "# TYPE tclb_lane_batches_total counter" in lines
    assert 'tclb_lane_batches_total{lane="0"} 4' in lines
    # histogram buckets are cumulative and end with +Inf/_sum/_count
    assert 'tclb_iterate_seconds_bucket{le="0.005"} 1' in lines
    assert 'tclb_iterate_seconds_bucket{le="0.025"} 2' in lines
    assert 'tclb_iterate_seconds_bucket{le="+Inf"} 2' in lines
    assert "tclb_iterate_seconds_count 2" in lines
    assert any(l.startswith("tclb_iterate_seconds_sum ") for l in lines)
    # events.counter totals surface as tclb_counter_total{name=...}
    assert 'tclb_counter_total{name="serve.jobs.submitted"} 7' in lines
    assert txt.endswith("\n")
    assert live.CONTENT_TYPE.startswith("text/plain; version=0.0.4")


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.gauge("g", 1.0, path='a\\b"c\nd')
    txt = reg.to_prometheus()
    assert 'g{path="a\\\\b\\"c\\nd"} 1' in txt.splitlines()


def test_observe_derives_metrics_from_events():
    reg = live.registry()
    live._observe({"kind": "span", "name": "iterate", "dur_s": 0.25,
                   "engine": "fused", "model": "d2q9", "mlups": 88.0,
                   "vs_roofline": 0.8, "iters": 10, "nodes": 1000,
                   "iteration": 50, "ts": 123.0})
    live._observe({"kind": "span", "name": "serve.lane_batch", "lane": 2,
                   "batch": 3, "dur_s": 0.5, "stage_s": 0.1,
                   "stall_s": 0.01, "wait_s": [0.2, 0.3]})
    live._observe({"kind": "failcheck", "iteration": 5})
    live._observe({"kind": "serve.device_evicted", "lane": 2})
    live._observe({"kind": "serve.job_done", "status": "done"})
    snap = reg.snapshot()
    assert snap["gauges"]["tclb_mlups{engine=fused,model=d2q9}"] == 88.0
    assert snap["counters"]["tclb_iterations_total"] == 10
    assert snap["counters"]["tclb_node_updates_total"] == 10000
    assert snap["counters"]["tclb_lane_batches_total{lane=2}"] == 1
    assert snap["counters"]["tclb_lane_jobs_total{lane=2}"] == 3
    assert snap["counters"]["tclb_failchecks_total"] == 1
    assert snap["counters"]["tclb_devices_evicted_total{lane=2}"] == 1
    assert snap["counters"]["tclb_jobs_total{status=done}"] == 1
    assert snap["histograms"]["tclb_queue_wait_seconds"]["count"] == 2
    last = reg.info("last_iterate")
    assert last["engine"] == "fused" and last["mlups"] == 88.0


# --------------------------------------------------------------------------- #
# Strict no-op when disabled
# --------------------------------------------------------------------------- #


def test_monitor_disabled_is_strict_noop():
    assert not telemetry.enabled()
    telemetry.event("should_vanish", x=1)
    telemetry.counter("should_vanish")
    assert telemetry.counters() == {}
    assert telemetry.path() is None
    # a live subscriber flips the single-boolean gate; dropping it
    # restores the no-op path
    live.enable_live()
    assert telemetry.enabled()
    live.disable_live()
    assert not telemetry.enabled()


def test_scheduler_lifecycle_gates_telemetry():
    # the flight recorder attaches for the Scheduler's lifetime and
    # releases the gate on close
    assert not telemetry.enabled()
    sched = Scheduler(max_batch=2)
    assert telemetry.enabled()
    sched.close()
    assert not telemetry.enabled()


# --------------------------------------------------------------------------- #
# Flight recorder
# --------------------------------------------------------------------------- #


def test_flight_ring_is_bounded():
    fr = FlightRecorder(capacity=8)
    for i in range(20):
        fr.record({"kind": "tick", "i": i})
    assert len(fr) == 8
    assert [e["i"] for e in fr.events()] == list(range(12, 20))


def test_flight_dump_on_failcheck(tmp_path):
    fr = FlightRecorder(capacity=16, dump_dir=str(tmp_path))
    fr.record({"kind": "span", "name": "iterate", "dur_s": 0.1})
    fr.record({"kind": "failcheck", "iteration": 7, "quantity": "Rho",
               "job_id": 3, "engine": "fused"})
    dumps = fr.dumps
    assert len(dumps) == 1
    path = dumps[0]
    assert os.path.basename(path) == f"flight-{os.getpid()}.jsonl"
    with open(path) as fh:
        docs = [json.loads(line) for line in fh]
    assert docs[-1]["kind"] == "flight_dump"
    assert docs[-1]["reason"] == "failcheck"
    fc = [d for d in docs if d.get("kind") == "failcheck"]
    assert fc and fc[0]["job_id"] == 3 and fc[0]["engine"] == "fused"


def test_flight_explicit_dump_with_context(tmp_path):
    fr = FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    assert fr.dump(reason="nothing_recorded") is None   # empty ring: no file
    fr.record({"kind": "serve.job_queued", "job_id": 9})
    path = fr.dump(reason="scheduler_exception", error="boom", job_ids=[9])
    with open(path) as fh:
        docs = [json.loads(line) for line in fh]
    assert docs[-1] == pytest.approx(docs[-1])  # valid json round-trip
    assert docs[-1]["reason"] == "scheduler_exception"
    assert docs[-1]["error"] == "boom" and docs[-1]["job_ids"] == [9]


def test_flight_attach_is_refcounted_and_env_gated(monkeypatch):
    fr = FlightRecorder(capacity=4)
    fr.attach()
    fr.attach()
    assert fr.attached and telemetry.enabled()
    telemetry.event("ping")
    assert len(fr) == 1
    fr.detach()
    assert fr.attached                  # one ref left
    fr.detach()
    assert not fr.attached and not telemetry.enabled()
    monkeypatch.setenv("TCLB_FLIGHT", "0")
    off = FlightRecorder(capacity=4)
    off.attach()
    assert not off.attached             # opt-out honored


def test_flight_dump_on_device_eviction(tmp_path, monkeypatch):
    """A poisoned lane must leave a readable post-mortem: the eviction
    event lands in the ring and triggers flight-<pid>.jsonl even though
    no JSONL trace was ever enabled."""
    monkeypatch.setenv("TCLB_FLIGHT_DIR", str(tmp_path))

    def bad(lane, plan, cases, niter, staged):
        raise RuntimeError("poisoned device")

    def bad_seq(lane, plan, case, niter):
        raise RuntimeError("poisoned device")

    plan = _d2q9_plan()
    fleet = FleetDispatcher(devices=jax.devices()[:1], max_batch=2,
                            retries=0, evict_after=1, batch_runner=bad,
                            sequential_runner=bad_seq)
    jobs = fleet.run(_specs(plan, (0.02, 0.03), niter=2))
    fleet.close()
    assert all(j.status != DONE for j in jobs)
    path = tmp_path / f"flight-{os.getpid()}.jsonl"
    assert path.exists(), "eviction must dump the flight ring"
    with open(path) as fh:
        docs = [json.loads(line) for line in fh]
    kinds = [d.get("kind") for d in docs]
    assert "serve.device_evicted" in kinds
    assert kinds[-1] == "flight_dump"
    assert docs[-1]["reason"] == "serve.device_evicted"


# --------------------------------------------------------------------------- #
# HTTP monitor
# --------------------------------------------------------------------------- #


def test_monitor_endpoints():
    with MonitorServer(port=0) as mon:
        st, ctype, body = _get(mon.url + "/")
        assert st == 200 and "/metrics" in body
        st, ctype, body = _get(mon.url + "/metrics")
        assert st == 200 and ctype == live.CONTENT_TYPE
        st, ctype, body = _get(mon.url + "/status")
        assert st == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["pid"] == os.getpid()
        assert "flight_recorder" in doc and "counters" in doc
        with pytest.raises(urllib.request.HTTPError) as ei:
            _get(mon.url + "/nope")
        assert ei.value.code == 404
    # stopped: the port no longer answers
    with pytest.raises(OSError):
        _get(mon.url + "/status")


def test_monitor_scrape_during_solve():
    """Scrapes racing a real solve must all succeed, and the metrics
    they return must reflect the solve's iterate spans; the handler
    thread never blocks on device work (hygiene check covers the
    static side, this covers the dynamic one)."""
    plan = _d2q9_plan()
    results: list = []
    stop = threading.Event()

    def scraper(url):
        while not stop.is_set():
            st1, ctype, body = _get(url + "/metrics")
            st2, _t, _b = _get(url + "/status")
            results.append((st1, st2, body))
            time.sleep(0.005)

    with MonitorServer(port=0) as mon:
        t = threading.Thread(target=scraper, args=(mon.url,), daemon=True)
        t.start()
        try:
            with Scheduler(max_batch=2) as sched:
                jobs = sched.run(_specs(plan, (0.03, 0.05, 0.07), niter=4))
        finally:
            stop.set()
            t.join(timeout=10)
    assert all(j.status == DONE for j in jobs)
    assert results and all(s1 == 200 and s2 == 200
                           for s1, s2, _ in results)
    # the last scrape saw the solve's event traffic
    assert "tclb_events_total" in results[-1][2]


def test_status_occupancy_matches_stats():
    """/status lane occupancy must track the dispatcher's own busy
    accounting (the acceptance bound is 5% vs the post-hoc table; here
    both views read the same busy_s, so they agree exactly)."""
    plan = _d2q9_plan()
    with FleetDispatcher(max_batch=2, monitor="127.0.0.1:0") as fleet:
        jobs = fleet.run(_specs(plan, (0.03, 0.05, 0.07, 0.09), niter=4))
        st, _t, body = _get(fleet.monitor_url + "/status")
        doc = json.loads(body)
    assert all(j.status == DONE for j in jobs)
    fstat = doc["fleet"]
    assert len(fstat["lanes"]) == len(fleet.lanes)
    assert fstat["jobs_submitted"] == 4
    served = {l["lane"]: l for l in fstat["lanes"]}
    for lane in fleet.lanes:
        if lane.busy_s > 0:
            got = served[lane.index]
            assert got["jobs"] == lane.jobs_served
            assert got["busy_s"] <= lane.busy_s + 1e-6
    assert sum(l["jobs"] for l in fstat["lanes"]) == 4


def test_capture_profile_is_single_flight(tmp_path):
    assert live._profile_lock.acquire(blocking=False)
    try:
        with pytest.raises(RuntimeError, match="already running"):
            live.capture_profile(0.1, outdir=str(tmp_path))
    finally:
        live._profile_lock.release()


def test_parse_monitor_spec():
    assert live.parse_monitor_spec("8080") == ("127.0.0.1", 8080)
    assert live.parse_monitor_spec(":9100") == ("127.0.0.1", 9100)
    assert live.parse_monitor_spec("0.0.0.0:9100") == ("0.0.0.0", 9100)
    for bad in ("", "host:", "host:port", "1:2:3:x", "99999"):
        with pytest.raises(ValueError):
            live.parse_monitor_spec(bad)


# --------------------------------------------------------------------------- #
# events: counters snapshots + array truncation
# --------------------------------------------------------------------------- #


def test_counters_periodic_snapshot(tmp_path, monkeypatch):
    monkeypatch.setattr(events, "COUNTER_SNAPSHOT_S", 0.0)
    trace = str(tmp_path / "t.jsonl")
    telemetry.enable(trace)
    telemetry.counter("work.done")
    telemetry.event("tick")            # piggybacks a cumulative snapshot
    telemetry.counter("work.done")
    telemetry.event("tick")
    telemetry.disable()
    with open(trace) as fh:
        evts = [json.loads(line) for line in fh]
    snaps = [e for e in evts if e.get("kind") == "counters"]
    periodic = [e for e in snaps if not e.get("final")]
    finals = [e for e in snaps if e.get("final")]
    assert periodic and periodic[0]["counters"]["work.done"] == 1
    assert len(finals) == 1 and finals[0]["counters"]["work.done"] == 2
    # cumulative snapshots aggregate to the final total, not the sum
    assert report.summarize(evts)["counters"]["work.done"] == 2


def test_json_default_truncates_large_arrays(tmp_path):
    class Chatty:                   # non-serializable, huge repr
        def __str__(self):
            return "x" * 2000

    trace = str(tmp_path / "t.jsonl")
    telemetry.enable(trace)
    telemetry.event("blob",
                    big=np.zeros((128, 64), dtype=np.float32),
                    small=np.arange(3),
                    obj=Chatty())
    telemetry.disable()
    with open(trace) as fh:
        evts = [json.loads(line) for line in fh]
    blob = next(e for e in evts if e.get("kind") == "blob")
    assert blob["big"] == "<array shape=(128, 64) dtype=float32>"
    assert blob["small"] == [0, 1, 2]       # small arrays stay inline
    assert blob["obj"].endswith("chars)") and len(blob["obj"]) < 600


def test_failcheck_stamps_job_context(tmp_path):
    trace = str(tmp_path / "t.jsonl")
    telemetry.enable(trace)
    with telemetry.job_context(42):
        telemetry.failcheck(iteration=9, quantity="Rho", n_bad=3,
                            engine="fused")
    telemetry.failcheck(iteration=10, quantity="Rho", n_bad=1,
                        engine="xla")
    telemetry.disable()
    with open(trace) as fh:
        evts = [json.loads(line) for line in fh]
    fcs = [e for e in evts if e.get("kind") == "failcheck"]
    assert fcs[0]["job_id"] == 42 and fcs[0]["engine"] == "fused"
    assert "job_id" not in fcs[1]


# --------------------------------------------------------------------------- #
# Job-correlated timeline (report --job)
# --------------------------------------------------------------------------- #


def test_job_timeline_over_fleet_trace(tmp_path, capsys):
    trace = str(tmp_path / "fleet.jsonl")
    telemetry.enable(trace)
    plan = _d2q9_plan()
    with FleetDispatcher(max_batch=2) as fleet:
        jobs = fleet.run(_specs(plan, (0.03, 0.05), niter=3))
    telemetry.disable()
    assert all(j.status == DONE for j in jobs)

    jid = jobs[0].id
    rc = report.main(["report", trace, "--job", str(jid)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "queued" in out and "done" in out
    assert "dispatched" in out or "staged" in out

    rc = report.main(["report", trace, "--job", "999999"])
    capsys.readouterr()
    assert rc == 3                       # no events for that job


def test_job_timeline_includes_degrades(tmp_path, capsys):
    """A job that fails its batch and degrades to sequential must show
    the degrade and the retry count in its timeline."""
    trace = str(tmp_path / "deg.jsonl")
    telemetry.enable(trace)
    calls = {"n": 0}

    def flaky_batch(lane, plan, cases, niter, staged):
        raise RuntimeError("batch always fails")

    def seq_ok(lane, plan, case, niter):
        calls["n"] += 1
        return "ok"

    plan = _d2q9_plan()
    fleet = FleetDispatcher(devices=jax.devices()[:2], max_batch=2,
                            retries=0, evict_after=100,
                            batch_runner=flaky_batch,
                            sequential_runner=seq_ok)
    jobs = fleet.run(_specs(plan, (0.03,), niter=2))
    fleet.close()
    telemetry.disable()
    assert jobs[0].status == DONE and calls["n"] == 1

    rc = report.main(["report", trace, "--job", str(jobs[0].id)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "degraded" in out and "done" in out


# --------------------------------------------------------------------------- #
# Drain hooks: shutdown work chained ahead of SIGTERM death
# --------------------------------------------------------------------------- #


def test_drain_hooks_run_and_claim_sigterm(tmp_path, monkeypatch):
    """A registered drain hook runs on SIGTERM before the flight dump;
    a truthy return claims the shutdown so _on_sigterm returns (clean
    exit path) instead of re-raising the signal."""
    monkeypatch.setenv("TCLB_FLIGHT_DIR", str(tmp_path))
    ran = []
    live.register_drain_hook("svc", lambda reason: ran.append(reason)
                             or True)
    try:
        # call the handler directly: with the hook claiming, it must
        # NOT fall through to the re-raise (which would kill pytest)
        live._on_sigterm(15, None)
    finally:
        live.unregister_drain_hook("svc")
    assert ran == ["sigterm"]


def test_drain_hooks_unclaimed_and_errors_contained(tmp_path,
                                                    monkeypatch):
    """run_drain_hooks returns False when no hook claims; a raising hook
    is contained (the shutdown path must not crash) and later hooks
    still run, in registration order."""
    monkeypatch.setenv("TCLB_FLIGHT_DIR", str(tmp_path))
    order = []

    def boom(reason):
        order.append("boom")
        raise RuntimeError("drain hook exploded")

    live.register_drain_hook("a", boom)
    live.register_drain_hook("b", lambda r: order.append("b"))  # falsy
    try:
        assert live.run_drain_hooks("test") is False
        assert order == ["boom", "b"]
        live.register_drain_hook("c", lambda r: True)
        assert live.run_drain_hooks("test") is True
    finally:
        live.unregister_drain_hook("a")
        live.unregister_drain_hook("b")
        live.unregister_drain_hook("c")


def test_drain_hook_unregister_is_exact():
    """unregister(name, fn) only evicts that exact fn — a closing
    component cannot evict its replacement — and last registration per
    name wins."""
    first, second = (lambda r: "one"), (lambda r: "two")
    live.register_drain_hook("gw", first)
    live.register_drain_hook("gw", second)        # replaces first
    live.unregister_drain_hook("gw", first)       # stale: no-op
    try:
        assert live.run_drain_hooks("x") is True  # second still wired
    finally:
        live.unregister_drain_hook("gw", second)
    assert live.run_drain_hooks("x") is False


def test_metrics_registry_concurrent_observe_and_scrape():
    """Satellite stress for the registry lock discipline: 8 writer
    threads x 10k events racing /metrics scrape threads.  Counters are
    lock-guarded read-modify-write — any unguarded window would lose
    increments; any iteration-during-mutation bug would raise in the
    scrapers.  Asserts the exact total and zero exceptions."""
    reg = live.registry()
    n_threads, n_events = 8, 10_000
    errors = []
    done = threading.Event()

    def writer(idx):
        try:
            for i in range(n_events):
                live._observe({"kind": "stress", "idx": idx, "i": i})
        except Exception as e:  # noqa: BLE001 — surfaced in the assert
            errors.append(e)

    def scraper():
        try:
            while not done.is_set():
                text = reg.to_prometheus()
                assert "tclb_events_total" in text or text == "" or True
                reg.snapshot()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    writers = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    scrapers = [threading.Thread(target=scraper) for _ in range(2)]
    for t in scrapers + writers:
        t.start()
    for t in writers:
        t.join(timeout=120.0)
    done.set()
    for t in scrapers:
        t.join(timeout=30.0)
    assert not errors, errors
    assert all(not t.is_alive() for t in writers + scrapers)
    snap = reg.snapshot()
    assert snap["counters"]["tclb_events_total{kind=stress}"] == \
        n_threads * n_events
