"""Phase-field family physics tests.

The reference validates d2q9_pf_curvature by fitting the curvature of a
circular drop against 1/R (src/d2q9_pf_curvature/check.py); we run the same
check directly, plus conservation/advection properties that the conservative
phase-field scheme guarantees by construction.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.models.d2q9 import E
from tclb_tpu.ops import lbm

pytestmark = pytest.mark.slow  # full-coverage job; the default lap runs the fast smoke suite

W9 = lbm.weights(E)


def _set_h(lat, pf, u=(0.0, 0.0)):
    """Write the phase-field population stack h_i = w_i pf (1 + 3 e.u + ...)."""
    dt = np.float64
    eq = np.asarray(lbm.equilibrium(
        E, W9, jnp.asarray(pf, dt),
        (jnp.full(pf.shape, u[0], dt), jnp.full(pf.shape, u[1], dt))))
    for i in range(9):
        lat.set_density(f"h[{i}]", eq[i])


def test_pf_mass_conservation_and_advection():
    """A phase-field blob in uniform flow: total phase field is conserved
    to round-off and its centroid advects at the flow velocity."""
    m = get_model("d2q9_pf")
    ny, nx = 48, 48
    u0 = 0.05
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"nu": 0.1, "M": 0.05, "W": 0.5,
                            "Velocity": u0, "PhaseField": -0.5})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    y, x = np.mgrid[0:ny, 0:nx]
    r = np.hypot(x - nx / 2, y - ny / 2)
    pf = -np.tanh(2.0 * (r - 8.0) * 0.5) / 2.0   # +0.5 inside the drop
    _set_h(lat, pf, (u0, 0.0))

    total0 = float(np.asarray(lat.get_quantity("PhaseField")).sum())
    # centroid of the positive marker (pf + 0.5 in [0, 1])
    w = pf + 0.5
    cx0 = float((x * w).sum() / w.sum())
    T = 100
    lat.iterate(T)
    pf1 = np.asarray(lat.get_quantity("PhaseField"))
    assert np.isfinite(pf1).all()
    total1 = float(pf1.sum())
    np.testing.assert_allclose(total1, total0, rtol=1e-12)
    w1 = pf1 + 0.5
    # periodic centroid via phase angle to tolerate wrap
    ang = (x - cx0) * (2 * np.pi / nx)
    shift = np.angle(np.sum(w1 * np.exp(1j * ang))) * nx / (2 * np.pi)
    np.testing.assert_allclose(shift, u0 * T, rtol=0.15)


def test_pf_curvature_matches_drop_radius():
    """Curvature quantity at the interface of a circular drop ~ 1/R — the
    reference's check.py validation for d2q9_pf_curvature."""
    m = get_model("d2q9_pf_curvature")
    ny = nx = 64
    R, w = 16.0, 0.25
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"nu": 0.1, "omega_l": 1.0, "M": 0.05,
                            "W": w, "PhaseField": -0.5,
                            "SurfaceTensionRate": 0.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    y, x = np.mgrid[0:ny, 0:nx]
    r = np.hypot(x - nx / 2, y - ny / 2)
    pf = -np.tanh(2.0 * (r - R) * w) / 2.0
    _set_h(lat, pf)
    lat.set_density("phi", pf)

    curv = np.asarray(lat.get_quantity("Curvature"))
    band = np.abs(pf) < 0.3          # interface band
    measured = curv[band]
    np.testing.assert_allclose(measured.mean(), 1.0 / R, rtol=0.1)

    # and the model runs stably with surface tension on
    lat.set_setting("SurfaceTensionRate", 0.1)
    lat.iterate(50)
    assert np.isfinite(np.asarray(lat.state.fields)).all()


def test_pf_curvature_wall_sentinel_stencil():
    """Walls write the -999 phi sentinel; the repaired stencil keeps
    curvature finite next to them."""
    m = get_model("d2q9_pf_curvature")
    ny, nx = 16, 32
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"nu": 0.1, "omega_l": 1.0, "M": 0.05, "W": 0.5,
                            "PhaseField": -0.5, "SurfaceTensionRate": 0.05})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    phi = np.asarray(lat.get_density("phi"))
    assert (phi[0, :] == -999.0).all()
    lat.iterate(30)
    assert np.isfinite(np.asarray(lat.state.fields[:18])).all()
    assert np.isfinite(np.asarray(lat.get_quantity("Curvature"))).all()


def test_pf_pressure_evolution_drop():
    """Static drop under pressure-evolution form: phase field conserved,
    TotalDensity global reported, state stays finite and bounded."""
    m = get_model("d2q9_pf_pressureEvolution")
    ny = nx = 48
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"Density_h": 1.0, "Density_l": 0.1,
                            "nu_l": 0.1, "nu_h": 0.1, "sigma": 1e-3,
                            "W": 4.0, "M": 0.05, "PhaseField": 0.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    y, x = np.mgrid[0:ny, 0:nx]
    r = np.hypot(x - nx / 2, y - ny / 2)
    pf = 0.5 + 0.5 * np.tanh(2.0 * (12.0 - r) / 4.0)   # 1 inside, 0 outside
    lat.set_density("PhaseF", pf)
    eq = np.asarray(lbm.equilibrium(E, W9, jnp.asarray(pf),
                                    (jnp.zeros_like(jnp.asarray(pf)),) * 2))
    for i in range(9):
        lat.set_density(f"h[{i}]", eq[i])

    total0 = float(np.asarray(lat.get_density("PhaseF")).sum())
    lat.iterate(50)
    pf1 = np.asarray(lat.get_quantity("PhaseField"))
    assert np.isfinite(np.asarray(lat.state.fields)).all()
    np.testing.assert_allclose(pf1.sum(), total0, rtol=1e-12)
    assert pf1.min() > -0.2 and pf1.max() < 1.2
    g = lat.get_globals()
    # TotalDensity ~ sum of interpolated density over collision nodes
    rho = np.asarray(lat.get_quantity("Rho"))
    np.testing.assert_allclose(g["TotalDensity"], rho.sum(), rtol=1e-10)


def test_pf_walls_and_zouhe_channel():
    """d2q9_pf channel with Zou/He velocity inlet + pressure outlet around
    a phase blob: stays finite, walls bounce both lattices."""
    m = get_model("d2q9_pf")
    ny, nx = 24, 64
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"nu": 0.1, "M": 0.05, "W": 0.5,
                            "Velocity": 0.02, "PhaseField": -0.5})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    y, x = np.mgrid[0:ny, 0:nx]
    pf = -np.tanh(2.0 * (np.hypot(x - 20, y - ny / 2) - 5.0) * 0.5) / 2.0
    _set_h(lat, pf, (0.02, 0.0))
    lat.iterate(200)
    assert np.isfinite(np.asarray(lat.state.fields)).all()
    u = np.asarray(lat.get_quantity("U"))
    assert u[0][1:-1, 1:-1].mean() > 0.0
