"""Physics validation of the electrokinetics models: d2q9_poison_boltzmann
against the Debye–Hückel solution, d2q9_npe_guo against the
electro-osmotic-flow structure the reference validates with
src/d2q9_npe_guo/python/test_eof.py.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model

pytestmark = pytest.mark.slow  # full-coverage job; the default lap runs the fast smoke suite


def test_pb_debye_huckel():
    """Channel between two zeta-potential walls: for small zeta the
    Poisson-Boltzmann equation linearizes to psi'' = kappa^2 psi with
    kappa^2 = 2 n_inf z^2 el^2/(eps kb T); solution
    psi = zeta cosh(kappa (y - c))/cosh(kappa h/2)."""
    m = get_model("d2q9_poison_boltzmann")
    ny, nx = 34, 16
    zeta = 0.01
    n_inf, eps = 0.01, 1.0
    kappa = np.sqrt(2 * n_inf / eps)
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"tau_psi": 1.0, "n_inf": n_inf,
                            "epsilon": eps, "psi_bc": zeta, "psi0": 0.0})
    flags = np.full((ny, nx), m.flag_for("BGK"), dtype=np.uint16)
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(6000)   # fixed-point sweeps to convergence

    psi = np.asarray(lat.get_quantity("Psi"))[:, nx // 2]
    assert np.isfinite(psi).all()
    y = np.arange(ny, dtype=float)
    # wet-node Dirichlet: walls are rows 0 and ny-1
    c = (ny - 1) / 2.0
    ref = zeta * np.cosh(kappa * (y - c)) / np.cosh(kappa * c)
    err = np.abs(psi[1:-1] - ref[1:-1]).max() / zeta
    assert err < 0.03, err
    # subiter counted the sweeps
    assert float(np.asarray(lat.get_quantity("Subiter")).max()) >= 6000


def test_npe_guo_equilibrium_double_layer():
    """No external field: the ion densities must relax to the Boltzmann
    distribution n_k = n_inf exp(-+ z el_kbT psi) against the self-
    consistent psi, and the fluid must stay at rest."""
    m = get_model("d2q9_npe_guo")
    ny, nx = 34, 16
    zeta = 0.05
    n_inf = 0.01
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"n_inf_0": n_inf, "n_inf_1": n_inf,
                            "psi_bc": zeta, "psi0": 0.0, "phi0": 0.0,
                            "phi_bc": 0.0, "el_kbT": 1.0, "epsilon": 1.0,
                            "nu": 1 / 6, "D": 1 / 6})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(8000)

    psi = np.asarray(lat.get_quantity("Psi"))[:, nx // 2]
    n0 = np.asarray(lat.get_quantity("n0"))[:, nx // 2]
    n1 = np.asarray(lat.get_quantity("n1"))[:, nx // 2]
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(psi).all() and np.isfinite(n0).all()
    # Boltzmann-distributed ions against the computed psi (interior)
    sl = slice(2, -2)
    np.testing.assert_allclose(n0[sl], n_inf * np.exp(-psi[sl]),
                               rtol=0.02)
    np.testing.assert_allclose(n1[sl], n_inf * np.exp(+psi[sl]),
                               rtol=0.02)
    # counter-ion excess near the positive wall: n1 > n0 at the wall
    assert n1[1] > n0[1]
    # fluid at rest (no external field)
    assert np.abs(u[:2]).max() < 1e-8


def test_npe_guo_eof_profile():
    """Electro-osmotic flow: an external-potential gradient along x (via
    phi_bc zones at W/E pressure boundaries) over charged walls drives a
    plug-like flow whose profile follows the Smoluchowski structure
    u(y) ~ (psi(y) - zeta): maximal at the centre, zero at the walls —
    the validation target of the reference's python/test_eof.py."""
    m = get_model("d2q9_npe_guo")
    ny, nx = 30, 64
    zeta = 0.05
    n_inf = 0.01
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"n_inf_0": n_inf, "n_inf_1": n_inf,
                            "psi_bc": zeta, "psi0": 0.0, "phi0": 0.0,
                            "phi_bc": 0.0, "el_kbT": 1.0, "epsilon": 1.0,
                            "nu": 1 / 6, "D": 1 / 6, "rho_bc": 1.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    flags[1:-1, 0] = m.flag_for("WPressure", "MRT", zone=1)
    flags[1:-1, -1] = m.flag_for("EPressure", "MRT")
    lat.set_flags(flags)
    lat.set_setting("phi_bc", 0.5, zone=1)   # potential drop along x
    lat.init()
    lat.iterate(8000)

    u = np.asarray(lat.get_quantity("U"))
    ux = u[0][:, nx // 2]
    psi = np.asarray(lat.get_quantity("Psi"))[:, nx // 2]
    assert np.isfinite(ux).all()
    # flow exists and is plug-shaped: centre fast, near-wall slow
    assert abs(ux[ny // 2]) > 5 * abs(ux[1] - ux[ny // 2] * (
        (psi[1] - zeta) / (psi[ny // 2] - zeta)))
    # profile follows (psi - zeta) shape: normalized u matches normalized
    # (psi - zeta) within a few percent in the interior
    shape_u = ux / ux[ny // 2]
    shape_p = (psi - zeta) / (psi[ny // 2] - zeta)
    np.testing.assert_allclose(shape_u[3:-3], shape_p[3:-3], atol=0.08)
