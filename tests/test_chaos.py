"""Seeded chaos suite: the failure-domain contracts of the serving
stack under injected faults.

Every test installs a deterministic :class:`~tclb_tpu.faults.FaultPlan`
(the same schedules the CI chaos job drives via ``TCLB_FAULTS``) and
asserts the blast-radius invariants:

* transient lane faults are absorbed by the retry ladder — zero hung or
  lost jobs, and surviving results bit-identical to a clean run;
* ENOSPC during a checkpoint save fails only the *save* (emergency
  prune, structured :class:`CheckpointSaveError`), never the process —
  through the gateway, the job lands failed-but-resumable;
* journal IO faults degrade the job store (in-memory state stays
  authoritative) instead of failing requests;
* an injected gateway-request fault 500s that one request; the gateway
  serves the next one;
* an evicted lane is probed after its fault clears, reinstated, and
  serves a subsequent batch;
* retries never outlive the submitted deadline (asserted from
  ``serve.batch.retry`` event timestamps);
* every crash-mode injection leaves a flight-recorder dump.
"""

import hashlib
import json
import os
import time

import jax
import numpy as np
import pytest

from tclb_tpu import faults, telemetry
from tclb_tpu.faults import FaultPlan, InjectedFault
from tclb_tpu.checkpoint.manager import CheckpointManager, CheckpointSaveError
from tclb_tpu.gateway import jobs as J
from tclb_tpu.gateway.service import GatewayService
from tclb_tpu.gateway.store import JobStore
from tclb_tpu.gateway.jobs import JobRecord
from tclb_tpu.models import get_model
from tclb_tpu.serve import Case, EnsemblePlan, FleetDispatcher, JobSpec
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.serve.scheduler import DONE, FAILED, Scheduler
from tclb_tpu.telemetry import live


@pytest.fixture(autouse=True)
def _clean():
    faults.uninstall()
    telemetry.disable()
    yield
    faults.uninstall()
    telemetry.disable()


def _channel_plan(ny=12, nx=24, **kw):
    m = get_model("d2q9")
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    return EnsemblePlan(m, (ny, nx), flags=flags,
                        base_settings={"nu": 0.05, "Velocity": 0.02}, **kw)


def _specs(plan, nus, niter=4, **kw):
    return [JobSpec(model=plan.model, shape=plan.shape,
                    case=Case(settings={"nu": v}, name=f"nu={v}"),
                    niter=niter, flags=plan.flags,
                    base_settings={"nu": 0.05, "Velocity": 0.02},
                    name=f"nu={v}", **kw) for v in nus]


def _digest(result):
    arr = np.ascontiguousarray(np.asarray(result.state.fields))
    return hashlib.sha256(arr.tobytes()).hexdigest()


# --------------------------------------------------------------------------- #
# Transient faults absorbed: zero lost jobs, bit-identical survivors
# --------------------------------------------------------------------------- #


def test_fleet_absorbs_transient_faults_bit_identical():
    """A bounded burst of injected dispatch faults is absorbed by the
    retry ladder: every job completes DONE and its state digest matches
    a fault-free run of the same specs."""
    plan = _channel_plan()
    nus = (0.02, 0.05, 0.08)
    with FleetDispatcher(devices=jax.devices()[:1]) as fleet:
        clean = {j.spec.name: _digest(j.result())
                 for j in fleet.run(_specs(plan, nus))}

    faults.install(FaultPlan.parse("seed=5;serve.lane_dispatch:error:n=2"))
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.01, max_delay_s=0.02)
    with FleetDispatcher(devices=jax.devices()[:1],
                         retry_policy=policy) as fleet:
        jobs = fleet.run(_specs(plan, nus))
    assert [j.status for j in jobs] == [DONE] * len(nus)
    assert not any(j.degraded for j in jobs)  # retries, not the seq path
    assert {j.spec.name: _digest(j.result()) for j in jobs} == clean
    st = faults.stats()
    assert st["injected"][0]["count"] == 2


# the CI chaos job drives these same schedules via TCLB_FAULTS over the
# fleet bench; here they run in-process over injected runners (fast) and
# pin the zero-hung/zero-lost invariant for each
CHAOS_SCHEDULES = [
    "seed=11;serve.lane_dispatch:error:p=0.4:n=6",
    "seed=23;serve.stage:slow:delay=0.01;serve.lane_dispatch:error:n=2",
    "seed=37;serve.lane_dispatch:error:n=3;serve.stage:slow:delay=0.005",
]


@pytest.mark.parametrize("schedule", CHAOS_SCHEDULES)
def test_chaos_schedule_no_hung_or_lost_jobs(schedule):
    """Under each seeded schedule every submitted job reaches a terminal
    state within its deadline — nothing hangs, nothing is lost."""
    def batch_runner(lane, plan, cases, niter, staged):
        faults.fire("serve.lane_dispatch", lane=lane.index,
                    batch=len(cases))
        return ["ok"] * len(cases)

    def seq_runner(lane, plan, case, niter):
        faults.fire("serve.lane_dispatch", lane=lane.index, seq=True)
        return "ok"

    faults.install(FaultPlan.parse(schedule))
    plan = _channel_plan()
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.005,
                         max_delay_s=0.01)
    specs = _specs(plan, (0.01, 0.02, 0.03, 0.04, 0.05, 0.06),
                   timeout_s=60.0)
    with FleetDispatcher(devices=jax.devices()[:2],
                         batch_runner=batch_runner,
                         sequential_runner=seq_runner,
                         retry_policy=policy) as fleet:
        jobs = [fleet.submit(s) for s in specs]
        for j in jobs:
            try:
                j.result(timeout=60)
            except Exception:  # noqa: BLE001 — verdict read off the handle
                pass
    assert all(j.status in (DONE, FAILED) for j in jobs)
    assert len(jobs) == len(specs)
    done = [j for j in jobs if j.status == DONE]
    assert all(j.result() == "ok" for j in done)


# --------------------------------------------------------------------------- #
# Checkpoint ENOSPC: fail the save, prune, keep the process
# --------------------------------------------------------------------------- #


def _lattice(shape=(8, 16)):
    from tclb_tpu.core.lattice import Lattice
    lat = Lattice(get_model("d2q9"), shape,
                  settings={"nu": 0.05, "Velocity": 0.02})
    lat.init()
    return lat


def test_checkpoint_enospc_prunes_and_fails_only_the_save(tmp_path):
    evts = []
    telemetry.subscribe(evts.append)
    try:
        lat = _lattice()
        mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=3,
                                async_saves=False)
        mgr.save(lat, step=1)
        mgr.save(lat, step=2)
        faults.install(FaultPlan.parse("checkpoint.write:enospc:n=1"))
        with pytest.raises(CheckpointSaveError) as ei:
            mgr.save(lat, step=3)
        assert ei.value.kind == "enospc" and ei.value.step == 3
        # emergency prune kept ONLY the newest committed step; no torn
        # temp directory survives; latest() still restores
        assert [s for s, _ in mgr.steps()] == [2]
        assert not any(n.endswith(".tmp") for n in os.listdir(mgr.root))
        assert mgr.latest() is not None
        kinds = [e.get("kind") for e in evts]
        assert "checkpoint.enospc" in kinds
        enospc = next(e for e in evts if e.get("kind") == "checkpoint.enospc")
        assert len(enospc["pruned"]) == 1
        # the manager still works once space is back
        faults.uninstall()
        mgr.save(lat, step=4)
        assert [s for s, _ in mgr.steps()] == [2, 4]
    finally:
        telemetry.unsubscribe(evts.append)


def test_gateway_enospc_fails_job_resumable_process_survives(tmp_path):
    """An ENOSPC mid-save through the gateway's resumable runner fails
    that one job with ``error_kind="checkpoint_enospc"`` — the gateway
    process survives and serves the next submission."""
    faults.install(FaultPlan.parse("checkpoint.write:enospc:n=1"))
    svc = GatewayService(str(tmp_path / "store"))
    svc.start()
    try:
        code, doc = svc.submit({"model": "d2q9", "shape": [8, 16],
                                "niter": 4, "resumable": True,
                                "checkpoint_every": 2})
        assert code == 202
        jid = doc["job"]["id"]
        code, doc = svc.result(jid, wait=120)
        assert code == 200
        assert doc["job"]["status"] == J.FAILED
        assert doc["job"]["error_kind"] == "checkpoint_enospc"
        assert "no space" in doc["job"]["error"]
        # the process (and its worker) lives: the next job runs clean
        faults.uninstall()
        code, doc = svc.submit({"model": "d2q9", "shape": [8, 16],
                                "niter": 4})
        assert code == 202
        code, doc = svc.result(doc["job"]["id"], wait=120)
        assert code == 200 and doc["job"]["status"] == J.DONE
    finally:
        svc.close()


# --------------------------------------------------------------------------- #
# Job store: journal faults degrade, never fail the request path
# --------------------------------------------------------------------------- #


def test_store_journal_fault_degrades_not_raises(tmp_path):
    evts = []
    telemetry.subscribe(evts.append)
    try:
        st = JobStore(str(tmp_path / "store"))
        faults.install(FaultPlan.parse("store.journal:error:n=1"))
        rec = JobRecord(id=st.new_id(), tenant="t")
        st.put(rec)  # journal write fails -> degraded, no raise
        assert st.degraded
        assert st.get(rec.id) is rec  # in-memory stays authoritative
        assert any(e.get("kind") == "gateway.store_degraded" for e in evts)
        # a successful snapshot restores durability and clears the flag
        st.snapshot()
        assert not st.degraded
        st.close()
        st2 = JobStore(str(tmp_path / "store"))
        assert st2.get(rec.id) is not None
        st2.close()
    finally:
        telemetry.unsubscribe(evts.append)


def test_store_torn_journal_write_loses_only_the_last_line(tmp_path):
    root = str(tmp_path / "store")
    st = JobStore(root)
    rec = JobRecord(id=st.new_id(), tenant="t", status=J.QUEUED)
    st.put(rec)
    # the kill-mid-write model: the FINAL journal line is torn
    faults.install(FaultPlan.parse("store.journal:torn:n=1"))
    rec.status = J.RUNNING
    st.put(rec)
    faults.uninstall()
    st._journal.flush()
    st2 = JobStore(root)  # replay skips the torn line
    assert st2.get(rec.id).status == J.QUEUED
    st2.close()


def test_gateway_request_fault_500s_one_request_not_the_gateway(tmp_path):
    faults.install(FaultPlan.parse("gateway.request:error:n=1"))
    svc = GatewayService(str(tmp_path / "store"))
    body = {"model": "d2q9", "shape": [8, 16], "niter": 2}
    code, doc = svc.submit(body)
    assert code == 500 and doc["error"] == "internal error"
    code, doc = svc.submit(body)  # budget spent: the next request lands
    assert code == 202
    svc.store.close()


# --------------------------------------------------------------------------- #
# Lane probation: fault clears -> probe -> reinstate -> serve
# --------------------------------------------------------------------------- #


def test_evicted_lane_reinstated_after_fault_clears_and_serves():
    """A lane evicted by an injected fault burst is probed once the
    fault budget is spent, reinstated, and serves a subsequent batch."""
    def batch_runner(lane, plan, cases, niter, staged):
        faults.fire("serve.lane_dispatch", lane=lane.index)
        return ["ok"] * len(cases)

    def seq_runner(lane, plan, case, niter):
        faults.fire("serve.lane_dispatch", lane=lane.index, seq=True)
        return "ok"

    evts = []
    telemetry.subscribe(evts.append)
    # exactly two injections: the first job's batched attempt + its
    # sequential degrade — enough to evict with evict_after=1, after
    # which the fault has "cleared"
    faults.install(FaultPlan.parse("serve.lane_dispatch:error:n=2"))
    plan = _channel_plan()
    fleet = FleetDispatcher(devices=jax.devices()[:1], retries=0,
                            evict_after=1, batch_runner=batch_runner,
                            sequential_runner=seq_runner,
                            probe_interval_s=0.05)
    try:
        first = fleet.submit(_specs(plan, (0.02,))[0])
        with pytest.raises(InjectedFault):
            first.result(timeout=60)
        assert first.status == FAILED
        deadline = time.monotonic() + 30
        while not fleet.lanes[0].evicted and time.monotonic() < deadline:
            time.sleep(0.01)
        assert fleet.lanes[0].evicted
        # submitted while evicted: with probation on, the job WAITS for
        # a reinstatement instead of failing fast
        second = fleet.submit(_specs(plan, (0.03,))[0])
        assert second.result(timeout=60) == "ok"
        assert second.status == DONE
        assert not fleet.lanes[0].evicted
        kinds = [e.get("kind") for e in evts]
        assert "serve.device_evicted" in kinds
        assert "serve.device_reinstated" in kinds
    finally:
        fleet.close()
        telemetry.unsubscribe(evts.append)


# --------------------------------------------------------------------------- #
# Retries never outlive the caller's deadline
# --------------------------------------------------------------------------- #


def test_retries_respect_submitted_deadline():
    """With a permanently-failing runner and a generous retry budget,
    every emitted ``serve.batch.retry`` sleep fits inside the job's
    remaining deadline, and the job resolves well before the budget's
    worst-case sleep total."""
    def runner(plan, cases, niter):
        raise RuntimeError("injected: permanently down")

    def seq(plan, case, niter):
        raise RuntimeError("injected: permanently down")

    evts = []
    telemetry.subscribe(evts.append)
    policy = RetryPolicy(max_attempts=50, base_delay_s=0.05,
                         max_delay_s=0.2, jitter=0.0)
    plan = _channel_plan()
    t0 = time.monotonic()
    try:
        with Scheduler(batch_runner=runner, sequential_runner=seq,
                       retry_policy=policy, autostart=False) as sched:
            jobs = sched.run(_specs(plan, (0.02,), timeout_s=0.5))
    finally:
        telemetry.unsubscribe(evts.append)
    elapsed = time.monotonic() - t0
    assert jobs[0].status == FAILED
    retries = [e for e in evts if e.get("kind") == "serve.batch.retry"]
    assert retries, "expected at least one in-deadline retry"
    for e in retries:
        # the policy's contract: a retry is scheduled only when its
        # sleep lands strictly inside the remaining deadline
        assert e["delay_s"] <= e["deadline_in_s"], e
    # the 50-attempt budget was cut short by the deadline, not slept out
    assert len(retries) < policy.max_attempts - 1
    assert elapsed < 5.0


# --------------------------------------------------------------------------- #
# Forensics: every crash-mode injection leaves a flight dump
# --------------------------------------------------------------------------- #


def test_crash_mode_injection_dumps_flight_recorder(tmp_path, monkeypatch):
    monkeypatch.setenv("TCLB_FLIGHT_DIR", str(tmp_path))
    rec = live.flight_recorder()
    rec.attach()
    try:
        faults.install(FaultPlan.parse(
            "serve.stage:error:n=1;serve.stage:slow:delay=0.001"))
        with pytest.raises(InjectedFault):
            faults.fire("serve.stage", lane=0)
        faults.fire("serve.stage", lane=0)  # slow: latency, not a crash
    finally:
        rec.detach()
    dumps = [n for n in os.listdir(tmp_path) if n.startswith("flight-")]
    assert len(dumps) == 1
    lines = [json.loads(s) for s in
             (tmp_path / dumps[0]).read_text().splitlines()]
    assert any(d.get("kind") == "fault.injected" for d in lines)
    assert lines[-1]["kind"] == "flight_dump"
    assert lines[-1]["reason"] == "fault.injected:serve.stage"


# --------------------------------------------------------------------------- #
# Worker-pool schedules: the four pool.* injection points
# --------------------------------------------------------------------------- #

_STUB = """
import json, os, struct, sys, time
H = struct.Struct("!II")
out = os.fdopen(os.dup(1), "wb"); os.dup2(2, 1)
inp = os.fdopen(os.dup(0), "rb")
lane = int(sys.argv[sys.argv.index("--lane") + 1])
def send(doc):
    body = json.dumps(doc).encode()
    out.write(H.pack(len(body), 0)); out.write(body); out.flush()
def recv():
    h = inp.read(H.size)
    if len(h) < H.size: raise EOFError
    bl, pl = H.unpack(h)
    doc = json.loads(inp.read(bl).decode()); inp.read(pl)
    return doc
send({"t": "ready", "pid": os.getpid(), "lane": lane})
while True:
    try: doc = recv()
    except EOFError: sys.exit(0)
    if doc.get("t") == "shutdown": sys.exit(0)
    if doc.get("t") != "job": continue
    jid = doc["id"]
    send({"t": "hb", "id": jid})
    send({"t": "result", "id": jid, "ok": True, "lane": lane,
          "globals": {"n": (doc.get("spec") or {}).get("n")}})
"""


def test_pool_points_registered_and_spec_roundtrips():
    """All five pool.* injection points are in the authoritative
    registry (a typo cannot silently disable a schedule) and a combined
    schedule round-trips through to_spec — the serialization that
    carries a plan into worker subprocesses."""
    for point in ("pool.spawn", "pool.heartbeat", "pool.ipc",
                  "pool.worker_exit", "pool.telemetry_relay"):
        assert point in faults.POINTS
    plan = FaultPlan.parse(
        "seed=42;pool.spawn:error:n=1;pool.heartbeat:error:n=1:after=3;"
        "pool.ipc:error:n=1:after=1;pool.worker_exit:error:n=1:after=2;"
        "pool.telemetry_relay:torn:n=2")
    assert FaultPlan.parse(plan.to_spec()) == plan
    with pytest.raises(ValueError, match="unknown injection point"):
        FaultPlan.parse("pool.nonsense:error")


def test_pool_supervisor_schedule_no_lost_jobs(tmp_path):
    """Seeded supervisor-side schedule (pool.spawn + pool.ipc errors)
    against a two-lane pool under a small backlog: every job completes
    (retried spawn, re-queued send — zero lost), both lanes end live,
    and every crash-mode injection left a flight dump trigger."""
    from tclb_tpu.serve.pool import WorkerPool
    script = tmp_path / "stub.py"
    script.write_text(_STUB)
    import sys as _sys
    faults.install(FaultPlan.parse(
        "seed=13;pool.spawn:error:n=1;pool.ipc:error:n=1:after=2"))
    pool = WorkerPool(workers=2, worker_cmd=[_sys.executable,
                                             str(script)],
                      heartbeat_timeout_s=5.0, term_grace_s=0.5,
                      retry_policy=RetryPolicy(max_attempts=4,
                                               base_delay_s=0.02,
                                               max_delay_s=0.1),
                      autostart=False)
    try:
        jobs = pool.run([{"n": i} for i in range(8)], timeout=120)
        assert [j.status for j in jobs] == ["done"] * 8
        assert sorted(j._result["globals"]["n"] for j in jobs) \
            == list(range(8))
        st = faults.stats()
        assert sum(r["count"] for r in st["injected"]) == 2
        assert pool.stats()["requeued"] == 1      # the ipc casualty
        deadline = time.time() + 30
        while pool.live_workers() < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert pool.live_workers() == 2
    finally:
        pool.close()
        faults.uninstall()


@pytest.mark.slow
def test_pool_worker_exit_schedule_blast_radius(tmp_path):
    """Seeded worker-side schedule (pool.worker_exit crash at a
    checkpointed segment boundary) with two REAL solver lanes and a
    mixed backlog: the crashed resumable job resumes bit-identical,
    sibling non-resumable jobs are untouched, and nothing is lost.  The
    plan crosses into the workers via TCLB_FAULTS re-serialization."""
    from tclb_tpu.serve.pool import WorkerPool
    base = {"model": "d2q9", "shape": [8, 16], "niter": 30,
            "params": {"nu": 0.05}, "digest": True,
            "case": {"name": "c", "settings": {}}}
    with WorkerPool(workers=1, autostart=False) as pool:
        ref = pool.submit(dict(base, ckpt_root=str(tmp_path / "ref"),
                               checkpoint_every=10))
        ref_sha = ref.result(timeout=600)["state_sha256"]

    # worker_exit hits per incarnation: job-start, then one per saved
    # segment.  after=2 -> lane 0's first job crashes at step 20 (post
    # save); the respawn fires only 2 hits and completes from 20.
    # Sibling lane 1 serves plain jobs whose specs also fire job-start
    # hits in THEIR OWN process (counters are per-incarnation).
    faults.install(FaultPlan.parse(
        "seed=404;pool.worker_exit:error:n=1:after=2"))
    pool = WorkerPool(workers=2, job_attempts=3,
                      retry_policy=RetryPolicy(max_attempts=4,
                                               base_delay_s=0.05,
                                               max_delay_s=0.2),
                      autostart=False)
    try:
        resumable = pool.submit(dict(base,
                                     ckpt_root=str(tmp_path / "x"),
                                     checkpoint_every=10))
        plain = [pool.submit(dict(base, niter=10,
                                  case={"name": f"s{i}",
                                        "settings": {}}))
                 for i in range(3)]
        res = resumable.result(timeout=600)
        for p in plain:
            assert p.result(timeout=600)["iteration"] == 10
        assert res["resumed_from"] == 20
        assert res["state_sha256"] == ref_sha
        assert pool.stats()["restarts"] >= 1
    finally:
        pool.close()
        faults.uninstall()


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["error", "slow", "torn"])
def test_pool_telemetry_relay_schedule_never_blocks_jobs(tmp_path, mode):
    """Seeded pool.telemetry_relay schedules (the wedged-relay chaos
    contract): injected relay faults in REAL workers drop telemetry
    batches (observable via pool.relay_dropped) but never block a
    heartbeat, lose a job, or perturb the solve — results stay
    bit-identical to a clean run and the watchdog never fires a
    missed-heartbeat false positive."""
    from tclb_tpu.serve.pool import WorkerPool
    base = {"model": "d2q9", "shape": [8, 16], "niter": 30,
            "params": {"nu": 0.05}, "digest": True,
            "case": {"name": "r", "settings": {}}}
    with WorkerPool(workers=1, autostart=False, relay=False) as pool:
        ref_sha = pool.submit(dict(base)).result(
            timeout=600)["state_sha256"]

    evts = []
    telemetry.subscribe(evts.append)
    clause = {"error": "pool.telemetry_relay:error:p=0.6:n=4",
              "slow": "pool.telemetry_relay:slow:delay=0.05",
              "torn": "pool.telemetry_relay:torn:p=0.6:n=4"}[mode]
    faults.install(FaultPlan.parse(f"seed=88;{clause}"))
    pool = WorkerPool(workers=1, autostart=False)
    try:
        jobs = pool.run([dict(base), dict(base)], timeout=600)
        assert [j.status for j in jobs] == ["done", "done"]
        for j in jobs:
            assert j._result["state_sha256"] == ref_sha
        # relay loss never masquerades as a hang: the watchdog stayed
        # quiet and nothing was requeued or restarted
        assert not [e for e in evts
                    if e.get("kind") == "serve.worker_hung"]
        st = pool.stats()
        assert st["requeued"] == 0 and st["restarts"] == 0
        from tclb_tpu.telemetry import events as tevents
        ctrs = tevents.counters()
        if mode in ("error", "torn"):
            # the dropped batches were counted, and later frames still
            # made it across (the relay recovers between injections)
            assert ctrs.get("pool.relay_dropped", 0) >= 1
        assert ctrs.get("pool.relay_events", 0) >= 1
    finally:
        pool.close()
        faults.uninstall()
        telemetry.unsubscribe(evts.append)


# --------------------------------------------------------------------------- #
# Adjoint D2D spill: failed peer parks degrade to the disk tier
# --------------------------------------------------------------------------- #


def _spill_fleet():
    # two non-default host devices (conftest forces 8): the peer park
    # is a genuine cross-device device_put
    return FleetDispatcher(devices=jax.devices()[1:3])


def test_adjoint_d2d_point_registered_and_spec_roundtrips():
    assert "adjoint.spill_d2d" in faults.POINTS
    plan = FaultPlan.parse(
        "seed=3;adjoint.spill_d2d:error:n=1;checkpoint.write:torn:n=1")
    assert FaultPlan.parse(plan.to_spec()) == plan


def test_adjoint_d2d_error_degrades_store_to_disk(tmp_path):
    """An injected D2D park failure tears down the peer tier: the
    snapshot (and everything after it) lands on disk bit-exact, the
    lane lease is returned, and the degrade is observable."""
    from tclb_tpu.adjoint.revolve import SnapshotStore
    evts = []
    telemetry.subscribe(evts.append)
    faults.install(FaultPlan.parse("seed=3;adjoint.spill_d2d:error:n=1"))
    try:
        with _spill_fleet() as d:
            store = SnapshotStore(mem_slots=0, peer_slots=2,
                                  spill_dir=str(tmp_path), dispatcher=d)
            try:
                vals = [(np.full((16, 16), float(k)), np.int32(k))
                        for k in range(2)]
                for k, v in enumerate(vals):
                    store.put(k, v)
                store.wait()
                assert [store.tier_of(k) for k in range(2)] \
                    == ["disk", "disk"]
                for k, v in enumerate(vals):
                    got = store.get(k)
                    for a, b in zip(got, v):
                        np.testing.assert_array_equal(np.asarray(a), b)
                # no lane left reserved after the failed park
                assert all(l.reserved is None for l in d.lanes)
            finally:
                store.close()
        assert faults.stats()["injected"][0]["count"] == 1
        assert any(e.get("kind") == "adjoint.spill_peer_down"
                   for e in evts)
    finally:
        telemetry.unsubscribe(evts.append)


def test_adjoint_d2d_slow_schedule_latency_only(tmp_path):
    """A slow-mode D2D schedule adds latency, never failure: the parks
    still land on the peer tier and the lease survives."""
    from tclb_tpu.adjoint.revolve import SnapshotStore
    faults.install(FaultPlan.parse(
        "seed=9;adjoint.spill_d2d:slow:delay=0.01:n=2"))
    with _spill_fleet() as d:
        store = SnapshotStore(mem_slots=0, peer_slots=2,
                              spill_dir=str(tmp_path), dispatcher=d)
        try:
            vals = [(np.full((16, 16), float(k)), np.int32(k))
                    for k in range(2)]
            for k, v in enumerate(vals):
                store.put(k, v)
            assert [store.tier_of(k) for k in range(2)] \
                == ["peer", "peer"]
            assert store.evacuations == 0
            assert store._lease is not None \
                and not store._lease.released
            for k, v in enumerate(vals):
                got = store.get(k)
                for a, b in zip(got, v):
                    np.testing.assert_array_equal(np.asarray(a), b)
        finally:
            store.close()
        assert all(l.reserved is None for l in d.lanes)
    assert faults.stats()["injected"][0]["count"] == 2


@pytest.mark.slow
def test_adjoint_d2d_fault_gradient_bit_identical(tmp_path):
    """The blast-radius contract for the peer spill tier: a seeded D2D
    failure mid-sweep degrades the spill to disk, the gradient stays
    bit-identical to the clean peer-tier run, and no lane is left
    reserved."""
    import jax.numpy as jnp
    from tclb_tpu.adjoint import InternalTopology
    from tclb_tpu.adjoint.revolve import make_revolve_gradient
    from tclb_tpu.core.lattice import Lattice

    m = get_model("d2q9_adj")
    lat = Lattice(m, (8, 16), dtype=jnp.float64,
                  settings={"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                            "DragInObj": 1.0, "MaterialInObj": 0.0})
    flags = np.full((8, 16), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    flags[2:6, 5:10] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)

    with _spill_fleet() as d:
        rev = make_revolve_gradient(m, design, 12, snapshots=4,
                                    engine="xla", shape=(8, 16),
                                    dtype=jnp.float64, mem_slots=1,
                                    peer_slots=3,
                                    spill_dir=str(tmp_path / "spill"),
                                    dispatcher=d)
        o_clean, g_clean, _ = rev(theta0, lat.state, lat.params)
        assert rev.last["spill_peer"] > 0

        faults.install(FaultPlan.parse(
            "seed=3;adjoint.spill_d2d:error:n=1"))
        o_fault, g_fault, _ = rev(theta0, lat.state, lat.params)
        assert rev.last["spill_peer"] == 0
        assert rev.last["spill_disk"] > 0
        assert "disk" in rev.last["tiers"]
        assert all(l.reserved is None for l in d.lanes)

    assert float(o_fault) == float(o_clean)
    np.testing.assert_array_equal(np.asarray(g_fault),
                                  np.asarray(g_clean))
    assert faults.stats()["injected"][0]["count"] == 1


# --------------------------------------------------------------------------- #
# Cluster schedules: the three cluster.* injection points
# --------------------------------------------------------------------------- #


def test_cluster_points_registered_and_spec_roundtrips():
    """The three cluster.* injection points are in the authoritative
    registry and a combined schedule round-trips through to_spec — the
    serialization TCLB_FAULTS carries into agent subprocesses."""
    for point in ("cluster.enroll", "cluster.channel",
                  "cluster.host_exit"):
        assert point in faults.POINTS
    plan = FaultPlan.parse(
        "seed=42;cluster.enroll:error:n=1;"
        "cluster.channel:torn:n=1:after=2;"
        "cluster.host_exit:error:n=1:after=3;cluster.channel:slow:delay=0.01")
    assert FaultPlan.parse(plan.to_spec()) == plan


def _cluster_stub_cmd(tmp_path):
    from test_cluster import STUB_WORKER
    tmp_path.mkdir(parents=True, exist_ok=True)
    script = tmp_path / "stub.py"
    script.write_text(STUB_WORKER)
    import sys as _sys
    return [_sys.executable, str(script)]


def _cluster_pair(tmp_path, **server_kw):
    """One in-process ClusterServer + two stub-pool agents."""
    from test_cluster import _agent, _wait
    from tclb_tpu.cluster.server import ClusterServer
    stub = _cluster_stub_cmd(tmp_path)
    srv = ClusterServer(**server_kw)
    srv.start()
    agents = [_agent(srv, h, stub).start() for h in ("hA", "hB")]
    _wait(lambda: srv.live_hosts() == 2, what="two enrollments")
    return srv, agents


def _run_cluster_burst(srv, n=6):
    jobs = [srv.submit({"n": i, "niter": 2}) for i in range(n)]
    return {i: j.result(timeout=120)["state_sha256"]
            for i, j in enumerate(jobs)}, jobs


CLUSTER_SCHEDULES = [
    # a torn control frame mid-dispatch: the host is marked lost, its
    # job requeues on the survivor
    "seed=19;cluster.channel:torn:n=1",
    # a hard channel error on a result receive: same requeue contract
    "seed=31;cluster.channel:error:n=1:after=4",
    # slow control-plane ops must add latency only, never lose a job
    "seed=47;cluster.channel:slow:delay=0.02:p=0.5:n=6",
]


@pytest.mark.parametrize("schedule", CLUSTER_SCHEDULES)
def test_cluster_channel_schedule_zero_lost_bit_identical(
        tmp_path, monkeypatch, schedule):
    """Seeded cluster.channel schedules against a 2-host pod: every
    job completes (zero lost) and every digest matches the clean run —
    a control channel tearing mid-frame moves work, never corrupts
    it."""
    monkeypatch.setenv("TCLB_FLIGHT_DIR", str(tmp_path / "flight"))
    srv, agents = _cluster_pair(tmp_path / "clean")
    try:
        clean, _ = _run_cluster_burst(srv)
    finally:
        for a in agents:
            a.stop()
        srv.close(wait=False)

    faults.install(FaultPlan.parse(schedule))
    srv, agents = _cluster_pair(tmp_path / "chaos", job_attempts=3)
    try:
        got, jobs = _run_cluster_burst(srv)
        assert got == clean                     # bit-identical digests
        assert all(j.status == "done" for j in jobs)  # zero lost
        st = srv.stats()
        assert st["done"] == 6 and st["failed"] == 0
        if "torn" in schedule or "error" in schedule:
            assert st["requeued"] >= 1
            assert st["hosts_live"] >= 1        # the survivor kept serving
        assert sum(r["count"] for r in faults.stats()["injected"]) >= 1
    finally:
        for a in agents:
            a.stop()
        srv.close(wait=False)
        faults.uninstall()


def test_cluster_enroll_fault_refused_then_rejoins(tmp_path, monkeypatch):
    """An injected cluster.enroll error refuses the first enrollment
    (gateway.host_rejected); the agent's reconnect loop re-enrolls once
    the budget is spent and the pod serves normally."""
    from test_cluster import STUB_WORKER, _agent, _wait
    from tclb_tpu.cluster.server import ClusterServer
    monkeypatch.setenv("TCLB_FLIGHT_DIR", str(tmp_path / "flight"))
    script = tmp_path / "stub.py"
    script.write_text(STUB_WORKER)
    import sys as _sys
    evts = []
    telemetry.subscribe(evts.append)
    faults.install(FaultPlan.parse("seed=3;cluster.enroll:error:n=1"))
    srv = ClusterServer()
    agent = None
    try:
        srv.start()
        agent = _agent(srv, "hA", [_sys.executable, str(script)]).start()
        _wait(lambda: srv.live_hosts() == 1, what="post-refusal enroll")
        res = srv.submit({"n": 5}).result(timeout=60)
        assert res["host"] == "hA"
        assert any(e.get("kind") == "gateway.host_rejected"
                   for e in evts)
        assert faults.stats()["injected"][0]["count"] == 1
    finally:
        if agent is not None:
            agent.stop()
        srv.close(wait=False)
        faults.uninstall()
        telemetry.unsubscribe(evts.append)


@pytest.mark.slow
def test_pool_heartbeat_schedule_hang_detected(tmp_path):
    """Seeded worker-side schedule (pool.heartbeat wedge): the beat
    stops mid-solve, the supervisor watchdog kills the worker within the
    heartbeat timeout, and the re-queued job resumes from the checkpoint
    that landed before the wedge — bit-identical."""
    from tclb_tpu.serve.pool import WorkerPool
    base = {"model": "d2q9", "shape": [8, 16], "niter": 30,
            "params": {"nu": 0.05}, "digest": True,
            "case": {"name": "h", "settings": {}}}
    with WorkerPool(workers=1, autostart=False) as pool:
        ref = pool.submit(dict(base, ckpt_root=str(tmp_path / "ref"),
                               checkpoint_every=10))
        ref_sha = ref.result(timeout=600)["state_sha256"]

    faults.install(FaultPlan.parse(
        "seed=606;pool.heartbeat:error:n=1:after=3"))
    pool = WorkerPool(workers=1, heartbeat_timeout_s=20.0,
                      job_attempts=3, term_grace_s=1.0,
                      retry_policy=RetryPolicy(max_attempts=4,
                                               base_delay_s=0.05,
                                               max_delay_s=0.2),
                      autostart=False)
    try:
        job = pool.submit(dict(base, ckpt_root=str(tmp_path / "w"),
                               checkpoint_every=10))
        res = job.result(timeout=600)
        assert res["resumed_from"] == 20
        assert res["state_sha256"] == ref_sha
        assert pool.stats()["requeued"] == 1
    finally:
        pool.close()
        faults.uninstall()
