"""The precision-ladder error contract: bf16 storage must stay within
the documented L2/Linf bounds of the f32 reference (tclb_tpu/precision.py
— ERROR_BOUNDS is the contract, this file makes it enforced).

These run the real 500-step harness cases on the CPU XLA path — the
worst case for the ladder (one bf16 round trip per step; the fused
device engines narrow once per K steps, so their error is at or below
what is asserted here).
"""

import json

import pytest

from tclb_tpu import precision


@pytest.mark.parametrize("case", precision.CASE_NAMES)
def test_bf16_error_within_documented_bounds(case):
    """Both storage representations of each case stay inside their
    documented bounds — off one shared f32 reference run."""
    raw, shifted = precision.compare_reprs(case, niter=500, n=64,
                                           storage_dtype="bfloat16")
    for rep in (raw, shifted):
        assert [r["iteration"] for r in rep["checkpoints"]] \
            == [100, 250, 500]
        violations = precision.check_bounds(rep)
        assert violations == [], violations
        # the harness must be measuring something: identical runs would
        # mean the narrowing silently didn't happen
        assert all(r["l2"] > 0 for r in rep["checkpoints"])
        # the informational velocity norms ride every row (the honest
        # bf16-tolerance signal for low-Mach cases — see README)
        assert all(r["u_linf"] > 0 for r in rep["checkpoints"])
    if case == "cavity":
        # the DDF-shifting headline: on the Ma~0.02 cavity the shifted
        # rung's velocity error is at least 10x below raw at every
        # checkpoint (measured ~40x) — Mach-independent narrow storage
        for rr, rs in zip(raw["checkpoints"], shifted["checkpoints"]):
            assert rs["u_linf"] <= rr["u_linf"] / 10, (rr, rs)
    else:
        # O(1)-signal cases pay at most a bounded early transient for
        # the default flip (kuper's spurious-current u_linf runs ~12x
        # raw at iter 100, back to ~4x by 500) — the hard contract is
        # the field bounds above; this guards against a blowup
        for rr, rs in zip(raw["checkpoints"], shifted["checkpoints"]):
            assert rs["u_linf"] <= 20 * rr["u_linf"], (rr, rs)


def test_check_bounds_flags_violations():
    rep = {"case": "cavity", "storage_dtype": "bfloat16",
           "checkpoints": [{"iteration": 100, "l2": 1.0, "linf": 1.0}]}
    v = precision.check_bounds(rep)
    assert len(v) == 2 and all("exceeds bound" in s for s in v)


def test_check_bounds_unknown_key():
    rep = {"case": "cavity", "storage_dtype": "float16",
           "checkpoints": []}
    v = precision.check_bounds(rep)
    assert v and "no documented error bound" in v[0]


def test_build_case_unknown_name():
    with pytest.raises(ValueError, match="unknown precision case"):
        precision.build_case("no_such_case")


def test_cli_json_smoke(capsys):
    """CLI exit 0 + parseable JSON on a short lap (the CI smoke job
    runs the full 500-step default)."""
    rc = precision.main(["--case", "cavity", "--niter", "100",
                        "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["violations"] == []
    assert out["reports"][0]["case"] == "cavity"
    # --repr defaults to 'both': one report per representation
    assert [r["storage_repr"] for r in out["reports"]] \
        == ["raw", "shifted"]


def test_cli_single_repr(capsys):
    rc = precision.main(["--case", "cavity", "--niter", "50",
                         "--repr", "shifted", "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert [r["storage_repr"] for r in out["reports"]] == ["shifted"]
