"""CLI entry point (python -m tclb_tpu): the reference's
``CLB/<model>/main case.xml`` surface (src/main.cpp.Rt:220-252)."""

import json
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # full-coverage job; the default lap runs \
    # the fast smoke suite


def _run(*args):
    return subprocess.run(
        [sys.executable, "-m", "tclb_tpu", *args],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})


def test_models_list():
    r = _run("models")
    assert r.returncode == 0, r.stderr
    names = r.stdout.split()
    assert "d2q9" in names and "d3q27_cumulant" in names
    assert len(names) >= 41


def test_describe_json():
    r = _run("describe", "d2q9")
    assert r.returncode == 0, r.stderr
    info = json.loads(r.stdout)
    assert info["name"] == "d2q9"
    assert "omega" in [s["name"] for s in info["settings"]]
    assert "Rho" in info["quantities"]


def test_run_case(tmp_path):
    case = tmp_path / "mini.xml"
    case.write_text("""<?xml version="1.0"?>
<CLBConfig version="2.0" model="d2q9" output="{out}/">
    <Geometry nx="32" ny="16">
        <MRT><Box/></MRT>
        <WVelocity name="Inlet"><Box nx="1"/></WVelocity>
        <EPressure name="Outlet"><Box dx="-1"/></EPressure>
        <Wall mask="ALL"><Channel/></Wall>
    </Geometry>
    <Model><Params Velocity="0.02" nu="0.05"/></Model>
    <Log Iterations="20"/>
    <Solve Iterations="40"/>
</CLBConfig>
""".replace("{out}", str(tmp_path)))
    r = _run("run", str(case))
    assert r.returncode == 0, r.stderr + r.stdout
    assert "done: 40 iterations" in r.stdout
    logs = list(tmp_path.glob("*Log*.csv"))
    assert logs, list(tmp_path.iterdir())


def test_config_provenance_dump(tmp_path):
    """The run writes an annotated config copy with version/precision/
    backend (reference MainContainer, src/Handlers.cpp.Rt:1504-1522)."""
    import xml.etree.ElementTree as ET
    case = tmp_path / "mini.xml"
    case.write_text("""<?xml version="1.0"?>
<CLBConfig version="2.0" model="d2q9" output="{out}/">
    <Geometry nx="16" ny="8"><MRT><Box/></MRT></Geometry>
    <Model><Params Velocity="0.0" nu="0.1"/></Model>
    <Solve Iterations="5"/>
</CLBConfig>
""".replace("{out}", str(tmp_path)))
    r = _run("run", str(case))
    assert r.returncode == 0, r.stderr
    dumps = list(tmp_path.glob("*config*.xml"))
    assert dumps, list(tmp_path.iterdir())
    root = ET.parse(dumps[0]).getroot()
    assert root.get("backend")
    assert root.get("precision") == "single"
    assert root.get("model_name") == "d2q9"
