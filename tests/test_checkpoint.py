"""Checkpoint/restart subsystem tests.

Covers the save -> restore round trip (bit-exact fields/flags/settings/
handler state, incl. Control time-series and sharded meshes), the
integrity manifest (corruption detection + ``latest()`` fallback),
retention, async serialization, the LoadBinary clock-sync regression,
and the headline property: a run SIGKILLed mid-solve resumes from its
newest valid checkpoint and finishes bit-identical to an uninterrupted
run.
"""

import json
import os
import signal
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu import checkpoint as ckpt
from tclb_tpu.checkpoint import (CheckpointManager, CheckpointError,
                                 manifest as mf, writer)
from tclb_tpu.checkpoint.cli import main as ckpt_cli
from tclb_tpu.control import run_config_string
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.parallel.mesh import make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flip_last_byte(path):
    with open(path, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))


def _channel_flags(m, ny, nx):
    wall = m.node_types["Wall"]
    f = np.zeros((ny, nx), dtype=np.uint16)
    f[0, :] = f[-1, :] = wall.value
    return f


def _make_lattice(mesh=None, dtype=jnp.float64, shape=(16, 32)):
    m = get_model("d2q9")
    lat = Lattice(m, shape, dtype=dtype,
                  settings={"nu": 0.05, "Velocity": 0.02}, mesh=mesh)
    lat.set_flags(_channel_flags(m, *shape))
    lat.init()
    return lat


def _state_tuple(lat):
    return (np.asarray(lat.state.fields), np.asarray(lat.state.flags),
            np.asarray(lat.params.settings),
            np.asarray(lat.params.zone_table),
            int(np.asarray(lat.state.iteration)))


def assert_lattices_identical(a, b):
    sa, sb = _state_tuple(a), _state_tuple(b)
    for xa, xb in zip(sa[:-1], sb[:-1]):
        np.testing.assert_array_equal(xa, xb)
    assert sa[-1] == sb[-1]


# --------------------------------------------------------------------------- #
# Round trip
# --------------------------------------------------------------------------- #


def test_roundtrip_bit_exact(tmp_path):
    lat = _make_lattice()
    lat.iterate(20)
    lat.avg_start = 7
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, lat)
    assert mf.is_checkpoint_dir(d)
    assert mf.verify_checkpoint(d) == []

    lat2 = _make_lattice()
    man = ckpt.restore_lattice(lat2, d)
    assert man["iteration"] == 20
    assert_lattices_identical(lat, lat2)
    assert lat2.avg_start == 7

    # the restored lattice keeps computing identically
    lat.iterate(10)
    lat2.iterate(10)
    assert_lattices_identical(lat, lat2)


def test_roundtrip_time_series(tmp_path):
    lat = _make_lattice()
    ramp = np.linspace(0.0, 0.05, 32)
    lat.set_setting_series("Velocity", ramp, zone=0)
    lat.iterate(8)
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, lat)

    lat2 = _make_lattice()
    ckpt.restore_lattice(lat2, d)
    np.testing.assert_array_equal(np.asarray(lat2.params.time_series),
                                  np.asarray(lat.params.time_series))
    assert lat2.params.series_map == lat.params.series_map
    lat.iterate(8)
    lat2.iterate(8)
    assert_lattices_identical(lat, lat2)


@pytest.mark.parametrize("decomp", [{"y": 2, "x": 1}, {"y": 2, "x": 2}])
def test_sharded_save_restores_onto_any_layout(tmp_path, decomp):
    import jax
    shape = (16, 32)
    nshards = int(np.prod(list(decomp.values())))
    mesh = make_mesh(shape, devices=jax.devices()[:nshards],
                     decomposition=decomp)
    lat = _make_lattice(mesh=mesh, shape=shape)
    lat.iterate(12)
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, lat)

    # one file per shard, keyed by mesh coordinates
    shard_files = [f for f in os.listdir(d)
                   if f.startswith("fields@") and f.endswith(".npy")]
    assert len(shard_files) == nshards
    man = mf.read_manifest(d)
    assert man["mesh"] == {"axes": decomp}
    assert len(man["arrays"]["fields"]["shards"]) == nshards
    assert mf.verify_checkpoint(d) == []

    # restore onto an UNSHARDED lattice: stitched global array, bit-exact
    plain = _make_lattice(shape=shape)
    ckpt.restore_lattice(plain, d)
    ref = _make_lattice(shape=shape)
    ref.iterate(12)
    np.testing.assert_array_equal(np.asarray(plain.state.fields),
                                  np.asarray(ref.state.fields))

    # and onto a DIFFERENT sharded layout
    other = _make_lattice(mesh=make_mesh(shape, devices=jax.devices()[:4],
                                         decomposition={"y": 4, "x": 1}),
                          shape=shape)
    ckpt.restore_lattice(other, d)
    np.testing.assert_array_equal(np.asarray(other.state.fields),
                                  np.asarray(ref.state.fields))
    other.iterate(4)
    ref.iterate(4)
    np.testing.assert_array_equal(np.asarray(other.state.fields),
                                  np.asarray(ref.state.fields))


def test_restore_refuses_wrong_model_and_shape(tmp_path):
    lat = _make_lattice()
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, lat)

    other = Lattice(get_model("d2q9_SRT"), (16, 32), dtype=jnp.float64)
    with pytest.raises(CheckpointError, match="fingerprint"):
        ckpt.restore_lattice(other, d)

    small = _make_lattice(shape=(8, 16))
    with pytest.raises(CheckpointError, match="shape"):
        ckpt.restore_lattice(small, d)


# --------------------------------------------------------------------------- #
# Integrity + retention + async
# --------------------------------------------------------------------------- #


def test_corruption_detected_and_latest_falls_back(tmp_path):
    lat = _make_lattice()
    mgr = CheckpointManager(str(tmp_path / "root"), keep_last=5,
                            async_saves=False)
    lat.iterate(10)
    mgr.save(lat)
    lat.iterate(10)
    p20 = mgr.save(lat)
    assert [s for s, _p in mgr.steps()] == [10, 20]
    assert mgr.latest() == p20

    # flip one byte in the newest checkpoint's field data
    _flip_last_byte(os.path.join(p20, "fields.npy"))
    problems = mf.verify_checkpoint(p20)
    assert problems and "crc" in problems[0].lower()
    # latest() skips it and lands on step 10
    assert mgr.latest() == mgr.step_path(10)

    # a missing file is also fatal
    os.unlink(os.path.join(mgr.step_path(10), "flags.npy"))
    assert mgr.latest() is None
    with pytest.raises(CheckpointError, match="no valid checkpoint"):
        mgr.restore(lat)


def test_truncated_file_detected(tmp_path):
    lat = _make_lattice()
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, lat)
    fpath = os.path.join(d, "fields.npy")
    with open(fpath, "r+b") as f:
        f.truncate(os.path.getsize(fpath) // 2)
    assert mf.verify_checkpoint(d) != []


def test_retention_keeps_last_n(tmp_path):
    lat = _make_lattice()
    mgr = CheckpointManager(str(tmp_path / "root"), keep_last=2,
                            async_saves=False)
    for step in (10, 20, 30, 40):
        mgr.save(lat, step=step)
    assert [s for s, _p in mgr.steps()] == [30, 40]


def test_async_saves_serialize_and_commit(tmp_path):
    lat = _make_lattice()
    mgr = CheckpointManager(str(tmp_path / "root"), keep_last=5,
                            async_saves=True)
    for step in (10, 20, 30):
        lat.iterate(2)
        mgr.save(lat, step=step)   # each save first drains the previous
    mgr.wait()
    assert [s for s, _p in mgr.steps()] == [10, 20, 30]
    for _s, p in mgr.steps():
        assert mf.verify_checkpoint(p) == []
    # no stray temp dirs once drained
    assert not [n for n in os.listdir(mgr.root) if n.endswith(".tmp")]


def test_async_writer_defers_errors_to_wait():
    w = writer.AsyncWriter()

    def boom():
        raise RuntimeError("disk on fire")

    w.submit(boom)
    with pytest.raises(RuntimeError, match="disk on fire"):
        w.wait()
    w.wait()   # error consumed, writer reusable


def test_atomic_path_never_leaves_partial_file(tmp_path):
    target = tmp_path / "out.txt"
    target.write_text("old")
    with pytest.raises(RuntimeError):
        with writer.atomic_path(str(target)) as tmp:
            with open(tmp, "w") as f:
                f.write("half-writ")
            raise RuntimeError("crash mid-write")
    assert target.read_text() == "old"
    assert os.listdir(tmp_path) == ["out.txt"]


# --------------------------------------------------------------------------- #
# Path normalization (the fn[:-4] suffix-juggling fix)
# --------------------------------------------------------------------------- #


def test_suffix_helpers_handle_dotted_stems():
    assert writer.with_suffix("a/state.v2", ".npz") == "a/state.v2.npz"
    assert writer.with_suffix("a/state.npz", ".npz") == "a/state.npz"
    assert writer.strip_suffix("a/state.v2.npz", ".npz") == "a/state.v2"
    assert writer.strip_suffix("a/state.v2", ".npz") == "a/state.v2"


def test_legacy_save_load_dotted_stem(tmp_path):
    lat = _make_lattice()
    lat.iterate(6)
    stem = str(tmp_path / "run.best")   # dot in the stem, no suffix
    lat.save(stem)
    assert os.path.exists(stem + ".npz")
    lat.save(stem + ".npz")             # suffixed spelling: same file
    assert not os.path.exists(stem + ".npz.npz")

    for name in (stem, stem + ".npz"):
        lat2 = _make_lattice()
        lat2.load(name)
        assert_lattices_identical(lat, lat2)


# --------------------------------------------------------------------------- #
# Full-run state through the control layer
# --------------------------------------------------------------------------- #

CHANNEL_XML = """<?xml version="1.0"?>
<CLBConfig output="{out}/">
    <Geometry nx="32" ny="16">
        <MRT><Box/></MRT>
        <Wall mask="ALL"><Box ny="1"/><Box dy="-1"/></Wall>
    </Geometry>
    <Model><Params Velocity="0.02" nu="0.05"/></Model>
    {body}
</CLBConfig>
"""


def _run(tmp_path, body, **kw):
    xml = CHANNEL_XML.format(out=tmp_path, body=body)
    return run_config_string(xml, get_model("d2q9"), dtype=jnp.float64,
                             conf_name="t", **kw)


def test_save_checkpoint_handler_records_handler_state(tmp_path):
    s = _run(tmp_path, """
    <SaveCheckpoint Iterations="10" keep="3" mode="sync"/>
    <Stop OutletFluxChange="1e-12" Times="100" Iterations="10"/>
    <Solve Iterations="20"/>""")
    assert s.iter == 20
    root = str(tmp_path) + "/t_checkpoint"
    mgr = CheckpointManager(root)
    latest = mgr.latest()
    assert latest == mgr.step_path(20)
    extra = mf.read_manifest(latest)["extra"]
    assert extra["iter"] == 20
    hands = extra["handlers"]
    # the periodic Stop handler's accumulator state rode along
    assert "cbStop#0" in hands
    assert "old" in hands["cbStop#0"] and "score" in hands["cbStop#0"]
    assert hands["cbStop#0"]["old"] != {}
    # the running <Solve> recorded its schedule anchor
    assert "acSolve#0" in hands
    assert hands["acSolve#0"]["__start_iter"] == 0


def test_resume_restores_handler_state_and_completes(tmp_path):
    body = """
    <SaveCheckpoint Iterations="10" keep="3" mode="sync"/>
    <Stop OutletFluxChange="1e-12" Times="100" Iterations="10"/>
    <Log Iterations="10"/>
    <Solve Iterations="40"/>"""
    ref = _run(tmp_path / "ref", body)
    assert ref.iter == 40

    part = _run(tmp_path / "res", body.replace('Iterations="40"',
                                               'Iterations="20"'))
    assert part.iter == 20
    # resume the FULL config from the interrupted run's checkpoint:
    # <Solve Iterations="40"> must complete to 40, not run 40 more
    res = _run(tmp_path / "res", body, resume="latest")
    assert res.iter == 40
    np.testing.assert_array_equal(np.asarray(res.lattice.state.fields),
                                  np.asarray(ref.lattice.state.fields))
    # Log CSV continues on the original cadence (10,20 then 30,40 — the
    # resumed run re-fires nothing before its restore point)
    csv = tmp_path / "res" / "t_Log.csv"
    rows = [ln.split(",")[0] for ln in csv.read_text().splitlines()[1:]]
    assert [int(float(r)) for r in rows[-2:]] == [30, 40]


def test_resume_explicit_path_and_cold_start(tmp_path):
    body = """
    <SaveCheckpoint Iterations="10" mode="sync"/>
    <Solve Iterations="20"/>"""
    s = _run(tmp_path, body)
    explicit = str(tmp_path) + "/t_checkpoint/step_00000010"
    s2 = _run(tmp_path, body, resume=explicit)
    assert s2.iter == 20

    with pytest.raises(ValueError, match="not a checkpoint directory"):
        _run(tmp_path, body, resume=str(tmp_path / "nowhere"))

    # resume with an empty root: cold start, still completes
    s3 = _run(tmp_path / "fresh", body, resume="latest")
    assert s3.iter == 20


def test_loadbinary_syncs_solver_clock(tmp_path):
    """Regression: LoadBinary used to jump the lattice iteration while
    solver.iter stayed at 0, so every Iterations=-based handler fired on
    a misaligned schedule and <Solve> ran the full count again."""
    a = _run(tmp_path, """
    <Solve Iterations="30"/>
    <SaveBinary filename="{0}/state.npz"/>""".format(tmp_path))
    assert a.iter == 30

    b = _run(tmp_path, """
    <LoadBinary filename="{0}/state.npz"/>
    <Log Iterations="10"/>
    <Solve Iterations="20"/>""".format(tmp_path))
    # clock reconciled: 30 restored + 20 more
    assert b.iter == 50
    assert int(np.asarray(b.lattice.state.iteration)) == 50
    csv = tmp_path / "t_Log.csv"
    rows = [ln.split(",")[0] for ln in csv.read_text().splitlines()[1:]]
    # Log fires at 40 and 50 — aligned to the restored clock
    assert [int(float(r)) for r in rows] == [40, 50]


def test_savebinary_directory_format_roundtrip(tmp_path):
    """A SaveBinary filename without .npz writes the manifest-verified
    checkpoint directory; LoadBinary restores it with full solver state."""
    a = _run(tmp_path, """
    <Solve Iterations="20"/>
    <SaveBinary filename="{0}/dump"/>""".format(tmp_path))
    d = str(tmp_path / "dump")
    assert mf.is_checkpoint_dir(d)
    assert mf.verify_checkpoint(d) == []

    b = _run(tmp_path, """
    <LoadBinary filename="{0}/dump"/>
    <Solve Iterations="15"/>""".format(tmp_path))
    assert b.iter == 35
    ref = _run(tmp_path / "ref", "<Solve Iterations=\"35\"/>")
    np.testing.assert_array_equal(np.asarray(b.lattice.state.fields),
                                  np.asarray(ref.lattice.state.fields))


# --------------------------------------------------------------------------- #
# CLI
# --------------------------------------------------------------------------- #


def test_cli_inspect_verify_prune(tmp_path, capsys):
    lat = _make_lattice()
    mgr = CheckpointManager(str(tmp_path / "root"), keep_last=9,
                            async_saves=False)
    for step in (10, 20, 30):
        mgr.save(lat, step=step)

    assert ckpt_cli(["inspect", mgr.root, "--format", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    # saved at explicit steps; the lattice itself never iterated
    assert [s["iteration"] for s in out] == [0, 0, 0]
    assert out[0]["model"]["name"] == "d2q9"
    assert out[0]["arrays"]["fields"]["dtype"] == "float64"

    assert ckpt_cli(["verify", mgr.root]) == 0

    # corrupt one -> verify exits 1 and names it
    _flip_last_byte(os.path.join(mgr.step_path(20), "flags.npy"))
    assert ckpt_cli(["verify", mgr.root]) == 1
    assert "FAIL" in capsys.readouterr().out

    assert ckpt_cli(["prune", mgr.root, "--keep", "1"]) == 0
    assert [s for s, _p in mgr.steps()] == [30]

    assert ckpt_cli(["inspect", str(tmp_path / "missing")]) == 2


# --------------------------------------------------------------------------- #
# Kill-resume: the property the subsystem exists for
# --------------------------------------------------------------------------- #

KILLER_MOD = """
import os, signal

def run(solver):
    if os.environ.get("TCLB_TEST_KILL") == "1":
        os.kill(os.getpid(), signal.SIGKILL)
"""

KILL_XML = """<?xml version="1.0"?>
<CLBConfig model="d2q9" output="{out}/">
    <Geometry nx="32" ny="16">
        <MRT><Box/></MRT>
        <Wall mask="ALL"><Box ny="1"/><Box dy="-1"/></Wall>
    </Geometry>
    <Model><Params Velocity="0.02" nu="0.05"/></Model>
    <SaveCheckpoint Iterations="10" keep="3" mode="sync"/>
    <CallPython module="killer" function="run" Iterations="25"/>
    <Solve Iterations="40"/>
    <SaveBinary filename="{out}/final.npz"/>
</CLBConfig>
"""


def _spawn(case, out, *extra, kill=False):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=f"{out}{os.pathsep}{REPO}")
    env.pop("TCLB_TEST_KILL", None)
    if kill:
        env["TCLB_TEST_KILL"] = "1"
    return subprocess.run(
        [sys.executable, "-m", "tclb_tpu", "run", str(case), *extra],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)


def test_kill_resume_bit_identical(tmp_path):
    (tmp_path / "killer.py").write_text(KILLER_MOD)
    case = tmp_path / "case.xml"

    # uninterrupted reference (same config => same checkpoint cadence,
    # so iterate chunk boundaries match exactly)
    ref_out = tmp_path / "ref"
    case.write_text(KILL_XML.format(out=ref_out))
    r = _spawn(case, tmp_path)
    assert r.returncode == 0, r.stderr

    # interrupted run: SIGKILL at iteration 25, after checkpoints 10+20
    out = tmp_path / "run"
    case.write_text(KILL_XML.format(out=out))
    r = _spawn(case, tmp_path, kill=True)
    assert r.returncode == -signal.SIGKILL
    root = out / "case_checkpoint"
    steps = sorted(os.listdir(root))
    assert steps == ["step_00000010", "step_00000020"]

    # corrupt the newest checkpoint: resume must fall back to step 10
    _flip_last_byte(root / "step_00000020" / "fields.npy")

    r = _spawn(case, tmp_path, "--resume")
    assert r.returncode == 0, r.stderr
    assert "resumed from" in r.stdout and "step_00000010" in r.stdout

    ref = np.load(ref_out / "final.npz")
    got = np.load(out / "final.npz")
    np.testing.assert_array_equal(got["fields"], ref["fields"])
    np.testing.assert_array_equal(got["flags"], ref["flags"])
    assert int(got["iteration"]) == int(ref["iteration"]) == 40


# --------------------------------------------------------------------------- #
# Multi-host save/restore: two real OS processes, one checkpoint
# --------------------------------------------------------------------------- #

# Each process plays ONE host of a {"y": 2, "x": 1} pod mesh: it builds
# the same lattice over two forced host devices, iterates, then writes
# ONLY its own host's addressable shard via write_shard_fragment — the
# exact per-process call CheckpointManager makes under jax.process_count
# > 1.  Process 0 additionally merges the fragments into the manifest
# (the main-process half of the barrier protocol; serial child execution
# stands in for the barrier).
_MULTIHOST_WRITER = """
import json, sys

import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from tclb_tpu.checkpoint import restore as rst
from tclb_tpu.parallel.mesh import make_mesh

proc, outdir = int(sys.argv[1]), sys.argv[2]

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
import numpy as np

m = get_model("d2q9")
mesh = make_mesh((16, 32), devices=jax.devices()[:2],
                 decomposition={{"y": 2, "x": 1}})
lat = Lattice(m, (16, 32), dtype=jax.numpy.float64,
              settings={{"nu": 0.05, "Velocity": 0.02}}, mesh=mesh)
flags = np.zeros((16, 32), dtype=np.uint16)
flags[0, :] = flags[-1, :] = m.node_types["Wall"].value
lat.set_flags(flags)
lat.init()
lat.iterate(12)
captured = rst.capture_lattice(lat)
# this process's addressable shards: on a real pod each host only SEES
# its own devices; emulate by keeping the shard at mesh row `proc`
for val in captured["arrays"].values():
    if isinstance(val, rst.ShardedCapture):
        val.shards = [s for s in val.shards
                      if s["coords"].get("y") == proc]
        assert len(val.shards) == 1, val.shards
rst.write_shard_fragment(outdir, captured, proc)
if proc == 0:
    total = rst.write_checkpoint_files(outdir, captured,
                                       merge_fragments=True)
    print("merged", total)
print("ok", proc)
"""


def test_multihost_two_process_save_restores_bit_identical(tmp_path):
    """Two OS processes each write their own host's shard fragment of a
    2-host mesh checkpoint; the merged manifest restores onto a 1-host
    lattice and back onto a sharded one, bit-identical fields + globals
    against an uninterrupted single-process reference."""
    import jax
    script = tmp_path / "writer.py"
    script.write_text(_MULTIHOST_WRITER.format(repo=REPO))
    d = tmp_path / "ck"
    d.mkdir()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    # host 1 first, then host 0 merging — the manager's barrier means
    # every fragment has landed before the main process merges
    for proc in (1, 0):
        r = subprocess.run(
            [sys.executable, str(script), str(proc), str(d)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        assert r.returncode == 0, r.stderr
        assert f"ok {proc}" in r.stdout
    assert "merged" in r.stdout

    # the merged checkpoint is whole: 2 shard files per sharded array,
    # fragments consumed, manifest verifies
    assert mf.verify_checkpoint(str(d)) == []
    assert not [f for f in os.listdir(d) if f.startswith("fragment.")]
    shard_files = [f for f in os.listdir(d) if f.startswith("fields@")]
    assert len(shard_files) == 2
    man = mf.read_manifest(str(d))
    assert man["mesh"] == {"axes": {"y": 2, "x": 1}}

    # reference: the same run, single process, no mesh
    ref = _make_lattice()
    ref.iterate(12)

    # restore onto a 1-host (unsharded) lattice: bit-identical
    plain = _make_lattice()
    got = ckpt.restore_lattice(plain, str(d))
    assert got["iteration"] == 12
    assert_lattices_identical(ref, plain)
    np.testing.assert_array_equal(np.asarray(plain.state.globals_),
                                  np.asarray(ref.state.globals_))

    # ... and back onto a sharded layout (different decomposition than
    # the writers used), still bit-identical, still iterating in step
    mesh = make_mesh((16, 32), devices=jax.devices()[:4],
                     decomposition={"y": 4, "x": 1})
    sharded = _make_lattice(mesh=mesh)
    ckpt.restore_lattice(sharded, str(d))
    assert_lattices_identical(ref, sharded)
    ref.iterate(6)
    sharded.iterate(6)
    np.testing.assert_array_equal(np.asarray(sharded.state.fields),
                                  np.asarray(ref.state.fields))
