"""Validation of Q-cut interpolated bounce-back (d3q27_cumulant_qibb_small).

The defining property of interpolated bounce-back: the zero-velocity plane
sits at the TRUE (off-grid) wall location, not at the half-way plane of
plain bounce-back.  A force-driven channel whose walls sit at fractional
offsets must recover the parabola anchored at those offsets.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.utils.geometry import cuts_from_sdf, sphere_sdf

pytestmark = pytest.mark.slow  # full-coverage job; the default lap runs the fast smoke suite


def _qibb_channel(delta, ny=16, niter=6000):
    """Channel along x; solid below y_w0 = 1 - delta and above
    y_w1 = ny - 2 + delta (so the fluid gap is (ny-3) + 2 delta wide).
    Rows 0 and ny-1 are solid; rows 1 and ny-2 are QIBB fluid nodes with
    cut links toward the solid."""
    m = get_model("d3q27_cumulant_qibb_small")
    nz, nx = 3, 4
    g = 1e-6
    lat = Lattice(m, (nz, ny, nx), dtype=jnp.float64,
                  settings={"nu": 1 / 6, "ForceY": 0.0, "ForceX": g})

    y_w0 = 1.0 - delta
    y_w1 = (ny - 2.0) + delta

    def sdf(coords):
        y = coords[1]          # (z, y, x) index order
        return np.minimum(y - y_w0, y_w1 - y)

    from tclb_tpu.models.d3q27_cumulant_qibb import E
    cuts = cuts_from_sdf(sdf, (nz, ny, nx), E)

    flags = np.full((nz, ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0, :] = m.flag_for("Solid")
    flags[:, -1, :] = m.flag_for("Solid")
    flags[:, 1, :] = m.flag_for("QIBB", "MRT")
    flags[:, -2, :] = m.flag_for("QIBB", "MRT")
    lat.set_flags(flags)
    lat.init()
    for i in range(1, 27):
        lat.set_density(f"q[{i}]", cuts[i - 1])
    lat.iterate(niter)
    u = np.asarray(lat.get_quantity("U"))
    return u[0][1, :, 2], y_w0, y_w1, g


@pytest.mark.parametrize("delta", [0.25, 0.75])
def test_qibb_offgrid_wall_position(delta):
    ny = 16
    ux, y_w0, y_w1, g = _qibb_channel(delta, ny)
    assert np.isfinite(ux).all()
    y = np.arange(ny, dtype=float)
    c = 0.5 * (y_w0 + y_w1)
    h = 0.5 * (y_w1 - y_w0)
    nu = 1 / 6
    ref = g / (2 * nu) * (h ** 2 - (y - c) ** 2)
    sl = slice(2, ny - 2)   # interior fluid nodes
    err = np.abs(ux[sl] - ref[sl]).max() / ref.max()
    # sub-grid wall placement: a few percent; plain bounce-back at the
    # half-way plane would be ~2 delta/ny ~ 10% off for delta=0.75
    assert err < 0.04, err
    # the fitted parabola's roots recover the intended wall offsets
    coef = np.polyfit(y[sl], ux[sl], 2)
    roots = np.sort(np.roots(coef))
    np.testing.assert_allclose(roots, [y_w0, y_w1], atol=0.15)


def test_qibb_beats_plain_bounceback():
    """For delta = 0.75 the off-grid wall is far from the half-way plane:
    QIBB must be substantially more accurate than treating rows 0/ny-1 as
    plain walls."""
    ny = 16
    delta = 0.75
    ux, y_w0, y_w1, g = _qibb_channel(delta, ny)
    y = np.arange(ny, dtype=float)
    c = 0.5 * (y_w0 + y_w1)
    h = 0.5 * (y_w1 - y_w0)
    ref = g / (2 * (1 / 6)) * (h ** 2 - (y - c) ** 2)
    sl = slice(2, ny - 2)
    err_qibb = np.abs(ux[sl] - ref[sl]).max() / ref.max()

    # plain bounce-back channel of the same node layout: wall planes at
    # 0.5 and ny-1.5 regardless of delta
    m = get_model("d3q27_cumulant_qibb_small")
    nz, nx = 3, 4
    lat = Lattice(m, (nz, ny, nx), dtype=jnp.float64,
                  settings={"nu": 1 / 6, "ForceX": g})
    flags = np.full((nz, ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0, :] = m.flag_for("Solid")
    flags[:, -1, :] = m.flag_for("Solid")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(6000)
    ux_bb = np.asarray(lat.get_quantity("U"))[0][1, :, 2]
    err_bb = np.abs(ux_bb[sl] - ref[sl]).max() / ref.max()
    assert err_qibb < 0.5 * err_bb, (err_qibb, err_bb)


def test_cuts_from_sdf_sphere():
    """Cut fractions for a sphere: only surface-adjacent fluid nodes carry
    cuts, fractions are in [0,1], and the axis-link cut equals the exact
    surface crossing."""
    from tclb_tpu.models.d3q27_cumulant_qibb import E
    n = 12
    sdf = sphere_sdf((6.0, 6.0, 6.0), 3.3)
    cuts = cuts_from_sdf(sdf, (n, n, n), E)
    assert cuts.shape == (26, n, n, n)
    has = cuts >= 0
    assert has.any()
    assert (cuts[has] <= 1.0).all()
    # node (6, 6, 2): +y-ish links don't cross; the +x link toward the
    # sphere surface at x = 6 - 3.3 = 2.7 crosses at q = 0.7
    (i_px,) = [i for i in range(1, 27)
               if tuple(E[i]) == (1, 0, 0)]
    q = cuts[i_px - 1, 6, 6, 2]
    np.testing.assert_allclose(q, 0.7, atol=1e-6)
