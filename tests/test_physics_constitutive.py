"""Constitutive-law validation for the models whose only prior test was
"finite + mass conserved" (round-2 VERDICT Weak #6): each test asserts the
distinguishing PHYSICS of the model, not just stability.

* d2q9_les / d3q19_les — the Smagorinsky closure adds eddy viscosity,
  so at identical molecular nu a sheared field must lose enstrophy
  faster than the plain collision (Hou et al. closure).
* d2q9_cumulant — at omega = omega_bulk = 1 every cumulant relaxes fully
  to equilibrium, which coincides with BGK at omega=1 up to the O(u^3)
  difference between the factorized-Maxwellian and quadratic equilibria.
* d2q9_heat_conjugate — conjugate heat transfer (framework extension):
  at steady state the temperature is continuous across the fluid/solid
  interface and the conductive flux alfa * dT/dx is continuous, so the
  slope ratio equals the inverse diffusivity ratio.
* d2q9_solid — dendritic solidification (reference
  src/d2q9_solid/Dynamics.c.Rt): a seed in an undercooled melt grows,
  rejects solute at the interface (partition coefficient), and the
  curvature getter recovers 1/R on a painted disc.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.ops import lbm


def _shear_field(n, u0=0.08, modes=3):
    y, x = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    k = 2.0 * np.pi / n
    ux = u0 * np.sin(modes * k * y) * np.cos(k * x)
    uy = u0 * 0.5 * np.sin(2 * modes * k * x)
    return ux, uy


def _set_field(lat, model, E, ux, uy):
    W = lbm.weights(E)
    dt = lat.dtype
    rho = jnp.ones(lat.shape, dt)
    feq = lbm.equilibrium(E, W, rho,
                          (jnp.asarray(ux, dt), jnp.asarray(uy, dt)))
    names = [model.storage_names[i] for i in model.groups["f"]]
    lat.set_density_planes({nm: feq[k] for k, nm in enumerate(names)})


def _enstrophy(u):
    """sum |curl u|^2 from a (3, ny, nx) velocity stack."""
    ux, uy = np.asarray(u[0]), np.asarray(u[1])
    dyux = np.gradient(ux, axis=0)
    dxuy = np.gradient(uy, axis=1)
    return float(((dxuy - dyux) ** 2).sum())


def test_les_reduces_enstrophy_2d():
    """d2q9_les at the same molecular nu dissipates a sheared field
    faster than plain BGK (d2q9_SRT): eddy viscosity is positive."""
    n = 64
    nu = 0.005

    def run(name, extra=None):
        m = get_model(name)
        lat = Lattice(m, (n, n), dtype=jnp.float64,
                      settings={"nu": nu, **(extra or {})})
        lat.set_flags(np.full((n, n), m.flag_for("BGK"), dtype=np.uint16))
        from tclb_tpu.models.d2q9 import E
        ux, uy = _shear_field(n)
        _set_field(lat, m, E, ux, uy)
        lat.iterate(200)
        return _enstrophy(lat.get_quantity("U"))

    ens_plain = run("d2q9_SRT")
    ens_les = run("d2q9_les", {"Smag": 0.16})
    assert ens_les < ens_plain * 0.98, \
        f"LES enstrophy {ens_les} not below plain {ens_plain}"
    # sanity: with Smag -> 0 the LES model degenerates to plain BGK
    ens_les0 = run("d2q9_les", {"Smag": 1e-12})
    assert abs(ens_les0 - ens_plain) / ens_plain < 1e-6


def test_les_reduces_enstrophy_3d():
    """d3q19_les vs plain d3q19 MRT at the same nu, 3D shear field."""
    n = 16
    nu = 0.01

    def run(name, extra=None):
        m = get_model(name)
        lat = Lattice(m, (n, n, n), dtype=jnp.float64,
                      settings={"nu": nu, **(extra or {})})
        coll = "MRT" if "MRT" in m.node_types else "BGK"
        lat.set_flags(np.full((n, n, n), m.flag_for(coll),
                              dtype=np.uint16))
        lat.init()
        # perturb: inject a strong shear through the Velocity init is not
        # available in 3D helpers; overwrite f with equilibrium of a
        # sheared field instead
        E = m.ei[:len(m.groups["f"])]
        W = lbm.weights(E)
        z, y, x = np.meshgrid(*[np.arange(n)] * 3, indexing="ij")
        k = 2 * np.pi / n
        u0 = 0.08
        ux = u0 * np.sin(2 * k * y) * np.cos(k * z)
        uy = 0.5 * u0 * np.sin(2 * k * z)
        uz = 0.25 * u0 * np.sin(2 * k * x)
        dt = lat.dtype
        rho = jnp.ones((n, n, n), dt)
        feq = lbm.equilibrium(E, W, rho, (jnp.asarray(ux, dt),
                                          jnp.asarray(uy, dt),
                                          jnp.asarray(uz, dt)))
        names = [m.storage_names[i] for i in m.groups["f"]]
        lat.set_density_planes({nm: feq[j] for j, nm in enumerate(names)})
        lat.iterate(100)
        u = np.asarray(lat.get_quantity("U"))
        dzy = np.gradient(u[2], axis=1) - np.gradient(u[1], axis=0)
        dxz = np.gradient(u[0], axis=0) - np.gradient(u[2], axis=2)
        dyx = np.gradient(u[1], axis=2) - np.gradient(u[0], axis=1)
        return float((dzy ** 2 + dxz ** 2 + dyx ** 2).sum())

    ens_plain = run("d3q19")
    ens_les = run("d3q19_les", {"Smag": 0.17})
    assert ens_les < ens_plain * 0.98, \
        f"3D LES enstrophy {ens_les} not below plain {ens_plain}"


def test_cumulant_matches_bgk_at_omega_one():
    """At omega = omega_bulk = 1 the cumulant collision relaxes every
    cumulant to its equilibrium, which agrees with BGK at omega=1 up to
    the O(u^3) factorized-vs-quadratic equilibrium difference."""
    n = 48
    u0 = 0.01
    from tclb_tpu.models.d2q9 import E as E9

    def run(name):
        m = get_model(name)
        lat = Lattice(m, (n, n), dtype=jnp.float64,
                      settings={"omega": 1.0})
        lat.set_flags(np.full((n, n), m.flag_for("BGK"), dtype=np.uint16))
        y, x = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
        k = 2 * np.pi / n
        ux = -u0 * np.cos(k * x) * np.sin(k * y)
        uy = u0 * np.sin(k * x) * np.cos(k * y)
        # both models share storage order f0..f8 within their own E
        # ordering; build feq in each model's own velocity order
        E = m.ei[:9, :2]
        _set_field(lat, m, E, ux, uy)
        lat.iterate(20)
        return np.asarray(lat.get_quantity("U"))

    u_bgk = run("d2q9_SRT")
    u_cum = run("d2q9_cumulant")
    err = np.abs(u_cum[:2] - u_bgk[:2]).max()
    assert err < 5.0 * u0 ** 3 * 100, \
        f"cumulant vs BGK at omega=1: max|du| = {err}"
    assert err < 5e-5


def test_solidification_seed_growth():
    """d2q9_solid dendritic solidification: a Seed in an undercooled melt
    (Cl_eq > C via a negative liquidus slope) grows outward, rejects
    solute at the interface (C rises above the far-field value by the
    partition coefficient), and banks Cs only where solid — the
    reference's interface update op-for-op
    (src/d2q9_solid/Dynamics.c.Rt:354-374)."""
    n = 48
    m = get_model("d2q9_solid")
    lat = Lattice(m, (n, n), dtype=jnp.float64, settings={
        "nu": 0.1, "FluidAlfa": 0.05, "SoluteDiffusion": 0.05,
        "C0": 0.5, "Concentration": 0.5, "Temperature": 0.95,
        "T0": 0.95, "Teq": 1.0, "LiquidusSlope": -1.0,
        "PartitionCoef": 0.1})
    flags = np.full((n, n), m.flag_for("MRT"), dtype=np.uint16)
    flags[n // 2 - 1:n // 2 + 1, n // 2 - 1:n // 2 + 1] = \
        m.flag_for("MRT", "Seed")
    lat.set_flags(flags)
    lat.init()
    fi0 = float(np.asarray(lat.get_quantity("Solid")).sum())
    assert fi0 == 4.0                      # the Seed starts fully solid
    sums = [fi0]
    for _ in range(4):
        lat.iterate(15)
        fi = np.asarray(lat.get_quantity("Solid"))
        assert np.isfinite(fi).all()
        assert fi.min() >= 0.0 and fi.max() <= 1.0 + 1e-12
        sums.append(float(fi.sum()))
    assert all(b > a for a, b in zip(sums, sums[1:])), \
        f"solid fraction must grow monotonically: {sums}"
    # growth decelerates as rejected solute raises C toward Cl_eq — the
    # physically expected diffusion-limited slowdown
    assert sums[-1] > 2 * fi0, f"growth too slow: {sums}"
    c = np.asarray(lat.get_quantity("C"))
    assert c.max() > 0.5 + 1e-4, "no solute rejection at the interface"
    cs = np.asarray(lat.state.fields[m.storage_index["Cs"]])
    assert cs.max() > 0.0
    assert abs(cs[0, 0]) < 1e-12           # far field: no banked solute
    # growth is centered on the seed (roughly isotropic with SA=0)
    com_y = (fi * np.arange(n)[:, None]).sum() / fi.sum()
    com_x = (fi * np.arange(n)[None, :]).sum() / fi.sum()
    assert abs(com_y - (n / 2 - 0.5)) < 1.0
    assert abs(com_x - (n / 2 - 0.5)) < 1.0


def test_solidification_curvature_getter():
    """The K quantity recovers ~1/R on a painted solid disc (the
    Gibbs-Thomson undercooling input, reference getCl_eq/getK)."""
    n, r = 48, 10.0
    m = get_model("d2q9_solid")
    lat = Lattice(m, (n, n), dtype=jnp.float64,
                  settings={"nu": 0.1, "LiquidusSlope": -1.0})
    lat.set_flags(np.full((n, n), m.flag_for("MRT"), dtype=np.uint16))
    lat.init()
    y, x = np.meshgrid(np.arange(n), np.arange(n), indexing="ij")
    d = np.sqrt((y - n / 2) ** 2 + (x - n / 2) ** 2)
    # smooth solid disc (a hard 0/1 disc has staircase curvature)
    fi = np.clip((r + 1.5 - d) / 3.0, 0.0, 1.0)
    lat.set_density("fi_s", fi)
    k = np.asarray(lat.get_quantity("K"))
    ring = (np.abs(d - r) < 1.0)
    k_mean = float(np.abs(k[ring]).mean())
    assert abs(k_mean - 1.0 / r) / (1.0 / r) < 0.3, \
        f"disc curvature {k_mean:.4f} vs 1/R = {1.0 / r:.4f}"


@pytest.mark.slow   # 6000 f64 XLA steps of a 3D model — physics-job fare
def test_cumulant_channel_matches_analytic_poiseuille():
    """d3q27_cumulant force-driven channel vs the analytic parabolic
    profile — a quantitative external pin on the cumulant collision
    (round-2 VERDICT Weak #9: the higher-order Isserlis closure had only
    self-recorded goldens; the laminar channel's exact solution
    u(y) = F (y-y0)(y1-y) / (2 nu) is closure-independent ground truth)."""
    nz, ny, nx = 4, 24, 32
    nu, force = 0.1, 1e-6
    m = get_model("d3q27_cumulant")
    lat = Lattice(m, (nz, ny, nx), dtype=jnp.float64,
                  settings={"nu": nu, "ForceX": force})
    flags = np.full((nz, ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0, :] = m.flag_for("Wall")
    flags[:, -1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(6000)
    ux = np.asarray(lat.get_quantity("U"))[0].mean(axis=(0, 2))
    y = np.arange(ny, dtype=float)
    y0, y1 = 0.5, ny - 1.5      # half-way bounce-back wall locations
    analytic = force / (2.0 * nu) * (y - y0) * (y1 - y)
    sel = slice(2, ny - 2)
    err = np.abs(ux[sel] - analytic[sel]).max() / analytic.max()
    assert err < 0.02, \
        f"cumulant channel vs analytic Poiseuille: rel err {err:.4f}"


def test_solid_conjugate_flux_continuity():
    """d2q9_heat_conjugate: steady 1D conduction, fluid|solid bilayer.

    Heaters pin T_hot at x=0 (zone 0) and T_cold at x=n-1 (zone 1,
    zonal HeaterTemperature); fluid occupies the left half (FluidAlfa),
    Solid the right half (SolidAlfa).  At steady state the temperature
    must be continuous at the interface and the conductive flux
    alfa*dT/dx equal on both sides: slope_fluid/slope_solid =
    SolidAlfa/FluidAlfa."""
    n, h = 64, 8
    alfa_f, alfa_s = 0.3, 0.05
    m = get_model("d2q9_heat_conjugate")
    lat = Lattice(m, (h, n), dtype=jnp.float64,
                  settings={"omega": 1.0, "InletVelocity": 0.0,
                            "FluidAlfa": alfa_f, "SolidAlfa": alfa_s,
                            "InitTemperature": 1.0,
                            "HeaterTemperature": 2.0})
    coll = "MRT" if "MRT" in m.node_types else "BGK"
    flags = np.full((h, n), m.flag_for(coll), dtype=np.uint16)
    flags[:, n // 2:-1] = m.flag_for("Solid")
    flags[:, 0] = m.flag_for(coll, "Heater")             # hot, zone 0
    flags[:, -1] = m.flag_for(coll, "Heater", zone=1)    # cold, zone 1
    lat.set_flags(flags)
    lat.set_setting("HeaterTemperature", 0.5, zone=1)
    lat.init()
    prev = None
    for _ in range(40):
        lat.iterate(500)
        T = np.asarray(lat.get_quantity("T"))[0]
        if prev is not None and np.abs(T - prev).max() < 1e-9:
            break
        prev = T

    # interface continuity: no jump beyond the one-cell discretization
    mid = n // 2
    jump = abs(T[mid] - T[mid - 1])
    left_step = abs(T[mid - 1] - T[mid - 2])
    right_step = abs(T[mid + 2] - T[mid + 1])
    assert jump < 4 * max(left_step, right_step) + 1e-12

    # flux continuity: fit interior slopes on both sides
    xs = np.arange(n)
    fl = slice(4, mid - 4)
    so = slice(mid + 4, n - 4)
    slope_f = np.polyfit(xs[fl], T[fl], 1)[0]
    slope_s = np.polyfit(xs[so], T[so], 1)[0]
    ratio = slope_f / slope_s
    expected = alfa_s / alfa_f
    assert abs(ratio - expected) / expected < 0.05, \
        f"flux continuity: slope ratio {ratio:.4f} vs " \
        f"alfa_s/alfa_f = {expected:.4f}"
