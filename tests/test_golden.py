"""Golden parity harness over the five BASELINE configs.

The reference's regression model (SURVEY §4.2): run each case config, then
compare the CSV log row at the final iteration against a recorded golden
with `tools/csvdiff`'s numeric tolerance (1e-10, Walltime discarded —
reference tools/tests.sh:100-110, tools/csvdiff:40-50).  The configs below
are the five driver-designated BASELINE cases (BASELINE.md) translated to
this framework's XML at reduced scale/horizon so they run in seconds on
the CI's virtual-device CPU build (the reference likewise run-tests only
its CPU binding, SURVEY §4.1).

Re-record after an intentional physics change with:
    TCLB_RECORD_GOLDENS=1 python -m pytest tests/test_golden.py
"""

import json
import os
import pathlib

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.control.solver import _run_root
from tclb_tpu.models import get_model

GOLDEN_DIR = pathlib.Path(__file__).parent / "goldens"
RECORD = bool(os.environ.get("TCLB_RECORD_GOLDENS"))
# csvdiff tolerance model (reference tools/csvdiff:40-50)
RTOL, ATOL = 1e-10, 1e-12
# columns that depend on the wall clock / environment, not physics
SKIP = {"Walltime"}

KARMAN = """<?xml version="1.0"?>
<CLBConfig version="2.0" output="{out}/">
    <Geometry nx="64" ny="32">
        <MRT><Box/></MRT>
        <WVelocity name="Inlet"><Inlet/></WVelocity>
        <EPressure name="Outlet"><Outlet/></EPressure>
        <Inlet nx='1' dx='2'><Box/></Inlet>
        <Outlet nx='1' dx='-2'><Box/></Outlet>
        <Wall mask="ALL">
            <Channel/>
            <Wedge dx="12" nx="4" dy="18" ny="4" direction="LowerRight"/>
            <Wedge dx="12" nx="4" dy="10" ny="4" direction="UpperRight"/>
        </Wall>
    </Geometry>
    <Model>
        <Params Velocity="0.05"/>
        <Params nu="0.05"/>
    </Model>
    <Solve Iterations="200"/>
</CLBConfig>
"""

POISEUILLE = """<?xml version="1.0"?>
<CLBConfig version="2.0" output="{out}/">
    <Units>
        <Params size="0.0005m" gauge="1"/>
        <Params nu="1e-5m2/s" gauge="0.1666666666"/>
    </Units>
    <Geometry nx="0.02m" ny="0.0105m">
        <MRT><Box/></MRT>
        <Wall mask="ALL"><Channel/></Wall>
    </Geometry>
    <Model>
        <Params Velocity="0.0"/>
        <Params omega="1.0"/>
        <Params GravitationX="0.000311634m/s2"/>
        <Params Density="1000kg/m3"/>
    </Model>
    <Solve Iterations="500"/>
</CLBConfig>
"""

CHANNEL3D = """<?xml version="1.0"?>
<CLBConfig version="2.0" output="{out}/">
    <Geometry nx="48" ny="16" nz="16">
        <MRT><Box/></MRT>
        <Wall mask="ALL"><Channel/></Wall>
    </Geometry>
    <Model>
        <Params nu="0.02"/>
        <Params ForceX="0.00001" ForceZ="-0.00003"/>
    </Model>
    <Solve Iterations="200"/>
</CLBConfig>
"""

DROP = """<?xml version="1.0"?>
<CLBConfig version="2.0" output="{out}/">
    <Geometry nx="64" ny="64">
        <MRT><Box/></MRT>
        <None name="zdrop">
            <Sphere dx="20" nx="24" dy="20" ny="24"/>
        </None>
    </Geometry>
    <Model>
        <Params omega="1"/>
        <!-- the REAL drop.xml parameters (225x density ratio), reduced
             from 512^2/500k to 64^2/300 -->
        <Params Density="3.2600529440452366"
                Density-zdrop="0.014500641645077492"
                Temperature="0.56" FAcc="1" Magic="0.01"
                MagicA="-0.152" MagicF="-0.6666666666666"/>
    </Model>
    <Solve Iterations="300"/>
</CLBConfig>
"""

HEAT_ADJ = """<?xml version="1.0"?>
<CLBConfig version="2.0" output="{out}/">
    <Geometry nx="32" ny="16">
        <MRT><Box/></MRT>
        <WVelocity name="Inlet"><Box nx="1"/></WVelocity>
        <EPressure name="Outlet"><Box dx="-1"/></EPressure>
        <Wall mask="ALL"><Channel/></Wall>
        <DesignSpace><Box dx="8" nx="16"/></DesignSpace>
    </Geometry>
    <Model>
        <Params InletVelocity="0.02" nu="0.05"/>
        <Params InletTemperature="1" InitTemperature="0"/>
        <Params FluidAlfa="0.05" SolidAlfa="0.005"/>
    </Model>
    <Solve Iterations="150"/>
</CLBConfig>
"""

CASES = {
    "karman": ("d2q9", KARMAN),
    "poiseuille": ("d2q9", POISEUILLE),
    "channel3d": ("d3q27_cumulant", CHANNEL3D),
    "drop": ("d2q9_kuper", DROP),
    "heat_adj": ("d2q9_heat_adj", HEAT_ADJ),
}


def _run_case(name, tmp_path):
    import xml.etree.ElementTree as ET
    model_name, xml = CASES[name]
    root = ET.fromstring(xml.format(out=tmp_path))
    solver = _run_root(root, get_model(model_name), None, jnp.float64,
                       str(tmp_path) + "/", name)
    row = solver.log_row()
    # fold in a field checksum so the golden pins the state, not just the
    # monitors (the reference pins binary fields via sha1; a checksum is
    # the tolerance-friendly equivalent)
    fields = np.asarray(solver.lattice.state.fields)
    row["FieldsL1"] = float(np.abs(fields).sum())
    row["FieldsSum"] = float(fields.sum())
    if name == "heat_adj":
        # the BASELINE heat_adj config exists to pin the GRADIENT (the
        # reference runs <FDTest>, src/Handlers.cpp.Rt:1944): golden
        # columns for the adjoint objective and its gradient
        from tclb_tpu.adjoint import InternalTopology, make_unsteady_gradient
        m = solver.model
        lat = solver.lattice
        lat.set_setting("HeatFluxInObj", 1.0)
        lat.set_setting("MaterialInObj", 0.1)
        design = InternalTopology(m)
        grad_fn = make_unsteady_gradient(m, design, 20, levels=2)
        theta0 = design.get(lat.state, lat.params)
        obj, g, _ = grad_fn(theta0, lat.state, lat.params)
        g = np.asarray(g)
        row["AdjObjective"] = float(obj)
        row["AdjGradL1"] = float(np.abs(g).sum())
        # two point probes inside the design strip
        row["AdjGradP1"] = float(g[0, 8, 12])
        row["AdjGradP2"] = float(g[0, 10, 20])
    return row


@pytest.mark.parametrize("name", sorted(CASES))
def test_golden(name, tmp_path):
    row = _run_case(name, tmp_path)
    assert all(np.isfinite(v) for v in row.values()), row
    path = GOLDEN_DIR / f"{name}.json"
    if RECORD:
        GOLDEN_DIR.mkdir(exist_ok=True)
        path.write_text(json.dumps(row, indent=1, sort_keys=True))
        pytest.skip(f"recorded {path}")
    golden = json.loads(path.read_text())
    assert set(golden) == set(row), \
        f"column set changed: {set(golden) ^ set(row)}"
    for key, want in golden.items():
        if key in SKIP:
            continue
        got = row[key]
        assert abs(got - want) <= ATOL + RTOL * abs(want), \
            f"{name}:{key}: {got!r} != {want!r}"
