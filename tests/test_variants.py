"""The formerly-phantom catalogue variants are now real physics:
d2q9_new (raw-moment MRT + LES + entropic stabilizer),
d3q19_heat_adj_art (momentum-reversing artificial solid),
d3q19_heat_adj_prop (propagating design weight)."""

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model

pytestmark = pytest.mark.slow  # full-coverage job; the default lap runs the fast smoke suite


def _shear_layer(name_mode, n=48, niter=1000):
    m = get_model("d2q9_new")
    lat = Lattice(m, (n, n), dtype=jnp.float64,
                  settings={"nu": 1e-4, "Smag": 0.2, "SL_U": 0.05,
                            "SL_lambda": 80.0, "SL_delta": 0.1,
                            "SL_L": float(n)})
    flags = np.full((n, n), m.flag_for("MRT", *name_mode), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    lat.iterate(niter)
    u = np.asarray(lat.get_quantity("U"))
    return u


def test_d2q9_new_shear_layer_modes():
    """The under-resolved double shear layer at nu=1e-4 blows up in plain
    MRT but survives with the Smagorinsky or entropic stabilizer — the
    variant's entire reason to exist."""
    u_plain = _shear_layer(())
    u_les = _shear_layer(("Smagorinsky",))
    u_stab = _shear_layer(("Stab",))
    assert np.isfinite(u_les).all()
    assert np.isfinite(u_stab).all()
    vmax_les = np.abs(u_les).max()
    vmax_stab = np.abs(u_stab).max()
    assert vmax_les < 0.2 and vmax_stab < 0.2   # bounded, physical
    # plain MRT at this nu either diverges or develops much larger spurious
    # velocities than the stabilized runs
    blowup = (not np.isfinite(u_plain).all()) \
        or np.abs(u_plain).max() > 3 * max(vmax_les, vmax_stab)
    assert blowup, np.abs(u_plain).max()


def test_d2q9_new_viscosity_sanity():
    """At resolved viscosity the plain MRT path gives the standard Taylor-
    Green-like decay: kinetic energy decreases monotonically."""
    m = get_model("d2q9_new")
    n = 32
    lat = Lattice(m, (n, n), dtype=jnp.float64,
                  settings={"nu": 0.05, "SL_U": 0.02, "SL_lambda": 10.0,
                            "SL_delta": 0.02, "SL_L": float(n)})
    lat.set_flags(np.full((n, n), m.flag_for("MRT"), dtype=np.uint16))
    lat.init()
    e = []
    for _ in range(4):
        lat.iterate(200)
        u = np.asarray(lat.get_quantity("U"))
        e.append(float((u ** 2).sum()))
    assert np.isfinite(e).all()
    assert all(b < a for a, b in zip(e, e[1:])), e


def _heat_channel(name, w_val, niter=400):
    m = get_model(name)
    shape = (4, 10, 24)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "Velocity": 0.05,
                            "InletTemperature": 1.0, "InitTemperature": 0.0})
    flags = np.full(shape, m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0, :] = m.flag_for("Wall")
    flags[:, -1, :] = m.flag_for("Wall")
    flags[:, 1:-1, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, 1:-1, -1] = m.flag_for("EPressure", "MRT")
    lat.set_flags(flags)
    lat.init()
    # design field: a solid block mid-channel
    w = np.ones(shape)
    w[:, 3:7, 8:14] = w_val
    lat.set_density("w", w)
    lat.iterate(niter)
    return lat, np.asarray(lat.get_quantity("U"))


def test_art_momentum_factor_differs():
    """The _art variant's 2w-1 momentum factor: at w=0.5 it kills the
    post-collision momentum entirely (scale 0) where the base keeps half
    (scale 0.5) — art flow through a porous w=0.5 block is much weaker.
    At w=1 the two variants coincide exactly."""
    _, u_base = _heat_channel("d3q19_heat_adj", 0.5)
    _, u_art = _heat_channel("d3q19_heat_adj_art", 0.5)
    assert np.isfinite(u_base).all() and np.isfinite(u_art).all()
    blk = (slice(None), slice(3, 7), slice(8, 14))
    v_base = np.abs(u_base[0][blk]).mean()
    v_art = np.abs(u_art[0][blk]).mean()
    assert v_art < 0.5 * v_base, (v_art, v_base)
    _, ub1 = _heat_channel("d3q19_heat_adj", 1.0)
    _, ua1 = _heat_channel("d3q19_heat_adj_art", 1.0)
    np.testing.assert_allclose(ua1, ub1, atol=1e-12)


def test_prop_propagates_design_downstream():
    """With PropagateX > 0 and Propagate nodes, solid material (w=0)
    shades the nodes downstream (+x): the effective weight w0 drops behind
    the block, unlike the base variant."""
    m = get_model("d3q19_heat_adj_prop")
    shape = (4, 10, 24)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "Velocity": 0.02,
                            "PropagateX": 0.8,
                            "InletTemperature": 1.0,
                            "InitTemperature": 0.0})
    flags = np.full(shape, m.flag_for("MRT", "Propagate"), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    w = np.ones(shape)
    w[:, 4:6, 6:8] = 0.0
    lat.set_density("w", w)
    # 10 steps: the +x shade reaches x ~ 18 without wrapping the
    # periodic domain back to the upstream probe
    lat.iterate(10)
    w0 = np.asarray(lat.get_density("w0"))
    assert np.isfinite(w0).all()
    # downstream of the block (x > 8) the propagated weight is depressed
    assert w0[2, 5, 10] < 0.8, w0[2, 5, 10]
    # far upstream it stays 1
    np.testing.assert_allclose(w0[2, 5, 2], 1.0, atol=1e-6)
    # MaterialPenalty global exists and is finite
    g = lat.get_globals()
    assert "MaterialPenalty" in g and np.isfinite(g["MaterialPenalty"])
