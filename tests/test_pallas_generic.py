"""Parity of the registry-driven generic Pallas engine vs the XLA step.

The generic engine (ops/pallas_generic.py) traces every model's OWN stage
functions inside a Pallas band kernel — the round-4 equivalent of the
reference guarantee that its code generator emits a tuned kernel for every
model (reference src/cuda.cu.Rt:81-283).  Because kernel and XLA path run
the SAME physics callables, parity must be essentially exact; these tests
pin it over all eligible 2D models, multi-stage actions, Field stencils,
zonal settings and the ghost-row padded path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.core.lattice import Lattice, make_iterate
from tclb_tpu.models import get_model, list_models
from tclb_tpu.ops import pallas_generic
from tclb_tpu.ops.lbm import present_types

# models with enough default-settings stability for a short parity lap;
# the full sweep below covers the rest
_KEY_MODELS = ["d2q9_heat", "d2q9_kuper", "d2q9_pf", "d2q9_adj"]

_SETTINGS = {
    "d2q9_heat": {"nu": 0.05, "InletVelocity": 0.02, "FluidAlfa": 0.05},
    "d2q9_heat_adj": {"nu": 0.05, "InletVelocity": 0.02,
                      "FluidAlfa": 0.05},
    "d2q9_heat_conjugate": {"nu": 0.05, "InletVelocity": 0.02,
                            "FluidAlfa": 0.05, "SolidAlfa": 0.02},
    "d2q9_kuper": {"nu": 0.1, "Temperature": 0.9, "Magic": 0.01,
                   "Density": 1.0},
    "d2q9_kuper_adj": {"nu": 0.1, "Temperature": 0.9, "Magic": 0.01,
                       "Density": 1.0},
    "d2q9_pf": {"nu": 0.1, "Velocity": 0.01},
    "d2q9_adj": {"nu": 0.05, "Velocity": 0.02},
    "d2q9": {"nu": 0.05, "Velocity": 0.02},
    "d2q9_lee": {"nu": 1 / 6, "LiquidDensity": 1.0,
                 "VaporDensity": 0.1, "Beta": 0.02, "Kappa": 0.02,
                 "InitDensity": 1.0, "WallDensity": 1.0},
    "d2q9_pp_MCMP": {"nu": 1 / 6, "nu_g": 1 / 6, "Gc": 1.8,
                     "Gad1": 0.0, "Gad2": 0.0,
                     "Density": 1.0, "Density_dry": 1.0},
    "d2q9_pp_LBL": {"nu": 1 / 6, "Density": 0.5, "T": 0.35},
    "sw": {"nu": 0.05},
}


def _eligible_2d():
    out = []
    for name in list_models():
        m = get_model(name)
        if m.ndim != 2:
            continue
        if pallas_generic.supports(m, (16, 64), jnp.float32):
            out.append(name)
    return out


def _paint(m, ny, nx):
    """Generic geometry: collision interior, walls top/bottom, W/E BCs
    when the model declares them, and a second settings zone."""
    coll = "MRT" if "MRT" in m.node_types else "BGK"
    flags = np.full((ny, nx), m.flag_for(coll), dtype=np.uint16)
    if "Wall" in m.node_types:
        flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    if "WVelocity" in m.node_types:
        flags[1:-1, 0] = m.flag_for("WVelocity", coll)
    if "EPressure" in m.node_types:
        flags[1:-1, -1] = m.flag_for("EPressure", coll)
    # a zone stripe exercises zonal-setting gathering
    flags[ny // 4:ny // 2, nx // 4:nx // 2] = m.flag_for(coll, zone=1)
    return flags


def _parity(name, ny=16, nx=64, niter=6, atol=1e-5):
    m = get_model(name)
    assert pallas_generic.supports(m, (ny, nx), jnp.float32), name
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings=_SETTINGS.get(name, {}))
    flags = _paint(m, ny, nx)
    lat.set_flags(flags)
    lat.init()
    present = present_types(m, flags)

    it_p = pallas_generic.make_pallas_iterate(
        m, (ny, nx), jnp.float32, interpret=True, present=present)
    s_p = it_p(jax.tree.map(jnp.copy, lat.state), lat.params, niter)

    it_x = jax.jit(make_iterate(m, present=present),
                   static_argnames=("niter",))
    s_x = it_x(lat.state, lat.params, niter)

    a = np.asarray(s_p.fields)
    b = np.asarray(s_x.fields)
    assert np.isfinite(b).all(), f"{name}: XLA reference went non-finite"
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=atol,
                               err_msg=f"{name} generic-pallas vs XLA")
    assert int(s_p.iteration) == int(s_x.iteration)


@pytest.mark.parametrize("name", _KEY_MODELS)
def test_generic_parity_key_models(name):
    """Fast-lap pin: the VERDICT r3 headline models (multi-lattice heat,
    Field-stencil kuper, 18-plane pf, adjoint-primal adj) match the XLA
    engine through the generic band kernel."""
    _parity(name)


@pytest.mark.slow
@pytest.mark.parametrize("name", [n for n in _eligible_2d()
                                  if n not in _KEY_MODELS])
def test_generic_parity_all(name):
    """Every trace-eligible 2D model matches the XLA engine."""
    _parity(name)


def test_generic_padded_height():
    """ny % 8 != 0 runs via mirror-ghost padding and stays exact (the
    generalized reach-m scheme of pallas_generic._pad_rows)."""
    _parity("d2q9_heat", ny=20, nx=64)


def test_generic_multistage_field_stencil():
    """kuper's two-stage action (Run + CalcPhi) with the phi +-1 Field
    stencil — the in-band stage pipeline must reproduce the XLA stage
    composition including the inter-stage phi refresh."""
    _parity("d2q9_kuper", ny=24, nx=64, niter=8)


def test_engine_dispatch_generic(monkeypatch):
    """Lattice.iterate auto-selects the generic engine for a model the
    tuned d2q9 kernels don't cover (TCLB_FASTPATH=force exercises the
    dispatch under interpret mode on CPU)."""
    monkeypatch.setenv("TCLB_FASTPATH", "force")
    m = get_model("d2q9_heat")
    lat = Lattice(m, (16, 64), dtype=jnp.float32,
                  settings=_SETTINGS["d2q9_heat"])
    lat.set_flags(_paint(m, 16, 64))
    lat.init()
    lat.iterate(5)
    # the fuse depth comes from the shared traffic planner (>= 2 at this
    # reach), so the tag tracks choose_fuse instead of a pinned constant
    fz = pallas_generic.choose_fuse(m)
    assert fz >= 2
    assert lat._fast_name == f"pallas_generic[d2q9_heat,fuse={fz}]"
    assert np.isfinite(np.asarray(lat.state.fields)).all()
    # globals refreshed by the hybrid's trailing XLA step
    g = lat.get_globals()
    assert "OutFlux" in g


def test_supports_structure():
    m = get_model("d2q9_heat")
    assert pallas_generic.supports(m, (16, 64), jnp.float32)
    assert not pallas_generic.supports(m, (16, 64), jnp.float64)
    assert not pallas_generic.supports(m, (4, 64), jnp.float32)
    # 3D models route to the z-slab engine (since round 4)
    assert pallas_generic.supports(get_model("d3q27_cumulant"),
                                   (16, 16, 64), jnp.float32)
    assert not pallas_generic.supports(get_model("d3q27_cumulant"),
                                       (16, 16, 64), jnp.float64)


def test_inkernel_globals_match_xla():
    """The generic engine's full contract: iterate() returns the LAST
    step's SUM Globals from the in-kernel accumulation (no trailing XLA
    step), matching the XLA engine's reductions (nx=128 — the partial-
    sums output needs whole lanes)."""
    name, ny, nx, niter = "d2q9", 16, 128, 6
    m = get_model(name)
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings=_SETTINGS[name])
    flags = _paint(m, ny, nx)
    flags[1:-1, 2] = m.flag_for("MRT", "Inlet")
    flags[1:-1, -3] = m.flag_for("MRT", "Outlet")
    lat.set_flags(flags)
    lat.init()
    present = present_types(m, flags)

    it_p = pallas_generic.make_pallas_iterate(
        m, (ny, nx), jnp.float32, interpret=True, present=present)
    assert it_p.full_globals
    s_p = it_p(jax.tree.map(jnp.copy, lat.state), lat.params, niter)

    it_x = jax.jit(make_iterate(m, present=present),
                   static_argnames=("niter",))
    s_x = it_x(lat.state, lat.params, niter)
    np.testing.assert_allclose(np.asarray(s_p.fields),
                               np.asarray(s_x.fields), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(s_p.globals_),
                               np.asarray(s_x.globals_),
                               rtol=1e-4, atol=1e-6)
    assert float(np.abs(np.asarray(s_x.globals_)).sum()) > 0.0, \
        "vacuous: the case must actually accumulate globals"


def test_inkernel_globals_padded_height():
    """Ghost-row padding must not leak mirror/wall rows into the Globals
    (the in-kernel row mask)."""
    name, ny, nx, niter = "d2q9", 20, 128, 5
    m = get_model(name)
    lat = Lattice(m, (ny, nx), dtype=jnp.float32, settings=_SETTINGS[name])
    flags = _paint(m, ny, nx)
    flags[1:-1, 2] = m.flag_for("MRT", "Inlet")
    flags[1:-1, -3] = m.flag_for("MRT", "Outlet")
    lat.set_flags(flags)
    lat.init()
    present = present_types(m, flags)
    it_p = pallas_generic.make_pallas_iterate(
        m, (ny, nx), jnp.float32, interpret=True, present=present)
    s_p = it_p(jax.tree.map(jnp.copy, lat.state), lat.params, niter)
    it_x = jax.jit(make_iterate(m, present=present),
                   static_argnames=("niter",))
    s_x = it_x(lat.state, lat.params, niter)
    np.testing.assert_allclose(np.asarray(s_p.globals_),
                               np.asarray(s_x.globals_),
                               rtol=1e-4, atol=1e-6)


def test_control_series_on_fast_path(monkeypatch):
    """A <Control> time series (per-iteration zonal settings) now runs
    the generic engine (the series kernel flavor gathers value + _DT
    planes per step) and matches the XLA path exactly."""
    ny, nx, niter = 16, 64, 7
    m = get_model("d2q9")
    series = 0.02 + 0.005 * np.sin(np.arange(11) * 0.7)

    def build():
        lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                      settings=_SETTINGS["d2q9"])
        lat.set_flags(_paint(m, ny, nx))
        lat.init()
        lat.set_setting_series("Velocity", series, zone=0)
        return lat

    monkeypatch.setenv("TCLB_FASTPATH", "0")
    ref = build()
    ref.iterate(niter)

    monkeypatch.setenv("TCLB_FASTPATH", "force")
    fast = build()
    fast.iterate(niter)
    assert fast._fast_name is not None and "pallas_generic" in fast._fast_name
    np.testing.assert_allclose(np.asarray(fast.state.fields),
                               np.asarray(ref.state.fields),
                               rtol=1e-5, atol=1e-6)
    assert int(fast.state.iteration) == int(ref.state.iteration)


def test_control_series_with_inkernel_globals(monkeypatch):
    """The combined series + globals kernel flavor (call_sg): at nx=128
    the engine runs the full contract under a Control series — fields
    AND last-step Globals must match the XLA path."""
    ny, nx, niter = 16, 128, 6
    m = get_model("d2q9")
    series = 0.02 + 0.004 * np.sin(np.arange(9) * 0.9)

    def build():
        lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                      settings=_SETTINGS["d2q9"])
        flags = _paint(m, ny, nx)
        flags[1:-1, 2] = m.flag_for("MRT", "Inlet")
        flags[1:-1, -3] = m.flag_for("MRT", "Outlet")
        lat.set_flags(flags)
        lat.init()
        lat.set_setting_series("Velocity", series, zone=0)
        return lat

    monkeypatch.setenv("TCLB_FASTPATH", "0")
    ref = build()
    ref.iterate(niter)

    monkeypatch.setenv("TCLB_FASTPATH", "force")
    fast = build()
    fast.iterate(niter)
    assert "pallas_generic" in (fast._fast_name or "")
    assert getattr(fast._fast, "full_globals", False)
    np.testing.assert_allclose(np.asarray(fast.state.fields),
                               np.asarray(ref.state.fields),
                               rtol=1e-5, atol=1e-6)
    g_ref, g_fast = ref.get_globals(), fast.get_globals()
    for k in g_ref:
        np.testing.assert_allclose(g_fast[k], g_ref[k], rtol=1e-4,
                                   atol=1e-6, err_msg=k)
    assert sum(abs(v) for v in g_ref.values()) > 0.0


def test_action_plan_reach():
    """Stage plan arithmetic: kuper's Run (pull 1 + phi stencil 1) then
    CalcPhi (pointwise) needs a 1-row input halo with CalcPhi running on
    the plain band; heat's single stage pulls reach 1."""
    m = get_model("d2q9_kuper")
    plan, reach = pallas_generic.action_plan(m, "Iteration", fuse=1)
    assert [s for s, _ in plan] == ["BaseIteration", "CalcPhi"]
    # CalcPhi is last (out_ext 0); Run must cover CalcPhi's pointwise
    # read of the f it stores -> out_ext 0 as well; input halo = Run's
    # own reach
    assert plan[-1][1] == 0
    assert reach == plan[0][1] + 1

    m2 = get_model("d2q9_heat")
    plan2, reach2 = pallas_generic.action_plan(m2, "Iteration", fuse=1)
    assert plan2 == [("BaseIteration", 0)]
    assert reach2 == 1


# ------------------------------------------------------------------------- #
# 3D generic engine
# ------------------------------------------------------------------------- #

_3D_SETTINGS = {
    "d3q19_heat": {"nu": 0.05, "Velocity": 0.02, "FluidAlfa": 0.05},
    "d3q19_heat_adj": {"nu": 0.05, "Velocity": 0.02, "FluidAlfa": 0.05},
    "d3q19_heat_adj_art": {"nu": 0.05, "Velocity": 0.02, "FluidAlfa": 0.05},
    "d3q19_heat_adj_prop": {"nu": 0.05, "Velocity": 0.02,
                            "FluidAlfa": 0.05},
    "d3q19_kuper": {"nu": 0.1, "Temperature": 0.9, "Magic": 0.01},
    "d3q19_adj": {"nu": 0.1, "Velocity": 0.02, "Porocity": 0.5},
    "d3q19_les": {"nu": 0.01, "Smag": 0.16},
    "d3q27_cumulant": {"nu": 0.01, "ForceX": 1e-5},
    "d3q27_viscoplastic": {"nu": 0.1},
}


def _eligible_3d(shape=(6, 16, 128)):
    out = []
    for name in list_models():
        m = get_model(name)
        if m.ndim == 3 and pallas_generic.supports_3d(m, shape, jnp.float32):
            out.append(name)
    return out


def _parity_3d(name, shape=(6, 16, 128), niter=4):
    m = get_model(name)
    lat = Lattice(m, shape, dtype=jnp.float32,
                  settings=_3D_SETTINGS.get(name, {}))
    coll = "MRT" if "MRT" in m.node_types else "BGK"
    flags = np.full(shape, m.flag_for(coll), dtype=np.uint16)
    flags[:, 0, :] = flags[:, -1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    present = present_types(m, flags)
    it_p = pallas_generic.make_pallas_iterate(
        m, shape, jnp.float32, interpret=True, present=present)
    s_p = it_p(jax.tree.map(jnp.copy, lat.state), lat.params, niter)
    it_x = jax.jit(make_iterate(m, present=present),
                   static_argnames=("niter",))
    s_x = it_x(lat.state, lat.params, niter)
    b = np.asarray(s_x.fields)
    assert np.isfinite(b).all(), f"{name}: XLA reference went non-finite"
    np.testing.assert_allclose(np.asarray(s_p.fields), b,
                               rtol=1e-5, atol=1e-5, err_msg=name)
    np.testing.assert_allclose(np.asarray(s_p.globals_),
                               np.asarray(s_x.globals_),
                               rtol=1e-3, atol=1e-5, err_msg=name)


@pytest.mark.parametrize("name", ["d3q19_heat", "d3q19_kuper"])
def test_generic3d_parity_key_models(name):
    """Fast-lap pin: 3D multi-lattice (heat) and Field-stencil (kuper)
    models on the z-slab generic engine."""
    _parity_3d(name)


@pytest.mark.slow
@pytest.mark.parametrize("name", [n for n in _eligible_3d()
                                  if n not in ("d3q19_heat", "d3q19_kuper")])
def test_generic3d_parity_all(name):
    """Every trace-eligible 3D model matches the XLA engine."""
    _parity_3d(name)


def test_generic3d_halo_straddle():
    """bz=1 with reach 2 (kuper's field stencil under a fused plan): the
    per-slab halo copies must wrap the periodic boundary slab by slab —
    a block copy starting at (base - R) mod nz would read out of bounds
    (the bug that NaN'd d3q19_kuper at 48x48x256 on TPU)."""
    _parity_3d("d3q19_kuper", shape=(12, 16, 128), niter=4)


def test_sharded_generic_matches_single(monkeypatch):
    """The generic kernel as the sharded building block: a y-sharded
    2-device mesh running d2q9_heat (a model the tuned sharded kernels
    do not cover) matches the single-device engine."""
    import jax
    from tclb_tpu.parallel.mesh import make_mesh
    ny, nx, niter = 32, 64, 9

    monkeypatch.setenv("TCLB_FASTPATH", "0")
    m = get_model("d2q9_heat")
    ref = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings=_SETTINGS["d2q9_heat"])
    flags = _paint(m, ny, nx)
    ref.set_flags(flags)
    ref.init()
    ref.iterate(niter)

    monkeypatch.setenv("TCLB_FASTPATH", "force")
    mesh = make_mesh((ny, nx), devices=jax.devices()[:2],
                     decomposition={"y": 2, "x": 1})
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings=_SETTINGS["d2q9_heat"], mesh=mesh)
    lat.set_flags(flags)
    lat.init()
    lat.iterate(niter)
    assert lat._fast_name is not None and "sharded" in lat._fast_name
    np.testing.assert_allclose(np.asarray(lat.state.fields),
                               np.asarray(ref.state.fields),
                               rtol=1e-5, atol=1e-6)


# --------------------------------------------------------------------- #
# deep temporal fusion (tier-1): K in {4, 8} bit-exact vs XLA
# --------------------------------------------------------------------- #


@pytest.mark.parametrize("name,K", [
    ("d2q9_heat", 4), ("d2q9_heat", 8),
    # kuper: reach 2/step (the CalcPhi gradient stencil), so fuse=4
    # saturates the 8-row band halo — this IS the fused Run+CalcPhi
    # deep-fusion case (phi rebuilt in-VMEM, no second HBM pass)
    ("d2q9_kuper", 4),
])
def test_fused_deep_bit_exact(name, K):
    """fuse=K band output is BIT-IDENTICAL (assert_array_equal, not
    allclose) to the same engine unfused: the progressive-extension
    windows replay each step's arithmetic exactly, so any reassociation
    or halo slip at the deeper depths fails at == level.  (The engine's
    parity vs the XLA step is the existing allclose contract `_parity`
    pins — the zonal where-chain reassociates by ~1 ulp.)"""
    ny, nx = 16, 64
    m = get_model(name)
    lat = Lattice(m, (ny, nx), dtype=jnp.float32,
                  settings=_SETTINGS[name])
    flags = _paint(m, ny, nx)
    lat.set_flags(flags)
    lat.init()
    present = present_types(m, flags)

    # K + 2 forces one fused chunk plus remainder single steps
    niter = K + 2
    it_p = pallas_generic.make_pallas_iterate(
        m, (ny, nx), jnp.float32, interpret=True, present=present,
        fuse=K)
    s_p = it_p(jax.tree.map(jnp.copy, lat.state), lat.params, niter)
    it_1 = pallas_generic.make_pallas_iterate(
        m, (ny, nx), jnp.float32, interpret=True, present=present,
        fuse=1)
    s_1 = it_1(jax.tree.map(jnp.copy, lat.state), lat.params, niter)
    np.testing.assert_array_equal(np.asarray(s_p.fields),
                                  np.asarray(s_1.fields))
    assert int(s_p.iteration) == int(s_1.iteration)
    # and the fused output still matches the XLA step at the engine's
    # established allclose tolerance
    it_x = jax.jit(make_iterate(m, present=present),
                   static_argnames=("niter",))
    s_x = it_x(lat.state, lat.params, niter)
    np.testing.assert_allclose(np.asarray(s_p.fields),
                               np.asarray(s_x.fields),
                               rtol=1e-5, atol=1e-5)


def test_choose_fuse_deep_depths():
    """The planner now extends past 2: reach-1 models saturate FUSE_MAX
    (8) and kuper's reach-2 plan caps at 4 (reach 8 == the band halo)."""
    assert pallas_generic.choose_fuse(get_model("d2q9_heat")) == 8
    assert pallas_generic.choose_fuse(get_model("d2q9_kuper")) == 4


# --------------------------------------------------------------------- #
# precision ladder: bf16 storage through the generic engines
# --------------------------------------------------------------------- #


def test_storage_dtype_is_opt_in():
    """Never silently narrowed: the default Lattice stores in the
    compute dtype, and non-float / widening storage dtypes are
    rejected up front."""
    m = get_model("d2q9_heat")
    lat = Lattice(m, (16, 64), dtype=jnp.float32,
                  settings=_SETTINGS["d2q9_heat"])
    assert lat.storage_dtype == jnp.dtype(jnp.float32)
    assert lat.state.fields.dtype == jnp.dtype(jnp.float32)
    with pytest.raises(ValueError, match="storage_dtype"):
        Lattice(m, (16, 64), dtype=jnp.float32, storage_dtype=jnp.int8,
                settings=_SETTINGS["d2q9_heat"])
    with pytest.raises(ValueError, match="storage_dtype"):
        Lattice(m, (16, 64), dtype=jnp.float32,
                storage_dtype=jnp.float64,
                settings=_SETTINGS["d2q9_heat"])


def test_storage_dtype_bf16_xla_close_to_f32():
    """bf16 storage on the XLA path: fields stay bf16 across iterate,
    compute happens in f32 (error stays at bf16-rounding scale instead
    of compounding catastrophically)."""
    m = get_model("d2q9_heat")

    def run(storage_dtype):
        lat = Lattice(m, (16, 64), dtype=jnp.float32,
                      settings=_SETTINGS["d2q9_heat"],
                      storage_dtype=storage_dtype)
        lat.set_flags(_paint(m, 16, 64))
        lat.init()
        lat.iterate(20)
        return lat

    ref = run(None)
    alt = run(jnp.bfloat16)
    assert alt.state.fields.dtype == jnp.dtype(jnp.bfloat16)
    # compare in the raw representation: the bf16 rung defaults to
    # shifted at-rest storage (f_i - w_i), so the raw stacks are the
    # representation-independent physics
    a = alt.fields_raw()
    b = ref.fields_raw()
    assert np.isfinite(a).all()
    denom = max(float(np.max(np.abs(b))), 1e-30)
    assert float(np.max(np.abs(a - b))) / denom < 2e-2


def test_storage_dtype_bf16_band_matches_xla_cast_path():
    """The generic band kernel under bf16 storage (widen-on-read,
    f32 accumulate, narrow-on-write) matches the XLA narrowed-carry
    reference bit-for-bit: both paths run f32 arithmetic between
    identical bf16 round trips."""
    m = get_model("d2q9_heat")
    lat = Lattice(m, (16, 64), dtype=jnp.float32,
                  settings=_SETTINGS["d2q9_heat"],
                  storage_dtype=jnp.bfloat16)
    flags = _paint(m, 16, 64)
    lat.set_flags(flags)
    lat.init()
    present = present_types(m, flags)
    niter = 6

    it_p = pallas_generic.make_pallas_iterate(
        m, (16, 64), jnp.bfloat16, interpret=True, present=present)
    s_p = it_p(jax.tree.map(jnp.copy, lat.state), lat.params, niter)
    assert s_p.fields.dtype == jnp.dtype(jnp.bfloat16)

    it_x = jax.jit(make_iterate(m, present=present,
                                storage_dtype=jnp.bfloat16),
                   static_argnames=("niter",))
    s_x = it_x(lat.state, lat.params, niter)
    np.testing.assert_array_equal(
        np.asarray(s_p.fields, dtype=np.float32),
        np.asarray(s_x.fields, dtype=np.float32))


def test_bf16_dispatch_skips_f32_only_kernels(monkeypatch, tmp_path):
    """Engine dispatch under bf16 storage routes past the f32-only tuned
    d2q9 kernels to a narrowed-capable engine, and stamps the storage
    dtype on iterate spans (telemetry attribution must not overstate
    bf16 runs' bytes)."""
    import json as _json
    from tclb_tpu import telemetry
    monkeypatch.setenv("TCLB_FASTPATH", "force")
    m = get_model("d2q9")
    lat = Lattice(m, (16, 64), dtype=jnp.float32,
                  settings=_SETTINGS["d2q9"],
                  storage_dtype=jnp.bfloat16)
    lat.set_flags(_paint(m, 16, 64))
    lat.init()
    trace = tmp_path / "t.jsonl"
    telemetry.enable(str(trace))
    try:
        lat.iterate(2)
    finally:
        telemetry.disable()
    assert lat._fast_name is not None
    assert "generic" in lat._fast_name   # tuned d2q9 kernels are f32-only
    evts = [_json.loads(x) for x in trace.read_text().splitlines()
            if x.strip()]
    spans = [e for e in evts
             if e.get("kind") == "span" and e.get("name") == "iterate"]
    assert spans and spans[0]["storage_dtype"] == "bfloat16"
    # actual bytes per node: 2 x n_storage x 2 (bf16) + flag read
    assert spans[0]["bytes_per_node"] == 2 * m.n_storage * 2 + 2
