"""Process-isolated worker pool tests: the frame protocol, supervisor
watchdog (hang detection, SIGTERM->SIGKILL escalation, crash-loop
backoff, job requeue), blast-radius containment with sibling lanes,
seeded ``pool.*`` fault schedules, the FleetDispatcher
``process_isolation`` route, and (slow) kill-resume bit-identity through
real solver workers and the ``--workers`` gateway CLI with SIGTERM
drain.

The fast supervisor tests drive a STUB worker — a plain-python script
speaking the frame protocol with none of the solver imports — so hang /
crash / requeue logic runs in milliseconds; real-worker coverage
(which pays the jax import at spawn) is the slow half.
"""

import io
import json
import os
import signal
import struct
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tclb_tpu import faults, telemetry
from tclb_tpu.serve.pool import PoolJobError, WorkerPool
from tclb_tpu.serve.retry import RetryPolicy
from tclb_tpu.serve.worker import (IpcError, npy_bytes, npy_load,
                                   read_frame, write_frame)
from tclb_tpu.telemetry import live

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    telemetry.disable()
    live.registry().reset()
    faults.uninstall()
    yield
    faults.uninstall()
    telemetry.disable()
    live.registry().reset()


# --------------------------------------------------------------------------- #
# Frame protocol
# --------------------------------------------------------------------------- #


def test_frame_roundtrip_with_payload():
    buf = io.BytesIO()
    arr = np.arange(12, dtype=np.float64).reshape(3, 4)
    doc = {"t": "result", "id": "pj-1", "ok": True,
           "globals": {"drag": np.float64(1.5)}}
    write_frame(buf, doc, npy_bytes(arr))
    buf.seek(0)
    got, payload = read_frame(buf)
    assert got["t"] == "result" and got["globals"]["drag"] == 1.5
    np.testing.assert_array_equal(npy_load(payload), arr)
    with pytest.raises(EOFError):          # clean close at a boundary
        read_frame(buf)


def test_frame_rejects_torn_and_malformed():
    buf = io.BytesIO()
    write_frame(buf, {"t": "hb"})
    torn = io.BytesIO(buf.getvalue()[:-2])  # truncated mid-body
    with pytest.raises(IpcError):
        read_frame(torn)
    with pytest.raises(IpcError):           # oversized header
        read_frame(io.BytesIO(struct.pack("!II", 1 << 31, 0)))
    body = json.dumps([1, 2]).encode()      # non-object body
    with pytest.raises(IpcError):
        read_frame(io.BytesIO(struct.pack("!II", len(body), 0) + body))


def test_npy_payload_never_pickles():
    with pytest.raises(ValueError):
        npy_bytes(np.array([object()], dtype=object))


# --------------------------------------------------------------------------- #
# Supervisor logic against a stub worker (no solver imports: fast spawns)
# --------------------------------------------------------------------------- #

STUB_WORKER = """
import json, os, struct, sys, time
H = struct.Struct("!II")
out = os.fdopen(os.dup(1), "wb")
os.dup2(2, 1)
inp = os.fdopen(os.dup(0), "rb")
lane = int(sys.argv[sys.argv.index("--lane") + 1])

def send(doc):
    body = json.dumps(doc).encode()
    out.write(H.pack(len(body), 0)); out.write(body); out.flush()

def recv():
    h = inp.read(H.size)
    if len(h) < H.size:
        raise EOFError
    bl, pl = H.unpack(h)
    doc = json.loads(inp.read(bl).decode())
    inp.read(pl)
    return doc

send({"t": "ready", "pid": os.getpid(), "lane": lane})
while True:
    try:
        doc = recv()
    except EOFError:
        sys.exit(0)
    if doc.get("t") == "shutdown":
        sys.exit(0)
    if doc.get("t") != "job":
        continue
    jid, spec = doc["id"], doc.get("spec") or {}
    mode = spec.get("behave", "ok")
    flag = spec.get("once_flag")
    if flag:                       # misbehave only on the FIRST attempt
        if os.path.exists(flag):
            mode = "ok"
        else:
            open(flag, "w").close()
    send({"t": "hb", "id": jid})
    if mode == "crash":
        os._exit(3)
    if mode == "wedge":
        time.sleep(3600)
    if mode == "error":
        send({"t": "result", "id": jid, "ok": False, "error": "boom"})
        continue
    send({"t": "result", "id": jid, "ok": True, "lane": lane,
          "pid": os.getpid(), "globals": {"x": 1.0},
          "iteration": spec.get("niter", 0)})
"""


@pytest.fixture()
def stub_cmd(tmp_path):
    script = tmp_path / "stub_worker.py"
    script.write_text(STUB_WORKER)
    return [sys.executable, str(script)]


def _fast_pool(stub_cmd, **kw):
    kw.setdefault("workers", 1)
    kw.setdefault("heartbeat_timeout_s", 3.0)
    kw.setdefault("spawn_timeout_s", 30.0)
    kw.setdefault("term_grace_s", 0.5)
    kw.setdefault("stable_after_s", 0.2)
    kw.setdefault("retry_policy",
                  RetryPolicy(max_attempts=4, base_delay_s=0.02,
                              max_delay_s=0.1))
    return WorkerPool(worker_cmd=stub_cmd, autostart=False, **kw)


def test_pool_serves_jobs_across_lanes(stub_cmd):
    with _fast_pool(stub_cmd, workers=2) as pool:
        jobs = pool.run([{"behave": "ok", "niter": i} for i in range(6)],
                        timeout=60)
        assert all(j.status == "done" for j in jobs)
        assert {j._result["iteration"] for j in jobs} == set(range(6))
        # which lane serves a given job is a queue race (under load one
        # lane can drain everything), but both lanes must be up and every
        # job must have been served by a real lane
        assert {j._result["lane"] for j in jobs} <= {0, 1}
        deadline = time.monotonic() + 30
        while pool.live_workers() < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pool.live_workers() == 2
        st = pool.stats()
        assert st["done"] == 6 and st["failed"] == 0
    assert pool.live_workers() == 0     # closed pool has no live lanes


def test_hung_worker_detected_killed_and_job_retried(stub_cmd, tmp_path):
    """A worker that stops beating mid-job is declared hung within the
    heartbeat timeout, killed (SIGTERM->SIGKILL escalation), and the job
    re-queued onto the respawned worker, where it completes."""
    flag = tmp_path / "wedged-once"
    with _fast_pool(stub_cmd, heartbeat_timeout_s=0.6) as pool:
        job = pool.submit({"behave": "wedge", "once_flag": str(flag)})
        res = job.result(timeout=60)
        assert res["globals"] == {"x": 1.0}
        assert job.attempts == 2
        st = pool.stats()
        assert st["requeued"] == 1 and st["restarts"] >= 1
        snap = pool._status()
        assert snap["workers"][0]["restarts"] >= 1


def test_crashed_worker_requeues_then_fails_permanently(stub_cmd):
    """A worker that dies mid-job re-queues the job up to job_attempts;
    a job that kills every worker it touches fails terminally with the
    attempt count in the error — never silently dropped."""
    with _fast_pool(stub_cmd, job_attempts=2) as pool:
        job = pool.submit({"behave": "crash"})
        with pytest.raises(PoolJobError, match="after 2 attempts"):
            job.result(timeout=60)
        assert pool.stats()["requeued"] == 1


def test_worker_reported_error_fails_job_without_respawn(stub_cmd):
    """An ok=False result is a *job* verdict (bad spec, solver raise) —
    the job fails once, the worker lives on, nothing is re-queued."""
    with _fast_pool(stub_cmd) as pool:
        job = pool.submit({"behave": "error"})
        with pytest.raises(PoolJobError, match="boom"):
            job.result(timeout=60)
        assert job.attempts == 1
        ok = pool.submit({"behave": "ok"})
        assert ok.result(timeout=60)["globals"] == {"x": 1.0}
        st = pool.stats()
        assert st["requeued"] == 0 and st["restarts"] == 0


def test_wedged_lane_never_stalls_siblings(stub_cmd, tmp_path):
    """Blast radius: with two lanes and one permanently wedging job, the
    sibling lane keeps serving the backlog; the wedged job fails after
    its attempts and both lanes end up live again."""
    with _fast_pool(stub_cmd, workers=2, heartbeat_timeout_s=0.6,
                    job_attempts=2) as pool:
        wedge = pool.submit({"behave": "wedge"})
        oks = [pool.submit({"behave": "ok", "niter": i})
               for i in range(8)]
        for j in oks:
            assert j.result(timeout=60)["globals"] == {"x": 1.0}
        with pytest.raises(PoolJobError):
            wedge.result(timeout=60)
        deadline = time.time() + 30
        while pool.live_workers() < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert pool.live_workers() == 2


def test_crash_loop_backoff_marks_lane_dead(tmp_path):
    """A worker that can never start exhausts the RetryPolicy spawn
    ladder; the lane is marked dead and queued + later submissions fail
    fast instead of stranding their waiters."""
    script = tmp_path / "dud.py"
    script.write_text("import sys; sys.exit(13)\n")
    pool = WorkerPool(workers=1, worker_cmd=[sys.executable, str(script)],
                      spawn_timeout_s=5.0,
                      retry_policy=RetryPolicy(max_attempts=2,
                                               base_delay_s=0.02,
                                               max_delay_s=0.05),
                      autostart=False)
    try:
        job = pool.submit({"behave": "ok"})
        with pytest.raises(PoolJobError, match="dead"):
            job.result(timeout=60)
        late = pool.submit({"behave": "ok"})
        with pytest.raises(PoolJobError, match="dead"):
            late.result(timeout=10)
    finally:
        pool.close()


def test_pool_registers_status_provider(stub_cmd):
    with _fast_pool(stub_cmd) as pool:
        pool.run([{"behave": "ok"}], timeout=60)
        snap = live.status_snapshot()
        assert "pool" in snap
        w = snap["pool"]["workers"][0]
        assert w["state"] in ("idle", "busy") and w["pid"] is not None
        assert snap["pool"]["jobs"]["done"] == 1
    assert "pool" not in live.status_snapshot()


# --------------------------------------------------------------------------- #
# Seeded fault schedules, supervisor side (pool.spawn / pool.ipc)
# --------------------------------------------------------------------------- #


def test_fault_plan_to_spec_roundtrip():
    spec = ("seed=7;pool.spawn:error:n=1;pool.ipc:error:p=0.5:after=2;"
            "checkpoint.write:slow:delay=0.2")
    plan = faults.FaultPlan.parse(spec)
    assert faults.FaultPlan.parse(plan.to_spec()) == plan
    faults.install(plan)
    try:
        assert faults.current_spec() == plan.to_spec()
    finally:
        faults.uninstall()
    assert faults.current_spec() is None


def test_pool_spawn_fault_retried_under_policy(stub_cmd):
    """An injected spawn failure rides the crash-loop RetryPolicy: the
    first attempt fails, the retry succeeds, the pool serves."""
    faults.install(faults.FaultPlan.parse("seed=3;pool.spawn:error:n=1"))
    with _fast_pool(stub_cmd) as pool:
        job = pool.submit({"behave": "ok"})
        assert job.result(timeout=60)["globals"] == {"x": 1.0}
    assert faults.stats()["injected"][0]["count"] == 1


def test_pool_ipc_fault_requeues_job(stub_cmd):
    """A torn pipe on the job send (injected at pool.ipc) reaps the
    worker and re-queues the job; the respawn completes it."""
    faults.install(faults.FaultPlan.parse("seed=5;pool.ipc:error:n=1"))
    with _fast_pool(stub_cmd) as pool:
        job = pool.submit({"behave": "ok"})
        assert job.result(timeout=60)["globals"] == {"x": 1.0}
        assert job.attempts == 2
        assert pool.stats()["requeued"] == 1


# --------------------------------------------------------------------------- #
# Real solver workers (pay the jax import per spawn)
# --------------------------------------------------------------------------- #

_SOLVE_DOC = {"model": "d2q9", "shape": [8, 16], "niter": 20,
              "params": {"nu": 0.05}, "digest": True,
              "case": {"name": "t", "settings": {}}}


@pytest.mark.slow
def test_real_worker_solve_roundtrip():
    """A real solver worker subprocess runs a small solve from a plain
    JSON spec and hands back globals + digest, bit-identical to the
    in-process Lattice run of the same case."""
    with WorkerPool(workers=1, autostart=False) as pool:
        job = pool.submit(dict(_SOLVE_DOC, return_state=True))
        res = job.result(timeout=300)
    import jax.numpy as jnp

    from tclb_tpu.core.lattice import Lattice
    from tclb_tpu.models import get_model
    lat = Lattice(get_model("d2q9"), (8, 16), dtype=jnp.float32,
                  settings={"nu": 0.05})
    lat.init()
    lat.iterate(20)
    import hashlib
    want = hashlib.sha256(np.ascontiguousarray(
        np.asarray(lat.state.fields)).tobytes()).hexdigest()
    assert res["state_sha256"] == want
    assert res["iteration"] == 20 and res["resumed_from"] is None
    np.testing.assert_array_equal(res["fields"],
                                  np.asarray(lat.state.fields))


def test_dispatcher_process_isolation_rejects_plan_specs():
    """Plan-carrying specs are rejected at submit, before any worker is
    spawned — a live EnsemblePlan cannot cross a process boundary."""
    import jax.numpy as jnp

    from tclb_tpu.models import get_model
    from tclb_tpu.serve import Case, EnsemblePlan, JobSpec
    from tclb_tpu.serve.dispatcher import FleetDispatcher
    model = get_model("d2q9")
    disp = FleetDispatcher(process_isolation=True, autostart=False)
    try:
        planned = JobSpec(model=model, shape=(8, 16), case=Case(name="x"),
                          niter=4, dtype=jnp.float32,
                          plan=EnsemblePlan(model, (8, 16),
                                            dtype=jnp.float32))
        with pytest.raises(ValueError, match="cannot cross"):
            disp.submit(planned)
    finally:
        disp.close()


@pytest.mark.slow
def test_dispatcher_process_isolation_route():
    """FleetDispatcher(process_isolation=True) routes submits through
    the pool: results come back as host-side PoolResults with digests,
    and plan/grad specs are rejected before anything is queued."""
    import jax.numpy as jnp

    from tclb_tpu.models import get_model
    from tclb_tpu.serve import Case, EnsemblePlan, JobSpec
    from tclb_tpu.serve.dispatcher import FleetDispatcher
    model = get_model("d2q9")
    spec = JobSpec(model=model, shape=(8, 16), case=Case(name="p"),
                   niter=10, dtype=jnp.float32,
                   base_settings={"nu": 0.05})
    disp = FleetDispatcher(process_isolation=True, autostart=False)
    try:
        job = disp.submit(spec)
        res = job.result()
        assert res.globals and res.state_sha256
        assert res.iteration == 10 and res.pid is not None
        planned = JobSpec(model=model, shape=(8, 16), case=Case(name="x"),
                          niter=4, dtype=jnp.float32,
                          plan=EnsemblePlan(model, (8, 16),
                                            dtype=jnp.float32))
        with pytest.raises(ValueError, match="cannot cross"):
            disp.submit(planned)
    finally:
        disp.close()


@pytest.mark.slow
def test_worker_exit_fault_resumes_bit_identical(tmp_path):
    """Seeded pool.worker_exit schedule: the worker hard-exits at a
    checkpointed segment boundary; the supervisor respawns it, the job
    re-enters via CheckpointManager.latest(), and the final digest is
    bit-identical to an uninterrupted run.  (Worker-side fault counters
    are per-incarnation, so after=2 makes the respawn survive: it fires
    only 2 hits — job start + final segment — before finishing.)"""
    base = dict(_SOLVE_DOC, niter=30, checkpoint_every=10)
    with WorkerPool(workers=1, autostart=False) as pool:
        ref = pool.submit(dict(base, ckpt_root=str(tmp_path / "ref")))
        ref_sha = ref.result(timeout=600)["state_sha256"]

    faults.install(faults.FaultPlan.parse(
        "seed=7;pool.worker_exit:error:n=1:after=2"))
    pool = WorkerPool(workers=1, job_attempts=3,
                      retry_policy=RetryPolicy(max_attempts=4,
                                               base_delay_s=0.05,
                                               max_delay_s=0.2),
                      autostart=False)
    try:
        job = pool.submit(dict(base, ckpt_root=str(tmp_path / "x")))
        res = job.result(timeout=600)
    finally:
        pool.close()
        faults.uninstall()
    assert job.attempts == 2
    assert res["resumed_from"] == 20
    assert res["state_sha256"] == ref_sha
    assert pool.stats()["restarts"] >= 1


@pytest.mark.slow
def test_heartbeat_fault_wedges_worker_then_resumes(tmp_path):
    """Seeded pool.heartbeat schedule: an injected wedge stops the beat
    mid-solve; the watchdog declares the worker hung, kills it, and the
    requeued job resumes from the checkpoint that landed before the
    wedge — still bit-identical."""
    base = dict(_SOLVE_DOC, niter=30, checkpoint_every=10)
    with WorkerPool(workers=1, autostart=False) as pool:
        ref = pool.submit(dict(base, ckpt_root=str(tmp_path / "ref")))
        ref_sha = ref.result(timeout=600)["state_sha256"]

    # worker beats: accepted(1), built(2), iter10(3), iter20(4) -- the
    # checkpoint at 20 saves BEFORE beat 4 wedges, so the respawn (3
    # beats: accepted, built, iter30) resumes from 20 and completes
    faults.install(faults.FaultPlan.parse(
        "seed=11;pool.heartbeat:error:n=1:after=3"))
    pool = WorkerPool(workers=1, heartbeat_timeout_s=20.0,
                      job_attempts=3, term_grace_s=1.0,
                      retry_policy=RetryPolicy(max_attempts=4,
                                               base_delay_s=0.05,
                                               max_delay_s=0.2),
                      autostart=False)
    try:
        job = pool.submit(dict(base, ckpt_root=str(tmp_path / "w")))
        res = job.result(timeout=600)
    finally:
        pool.close()
        faults.uninstall()
    assert res["resumed_from"] == 20
    assert res["state_sha256"] == ref_sha
    assert pool.stats()["requeued"] == 1


# --------------------------------------------------------------------------- #
# Gateway CLI with --workers: supervisor smoke + SIGTERM drain (slow)
# --------------------------------------------------------------------------- #


def _http(url, method="GET", body=None, timeout=300):
    req = urllib.request.Request(
        url, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), e.headers


def _spawn_cli_gateway(tmp_path, store, tag, workers=2, extra=()):
    """Start ``python -m tclb_tpu gateway --workers N`` and parse the
    gateway + monitor URLs it prints."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               TCLB_FLIGHT_DIR=str(tmp_path / f"flight-{tag}"),
               TCLB_TELEMETRY=str(tmp_path / f"trace-{tag}.jsonl"))
    logf = open(tmp_path / f"gateway-{tag}.log", "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tclb_tpu", "gateway",
         "--port", "0", "--store", str(store),
         "--workers", str(workers),
         "--monitor", "127.0.0.1:0", *extra],
        env=env, cwd=REPO, stdout=logf, stderr=subprocess.STDOUT,
        text=True)
    deadline = time.time() + 120
    urls = {}
    while time.time() < deadline:
        logf.flush()
        text = open(logf.name).read()
        for line in text.splitlines():
            if line.startswith("monitor: "):
                urls["monitor"] = line.split()[1].rsplit("/", 1)[0]
            if line.startswith("gateway: http"):
                urls["gateway"] = line.split()[1].rsplit("/v1", 1)[0]
        if "gateway" in urls and "monitor" in urls:
            return proc, urls
        if proc.poll() is not None:
            raise AssertionError(f"gateway CLI died:\n{text}")
        time.sleep(0.1)
    proc.kill()
    raise AssertionError("gateway CLI never printed its URLs")


_RESUMABLE = {"model": "d2q9", "shape": [16, 32], "niter": 60,
              "params": {"nu": 0.05}, "resumable": True,
              "checkpoint_every": 10, "digest": True}


@pytest.mark.slow
def test_gateway_pool_worker_sigkill_resume_bit_identical(tmp_path):
    """Supervisor smoke through the serving path: SIGKILL the busy pool
    worker mid-solve of an HTTP-submitted resumable job.  The GATEWAY
    PROCESS never dies: the supervisor restarts the worker in place, the
    job resumes from its newest checkpoint (resumed_from > 0), /metrics
    stays scrapeable throughout, and the digest matches an uninterrupted
    run."""
    proc, urls = _spawn_cli_gateway(tmp_path, tmp_path / "ref-store",
                                    "ref", workers=1)
    try:
        code, doc, _ = _http(urls["gateway"] + "/v1/jobs", "POST",
                             _RESUMABLE)
        assert code == 202, doc
        code, doc, _ = _http(urls["gateway"]
                             + f"/v1/jobs/{doc['job']['id']}"
                             + "/result?wait=300")
        assert code == 200 and doc["job"]["resumed_from"] is None
        ref = doc["results"][0]
    finally:
        proc.kill()
        proc.wait()

    proc, urls = _spawn_cli_gateway(tmp_path, tmp_path / "store", "a",
                                    workers=2)
    try:
        # readiness flips 503 -> 200 once at least one worker is live
        deadline = time.time() + 120
        while True:
            code, doc, _ = _http(urls["gateway"] + "/healthz/ready")
            if code == 200:
                break
            assert doc["workers_live"] == 0     # why it wasn't ready
            assert proc.poll() is None and time.time() < deadline
            time.sleep(0.2)
        assert doc["workers_live"] >= 1
        code, doc, _ = _http(urls["gateway"] + "/v1/jobs", "POST",
                             _RESUMABLE)
        assert code == 202, doc
        jid = doc["job"]["id"]
        # wait for a busy worker AND a landed checkpoint, then SIGKILL
        # the worker out from under the job
        ckroot = tmp_path / "store" / "ckpt" / jid
        victim = None
        deadline = time.time() + 240
        while time.time() < deadline:
            _, snap, _ = _http(urls["monitor"] + "/status")
            busy = [w for w in snap.get("pool", {}).get("workers", [])
                    if w["state"] == "busy"]
            steps = os.listdir(ckroot) if ckroot.exists() else []
            if busy and steps:
                victim = busy[0]["pid"]
                break
            assert proc.poll() is None
            time.sleep(0.1)
        assert victim, "no busy pool worker with a checkpoint appeared"
        os.kill(victim, signal.SIGKILL)
        # the front door and the monitor stay responsive while the
        # supervisor respawns the lane
        code, _, _ = _http(urls["gateway"] + "/healthz")
        assert code == 200
        with urllib.request.urlopen(urls["monitor"] + "/metrics",
                                    timeout=30) as resp:   # raw text
            assert resp.status == 200
            assert b"tclb_pool_workers" in resp.read()
        code, doc, _ = _http(urls["gateway"]
                             + f"/v1/jobs/{jid}/result?wait=300")
        assert code == 200, doc
        assert proc.poll() is None          # the gateway never died
        job = doc["job"]
        assert job["status"] == "done"
        assert job["resumed_from"] is not None and job["resumed_from"] > 0
        got = doc["results"][0]
        assert got["state_sha256"] == ref["state_sha256"]
        assert got["globals"] == ref["globals"]
        _, snap, _ = _http(urls["monitor"] + "/status")
        assert sum(w["restarts"]
                   for w in snap["pool"]["workers"]) >= 1
        # cross-process relay: worker-originated iterate metrics reach
        # the GATEWAY's /metrics, labelled by the worker pid
        with urllib.request.urlopen(urls["monitor"] + "/metrics",
                                    timeout=30) as resp:
            metrics = resp.read().decode()
        assert 'tclb_iterate_seconds_count{worker_pid="' in metrics
        assert 'tclb_gateway_phase_seconds_count{phase="solve"}' \
            in metrics
        # ... and the JSONL trace stitches ONE timeline for the job:
        # worker iterate spans from BOTH incarnations (before and after
        # the SIGKILL), keyed by the gateway record id
        from tclb_tpu.telemetry import report
        evts = report.load(str(tmp_path / "trace-a.jsonl"))
        je = report.job_events(evts, jid)
        pids = {e["worker_pid"] for e in je
                if e.get("kind") == "span" and e.get("name") == "iterate"
                and e.get("worker_pid") is not None}
        assert len(pids) >= 2, \
            f"expected iterate spans from 2 worker incarnations: {pids}"
        kinds = {e.get("kind") for e in je}
        assert {"gateway.admitted", "serve.pool_job_started",
                "gateway.resumed", "gateway.job_done"} <= kinds
        done = next(e for e in je if e.get("kind") == "gateway.job_done")
        assert done.get("solve_s") is not None
    finally:
        proc.kill()
        proc.wait()


@pytest.mark.slow
def test_gateway_cli_sigterm_drains_and_exits_zero(tmp_path):
    """Zero-downtime drain: SIGTERM to the --workers gateway stops
    admission (503 + Retry-After, readiness 503 while liveness stays
    200), parks the in-flight resumable job at a checkpointed boundary,
    flushes a store snapshot, and exits 0.  A restart on the same store
    resumes the parked job from latest()."""
    store = tmp_path / "store"
    # big enough that the solve cannot finish inside the drain grace:
    # the drain MUST kill the worker and park the record mid-run
    body = dict(_RESUMABLE, shape=[64, 128], niter=20000,
                checkpoint_every=500)
    proc, urls = _spawn_cli_gateway(tmp_path, store, "d", workers=1,
                                    extra=("--drain-grace", "8"))
    try:
        code, doc, _ = _http(urls["gateway"] + "/v1/jobs", "POST", body)
        assert code == 202, doc
        jid = doc["job"]["id"]
        ckroot = store / "ckpt" / jid
        deadline = time.time() + 240
        while not (ckroot.exists() and os.listdir(ckroot)):
            assert proc.poll() is None and time.time() < deadline
            time.sleep(0.1)
        proc.send_signal(signal.SIGTERM)
        # while draining, liveness holds and readiness/submission 503
        saw_draining = False
        for _ in range(50):
            if proc.poll() is not None:
                break
            try:
                code, doc, hdrs = _http(urls["gateway"]
                                        + "/healthz/ready", timeout=5)
            except (urllib.error.URLError, ConnectionError, OSError):
                break                        # already exited
            if code == 503 and doc.get("draining"):
                assert hdrs["Retry-After"] is not None
                c2, d2, h2 = _http(urls["gateway"] + "/v1/jobs",
                                   "POST", body, timeout=5)
                assert c2 == 503 and h2["Retry-After"] is not None
                c3, _, _ = _http(urls["gateway"] + "/healthz",
                                 timeout=5)
                assert c3 == 200
                saw_draining = True
                break
            time.sleep(0.1)
        assert proc.wait(timeout=120) == 0   # claimed shutdown: exit 0
        assert saw_draining
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()

    # the job was parked, not lost; a restart resumes it from latest()
    proc, urls = _spawn_cli_gateway(tmp_path, store, "e", workers=1)
    try:
        code, doc, _ = _http(urls["gateway"]
                             + f"/v1/jobs/{jid}/result?wait=600")
        assert code == 200, doc
        job = doc["job"]
        assert job["status"] == "done"
        assert job["resumed_from"] is not None and job["resumed_from"] > 0
        assert job["progress_iter"] == 20000
    finally:
        proc.kill()
        proc.wait()
