"""serve/ subsystem tests: the batched ensemble engine's bit-parity
contract (plain + zonal-settings models), the compiled-executable cache
(fingerprint keys, LRU eviction, env-var capacity), the job scheduler's
fault tolerance (retry -> degrade, timeouts surface as failed jobs, not
hung callers), the sweep CLI's param expansion, the checkpoint shard
codecs that ride along in this PR, the ensemble_unsafe hygiene check,
and the telemetry Serving table.
"""

import json
import os
import subprocess
import sys
import time

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu import checkpoint as ckpt
from tclb_tpu import telemetry
from tclb_tpu.analysis import hygiene
from tclb_tpu.checkpoint import CheckpointManager, manifest as mf, writer
from tclb_tpu.control.sweep import expand_cases, load_setup, parse_param
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.serve import (Case, CompiledCache, EnsemblePlan, JobSpec,
                            JobTimeout, Scheduler, run_ensemble)
from tclb_tpu.serve.scheduler import DONE, FAILED, PENDING
from tclb_tpu.telemetry import report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _sink_off():
    """Telemetry is process-global: every test starts and ends disabled."""
    telemetry.disable()
    yield
    telemetry.disable()


def _channel_flags(m, ny, nx):
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = flags[-1, :] = m.flag_for("Wall")
    return flags


def _d2q9_plan(ny=12, nx=24, **kw):
    m = get_model("d2q9")
    return EnsemblePlan(m, (ny, nx), flags=_channel_flags(m, ny, nx),
                        base_settings={"nu": 0.05, "Velocity": 0.02}, **kw)


def _assert_case_matches(batched, seq):
    """Bit-parity: the batched run's per-case output equals the
    sequential single-case run exactly — fields, clock and globals."""
    np.testing.assert_array_equal(np.asarray(batched.state.fields),
                                  np.asarray(seq.state.fields))
    assert int(np.asarray(batched.state.iteration)) \
        == int(np.asarray(seq.state.iteration))
    assert batched.globals == seq.globals


# --------------------------------------------------------------------------- #
# Ensemble engine: bit-parity
# --------------------------------------------------------------------------- #


def test_ensemble_parity_d2q9():
    plan = _d2q9_plan()
    cases = [Case(settings={"nu": v}, name=f"nu={v}")
             for v in (0.02, 0.05, 0.11)]
    batched = plan.run(cases, niter=10)
    assert [r.case.name for r in batched] == [c.name for c in cases]
    for b, c in zip(batched, cases):
        _assert_case_matches(b, plan.run_sequential(c, 10))


def test_ensemble_parity_zonal_kuper():
    """A zonal-settings model with per-case zone-table differences: the
    kuper drop with each case carrying its own drop density."""
    n = 16
    m = get_model("d2q9_kuper")
    flags = np.full((n, n), m.flag_for("MRT"), dtype=np.uint16)
    yy, xx = np.mgrid[0:n, 0:n]
    drop = (yy - n / 2) ** 2 + (xx - n / 2) ** 2 < (n / 4) ** 2
    flags[drop] = m.flag_for("MRT", zone=1)
    plan = EnsemblePlan(m, (n, n), flags=flags, base_settings={
        "omega": 1.0, "Temperature": 0.56, "FAcc": 1.0, "Magic": 0.01,
        "MagicA": -0.152, "MagicF": -2.0 / 3.0, "Density": 3.26})
    cases = [Case(zonal={("Density", 1): v}, name=f"rho={v}")
             for v in (0.0145, 0.02, 0.05)]
    batched = plan.run(cases, niter=10)
    # the per-case zone tables actually differ (the test has teeth)
    assert not np.array_equal(np.asarray(batched[0].state.fields),
                              np.asarray(batched[1].state.fields))
    for b, c in zip(batched, cases):
        _assert_case_matches(b, plan.run_sequential(c, 10))


def test_ensemble_parity_through_cache():
    """The AOT-compiled path (what serving actually dispatches) keeps
    the same bit-parity as the jit path."""
    plan = _d2q9_plan()
    cache = CompiledCache(capacity=4)
    cases = [Case(settings={"nu": v}) for v in (0.03, 0.07)]
    for b, c in zip(plan.run(cases, niter=8, cache=cache), cases):
        _assert_case_matches(b, plan.run_sequential(c, 8))
    assert cache.stats()["misses"] == 1


def test_ensemble_vmap_mode_runs():
    """mode='vmap' is the throughput engine: no parity promise, but it
    must run, keep per-case independence and tag itself distinctly."""
    plan = _d2q9_plan(mode="vmap")
    assert ",vmap,b=2]" in plan.engine_tag(2)
    res = plan.run([Case(settings={"nu": 0.02}),
                    Case(settings={"nu": 0.2})], niter=5)
    assert all(np.isfinite(np.asarray(r.state.fields)).all() for r in res)
    assert not np.array_equal(np.asarray(res[0].state.fields),
                              np.asarray(res[1].state.fields))


def test_case_params_matches_set_setting():
    """Per-case params derive with the exact set_setting host math —
    including derived-setting updates (nu -> omega etc.)."""
    m = get_model("d2q9")
    plan = _d2q9_plan()
    lat = Lattice(m, plan.shape, dtype=plan.dtype,
                  settings={"nu": 0.05, "Velocity": 0.02})
    lat.set_setting("nu", 0.123)
    from tclb_tpu.serve.ensemble import case_params
    p = case_params(m, plan.base_params, Case(settings={"nu": 0.123}),
                    plan.dtype)
    np.testing.assert_array_equal(np.asarray(p.settings),
                                  np.asarray(lat.params.settings))
    np.testing.assert_array_equal(np.asarray(p.zone_table),
                                  np.asarray(lat.params.zone_table))


def test_run_ensemble_requires_shape():
    with pytest.raises(ValueError, match="shape"):
        run_ensemble(get_model("d2q9"), [Case()], 1)


# --------------------------------------------------------------------------- #
# Compiled-executable cache
# --------------------------------------------------------------------------- #


def test_cache_hits_across_plan_rebuilds():
    """Keys on Model.fingerprint + program shape, never object id(): a
    second plan built from scratch for the same class reuses the first
    plan's executable."""
    cache = CompiledCache(capacity=4)
    case = [Case(settings={"nu": 0.04})]
    _d2q9_plan().run(case, niter=6, cache=cache)
    _d2q9_plan().run(case, niter=6, cache=cache)
    s = cache.stats()
    assert (s["hits"], s["misses"]) == (1, 1)


def test_cache_distinct_programs_miss():
    cache = CompiledCache(capacity=8)
    plan = _d2q9_plan()
    case = [Case(settings={"nu": 0.04})]
    plan.run(case, niter=6, cache=cache)
    plan.run(case, niter=7, cache=cache)          # different static niter
    plan.run(case * 2, niter=6, cache=cache)      # different batch
    assert cache.stats() == {"hits": 0, "misses": 3, "evictions": 0,
                             "size": 3, "capacity": 8}


def test_cache_lru_eviction():
    cache = CompiledCache(capacity=1)
    plan = _d2q9_plan()
    case = [Case(settings={"nu": 0.04})]
    plan.run(case, niter=6, cache=cache)
    plan.run(case * 2, niter=6, cache=cache)      # evicts the b=1 entry
    plan.run(case, niter=6, cache=cache)          # miss again
    s = cache.stats()
    assert (s["misses"], s["evictions"], s["size"]) == (3, 2, 1)


def test_cache_capacity_from_env(monkeypatch):
    monkeypatch.setenv("TCLB_SERVE_CACHE_CAP", "3")
    assert CompiledCache().capacity == 3


# --------------------------------------------------------------------------- #
# Scheduler: binning, fault tolerance, timeouts
# --------------------------------------------------------------------------- #


def _specs(plan, nus, **kw):
    return [JobSpec(model=plan.model, shape=plan.shape,
                    case=Case(settings={"nu": v}, name=f"nu={v}"),
                    niter=6, flags=plan.flags,
                    base_settings={"nu": 0.05, "Velocity": 0.02},
                    name=f"nu={v}", **kw) for v in nus]


def test_scheduler_bins_one_batch_bit_exact():
    plan = _d2q9_plan()
    cache = CompiledCache(capacity=4)
    with Scheduler(max_batch=4, cache=cache, autostart=False) as sched:
        jobs = sched.run(_specs(plan, (0.02, 0.05, 0.11)))
    assert [j.status for j in jobs] == [DONE] * 3
    assert all(j.attempts == 1 and not j.degraded for j in jobs)
    # the whole burst binned into ONE batched dispatch (one compile)
    assert cache.stats()["misses"] == 1
    for j in jobs:
        _assert_case_matches(j.result(),
                             plan.run_sequential(j.spec.case, 6))


def test_scheduler_retry_then_succeed():
    calls = {"n": 0}

    def flaky(plan, cases, niter):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected transient failure")
        return ["ok"] * len(cases)

    with Scheduler(max_batch=4, retries=2, batch_runner=flaky,
                   autostart=False) as sched:
        jobs = sched.run(_specs(_d2q9_plan(), (0.02, 0.05)))
    assert calls["n"] == 2
    assert [j.status for j in jobs] == [DONE] * 2
    assert all(j.attempts == 2 and not j.degraded for j in jobs)
    assert jobs[0].result() == "ok"


def test_scheduler_degrades_to_sequential_after_retries():
    """Batched compile poisoned -> bounded retries -> every job served
    individually on the sequential path, marked degraded, still DONE."""
    seen = []

    def broken(plan, cases, niter):
        raise RuntimeError("injected poisoned batch")

    def seq(plan, case, niter):
        seen.append(case.name)
        return f"seq:{case.name}"

    streamed = []
    with Scheduler(max_batch=4, retries=1, batch_runner=broken,
                   sequential_runner=seq, on_result=streamed.append,
                   autostart=False) as sched:
        jobs = sched.run(_specs(_d2q9_plan(), (0.02, 0.05, 0.11)))
    assert [j.status for j in jobs] == [DONE] * 3
    assert all(j.degraded and j.attempts == 2 for j in jobs)
    assert jobs[1].result() == "seq:nu=0.05"
    assert seen == ["nu=0.02", "nu=0.05", "nu=0.11"]
    assert [j.id for j in streamed] == [j.id for j in jobs]


def test_scheduler_per_job_failure_does_not_kill_batchmates():
    def broken(plan, cases, niter):
        raise RuntimeError("no batch today")

    def seq(plan, case, niter):
        if case.name == "nu=0.05":
            raise RuntimeError("this one case is genuinely bad")
        return "ok"

    with Scheduler(max_batch=4, retries=0, batch_runner=broken,
                   sequential_runner=seq, autostart=False) as sched:
        jobs = sched.run(_specs(_d2q9_plan(), (0.02, 0.05, 0.11)))
    assert [j.status for j in jobs] == [DONE, FAILED, DONE]
    with pytest.raises(RuntimeError, match="genuinely bad"):
        jobs[1].result()


def test_scheduler_timeout_is_failed_not_hung():
    def stuck(plan, cases, niter):
        time.sleep(5.0)
        return ["late"] * len(cases)

    with Scheduler(max_batch=2, batch_runner=stuck) as sched:
        job = sched.submit(_specs(_d2q9_plan(), (0.02,),
                                  timeout_s=0.3)[0])
        t0 = time.monotonic()
        with pytest.raises(JobTimeout):
            job.result()
        assert time.monotonic() - t0 < 2.0
        assert job.status == FAILED


def test_scheduler_close_sweeps_inflight_past_deadline():
    """The close(wait=True) vs in-flight-timeout race: the worker is
    stuck inside a batch whose job deadline passes while close() is
    draining.  close must not return leaving the job PENDING forever —
    it sweeps in-flight jobs past their deadline into JobTimeout, so a
    caller that trusted close() never hangs on result() afterwards."""
    release = time.monotonic() + 3.0

    def stuck(plan, cases, niter):
        while time.monotonic() < release:   # worker wedged mid-batch
            time.sleep(0.05)
        return ["late"] * len(cases)

    sched = Scheduler(max_batch=2, batch_runner=stuck)
    job = sched.submit(_specs(_d2q9_plan(), (0.02,), timeout_s=0.2)[0])
    time.sleep(0.4)                          # rot past the deadline
    t0 = time.monotonic()
    sched.close(wait=True, join_timeout=0.5)
    assert time.monotonic() - t0 < 5.0       # close returned, not hung
    assert job.status == FAILED
    with pytest.raises(JobTimeout, match="during close"):
        job.result(timeout=0.1)


def test_scheduler_close_leaves_undeadlined_jobs_pending():
    """Queued jobs with no timeout_s are NOT swept by close — a late
    background finish may still legitimately flip them (the documented
    Job.result() semantics); close only resolves the timeout race."""
    with Scheduler(max_batch=2, autostart=False) as sched:
        job = sched.submit(_specs(_d2q9_plan(), (0.02,))[0])
    # never started, no deadline: still pending, error-free
    assert job.status == PENDING and job.error is None


def test_scheduler_expires_jobs_that_rotted_in_queue():
    specs = _specs(_d2q9_plan(), (0.02,), timeout_s=0.05)
    with Scheduler(max_batch=2, autostart=False) as sched:
        job = sched.submit(specs[0])
        time.sleep(0.2)              # rot past the deadline, then start
        sched.start()
        with pytest.raises(JobTimeout, match="expired in queue"):
            job.result(timeout=10.0)
    assert job.status == FAILED


def test_scheduler_incompatible_specs_split_batches():
    plan = _d2q9_plan()
    cache = CompiledCache(capacity=4)
    specs = _specs(plan, (0.02, 0.05))
    specs[1].niter = 7               # different program class
    with Scheduler(max_batch=4, cache=cache, autostart=False) as sched:
        jobs = sched.run(specs)
    assert [j.status for j in jobs] == [DONE] * 2
    assert cache.stats()["misses"] == 2


# --------------------------------------------------------------------------- #
# Sweep: param expansion + CLI
# --------------------------------------------------------------------------- #


def test_parse_param_range_and_list():
    name, vals = parse_param("nu=0.01:0.05:5")
    assert name == "nu" and len(vals) == 5
    assert np.allclose([float(v) for v in vals],
                       np.linspace(0.01, 0.05, 5))
    assert parse_param("Velocity=1,2") == ("Velocity", ["1", "2"])
    for bad in ("nu", "nu=", "=3", "nu=1:2", "nu=1:2:0"):
        with pytest.raises(ValueError):
            parse_param(bad)


def test_expand_cases_product_and_zones():
    setup = load_setup(os.path.join(REPO, "example", "drop.xml"))
    assert setup.model.name == "d2q9_kuper"
    assert "zdrop" in setup.zone_names
    cases = expand_cases(setup, ["Magic=0.01,0.02",
                                 "Density-zdrop=0.0145:0.05:3"])
    assert len(cases) == 6           # 2 x 3 cartesian product
    zid = setup.zone_names["zdrop"]
    assert cases[0].settings == {"Magic": 0.01}
    assert ("Density", zid) in cases[0].zonal
    assert "Density@" in cases[0].name and "Magic=" in cases[0].name
    with pytest.raises(ValueError, match="settings-zone"):
        expand_cases(setup, ["Density-nosuch=1"])
    with pytest.raises(ValueError, match="no setting"):
        expand_cases(setup, ["NotASetting=1"])
    assert expand_cases(setup, [])[0].name == "case0"


def test_sweep_cli_end_to_end(tmp_path):
    """The CI smoke invariant: 4 cases at batch 2 share one compiled
    executable — the second batch hits the cache."""
    out = subprocess.run(
        [sys.executable, "-m", "tclb_tpu", "sweep",
         os.path.join(REPO, "example", "cavity.xml"),
         "--param", "nu=0.1,0.12,0.14,0.16", "--iters", "2",
         "--batch", "2"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["model"] == "d2q9_kuper" and doc["iterations"] == 2
    assert [c["status"] for c in doc["cases"]] == ["done"] * 4
    assert doc["cases"][0]["settings"] == {"nu": 0.1}
    assert all(np.isfinite(v) for c in doc["cases"]
               for v in c["globals"].values())
    assert doc["cache"]["misses"] == 1 and doc["cache"]["hits"] >= 1


# --------------------------------------------------------------------------- #
# Checkpoint shard codecs
# --------------------------------------------------------------------------- #


def _small_lattice():
    m = get_model("d2q9")
    lat = Lattice(m, (8, 16), dtype=jnp.float64,
                  settings={"nu": 0.05, "Velocity": 0.02})
    lat.set_flags(_channel_flags(m, 8, 16))
    lat.init()
    return lat


def test_checkpoint_zlib_roundtrip(tmp_path):
    lat = _small_lattice()
    lat.iterate(10)
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, lat, compress="zlib")
    assert any(f.endswith(".npy.zlib") for f in os.listdir(d))
    assert not any(f.endswith(".npy") for f in os.listdir(d))
    assert mf.verify_checkpoint(d) == []
    lat2 = _small_lattice()
    ckpt.restore_lattice(lat2, d)
    np.testing.assert_array_equal(np.asarray(lat.state.fields),
                                  np.asarray(lat2.state.fields))


def test_checkpoint_zlib_corruption_detected(tmp_path):
    lat = _small_lattice()
    d = str(tmp_path / "ck")
    ckpt.save_checkpoint(d, lat, compress="zlib")
    shard = next(os.path.join(d, f) for f in sorted(os.listdir(d))
                 if f.endswith(".npy.zlib"))
    with open(shard, "r+b") as f:     # flip one payload byte
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))
    assert mf.verify_checkpoint(d) != []


def test_checkpoint_manager_compresses(tmp_path):
    lat = _small_lattice()
    lat.iterate(5)
    mgr = CheckpointManager(str(tmp_path), async_saves=False,
                            compress="zlib")
    mgr.save(lat)
    path = mgr.latest()
    assert path is not None
    assert any(f.endswith(".npy.zlib") for f in os.listdir(path))
    lat2 = _small_lattice()
    mgr.restore(lat2, path)
    np.testing.assert_array_equal(np.asarray(lat.state.fields),
                                  np.asarray(lat2.state.fields))


def test_codec_resolution_and_zstd_fallback():
    assert writer.resolve_codec(None) == "none"
    assert writer.resolve_codec("zlib") == "zlib"
    with pytest.raises(ValueError, match="unknown checkpoint codec"):
        writer.resolve_codec("lz4")
    try:
        import zstandard  # noqa: F401
        have_zstd = True
    except ImportError:
        have_zstd = False
    # zstd-without-package must degrade to an uncompressed save, never
    # fail the save
    assert writer.resolve_codec("zstd") == ("zstd" if have_zstd
                                            else "none")


def test_crc_covers_uncompressed_bytes(tmp_path):
    """The manifest CRC is over the UNCOMPRESSED npy bytes: the same
    array yields the same crc32 whatever the codec."""
    arr = np.arange(24, dtype=np.float64).reshape(4, 6)
    r0 = writer.write_npy(str(tmp_path / "a.npy"), arr)
    r1 = writer.write_npy(str(tmp_path / "b.npy"), arr, codec="zlib")
    assert r0["crc32"] == r1["crc32"]
    assert "codec" not in r0 and r1["codec"] == "zlib"
    assert r1["file"] == "b.npy.zlib"
    np.testing.assert_array_equal(
        writer.read_npy(str(tmp_path / "b.npy.zlib"), "zlib"), arr)


# --------------------------------------------------------------------------- #
# Hygiene: ensemble_unsafe
# --------------------------------------------------------------------------- #

_BAD_STAGE = '''
def stage_bgk(ctx, f):
    nu = ctx.setting("nu")
    omega = 1.0 / (3.0 * nu + 0.5)
    a = float(nu)                     # host cast of a per-case value
    b = omega.item()                  # host pull of a derived value
    if float(omega) > 1.0:            # cast AND branch on one line
        f = f * omega
    return f
'''

_CLEAN_STAGE = '''
import numpy as np
E = np.ones((9, 2))

def stage_bgk(ctx, f, i):
    c = float(E[i, 0])                # numpy stencil constant: fine
    nu = ctx.setting("nu")
    quad = None
    if quad is None:                  # is-None structure test: fine
        quad = nu
    nu = 0.05                         # strong update clears the taint
    d = float(nu)
    return f * (c + d + quad)
'''


def test_hygiene_ensemble_unsafe_fires(tmp_path):
    p = tmp_path / "badmodel.py"
    p.write_text(_BAD_STAGE)
    fs = hygiene.scan_ensemble_unsafe(paths=[str(p)])
    assert all(f.check == "hygiene.ensemble_unsafe" for f in fs)
    assert all(f.severity == "error" for f in fs)
    # float(nu), omega.item(), and BOTH violations on the if-line
    assert len(fs) == 4


def test_hygiene_ensemble_unsafe_clean_patterns(tmp_path):
    p = tmp_path / "okmodel.py"
    p.write_text(_CLEAN_STAGE)
    assert hygiene.scan_ensemble_unsafe(paths=[str(p)]) == []


def test_hygiene_ensemble_unsafe_in_check_repo():
    """The shipped model tree is clean AND the check actually runs as
    part of check_repo (a fixture-only check protects nothing)."""
    assert [f for f in hygiene.check_repo()
            if f.check == "hygiene.ensemble_unsafe"] == []


# --------------------------------------------------------------------------- #
# Telemetry: the Serving table
# --------------------------------------------------------------------------- #


def _serving_trace(batch2_outcome="ok", hits=1, misses=1):
    evts = [{"kind": "span", "name": "serve.batch", "dur_s": 0.5,
             "batch": 4, "capacity": 4, "outcome": "ok",
             "wait_s": [0.1, 0.2, 0.3, 0.4]},
            {"kind": "span", "name": "serve.batch", "dur_s": 0.5,
             "batch": 2, "capacity": 4, "outcome": batch2_outcome,
             "wait_s": [0.1, 0.5]}]
    evts += [{"kind": "span", "name": "serve.compile", "cache": "miss",
              "dur_s": 2.0}] * misses
    evts += [{"kind": "span", "name": "serve.compile", "cache": "hit",
              "dur_s": 0.001}] * hits
    return evts


def test_serving_summary():
    s = report.summarize(_serving_trace(batch2_outcome="degraded"))
    sv = s["serving"]
    assert sv["batches"] == 2 and sv["jobs"] == 6
    assert sv["occupancy_pct"] == 75.0
    assert sv["degraded_batches"] == 1
    assert sv["queue_wait_p50_s"] == pytest.approx(0.25)
    assert sv["queue_wait_p95_s"] <= 0.5
    assert sv["compile_lookups"] == 2
    assert sv["cache_hit_rate_pct"] == 50.0
    assert sv["compile_miss_s"] == pytest.approx(2.0)
    assert "serving" in report.format_text(s)
    # a trace with no serving activity renders no serving section
    assert report.summarize([])["serving"] == {}


def test_serving_compare_flags_regressions():
    base = report.summarize(_serving_trace(hits=9, misses=1))
    bad = [dict(e) for e in _serving_trace(hits=1, misses=9)]
    for e in bad:
        if e["name"] == "serve.batch":
            e["batch"] = 1            # fleet fell back to singletons
    other = report.summarize(bad)
    diff = report.compare(base, other, threshold=0.05)
    whats = {r["what"] for r in diff["regressions"]}
    assert {"batch_occupancy", "compile_cache_hit_rate"} <= whats
    assert "serving" in report.format_compare_text(diff)
    # and no serving regressions when the candidate matches the base
    same = report.compare(base, base, threshold=0.05)
    assert not {r["what"] for r in same["regressions"]} \
        & {"batch_occupancy", "compile_cache_hit_rate"}


def test_scheduler_emits_serving_spans(tmp_path):
    """Live integration: a real scheduler run under an enabled sink
    produces a trace whose report has the Serving numbers."""
    trace = str(tmp_path / "t.jsonl")
    telemetry.enable(trace)
    plan = _d2q9_plan()
    with Scheduler(max_batch=4, autostart=False) as sched:
        jobs = sched.run(_specs(plan, (0.02, 0.05)))
    cnt = dict(telemetry.counters())
    telemetry.disable()
    assert [j.status for j in jobs] == [DONE] * 2
    with open(trace) as fh:
        evts = [json.loads(line) for line in fh]
    sv = report.summarize(evts)["serving"]
    assert sv["jobs"] == 2 and sv["batches"] == 1
    assert sv["compile_lookups"] == 1
    assert sv["cache_hit_rate_pct"] == 0.0
    assert cnt.get("serve.jobs.submitted") == 2
    assert cnt.get("serve.jobs.done") == 2
