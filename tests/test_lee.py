"""Physics validation of d2q9_lee (Lee multiphase, potential forcing).

The double-well chemical potential mu0 = 2 Beta (r-rl)(r-rv)(2r-rv-rl)
has minima exactly at rho = LiquidDensity and rho = VaporDensity: a flat
interface must relax to those bulk densities with a tanh profile of width
set by Kappa/Beta, conserving mass.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model

pytestmark = pytest.mark.slow  # full-coverage job; the default lap runs the fast smoke suite

RL, RV = 1.0, 0.1


def _make(n=64, beta=0.02, kappa=0.02):
    m = get_model("d2q9_lee")
    lat = Lattice(m, (n, n), dtype=jnp.float64,
                  settings={"nu": 1 / 6, "LiquidDensity": RL,
                            "VaporDensity": RV, "Beta": beta,
                            "Kappa": kappa, "InitDensity": RV})
    return m, lat


def _set_rho_profile(lat, rho):
    """Set f to equilibrium at the given density profile (zero velocity)."""
    base = np.asarray(lat.get_density("f[0]")) * 0  # shape
    from tclb_tpu.ops import lbm
    from tclb_tpu.models.d2q9 import E
    W = lbm.weights(E)
    feq = np.asarray(lbm.equilibrium(E, W, jnp.asarray(rho),
                                     (jnp.zeros_like(jnp.asarray(rho)),) * 2))
    for i in range(9):
        lat.set_density(f"f[{i}]", feq[i])


def test_lee_flat_interface_bulk_densities():
    n = 64
    m, lat = _make(n)
    flags = np.full((n, n), m.flag_for("BGK"), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    y = np.arange(n)
    prof = RV + (RL - RV) * 0.5 * (1 + np.tanh((y[:, None] - n / 2) / 4.0))
    rho0 = np.broadcast_to(prof, (n, n)).copy()
    _set_rho_profile(lat, rho0)
    lat.iterate(2)   # refresh rho/nu fields from the new f
    mass0 = float(np.asarray(lat.get_quantity("Rho")).sum())

    lat.iterate(2000)
    rho = np.asarray(lat.get_quantity("Rho"))
    assert np.isfinite(rho).all()
    # Lee's mixed-difference forcing conserves mass only approximately
    # (the reference ships a Mass global precisely to monitor this drift);
    # bound the drift rather than demand exactness
    np.testing.assert_allclose(rho.sum(), mass0, rtol=5e-3)
    # bulk densities sit near the double-well minima (discrete-lattice
    # equilibrium shifts the vapor branch by a few percent of rho_l-rho_v)
    np.testing.assert_allclose(rho[5, :].mean(), RV, atol=0.03)
    np.testing.assert_allclose(rho[-5, :].mean(), RL, atol=0.03)
    # interface is monotone along y between the two bulks (the periodic
    # wrap carries a second, mirrored interface near y=0 — exclude it)
    mid = rho[:, n // 2]
    assert (np.diff(mid[8:n - 12]) > -1e-3).all()


def test_lee_chemical_potential_flat_in_equilibrium():
    """At equilibrium the chemical potential nu must be (nearly) uniform
    across the interface — that is the defining property of the Lee
    potential form."""
    n = 64
    m, lat = _make(n)
    flags = np.full((n, n), m.flag_for("BGK"), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    y = np.arange(n)
    prof = RV + (RL - RV) * 0.5 * (1 + np.tanh((y[:, None] - n / 2) / 4.0))
    _set_rho_profile(lat, np.broadcast_to(prof, (n, n)).copy())
    lat.iterate(4000)
    nu = np.asarray(lat.get_quantity("Nu"))
    rho = np.asarray(lat.get_quantity("Rho"))
    assert np.isfinite(nu).all()
    # nu spread across the domain is small compared to the barrier scale
    barrier = 2 * 0.02 * (RL - RV) ** 3   # ~ mu0 magnitude scale
    assert nu.max() - nu.min() < 0.2 * barrier, (nu.min(), nu.max())
    # still two phases
    assert rho.max() > 0.8 * RL and rho.min() < 2 * RV


def test_lee_moving_wall_couette():
    """Single-phase configuration (rho = liquid everywhere; the double well
    pins the density at the liquid minimum): a MovingWall lid drives a
    linear Couette profile."""
    ny, nx = 32, 16
    m, lat = _make(ny)
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"nu": 1 / 6, "LiquidDensity": RL,
                            "VaporDensity": RV, "Beta": 0.02, "Kappa": 0.02,
                            "InitDensity": RL, "WallDensity": RL,
                            "MovingWallVelocity": 0.05})
    flags = np.full((ny, nx), m.flag_for("BGK"), dtype=np.uint16)
    # the reference MovingWall reconstructs the UPWARD populations
    # (f2, f5, f6 — src/d2q9_lee/Dynamics.c.Rt:62-71): it is a lid at the
    # bottom of the fluid
    flags[0, :] = m.flag_for("MovingWall", "BGK")   # wet node: collides
    flags[-1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(3000)
    u = np.asarray(lat.get_quantity("U"))
    ux = u[0][:, nx // 2]
    assert np.isfinite(ux).all()
    # linear profile from ~lid velocity at the bottom to 0 at the wall
    y = np.arange(1, ny - 1)
    fit = np.polyfit(y, ux[1:-1], 1)
    expect_slope = -0.05 / (ny - 1)
    np.testing.assert_allclose(fit[0], expect_slope, rtol=0.15)
