"""Every shipped example case runs end to end — the reference treats its
example/*.xml set as its smoke suite (SURVEY §4.3); ours plays the same
role.  Iteration counts are scaled down for CI: the full cases run on
real hardware via ``tclb run example/<case>.xml``."""

import re
import xml.etree.ElementTree as ET
from pathlib import Path

import numpy as np
import pytest

EXAMPLES = sorted(Path(__file__).parent.parent.glob("example/*.xml"))


def _shrink(tree: ET.ElementTree) -> None:
    """Scale iteration-bearing handlers down to CI size (keep ratios:
    Control horizons stay >= the Solve length so series semantics hold)."""
    root = tree.getroot()
    for el in root.iter():
        for attr in ("Iterations",):
            v = el.get(attr)
            if v is None or not re.fullmatch(r"\d+", v):
                continue
            n = int(v)
            if el.tag in ("Solve", "Log", "VTK", "TXT", "BIN", "Failcheck",
                          "Catalyst", "Sample", "Average"):
                el.set(attr, str(max(2, min(n, 20))))
            elif el.tag in ("Optimize", "FDTest", "Adjoint"):
                el.set(attr, str(max(2, min(n, 4))))
            elif el.tag == "Control":
                el.set(attr, str(max(4, min(n, 20))))
        for attr in ("MaxEvaluations", "Checks"):
            v = el.get(attr)
            if v is not None and re.fullmatch(r"\d+", v):
                el.set(attr, str(min(int(v), 2)))
    # geometry stays as authored: with the iteration counts capped, even
    # the 1024-wide cases run in under a second on CPU, and shrinking
    # the domain would clip the authored obstacles/zones out of the case


@pytest.mark.slow
@pytest.mark.parametrize("case", EXAMPLES, ids=[c.stem for c in EXAMPLES])
def test_example_runs(case, tmp_path, monkeypatch):
    from tclb_tpu.control import run_config_string
    from tclb_tpu.models import get_model

    tree = ET.parse(case)
    root = tree.getroot()
    _shrink(tree)
    root.set("output", str(tmp_path) + "/")
    # file references inside cases are repo-relative
    monkeypatch.chdir(Path(__file__).parent.parent)
    xml = ET.tostring(root, encoding="unicode")
    solver = run_config_string(xml, get_model(root.get("model")))
    fields = np.asarray(solver.lattice.state.fields)
    assert np.isfinite(fields).all(), f"{case.stem} went non-finite"
