"""Revolve checkpointing + gradient serving tests.

The schedule tests are pure-python and run in the fast tier: the
planner must emit a VALID reversal (every step reversed exactly once,
in order, from a correctly positioned primal) whose advance count
equals the Griewank binomial optimum with peak live snapshots <= S.
The gradient tests (slow tier) hold the bit-parity contract: a revolve
sweep's objective and final state are bit-identical to
``make_unsteady_gradient``'s, its gradient within 1 ulp, and the
gradient is bit-invariant to the snapshot budget S (checkpointing must
introduce ZERO numerical error)."""

import os
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu import telemetry
from tclb_tpu.adjoint import (InternalTopology, batched_descent,
                              make_unsteady_gradient)
from tclb_tpu.adjoint.revolve import (SnapshotStore, auto_plan,
                                      binomial_bound,
                                      make_revolve_gradient,
                                      revolve_schedule)
from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.ops import fusion
from tclb_tpu.serve import (Case, FleetDispatcher, GradSpec, JobSpec,
                            Scheduler, make_grad_evaluator)
from tclb_tpu.serve.ensemble import EnsemblePlan


# --------------------------------------------------------------------------- #
# Schedule: validity + optimality over a (T, S) grid
# --------------------------------------------------------------------------- #


def _simulate(T, S, schedule):
    """Execute a schedule abstractly; returns (advances, peak_live)."""
    live = set()
    peak = 0
    pos = None
    advances = 0
    reversed_steps = []
    for act in schedule:
        if act[0] == "snapshot":
            assert act[1] not in live, "double snapshot of one step"
            live.add(act[1])
            peak = max(peak, len(live))
            if act[1] == 0 and pos is None:
                pos = 0
        elif act[0] == "restore":
            assert act[1] in live, "restore of a freed snapshot"
            pos = act[1]
        elif act[0] == "free":
            live.discard(act[1])
        elif act[0] == "advance":
            _, i, j = act
            assert pos == i and j > i, "advance from wrong position"
            advances += j - i
            pos = j
        elif act[0] == "reverse":
            assert pos == act[1], "reverse away from the primal state"
            reversed_steps.append(act[1])
        else:  # pragma: no cover - planner emits no other actions
            raise AssertionError(f"unknown action {act[0]}")
    assert reversed_steps == list(range(T - 1, -1, -1)), \
        "steps must reverse exactly once each, in decreasing order"
    assert not live, "schedule leaks snapshots"
    return advances, peak


@pytest.mark.parametrize("S", [1, 2, 3, 5, 8])
def test_revolve_schedule_grid(S):
    for T in range(1, 26):
        sched = revolve_schedule(T, S)
        advances, peak = _simulate(T, S, sched)
        assert advances == binomial_bound(T, S), (T, S)
        assert peak <= S, (T, S)


def test_binomial_bound_edges():
    assert binomial_bound(1, 1) == 0
    # S >= T: one snapshot per step -> the forward sweep alone (T-1
    # advances; the last step's unit is re-run at its reverse)
    for T in (2, 5, 9):
        assert binomial_bound(T, T) == T - 1
        assert binomial_bound(T, 3 * T) == T - 1
    # S = 1: the quadratic single-snapshot sweep
    for T in (2, 5, 9):
        assert binomial_bound(T, 1) == T * (T - 1) // 2
    with pytest.raises(ValueError):
        binomial_bound(4, 0)


def test_recompute_grows_as_budget_shrinks():
    T = 24
    costs = [binomial_bound(T, S) for S in (24, 12, 6, 3, 2, 1)]
    assert costs == sorted(costs)
    assert costs[0] == T - 1          # full budget: forward sweep only


# --------------------------------------------------------------------------- #
# Two-tier snapshot store
# --------------------------------------------------------------------------- #


def _tree(k):
    return (np.full((3, 4), float(k)), np.arange(5) + k,
            np.int32(k))


def test_snapshot_store_mem_tier():
    store = SnapshotStore(mem_slots=8, spill_dir=None)
    try:
        for k in range(3):
            store.put(k, _tree(k))
        for k in range(3):
            got = store.get(k)
            for a, b in zip(got, _tree(k)):
                np.testing.assert_array_equal(np.asarray(a), b)
        assert store.peak_live == 3
        assert store.spill_bytes == 0
        store.free(1)
        store.put(7, _tree(7))
        np.testing.assert_array_equal(np.asarray(store.get(7)[0]),
                                      _tree(7)[0])
    finally:
        store.close()


def test_snapshot_store_disk_tier_crc(tmp_path):
    """Snapshots past the memory budget spill to disk with a CRC
    sidecar; fetch verifies and the store cleans up after itself."""
    store = SnapshotStore(mem_slots=1, spill_dir=str(tmp_path))
    try:
        for k in range(4):
            store.put(k, _tree(k))
        store.wait()
        spilled = sorted(p for p in os.listdir(tmp_path)
                         if p.endswith(".npy"))
        assert len(spilled) == 3          # slot 0 stayed in memory
        for p in spilled:
            assert os.path.exists(os.path.join(tmp_path, p + ".crc"))
        for k in range(4):
            got = store.get(k)
            for a, b in zip(got, _tree(k)):
                np.testing.assert_array_equal(np.asarray(a), b)
        assert store.spill_bytes > 0
    finally:
        store.close()
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".npy")]


def test_snapshot_mem_slots_budget():
    # 4 GiB default budget over a (64, 128) f32 9-plane stack
    per = 64 * 128 * 9 * 4
    assert fusion.snapshot_mem_slots(9, (64, 128), 4) \
        == (4 * 1024 * 1024 * 1024) // per
    assert fusion.snapshot_mem_slots(
        9, (64, 128), 4, budget_bytes=per * 3 + 1) == 3
    # a snapshot bigger than the budget still gets one slot
    assert fusion.snapshot_mem_slots(9, (64, 128), 4, budget_bytes=1) == 1


def test_auto_plan_splits_tiers():
    m = get_model("d2q9_adj")
    # budget of ~2 snapshots, no spill: S clamps to the memory tier
    per = 8 * 16 * m.n_storage * 4
    p = auto_plan(m, (8, 16), 64, dtype=jnp.float32,
                  host_budget_bytes=per * 2 + 1, spill=False)
    assert p.snapshots == p.mem_slots == 2
    # with spill: S grows past the memory tier until the recompute
    # factor is acceptable
    p2 = auto_plan(m, (8, 16), 64, dtype=jnp.float32,
                   host_budget_bytes=per * 2 + 1, spill=True)
    assert p2.mem_slots == 2
    assert p2.snapshots > 2
    assert binomial_bound(64, p2.snapshots) <= 1.5 * 64


# --------------------------------------------------------------------------- #
# Three-tier store: peer-device HBM via a leased fleet lane (D2D)
# --------------------------------------------------------------------------- #


def _fleet2():
    """Two NON-default host devices (conftest forces 8 virtual CPU
    devices), so the peer park is a genuine cross-device device_put —
    the forced-host stand-in for a pod's D2D over ICI."""
    return FleetDispatcher(devices=jax.devices()[1:3])


def test_snapshot_store_peer_tier_d2d_round_trip():
    with _fleet2() as d:
        store = SnapshotStore(mem_slots=1, peer_slots=2, dispatcher=d)
        try:
            for k in range(3):
                store.put(k, _tree(k))
            assert [store.tier_of(k) for k in range(3)] \
                == ["mem", "peer", "peer"]
            lease = store._lease
            assert lease is not None and not lease.released
            assert lease.device in jax.devices()[1:3]
            # the parked leaves actually live on the leased peer device
            for leaf in jax.tree.leaves(store._peer[1]):
                assert leaf.devices() == {lease.device}
            for k in range(3):
                got = store.get(k)
                for a, b in zip(got, _tree(k)):
                    np.testing.assert_array_equal(np.asarray(a), b)
            assert store.tier_bytes["peer"] > 0
            assert store.spill_bytes == store.tier_bytes["peer"]
        finally:
            store.close()
        # the lease is returned with the store: nothing stays reserved
        assert all(l.reserved is None for l in d.lanes)


def test_snapshot_store_three_tier_ladder(tmp_path):
    """mem -> peer -> disk, in that order, and every tier round-trips
    the exact bytes."""
    with _fleet2() as d:
        store = SnapshotStore(mem_slots=1, peer_slots=1,
                              spill_dir=str(tmp_path), dispatcher=d)
        try:
            for k in range(4):
                store.put(k, _tree(k))
            assert [store.tier_of(k) for k in range(4)] \
                == ["mem", "peer", "disk", "disk"]
            store.wait()
            for k in range(4):
                got = store.get(k)
                for a, b in zip(got, _tree(k)):
                    np.testing.assert_array_equal(np.asarray(a), b)
            for tier in ("mem", "peer", "disk"):
                assert store.tier_bytes[tier] > 0, tier
            assert store.spill_bytes \
                == store.tier_bytes["peer"] + store.tier_bytes["disk"]
        finally:
            store.close()
        assert all(l.reserved is None for l in d.lanes)
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".npy")]


def test_peer_revocation_migrates_snapshots_down(tmp_path):
    """Serving demand reclaims the leased lane: every peer snapshot
    migrates down the ladder bit-exact, the lane comes back unreserved,
    and later parks go straight to disk (no re-lease mid-sweep)."""
    evts = []
    telemetry.subscribe(evts.append)
    try:
        with _fleet2() as d:
            store = SnapshotStore(mem_slots=0, peer_slots=2,
                                  spill_dir=str(tmp_path), dispatcher=d)
            try:
                store.put(0, _tree(0))
                store.put(1, _tree(1))
                assert [store.tier_of(k) for k in (0, 1)] \
                    == ["peer", "peer"]
                d.revoke_lease(store._lease, reason="demand")
                assert [store.tier_of(k) for k in (0, 1)] \
                    == ["disk", "disk"]
                assert store.evacuations == 2
                assert all(l.reserved is None for l in d.lanes)
                store.put(2, _tree(2))
                assert store.tier_of(2) == "disk"
                for k in range(3):
                    got = store.get(k)
                    for a, b in zip(got, _tree(k)):
                        np.testing.assert_array_equal(np.asarray(a), b)
            finally:
                store.close()
        kinds = [e.get("kind") for e in evts]
        assert "serve.lane_revoked" in kinds
        assert "adjoint.spill_peer_down" in kinds
    finally:
        telemetry.unsubscribe(evts.append)


def test_reserve_lane_never_starves_serving():
    """The dispatcher never leases its last healthy lane: a 1-lane
    fleet refuses, a 2-lane fleet grants exactly one."""
    with FleetDispatcher(devices=jax.devices()[:1]) as d1:
        assert d1.reserve_lane(tenant="adjoint.spill") is None
    with _fleet2() as d2:
        lease = d2.reserve_lane(tenant="adjoint.spill")
        assert lease is not None
        assert d2.reserve_lane(tenant="adjoint.spill") is None
        lease.release()
        assert all(l.reserved is None for l in d2.lanes)


# --------------------------------------------------------------------------- #
# Gradient parity (slow tier: full adjoint compiles)
# --------------------------------------------------------------------------- #


def _setup(ny=8, nx=16):
    m = get_model("d2q9_adj")
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                            "DragInObj": 1.0, "MaterialInObj": 0.0})
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    flags[2:6, 5:10] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    return m, lat


def _assert_ulp_close(a, b, ulps=64):
    # Revolve itself is bit-deterministic (see the S-invariance assertion
    # below), but the levels=1 reference compiles its scans with different
    # trip counts than the revolve segments, so XLA may reassociate the
    # cotangent accumulation differently.  Bound the divergence by a few
    # ulps of the largest gradient element.
    a, b = np.asarray(a), np.asarray(b)
    tol = ulps * np.spacing(np.max(np.maximum(np.abs(a), np.abs(b))))
    err = np.max(np.abs(a - b))
    assert err <= tol, \
        f"gradient differs by {err} (> {ulps} ulps of max element {tol})"


@pytest.mark.slow
def test_revolve_matches_reference_bitwise():
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 12

    ref = make_unsteady_gradient(m, design, niter, levels=1)
    o_ref, g_ref, s_ref = ref(theta0, lat.state, lat.params)

    rev = make_revolve_gradient(m, design, niter, snapshots=3,
                                engine="xla", shape=(8, 16),
                                dtype=jnp.float64)
    o_rev, g_rev, s_rev = rev(theta0, lat.state, lat.params)

    assert float(o_rev) == float(o_ref)
    np.testing.assert_array_equal(np.asarray(s_rev.fields),
                                  np.asarray(s_ref.fields))
    _assert_ulp_close(g_rev, g_ref)

    # revolve introduces ZERO numerical error: the gradient is
    # bit-invariant to the snapshot budget
    rev8 = make_revolve_gradient(m, design, niter, snapshots=8,
                                 engine="xla", shape=(8, 16),
                                 dtype=jnp.float64)
    _, g8, _ = rev8(theta0, lat.state, lat.params)
    np.testing.assert_array_equal(np.asarray(g8), np.asarray(g_rev))

    # the sweep's accounting matches the planner's promise
    T = rev.horizon
    assert rev.last["advances"] == binomial_bound(T, 3)
    assert rev.last["peak_snapshots"] <= 3


@pytest.mark.slow
def test_revolve_spill_tier_matches(tmp_path):
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 12

    rev = make_revolve_gradient(m, design, niter, snapshots=4,
                                engine="xla", shape=(8, 16),
                                dtype=jnp.float64, mem_slots=1,
                                spill_dir=str(tmp_path))
    o1, g1, _ = rev(theta0, lat.state, lat.params)
    assert rev.last["spill_bytes"] > 0

    ref = make_unsteady_gradient(m, design, niter, levels=1)
    o_ref, g_ref, _ = ref(theta0, lat.state, lat.params)
    assert float(o1) == float(o_ref)
    _assert_ulp_close(g1, g_ref)


@pytest.mark.slow
def test_revolve_tier_split_bit_invariant(tmp_path):
    """The gradient is bit-invariant to the TIER SPLIT, not just to S:
    all-mem == mem+peer == mem+peer+disk, bit for bit, and no lane is
    left reserved after any sweep."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 12

    rev0 = make_revolve_gradient(m, design, niter, snapshots=4,
                                 engine="xla", shape=(8, 16),
                                 dtype=jnp.float64)
    o0, g0, s0 = rev0(theta0, lat.state, lat.params)
    assert rev0.last["tiers"] == ["mem"]

    with _fleet2() as d:
        rev1 = make_revolve_gradient(m, design, niter, snapshots=4,
                                     engine="xla", shape=(8, 16),
                                     dtype=jnp.float64, mem_slots=1,
                                     peer_slots=3, dispatcher=d)
        o1, g1, s1 = rev1(theta0, lat.state, lat.params)
        assert rev1.last["spill_peer"] > 0
        assert all(l.reserved is None for l in d.lanes)

        rev2 = make_revolve_gradient(m, design, niter, snapshots=4,
                                     engine="xla", shape=(8, 16),
                                     dtype=jnp.float64, mem_slots=1,
                                     peer_slots=1,
                                     spill_dir=str(tmp_path),
                                     dispatcher=d)
        o2, g2, s2 = rev2(theta0, lat.state, lat.params)
        assert rev2.last["spill_peer"] > 0
        assert rev2.last["spill_disk"] > 0
        assert sorted(rev2.last["tiers"]) == ["disk", "mem", "peer"]
        assert all(l.reserved is None for l in d.lanes)

    assert float(o1) == float(o0) == float(o2)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))
    np.testing.assert_array_equal(np.asarray(g2), np.asarray(g0))
    np.testing.assert_array_equal(np.asarray(s1.fields),
                                  np.asarray(s0.fields))
    np.testing.assert_array_equal(np.asarray(s2.fields),
                                  np.asarray(s0.fields))


@pytest.mark.slow
def test_revolve_peer_eviction_mid_sweep_gradient_unchanged(tmp_path):
    """Serving demand revokes the leased lane DURING the sweep (the
    revocation fires synchronously off the lane-reserved event): the
    spill falls through to the disk tier mid-flight, the gradient stays
    bit-identical to the all-memory run, and no lane is left
    reserved."""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    niter = 12

    rev0 = make_revolve_gradient(m, design, niter, snapshots=4,
                                 engine="xla", shape=(8, 16),
                                 dtype=jnp.float64)
    _, g0, _ = rev0(theta0, lat.state, lat.params)

    with _fleet2() as d:
        def demand(e):
            if e.get("kind") == "serve.lane_reserved" \
                    and e.get("tenant") == "adjoint.spill":
                d.revoke_lease(d._leases[-1], reason="demand")

        telemetry.subscribe(demand)
        try:
            rev = make_revolve_gradient(m, design, niter, snapshots=4,
                                        engine="xla", shape=(8, 16),
                                        dtype=jnp.float64, mem_slots=1,
                                        peer_slots=3,
                                        spill_dir=str(tmp_path),
                                        dispatcher=d)
            _, g1, _ = rev(theta0, lat.state, lat.params)
        finally:
            telemetry.unsubscribe(demand)
        assert all(l.reserved is None for l in d.lanes)

    assert rev.last["spill_peer"] == 0    # the lane was reclaimed
    assert rev.last["spill_disk"] > 0     # ... and the spill degraded
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g0))


@pytest.mark.slow
def test_revolve_gradient_vs_fd():
    from tclb_tpu.adjoint import fd_test
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    rev = make_revolve_gradient(m, design, 6, snapshots=3, engine="xla",
                                shape=(8, 16), dtype=jnp.float64)
    obj, g, _ = rev(theta0, lat.state, lat.params)

    def loss(th):
        o, _, _ = rev(th, lat.state, lat.params)
        return o

    checks = fd_test(loss, jnp.asarray(g), theta0, n_checks=4, eps=1e-6)
    for c in checks:
        # probed indices may fall outside the design mask (both grads 0)
        if c["adjoint"] == 0.0 and abs(c["fd"]) < 1e-9:
            continue
        assert c["rel_err"] < 1e-6, c


@pytest.mark.slow
def test_revolve_d3q19_xla():
    m = get_model("d3q19_adj")
    shape = (4, 8, 16)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "Velocity": 0.02, "Porocity": 0.5,
                            "DragInObj": 1.0})
    flags = np.full(shape, m.flag_for("MRT"), np.uint16)
    flags[:, 0, :] = flags[:, -1, :] = m.flag_for("Wall")
    flags[1:3, 2:6, 4:12] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)

    ref = make_unsteady_gradient(m, design, 6, levels=1)
    o_ref, g_ref, _ = ref(theta0, lat.state, lat.params)
    rev = make_revolve_gradient(m, design, 6, snapshots=2, engine="xla",
                                shape=shape, dtype=jnp.float64)
    o_rev, g_rev, _ = rev(theta0, lat.state, lat.params)
    assert float(o_rev) == float(o_ref)
    _assert_ulp_close(g_rev, g_ref)


# --------------------------------------------------------------------------- #
# Gradient serving (fast tier: tiny case, the serving invariants)
# --------------------------------------------------------------------------- #


def _grad_spec(m, flags, niter=4):
    return JobSpec(
        model=m, shape=flags.shape, case=Case(), niter=niter,
        flags=flags, dtype=jnp.float64,
        base_settings={"nu": 0.1, "Velocity": 0.05, "Porocity": 0.5,
                       "DragInObj": 1.0, "MaterialInObj": 0.0},
        grad=GradSpec(design=InternalTopology(m), levels=2))


@pytest.mark.slow
def test_grad_serving_batched_parity():
    """N batched adjoint evaluations == N direct make_unsteady_gradient
    runs, bit for bit, and the sequential degrade target agrees.
    (slow: compiles a batched f64 VJP — CI's fast job covers the same
    invariant through the inline gradient-serving smoke)"""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    flags = np.asarray(lat._flags_host())
    spec = _grad_spec(m, flags)

    with Scheduler(autostart=False) as sched:
        ev = make_grad_evaluator(sched, spec)
        thetas = [theta0, jnp.clip(theta0 + 0.25, 0.0, 1.0)]
        out = ev(thetas)

    gfn = make_unsteady_gradient(m, design, spec.niter, levels=2)
    for th, (obj, grad) in zip(thetas, out):
        o_ref, g_ref, _ = gfn(th, lat.state, lat.params)
        assert obj == float(o_ref)
        np.testing.assert_array_equal(np.asarray(grad),
                                      np.asarray(g_ref))

    plan = EnsemblePlan(m, flags.shape, flags=flags, dtype=jnp.float64,
                        base_settings=spec.base_settings, grad=spec.grad)
    r = plan.run_sequential(Case(theta=theta0), spec.niter)
    assert r.objective == out[0][0]
    np.testing.assert_allclose(np.asarray(r.grad),
                               np.asarray(out[0][1]), rtol=1e-12)


@pytest.mark.slow
def test_grad_line_search_single_executable():
    """The CI serving smoke invariant: a whole batched line search runs
    through ONE AOT-compiled VJP executable (every dispatch shares the
    candidate width, so the cache compiles exactly once).  (slow: the
    fast CI job asserts the same misses==1 invariant inline)"""
    m, lat = _setup()
    design = InternalTopology(m)
    theta0 = design.get(lat.state, lat.params)
    spec = _grad_spec(m, np.asarray(lat._flags_host()))

    with Scheduler(autostart=False) as sched:
        ev = make_grad_evaluator(sched, spec)
        hist = []
        theta, obj = batched_descent(
            ev, theta0, max_iter=2, steps=(0.5, 1.0, 2.0, 4.0),
            bounds=(0.0, 1.0), callback=lambda k, o, t: hist.append(o))
        stats = sched.cache.stats()

    assert obj <= hist[0]
    assert stats["misses"] == 1, \
        f"line search must reuse one compiled VJP executable: {stats}"
    assert stats["hits"] >= 2


def test_grad_jobs_bin_separately_from_forward():
    """A gradient job must never batch with a forward job of the same
    class (their compiled programs differ)."""
    from tclb_tpu.serve.scheduler import _bin_key
    m, lat = _setup()
    flags = np.asarray(lat._flags_host())
    fwd = _grad_spec(m, flags)
    fwd = JobSpec(model=fwd.model, shape=fwd.shape, case=Case(),
                  niter=fwd.niter, flags=flags, dtype=fwd.dtype,
                  base_settings=fwd.base_settings)
    grad = _grad_spec(m, flags)
    assert _bin_key(fwd) != _bin_key(grad)
    # two grad specs of the same design class DO bin together
    assert _bin_key(grad) == _bin_key(_grad_spec(m, flags))


# --------------------------------------------------------------------------- #
# Kill-resume: a SIGKILLed spilling run leaves only CRC-valid files
# --------------------------------------------------------------------------- #

_KILL_SCRIPT = r"""
import os, sys
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from tclb_tpu.adjoint.revolve import SnapshotStore
store = SnapshotStore(mem_slots=0, spill_dir=sys.argv[1])
k = 0
while True:
    store.put(k, (np.full((64, 64), float(k)), np.int32(k)))
    k += 1
    if k == 3:
        print("SPILLING", flush=True)
"""


@pytest.mark.slow
def test_spill_kill_leaves_only_crc_valid_files(tmp_path):
    """SIGKILL mid-spill: every surviving payload file must verify
    against its CRC sidecar (atomic rename + sidecar-after-payload
    ordering), so a resume can trust whatever it finds."""
    import zlib
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILL_SCRIPT, str(tmp_path)],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))})
    try:
        assert proc.stdout.readline().strip() == "SPILLING"
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()
    files = [p for p in os.listdir(tmp_path) if p.endswith(".npy")]
    checked = 0
    for p in files:
        crc_path = os.path.join(tmp_path, p + ".crc")
        if not os.path.exists(crc_path):
            # payload without sidecar: the writer died between the
            # atomic payload rename and the sidecar write — the resume
            # protocol discards it, so it is not a valid-looking lie
            continue
        with open(os.path.join(tmp_path, p), "rb") as fh:
            payload = fh.read()
        with open(crc_path) as fh:
            expect = int(fh.read().strip())
        assert zlib.crc32(payload) & 0xFFFFFFFF == expect, p
        checked += 1
    # the run spilled, so SOMETHING must have survived verification
    assert checked + len(files) > 0
