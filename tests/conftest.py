"""Test environment: force CPU with 8 virtual devices (multi-chip emulation —
the reference tests its MPI path by running any-rank-count CPU builds on one
box, SURVEY.md §4.8; we do the same with XLA host devices) and enable f64 so
goldens can use the reference's 1e-10 tolerance model (tools/csvdiff).

Note: the environment's sitecustomize imports jax at interpreter startup, so
plain env-var assignment here is too late; ``jax.config.update`` still works
as long as no backend has been initialized yet.
"""

import os
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
# flight-recorder dumps (telemetry/live.py) go to a scratch dir, not the
# repo checkout, when eviction/failcheck tests trigger them
os.environ.setdefault("TCLB_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="tclb-flight-"))
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
