"""Fault-injection layer: spec parsing, determinism, the no-op gate,
per-point firing semantics and env activation."""

import errno
import os
import subprocess
import sys

import pytest

from tclb_tpu import faults, telemetry
from tclb_tpu.faults import FaultPlan, FaultRule, InjectedFault


@pytest.fixture(autouse=True)
def _clean_plan():
    faults.uninstall()
    yield
    faults.uninstall()


# -- spec parsing ------------------------------------------------------------- #


def test_parse_full_grammar():
    plan = FaultPlan.parse(
        "seed=7; serve.lane_dispatch:error:n=2 ;"
        "checkpoint.write:enospc:n=1:after=1;"
        "serve.stage:slow:delay=0.25;store.journal:torn:p=0.5")
    assert plan.seed == 7
    assert len(plan.rules) == 4
    r0, r1, r2, r3 = plan.rules
    assert (r0.point, r0.mode, r0.times) == ("serve.lane_dispatch",
                                             "error", 2)
    assert (r1.mode, r1.times, r1.after) == ("enospc", 1, 1)
    assert (r2.mode, r2.delay_s) == ("slow", 0.25)
    assert (r3.mode, r3.prob) == ("torn", 0.5)


def test_parse_defaults_to_error_mode():
    plan = FaultPlan.parse("gateway.request")
    assert plan.rules[0].mode == "error"
    assert plan.rules[0].prob == 1.0
    assert plan.rules[0].times is None


def test_parse_rejects_unknown_point_mode_and_knob():
    with pytest.raises(ValueError, match="unknown injection point"):
        FaultPlan.parse("serve.lane_dispatc:error")
    with pytest.raises(ValueError, match="unknown fault mode"):
        FaultPlan.parse("serve.stage:explode")
    with pytest.raises(ValueError, match="unknown fault-rule knob"):
        FaultPlan.parse("serve.stage:error:bogus=1")
    with pytest.raises(ValueError, match="p must be"):
        FaultRule("serve.stage", prob=1.5)


# -- the no-op gate ----------------------------------------------------------- #


def test_fire_is_noop_without_plan():
    assert not faults.active()
    assert faults.fire("serve.lane_dispatch") is None
    assert faults.fire("checkpoint.write", file="x") is None


def test_fire_rejects_unregistered_point_when_active():
    faults.install(FaultPlan.parse("serve.stage:error"))
    with pytest.raises(ValueError, match="unregistered injection point"):
        faults.fire("serve.typo")


def test_uninstall_restores_noop():
    faults.install(FaultPlan.parse("serve.stage:error"))
    assert faults.active()
    faults.uninstall()
    assert not faults.active()
    assert faults.fire("serve.stage") is None


# -- firing semantics --------------------------------------------------------- #


def test_modes_raise_sleep_and_tear():
    faults.install(FaultPlan.parse(
        "serve.stage:error;checkpoint.write:enospc;store.journal:torn"))
    with pytest.raises(InjectedFault):
        faults.fire("serve.stage")
    with pytest.raises(OSError) as ei:
        faults.fire("checkpoint.write")
    assert ei.value.errno == errno.ENOSPC
    assert faults.fire("store.journal") == "torn"
    # points with no rule stay clean
    assert faults.fire("gateway.request") is None


def test_n_and_after_budgets():
    faults.install(FaultPlan.parse("serve.compile:error:n=2:after=1"))
    assert faults.fire("serve.compile") is None          # hit 1: skipped
    for _ in range(2):                                   # hits 2-3: inject
        with pytest.raises(InjectedFault):
            faults.fire("serve.compile")
    assert faults.fire("serve.compile") is None          # budget spent
    st = faults.stats()
    assert st["hits"]["serve.compile"] == 4
    assert st["injected"][0]["count"] == 2


def test_probabilistic_rule_is_deterministic_per_seed():
    def trace(seed):
        faults.install(FaultPlan(
            rules=(FaultRule("serve.stage", prob=0.5),), seed=seed))
        out = []
        for _ in range(32):
            try:
                faults.fire("serve.stage")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    a, b = trace(7), trace(7)
    assert a == b                       # same seed -> same schedule
    assert 0 < sum(a) < 32              # actually probabilistic
    assert trace(8) != a                # seed changes the schedule


def test_injection_emits_event_and_counter():
    events = []
    telemetry.subscribe(events.append)
    try:
        faults.install(FaultPlan.parse("serve.stage:error:n=1"))
        with pytest.raises(InjectedFault):
            faults.fire("serve.stage", lane=3)
    finally:
        telemetry.unsubscribe(events.append)
    inj = [e for e in events if e.get("kind") == "fault.injected"]
    assert len(inj) == 1
    assert inj[0]["point"] == "serve.stage"
    assert inj[0]["mode"] == "error"
    assert inj[0]["lane"] == 3


# -- env activation ----------------------------------------------------------- #


def test_env_var_installs_plan_at_import():
    code = ("import tclb_tpu.faults as f; "
            "print(f.active(), len(f._states), f._plan.seed)")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TCLB_FAULTS="seed=3;serve.stage:slow:delay=0.01")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, check=True)
    assert out.stdout.split() == ["True", "1", "3"]
