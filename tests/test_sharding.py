"""Multi-device sharding tests on the 8-virtual-CPU-device mesh — the
framework's equivalent of the reference's MPI-path testing (SURVEY.md §4.8:
any-rank-count CPU runs on one box).  Exit test per SURVEY.md §7.3: identical
results on 1 chip vs N chips."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model
from tclb_tpu.parallel.mesh import (choose_decomposition, make_mesh,
                                    decomposition_overhead)


def _karman_flags(m, ny, nx):
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[:, 0] = m.flag_for("WVelocity", "MRT")
    flags[:, -1] = m.flag_for("EPressure", "MRT")
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    flags[12:20, 20:28] = m.flag_for("Wall")
    flags[1:-1, 4] = m.flag_for("MRT", "Inlet")
    flags[1:-1, -5] = m.flag_for("MRT", "Outlet")
    return flags


def test_choose_decomposition_prefers_whole_x():
    d = choose_decomposition((64, 128), 8)
    assert d["x"] == 1 and d["y"] == 8
    d = choose_decomposition((32, 32, 128), 8)
    assert d["x"] == 1 and d["z"] * d["y"] == 8


def test_choose_decomposition_overhead():
    d = choose_decomposition((64, 128), 4)
    assert decomposition_overhead((64, 128), d) > 0


def test_sharded_matches_single_device():
    assert len(jax.devices()) == 8, "conftest must provide 8 cpu devices"
    m = get_model("d2q9")
    ny, nx = 32, 64
    flags = _karman_flags(m, ny, nx)
    settings = {"nu": 0.05, "Velocity": 0.02}

    ref = Lattice(m, (ny, nx), dtype=jnp.float64, settings=settings)
    ref.set_flags(flags)
    ref.init()
    ref.iterate(100)

    mesh = make_mesh((ny, nx), decomposition={"y": 4, "x": 2})
    lat = Lattice(m, (ny, nx), dtype=jnp.float64, settings=settings,
                  mesh=mesh)
    lat.set_flags(flags)
    lat.init()
    lat.iterate(100)

    np.testing.assert_allclose(np.asarray(lat.state.fields),
                               np.asarray(ref.state.fields),
                               rtol=0, atol=1e-12)
    # globals identical too (psum vs global sum, fp-order tolerance)
    g_ref, g_sh = ref.get_globals(), lat.get_globals()
    for k in g_ref:
        assert np.isclose(g_sh[k], g_ref[k], rtol=1e-10, atol=1e-14), k


def test_sharded_field_load_crosses_boundaries():
    """A model whose Run reads Field neighbors via ctx.load must see data
    from the adjacent shard, not its own wrapped edge (regression for the
    halo-aware loader)."""
    from tclb_tpu.core.registry import ModelDef
    from tclb_tpu.core.lattice import Lattice as Lat

    def build():
        d = ModelDef("difftest", ndim=2)
        d.add_density("c[0]")
        d.add_field("phi", dx=(-1, 1), dy=(-1, 1))

        def run(ctx):
            phi = (ctx.load("phi", dx=1) + ctx.load("phi", dx=-1)
                   + ctx.load("phi", dy=1) + ctx.load("phi", dy=-1)) * 0.25
            return ctx.store({"c": phi[None], "phi": phi[None]})

        def init(ctx):
            return ctx._fields

        m = d.finalize()
        return m.bind(run=run, init=init)

    ny, nx = 16, 32
    rng = np.random.default_rng(0)
    phi0 = rng.random((ny, nx))

    results = []
    for mesh in (None, make_mesh((ny, nx), decomposition={"y": 4, "x": 2})):
        m = build()
        lat = Lat(m, (ny, nx), dtype=jnp.float64, mesh=mesh)
        lat.set_density("phi", phi0)
        lat.iterate(5)
        results.append(np.asarray(lat.get_density("phi")))
    np.testing.assert_allclose(results[1], results[0], rtol=0, atol=1e-15)


def test_mesh_axis_validation():
    from jax.sharding import Mesh
    from tclb_tpu.parallel.halo import make_sharded_iterate
    m = get_model("d2q9")
    bad = Mesh(np.array(jax.devices()[:2]), ("y",))
    with pytest.raises(ValueError, match="mesh axes"):
        make_sharded_iterate(m, bad)


def test_sharded_8way_y():
    m = get_model("d2q9")
    ny, nx = 64, 32
    mesh = make_mesh((ny, nx), decomposition={"y": 8, "x": 1})
    lat = Lattice(m, (ny, nx), dtype=jnp.float64,
                  settings={"nu": 0.1, "GravitationX": 1e-6}, mesh=mesh)
    flags = np.full((ny, nx), m.flag_for("MRT"), dtype=np.uint16)
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(200)
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(u).all()
    # mid-channel faster than near-wall: the halo exchange really moves data
    assert u[0, ny // 2].mean() > u[0, 1].mean() > 0
