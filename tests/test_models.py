"""Model catalogue tests: every registered model runs, conserves mass with
bounce-back walls + periodic wrap, and stays finite; hydrodynamic families
reproduce the analytic Poiseuille profile (the reference's regression-test
role, tools/tests.sh + the d2q9_npe_guo python physics checks)."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest

from tclb_tpu.core.lattice import Lattice
from tclb_tpu.models import get_model, list_models

pytestmark = pytest.mark.slow  # full-coverage job; the default lap runs the fast smoke suite

HYDRO_2D = ["d2q9", "d2q9_SRT", "d2q9_cumulant", "d2q9_inc", "d2q9_les"]
HYDRO_3D = ["d3q19", "d3q19_les", "d3q27", "d3q27_BGK", "d3q27_BGK_galcor",
            "d3q27_cumulant"]


def _flags_channel(m, shape):
    """Walls on the first lattice axis extremes, collision elsewhere."""
    coll = "MRT" if "MRT" in {t.name for t in m.node_types.values()
                              if t.group == "COLLISION"} else "BGK"
    coll = ("MRT" if m.name in ("d2q9", "d2q9_adj") else "BGK")
    flags = np.full(shape, m.flag_for(coll), dtype=np.uint16)
    flags[0] = m.flag_for("Wall")
    flags[-1] = m.flag_for("Wall")
    return flags


def _poiseuille_check(model_name, shape, g=1e-5, nu=0.1, iters=3000,
                      rtol=0.02):
    m = get_model(model_name)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": nu, "GravitationX": g})
    lat.set_flags(_flags_channel(m, shape))
    lat.init()
    lat.iterate(iters)
    u = np.asarray(lat.get_quantity("U"))[0]          # ux
    # profile across the first axis, averaged over the rest
    prof = u.reshape(shape[0], -1).mean(axis=1)
    h = shape[0] - 2                                  # fluid width (nodes)
    # bounce-back wall planes sit half-way between wall and fluid nodes:
    # u(y) = g/(2 nu) (y - 0.5)(h + 0.5 - y) at fluid rows y = 1..h
    y = np.arange(1, shape[0] - 1, dtype=np.float64)
    ana = g / (2 * nu) * (y - 0.5) * (h + 0.5 - y)
    np.testing.assert_allclose(prof[1:-1], ana, rtol=rtol)
    return lat


@pytest.mark.parametrize("name", HYDRO_2D)
def test_2d_mass_conservation_and_finite(name):
    m = get_model(name)
    shape = (10, 12)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.05, "GravitationX": 1e-5})
    lat.set_flags(_flags_channel(m, shape))
    lat.init()
    mass0 = float(np.asarray(lat.get_quantity("Rho")).sum())
    lat.iterate(50)
    rho = np.asarray(lat.get_quantity("Rho"))
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(rho).all() and np.isfinite(u).all()
    assert float(rho.sum()) == pytest.approx(mass0, rel=1e-10)
    assert u[0, 5].mean() > 0          # flow responds to the force


@pytest.mark.parametrize("name", HYDRO_3D)
def test_3d_mass_conservation_and_finite(name):
    m = get_model(name)
    shape = (6, 8, 10)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.05, "GravitationX": 1e-5})
    lat.set_flags(_flags_channel(m, shape))
    lat.init()
    mass0 = float(np.asarray(lat.get_quantity("Rho")).sum())
    lat.iterate(30)
    rho = np.asarray(lat.get_quantity("Rho"))
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(rho).all() and np.isfinite(u).all()
    assert float(rho.sum()) == pytest.approx(mass0, rel=1e-10)
    assert u[0, 3, 4].mean() > 0


@pytest.mark.parametrize("name", ["d2q9_SRT", "d2q9_cumulant", "d2q9_inc"])
def test_2d_poiseuille_profile(name):
    _poiseuille_check(name, (18, 4))


def test_3d_poiseuille_profile():
    _poiseuille_check("d3q27_cumulant", (14, 3, 4), iters=2000, rtol=0.03)


def test_d3q19_poiseuille_profile():
    _poiseuille_check("d3q19", (14, 3, 4), iters=2000, rtol=0.03)


def test_inlet_outlet_3d():
    """Velocity inlet / pressure outlet drive a through-flow in 3D."""
    m = get_model("d3q19")
    shape = (6, 8, 16)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "Velocity": 0.02})
    flags = np.full(shape, m.flag_for("BGK"), dtype=np.uint16)
    flags[0], flags[-1] = m.flag_for("Wall"), m.flag_for("Wall")
    flags[1:-1, :, 0] = m.flag_for("WVelocity", "BGK")
    flags[1:-1, :, -1] = m.flag_for("EPressure", "BGK")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(200)
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(u).all()
    assert u[0, 3, 4, 8] > 1e-4        # through-flow developed


def test_symmetry_faces_3d():
    """N/S symmetry mirrors keep the flow finite and symmetric-ish."""
    m = get_model("d3q27_cumulant")
    shape = (6, 10, 8)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.05, "ForceX": 1e-5})
    flags = np.full(shape, m.flag_for("BGK"), dtype=np.uint16)
    flags[0], flags[-1] = m.flag_for("Wall"), m.flag_for("Wall")
    flags[:, 0, :] = m.flag_for("SSymmetry")
    flags[:, -1, :] = m.flag_for("NSymmetry")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(50)
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(u).all()
    assert u[0, 3, 5].mean() > 0


def test_heat_advects_temperature():
    """Hot inlet + flow: temperature front moves downstream; Heater pins."""
    m = get_model("d2q9_heat")
    shape = (10, 24)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "InletVelocity": 0.05,
                            "InletTemperature": 2.0, "InitTemperature": 1.0,
                            "FluidAlfa": 0.05})
    flags = np.full(shape, m.flag_for("BGK"), dtype=np.uint16)
    flags[0], flags[-1] = m.flag_for("Wall"), m.flag_for("Wall")
    flags[1:-1, 0] = m.flag_for("WVelocity", "BGK")
    flags[1:-1, -1] = m.flag_for("EPressure", "BGK")
    flags[5, 10] = m.flag_for("BGK", "Heater")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(300)
    T = np.asarray(lat.get_quantity("T"))
    assert np.isfinite(T).all()
    assert T[5, 2] > 1.5                # hot fluid entered
    assert T[5, 10] > 10.0              # heater pinned toward 100
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(u).all()


def test_kuper_phase_separation():
    """Reference drop.xml regime: a vapor bubble (rho=0.0145) inside
    liquid (rho=3.26) at T=0.56 persists with a sharp interface — the
    vdW pseudopotential holds the 225x density ratio (with the round-1
    sign-flipped force this configuration exploded within 20 steps)."""
    m = get_model("d2q9_kuper")
    shape = (48, 48)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"omega": 1.0, "Temperature": 0.56,
                            "Density": 3.2600529440452366, "Magic": 0.01,
                            "FAcc": 1.0, "MagicA": -0.152,
                            "MagicF": -2.0 / 3.0})
    # vapor bubble via a settings zone (the drop.xml <None name="zdrop">
    # mechanism) so Init computes f and phi consistently in one pass
    flags = np.full(shape, m.flag_for("MRT"), dtype=np.uint16)
    yy, xx = np.mgrid[0:48, 0:48]
    bubble = ((yy - 24) ** 2 + (xx - 24) ** 2) < 100
    flags[bubble] = m.flag_for("MRT", zone=1)
    lat.set_flags(flags)
    lat.set_setting("Density", 0.014500641645077492, zone=1)
    lat.init()
    mass0 = float(np.asarray(lat.get_quantity("Rho")).sum())
    lat.iterate(400)
    rho2 = np.asarray(lat.get_quantity("Rho"))
    assert np.isfinite(rho2).all()
    # mass conserved exactly; the bubble survives with both phases intact
    assert float(rho2.sum()) == pytest.approx(mass0, rel=1e-12)
    assert rho2[24, 24] < 0.2          # vapor core
    assert rho2[4, 4] > 3.0            # liquid bulk
    p = np.asarray(lat.get_quantity("P"))
    assert np.isfinite(p).all()
    # Laplace law direction: pressure inside the bubble differs from bulk
    assert abs(p[24, 24] - p[4, 4]) > 0


def test_sw_gravity_wave():
    """A height bump spreads as a gravity wave, mass conserved."""
    m = get_model("sw")
    shape = (20, 20)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "Gravity": 0.5, "Height": 1.0})
    flags = np.full(shape, m.flag_for("MRT"), dtype=np.uint16)
    lat.set_flags(flags)
    lat.init()
    rho0 = np.asarray(lat.get_quantity("Rho"))
    # bump the height in the middle
    f0 = np.asarray(lat.state.fields)
    bump = np.zeros(shape)
    bump[9:11, 9:11] = 0.1
    rest = m.storage_names[m.groups["f"][0]]     # rest population
    lat.set_density(rest, f0[m.storage_index[rest]] + bump)
    mass0 = float(np.asarray(lat.get_quantity("Rho")).sum())
    lat.iterate(40)
    rho = np.asarray(lat.get_quantity("Rho"))
    assert np.isfinite(rho).all()
    assert float(rho.sum()) == pytest.approx(mass0, rel=1e-10)
    # wave propagated away from the center
    assert rho[9, 9] < rho0[9, 9] + 0.1


def test_wave2d_oscillates():
    m = get_model("wave2d")
    shape = (16, 16)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"WaveK": 0.1, "Loss": 1.0, "SolidH": 1.0})
    flags = np.full(shape, 0, dtype=np.uint16)
    flags[0, :] = m.flag_for("Wall")
    flags[-1, :] = m.flag_for("Wall")
    flags[:, 0] = m.flag_for("Wall")
    flags[:, -1] = m.flag_for("Wall")
    flags[7:9, 7:9] = m.flag_for("Solid")
    lat.set_flags(flags)
    lat.init()
    h0 = np.asarray(lat.get_quantity("H"))
    assert h0[7, 7] == 1.0
    lat.iterate(30)
    h = np.asarray(lat.get_quantity("H"))
    assert np.isfinite(h).all()
    assert abs(h[7, 7]) < 1.0           # wave left the source
    assert np.abs(h[3, :]).max() > 1e-4  # and reached elsewhere


def test_wave_fields_dirichlet():
    m = get_model("wave")
    shape = (12, 12)
    lat = Lattice(m, shape, dtype=jnp.float64, settings={"Speed": 0.2})
    flags = np.zeros(shape, dtype=np.uint16)
    flags[0, :] = m.flag_for("Dirichlet", zone=1)
    lat.set_flags(flags)
    lat.set_setting("Value", 1.0, zone=1)
    lat.init()
    lat.iterate(40)
    u = np.asarray(lat.get_quantity("U"))
    assert np.isfinite(u).all()
    assert u[0, 5] == pytest.approx(1.0)   # Dirichlet row pinned
    assert np.abs(u[4, :]).max() > 1e-5    # wave propagates inward


def test_diff_source_gradient():
    """d2q9_diff: source design field drives concentration; adjoint wrt w."""
    from tclb_tpu.adjoint import InternalTopology, make_unsteady_gradient
    m = get_model("d2q9_diff")
    shape = (10, 10)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"Diffusivity": 0.1, "UX": 0.02,
                            "Source": 0.01, "TotalCInObj": 1.0})
    flags = np.full(shape, m.flag_for("BGK"), dtype=np.uint16)
    flags[4:6, 4:6] |= m.flag_for("DesignSpace")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    gf = make_unsteady_gradient(m, design, 6, levels=1)
    theta = design.get(lat.state, lat.params)
    obj, g, _ = gf(theta, lat.state, lat.params)
    g = np.asarray(g)
    assert np.isfinite(float(obj))
    assert np.abs(g).max() > 0          # source influences total C


def test_hb_destruction():
    m = get_model("d2q9_hb")
    shape = (10, 16)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "InletVelocity": 0.05,
                            "DestructionRate": 0.1,
                            "DestructionPower": 0.5,
                            "InitTemperature": 1.0, "FluidAlfa": 0.1})
    flags = np.full(shape, m.flag_for("BGK"), dtype=np.uint16)
    flags[0], flags[-1] = m.flag_for("Wall"), m.flag_for("Wall")
    flags[1:-1, 0] = m.flag_for("WVelocity", "BGK")
    flags[1:-1, -1] = m.flag_for("EPressure", "BGK")
    flags[4:6, 8] = m.flag_for("BGK", "Destroy")
    lat.set_flags(flags)
    lat.init()
    lat.iterate(100)
    T = np.asarray(lat.get_quantity("T"))
    assert np.isfinite(T).all()
    assert T[4, 8] < 1.0                # eroded at Destroy nodes
    ss = np.asarray(lat.get_quantity("SS"))
    assert np.isfinite(ss).all()


@pytest.mark.parametrize("name", ["d2q9_heat_adj", "d2q9_plate",
                                  "d2q9_optimalMixing", "d2q9_solid",
                                  "d3q19_heat", "d3q19_heat_adj",
                                  "d3q19_adj"])
def test_variant_models_run_finite(name):
    m = get_model(name)
    shape = (8, 12) if m.ndim == 2 else (6, 6, 10)
    settings = {"nu": 0.1}
    if "InletVelocity" in m.setting_index:
        settings["InletVelocity"] = 0.02
    if "Velocity" in m.setting_index:
        settings["Velocity"] = 0.02
    lat = Lattice(m, shape, dtype=jnp.float64, settings=settings)
    lat.set_flags(_flags_channel(m, shape))
    lat.init()
    lat.iterate(30)
    for q in m.quantities:
        if q.adjoint:
            continue
        assert np.isfinite(np.asarray(lat.get_quantity(q.name))).all(), \
            (name, q.name)


def test_d3q19_kuper_runs():
    m = get_model("d3q19_kuper")
    shape = (8, 8, 8)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.18, "Temperature": 0.56,
                            "Density": 3.26, "Magic": 0.01})
    lat.set_flags(np.full(shape, m.flag_for("BGK"), dtype=np.uint16))
    lat.init()
    mass0 = float(np.asarray(lat.get_quantity("Rho")).sum())
    lat.iterate(30)
    rho = np.asarray(lat.get_quantity("Rho"))
    assert np.isfinite(rho).all()
    assert float(rho.sum()) == pytest.approx(mass0, rel=1e-10)


def test_heat_adj_gradient():
    """The heat_adj.xml benchmark case family: gradient of HeatFlux wrt the
    conjugate-design field checks against finite differences."""
    from tclb_tpu.adjoint import (InternalTopology, fd_test,
                                  make_objective_run,
                                  make_unsteady_gradient)
    m = get_model("d2q9_heat_adj")
    shape = (8, 12)
    lat = Lattice(m, shape, dtype=jnp.float64,
                  settings={"nu": 0.1, "InletVelocity": 0.05,
                            "InletTemperature": 2.0,
                            "HeatFluxInObj": 1.0, "Porocity": 0.5})
    flags = np.full(shape, m.flag_for("BGK"), dtype=np.uint16)
    flags[0], flags[-1] = m.flag_for("Wall"), m.flag_for("Wall")
    flags[1:-1, 0] = m.flag_for("WVelocity", "BGK")
    flags[1:-1, -1] = m.flag_for("EPressure", "BGK")
    flags[2:6, 4:8] |= m.flag_for("DesignSpace")
    flags[1:-1, -2] |= m.flag_for("Outlet")
    lat.set_flags(flags)
    lat.init()
    design = InternalTopology(m)
    gf = make_unsteady_gradient(m, design, 6, levels=2)
    theta = design.get(lat.state, lat.params)
    obj, g, _ = gf(theta, lat.state, lat.params)
    assert np.isfinite(float(obj)) and np.abs(np.asarray(g)).max() > 0
    run = make_objective_run(m, 6, levels=2)

    @jax.jit
    def loss(th):
        s2, p2 = design.put(th, lat.state, lat.params)
        return run(s2, p2)[0]

    import jax as _jax
    for c in fd_test(loss, _jax.numpy.asarray(g), theta, n_checks=3,
                     eps=1e-6, seed=7):
        if c["adjoint"] == 0 and abs(c["fd"]) < 1e-10:
            continue
        assert c["rel_err"] < 1e-5, c


def test_all_registered_models_build():
    for name in list_models():
        m = get_model(name)
        assert m.run is not None and m.init is not None, name
        assert m.n_storage >= 1


def test_cumulant_galilean_correction_improves_invariance():
    """Geier's Galilean correction: the decay rate of a shear wave advected
    at background velocity U0 must be closer to the rest-frame rate with
    GalileanCorrection=1 than with 0 (reference
    src/d3q27_cumulant/Dynamics.c.Rt:299-319)."""
    import jax.numpy as jnp
    m = get_model("d3q27_cumulant")
    n = 32
    u0, amp, nu = 0.2, 0.005, 0.02

    def decay(gc, background):
        lat = Lattice(m, (4, 4, n), dtype=jnp.float64,
                      settings={"nu": nu, "GalileanCorrection": gc})
        lat.set_flags(np.full((4, 4, n), m.flag_for("MRT"),
                              dtype=np.uint16))
        lat.init()
        # shear wave uy(x) = amp sin(2 pi x / n) on top of ux = background
        x = np.arange(n)
        uy = amp * np.sin(2 * np.pi * x / n)
        from tclb_tpu.ops import lbm
        from tclb_tpu.models.d3q27_cumulant import E, W
        shape = (4, 4, n)
        rho = np.ones(shape)
        ux = np.full(shape, background)
        uyf = np.broadcast_to(uy, shape).copy()
        feq = np.asarray(lbm.equilibrium(
            E, W, jnp.asarray(rho),
            (jnp.asarray(ux), jnp.asarray(uyf), jnp.zeros(shape))))
        for i in range(27):
            lat.set_density(f"f[{i}]", feq[i])
        niter = 300
        lat.iterate(niter)
        u = np.asarray(lat.get_quantity("U"))
        a1 = 2 * np.abs(np.fft.rfft(u[1][2, 2, :])[1]) / n
        k = 2 * np.pi / n
        return -np.log(a1 / amp) / (k * k * niter)   # measured nu

    nu_rest = decay(0.0, 0.0)
    nu_gc0 = decay(0.0, u0)
    nu_gc1 = decay(1.0, u0)
    # rest frame: viscosity accurate regardless
    np.testing.assert_allclose(nu_rest, nu, rtol=0.05)
    # advected frame: the corrected run is closer to the rest-frame value
    assert abs(nu_gc1 - nu_rest) < abs(nu_gc0 - nu_rest), \
        (nu_rest, nu_gc0, nu_gc1)


def test_kuper_adj_init_and_step():
    """d2q9_kuper_adj composes d2q9_kuper's init through the write-set
    contract (regression: the ctx.store dict change broke its init)."""
    import jax.numpy as jnp
    m = get_model("d2q9_kuper_adj")
    lat = Lattice(m, (16, 16), dtype=jnp.float64,
                  settings={"nu": 0.18, "Temperature": 0.56,
                            "Density": 3.26, "Magic": 0.01, "FAcc": 1.0})
    lat.set_flags(np.full((16, 16), m.flag_for("MRT"), dtype=np.uint16))
    lat.init()
    assert float(np.asarray(lat.get_density("wd")).min()) == 1.0
    lat.iterate(5)
    assert np.isfinite(np.asarray(lat.get_quantity("Rho"))).all()
