"""Unit tests of the model registry / node-type packing — coverage the
reference lacks entirely (its conf.R derivations are only exercised end-to-end,
SURVEY.md §4)."""

import numpy as np

from tclb_tpu.models import get_model


def test_node_type_packing_disjoint_groups():
    m = get_model("d2q9")
    masks = [t for g, t in m.group_masks.items()
             if g not in ("ALL", "NONE")]
    # group bit-spans must not overlap
    for i, a in enumerate(masks):
        for b in masks[i + 1:]:
            assert a & b == 0
    # values stay within their group's mask
    for t in m.node_types.values():
        assert t.value & ~t.mask == 0


def test_flag_compose_and_zone():
    m = get_model("d2q9")
    v = m.flag_for("MRT", "Outlet", zone=3)
    assert v & m.group_masks["COLLISION"] == m.nt_value("MRT")
    assert v & m.group_masks["OBJECTIVE"] == m.nt_value("Outlet")
    assert v >> m.zone_shift == 3
    assert m.zone_max >= 2  # room for settings zones in 16 bits


def test_derived_settings():
    m = get_model("d2q9")
    vec = m.settings_vector({"nu": 0.02})
    omega = vec[m.setting_index["omega"]]
    assert np.isclose(omega, 1.0 / (3 * 0.02 + 0.5))
    # derived chains: nu -> omega -> S78 = 1 - omega
    assert np.isclose(vec[m.setting_index["S78"]], 1.0 - omega)


def test_globals_imply_inobj_settings():
    m = get_model("d2q9")
    for g in m.globals_:
        assert g.name + "InObj" in m.setting_index


def test_streaming_vectors():
    m = get_model("d2q9")
    ei = m.ei[:9]
    # d2q9 set: one rest + 4 axis + 4 diagonal, momentum-free
    assert (ei.sum(axis=0) == 0).all()
    assert sorted((np.abs(e).sum() for e in ei)) == [0, 1, 1, 1, 1, 2, 2, 2, 2]


def test_packing_overflow_raises():
    """More node types than fit the 16-bit flag must fail loudly
    (reference conf.R packs groups into the flag_t; overflow there is a
    build error — here a registry error)."""
    from tclb_tpu.core.registry import ModelDef
    import pytest as _pytest
    d = ModelDef("overflow", ndim=2)
    d.add_density("f0")
    # 6 groups x 15 members = 4 bits each = 24 bits > 16
    for g in range(6):
        for i in range(15):
            d.add_node_type(f"T{g}_{i}", f"G{g}")
    with _pytest.raises(ValueError, match="bits"):
        d.finalize()


def test_packing_group_isolation_and_zone_bits():
    """Group masks are disjoint, values stay within their mask, and the
    zone field occupies exactly the remaining high bits."""
    m = get_model("d2q9")
    masks = [v for k, v in m.group_masks.items()
             if k not in ("ALL", "SETTINGZONE", "NONE")]
    for i, a in enumerate(masks):
        for b in masks[i + 1:]:
            assert a & b == 0
    for t in m.node_types.values():
        assert t.value & ~t.mask == 0
    used = 0
    for v in masks:
        used |= v
    assert used | m.group_masks["SETTINGZONE"] == 0xFFFF
    assert used & m.group_masks["SETTINGZONE"] == 0
    # flag_for composes type bits + zone bits reversibly
    f = m.flag_for("WVelocity", "MRT", zone=3)
    assert (f >> m.zone_shift) == 3
    assert f & m.node_types["WVelocity"].mask \
        == m.node_types["WVelocity"].value


def test_zone_capacity_limit():
    """Zone ids beyond the remaining bits must be rejected by the
    geometry painter (reference SettingZones allocation)."""
    from tclb_tpu.utils.geometry import Geometry
    m = get_model("d2q9")
    g = Geometry(m, (4, 4))
    import pytest as _pytest
    for i in range(m.zone_max - 1):
        g.set_zone(f"z{i}")
    with _pytest.raises(ValueError, match="zone"):
        g.set_zone("one_too_many")
